"""Multi-host distributed entry points (SURVEY.md §3 "Distributed init";
BASELINE.json:11 — Criteo-1TB on v5p-64).

The reference's NCCL world is replaced by JAX's runtime: every host runs the
same program, ``initialize()`` wires the cluster (coordinator + process
ids), and a global ``Mesh`` over all devices carries the row-sharded
training state.  The per-split histogram allreduce rides
``jax.lax.psum`` over ICI within a slice and DCN across hosts — the mesh
abstracts both links, nothing in the engine changes between single-chip,
single-host-multi-chip, and multi-host.

Determinism contract for the sketch: every worker must bin through
IDENTICAL edges.  ``sketch_distributed`` computes the sketch from a
deterministic per-host sample union (allgathered), so all hosts derive the
same BinMapper without any host seeing the full data — the Criteo-1TB
ingest pattern (each host reads only its row range).

Single-process testing: ``XLA_FLAGS=--xla_force_host_platform_device_count=8``
gives an 8-device CPU mesh; the exact code paths here then run in CI
(tests/test_multihost.py), per SURVEY.md §4.
"""

from __future__ import annotations

from typing import Iterable, Optional, Sequence

import numpy as np

from dryad_tpu.config import Params, make_params
from dryad_tpu.dataset import Dataset


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None) -> None:
    """Wire this host into the cluster (NCCL-init equivalent).

    On TPU pods, all arguments auto-detect from the environment; pass them
    explicitly for manual clusters.  Call once, before any jax use.
    """
    import jax

    kw = {}
    if coordinator_address is not None:
        kw["coordinator_address"] = coordinator_address
    if num_processes is not None:
        kw["num_processes"] = num_processes
    if process_id is not None:
        kw["process_id"] = process_id
    jax.distributed.initialize(**kw)


def global_mesh(axis: str = "data"):
    """One mesh over every device in the cluster (all hosts)."""
    import jax
    from jax.sharding import Mesh

    return Mesh(np.asarray(jax.devices()), (axis,))


def host_row_range(num_rows: int) -> tuple[int, int]:
    """[start, stop) row range this host should ingest — contiguous blocks
    in process order, balanced to within one row."""
    import jax

    p, n = jax.process_index(), jax.process_count()
    base, rem = divmod(num_rows, n)
    start = p * base + min(p, rem)
    return start, start + base + (1 if p < rem else 0)


def sketch_distributed(
    X_local: np.ndarray,
    total_rows: int,
    row_offset: int,
    *,
    max_bins: int = 256,
    categorical_features: Sequence[int] = (),
    sample_rows: int = 1 << 20,
    seed: int = 0,
    allgather=None,
):
    """Identical BinMapper on every host from row-sharded data.

    Each host keeps the rows whose global-row-id-keyed draw (stateless
    splitmix64 hash — data/streaming.py::_keyed_uniform) falls under
    ``sample_rows / total_rows``, allgathers the (small) samples, and
    sketches the union — deterministic in the partitioning, so every host
    freezes the same edges (the bit-identity requirement, BASELINE.json:5).

    ``allgather(arr) -> list[arr]`` exchanges host arrays; default uses
    ``jax.experimental.multihost_utils`` (single-process: identity).
    """
    from dryad_tpu.data.sketch import sketch_features

    n = X_local.shape[0]
    rate = min(1.0, sample_rows / max(total_rows, 1))
    keep = _global_row_uniform(row_offset, n, seed) < rate
    local_sample = np.ascontiguousarray(X_local[keep], np.float32)

    if allgather is None:
        allgather = _default_allgather
    parts = allgather(local_sample)
    sample = np.concatenate(parts, axis=0)
    return sketch_features(sample, max_bins=max_bins,
                           categorical_features=categorical_features)


def _global_row_uniform(row_offset: int, n: int, seed: int) -> np.ndarray:
    """uniform(0,1) per row, a pure function of (seed, global row id)."""
    from dryad_tpu.data.streaming import _keyed_uniform

    return _keyed_uniform(row_offset, n, seed)


def _default_allgather(arr: np.ndarray) -> list[np.ndarray]:
    import jax

    if jax.process_count() == 1:
        return [arr]
    from jax.experimental import multihost_utils

    # pad to the max local length so process_allgather gets uniform shapes
    n = np.int64(arr.shape[0])
    ns = multihost_utils.process_allgather(n)
    m = int(ns.max())
    pad = np.zeros((m - arr.shape[0],) + arr.shape[1:], arr.dtype)
    stacked = multihost_utils.process_allgather(
        np.concatenate([arr, pad], axis=0))
    return [stacked[i, : int(ns[i])] for i in range(stacked.shape[0])]


def train_distributed(
    params: "Params | dict | None",
    data: Dataset,
    valid: Optional[Dataset] = None,
    *,
    mesh=None,
    **kw,
):
    """``dryad.train`` over a (multi-host) mesh: rows sharded, histograms
    psum'd — the NCCL data-parallel mode (SURVEY.md §2 #13-14)."""
    from dryad_tpu.engine.train import train_device

    p = make_params(params)
    if mesh is None:
        mesh = global_mesh()
    return train_device(p, data, valid, mesh=mesh, **kw)
