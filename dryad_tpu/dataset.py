"""Dataset container: raw ingest → frozen sketch → binned matrix.

The public surface mirrors the reference's train-time data object implied by
``dryad.train(params, dataset)`` (BASELINE.json:5).  A Dataset owns:

* the frozen BinMapper (quantile sketch output — the bit-identity anchor),
* the binned matrix (N, F) uint8/uint16,
* labels, optional weights, and optional ranking query groups.

Validation sets bin through the *training* mapper (``Dataset.bind``), exactly
as predict does.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from dryad_tpu.data.binning import bin_csr, bin_matrix
from dryad_tpu.data.sketch import BinMapper, sketch_features


class Dataset:
    def __init__(
        self,
        X: Optional[np.ndarray] = None,
        y: Optional[np.ndarray] = None,
        *,
        weight: Optional[np.ndarray] = None,
        group: Optional[np.ndarray] = None,
        categorical_features: Sequence[int] = (),
        max_bins: int = 256,
        mapper: Optional[BinMapper] = None,
        csr: Optional[tuple[np.ndarray, np.ndarray, np.ndarray, int]] = None,
        bundle: bool = True,
    ):
        if (X is None) == (csr is None):
            raise ValueError("provide exactly one of X (dense) or csr=(indptr, indices, values, num_features)")
        self.categorical_features = tuple(int(c) for c in categorical_features)
        if csr is not None:
            from dryad_tpu.data.bundling import BundledMapper, plan_bundles

            indptr, indices, values, num_features = csr
            if mapper is None:
                base = _sketch_csr(indptr, indices, values, num_features,
                                   max_bins, self.categorical_features)
                Xb0 = bin_csr(indptr, indices, values, num_features, base)
                plan = plan_bundles(Xb0, base, max_bins) if bundle else []
                if plan:
                    # exclusive feature bundling: fold strictly-exclusive
                    # sparse columns (deterministic plan, stored in the
                    # mapper) — the grower sees fewer, denser features
                    mapper = BundledMapper(base, plan)
                    self.mapper = mapper
                    self.X_binned = mapper.fold(Xb0)
                else:
                    self.mapper = base
                    self.X_binned = Xb0
            elif isinstance(mapper, BundledMapper):
                self.mapper = mapper
                self.X_binned = mapper.fold(
                    bin_csr(indptr, indices, values, num_features, mapper.base))
            else:
                self.mapper = mapper
                self.X_binned = bin_csr(indptr, indices, values, num_features,
                                        mapper)
        else:
            X = np.asarray(X, np.float32)
            if mapper is None:
                mapper = sketch_features(X, max_bins=max_bins, categorical_features=self.categorical_features)
            self.mapper = mapper
            self.X_binned = bin_matrix(X, mapper)

        self.num_rows, self.num_features = self.X_binned.shape
        self._attach_targets(y, weight, group)

    _has_missing: Optional[bool] = None
    #: overridden by data.stream_dataset.StreamedDataset — trainers branch
    #: to bounded-read accessors instead of the resident X_binned
    is_streamed: bool = False

    @property
    def has_missing(self) -> bool:
        """True when any NUMERICAL column contains missing (bin 0) rows —
        the growers then scan splits in both missing directions.  On
        missing-free data the flag keeps the split scan single-plane, so
        compiled programs and grown trees are unchanged.  (Categorical
        missing learns its direction through subset membership instead.)"""
        if self._has_missing is None:
            zero_cols = (self.X_binned == 0).any(axis=0)
            eligible = ~self.mapper.is_categorical
            # bundled (EFB) columns: bin 0 means "all members default",
            # never "missing" — they must not trigger the two-plane scan
            bundled = getattr(self.mapper, "bundled_mask", None)
            if bundled is not None:
                eligible &= ~bundled
            self._has_missing = bool((zero_cols & eligible).any())
        return self._has_missing

    def _attach_targets(self, y, weight, group) -> None:
        """Validate + store labels/weights/query groups (shared by __init__
        and the from_binned factory so the checks can never drift)."""
        self.y = None if y is None else np.ascontiguousarray(y, np.float32)
        if self.y is not None and self.y.shape[0] != self.num_rows:
            raise ValueError("y length mismatch")
        self.weight = None if weight is None else np.ascontiguousarray(weight, np.float32)
        if self.weight is not None and self.weight.shape[0] != self.num_rows:
            raise ValueError(
                f"weight length {self.weight.shape[0]} != num_rows {self.num_rows}"
            )
        # ranking: group[i] = #rows in query i (LightGBM convention)
        self.group = None if group is None else np.ascontiguousarray(group, np.int64)
        if self.group is not None and int(self.group.sum()) != self.num_rows:
            raise ValueError("group sizes must sum to num_rows")
        self._device_cache = None

    def device_arrays(self):
        """Memoized device copies of (X_binned, y, weight).

        Repeated ``train`` calls on one Dataset skip the host->device
        upload — 280 MB of binned matrix at Higgs-10M scale, tens of
        seconds through a remote device tunnel.  The arrays are treated as
        immutable once uploaded; mutate ``X_binned``/``y`` in place and the
        cache goes stale (construct a new Dataset instead)."""
        if self._device_cache is None:
            import jax.numpy as jnp

            self._device_cache = (
                jnp.asarray(self.X_binned),
                None if self.y is None else jnp.asarray(self.y),
                None if self.weight is None else jnp.asarray(self.weight),
            )
        return self._device_cache

    @classmethod
    def from_binned(
        cls,
        X_binned: np.ndarray,
        mapper: BinMapper,
        y: Optional[np.ndarray] = None,
        *,
        weight: Optional[np.ndarray] = None,
        group: Optional[np.ndarray] = None,
        categorical_features: Sequence[int] = (),
    ) -> "Dataset":
        """Dataset over an already-binned matrix (streaming/out-of-core
        ingest) — runs the same label/weight/group validation as __init__."""
        ds = cls.__new__(cls)
        ds.categorical_features = tuple(int(c) for c in categorical_features)
        ds.mapper = mapper
        ds.X_binned = np.ascontiguousarray(X_binned, mapper.bin_dtype)
        ds.num_rows, ds.num_features = ds.X_binned.shape
        ds._attach_targets(y, weight, group)
        return ds

    def bind(self, X: np.ndarray, y: Optional[np.ndarray] = None, **kw) -> "Dataset":
        """Bin new data (validation/test) through this dataset's frozen mapper."""
        return Dataset(X, y, mapper=self.mapper, categorical_features=self.categorical_features, **kw)

    @property
    def query_offsets(self) -> Optional[np.ndarray]:
        if self.group is None:
            return None
        return np.concatenate([[0], np.cumsum(self.group)]).astype(np.int64)


def _sketch_csr(indptr, indices, values, num_features, max_bins, categorical_features):
    """Sketch from CSR by densifying per-feature value lists + implicit zeros.

    Implicit zeros participate in the sketch (they dominate Criteo-style
    data), represented by injecting the exact count of zeros per feature.
    """
    n = indptr.shape[0] - 1
    cols = np.asarray(indices)
    vals = np.asarray(values, np.float32)
    order = np.argsort(cols, kind="stable")
    cols_s, vals_s = cols[order], vals[order]
    bounds = np.searchsorted(cols_s, np.arange(num_features + 1))
    from dryad_tpu.data.sketch import FeatureBins, _sketch_categorical, _sketch_numerical  # noqa: PLC0415

    cats = frozenset(int(c) for c in categorical_features)
    feats: list[FeatureBins] = []
    for f in range(num_features):
        explicit = vals_s[bounds[f] : bounds[f + 1]]
        n_zero = n - explicit.size
        if n_zero > 0:
            col = np.concatenate([explicit, np.zeros(n_zero, np.float32)])
        else:
            col = explicit
        feats.append(_sketch_categorical(col, max_bins) if f in cats else _sketch_numerical(col, max_bins))
    return BinMapper(feats, max_bins)
