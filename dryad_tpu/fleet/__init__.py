"""dryad_tpu.fleet — a replicated serving pool behind one router.

The serve stack (dryad_tpu/serve) is one process: one crash, stall, or
hot-swap pause takes down all traffic.  This package is the
shared-nothing layer above it — N serve subprocesses supervised like
training runs (crash/hang detection, budgeted respawn with backoff, an
append-only journal: the resilience subsystem's machinery pointed at
processes instead of device faults), fronted by a thin stdlib router
(health-aware routing, one retry on a different replica, priority-classed
load shedding, per-model admission caps) with zero-drop rolling model
pushes (drain at the pinned version, then swap, replica by replica),
one aggregated ``/metrics``/``/healthz`` scrape for the whole pool, and
(r22) an SLO-driven capacity loop (``CapacityController``) that adds a
replica on sustained p99 breach or admission saturation and drains one
back out on sustained headroom, inside declared min/max bounds.

The package is host-side and jax-free by lint (the same contract as
``dryad_tpu/obs``): replicas own the devices; the fleet owns processes
and sockets.  Entry points::

    from dryad_tpu.fleet import FleetSupervisor, FleetRouter, serve_argv
    sup = FleetSupervisor(
        lambda i, pf: serve_argv(["m.dryad"], pf, backend="auto"),
        n_replicas=2, journal="fleet.jsonl").start()
    router = FleetRouter(sup, port=8000).start()

or ``python -m dryad_tpu fleet --model m.dryad --replicas 2 --port 8000``.
"""

from dryad_tpu.fleet.autoscale import CapacityController
from dryad_tpu.fleet.replica import (ReplicaProcess, ReplicaStartupError,
                                     serve_argv)
from dryad_tpu.fleet.router import (FleetRouter, make_fleet_router,
                                    relabel_exposition)
from dryad_tpu.fleet.supervisor import FleetSupervisor, ReplicaSlot

__all__ = [
    "CapacityController", "FleetRouter", "FleetSupervisor",
    "ReplicaProcess", "ReplicaSlot", "ReplicaStartupError",
    "make_fleet_router", "relabel_exposition", "serve_argv",
]
