"""Health-routed HTTP front door for a replica fleet.

A thin stdlib router in front of the supervisor's slots: requests land
here, get admission-controlled, and are forwarded over plain HTTP to one
healthy replica.  The router holds NO model state and never touches jax
(the fleet package is jax-free by lint) — it is deliberately the
smallest thing that can make N shared-nothing serve processes look like
one endpoint.

Routing and degradation, in order:

1. **Admission (priority shedding).**  Each request carries a priority
   class (``X-Dryad-Priority: interactive|bulk``; default interactive;
   the body stays opaque bytes — a body ``"priority"`` is honored only
   when a per-model cap already forces a body parse).  Bulk
   sheds FIRST: when total in-flight reaches ``bulk_max_inflight`` new
   bulk requests get 503 while interactive traffic still flows; at
   ``max_inflight`` everything sheds.  Optional per-model caps
   (``model_caps``) bound any one model's in-flight share the same way.
   This is LAYERED ON the per-replica micro-batcher queue: the router
   bounds what enters the fleet, each replica's bounded queue
   (``ServeOverloaded`` -> 503) remains the final backstop.
2. **Routing.**  Round-robin over routable slots (healthy, not draining,
   not failed closed) — the supervisor's monitor updates that set, the
   router just reads it.
3. **Retry.**  A forwarded request that dies on the wire (connect error,
   timeout) or answers 5xx is retried EXACTLY ONCE against a different
   routable replica.  One retry is the whole budget: the recorded
   fleet drills (crash mid-request, stuck-503) need exactly one, and
   unbounded retries would amplify overload into a retry storm.

Observability: ``/metrics`` serves the router's own ``dryad_fleet_*``
series PLUS every live replica's scrape, each sample relabeled with
``replica="rN"`` — one endpoint scrapes the whole fleet — and (r17)
``dryad_fleet_latency_ms{priority,stage,q}`` gauges: fleet-wide
p50/p95/p99 computed by EXACT count-merge of the replicas'
fixed-log-bucket ``dryad_request_latency_seconds`` histograms (scraped
as JSON from each replica's ``/obs``) plus the router's own
stage="router" series.  ``/healthz`` (auth-exempt, like every other
healthz in this repo) answers 200 while at least ``min_healthy``
replicas are routable AND no per-priority p99 SLO budget is in
sustained breach (obs/slo.py; verdicts ride the payload).  ``/stats``
returns the JSON view (slot states + shed/retry counters).  r18 model
drift: each replica's ``/obs`` answer also carries its raw drift-window
bin counts (serve-side ``DriftMonitor``); the router merges the COUNTS
per model bitwise (never PSI values or ratios), computes fleet-wide PSI
once on the merged state, serves ``dryad_fleet_drift_*`` gauges on
``/metrics`` and a ``GET /drift`` JSON report, and a DriftGate turns a
SUSTAINED breach into a journaled ``drift_breach`` + a ``drift:<model>``
warning in /healthz payloads (warn-only: a drifted model still serves —
the event is the retrain/rollback trigger, not an outage).  ``/trace``
(r17) assembles the fleet-wide Chrome trace: router spans, every live
replica's span ring clock-aligned by the registration-time offset
handshake, and the supervisor journal as an annotation track —
tail-sampled to the slowest ``?k=`` requests per window.  Request
tracing: the router mints (or honors) ``X-Dryad-Trace`` per /predict,
forwards it to the replica, echoes it on the response, and records every
forward ATTEMPT as a trace-tagged span, so a request that survives a
replica crash shows both attempts under one id.  Bearer auth reuses the
obs exporter's scheme.
"""

from __future__ import annotations

import http.client
import json
import re
import socket
import sys
import threading
import time
import urllib.parse
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from dryad_tpu.obs.drift import (DriftGate, drift_report,
                                 merge_drift_states)
from dryad_tpu.obs.exporter import authorized, send_unauthorized
from dryad_tpu.obs.health import HealthState
from dryad_tpu.obs.registry import (LOG_BUCKETS, REQUEST_LATENCY, Registry,
                                    default_registry, hist_quantile,
                                    merge_hist_states)
from dryad_tpu.obs.slo import SloGate
from dryad_tpu.obs.spans import record_at
from dryad_tpu.obs.trace_export import (TailSampler, active_trace,
                                        dumps_fleet_trace, tracing_active)

PRIORITIES = ("interactive", "bulk")
TRACE_HEADER = "X-Dryad-Trace"
#: statuses that count as "this replica failed us" for the single retry
RETRYABLE_STATUSES = (500, 502, 503, 504)
#: hop-by-hop / recomputed headers never forwarded either direction
_SKIP_HEADERS = {"host", "content-length", "connection", "transfer-encoding",
                 "keep-alive"}
#: label parser for registry label blocks ('priority="bulk",stage="total"')
_LABEL_RE = re.compile(r'(\w+)="([^"]*)"')


def relabel_exposition(text: str, replica: str) -> str:
    """Inject ``replica="rN"`` into every sample line of a Prometheus
    text exposition.  Comment lines (# HELP/# TYPE) are dropped — N
    replicas would repeat them per family, which scrapers reject."""
    out = []
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        # sample shape: name[{labels}] value [timestamp]
        brace = line.find("{")
        space = line.find(" ")
        if brace != -1 and (space == -1 or brace < space):
            out.append(line[:brace + 1] + f'replica="{replica}",'
                       + line[brace + 1:])
        elif space != -1:
            out.append(f'{line[:space]}{{replica="{replica}"}}{line[space:]}')
    return "\n".join(out) + ("\n" if out else "")


class _RouterState:
    """Everything the handler threads share (rides on the HTTP server).
    ``_lock`` guards the admission ledger (total + per-model in-flight)
    and the round-robin cursor; admit/release are single short critical
    sections so shedding decisions are atomic against concurrent handler
    threads, and no forward/scrape I/O ever happens under it."""

    GUARDED_BY = {"_inflight_total": "_lock", "_inflight_model": "_lock",
                  "_inflight_priority": "_lock",
                  "_rr": "_lock", "_slo_last": "_lock"}

    def __init__(self, supervisor, *, registry: Optional[Registry],
                 max_inflight: int, bulk_max_inflight: Optional[int],
                 model_caps: Optional[dict], request_timeout_s: float,
                 min_healthy: int, auth_token: Optional[str],
                 slo_budgets_ms: Optional[dict] = None,
                 slo_quantile: float = 0.99, slo_breach_after: int = 3,
                 tail_window: int = 512, tail_keep: int = 16,
                 drift_budget_psi: Optional[float] = None,
                 drift_breach_after: int = 2, drift_top_k: int = 5):
        self.supervisor = supervisor
        self.registry = (registry if registry is not None
                         else default_registry())
        # request-scoped observability (r17): the tail sampler feeds the
        # merged /trace (full detail for the slowest requests per
        # window), the SLO gate turns per-priority p99 budgets into
        # /healthz verdicts.  The gate gets its OWN health state so a
        # sustained breach degrades THIS router's /healthz, not the
        # process-global surface another subsystem may be serving.
        self.sampler = TailSampler(window=tail_window)
        self.tail_keep = int(tail_keep)
        self.slo_health = HealthState(registry=self.registry)
        self.slo = SloGate(slo_budgets_ms, quantile=slo_quantile,
                           breach_after=slo_breach_after,
                           registry=self.registry, health=self.slo_health)
        self._slo_last: dict[str, tuple] = {}
        # drift verdicts (r18, obs/drift.py): WARN-ONLY by default — a
        # drifted model keeps serving; a sustained breach journals
        # ``drift_breach`` through the supervisor (the continual-
        # boosting retrain/rollback trigger) and rides /healthz PAYLOADS
        # as a ``drift:<model>`` warning.  None disables the layer.
        self.drift_top_k = int(drift_top_k)
        self.drift = (None if drift_budget_psi is None else DriftGate(
            float(drift_budget_psi), breach_after=drift_breach_after,
            registry=self.registry, on_breach=self._journal_drift_breach))
        self.max_inflight = int(max_inflight)
        self.bulk_max_inflight = (int(bulk_max_inflight)
                                  if bulk_max_inflight is not None
                                  else max(1, self.max_inflight // 2))
        if not 0 < self.bulk_max_inflight <= self.max_inflight:
            raise ValueError("need 0 < bulk_max_inflight <= max_inflight "
                             "(bulk sheds first, never last)")
        self.model_caps = {str(k): int(v)
                           for k, v in (model_caps or {}).items()}
        self.request_timeout_s = float(request_timeout_s)
        self.min_healthy = int(min_healthy)
        self.auth_token = auth_token
        self._lock = threading.Lock()
        self._inflight_total = 0
        self._inflight_model: dict[str, int] = {}
        self._inflight_priority: dict[str, int] = {p: 0
                                                   for p in PRIORITIES}
        self._rr = 0
        # the capacity loop (fleet/autoscale.py), when the CLI arms one;
        # purely observational here — /stats surfaces its state
        self.autoscale = None

    # ---- admission ---------------------------------------------------------
    def admit(self, priority: str, model: Optional[str]) -> Optional[str]:
        """Take an admission slot, or return the refusal reason.  The
        caller MUST pair a None return with a later ``release``."""
        with self._lock:
            if self._inflight_total >= self.max_inflight:
                return "fleet at max_inflight"
            if (priority == "bulk"
                    and self._inflight_total >= self.bulk_max_inflight):
                return "bulk shed (fleet beyond bulk_max_inflight)"
            if model is not None and model in self.model_caps:
                if (self._inflight_model.get(model, 0)
                        >= self.model_caps[model]):
                    return f"model {model!r} at its admission cap"
            self._inflight_total += 1
            self._inflight_priority[priority] = (
                self._inflight_priority.get(priority, 0) + 1)
            if model is not None:
                self._inflight_model[model] = (
                    self._inflight_model.get(model, 0) + 1)
            return None

    def release(self, priority: str, model: Optional[str]) -> None:
        with self._lock:
            self._inflight_total -= 1
            self._inflight_priority[priority] = (
                self._inflight_priority.get(priority, 1) - 1)
            if model is not None:
                self._inflight_model[model] = (
                    self._inflight_model.get(model, 1) - 1)

    @property
    def inflight_total(self) -> int:
        with self._lock:
            return self._inflight_total

    def inflight_by_priority(self) -> dict:
        with self._lock:
            return dict(self._inflight_priority)

    # ---- slot choice -------------------------------------------------------
    def pick(self, exclude=()) -> Optional[object]:
        slots = [s for s in self.supervisor.routable_slots()
                 if s.name not in exclude]
        if not slots:
            return None
        with self._lock:
            self._rr += 1
            return slots[self._rr % len(slots)]

    # ---- metrics helpers ---------------------------------------------------
    def count(self, name: str, help: str, **labels) -> None:
        if self.registry.enabled:
            fam = self.registry.counter(name, help)
            (fam.labels(**labels) if labels else fam).inc()

    def gauge_inflight(self) -> None:
        """Live admission-depth gauges (r22): per-priority fleet depth
        plus each slot's router-side in-flight count — the numbers the
        capacity loop steers on, exported so operators read the same
        signal the controller does."""
        if not self.registry.enabled:
            return
        with self._lock:
            per = dict(self._inflight_priority)
            total = self._inflight_total
        fam = self.registry.gauge(
            "dryad_fleet_inflight",
            "Requests currently inside the fleet, by priority class")
        for priority in PRIORITIES:
            fam.labels(priority=priority).set(per.get(priority, 0))
        fam.labels(priority="total").set(total)
        slot_fam = self.registry.gauge(
            "dryad_fleet_slot_inflight",
            "Router-side in-flight requests per replica slot")
        for s in self.supervisor.slots:
            slot_fam.labels(replica=s.name).set(s.inflight)

    def capacity_signals(self) -> dict:
        """The autoscaler's one-call view of the router (r22): a fresh
        SLO window evaluation (the gate's streaks advance — sustained
        semantics are shared with /healthz), the admission ledger, and
        per-slot in-flight.  Jax-free, scrape-free, one short critical
        section."""
        slo = self.evaluate_slo()
        with self._lock:
            per = dict(self._inflight_priority)
            total = self._inflight_total
        return {
            "slo": slo,
            "inflight": total,
            "inflight_priority": per,
            "max_inflight": self.max_inflight,
            "slots": {s.name: {"inflight": s.inflight,
                               "routable": s.routable,
                               "retiring": s.retiring}
                      for s in self.supervisor.slots},
        }

    # ---- drift (r18) -------------------------------------------------------
    def _journal_drift_breach(self, model: str, verdict: dict) -> None:
        """DriftGate's on_breach: one journal line per NEW sustained
        breach, in the supervisor's flight recorder next to crashes and
        swaps (stub supervisors without a journal are skipped)."""
        jr = getattr(self.supervisor, "journal", None)
        if jr is not None:
            jr("drift_breach", model=model,
               psi_max=verdict.get("psi_max"),
               score_psi=verdict.get("score_psi"),
               features_over=verdict.get("features_over"),
               features=[t["feature"] for t in verdict.get("top", [])],
               streak=verdict.get("streak"))
        self.count("dryad_fleet_drift_breach_total",
                   "Sustained fleet drift breaches journaled", model=model)

    def update_drift(self, blocks: list) -> dict:
        """Fold per-replica drift blocks (each ``{model: export_state}``)
        into fleet verdicts: counts are merged EXACTLY per model (the
        r17 histogram discipline — merge counts, never PSI values), PSI
        runs once on the merged state, ``dryad_fleet_drift_*`` gauges
        mirror it, and the gate advances its sustained-breach streaks.
        Runs on the scrape cadence (/metrics and /drift), never inside
        /healthz — the health path stays scrape-free and reads the
        LATCHED verdicts."""
        if self.drift is None:
            return {}
        per_model: dict[str, list] = {}
        for block in blocks:
            if not isinstance(block, dict):
                continue
            for model, st in block.items():
                per_model.setdefault(str(model), []).append(st)
        reports: dict = {}
        for model, sts in sorted(per_model.items()):
            try:
                merged = merge_drift_states(sts)
            except ValueError:
                # a malformed or mixed-version replica block must not
                # kill the whole fleet scrape — skip it, on the record
                self.count("dryad_fleet_drift_merge_error_total",
                           "Replica drift blocks that failed the exact "
                           "merge", model=model)
                continue
            reports[model] = drift_report(
                merged, budget_psi=self.drift.budget_psi,
                top_k=self.drift_top_k)
        if self.registry.enabled:
            fam = self.registry.gauge(
                "dryad_fleet_drift_psi",
                "Fleet-merged per-feature PSI, top offenders")
            for model, r in reports.items():
                for name, key in (("dryad_fleet_drift_psi_max", "psi_max"),
                                  ("dryad_fleet_drift_score_psi",
                                   "score_psi"),
                                  ("dryad_fleet_drift_rows", "rows"),
                                  ("dryad_fleet_drift_features_over",
                                   "features_over")):
                    self.registry.gauge(
                        name, "Fleet-merged drift telemetry").labels(
                        model=model).set(float(r.get(key, 0)))
                for item in r["top"]:
                    fam.labels(model=model,
                               feature=item["feature"]).set(item["psi"])
        return self.drift.evaluate(reports)

    def evaluate_slo(self) -> dict:
        """One SLO evaluation pass from the router's OWN per-priority
        end-to-end histograms (stage="router" covers queueing, retries
        and the replica — every request traverses this process, so the
        local series already IS fleet-wide).  Called on the /healthz
        cadence; deliberately no replica scrape in the health path.

        The gate sees the WINDOW since the previous evaluation (the
        delta of the cumulative series — counts subtract exactly), not
        the lifetime state: cumulative history would both mask a fresh
        regression after long uptime and keep one past slow burst
        breaching forever."""
        fam = self.registry.log_histogram(
            REQUEST_LATENCY,
            "Request latency by priority class and pipeline stage")
        windows: dict = {}
        # snapshot AND delta under _lock: /healthz is polled by several
        # probers concurrently, and an out-of-order commit to _slo_last
        # would hand the gate a negative window (which could spuriously
        # clear a sustained breach).  The family reads take each
        # family's own lock inside ours — that order never inverts
        # (registry code never acquires router state locks).
        with self._lock:
            for priority in self.slo.budgets_ms:
                counts, total, n = fam.labels(priority=priority,
                                              stage="router").value()
                last = self._slo_last.get(priority)
                if last is None:
                    windows[priority] = (counts, total, n)
                else:
                    lc, lt, ln = last
                    windows[priority] = (
                        [a - b for a, b in zip(counts, lc)],
                        total - lt, n - ln)
                self._slo_last[priority] = (counts, total, n)
        return self.slo.evaluate(windows)


class _Handler(BaseHTTPRequestHandler):
    # the _RouterState rides on the server object (see make_fleet_router)

    def log_message(self, fmt, *args):  # quiet by default
        if getattr(self.server, "verbose", False):
            super().log_message(fmt, *args)

    def _send(self, code: int, payload: dict,
              extra_headers: Optional[dict] = None) -> None:
        self._send_raw(code, json.dumps(payload).encode(),
                       "application/json", extra_headers)

    def _send_raw(self, code: int, body: bytes, ctype: str,
                  extra_headers: Optional[dict] = None) -> None:
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        if extra_headers:
            for k, v in extra_headers.items():
                self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def _authorized(self) -> bool:
        if authorized(self, self.server.state.auth_token):
            return True
        send_unauthorized(self)
        return False

    # ---- GET ---------------------------------------------------------------
    def do_GET(self):  # noqa: N802 — stdlib handler API
        state: _RouterState = self.server.state
        if self.path == "/healthz":
            states = state.supervisor.states()
            fleet_ok = state.supervisor.fleet_ok(state.min_healthy)
            # SLO verdicts ride the health probe's cadence: a SUSTAINED
            # per-priority p99 breach degrades the router like a lost
            # replica would — latency budgets are part of "healthy"
            slo = state.evaluate_slo()
            ok = fleet_ok and state.slo_health.ok
            payload = {"ok": ok, "replicas": states, "slo": slo,
                       "degraded": sorted(state.slo_health.reasons())}
            if state.drift is not None:
                # drift verdicts are WARN-ONLY: the payload surfaces
                # ``drift:<model>`` (latched on the scrape cadence — no
                # replica scrape ever runs in the health path) but the
                # status code stays governed by replicas + SLO
                payload["drift"] = {
                    "warnings": state.drift.warnings(),
                    "models": state.drift.verdicts()}
            self._send(200 if ok else 503, payload)
            return
        if not self._authorized():
            return
        if self.path == "/metrics":
            self._send_raw(200, self._aggregate_metrics().encode(),
                           "text/plain; version=0.0.4; charset=utf-8")
        elif self.path == "/stats":
            self._send(200, {
                "replicas": state.supervisor.states(),
                "inflight": state.inflight_total,
                "inflight_priority": state.inflight_by_priority(),
                "max_inflight": state.max_inflight,
                "bulk_max_inflight": state.bulk_max_inflight,
                "model_caps": state.model_caps,
                "autoscale": (state.autoscale.state()
                              if state.autoscale is not None else None),
                "fleet": state.registry.snapshot(),
            })
        elif self.path == "/trace" or self.path.startswith("/trace?"):
            self._send_raw(200, self._merged_trace().encode(),
                           "application/json")
        elif self.path == "/drift" or self.path.startswith("/drift?"):
            self._send(200, self._drift_report())
        else:
            self._send(404, {"error": f"unknown path {self.path}"})

    def _aggregate_metrics(self) -> str:
        state: _RouterState = self.server.state
        state.gauge_inflight()
        # replica /metrics honors the same bearer auth as ours — an authed
        # fleet must not silently lose every per-replica series
        headers = ({"Authorization": f"Bearer {state.auth_token}"}
                   if state.auth_token else {})
        live = [s for s in state.supervisor.slots
                if s.proc is not None and s.proc.alive
                and s.proc.host is not None]
        results: dict[str, str] = {}
        obs_blocks: dict[str, dict] = {}
        drift_blocks: dict[str, dict] = {}

        def scrape(slot) -> None:
            # ONE ~4 s budget covers BOTH requests to this slot, so the
            # join below (4.5 s) always outlives the thread — a replica
            # slow on /metrics cannot push its /obs answer past the
            # join and get silently dropped from the merged gauges
            deadline = time.monotonic() + 4.0
            try:
                status, body = slot.proc.request("GET", "/metrics",
                                                 headers=headers,
                                                 timeout_s=2.0)
            except OSError:
                status, body = None, b""
            if status == 200:
                results[slot.name] = relabel_exposition(
                    body.decode(errors="replace"), slot.name)
            else:
                state.count("dryad_fleet_scrape_error_total",
                            "Replica /metrics scrapes that failed",
                            replica=slot.name)
            # the exact-merge feed: the replica's histogram counts as
            # JSON (/obs).  Optional — a stub replica without /obs just
            # contributes nothing to the merged percentiles.
            try:
                status, body = slot.proc.request(
                    "GET", "/obs", headers=headers,
                    timeout_s=max(0.2, deadline - time.monotonic()))
                if status == 200:
                    doc = json.loads(body)
                    block = doc.get("histograms", {}).get(REQUEST_LATENCY)
                    if block:
                        obs_blocks[slot.name] = block
                    # the drift counts ride the same /obs answer (r18)
                    dblock = doc.get("drift")
                    if isinstance(dblock, dict) and dblock:
                        drift_blocks[slot.name] = dblock
            except (OSError, ValueError):
                pass

        # concurrent scrapes: one hung replica costs the whole request
        # its OWN per-slot budget (~4 s), not that much per sick slot
        threads = [threading.Thread(target=scrape, args=(s,), daemon=True)
                   for s in live]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=4.5)
        self._merged_latency_gauges(state, list(obs_blocks.values()))
        state.update_drift(list(drift_blocks.values()))
        parts = [state.registry.exposition()]
        parts += [results[s.name] for s in live if s.name in results]
        return "".join(parts)

    @staticmethod
    def _merged_latency_gauges(state: "_RouterState",
                               blocks: list) -> None:
        """Fold the replicas' request-latency histograms into fleet-wide
        per-(priority, stage) p50/p95/p99 gauges by EXACT count-merge
        (the fixed log-bucket layout makes the merged histogram equal
        the histogram of the concatenated observations).  The router's
        own stage="router" series joins through the same path."""
        if not state.registry.enabled:
            return
        own = state.registry.snapshot()["histograms"].get(
            REQUEST_LATENCY, {})
        n_bounds = len(LOG_BUCKETS) + 1
        series: dict[str, list] = {}
        for block in [own] + blocks:
            if not isinstance(block, dict):
                continue
            for lbl, st in block.items():
                # defensive shape check: a malformed or mixed-version
                # replica block (wrong keys, different bucket layout)
                # is SKIPPED, never allowed to raise out of /metrics —
                # one bad replica must not kill the whole fleet scrape
                try:
                    counts = list(st["counts"])
                    entry = (counts, float(st["sum"]), int(st["count"]))
                except (TypeError, KeyError, ValueError):
                    continue
                if len(counts) != n_bounds:
                    continue
                series.setdefault(str(lbl), []).append(entry)
        fam = state.registry.gauge(
            "dryad_fleet_latency_ms",
            "Fleet-wide latency quantiles by priority/stage "
            "(exact histogram merge across replicas)")
        for lbl, sts in series.items():
            counts, _total, n = merge_hist_states(sts)
            labels = dict(_LABEL_RE.findall(lbl))
            if not n or "priority" not in labels:
                continue
            for q, name in ((0.50, "p50"), (0.95, "p95"), (0.99, "p99")):
                fam.labels(q=name, **labels).set(
                    hist_quantile(counts, q) * 1e3)

    def _merged_trace(self) -> str:
        """The fleet-wide Chrome trace: the router's span ring, every
        live replica's ring (clock-aligned by the registration-time
        offset handshake, falling back to the replica's self-reported
        wall−perf pair), and the supervisor journal as an annotation
        track.  Tail-sampled: full span detail only for the slowest
        ``?k=`` requests in the sampler window (default the router's
        ``tail_keep``; ``k=0`` keeps everything)."""
        state: _RouterState = self.server.state
        params = urllib.parse.parse_qs(
            urllib.parse.urlparse(self.path).query)
        try:
            k = int(params.get("k", [state.tail_keep])[0])
        except ValueError:
            k = state.tail_keep
        keep = state.sampler.slowest(k) if k > 0 else None
        tracks: list = []
        buf = active_trace()
        if buf is not None:
            # one wall−perf sample maps this process's whole ring: the
            # perf_counter origin is constant for the process lifetime
            tracks.append({"pid": 1, "name": "fleet router",
                           "events": buf.events(),
                           "offset_s": time.time() - time.perf_counter()})
        headers = ({"Authorization": f"Bearer {state.auth_token}"}
                   if state.auth_token else {})
        live = [s for s in state.supervisor.slots
                if s.proc is not None and s.proc.alive
                and s.proc.host is not None]
        results: dict[str, tuple] = {}

        def scrape(slot) -> None:
            try:
                status, body = slot.proc.request("GET", "/trace/events",
                                                 headers=headers,
                                                 timeout_s=3.0)
                if status != 200:
                    return
                doc = json.loads(body)
            except (OSError, ValueError):
                return
            offset = slot.clock_offset
            clock = doc.get("clock") or {}
            if offset is None and "wall_s" in clock and "perf_s" in clock:
                offset = float(clock["wall_s"]) - float(clock["perf_s"])
            results[slot.name] = (doc.get("events") or [], offset)

        threads = [threading.Thread(target=scrape, args=(s,), daemon=True)
                   for s in live]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=4.0)
        for slot in live:
            if slot.name in results:
                events, offset = results[slot.name]
                tracks.append({"pid": 10 + slot.index,
                               "name": f"replica {slot.name}",
                               "events": events, "offset_s": offset})
        journal_events: list = []
        journal_path = getattr(state.supervisor, "journal_path", None)
        if journal_path:
            from dryad_tpu.resilience.journal import RunJournal

            try:
                journal_events = RunJournal.read(journal_path)
            except (OSError, ValueError):
                journal_events = []
        return dumps_fleet_trace(tracks, journal_events, keep)

    def _drift_report(self) -> dict:
        """``GET /drift``: a fresh concurrent ``/obs`` scrape of the
        live replicas, the per-model EXACT count-merge, PSI on the
        merged state, and the gate's sustained-breach verdicts — the
        operator's one-call answer to "does serving traffic still look
        like the training data"."""
        state: _RouterState = self.server.state
        if state.drift is None:
            return {"enabled": False}
        headers = ({"Authorization": f"Bearer {state.auth_token}"}
                   if state.auth_token else {})
        live = [s for s in state.supervisor.slots
                if s.proc is not None and s.proc.alive
                and s.proc.host is not None]
        blocks: dict[str, dict] = {}

        def scrape(slot) -> None:
            try:
                status, body = slot.proc.request("GET", "/obs",
                                                 headers=headers,
                                                 timeout_s=3.0)
                if status != 200:
                    return
                dblock = json.loads(body).get("drift")
                if isinstance(dblock, dict) and dblock:
                    blocks[slot.name] = dblock
            except (OSError, ValueError):
                pass

        threads = [threading.Thread(target=scrape, args=(s,), daemon=True)
                   for s in live]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=3.5)
        verdicts = state.update_drift(list(blocks.values()))
        return {
            "enabled": True,
            "budget_psi": state.drift.budget_psi,
            "breach_after": state.drift.breach_after,
            "replicas": sorted(blocks),
            "models": verdicts,
            "warnings": state.drift.warnings(),
        }

    # ---- POST --------------------------------------------------------------
    def do_POST(self):  # noqa: N802 — stdlib handler API
        if not self._authorized():
            return
        state: _RouterState = self.server.state
        try:
            length = int(self.headers.get("Content-Length") or 0)
            body = self.rfile.read(length) if length else b""
            if self.path == "/predict":
                self._route_predict(body)
            elif self.path == "/models/push":
                spec = json.loads(body or b"{}")
                result = state.supervisor.rolling_push(
                    spec["path"], name=spec.get("name"),
                    activate=bool(spec.get("activate", True)),
                    auth_token=state.auth_token)
                self._send(200 if not result["errors"] else 502, result)
            else:
                self._send(404, {"error": f"unknown path {self.path}"})
        except (KeyError, ValueError) as e:
            self._send(400, {"error": repr(e)})
        except Exception as e:  # noqa: BLE001 — surface, don't kill the router
            self._send(500, {"error": repr(e)})

    def _priority_and_model(self, body: bytes) -> tuple[str, Optional[str]]:
        """Priority from the ``X-Dryad-Priority`` header; the body stays
        opaque bytes on the default path (parsing MB-scale bulk payloads
        at the router just to read one key would double the JSON cost of
        every request).  Only a configured per-model cap forces a body
        parse (the model name lives there unless ``X-Dryad-Model`` is
        set), and THAT parse also honors a body ``"priority"`` as a
        convenience — header-less priority classing without model caps
        defaults to interactive."""
        state: _RouterState = self.server.state
        priority = (self.headers.get("X-Dryad-Priority") or "").lower()
        model = self.headers.get("X-Dryad-Model")
        if state.model_caps and model is None and body:
            try:
                doc = json.loads(body)
                priority = priority or str(doc.get("priority", "")).lower()
                model = doc.get("model")
            except ValueError:
                pass
        if priority not in PRIORITIES:
            priority = "interactive"
        return priority, model

    def _route_predict(self, body: bytes) -> None:
        state: _RouterState = self.server.state
        priority, model = self._priority_and_model(body)
        # trace context: honor a client-supplied id, mint one while
        # tracing is on (minting rides the traced path only — with
        # tracing off an id-less request stays allocation-free)
        trace = self.headers.get(TRACE_HEADER)
        if trace is None and tracing_active(state.registry):
            trace = uuid.uuid4().hex[:16]
        state.count("dryad_fleet_request_total",
                    "Requests entering the fleet router",
                    priority=priority)
        reason = state.admit(priority, model)
        if reason is not None:
            state.count("dryad_fleet_shed_total",
                        "Requests shed by fleet admission control",
                        priority=priority)
            self._send(503, {"error": f"shed: {reason}",
                             "priority": priority},
                       extra_headers=({TRACE_HEADER: trace}
                                      if trace else None))
            return
        t0 = time.perf_counter()
        try:
            status, payload, replica = self._forward(body, trace)
            if status is None:
                self._send(503, {"error": "no healthy replica"},
                           extra_headers=({TRACE_HEADER: trace}
                                          if trace else None))
                return
            self._send_raw(status, payload, "application/json",
                           extra_headers=({TRACE_HEADER: trace}
                                          if trace else None))
            if state.registry.enabled:
                dur = time.perf_counter() - t0
                # the mergeable per-priority family (stage="router" is
                # the fleet-wide end-to-end view — every request passes
                # here); the span ring gets the trace-tagged request
                # span; the tail sampler ranks it for /trace detail
                state.registry.log_histogram(
                    REQUEST_LATENCY,
                    "Request latency by priority class and pipeline "
                    "stage").labels(
                    priority=priority, stage="router").observe(dur)
                record_at("fleet.request", t0, dur, trace=trace,
                          registry=state.registry)
                state.sampler.observe(trace, dur)
                if replica is not None:
                    state.count("dryad_fleet_routed_total",
                                "Requests served, by replica",
                                replica=replica)
        finally:
            state.release(priority, model)

    def _forward(self, body: bytes, trace: Optional[str] = None):
        """Forward to one routable replica; retry once elsewhere on a
        wire failure or 5xx.  Returns (status, payload, replica_name) —
        status None when no replica was available at all.  Every attempt
        — including the failed one a retry follows — records a
        trace-tagged ``fleet.forward/<replica>`` span, so a request that
        survives a replica crash shows BOTH attempts under one id in the
        merged trace."""
        state: _RouterState = self.server.state
        headers = {k: v for k, v in self.headers.items()
                   if k.lower() not in _SKIP_HEADERS}
        headers["Content-Type"] = "application/json"
        if trace is not None:
            headers[TRACE_HEADER] = trace
        tried: list[str] = []
        last: Optional[tuple] = None
        for attempt in (0, 1):
            slot = state.pick(exclude=tried)
            if slot is None:
                break
            tried.append(slot.name)
            if attempt == 1:
                state.count("dryad_fleet_retry_total",
                            "Requests retried on a second replica")
            slot.inflight_inc()
            if not slot.routable:
                # closed the pick->inc window: a drain (rolling swap) or
                # health flip between pick() and the in-flight mark must
                # not slip this request onto the slot — the drain's
                # inflight==0 wait reads the count AFTER the flag
                slot.inflight_dec()
                continue
            t_a = time.perf_counter()
            try:
                conn = http.client.HTTPConnection(
                    slot.proc.host, slot.proc.port,
                    timeout=state.request_timeout_s)
                try:
                    conn.request("POST", "/predict", body=body,
                                 headers=headers)
                    resp = conn.getresponse()
                    status, payload = resp.status, resp.read()
                finally:
                    conn.close()
            except (OSError, http.client.HTTPException, socket.timeout):
                state.count("dryad_fleet_upstream_error_total",
                            "Forwards that died on the wire",
                            replica=slot.name)
                record_at(f"fleet.forward/{slot.name}", t_a,
                          time.perf_counter() - t_a, trace=trace,
                          registry=state.registry)
                last = (502, json.dumps(
                    {"error": f"replica {slot.name} unreachable"}).encode(),
                    slot.name)
                continue
            finally:
                slot.inflight_dec()
            record_at(f"fleet.forward/{slot.name}", t_a,
                      time.perf_counter() - t_a, trace=trace,
                      registry=state.registry)
            if status in RETRYABLE_STATUSES:
                state.count("dryad_fleet_upstream_5xx_total",
                            "5xx answers from replicas",
                            replica=slot.name)
                last = (status, payload, slot.name)
                continue
            return status, payload, slot.name
        if last is not None:
            return last
        return None, b"", None


def make_fleet_router(supervisor, host: str = "127.0.0.1", port: int = 0, *,
                      registry: Optional[Registry] = None,
                      max_inflight: int = 64,
                      bulk_max_inflight: Optional[int] = None,
                      model_caps: Optional[dict] = None,
                      request_timeout_s: float = 30.0,
                      min_healthy: int = 1,
                      auth_token: Optional[str] = None,
                      verbose: bool = False,
                      slo_budgets_ms: Optional[dict] = None,
                      slo_quantile: float = 0.99,
                      slo_breach_after: int = 3,
                      tail_window: int = 512,
                      tail_keep: int = 16,
                      drift_budget_psi: Optional[float] = None,
                      drift_breach_after: int = 2,
                      drift_top_k: int = 5) -> ThreadingHTTPServer:
    """Bind the fleet router (port 0 picks a free one; read it back from
    ``httpd.server_address``); the caller runs ``serve_forever()`` /
    ``shutdown()``, exactly like ``serve.http.make_http_server``.
    ``slo_budgets_ms`` declares per-priority p-quantile budgets
    (obs/slo.py defaults when None); ``tail_window``/``tail_keep`` shape
    the merged ``/trace`` endpoint's tail sampling.  ``drift_budget_psi``
    arms the model-drift layer (r18): replica drift counts are merged
    exactly on the scrape cadence, ``GET /drift`` reports per-model PSI
    verdicts, and a sustained breach journals ``drift_breach`` + warns in
    /healthz payloads (None = drift reporting off)."""
    httpd = ThreadingHTTPServer((host, port), _Handler)
    httpd.daemon_threads = True
    httpd.verbose = verbose
    httpd.state = _RouterState(
        supervisor, registry=registry, max_inflight=max_inflight,
        bulk_max_inflight=bulk_max_inflight, model_caps=model_caps,
        request_timeout_s=request_timeout_s, min_healthy=min_healthy,
        auth_token=auth_token, slo_budgets_ms=slo_budgets_ms,
        slo_quantile=slo_quantile, slo_breach_after=slo_breach_after,
        tail_window=tail_window, tail_keep=tail_keep,
        drift_budget_psi=drift_budget_psi,
        drift_breach_after=drift_breach_after, drift_top_k=drift_top_k)
    return httpd


class FleetRouter:
    """Bind-and-serve wrapper around ``make_fleet_router`` (the shape of
    ``obs.exporter.MetricsExporter``): ``start()`` serves on a daemon
    thread, ``stop()`` shuts down; tests and the fleet bench drive it
    in-process."""

    def __init__(self, supervisor, host: str = "127.0.0.1", port: int = 0,
                 **kw):
        self._args = (supervisor, host, port)
        self._kw = kw
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    @property
    def host(self) -> str:
        return self._httpd.server_address[0] if self._httpd else self._args[1]

    @property
    def port(self) -> int:
        return self._httpd.server_address[1] if self._httpd else self._args[2]

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    @property
    def state(self) -> "Optional[_RouterState]":
        """The live router state (None before start()) — the capacity
        controller's signal source in tests and the smoke."""
        return self._httpd.state if self._httpd is not None else None

    def start(self) -> "FleetRouter":
        if self._httpd is not None:
            return self
        supervisor, host, port = self._args
        self._httpd = make_fleet_router(supervisor, host, port, **self._kw)
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True,
                                        name="dryad-fleet-router")
        self._thread.start()
        return self

    def stop(self) -> None:
        if self._httpd is None:
            return
        self._httpd.shutdown()
        self._httpd.server_close()
        self._httpd = None
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def __enter__(self) -> "FleetRouter":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()


def main_loop(httpd: ThreadingHTTPServer, quiet: bool = False) -> None:
    """Foreground serve_forever with a clean KeyboardInterrupt exit (the
    CLI's inner loop; split out so tests can cover the construction
    without serving)."""
    try:
        httpd.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        httpd.server_close()
        if not quiet:
            print("fleet router stopped", file=sys.stderr)
