"""Replica lifecycle supervision: spawn N, probe, respawn, swap.

The training supervisor (resilience/supervisor.py) survives device faults
by classify -> degrade -> resume; this is its serving twin.  The fault
surface is different — a replica is a PROCESS, so death is an exit code
and sickness is a failing ``/healthz`` — but the policy machinery is
deliberately the same ``RetryPolicy`` (budgeted retries, exponential
backoff) and the same append-only ``RunJournal``, so a fleet incident
reads exactly like a training incident: a stream of classified events
with every decision on the record.

Detection model (the monitor thread, one pass per ``probe_interval_s``):

* **crash** — ``proc.poll()`` returns an exit code.  Respawn under the
  budget.  An injected ``replica_crash`` drill dies with the recorded
  ``REPLICA_CRASH_EXIT`` so tests can tell drills from real bugs.
* **hang / slow health** — the process is alive but ``/healthz`` times
  out or refuses.  ``unhealthy_after`` consecutive bad probes take the
  replica out of routing (the cheap, reversible remedy — the router
  simply stops picking it); ``recycle_after`` consecutive bad probes
  kill + respawn it (the expensive remedy, same budget as a crash).
* **stuck-503** — ``/healthz`` ANSWERS, but 503 (the serve tripwire
  latched, or the ``reject_503`` drill): same ladder — out of routing
  first, recycled if it never recovers.  A 503 that clears (e.g. the
  deploy-window recompile case) costs only the routing pause.

Respawn budget is PER SLOT: ``policy.retry_budget`` respawns, backoff
``policy.backoff_s(n)`` between attempts, then the slot FAILS CLOSED
(journaled; the rest of the fleet keeps serving — shared-nothing means
one bad slot never takes the pool down).  Drill faults ride the spawn
environment for generation 0 only: a respawned replica is clean, so a
crash drill proves exactly one death + one recovery.

``rolling_push`` is the zero-drop deploy: replica by replica it DRAINS
(router stops routing to the slot, in-flight requests finish at the
version they resolved — per-process pinning is serve/registry.py's
submit-time contract), then loads + activates the new model through the
replica's own ``/models/load``, waits for health, and restores routing.
In-flight requests are never cut: a drain that cannot reach zero within
``drain_timeout_s`` ABORTS that replica's swap (old model keeps serving)
rather than dropping work.  NOTE a later respawn re-runs the spawn argv,
so a respawned replica comes back with the spawn-time model set — ship a
push by also updating the argv the supervisor was built with (the CLI
does this by restarting the fleet on the new path).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Optional

from dryad_tpu.fleet.replica import ReplicaProcess, ReplicaStartupError
from dryad_tpu.obs.registry import Registry, default_registry
from dryad_tpu.resilience.faults import REPLICA_FAULTS_ENV
from dryad_tpu.resilience.journal import RunJournal
from dryad_tpu.resilience.policy import RetryPolicy


class ReplicaSlot:
    """One position in the fleet: the live process (across respawns) plus
    the routing state the router reads.  ``inflight`` is the router's
    in-flight request count against this slot — the drain condition, so
    it is the one field that must never tear: router handler threads
    inc/dec it while ``rolling_push`` waits on it reaching zero.  The
    remaining flags (``healthy``/``draining``/...) are single-writer
    (monitor or push path) with benignly racy reads — ``routable`` is a
    point-in-time answer by design, and the router re-checks it AFTER
    the in-flight mark to close the pick->inc window."""

    def __init__(self, index: int):
        self.index = index
        self.name = f"r{index}"
        self.proc: Optional[ReplicaProcess] = None
        self.healthy = False
        self.draining = False
        self.recovering = False
        # r22 elastic capacity: a slot being drained OUT OF THE FLEET
        # (scale-down).  Single-writer (the retiring thread) like
        # ``draining``; the monitor must never respawn a retiring slot —
        # resurrection would undo the capacity decision mid-drain.
        self.retiring = False
        self.fail_closed = False
        self.generation = 0
        self.respawns = 0
        self.consecutive_bad = 0
        self.last_status: Optional[int] = None
        # perf→wall clock offset captured at registration (r17): the
        # router's merged /trace aligns this slot's spans with it; reset
        # per generation (a respawn is a new perf_counter origin).
        # Single-writer (the spawning thread) with benignly racy reads,
        # like the health flags above.
        self.clock_offset: Optional[float] = None
        self._inflight = 0        # guarded-by: _lock
        self._lock = threading.Lock()

    @property
    def inflight(self) -> int:
        with self._lock:
            return self._inflight

    def inflight_inc(self) -> None:
        with self._lock:
            self._inflight += 1

    def inflight_dec(self) -> None:
        with self._lock:
            self._inflight -= 1

    @property
    def routable(self) -> bool:
        """Whether the router may pick this slot for a new request."""
        return (self.healthy and not self.draining and not self.retiring
                and not self.fail_closed
                and self.proc is not None and self.proc.alive)

    def state(self) -> dict:
        """The observability view (/healthz + /stats on the router)."""
        return {
            "healthy": self.healthy, "draining": self.draining,
            "retiring": self.retiring,
            "fail_closed": self.fail_closed, "generation": self.generation,
            "respawns": self.respawns, "inflight": self.inflight,
            "alive": self.proc is not None and self.proc.alive,
            "url": (self.proc.url if self.proc is not None
                    and self.proc.host is not None else None),
        }


class FleetSupervisor:
    """Own ``n_replicas`` serve processes; keep them alive and swappable.

    ``make_argv(index, port_file)`` builds each replica's command line
    (``fleet.replica.serve_argv`` for production; tests pass a stub).
    ``fault_env`` maps replica index -> a ``DRYAD_REPLICA_FAULTS`` spec
    string armed for that replica's FIRST generation only (drills).
    ``journal`` takes a path (owned/closed here) or an open RunJournal,
    exactly like ``supervise_train``.

    Lock contract (r15, extended r22): three locks, committed order
    ``_swap_lock`` before ``_slots_lock`` before ``_journal_lock``
    (analysis/goldens/lock_order.json).  ``_journal_lock``
    guards the journal HANDLE — monitor, recovery threads, and the push
    path all journal concurrently, and ``stop()`` swaps the owned handle
    to None under it (each ``event()`` line is additionally atomic under
    the journal's own lock).  ``_swap_lock`` is a pure serialization
    mutex — one rolling push at a time; nothing else ever acquires it,
    which is why blocking inside it (the drain wait) is waived rather
    than redesigned.  ``_slots_lock`` (r22) guards the MUTABLE slot
    registry: the autoscaler adds and retires slots at runtime, so every
    reader takes a point-in-time snapshot through the ``slots`` property
    (append/remove are the only mutations, both short critical
    sections); slot STATE still crosses threads via each slot's own
    lock (the in-flight count) and single-writer flags.
    """

    GUARDED_BY = {"_journal": "_journal_lock", "_slots": "_slots_lock",
                  "_next_index": "_slots_lock"}

    def __init__(self, make_argv, n_replicas: int, *,
                 policy: Optional[RetryPolicy] = None,
                 journal: "RunJournal | str | None" = None,
                 registry: Optional[Registry] = None,
                 probe_interval_s: float = 0.25,
                 probe_timeout_s: float = 2.0,
                 unhealthy_after: int = 2,
                 recycle_after: int = 8,
                 startup_timeout_s: float = 60.0,
                 fault_env: Optional[dict] = None,
                 log_dir: Optional[str] = None):
        if n_replicas < 1:
            raise ValueError("a fleet needs at least one replica")
        if recycle_after < unhealthy_after:
            raise ValueError("recycle_after must be >= unhealthy_after "
                             "(out-of-routing is the first rung)")
        self.make_argv = make_argv
        self.policy = policy or RetryPolicy()
        self.probe_interval_s = float(probe_interval_s)
        self.probe_timeout_s = float(probe_timeout_s)
        self.unhealthy_after = int(unhealthy_after)
        self.recycle_after = int(recycle_after)
        self.startup_timeout_s = float(startup_timeout_s)
        self.fault_env = dict(fault_env or {})
        self.log_dir = log_dir
        self._slots = [ReplicaSlot(i) for i in range(int(n_replicas))]
        self._next_index = int(n_replicas)
        self._slots_lock = threading.Lock()
        self._registry = registry
        self._own_journal = isinstance(journal, (str, os.PathLike))
        self._journal = (RunJournal(os.fspath(journal)) if self._own_journal
                         else journal)
        # the readable journal location (when there is one): the router's
        # merged /trace reads it back as the fleet's annotation track
        self.journal_path = (os.fspath(journal) if self._own_journal
                             else getattr(journal, "path", None))
        self._stop = threading.Event()
        self._monitor: Optional[threading.Thread] = None
        self._swap_lock = threading.Lock()
        self._journal_lock = threading.Lock()
        self._recoveries: list[threading.Thread] = []

    # ---- plumbing ----------------------------------------------------------
    def _reg(self) -> Registry:
        return (self._registry if self._registry is not None
                else default_registry())

    def _event(self, kind: str, /, **fields) -> None:
        # recovery threads journal concurrently with the monitor — one
        # lock keeps event lines whole (and guards the close in stop())
        with self._journal_lock:
            if self._journal is not None:
                self._journal.event(kind, **fields)

    def journal(self, kind: str, /, **fields) -> None:
        """Public journal passthrough for fleet-level observers that own
        no journal of their own — the router's drift gate records its
        ``drift_breach`` verdicts here (r18), so a model-quality incident
        reads in the same flight recorder as a crash or a swap."""
        self._event(kind, **fields)

    @property
    def slots(self) -> "list[ReplicaSlot]":
        """Point-in-time snapshot of the slot registry.  The list is
        MUTABLE at runtime (r22: the autoscaler adds/retires slots), so
        every iteration — monitor, router, push, teardown — runs over
        its own snapshot; the slot OBJECTS stay shared and carry their
        own synchronization."""
        with self._slots_lock:
            return list(self._slots)

    def gauge_replicas(self) -> None:
        """The fleet census gauge the capacity loop (and operators)
        read: ``dryad_fleet_replicas{state=...}``."""
        reg = self._reg()
        if not reg.enabled:
            return
        slots = self.slots
        fam = reg.gauge("dryad_fleet_replicas",
                        "Fleet slot census by state")
        fam.labels(state="total").set(len(slots))
        fam.labels(state="routable").set(
            sum(1 for s in slots if s.routable))
        fam.labels(state="retiring").set(
            sum(1 for s in slots if s.retiring))
        fam.labels(state="recovering").set(
            sum(1 for s in slots if s.recovering))
        fam.labels(state="fail_closed").set(
            sum(1 for s in slots if s.fail_closed))

    def _gauge_healthy(self, slot: ReplicaSlot) -> None:
        reg = self._reg()
        if reg.enabled:
            reg.gauge("dryad_fleet_replica_healthy",
                      "1 while the replica is in routing").labels(
                replica=slot.name).set(1 if slot.routable else 0)

    def _count(self, name: str, help: str, slot: ReplicaSlot) -> None:
        reg = self._reg()
        if reg.enabled:
            reg.counter(name, help).labels(replica=slot.name).inc()

    # ---- lifecycle ---------------------------------------------------------
    def start(self) -> "FleetSupervisor":
        self._event("fleet_start", replicas=len(self.slots),
                    retry_budget=self.policy.retry_budget,
                    probe_interval_s=self.probe_interval_s)
        for slot in self.slots:
            if not self._spawn(slot, first=True):
                # budget burned before the slot ever served: fail closed
                # and keep bringing up the REST of the fleet
                continue
        if not any(s.routable for s in self.slots):
            self.stop()
            raise ReplicaStartupError("no replica became ready at fleet "
                                      "start (see the journal / logs)")
        self._monitor = threading.Thread(target=self._monitor_loop,
                                         daemon=True,
                                         name="dryad-fleet-monitor")
        self._monitor.start()
        self.gauge_replicas()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._monitor is not None:
            self._monitor.join(timeout=5.0)
            self._monitor = None
        # terminate children FIRST: a recovery thread mid-ready-wait sees
        # its child die, raises, observes _stop, and exits — then the
        # joins below converge instead of waiting out a startup timeout
        for slot in self.slots:
            if slot.proc is not None:
                slot.proc.stop()
            slot.healthy = False
            self._gauge_healthy(slot)
        for t in self._recoveries:
            t.join(timeout=5.0)
        self._recoveries = []
        # one more sweep: a recovery thread may have spawned a replica
        # between the first sweep and its _stop check
        for slot in self.slots:
            if slot.proc is not None:
                slot.proc.stop()
        self._event("fleet_stop",
                    respawns=sum(s.respawns for s in self.slots))
        with self._journal_lock:
            if self._own_journal and self._journal is not None:
                self._journal.close()
                self._journal = None

    def __enter__(self) -> "FleetSupervisor":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # ---- spawn / recover ---------------------------------------------------
    def _spawn_env(self, slot: ReplicaSlot) -> dict:
        """Drill faults arm generation 0 ONLY: a respawned replica is
        clean, so one crash drill proves one death + one recovery instead
        of a crash loop that burns the budget.  The override is ALWAYS
        returned (empty when not arming) because replicas inherit this
        process's environment — a DRYAD_REPLICA_FAULTS set on the fleet
        process itself would otherwise re-arm EVERY generation and turn
        one drill into a budget-exhausting fleet outage; supervisor-owned
        replicas take drills only through ``fault_env``."""
        if slot.generation == 0 and slot.index in self.fault_env:
            return {REPLICA_FAULTS_ENV: self.fault_env[slot.index]}
        return {REPLICA_FAULTS_ENV: ""}

    def _spawn(self, slot: ReplicaSlot, first: bool = False) -> bool:
        """Spawn (or respawn) the slot's process; on startup failure keep
        retrying under the slot's budget.  True when the slot serves."""
        while True:
            if self._stop.is_set():
                # a fleet stop() mid-recovery must not leak a fresh
                # subprocess the teardown loop will never see
                return False
            self._event("replica_spawn", replica=slot.name,
                        generation=slot.generation, first=first)
            proc = ReplicaProcess(
                lambda pf: self.make_argv(slot.index, pf),
                name=f"{slot.name}g{slot.generation}",
                env=self._spawn_env(slot),
                startup_timeout_s=self.startup_timeout_s,
                log_dir=self.log_dir)
            # registered on the slot BEFORE the (long) ready wait: a fleet
            # stop() terminates this child even while it is still paying
            # its jax import — the slot is not routable until healthy
            # flips below, so nothing routes to the half-born process
            slot.proc = proc
            try:
                proc.start()
            except ReplicaStartupError as e:
                self._event("replica_spawn_failed", replica=slot.name,
                            generation=slot.generation,
                            exit_code=e.exit_code, message=str(e)[:300])
                proc.stop()
                if not self._charge_budget(slot):
                    return False
                continue
            if self._stop.is_set():
                proc.stop()
                return False
            # the registration-time clock handshake: map this process
            # generation's perf_counter onto the wall clock so the merged
            # fleet /trace can align its spans (None for replicas that do
            # not speak /clock — stubs)
            slot.clock_offset = proc.clock_offset()
            slot.healthy = True
            slot.consecutive_bad = 0
            slot.last_status = 200
            self._gauge_healthy(slot)
            self._event("replica_ready", replica=slot.name,
                        generation=slot.generation, url=proc.url,
                        clock_offset_s=slot.clock_offset)
            return True

    def _charge_budget(self, slot: ReplicaSlot) -> bool:
        """One respawn attempt against the slot's budget; sleeps the
        backoff.  False (and fail-closed) when the budget is exhausted."""
        slot.respawns += 1
        if slot.respawns > self.policy.retry_budget:
            slot.fail_closed = True
            slot.healthy = False
            self._gauge_healthy(slot)
            self._event("replica_fail_closed", replica=slot.name,
                        reason="retry_budget_exhausted",
                        respawns=slot.respawns - 1)
            return False
        sleep_s = self.policy.backoff_s(slot.respawns - 1)
        self._event("replica_backoff", replica=slot.name,
                    attempt=slot.respawns, sleep_s=sleep_s)
        if sleep_s > 0:
            # interruptible: a fleet stop() must not wait out a backoff
            self._stop.wait(sleep_s)
        slot.generation += 1
        return True

    def _recover(self, slot: ReplicaSlot, reason: str,
                 exit_code: Optional[int] = None) -> None:
        self._count("dryad_fleet_respawn_total",
                    "Replica respawns by the fleet supervisor", slot)
        slot.healthy = False
        self._gauge_healthy(slot)
        if slot.proc is not None:
            slot.proc.stop()
        self._event("replica_respawn", replica=slot.name, reason=reason,
                    exit_code=exit_code, generation=slot.generation)
        if self._charge_budget(slot):
            self._spawn(slot)

    def _recover_async(self, slot: ReplicaSlot, reason: str,
                       exit_code: Optional[int] = None) -> None:
        """Run the (slow: backoff + spawn + ready wait) recovery on its
        own thread so the monitor keeps probing the OTHER slots — a
        second failure during one slot's recovery must still be detected
        and taken out of routing.  ``slot.recovering`` keeps the monitor
        from double-dispatching the same slot."""
        slot.recovering = True

        def run() -> None:
            try:
                self._recover(slot, reason, exit_code=exit_code)
            finally:
                slot.recovering = False

        t = threading.Thread(target=run, daemon=True,
                             name=f"dryad-fleet-recover-{slot.name}")
        self._recoveries.append(t)
        self._recoveries = [x for x in self._recoveries
                            if x.is_alive() or x is t]
        t.start()

    # ---- monitor -----------------------------------------------------------
    @staticmethod
    def _monitor_skips(slot: ReplicaSlot) -> bool:
        """Slots the monitor must leave alone this pass.  ``retiring``
        is load-bearing (r22): a scale-down drains the slot and then
        KILLS its process — without the guard the monitor would read
        that planned death as a crash and respawn the replica the
        capacity decision just removed."""
        return (slot.fail_closed or slot.recovering or slot.retiring
                or slot.proc is None)

    def _monitor_loop(self) -> None:
        while not self._stop.wait(self.probe_interval_s):
            for slot in self.slots:
                if self._monitor_skips(slot):
                    continue
                if self._stop.is_set():
                    return
                code = slot.proc.poll()
                if code is not None:
                    self._count("dryad_fleet_crash_total",
                                "Replica processes found dead", slot)
                    self._event("replica_crash", replica=slot.name,
                                exit_code=code, generation=slot.generation)
                    self._recover_async(slot, "crash", exit_code=code)
                    continue
                status, _latency = slot.proc.health(
                    timeout_s=self.probe_timeout_s)
                slot.last_status = status
                if status == 200:
                    if not slot.healthy:
                        self._event("replica_recovered", replica=slot.name,
                                    generation=slot.generation)
                    slot.healthy = True
                    slot.consecutive_bad = 0
                    self._gauge_healthy(slot)
                    continue
                # alive but sick: probe timeout/refused (None) or a 503
                slot.consecutive_bad += 1
                if (slot.consecutive_bad == self.unhealthy_after
                        and slot.healthy):
                    slot.healthy = False
                    self._gauge_healthy(slot)
                    self._event("replica_unhealthy", replica=slot.name,
                                status=status,
                                consecutive=slot.consecutive_bad)
                if slot.consecutive_bad >= self.recycle_after:
                    self._count("dryad_fleet_recycle_total",
                                "Hung/stuck replicas killed and respawned",
                                slot)
                    self._event("replica_hang", replica=slot.name,
                                status=status,
                                consecutive=slot.consecutive_bad)
                    slot.consecutive_bad = 0
                    self._recover_async(slot, "hang")

    # ---- elastic capacity (r22) --------------------------------------------
    def add_slot(self) -> Optional[ReplicaSlot]:
        """Grow the fleet by one slot: register it, spawn its replica,
        wait for readiness (the same ``_spawn`` budgeted path a respawn
        takes).  The slot joins the registry BEFORE the long ready wait
        so a concurrent ``stop()`` terminates the half-born child in its
        normal sweep; ``recovering`` keeps the monitor off it until it
        serves.  Returns the routable slot, or None (spawn failed under
        budget, or the fleet is stopping — either way the registry is
        left without the dead slot)."""
        if self._stop.is_set():
            return None
        with self._slots_lock:
            slot = ReplicaSlot(self._next_index)
            self._next_index += 1
            slot.recovering = True
            self._slots.append(slot)
        try:
            ok = self._spawn(slot, first=True)
        finally:
            slot.recovering = False
        if not ok:
            with self._slots_lock:
                if slot in self._slots:
                    self._slots.remove(slot)
            self.gauge_replicas()
            return None
        self.gauge_replicas()
        return slot

    def retire_slot(self, name: str, *,
                    drain_timeout_s: float = 30.0) -> bool:
        """Shrink the fleet by one slot through the rolling push's
        zero-drop discipline: mark it non-routable (``retiring``), wait
        for its in-flight count to reach zero (requests already on the
        slot finish normally), then reap the process and drop the slot
        from the registry.  A drain that cannot reach zero within
        ``drain_timeout_s`` ABORTS the retire (the slot returns to
        routing) rather than dropping work.  The wait holds NO lock —
        ``retiring`` is a single-writer flag and the router re-checks
        ``routable`` after its in-flight mark, the same window-closing
        discipline ``draining`` rides."""
        slot = next((s for s in self.slots if s.name == name), None)
        if slot is None or slot.retiring:
            return False
        self._event("replica_retire", replica=slot.name,
                    inflight=slot.inflight)
        slot.retiring = True
        self._gauge_healthy(slot)
        deadline = time.monotonic() + float(drain_timeout_s)
        while slot.inflight > 0:
            if self._stop.is_set() or time.monotonic() > deadline:
                slot.retiring = False
                self._gauge_healthy(slot)
                self._event("replica_retire_aborted", replica=slot.name,
                            inflight=slot.inflight,
                            stopping=self._stop.is_set())
                return False
            time.sleep(0.002)
        if slot.proc is not None:
            slot.proc.stop()
        slot.healthy = False
        with self._slots_lock:
            if slot in self._slots:
                self._slots.remove(slot)
        self._gauge_healthy(slot)
        self._event("replica_retired", replica=slot.name,
                    generation=slot.generation, respawns=slot.respawns)
        self.gauge_replicas()
        return True

    # ---- routing / observability views -------------------------------------
    def routable_slots(self) -> list[ReplicaSlot]:
        return [s for s in self.slots if s.routable]

    def fleet_ok(self, min_healthy: int = 1) -> bool:
        return len(self.routable_slots()) >= int(min_healthy)

    def states(self) -> dict:
        return {s.name: s.state() for s in self.slots}

    # ---- rolling model push -------------------------------------------------
    def rolling_push(self, path: str, *, name: Optional[str] = None,
                     activate: bool = True,
                     drain_timeout_s: float = 30.0,
                     load_timeout_s: float = 120.0,
                     auth_token: Optional[str] = None) -> dict:
        """Push ``path`` replica by replica with a version-pinned drain.

        Per replica: stop routing to it (``draining``), wait for its
        in-flight count to reach zero (those requests complete at the
        version they resolved at submit — serve pins versions, so a swap
        can never change a queued request), POST ``/models/load`` through
        the replica's own registry (hot-swap + rollback stay available
        per process), wait for health, restore routing.  Replicas swap
        ONE at a time, so the rest of the pool serves throughout.

        Returns ``{"versions": {replica: version}, "errors": {replica:
        reason}, "skipped": [replica, ...]}``; a drain timeout or load
        failure aborts THAT replica's swap (it keeps serving the old
        model) and the push continues — zero in-flight requests are
        dropped in every outcome.
        """
        with self._swap_lock:
            versions: dict = {}
            errors: dict = {}
            skipped: list = []
            self._event("push_start", path=path, name=name,
                        activate=bool(activate))
            for slot in self.slots:
                if not slot.routable:
                    skipped.append(slot.name)
                    continue
                self._event("replica_drain", replica=slot.name,
                            inflight=slot.inflight)
                slot.draining = True
                self._gauge_healthy(slot)
                try:
                    deadline = time.monotonic() + float(drain_timeout_s)
                    while slot.inflight > 0:
                        if time.monotonic() > deadline:
                            raise TimeoutError(
                                f"drain timed out with {slot.inflight} "
                                "in flight")
                        # dryadlint: disable=no-blocking-under-lock -- the swap mutex has a sole acquirer; the drain wait under it IS the zero-drop design
                        time.sleep(0.002)
                    version = slot.proc.load_model(
                        path, name=name, activate=activate,
                        auth_token=auth_token, timeout_s=load_timeout_s)
                    status, _ = slot.proc.health(
                        timeout_s=self.probe_timeout_s)
                    if status != 200:
                        raise RuntimeError(
                            f"post-swap health probe answered {status}")
                    versions[slot.name] = version
                    self._event("replica_swapped", replica=slot.name,
                                version=version)
                except Exception as e:  # noqa: BLE001 — per-replica verdict
                    errors[slot.name] = repr(e)
                    self._event("replica_swap_failed", replica=slot.name,
                                message=str(e)[:300])
                finally:
                    slot.draining = False
                    self._gauge_healthy(slot)
            reg = self._reg()
            if reg.enabled:
                reg.counter("dryad_fleet_push_total",
                            "Rolling model pushes").inc()
            self._event("push_complete", swapped=sorted(versions),
                        errors=sorted(errors), skipped=skipped)
            return {"versions": versions, "errors": errors,
                    "skipped": skipped}
