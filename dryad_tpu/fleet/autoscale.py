"""SLO-driven elastic capacity for the serving fleet (r22).

The r17/r18 telemetry stack made the fleet measurable — exact merged
per-priority p99 gauges, sustained-breach SLO verdicts, admission
depth — and until now the router's only overload response was shedding.
``CapacityController`` closes the telemetry→capacity loop: it polls the
router's live signals and drives the supervisor's (now mutable) slot
registry, so a sustained p99 breach or admission-depth saturation ADDS
a replica before the router sheds users, and sustained headroom drains
one back out through the rolling push's zero-drop discipline.

Control discipline (the ``RetrainScheduler`` debounce idiom, applied to
capacity):

* **Hysteresis.**  Per-direction streak counters over the controller's
  own poll cadence: ``breach_after`` consecutive pressure polls admit a
  scale-up, ``idle_after`` consecutive headroom polls admit a
  scale-down.  Pressure is any sustained per-priority SLO verdict OR
  admission depth at ``saturation`` of ``max_inflight``; headroom is no
  breached window at all AND depth under ``idle_below`` of the cap.  A
  poll that is neither resets both streaks — flapping traffic never
  accumulates toward an action.
* **Exactly one action per burst.**  The decision is an atomic
  check-and-mark under one lock (``_admit``): an admitted action marks
  itself in flight, resets its streak, and runs on a worker thread
  OUTSIDE the lock; every refused poll is journaled as
  ``scale_skipped`` with a machine-readable reason — ``cooldown``,
  ``at-bound``, ``already-in-flight``, ``insufficient-sustain`` —
  debounced so a sustained condition journals each reason once, not
  once per poll.
* **Per-direction cooldowns.**  A finished action (either outcome)
  starts its direction's cooldown clock, so one breach burst yields one
  replica, not a ramp-to-max.
* **Bounds.**  Never below ``min_replicas``, never above
  ``max_replicas`` (the census counts slots that still represent
  capacity: not failed closed, not already retiring).

Every decision lands in the supervisor's journal next to crashes and
swaps (``scale_up`` / ``scale_down`` / ``scale_skipped`` /
``scale_failed``), and ``dryad_fleet_scale_*`` counters plus the
supervisor's ``dryad_fleet_replicas{state}`` census gauge mirror it for
scrapers.

This module is jax-free by lint (fleet-jax-free) and in the r15
concurrency-lint scope: ``GUARDED_BY`` is declared up front, blocking
work (spawn, ready wait, drain) never happens under the lock, and the
schedule harness's ``capacity-vs-breach-vs-push`` drill runs the real
class under the seeded scheduler.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional

from dryad_tpu.obs.registry import Registry, default_registry

#: the journaled refusal reasons (the drill and the smoke assert on
#: these exact strings)
SKIP_COOLDOWN = "cooldown"
SKIP_AT_BOUND = "at-bound"
SKIP_IN_FLIGHT = "already-in-flight"
SKIP_SUSTAIN = "insufficient-sustain"


class CapacityController:
    """Poll router signals, decide, drive the supervisor's slot pool.

    ``signals`` is a zero-argument callable returning the router's live
    view (``_RouterState.capacity_signals()`` in production; drills and
    tests inject their own):

    ``{"slo": {priority: verdict}, "inflight": int, "max_inflight": int,
    "slots": {name: {"inflight": int, ...}}}``

    where each verdict carries the ``SloGate`` keys (``breached``,
    ``sustained``).  The controller inherits the gate's hysteresis
    semantics — ``sustained`` already means ``breach_after`` consecutive
    over-budget windows — and layers its own per-direction sustain on
    top, so one slow request can never buy a replica.
    """

    GUARDED_BY = {
        "_up_streak": "_lock", "_down_streak": "_lock",
        "_cooldown_until": "_lock", "_action": "_lock",
        "_last_skip": "_lock", "_workers": "_lock",
        "_actions_total": "_lock",
    }

    def __init__(self, supervisor, signals: Callable[[], dict], *,
                 min_replicas: int, max_replicas: int,
                 breach_after: int = 2, idle_after: int = 4,
                 cooldown_up_s: float = 30.0,
                 cooldown_down_s: float = 60.0,
                 saturation: float = 0.8, idle_below: float = 0.25,
                 poll_interval_s: float = 1.0,
                 drain_timeout_s: float = 30.0,
                 registry: Optional[Registry] = None):
        if not 1 <= int(min_replicas) <= int(max_replicas):
            raise ValueError("need 1 <= min_replicas <= max_replicas")
        if int(breach_after) < 1 or int(idle_after) < 1:
            raise ValueError("breach_after and idle_after must be >= 1")
        self.supervisor = supervisor
        self._signals = signals
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.breach_after = int(breach_after)
        self.idle_after = int(idle_after)
        self.cooldown_s = {"up": float(cooldown_up_s),
                           "down": float(cooldown_down_s)}
        self.saturation = float(saturation)
        self.idle_below = float(idle_below)
        self.poll_interval_s = float(poll_interval_s)
        self.drain_timeout_s = float(drain_timeout_s)
        self._registry = registry
        self._lock = threading.Lock()
        self._up_streak = 0
        self._down_streak = 0
        self._cooldown_until = {"up": 0.0, "down": 0.0}
        self._action: Optional[str] = None
        self._last_skip: dict = {"up": None, "down": None}
        self._actions_total = {"up": 0, "down": 0}
        self._workers: list = []
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ---- plumbing (all called WITHOUT the lock held) ------------------------
    def _reg(self) -> Registry:
        return (self._registry if self._registry is not None
                else default_registry())

    def _journal(self, kind: str, /, **fields) -> None:
        jr = getattr(self.supervisor, "journal", None)
        if jr is not None:
            jr(kind, **fields)

    def _count(self, name: str, help: str, **labels) -> None:
        reg = self._reg()
        if reg.enabled:
            fam = reg.counter(name, help)
            (fam.labels(**labels) if labels else fam).inc()

    # ---- lifecycle ----------------------------------------------------------
    def start(self) -> "CapacityController":
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="dryad-fleet-autoscale")
        self._thread.start()
        return self

    def stop(self, timeout_s: float = 10.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout_s)
            self._thread = None
        with self._lock:
            workers = list(self._workers)
        for t in workers:
            # an in-flight scale-up unblocks when the supervisor stops
            # (its _spawn observes the stop event); best-effort join —
            # the supervisor's teardown sweep reaps any child either way
            t.join(timeout=timeout_s)

    def _loop(self) -> None:
        while not self._stop.wait(self.poll_interval_s):
            try:
                self.poke()
            except Exception as e:  # noqa: BLE001 — the loop must survive
                self._journal("autoscale_error", message=str(e)[:300])

    # ---- the decision pass --------------------------------------------------
    def _census(self) -> int:
        """Slots that still represent capacity (bound accounting):
        failed-closed slots serve nothing and retiring slots are already
        leaving, so neither counts against the bounds."""
        return sum(1 for s in self.supervisor.slots
                   if not s.fail_closed and not s.retiring)

    def _classify(self, sig: dict) -> tuple:
        """(pressure, headroom, why) from one signals sample."""
        slo = sig.get("slo") or {}
        inflight = int(sig.get("inflight") or 0)
        max_inflight = int(sig.get("max_inflight") or 0)
        sustained = sorted(p for p, v in slo.items() if v.get("sustained"))
        breached = sorted(p for p, v in slo.items()
                          if v.get("breached") or v.get("sustained"))
        saturated = (max_inflight > 0
                     and inflight >= self.saturation * max_inflight)
        pressure = bool(sustained) or saturated
        headroom = (not breached and max_inflight > 0
                    and inflight <= self.idle_below * max_inflight)
        why = {"inflight": inflight, "max_inflight": max_inflight,
               "slo_sustained": sustained, "saturated": saturated}
        return pressure, headroom, why

    def _admit(self, pressure: bool, headroom: bool,
               census: int) -> tuple:
        """The atomic check-and-mark: advance the streaks and either
        claim the action (marking it in flight so a concurrent poke —
        or the next poll during a slow spawn — cannot double-launch) or
        produce the refusal reason.  Returns ``(decision, direction,
        reason, journal_skip)``; everything else (journal, metrics, the
        action itself) happens OUTSIDE the lock.  The
        capacity-vs-breach-vs-push schedule drill reverts exactly this
        atomicity and proves the harness catches the double-launch."""
        now = time.monotonic()
        with self._lock:
            if pressure:
                self._down_streak = 0
                self._up_streak += 1
                direction, streak, sustain_n = ("up", self._up_streak,
                                                self.breach_after)
                bound_hit = census >= self.max_replicas
            elif headroom:
                self._up_streak = 0
                self._down_streak += 1
                direction, streak, sustain_n = ("down", self._down_streak,
                                                self.idle_after)
                bound_hit = census <= self.min_replicas
            else:
                self._up_streak = 0
                self._down_streak = 0
                self._last_skip = {"up": None, "down": None}
                return None, None, None, False
            if self._action is not None:
                reason = SKIP_IN_FLIGHT
            elif bound_hit:
                reason = SKIP_AT_BOUND
            elif streak < sustain_n:
                reason = SKIP_SUSTAIN
            elif now < self._cooldown_until[direction]:
                reason = SKIP_COOLDOWN
            else:
                self._action = direction
                if direction == "up":
                    self._up_streak = 0
                else:
                    self._down_streak = 0
                self._last_skip[direction] = None
                return ("scale_up" if direction == "up" else "scale_down",
                        direction, None, False)
            journal_skip = reason != self._last_skip[direction]
            self._last_skip[direction] = reason
            return None, direction, reason, journal_skip

    def poke(self) -> Optional[str]:
        """One decision pass (the poll loop's body; drills call it
        directly).  Returns the admitted decision kind or None."""
        sig = self._signals()
        pressure, headroom, why = self._classify(sig)
        census = self._census()
        decision, direction, reason, journal_skip = self._admit(
            pressure, headroom, census)
        self.supervisor.gauge_replicas()
        if decision is None:
            if journal_skip:
                self._journal("scale_skipped", direction=direction,
                              reason=reason, replicas=census, **why)
                self._count("dryad_fleet_scale_skipped_total",
                            "Refused capacity decisions by reason",
                            direction=direction, reason=reason)
            return None
        t = threading.Thread(
            target=self._run_action, args=(decision, census, why),
            daemon=True, name=f"dryad-fleet-scale-{direction}")
        with self._lock:
            self._workers = [w for w in self._workers if w.is_alive()]
            self._workers.append(t)
        t.start()
        return decision

    # ---- the actions (worker thread; no controller lock held) ---------------
    def _run_action(self, decision: str, census: int, why: dict) -> None:
        direction = "up" if decision == "scale_up" else "down"
        try:
            if decision == "scale_up":
                slot = self.supervisor.add_slot()
                if slot is not None:
                    self._journal("scale_up", replica=slot.name,
                                  replicas=census + 1, **why)
                    self._count("dryad_fleet_scale_up_total",
                                "Replicas added by the capacity loop")
                else:
                    self._journal("scale_failed", direction="up",
                                  replicas=census, **why)
            else:
                victim = self._pick_victim()
                if victim is not None and self.supervisor.retire_slot(
                        victim.name, drain_timeout_s=self.drain_timeout_s):
                    self._journal("scale_down", replica=victim.name,
                                  replicas=census - 1, **why)
                    self._count("dryad_fleet_scale_down_total",
                                "Replicas drained out by the capacity "
                                "loop")
                else:
                    self._journal("scale_failed", direction="down",
                                  replicas=census,
                                  replica=(victim.name if victim else None),
                                  **why)
        finally:
            now = time.monotonic()
            with self._lock:
                self._action = None
                self._cooldown_until[direction] = (
                    now + self.cooldown_s[direction])
                self._actions_total[direction] += 1
        self.supervisor.gauge_replicas()

    def _pick_victim(self):
        """Highest-index routable slot — the most recently added
        capacity leaves first, and the fleet never drains its last
        routable replica (capacity below ``min_replicas`` is a bound
        violation; zero routable is an outage)."""
        routable = self.supervisor.routable_slots()
        if len(routable) < 2:
            return None
        return max(routable, key=lambda s: s.index)

    # ---- observability ------------------------------------------------------
    def state(self) -> dict:
        with self._lock:
            return {
                "min_replicas": self.min_replicas,
                "max_replicas": self.max_replicas,
                "up_streak": self._up_streak,
                "down_streak": self._down_streak,
                "action_in_flight": self._action,
                "cooldown_until": dict(self._cooldown_until),
                "actions_total": dict(self._actions_total),
            }
