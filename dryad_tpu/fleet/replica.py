"""One serve replica as a supervised subprocess.

``ReplicaProcess`` owns exactly the mechanics a fleet supervisor needs:
spawn the process, discover where it bound (the serve CLI's
``--port-file`` handshake — replicas bind port 0, so the OS picks a free
port and the replica writes ``host port`` once it is LISTENING, which
makes readiness detection race-free), probe its ``/healthz``, send it
HTTP requests, and kill it.  Everything is stdlib (``subprocess`` +
``http.client``): the fleet package is host-side and jax-free by lint,
exactly like ``dryad_tpu/obs`` — the replicas own the devices, the
supervisor only owns processes.

The command line is caller-supplied (``make_argv(port_file) -> argv``):
production spawns ``python -m dryad_tpu serve ...`` (``serve_argv``
below), tests spawn a protocol stub that speaks the same four endpoints
without paying the jax import.  Fault drills ride the environment
(``resilience.faults.REPLICA_FAULTS_ENV``), so the SAME spawn path runs
clean replicas and drilled ones.
"""

from __future__ import annotations

import http.client
import json
import os
import subprocess
import sys
import tempfile
import time
from typing import Optional, Sequence


class ReplicaStartupError(RuntimeError):
    """The replica never became ready (exited early, or the port-file /
    health handshake timed out).  ``exit_code`` is the process's exit
    status when it died, None when it was still running (hung startup)."""

    def __init__(self, message: str, exit_code: Optional[int] = None):
        super().__init__(message)
        self.exit_code = exit_code


def serve_argv(model_specs: Sequence[str], port_file: str, *,
               backend: str = "auto", host: str = "127.0.0.1",
               max_batch_rows: Optional[int] = None,
               max_wait_ms: Optional[float] = None,
               queue_size: Optional[int] = None,
               warmup: bool = False,
               drift_window: Optional[int] = None,
               auth_token: Optional[str] = None,
               python: Optional[str] = None) -> list[str]:
    """The production replica command: ``python -m dryad_tpu serve`` on
    port 0 with the port-file handshake.  ``model_specs`` are the serve
    CLI's ``--model`` values (paths or ``NAME=path`` aliases)."""
    argv = [python or sys.executable, "-m", "dryad_tpu", "serve",
            "--host", host, "--port", "0", "--port-file", port_file,
            "--backend", backend, "--quiet"]
    for spec in model_specs:
        argv += ["--model", spec]
    if max_batch_rows is not None:
        argv += ["--max-batch-rows", str(int(max_batch_rows))]
    if max_wait_ms is not None:
        argv += ["--max-wait-ms", str(float(max_wait_ms))]
    if queue_size is not None:
        argv += ["--queue-size", str(int(queue_size))]
    if warmup:
        argv += ["--warmup"]
    if drift_window is not None:
        argv += ["--drift-window", str(int(drift_window))]
    if auth_token:
        argv += ["--auth-token", auth_token]
    return argv


class ReplicaProcess:
    """Spawn + address + probe one replica subprocess."""

    def __init__(self, make_argv, *, name: str = "r0",
                 env: Optional[dict] = None,
                 startup_timeout_s: float = 60.0,
                 log_dir: Optional[str] = None):
        self.make_argv = make_argv
        self.name = name
        self.env = dict(env) if env is not None else None
        self.startup_timeout_s = float(startup_timeout_s)
        self._log_dir = log_dir
        self.proc: Optional[subprocess.Popen] = None
        self.host: Optional[str] = None
        self.port: Optional[int] = None
        self.log_path: Optional[str] = None
        self._port_file: Optional[str] = None

    # ---- lifecycle ---------------------------------------------------------
    def start(self) -> "ReplicaProcess":
        """Spawn and wait until the replica is LISTENING and /healthz
        answers 200; raises ReplicaStartupError otherwise.  Idempotence is
        the caller's job — a live replica must be stopped first."""
        if self.proc is not None and self.proc.poll() is None:
            raise RuntimeError(f"replica {self.name} is already running")
        fd, self._port_file = tempfile.mkstemp(prefix=f"dryad-{self.name}-",
                                               suffix=".port")
        os.close(fd)
        os.unlink(self._port_file)          # the replica creates it when ready
        argv = self.make_argv(self._port_file)
        log_dir = self._log_dir or tempfile.gettempdir()
        self.log_path = os.path.join(log_dir, f"dryad-replica-{self.name}.log")
        log = open(self.log_path, "ab")
        try:
            env = dict(os.environ, **self.env) if self.env else None
            self.proc = subprocess.Popen(argv, stdout=log, stderr=log, env=env)
        finally:
            log.close()                      # the child holds its own handle
        self._await_ready()
        return self

    def _await_ready(self) -> None:
        deadline = time.monotonic() + self.startup_timeout_s
        while time.monotonic() < deadline:
            code = self.proc.poll()
            if code is not None:
                raise ReplicaStartupError(
                    f"replica {self.name} exited with code {code} before "
                    f"becoming ready (log: {self.log_path})", exit_code=code)
            if self.host is None and os.path.exists(self._port_file):
                try:
                    with open(self._port_file) as f:
                        host, port = f.read().split()
                    self.host, self.port = host, int(port)
                except (ValueError, OSError):
                    pass                     # partially written; retry
            if self.host is not None:
                status, _ = self.health(timeout_s=1.0)
                if status == 200:
                    return
            time.sleep(0.02)
        code = self.proc.poll()
        raise ReplicaStartupError(
            f"replica {self.name} not ready after {self.startup_timeout_s}s "
            f"(log: {self.log_path})", exit_code=code)

    def poll(self) -> Optional[int]:
        """The process exit code, or None while it runs."""
        return self.proc.poll() if self.proc is not None else None

    @property
    def alive(self) -> bool:
        return self.proc is not None and self.proc.poll() is None

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def stop(self, grace_s: float = 3.0) -> Optional[int]:
        """Terminate (then kill) the process; returns the exit code."""
        if self.proc is None:
            return None
        if self.proc.poll() is None:
            self.proc.terminate()
            try:
                self.proc.wait(timeout=grace_s)
            except subprocess.TimeoutExpired:
                self.proc.kill()
                self.proc.wait()
        if self._port_file and os.path.exists(self._port_file):
            try:
                os.unlink(self._port_file)
            except OSError:
                pass
        return self.proc.poll()

    # ---- wire --------------------------------------------------------------
    def request(self, method: str, path: str, body: Optional[bytes] = None,
                headers: Optional[dict] = None,
                timeout_s: float = 10.0) -> tuple[int, bytes]:
        """One HTTP round trip to the replica; raises OSError-family on
        connection failure (the caller classifies)."""
        conn = http.client.HTTPConnection(self.host, self.port,
                                          timeout=timeout_s)
        try:
            conn.request(method, path, body=body, headers=headers or {})
            resp = conn.getresponse()
            return resp.status, resp.read()
        finally:
            conn.close()

    def clock_offset(self, timeout_s: float = 2.0) -> Optional[float]:
        """The replica's perf_counter→wall-clock offset via the
        auth-exempt ``GET /clock`` handshake: ``remote_perf + offset ≈
        wall``, with the local round trip's midpoint standing in for the
        instant the replica sampled its clocks (halves the RTT error).
        None when the replica does not speak /clock (e.g. a protocol
        stub) — the trace assembly then falls back to the replica's
        self-reported offset or renders unaligned."""
        t_a = time.time()
        try:
            status, body = self.request("GET", "/clock", timeout_s=timeout_s)
        except OSError:
            return None
        t_b = time.time()
        if status != 200:
            return None
        try:
            perf = float(json.loads(body)["perf_s"])
        except (ValueError, KeyError, TypeError):
            return None
        return (t_a + t_b) / 2.0 - perf

    def health(self, timeout_s: float = 2.0) -> tuple[Optional[int], float]:
        """(/healthz status or None on connect/timeout failure, latency)."""
        t0 = time.monotonic()
        try:
            status, _ = self.request("GET", "/healthz", timeout_s=timeout_s)
        except OSError:
            return None, time.monotonic() - t0
        return status, time.monotonic() - t0

    def load_model(self, path: str, *, name: Optional[str] = None,
                   activate: bool = True, auth_token: Optional[str] = None,
                   timeout_s: float = 120.0) -> int:
        """POST /models/load on the replica; returns the new version.
        The generous default timeout covers a cold compile of the new
        version's buckets on a device replica."""
        body = {"path": path, "activate": bool(activate)}
        if name is not None:
            body["name"] = name
        headers = {"Content-Type": "application/json"}
        if auth_token:
            headers["Authorization"] = f"Bearer {auth_token}"
        status, payload = self.request("POST", "/models/load",
                                       json.dumps(body).encode(),
                                       headers, timeout_s=timeout_s)
        if status != 200:
            raise RuntimeError(
                f"replica {self.name} /models/load -> {status}: "
                f"{payload[:300]!r}")
        return int(json.loads(payload)["version"])
