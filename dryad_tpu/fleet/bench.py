"""Closed-loop fleet benchmark: rows/s through the router, over HTTP.

The serve bench (serve/bench.py) measures one in-process PredictServer;
this measures the whole fleet path — router admission, forwarding, the
replicas' own HTTP front ends — with REAL subprocess replicas, which is
the ISSUE's acceptance shape ("real subprocess replicas, not mocked").
It is deliberately jax-free (the fleet lint covers it): every number
comes back over the wire, so the bench measures what a client sees, not
what the process could do in-process.

Two arms, reported together by ``scripts/bench_serve.py --fleet``:

* **scaling** — the same closed loop against 1/2/4-replica fleets
  (``fleet_rows_per_s_n1/n2/n4`` + per-arm spreads).  The CLAUDE.md
  discipline carries over: closed loop (clients wait for each answer, so
  concurrency is exact), min-free measurement is replaced by arms +
  spread fields because walls here are end-to-end HTTP, and the payload
  bytes are pre-encoded so the client loop measures the FLEET, not
  ``json.dumps``.
* **rolling-swap drill** — a 2-replica fleet under continuous interactive
  load takes a ``/models/push`` mid-loop; the drill asserts zero failed
  requests (the zero-drop contract) and records the swap wall and the
  version mix the clients observed (both versions MUST appear: proof the
  swap really happened under load, not after it).
"""

from __future__ import annotations

import http.client
import json
import random
import threading
import time
from typing import Optional, Sequence

from dryad_tpu.fleet.replica import serve_argv
from dryad_tpu.fleet.router import FleetRouter
from dryad_tpu.fleet.supervisor import FleetSupervisor
from dryad_tpu.obs.registry import (REQUEST_LATENCY, Registry,
                                    hist_quantile)
from dryad_tpu.resilience.policy import RetryPolicy

SPREAD_SUSPECT = 0.05    # per-arm spread above this flags the capture
#: the priorities the bench reports percentiles for (router admission
#: classes; bulk gets its own short loop so its series is populated)
BENCH_PRIORITIES = ("interactive", "bulk")


def _payloads(num_features: int, sizes: Sequence[int], seed: int) -> dict:
    """size -> pre-encoded /predict body bytes (one per size: the loop
    must measure the fleet, not request construction)."""
    rng = random.Random(seed)
    out = {}
    for n in sizes:
        rows = [[rng.uniform(-2.0, 2.0) for _ in range(num_features)]
                for _ in range(n)]
        out[n] = json.dumps({"rows": rows}).encode()
    return out


def _closed_loop(host: str, port: int, payloads: dict, *, clients: int,
                 duration_s: float, seed: int,
                 priority: str = "interactive",
                 trace: bool = False,
                 on_response=None) -> dict:
    """Run the closed loop; returns requests/rows/failures and elapsed.
    ``on_response(status, body_bytes)`` (when set) sees every answer —
    the swap drill uses it to tally versions.  With ``trace=True`` every
    request carries a unique ``X-Dryad-Trace`` id and the loop counts
    responses whose echoed id does not round-trip
    (``trace_mismatches``; a successful answer MUST echo the id)."""
    sizes = sorted(payloads)
    counts = [0] * clients
    rows = [0] * clients
    failures = [0] * clients
    mismatches = [0] * clients
    barrier = threading.Barrier(clients + 1)
    stop_at = [float("inf")]

    def client(ci: int) -> None:
        crng = random.Random(seed + 7919 * (ci + 1))
        conn = http.client.HTTPConnection(host, port, timeout=30.0)
        headers = {"Content-Type": "application/json",
                   "X-Dryad-Priority": priority}
        barrier.wait()
        try:
            while time.perf_counter() < stop_at[0]:
                n = crng.choice(sizes)
                if trace:
                    headers["X-Dryad-Trace"] = (
                        f"bench{seed & 0xffff:04x}{ci:02x}{counts[ci]:06x}")
                try:
                    conn.request("POST", "/predict", body=payloads[n],
                                 headers=headers)
                    resp = conn.getresponse()
                    body = resp.read()
                    status = resp.status
                    echoed = resp.getheader("X-Dryad-Trace")
                except (OSError, http.client.HTTPException):
                    conn.close()
                    conn = http.client.HTTPConnection(host, port,
                                                      timeout=30.0)
                    status, body, echoed = 0, b"", None
                counts[ci] += 1
                if status == 200:
                    rows[ci] += n
                    if trace and echoed != headers["X-Dryad-Trace"]:
                        mismatches[ci] += 1
                else:
                    failures[ci] += 1
                if on_response is not None:
                    on_response(status, body)
        finally:
            conn.close()

    threads = [threading.Thread(target=client, args=(ci,), daemon=True)
               for ci in range(clients)]
    for t in threads:
        t.start()
    stop_at[0] = time.perf_counter() + float(duration_s)
    barrier.wait()
    t0 = time.perf_counter()
    for t in threads:
        t.join()
    elapsed = time.perf_counter() - t0
    return {"requests": sum(counts), "rows": sum(rows),
            "failures": sum(failures), "elapsed_s": elapsed,
            "trace_mismatches": sum(mismatches),
            "rows_per_s": sum(rows) / elapsed if elapsed > 0 else 0.0}


def _start_fleet(model_path: str, n_replicas: int, *, backend: str,
                 max_batch_rows: int, max_wait_ms: float,
                 warmup: bool, startup_timeout_s: float,
                 max_inflight: int) -> tuple:
    def make_argv(index: int, port_file: str) -> list:
        return serve_argv([model_path], port_file, backend=backend,
                          max_batch_rows=max_batch_rows,
                          max_wait_ms=max_wait_ms, warmup=warmup)

    # a PRIVATE registry per fleet: the router's per-priority latency
    # histograms are what the bench reads back as p50/p95/p99, so they
    # must not mix with a previous arm's (or the process default's)
    reg = Registry()
    sup = FleetSupervisor(make_argv, n_replicas,
                          policy=RetryPolicy(backoff_base_s=0.1),
                          registry=reg,
                          startup_timeout_s=startup_timeout_s)
    sup.start()
    router = FleetRouter(sup, registry=reg,
                         max_inflight=max_inflight).start()
    return sup, router, reg


def _replica_layouts(sup: FleetSupervisor) -> Optional[str]:
    """The fleet's served predict layout ("packed"/"legacy", r21), read
    over the wire from one routable replica's ``/stats`` (the registry's
    ``memory.staged_layouts`` block).  This module is jax-free by lint,
    so the layout is observed exactly as an operator would see it — via
    HTTP, never by loading the model.  None when no replica answers or
    the replica predates the field (protocol stubs in tests)."""
    for slot in sup.routable_slots():
        if slot.proc is None:
            continue
        try:
            status, payload = slot.proc.request("GET", "/stats",
                                                timeout_s=5.0)
            if status != 200:
                continue
            layouts = (json.loads(payload).get("memory") or {}).get(
                "staged_layouts") or {}
            if layouts:
                # one model per bench fleet; newest staged version wins
                return layouts[max(layouts, key=int)]
        except (OSError, ValueError):
            continue
    return None


def _router_states(reg: Registry) -> dict:
    """priority -> the router's end-to-end (stage="router") histogram
    state — snapshotted after warmup so percentiles cover MEASURED
    traffic only."""
    fam = reg.log_histogram(REQUEST_LATENCY)
    return {p: fam.labels(priority=p, stage="router").value()
            for p in BENCH_PRIORITIES}


def _router_percentiles(reg: Registry,
                        baseline: Optional[dict] = None) -> dict:
    """priority -> {p50_ms, p95_ms, p99_ms, count} from the router's
    log-bucket histograms, minus ``baseline`` (the post-warmup snapshot:
    cold-start first-connection latencies would otherwise sit exactly in
    the reported — and trend-gated — p99 tail)."""
    out = {}
    for priority, (counts, _total, n) in _router_states(reg).items():
        if baseline is not None and priority in baseline:
            bc, _bt, bn = baseline[priority]
            counts = [a - b for a, b in zip(counts, bc)]
            n -= bn
        out[priority] = {
            "count": int(n),
            "p50_ms": round(hist_quantile(counts, 0.50) * 1e3, 3),
            "p95_ms": round(hist_quantile(counts, 0.95) * 1e3, 3),
            "p99_ms": round(hist_quantile(counts, 0.99) * 1e3, 3),
        }
    return out


def run_fleet_bench(model_path: str, num_features: int, *,
                    backend: str = "cpu",
                    replica_counts: Sequence[int] = (1, 2, 4),
                    clients: int = 8, duration_s: float = 2.0,
                    sizes: Sequence[int] = (1, 3, 9, 17),
                    arms: int = 2, seed: int = 0,
                    max_batch_rows: int = 256, max_wait_ms: float = 1.0,
                    warmup: bool = False,
                    swap_drill: bool = True,
                    swap_model_path: Optional[str] = None,
                    swap_replicas: int = 2,
                    startup_timeout_s: float = 120.0,
                    max_inflight: int = 256,
                    verbose: bool = False) -> dict:
    """The full fleet arm: scaling sweep + rolling-swap drill.  Returns a
    flat report dict (``fleet_rows_per_s_nN``, ``fleet_spread_nN``,
    ``fleet_scaling_nK``, ``fleet_swap_*``)."""
    payloads = _payloads(int(num_features), sizes, seed)
    report: dict = {"bench": "serve_fleet", "fleet_clients": clients,
                    "fleet_duration_s": duration_s,
                    "fleet_backend": backend}
    base_n = min(replica_counts)
    for n in replica_counts:
        sup, router, reg = _start_fleet(
            model_path, n, backend=backend, max_batch_rows=max_batch_rows,
            max_wait_ms=max_wait_ms, warmup=warmup,
            startup_timeout_s=startup_timeout_s, max_inflight=max_inflight)
        try:
            # one untimed pass warms every replica's compile caches so the
            # measured arms see steady state, not first-touch compiles
            _closed_loop(router.host, router.port, payloads,
                         clients=clients, duration_s=min(duration_s, 1.0),
                         seed=seed - 1)
            # percentile baseline AFTER warmup: the reported (and
            # trend-gated) p99 must cover measured traffic only
            pct_base = _router_states(reg)
            if "fleet_predict_layout" not in report:
                # which traversal layout (r21 packed vs legacy) the
                # replicas actually staged — read over the wire so the
                # rows/s numbers are attributable to a layout arm
                layout = _replica_layouts(sup)
                if layout is not None:
                    report["fleet_predict_layout"] = layout
            arm_rates = []
            failures = 0
            mismatches = 0
            for arm in range(max(1, int(arms))):
                loop = _closed_loop(router.host, router.port, payloads,
                                    clients=clients, duration_s=duration_s,
                                    seed=seed + 100 * (arm + 1),
                                    trace=True)
                arm_rates.append(loop["rows_per_s"])
                failures += loop["failures"]
                mismatches += loop["trace_mismatches"]
            # a short bulk pass populates the bulk-priority series so the
            # percentile report covers BOTH admission classes (kept out
            # of the timed arms: the rows/s trend keys off the historic
            # interactive-only workload)
            bulk = _closed_loop(router.host, router.port, payloads,
                                clients=min(2, clients),
                                duration_s=min(duration_s, 1.0),
                                seed=seed + 7, priority="bulk")
            failures += bulk["failures"]
            pcts = _router_percentiles(reg, baseline=pct_base)
        finally:
            router.stop()
            sup.stop()
        spread = (max(arm_rates) / min(arm_rates) - 1
                  if len(arm_rates) > 1 and min(arm_rates) > 0 else 0.0)
        rate = sum(arm_rates) / len(arm_rates)
        report[f"fleet_rows_per_s_n{n}"] = round(rate, 1)
        report[f"fleet_spread_n{n}"] = round(spread, 3)
        report[f"fleet_failures_n{n}"] = failures
        report[f"fleet_trace_mismatches_n{n}"] = mismatches
        # per-priority latency percentiles (the ROADMAP's "p99 budgets
        # per priority class, not just rows/s") — obs/trends.py tracks
        # these fields like bench walls
        for priority, p in pcts.items():
            for key in ("p50_ms", "p95_ms", "p99_ms"):
                report[f"fleet_{priority}_{key}_n{n}"] = p[key]
        if verbose:
            print(f"fleet n={n}: {rate:.0f} rows/s "
                  f"(spread {spread:.3f}, {failures} failures; "
                  f"interactive p99 {pcts['interactive']['p99_ms']} ms)")
    for n in replica_counts:
        if n != base_n:
            base = report[f"fleet_rows_per_s_n{base_n}"]
            report[f"fleet_scaling_n{n}"] = round(
                report[f"fleet_rows_per_s_n{n}"] / base, 3) if base else 0.0
    report["suspect_capture"] = any(
        report.get(f"fleet_spread_n{n}", 0.0) > SPREAD_SUSPECT
        for n in replica_counts)

    if swap_drill:
        report.update(run_swap_drill(
            model_path, num_features,
            swap_model_path=swap_model_path or model_path,
            backend=backend, n_replicas=swap_replicas, clients=clients,
            duration_s=max(2.0, duration_s), sizes=sizes, seed=seed,
            max_batch_rows=max_batch_rows, max_wait_ms=max_wait_ms,
            startup_timeout_s=startup_timeout_s,
            max_inflight=max_inflight, verbose=verbose))
    return report


def run_swap_drill(model_path: str, num_features: int, *,
                   swap_model_path: str, backend: str = "cpu",
                   n_replicas: int = 2, clients: int = 4,
                   duration_s: float = 2.0,
                   sizes: Sequence[int] = (1, 3, 9, 17), seed: int = 0,
                   max_batch_rows: int = 256, max_wait_ms: float = 1.0,
                   startup_timeout_s: float = 120.0,
                   max_inflight: int = 256,
                   verbose: bool = False) -> dict:
    """Rolling swap under load: zero failed requests, both versions seen."""
    payloads = _payloads(int(num_features), sizes, seed)
    sup, router, _reg = _start_fleet(
        model_path, n_replicas, backend=backend,
        max_batch_rows=max_batch_rows, max_wait_ms=max_wait_ms,
        warmup=False, startup_timeout_s=startup_timeout_s,
        max_inflight=max_inflight)
    versions: dict = {}
    vlock = threading.Lock()

    def on_response(status: int, body: bytes) -> None:
        if status != 200:
            return
        try:
            v = json.loads(body).get("version")
        except ValueError:
            return
        with vlock:
            versions[v] = versions.get(v, 0) + 1

    swap: dict = {}

    def pusher() -> None:
        # fire mid-loop so both versions serve under measurement
        time.sleep(duration_s * 0.3)
        t0 = time.perf_counter()
        swap.update(sup.rolling_push(swap_model_path))
        swap["wall_s"] = time.perf_counter() - t0

    try:
        push_thread = threading.Thread(target=pusher, daemon=True)
        push_thread.start()
        loop = _closed_loop(router.host, router.port, payloads,
                            clients=clients, duration_s=duration_s,
                            seed=seed + 31, on_response=on_response)
        push_thread.join(timeout=120.0)
    finally:
        router.stop()
        sup.stop()
    return {
        "fleet_swap_requests": loop["requests"],
        "fleet_swap_failed": loop["failures"] + len(swap.get("errors", {})),
        "fleet_swap_wall_s": round(swap.get("wall_s", float("nan")), 3),
        "fleet_swap_versions_seen": len(versions),
        "fleet_swap_replicas_swapped": len(swap.get("versions", {})),
    }
