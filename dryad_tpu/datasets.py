"""Synthetic stand-ins for the acceptance-config datasets.

The environment has zero network egress and no dataset files, so each config
in BASELINE.json:7-11 gets a deterministic generator with the same shape,
dtype, and statistical character (separable but noisy signal) as the real
workload.  Loaders accept an optional on-disk path so real data slots in
unchanged when available.
"""

from __future__ import annotations

import numpy as np


def _rng(seed: int) -> np.random.Generator:
    return np.random.Generator(np.random.Philox(seed))


def higgs_like(n: int = 100_000, num_features: int = 28, seed: int = 7):
    """Binary physics-ish task: nonlinear signal over dense float features.

    Mirrors HIGGS (11M x 28 dense, binary) per BASELINE.json:7 at any n.
    """
    rng = _rng(seed)
    X = rng.normal(size=(n, num_features)).astype(np.float32)
    # low-level "momenta" + engineered nonlinear combos, like HIGGS's feature mix
    w1 = rng.normal(size=num_features).astype(np.float32)
    score = (
        X @ w1
        + 0.9 * np.sin(X[:, 0] * X[:, 1])
        + 0.8 * (X[:, 2] * X[:, 3])
        + 0.7 * np.square(X[:, 4])
        - 0.5 * np.abs(X[:, 5])
    )
    score = (score - score.mean()) / (score.std() + 1e-9)
    p = 1.0 / (1.0 + np.exp(-1.5 * score))
    y = (rng.uniform(size=n) < p).astype(np.float32)
    return X, y


def covertype_like(n: int = 100_000, num_features: int = 54, num_class: int = 7, seed: int = 11):
    """Multiclass task shaped like Covertype (581k x 54, 7 classes), BASELINE.json:8.

    Last 44 features are binary indicator-ish, like Covertype's soil/wilderness
    one-hots.
    """
    rng = _rng(seed)
    dense = rng.normal(size=(n, 10)).astype(np.float32)
    binary = (rng.uniform(size=(n, num_features - 10)) < 0.15).astype(np.float32)
    X = np.concatenate([dense, binary], axis=1)
    W = rng.normal(size=(num_features, num_class)).astype(np.float32)
    logits = X @ W + 0.8 * np.square(dense[:, :1]) @ rng.normal(size=(1, num_class)).astype(np.float32)
    logits += rng.gumbel(size=(n, num_class)).astype(np.float32)
    y = np.argmax(logits, axis=1).astype(np.float32)
    return X, y


def epsilon_like(n: int = 50_000, num_features: int = 2000, seed: int = 13):
    """Wide-dense regression stress (Epsilon is 400k x 2000), BASELINE.json:9."""
    rng = _rng(seed)
    X = rng.normal(size=(n, num_features)).astype(np.float32)
    w = (rng.normal(size=num_features) * (rng.uniform(size=num_features) < 0.05)).astype(np.float32)
    y = X @ w + 0.5 * np.sin(X[:, 0]) * X[:, 1] + rng.normal(size=n).astype(np.float32) * 0.1
    return X, y.astype(np.float32)


def mslr_like(num_queries: int = 1000, docs_per_query: tuple[int, int] = (5, 120),
              num_features: int = 136, seed: int = 17):
    """LambdaMART ranking task shaped like MSLR-WEB30K (BASELINE.json:10).

    Returns (X, y, group) with graded relevance labels 0-4 and variable query
    sizes.
    """
    rng = _rng(seed)
    group = rng.integers(docs_per_query[0], docs_per_query[1] + 1, size=num_queries)
    n = int(group.sum())
    X = rng.normal(size=(n, num_features)).astype(np.float32)
    w = rng.normal(size=num_features).astype(np.float32) * 0.3
    # per-query bias so relevance is only meaningful within a query
    qbias = np.repeat(rng.normal(size=num_queries).astype(np.float32), group)
    score = X @ w + qbias + rng.normal(size=n).astype(np.float32) * 0.7
    # map scores to graded relevance 0..4 by global quantiles
    qs = np.quantile(score, [0.5, 0.75, 0.9, 0.97])
    y = np.digitize(score, qs).astype(np.float32)
    return X, y, group.astype(np.int64)


def criteo_like(n: int = 200_000, num_dense: int = 13, num_cat: int = 26,
                cat_cardinality: int = 1000, density: float = 0.7, seed: int = 19):
    """Sparse CTR task shaped like Criteo-1TB (13 dense + 26 categorical),
    BASELINE.json:11.  Returns CSR (indptr, indices, values, F), y, and the
    categorical feature ids.  Dense slots are present with prob ``density``;
    categorical values are skewed (Zipf-ish) integer ids.
    """
    rng = _rng(seed)
    F = num_dense + num_cat
    present = rng.uniform(size=(n, F)) < density
    present[:, num_dense:] |= rng.uniform(size=(n, num_cat)) < 0.5
    dense_vals = np.log1p(rng.exponential(scale=3.0, size=(n, num_dense))).astype(np.float32)
    cat_vals = (rng.zipf(a=1.3, size=(n, num_cat)) % cat_cardinality).astype(np.float32)
    allvals = np.concatenate([dense_vals, cat_vals], axis=1)
    w_d = rng.normal(size=num_dense).astype(np.float32)
    cat_w = rng.normal(size=(num_cat, cat_cardinality)).astype(np.float32) * 0.5
    logit = (dense_vals * present[:, :num_dense]) @ w_d - 1.0
    for j in range(num_cat):
        logit += np.where(present[:, num_dense + j], cat_w[j, cat_vals[:, j].astype(np.int64)], 0.0)
    y = (rng.uniform(size=n) < 1.0 / (1.0 + np.exp(-logit))).astype(np.float32)

    rows, cols = np.nonzero(present)
    values = allvals[rows, cols]
    counts = np.bincount(rows, minlength=n)
    indptr = np.concatenate([[0], np.cumsum(counts)]).astype(np.int64)
    cat_ids = tuple(range(num_dense, F))
    return (indptr, cols.astype(np.int64), values.astype(np.float32), F), y, cat_ids
