"""Checkpoint/resume (SURVEY.md §5).

The model is small (tree tables + bin edges), so a checkpoint is simply the
partial booster serialized at iteration boundaries.  Resume feeds the
checkpoint back as ``init_booster``: scores are replayed tree-by-tree in the
same fp32 order and bagging masks are drawn from Philox(seed, iteration)
(cpu/trainer.py::sample_masks), so the remaining schedule reproduces the
uninterrupted run bit for bit — the keystone resume invariant, asserted in
tests/test_checkpoint.py.

Writes are atomic (tmp file + os.replace) so a crash mid-write can never
corrupt the latest checkpoint; old checkpoints are pruned, keeping ``keep``.
"""

from __future__ import annotations

import os
import re
from typing import Optional

from dryad_tpu.booster import Booster

_PATTERN = re.compile(r"^ckpt_(\d{8})\.dryad$")


class Checkpointer:
    """Periodic atomic booster snapshots in a directory."""

    def __init__(self, directory: str, every: int = 10, keep: int = 2):
        if every < 1:
            raise ValueError("checkpoint 'every' must be >= 1")
        self.directory = directory
        self.every = int(every)
        self.keep = int(keep)
        os.makedirs(directory, exist_ok=True)

    def _path(self, iteration: int) -> str:
        return os.path.join(self.directory, f"ckpt_{iteration:08d}.dryad")

    @staticmethod
    def has_checkpoints(directory: str) -> bool:
        """Read-only probe (no mkdir): does ``directory`` hold any
        checkpoint?  Keeps the filename convention in one place for
        callers that must not create the directory as a side effect
        (e.g. the CLI's --supervise stale-checkpoint guard)."""
        try:
            return any(_PATTERN.match(name)
                       for name in os.listdir(directory))
        except OSError:
            return False

    def iterations(self) -> list[int]:
        out = []
        for name in os.listdir(self.directory):
            m = _PATTERN.match(name)
            if m:
                out.append(int(m.group(1)))
        return sorted(out)

    def latest(self) -> Optional[tuple[Booster, int]]:
        """(booster, iteration) of the newest checkpoint, or None."""
        its = self.iterations()
        if not its:
            return None
        it = its[-1]
        return Booster.load(self._path(it)), it

    def save(self, booster: Booster, iteration: int) -> str:
        path = self._path(iteration)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(booster.to_bytes())
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)           # atomic on POSIX
        for it in self.iterations()[: -self.keep]:
            try:
                os.remove(self._path(it))
            except OSError:
                pass
        return path

    def due(self, iteration: int) -> bool:
        """True when iteration (1-based count of completed iters) hits the period."""
        return iteration % self.every == 0
