"""Exclusive feature bundling (EFB) for sparse data (SURVEY.md §7 step 6).

Criteo-style matrices carry many near-one-hot columns that are almost never
non-default in the same row.  Bundling folds strictly-exclusive sparse
columns into one column whose bin space is the offset-stacked union of the
members' bins, shrinking F — and the histogram pass is O(N·F·B), so the
grower speeds up by the bundling ratio with bit-identical information
content (strict exclusivity: no conflicts, nothing dropped).

Determinism contract: the plan is a pure function of the binned matrix and
the frozen mapper (features scanned in ascending id order, first-fit into
bundles) — re-running ingest on the same data reproduces the same bundles,
and the plan is serialized with the mapper so predict folds identically.

Bundle encoding (bundle members f_1..f_m with bin counts n_1..n_m):

* bundle bin 0            — every member at its default (zero-value) bin
* offset_k + b            — member f_k at bin b (offset_1 = 1,
                            offset_{k+1} = offset_k + n_k)

Missing values (member bin 0) encode at offset_k + 0, so a bundled column's
bin 0 never means "missing" — bundled columns are excluded from the
missing-direction machinery (Dataset.has_missing).  Categorical columns
bundle with other categoricals only; the bundle column is categorical and
node bitsets address its offset-stacked bins (see plan_bundles).

Bundling runs automatically on the in-memory CSR ingest path
(``Dataset(csr=..., bundle=True)``, the default).  The out-of-core
streaming ingest (data/streaming.py) does NOT auto-bundle — its binned
matrix is built chunk-by-chunk before a global plan exists; fold it
afterwards via ``BundledMapper(base, plan_bundles(Xb, base, max_bins))``
and ``Dataset.from_binned`` when the matrix fits in memory.
"""

from __future__ import annotations

import io
from typing import Sequence

import numpy as np

from dryad_tpu.data.binning import zero_bins
from dryad_tpu.data.sketch import BinMapper


def _conflicts(sorted_idx: np.ndarray, idx: np.ndarray) -> bool:
    """True when any element of ``idx`` appears in ``sorted_idx``."""
    if sorted_idx.size == 0 or idx.size == 0:
        return False
    pos = np.minimum(np.searchsorted(sorted_idx, idx), sorted_idx.size - 1)
    return bool((sorted_idx[pos] == idx).any())


def plan_bundles(
    Xb: np.ndarray,
    mapper: BinMapper,
    max_bins: int,
    *,
    min_default_frac: float = 0.8,
    sample_rows: int = 1 << 20,
    max_scan: int = 256,
) -> list[list[int]]:
    """Greedy strict-exclusive bundling plan -> member-id lists (len >= 2).

    A feature is eligible when its default (zero-value) bin covers >=
    ``min_default_frac`` of rows.  Categorical columns bundle too (criteo-
    style data is CATEGORICAL-sparse), but only with other categoricals:
    the bundle column is then itself categorical, and the sorted-subset
    scan over its offset-stacked bins expresses any union of per-member
    category subsets (each member keeps its own bin range).  Mixing kinds
    is never planned — a numeric member inside a categorical bundle would
    lose its ordering under subset splits.  Categorical bundles are capped
    at 255 bins so node bitsets (CAT_WORDS = 8 words) always cover them.
    Exclusivity is planned on
    a deterministic row prefix of up to ``sample_rows`` rows using sorted
    nonzero-row-index intersection (O(nnz log nnz) per attempt — dense
    (N,) bool masks would make wide-sparse ingest quadratic in bytes),
    scanning at most ``max_scan`` candidate bundles per feature, and then
    RE-VERIFIED over the full data: members that conflict beyond the
    prefix are evicted back to singleton columns, so every emitted bundle
    is strictly exclusive end to end and the fold drops nothing.
    """
    zb = zero_bins(mapper)
    n_bins = mapper.n_bins
    is_cat = mapper.is_categorical
    F = mapper.num_features
    N = Xb.shape[0]
    S = min(N, int(sample_rows))

    bundles: list[dict] = []
    for f in range(F):
        nz_idx = np.flatnonzero(Xb[:S, f] != zb[f]).astype(np.int64)
        if nz_idx.size > (1.0 - min_default_frac) * S:
            continue
        kind_cat = bool(is_cat[f])
        # categorical bundles must fit the (CAT_WORDS * 32)-bit node bitset
        cap = min(max_bins - 1, 255) if kind_cat else max_bins - 1
        placed = False
        for bd in bundles[:max_scan]:
            if bd["cat"] != kind_cat:
                continue
            if bd["bins"] + int(n_bins[f]) > cap:
                continue
            if _conflicts(bd["idx"], nz_idx):
                continue
            bd["members"].append(f)
            bd["idx"] = np.union1d(bd["idx"], nz_idx)
            bd["bins"] += int(n_bins[f])
            placed = True
            break
        if not placed:
            bundles.append({"members": [f], "idx": nz_idx,
                            "bins": int(n_bins[f]), "cat": kind_cat})

    plan = [bd["members"] for bd in bundles if len(bd["members"]) >= 2]
    if S == N:
        return plan

    # full-data verification: rebuild each bundle greedily over ALL rows,
    # evicting members whose nonzeros collide beyond the planning prefix
    verified: list[list[int]] = []
    for members in plan:
        kept: list[int] = []
        mask = np.zeros(N, bool)
        for f in members:
            nz = Xb[:, f] != zb[f]
            if (mask & nz).any():
                continue  # conflicts outside the prefix: back to singleton
            mask |= nz
            kept.append(f)
        if len(kept) >= 2:
            verified.append(kept)
    return verified


def fold_bundles(Xb: np.ndarray, mapper: BinMapper,
                 bundles: Sequence[Sequence[int]],
                 out_dtype: np.dtype,
                 conflict_out: list | None = None) -> np.ndarray:
    """Fold an original-feature binned matrix into the bundled layout.

    Output columns: bundle_0, bundle_1, ..., then the unbundled features in
    ascending id order (the layout ``BundledMapper`` describes).  Plans from
    ``plan_bundles`` are strictly exclusive over the full TRAINING data
    (verified there) — but validation/test/predict matrices carry no such
    guarantee: when two members are non-default in the same row, the lowest
    member wins and the other value is DROPPED.  Such conflicts are counted
    and surfaced with a warning (and appended to ``conflict_out`` when
    given) so silent feature loss cannot go unnoticed."""
    zb = zero_bins(mapper)
    n_bins = mapper.n_bins
    N = Xb.shape[0]
    in_bundle = np.zeros(mapper.num_features, bool)
    cols = []
    conflicts = 0
    for members in bundles:
        enc = np.zeros(N, np.int32)
        taken = np.zeros(N, bool)
        off = 1
        for f in members:
            in_bundle[f] = True
            b = Xb[:, f].astype(np.int32)
            on = b != zb[f]
            conflicts += int(np.count_nonzero(on & taken))
            nz = on & ~taken  # lowest member wins a conflict
            enc[nz] = off + b[nz]
            taken |= nz
            off += int(n_bins[f])
        cols.append(enc)
    if conflict_out is not None:
        conflict_out.append(conflicts)
    if conflicts:
        import warnings

        warnings.warn(
            f"EFB fold dropped {conflicts} non-default values: bundle "
            "members exclusive on the training data conflicted in this "
            "matrix (lowest member wins); predictions lose that feature "
            "information", RuntimeWarning, stacklevel=2)
    rest = [Xb[:, f].astype(np.int32)
            for f in range(mapper.num_features) if not in_bundle[f]]
    return np.stack(cols + rest, axis=1).astype(out_dtype)


class BundledMapper:
    """BinMapper facade over a base mapper plus a bundling plan.

    Exposes the downstream surface (transform / n_bins / total_bins /
    is_categorical / bin_dtype / serialization); raw features bin through
    the base mapper, then fold through the plan."""

    def __init__(self, base: BinMapper, bundles: list[list[int]]):
        self.base = base
        self.bundles = [list(map(int, m)) for m in bundles]
        in_bundle = np.zeros(base.num_features, bool)
        for m in self.bundles:
            for f in m:
                in_bundle[f] = True
        self.rest = [f for f in range(base.num_features) if not in_bundle[f]]
        base_bins = base.n_bins
        self._n_bins = np.array(
            [1 + sum(int(base_bins[f]) for f in m) for m in self.bundles]
            + [int(base_bins[f]) for f in self.rest], np.int32)
        # True for the bundle columns — their bin 0 means "all default",
        # not "missing" (Dataset.has_missing exclusion)
        self.bundled_mask = np.array(
            [True] * len(self.bundles) + [False] * len(self.rest), bool)
        # conflicts dropped by the most recent transform()/fold() call
        # (non-training matrices can violate the plan's exclusivity)
        self.last_conflict_count = 0

    @property
    def num_features(self) -> int:
        return len(self.bundles) + len(self.rest)

    @property
    def n_bins(self) -> np.ndarray:
        return self._n_bins

    @property
    def total_bins(self) -> int:
        return int(self._n_bins.max(initial=2))

    @property
    def bin_dtype(self) -> np.dtype:
        return np.dtype(np.uint8 if self.total_bins <= 256 else np.uint16)

    @property
    def is_categorical(self) -> np.ndarray:
        # a bundle of categorical members is itself categorical (members
        # are never mixed-kind — plan_bundles); its subset splits address
        # the offset-stacked bin space
        base_cat = self.base.is_categorical
        return np.array(
            [bool(base_cat[m[0]]) for m in self.bundles]
            + [bool(base_cat[f]) for f in self.rest], bool)

    def transform(self, X: np.ndarray) -> np.ndarray:
        from dryad_tpu.data.binning import bin_matrix

        out = []
        Xb = fold_bundles(bin_matrix(np.asarray(X, np.float32), self.base),
                          self.base, self.bundles, self.bin_dtype,
                          conflict_out=out)
        self.last_conflict_count = out[0]
        return Xb

    def fold(self, Xb_base: np.ndarray) -> np.ndarray:
        """Fold an already-binned ORIGINAL-layout matrix (CSR ingest)."""
        out = []
        Xb = fold_bundles(Xb_base, self.base, self.bundles, self.bin_dtype,
                          conflict_out=out)
        self.last_conflict_count = out[0]
        return Xb

    # ---- serialization -----------------------------------------------------
    def to_json_dict(self) -> dict:
        """Text-format dump (Booster.save_text): the base mapper's JSON
        plus the bundle plan."""
        return {
            "type": "bundled",
            "base": self.base.to_json_dict(),
            "bundles": [list(map(int, m)) for m in self.bundles],
        }

    @classmethod
    def from_json_dict(cls, d: dict) -> "BundledMapper":
        return cls(BinMapper.from_json_dict(d["base"]), d["bundles"])

    def to_bytes(self) -> bytes:
        buf = io.BytesIO()
        arrs = {
            "efb_base": np.frombuffer(self.base.to_bytes(), np.uint8),
            "efb_count": np.array([len(self.bundles)], np.int64),
        }
        for i, m in enumerate(self.bundles):
            arrs[f"efb_members_{i}"] = np.asarray(m, np.int64)
        np.savez_compressed(buf, **arrs)
        return buf.getvalue()

    @classmethod
    def from_bytes(cls, data: bytes) -> "BundledMapper":
        with np.load(io.BytesIO(data)) as z:
            base = BinMapper.from_bytes(bytes(z["efb_base"]))
            count = int(z["efb_count"][0])
            bundles = [z[f"efb_members_{i}"].tolist() for i in range(count)]
            return cls(base, bundles)
