"""Binning front-ends: dense matrices and CSR-style sparse input.

The dense path simply routes through the frozen BinMapper (see data/sketch.py
for the bit-exact contract).  The sparse path serves Criteo-style workloads
(BASELINE.json:11): a CSR triple is densified *per row-block* into bin ids,
where absent entries take the feature's zero-value bin — never materializing
the dense float matrix.
"""

from __future__ import annotations

import numpy as np

from dryad_tpu.data.sketch import BinMapper


def bin_matrix(X: np.ndarray, mapper: BinMapper) -> np.ndarray:
    """Dense raw features → bin ids (N, F) uint8/uint16."""
    if hasattr(mapper, "fold"):   # BundledMapper: bin via base, then fold
        return mapper.transform(X)
    from dryad_tpu import native

    out = native.bin_matrix(np.asarray(X, np.float32), mapper)
    if out is not None:
        return out
    return mapper.transform(X)


def zero_bins(mapper: BinMapper) -> np.ndarray:
    """Per-feature bin id that the raw value 0.0 maps to (sparse default)."""
    zero = np.zeros((1,), np.float32)
    return np.array(
        [mapper.transform_column(zero, f)[0] for f in range(mapper.num_features)],
        np.int32,
    )


def bin_csr(
    indptr: np.ndarray,
    indices: np.ndarray,
    values: np.ndarray,
    num_features: int,
    mapper: BinMapper,
    block_rows: int = 65536,
) -> np.ndarray:
    """CSR (indptr, indices, values) → dense binned (N, F) without a dense float pass.

    Implicit zeros bin to the feature's zero bin, matching the dense semantics
    of a materialized matrix with explicit 0.0 entries bit-for-bit.
    """
    n = indptr.shape[0] - 1
    out = np.empty((n, num_features), mapper.bin_dtype)
    zb = zero_bins(mapper).astype(mapper.bin_dtype)
    for start in range(0, n, block_rows):
        stop = min(start + block_rows, n)
        block = np.broadcast_to(zb, (stop - start, num_features)).copy()
        lo, hi = indptr[start], indptr[stop]
        rows = np.repeat(
            np.arange(start, stop, dtype=np.int64) - start,
            np.diff(indptr[start : stop + 1]),
        )
        cols = indices[lo:hi]
        vals = values[lo:hi].astype(np.float32)
        # bin the explicit entries feature-by-feature (vectorized inside)
        order = np.argsort(cols, kind="stable")
        rows_s, cols_s, vals_s = rows[order], cols[order], vals[order]
        bounds = np.searchsorted(cols_s, np.arange(num_features + 1))
        for f in range(num_features):
            a, b = bounds[f], bounds[f + 1]
            if a == b:
                continue
            block[rows_s[a:b], f] = mapper.transform_column(vals_s[a:b], f).astype(
                mapper.bin_dtype
            )
        out[start:stop] = block
    return out
