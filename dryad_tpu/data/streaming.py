"""Out-of-core ingest: build a Dataset from row chunks without ever holding
the raw float table in memory (SURVEY.md §7 hard part e — Criteo-1TB).

Two passes over the chunk stream:

1. **Sketch pass** — a deterministic subsample keyed on the global row id
   (stateless splitmix64 hash, see ``_keyed_uniform``) feeds the canonical
   sketch.  The kept set depends only on (seed, global row id), never on
   chunk boundaries, so re-chunking (or sharding across hosts —
   distributed.sketch_distributed uses the same keying) cannot change the
   frozen edges.
2. **Bin pass** — each chunk is binned through the frozen mapper straight
   into the preallocated uint8/uint16 matrix (4-8x smaller than the floats).

The binned matrix for Criteo-scale data is what must fit: 1e9 rows x 39
features x 1 byte = 39 GB across a pod — per-host slices of it are what
``distributed.host_row_range`` hands each worker.
"""

from __future__ import annotations

from typing import Callable, Iterable, Optional, Sequence

import numpy as np

from dryad_tpu.data.sketch import BinMapper, sketch_features


def _keyed_uniform(row_offset: int, n: int, seed: int) -> np.ndarray:
    """uniform(0,1) per row, a pure function of (seed, global row id).

    Stateless splitmix64 finalizer — unlike a streamed PRNG there is no
    block structure, so any partitioning of the row range reproduces exactly
    the same per-row draws (the chunking/sharding invariance the sketch
    contract needs).
    """
    r = np.arange(row_offset, row_offset + n, dtype=np.uint64)
    z = r + np.uint64(seed) * np.uint64(0x9E3779B97F4A7C15) + np.uint64(
        0x9E3779B97F4A7C15)
    z = (z ^ (z >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
    z = (z ^ (z >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
    z = z ^ (z >> np.uint64(31))
    return (z >> np.uint64(11)).astype(np.float64) * (1.0 / (1 << 53))


def sketch_stream(
    chunks: Callable[[], Iterable[np.ndarray]],
    total_rows: int,
    *,
    max_bins: int = 256,
    categorical_features: Sequence[int] = (),
    sample_rows: int = 1 << 20,
    seed: int = 0,
) -> BinMapper:
    """Frozen BinMapper from one streaming pass (deterministic subsample)."""
    rate = min(1.0, sample_rows / max(total_rows, 1))
    parts: list[np.ndarray] = []
    offset = 0
    for chunk in chunks():
        chunk = np.asarray(chunk, np.float32)
        keep = _keyed_uniform(offset, chunk.shape[0], seed) < rate
        parts.append(np.ascontiguousarray(chunk[keep]))
        offset += chunk.shape[0]
    if offset != total_rows:
        raise ValueError(f"stream yielded {offset} rows, expected {total_rows}")
    sample = np.concatenate(parts, axis=0)
    return sketch_features(sample, max_bins=max_bins,
                           categorical_features=categorical_features)


def dataset_from_chunks(
    chunks: Callable[[], Iterable[np.ndarray]],
    y: np.ndarray,
    total_rows: int,
    num_features: int,
    *,
    weight: Optional[np.ndarray] = None,
    group: Optional[np.ndarray] = None,
    categorical_features: Sequence[int] = (),
    max_bins: int = 256,
    mapper: Optional[BinMapper] = None,
    sample_rows: int = 1 << 20,
    seed: int = 0,
):
    """Out-of-core Dataset: ``chunks`` is a restartable factory of row-chunk
    iterables (called twice: sketch pass, bin pass)."""
    from dryad_tpu.dataset import Dataset

    if mapper is None:
        mapper = sketch_stream(
            chunks, total_rows, max_bins=max_bins,
            categorical_features=categorical_features,
            sample_rows=sample_rows, seed=seed,
        )
    Xb = np.empty((total_rows, num_features), mapper.bin_dtype)
    offset = 0
    for chunk in chunks():
        chunk = np.asarray(chunk, np.float32)
        Xb[offset : offset + chunk.shape[0]] = mapper.transform(chunk)
        offset += chunk.shape[0]
    if offset != total_rows:
        raise ValueError(f"stream yielded {offset} rows, expected {total_rows}")

    return Dataset.from_binned(
        Xb, mapper, y, weight=weight, group=group,
        categorical_features=categorical_features,
    )
