"""Out-of-core ingest: build a Dataset from row chunks without ever holding
the raw float table in memory (SURVEY.md §7 hard part e — Criteo-1TB).

Two passes over the chunk stream:

1. **Sketch pass** — a deterministic subsample keyed on the global row id
   (stateless splitmix64 hash, see ``_keyed_uniform``) feeds the canonical
   sketch.  The kept set depends only on (seed, global row id), never on
   chunk boundaries, so re-chunking (or sharding across hosts —
   distributed.sketch_distributed uses the same keying) cannot change the
   frozen edges.
2. **Bin pass** — each chunk is binned through the frozen mapper straight
   into the preallocated uint8/uint16 matrix (4-8x smaller than the floats).

The binned matrix for Criteo-scale data is what must fit: 1e9 rows x 39
features x 1 byte = 39 GB across a pod — per-host slices of it are what
``distributed.host_row_range`` hands each worker.
"""

from __future__ import annotations

from typing import Callable, Iterable, Optional, Sequence

import numpy as np

from dryad_tpu.data.sketch import BinMapper, sketch_features


def _keyed_uniform(row_offset: int, n: int, seed: int) -> np.ndarray:
    """uniform(0,1) per row, a pure function of (seed, global row id).

    Stateless splitmix64 finalizer — unlike a streamed PRNG there is no
    block structure, so any partitioning of the row range reproduces exactly
    the same per-row draws (the chunking/sharding invariance the sketch
    contract needs).
    """
    r = np.arange(row_offset, row_offset + n, dtype=np.uint64)
    z = r + np.uint64(seed) * np.uint64(0x9E3779B97F4A7C15) + np.uint64(
        0x9E3779B97F4A7C15)
    z = (z ^ (z >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
    z = (z ^ (z >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
    z = z ^ (z >> np.uint64(31))
    return (z >> np.uint64(11)).astype(np.float64) * (1.0 / (1 << 53))


def sketch_stream(
    chunks: Callable[[], Iterable[np.ndarray]],
    total_rows: int,
    *,
    max_bins: int = 256,
    categorical_features: Sequence[int] = (),
    sample_rows: int = 1 << 20,
    seed: int = 0,
) -> BinMapper:
    """Frozen BinMapper from one streaming pass (deterministic subsample)."""
    rate = min(1.0, sample_rows / max(total_rows, 1))
    parts: list[np.ndarray] = []
    offset = 0
    for chunk in chunks():
        chunk = np.asarray(chunk, np.float32)
        keep = _keyed_uniform(offset, chunk.shape[0], seed) < rate
        parts.append(np.ascontiguousarray(chunk[keep]))
        offset += chunk.shape[0]
    if offset != total_rows:
        raise ValueError(f"stream yielded {offset} rows, expected {total_rows}")
    sample = np.concatenate(parts, axis=0)
    return sketch_features(sample, max_bins=max_bins,
                           categorical_features=categorical_features)


def dataset_from_chunks(
    chunks: Callable[[], Iterable[np.ndarray]],
    y: np.ndarray,
    total_rows: int,
    num_features: int,
    *,
    weight: Optional[np.ndarray] = None,
    group: Optional[np.ndarray] = None,
    categorical_features: Sequence[int] = (),
    max_bins: int = 256,
    mapper: Optional[BinMapper] = None,
    sample_rows: int = 1 << 20,
    seed: int = 0,
    spill: Optional[str] = None,
    chunk_rows: Optional[int] = None,
):
    """Out-of-core Dataset: ``chunks`` is a restartable factory of row-chunk
    iterables (called twice: sketch pass, bin pass).

    With ``spill=path`` the pass-2 bins are written straight to disk
    through a flushed+dropped memmap window (``SpillSink``) and a
    :class:`~dryad_tpu.data.stream_dataset.StreamedDataset` is returned —
    the full binned matrix is never resident, and training streams it back
    in ``chunk_rows``-row tiles (bitwise ≡ the resident path).  The
    sketch pass and its global-row-id keying are identical either way.
    """
    from dryad_tpu.dataset import Dataset

    if mapper is None:
        mapper = sketch_stream(
            chunks, total_rows, max_bins=max_bins,
            categorical_features=categorical_features,
            sample_rows=sample_rows, seed=seed,
        )
    if spill is not None:
        from dryad_tpu.data.stream_dataset import (DEFAULT_CHUNK_ROWS,
                                                   SpillSink, StreamedDataset)

        # mapper.num_features, not the raw column count: a BundledMapper's
        # transform emits the folded (bundled) width
        sink = SpillSink(spill, total_rows, mapper.num_features,
                         np.dtype(mapper.bin_dtype))
        for chunk in chunks():
            sink.write(mapper.transform(np.asarray(chunk, np.float32)))
        sink.finish()
        return StreamedDataset(
            spill, mapper, y, weight=weight, group=group,
            categorical_features=categorical_features, num_rows=total_rows,
            chunk_rows=chunk_rows or DEFAULT_CHUNK_ROWS,
        )
    Xb = np.empty((total_rows, num_features), mapper.bin_dtype)
    offset = 0
    for chunk in chunks():
        chunk = np.asarray(chunk, np.float32)
        Xb[offset : offset + chunk.shape[0]] = mapper.transform(chunk)
        offset += chunk.shape[0]
    if offset != total_rows:
        raise ValueError(f"stream yielded {offset} rows, expected {total_rows}")

    return Dataset.from_binned(
        Xb, mapper, y, weight=weight, group=group,
        categorical_features=categorical_features,
    )


def sketch_stream_csr(
    chunks: Callable[[], Iterable[tuple]],
    total_rows: int,
    num_features: int,
    *,
    max_bins: int = 256,
    categorical_features: Sequence[int] = (),
    sample_rows: int = 1 << 20,
    seed: int = 0,
) -> BinMapper:
    """Frozen BinMapper from one pass over CSR chunks ``(indptr, indices,
    values)`` (indptr chunk-local).  Only the keyed row subsample is ever
    densified, so the float table never materializes."""
    rate = min(1.0, sample_rows / max(total_rows, 1))
    parts: list[np.ndarray] = []
    offset = 0
    for indptr, indices, values in chunks():
        n = len(indptr) - 1
        keep = np.flatnonzero(_keyed_uniform(offset, n, seed) < rate)
        dense = np.zeros((keep.size, num_features), np.float32)
        # vectorized densify (ADVICE r3 #3): one fancy-index scatter for
        # the whole chunk's kept rows — the per-row Python loop cost
        # minutes of interpreter time at the default 1M-row sample on
        # Criteo-scale streams.  np.repeat maps each kept nonzero back to
        # its (compacted) row; column ids and values are sliced per row
        # via a ragged take.
        indptr = np.asarray(indptr)
        counts = (indptr[keep + 1] - indptr[keep]).astype(np.int64)
        rows_rep = np.repeat(np.arange(keep.size, dtype=np.int64), counts)
        starts = indptr[keep].astype(np.int64)
        # positions of the kept rows' nonzeros inside indices/values:
        # contiguous runs [starts[j], starts[j]+counts[j]) concatenated
        runs = np.arange(counts.sum(), dtype=np.int64)
        run_base = np.repeat(np.cumsum(counts) - counts, counts)
        src = np.repeat(starts, counts) + (runs - run_base)
        dense[rows_rep, np.asarray(indices)[src]] = np.asarray(values)[src]
        parts.append(dense)
        offset += n
    if offset != total_rows:
        raise ValueError(f"stream yielded {offset} rows, expected {total_rows}")
    sample = np.concatenate(parts, axis=0)
    return sketch_features(sample, max_bins=max_bins,
                           categorical_features=categorical_features)


def dataset_from_csr_chunks(
    chunks: Callable[[], Iterable[tuple]],
    y: np.ndarray,
    total_rows: int,
    num_features: int,
    *,
    weight: Optional[np.ndarray] = None,
    group: Optional[np.ndarray] = None,
    categorical_features: Sequence[int] = (),
    max_bins: int = 256,
    mapper: Optional[BinMapper] = None,
    sample_rows: int = 1 << 20,
    seed: int = 0,
    bundle: bool = True,
    plan_rows: int = 1 << 20,
    spill: Optional[str] = None,
    chunk_rows: Optional[int] = None,
):
    """Out-of-core sparse ingest WITH exclusive feature bundling — the
    Criteo-1TB composition (SURVEY.md §7 hard part e; BASELINE.json:11):
    CSR chunk stream -> streamed sketch -> EFB plan on a row prefix ->
    streaming exclusivity verification -> chunkwise fold into the final
    bundled matrix.  Nothing bigger than (total_rows, F_bundled) bins plus
    one chunk's temporaries is ever resident.

    ``chunks`` is a restartable factory yielding chunk-local CSR triples
    ``(indptr, indices, values)``; it is iterated up to four times (sketch
    — skipped when ``mapper`` is given, e.g. from
    ``distributed.sketch_distributed`` —, prefix plan, verification, fold).

    The bundling plan is greedy on the first ``plan_rows`` rows, then
    verified EXACTLY over the full stream: one pass accumulates each
    bundle's pairwise member-conflict matrix (two members non-default in
    the same row, any chunk), and the same greedy eviction
    ``plan_bundles`` runs in memory replays on the accumulated matrix —
    so every emitted bundle is strictly exclusive end to end and the fold
    drops nothing (bit-identical to in-memory ingest of the same rows).

    ``spill=path`` routes the final fold through ``SpillSink`` and returns
    a ``StreamedDataset`` (see ``dataset_from_chunks``) — out-of-core end
    to end, plan/verify passes included.
    """
    from dryad_tpu.data.binning import bin_csr, zero_bins
    from dryad_tpu.data.bundling import BundledMapper, plan_bundles
    from dryad_tpu.dataset import Dataset

    if mapper is None:
        mapper = sketch_stream_csr(
            chunks, total_rows, num_features, max_bins=max_bins,
            categorical_features=categorical_features,
            sample_rows=sample_rows, seed=seed,
        )

    def bin_chunk(indptr, indices, values):
        return bin_csr(np.asarray(indptr, np.int64),
                       np.asarray(indices, np.int64),
                       np.asarray(values, np.float32),
                       num_features, mapper)

    plan: list[list[int]] = []
    if bundle:
        # ---- plan on a prefix ------------------------------------------
        prefix: list[np.ndarray] = []
        got = 0
        for triple in chunks():
            prefix.append(bin_chunk(*triple))
            got += prefix[-1].shape[0]
            if got >= min(plan_rows, total_rows):
                break
        Xb_prefix = np.concatenate(prefix, axis=0)[:plan_rows]
        del prefix
        plan = plan_bundles(Xb_prefix, mapper, max_bins,
                            sample_rows=plan_rows)
        del Xb_prefix

    if plan:
        # ---- streaming exclusivity verification ------------------------
        zb = zero_bins(mapper)
        mats = [np.zeros((len(m), len(m)), np.int64) for m in plan]
        for triple in chunks():
            Xb0 = bin_chunk(*triple)
            for bi, members in enumerate(plan):
                nz = (Xb0[:, members] != zb[members][None, :])
                mats[bi] += nz.T.astype(np.int64) @ nz.astype(np.int64)
        verified: list[list[int]] = []
        for members, mat in zip(plan, mats):
            kept_pos: list[int] = []
            for i in range(len(members)):
                if any(mat[i, j] for j in kept_pos):
                    continue  # conflicts with an earlier kept member
                kept_pos.append(i)
            if len(kept_pos) >= 2:
                verified.append([members[i] for i in kept_pos])
        plan = verified

    if plan:
        out_mapper = BundledMapper(mapper, plan)

        def fold_chunk(triple):
            return out_mapper.fold(bin_chunk(*triple))
    else:
        out_mapper = mapper
        fold_chunk = lambda triple: bin_chunk(*triple)  # noqa: E731

    if spill is not None:
        # same fold pass, written through the flushed+dropped memmap
        # window — the bundled matrix itself is never resident
        from dryad_tpu.data.stream_dataset import (DEFAULT_CHUNK_ROWS,
                                                   SpillSink, StreamedDataset)

        sink = SpillSink(spill, total_rows, out_mapper.num_features,
                         np.dtype(out_mapper.bin_dtype))
        for triple in chunks():
            sink.write(fold_chunk(triple))
        sink.finish()
        return StreamedDataset(
            spill, out_mapper, y, weight=weight, group=group,
            categorical_features=categorical_features, num_rows=total_rows,
            chunk_rows=chunk_rows or DEFAULT_CHUNK_ROWS,
        )

    Xb = np.empty((total_rows, out_mapper.num_features), out_mapper.bin_dtype)
    offset = 0
    for triple in chunks():
        block = fold_chunk(triple)
        Xb[offset:offset + block.shape[0]] = block
        offset += block.shape[0]
    if offset != total_rows:
        raise ValueError(f"stream yielded {offset} rows, expected {total_rows}")

    return Dataset.from_binned(
        Xb, out_mapper, y, weight=weight, group=group,
        categorical_features=categorical_features,
    )
