"""Train-time reference profile: the model's own drift baseline.

At ``dryad.train`` completion a compact per-feature profile of the
training distribution is computed and embedded in the model artifact
(``Booster.profile``; text-format section + binary meta — both round-
trip through ``Booster.load_any``), so every served model carries its
own baseline and the serve-path drift monitor (obs/drift.py) needs no
side channel:

* **per-feature binned-count distribution** over the sketch's frozen bin
  space — the SAME space the serve batcher bins every request into, so
  serve-side drift accounting is exact set-membership, not re-binning;
  bin 0 is the missing bin, so missing rates ride along for free;
* **bin-edge quantiles** — a decile summary of each numerical feature's
  sketch edges (inspection/debugging; the full edges live in the
  mapper);
* **score histograms** of the model's own raw margin scores on train
  (and the first valid set) on the fixed ``obs.drift.SCORE_BUCKETS``
  layout — the serve side histograms its predictions into the same
  layout, so score-shift PSI is an exact count comparison.

The profile is computed on a deterministic row subsample (stride over
the binned matrix, ``max_rows`` cap) so a 10M-row headline pays one
bounded CPU predict, not a second epoch; counts are INTEGERS end to end
(the merge-counts discipline).  ``DRYAD_PROFILE=0`` skips the capture
entirely (tests/conftest.py pins it off for the tier-1 suite; the
serve/fleet smokes run with it on).
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from dryad_tpu.obs.drift import new_score_state, observe_scores_state

PROFILE_VERSION = 1
#: profile subsample cap — bounds the completion-time CPU predict
DEFAULT_MAX_ROWS = 65536
#: decile grid for the per-feature edge-quantile summary
_QUANTILE_GRID = tuple(i / 10 for i in range(11))


class ReferenceProfile:
    """The compact baseline embedded in the model artifact."""

    __slots__ = ("version", "n_rows", "feature_counts", "quantiles",
                 "score_hist")

    def __init__(self, feature_counts: Sequence[Sequence[int]],
                 quantiles: Sequence[Sequence[float]],
                 score_hist: dict, n_rows: int,
                 version: int = PROFILE_VERSION):
        self.version = int(version)
        self.n_rows = int(n_rows)
        self.feature_counts = [list(map(int, c)) for c in feature_counts]
        self.quantiles = [[float(v) for v in q] for q in quantiles]
        # split name -> [counts, sum, count] on obs.drift.SCORE_BUCKETS
        self.score_hist = {
            str(k): [list(map(int, st[0])), float(st[1]), int(st[2])]
            for k, st in (score_hist or {}).items()}

    @property
    def num_features(self) -> int:
        return len(self.feature_counts)

    def missing_rate(self) -> list[float]:
        """Per-feature missing rate — bin 0 is the missing bin by the
        frozen sketch contract."""
        return [(c[0] / s if (s := sum(c)) else 0.0)
                for c in self.feature_counts]

    # ---- serialization (json-safe; floats round-trip exactly) --------------
    def to_json_dict(self) -> dict:
        return {
            "profile_version": self.version,
            "n_rows": self.n_rows,
            "feature_counts": [list(c) for c in self.feature_counts],
            "quantiles": [list(q) for q in self.quantiles],
            "score_hist": {k: [list(st[0]), st[1], st[2]]
                           for k, st in self.score_hist.items()},
        }

    @classmethod
    def from_json_dict(cls, d: dict) -> "ReferenceProfile":
        return cls(d["feature_counts"], d.get("quantiles") or [],
                   d.get("score_hist") or {}, d.get("n_rows", 0),
                   d.get("profile_version", PROFILE_VERSION))

    def __eq__(self, other) -> bool:
        return (isinstance(other, ReferenceProfile)
                and self.to_json_dict() == other.to_json_dict())

    def __repr__(self) -> str:
        return (f"ReferenceProfile({self.num_features} features, "
                f"{self.n_rows} rows, splits={sorted(self.score_hist)})")


def _subsample(Xb: np.ndarray, max_rows: int) -> np.ndarray:
    """Deterministic stride subsample — chunk/backend invariant (the
    stride depends only on N and the cap, never on data values)."""
    n = int(Xb.shape[0])
    if n <= max_rows:
        return Xb
    stride = -(-n // max_rows)          # ceil: at most max_rows rows
    return Xb[::stride]


def _feature_counts(Xb: np.ndarray, n_bins: Sequence[int]) -> list:
    counts = []
    for f, nb in enumerate(n_bins):
        col = np.minimum(Xb[:, f].astype(np.int64, copy=False), int(nb) - 1)
        counts.append(np.bincount(col, minlength=int(nb)).tolist())
    return counts


def _edge_quantiles(mapper) -> list:
    """Decile summary of each numerical feature's sketch edges (empty
    for categorical features and for bundled mappers, whose columns are
    synthetic stacks without a single edge vector)."""
    feats = getattr(mapper, "features", None)
    if feats is None:
        return []
    out = []
    for fb in feats:
        edges = np.asarray(fb.edges, np.float32)
        if fb.is_categorical or edges.size == 0:
            out.append([])
            continue
        idx = [min(int(round(q * (edges.size - 1))), edges.size - 1)
               for q in _QUANTILE_GRID]
        out.append([float(edges[i]) for i in idx])
    return out


def profile_from_binned(booster, Xb: np.ndarray,
                        valid_binned: Optional[dict] = None, *,
                        max_rows: int = DEFAULT_MAX_ROWS) -> ReferenceProfile:
    """Build a profile from an already-binned matrix (the core both
    ``build_reference_profile`` and the serve bench use).  Scores come
    from the canonical CPU predict — bit-identical across backends, so
    the baseline is backend-invariant by construction."""
    mapper = booster.mapper
    sample = _subsample(np.asarray(Xb), int(max_rows))
    n_bins = [int(b) for b in mapper.n_bins]
    score_hist: dict = {}
    for split, mat in dict({"train": sample}, **(valid_binned or {})).items():
        mat = _subsample(np.asarray(mat), int(max_rows))
        if mat.shape[0] == 0:
            continue
        raw = booster.predict_binned(mat, raw_score=True, backend="cpu")
        state = new_score_state()
        observe_scores_state(state, np.asarray(raw, np.float64))
        score_hist[split] = state
    return ReferenceProfile(
        _feature_counts(sample, n_bins), _edge_quantiles(mapper),
        score_hist, sample.shape[0])


def build_reference_profile(booster, train_set, valid_sets=None, *,
                            max_rows: int = DEFAULT_MAX_ROWS
                            ) -> ReferenceProfile:
    """The ``dryad.train`` completion hook: profile the training
    dataset's binned matrix plus the FIRST valid set's scores (early
    stopping watches that one, so it is the deployment-relevant holdout
    distribution)."""
    valid_binned: dict = {}
    for item in (valid_sets or [])[:1]:
        ds = item[1] if isinstance(item, tuple) else item
        valid_binned["valid"] = ds.X_binned
    if getattr(train_set, "is_streamed", False):
        # the streamed stride sample is exactly X_binned[::stride] read
        # chunk-by-chunk, so streamed-trained models embed bitwise the
        # same reference profile as resident-trained ones
        n = int(train_set.num_rows)
        stride = 1 if n <= int(max_rows) else -(-n // int(max_rows))
        Xb_train = train_set.strided_rows(stride)
    else:
        Xb_train = train_set.X_binned
    return profile_from_binned(booster, Xb_train, valid_binned,
                               max_rows=max_rows)
