"""Canonical quantile sketch → per-feature bin mapper.

This is the host-side, bit-exact ground truth required by the north-star
("Quantile sketching ... stay[s] bit-identical with the CPU reference",
BASELINE.json:5).  Every consumer — the CPU reference trainer, the TPU engine,
and predict on either backend — bins through the *same* frozen edges produced
here, so bit-identity of binned ids is structural rather than numerical.

Binning semantics (frozen contract, shared with data/binning.py and the
device predict path):

* bin id 0 is **always** the missing (NaN) bin, for every feature.
* numerical feature with edges ``e[0..k-1]`` (ascending float32):
  ``bin(x) = 1 + searchsorted(e, x, side='left')`` — i.e. x <= e[i] lands in
  bin i+1; x greater than every edge lands in bin k+1.  Total bins = k+2
  (missing + k+1 value bins), bounded by ``max_bins``.
* categorical feature: categories ranked by (frequency desc, value asc);
  rank r maps to bin r+1; categories beyond the vocab and unseen-at-predict
  values map to the overflow bin (the last bin id).

A split at (feature f, threshold bin t) sends rows with ``bin <= t`` left.
Because the missing bin is 0, missing always travels left; t = 0 expresses
"split missing off from everything else".  (Learned per-node default
direction is layered on top by the grower; the mapper stays direction-free.)
"""

from __future__ import annotations

import dataclasses
import io
from typing import Sequence

import numpy as np

MISSING_BIN = 0


@dataclasses.dataclass
class FeatureBins:
    """Frozen binning recipe for one feature."""

    is_categorical: bool
    # numerical: ascending upper-boundary edges (float32); len k → bins 1..k+1
    edges: np.ndarray
    # categorical: vocab values sorted ascending + their bin ids
    cat_values: np.ndarray
    cat_bins: np.ndarray
    n_bins: int  # total bins including the missing bin (and overflow bin for cats)

    @property
    def overflow_bin(self) -> int:
        return self.n_bins - 1


def _sketch_numerical(col: np.ndarray, max_bins: int) -> FeatureBins:
    from dryad_tpu import native

    edges = native.sketch_numerical(col, max_bins)
    if edges is not None:
        return FeatureBins(
            False, edges, np.empty(0, np.float32), np.empty(0, np.int32),
            int(edges.size) + 2,
        )
    return _sketch_numerical_np(col, max_bins)


def _sketch_numerical_np(col: np.ndarray, max_bins: int) -> FeatureBins:
    """Pure-numpy canonical sketch — the bit-exact spec the native path must match."""
    finite = col[np.isfinite(col)]
    if finite.size == 0:
        edges = np.empty((0,), np.float32)
        return FeatureBins(False, edges, np.empty(0, np.float32), np.empty(0, np.int32), 2)
    distinct = np.unique(finite)
    max_edges = max_bins - 2  # bins = missing + (edges+1)
    if distinct.size - 1 <= max_edges:
        # One bin per distinct value; boundaries midway between neighbours.
        edges = ((distinct[:-1] + distinct[1:]) * np.float32(0.5)).astype(np.float32)
        # A midpoint can collapse onto the lower value for adjacent floats;
        # that still separates the pair (x <= edge keeps the lower value left).
    else:
        # Equal-frequency cuts over the sorted sample, deduplicated so heavy
        # ties never straddle a boundary.
        svals = np.sort(finite)
        pos = (np.arange(1, max_edges + 1, dtype=np.int64) * svals.size) // (max_edges + 1)
        edges = np.unique(svals[pos].astype(np.float32))
    return FeatureBins(
        False, edges.astype(np.float32), np.empty(0, np.float32), np.empty(0, np.int32),
        int(edges.size) + 2,
    )


def _sketch_categorical(col: np.ndarray, max_bins: int) -> FeatureBins:
    finite = col[np.isfinite(col)]
    vals, counts = np.unique(finite, return_counts=True)
    # rank by (count desc, value asc) — deterministic
    order = np.lexsort((vals, -counts))
    n_kept = int(min(vals.size, max_bins - 2))  # reserve missing(0) + overflow(last)
    kept = vals[order[:n_kept]]
    bins = np.arange(1, n_kept + 1, dtype=np.int32)
    # store sorted by value for searchsorted lookup
    sort_idx = np.argsort(kept, kind="stable")
    return FeatureBins(
        True,
        np.empty(0, np.float32),
        kept[sort_idx].astype(np.float32),
        bins[sort_idx].astype(np.int32),
        n_kept + 2,
    )


def sketch_features(
    X: np.ndarray,
    max_bins: int = 256,
    categorical_features: Sequence[int] = (),
) -> "BinMapper":
    """Build the frozen per-feature bin mapper from training data.

    Deterministic pure-numpy canonical implementation; the optional C++
    accelerated path (dryad_tpu.native) must match it bit-for-bit.
    """
    X = np.asarray(X, dtype=np.float32)
    if X.ndim != 2:
        raise ValueError(f"X must be 2-D, got shape {X.shape}")
    cats = frozenset(int(c) for c in categorical_features)
    feats = [
        _sketch_categorical(X[:, f], max_bins) if f in cats else _sketch_numerical(X[:, f], max_bins)
        for f in range(X.shape[1])
    ]
    return BinMapper(feats, max_bins)


class BinMapper:
    """Frozen collection of per-feature binning recipes."""

    def __init__(self, features: list[FeatureBins], max_bins: int):
        self.features = features
        self.max_bins = int(max_bins)

    @property
    def num_features(self) -> int:
        return len(self.features)

    @property
    def n_bins(self) -> np.ndarray:
        return np.array([f.n_bins for f in self.features], np.int32)

    @property
    def total_bins(self) -> int:
        return int(self.n_bins.max(initial=2))

    @property
    def bin_dtype(self) -> np.dtype:
        return np.dtype(np.uint8 if self.total_bins <= 256 else np.uint16)

    @property
    def is_categorical(self) -> np.ndarray:
        return np.array([f.is_categorical for f in self.features], bool)

    def transform_column(self, col: np.ndarray, f: int) -> np.ndarray:
        fb = self.features[f]
        col = np.asarray(col, np.float32)
        out = np.zeros(col.shape, np.int32)
        missing = np.isnan(col)
        if fb.is_categorical:
            idx = np.searchsorted(fb.cat_values, col)
            idx_c = np.minimum(idx, max(fb.cat_values.size - 1, 0))
            if fb.cat_values.size:
                hit = fb.cat_values[idx_c] == col
                out = np.where(hit, fb.cat_bins[idx_c], fb.overflow_bin).astype(np.int32)
            else:
                out[:] = fb.overflow_bin
        else:
            out = (1 + np.searchsorted(fb.edges, col, side="left")).astype(np.int32)
        out[missing] = MISSING_BIN
        return out

    def transform(self, X: np.ndarray) -> np.ndarray:
        """Map raw features → bin ids, dtype uint8/uint16, shape (N, F)."""
        X = np.asarray(X, np.float32)
        out = np.empty(X.shape, self.bin_dtype)
        for f in range(self.num_features):
            out[:, f] = self.transform_column(X[:, f], f)
        return out

    # device-side view: edges padded to a rectangle for jnp bucketize
    def padded_edges(self) -> tuple[np.ndarray, np.ndarray]:
        k = max((f.edges.size for f in self.features), default=0)
        pad = np.full((self.num_features, max(k, 1)), np.inf, np.float32)
        n_edges = np.zeros(self.num_features, np.int32)
        for i, f in enumerate(self.features):
            pad[i, : f.edges.size] = f.edges
            n_edges[i] = f.edges.size
        return pad, n_edges

    # ---- serialization -----------------------------------------------------
    def to_json_dict(self) -> dict:
        """JSON-safe structural dump for the versioned model TEXT format
        (Booster.save_text).  Floats pass through Python float (exact f64
        widening of the f32 edges), so json round-trips them bit-exactly;
        ±inf edges serialize as JSON Infinity (Python json default)."""
        return {
            "type": "plain",
            "max_bins": int(self.max_bins),
            "features": [
                {
                    "is_categorical": bool(f.is_categorical),
                    "edges": [float(e) for e in np.asarray(f.edges, np.float32)],
                    "cat_values": [float(v) for v in
                                   np.asarray(f.cat_values, np.float32)],
                    "cat_bins": [int(b) for b in f.cat_bins],
                    "n_bins": int(f.n_bins),
                }
                for f in self.features
            ],
        }

    @classmethod
    def from_json_dict(cls, d: dict) -> "BinMapper":
        feats = [
            FeatureBins(
                bool(f["is_categorical"]),
                np.asarray(f["edges"], np.float32),
                np.asarray(f["cat_values"], np.float32),
                np.asarray(f["cat_bins"], np.int32),
                int(f["n_bins"]),
            )
            for f in d["features"]
        ]
        return cls(feats, int(d["max_bins"]))

    def to_bytes(self) -> bytes:
        buf = io.BytesIO()
        arrs: dict[str, np.ndarray] = {
            "max_bins": np.array([self.max_bins], np.int64),
            "is_cat": self.is_categorical,
            "n_bins": self.n_bins,
        }
        for i, f in enumerate(self.features):
            arrs[f"edges_{i}"] = f.edges
            arrs[f"catv_{i}"] = f.cat_values
            arrs[f"catb_{i}"] = f.cat_bins
        np.savez_compressed(buf, **arrs)
        return buf.getvalue()

    @classmethod
    def from_bytes(cls, data: bytes) -> "BinMapper":
        with np.load(io.BytesIO(data)) as z:
            if "efb_base" in z.files:   # bundled-mapper container (EFB)
                from dryad_tpu.data.bundling import BundledMapper

                return BundledMapper.from_bytes(data)
            n = z["is_cat"].shape[0]
            feats = [
                FeatureBins(
                    bool(z["is_cat"][i]),
                    z[f"edges_{i}"],
                    z[f"catv_{i}"],
                    z[f"catb_{i}"],
                    int(z["n_bins"][i]),
                )
                for i in range(n)
            ]
            return cls(feats, int(z["max_bins"][0]))
