from dryad_tpu.data.sketch import BinMapper, sketch_features
from dryad_tpu.data.binning import bin_matrix

__all__ = ["BinMapper", "sketch_features", "bin_matrix"]
