"""Streamed (out-of-core) binned dataset: row-chunked tiles on disk.

The binned matrix lives in ONE raw on-disk file (row-major ``(N, F)``
``uint8``/``uint16``, no header — offset math is ``row * F * itemsize``)
and is served in bounded row-range reads.  Everything else (labels,
weights, groups, the frozen mapper) stays resident — at Criteo scale the
binned matrix is what doesn't fit, not the 4-byte-per-row label vector.

Exactness contract (the Issue-17 headline): streamed ≡ resident training
**bitwise**.  The CPU trainer reaches the matrix through
``binned_view()``, whose gathers return arrays elementwise identical to
resident slices — so ``cpu/histogram.build_hist``'s own positional
chunking (and therefore every f64 fold order) is preserved exactly, and
exactness holds by construction rather than by an associativity
argument.  The engine arm assembles the device-resident matrix
chunk-by-chunk through ``device_arrays()`` (prefetcher reads chunk i+1
from disk while chunk i's async ``device_put`` is in flight) and then
dispatches the UNCHANGED jitted programs: out-of-HOST-core, with traced
programs — and their audit goldens — untouched.  Chunking-invariant
subsampling (sketch/GOSS/bagging keyed on global row id) does the rest.

``ChunkPrefetcher`` is the serve batcher's two-deep pipeline idiom as a
data-plane producer: one reader thread, a bounded queue, reads outside
any lock, cancel-safe drain on close.  It is schedule-drill covered
(``analysis/schedules.py`` ``stream-prefetch``) and in dryadlint's
concurrency-target set.
"""

from __future__ import annotations

import mmap
import os
import queue
import threading
from typing import Callable, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from dryad_tpu.data.sketch import BinMapper
from dryad_tpu.dataset import Dataset

#: default rows per streamed chunk (~64 MB of u8 bins at F=64)
DEFAULT_CHUNK_ROWS = 1 << 20

_DONE = object()  # producer sentinel: the stream ended (exhausted or error)


class ChunkPrefetcher:
    """Bounded single-producer chunk pipeline (the two-deep idiom).

    A daemon thread calls ``read(i)`` for ``i in range(n_chunks)`` —
    always OUTSIDE any lock — and feeds a ``queue.Queue(maxsize=depth)``;
    iterating the prefetcher yields ``(i, chunk)`` in order, so chunk
    ``i+1``'s read overlaps the consumer's work on chunk ``i``.
    ``close()`` is cancel-safe from the consumer side at any point: it
    flips the stop flag, drains the queue so a producer blocked on a full
    queue can observe the flag, and joins the thread.  Read errors are
    captured and re-raised in the consumer.
    """

    GUARDED_BY = {"_closed": "_lock", "_error": "_lock"}

    def __init__(self, read: Callable[[int], np.ndarray], n_chunks: int,
                 depth: int = 2):
        self._read = read
        self._n = int(n_chunks)
        self._q: "queue.Queue" = queue.Queue(maxsize=max(1, int(depth)))
        self._lock = threading.Lock()
        self._closed = False
        self._error: Optional[BaseException] = None
        self._thread = threading.Thread(
            target=self._produce, name="dryad-chunk-prefetch", daemon=True)
        self._thread.start()

    def _stopped(self) -> bool:
        with self._lock:
            return self._closed

    def _put_cancellable(self, item) -> bool:
        """Timeout-put loop so a full queue never wedges the producer past
        a close(); True when the item landed, False on cancellation."""
        while True:
            if self._stopped():
                return False
            try:
                self._q.put(item, timeout=0.05)
                return True
            except queue.Full:
                continue

    def _produce(self) -> None:
        try:
            for i in range(self._n):
                if self._stopped():
                    return
                chunk = self._read(i)          # disk I/O outside any lock
                if not self._put_cancellable((i, chunk)):
                    return
        except BaseException as e:             # re-raised in the consumer
            with self._lock:
                self._error = e
        finally:
            self._put_cancellable(_DONE)

    def __iter__(self) -> Iterator[Tuple[int, np.ndarray]]:
        delivered = 0
        while delivered < self._n:
            if self._stopped():
                break
            try:
                item = self._q.get(timeout=0.05)
            except queue.Empty:
                continue
            if item is _DONE:
                break
            delivered += 1
            yield item
        with self._lock:
            err = self._error
        if err is not None:
            raise err

    def close(self) -> None:
        with self._lock:
            already = self._closed
            self._closed = True
        if already:
            return
        # drain OUTSIDE the lock: a producer blocked on the full queue
        # needs the space (or the timeout) to observe the stop flag
        while True:
            try:
                self._q.get_nowait()
            except queue.Empty:
                break
        self._thread.join(timeout=10.0)


class _StreamedMatrix:
    """Read-only stand-in for the resident ``(N, F)`` binned matrix.

    Serves exactly the access patterns the CPU trainer uses — ``Xb[rows]``
    and ``Xb[rows, col]`` with ASCENDING ``rows`` index arrays, plus
    ``.shape``/``.dtype`` — via bounded per-chunk range reads: a gather
    touches only the sub-range ``[rows[i0], rows[i1-1]]`` of each data
    chunk it spans, so nothing larger than one chunk's rows is ever
    resident.  Returned arrays are elementwise identical to resident
    slices, which is what makes every downstream computation (histogram
    fold order included) bitwise unchanged.
    """

    def __init__(self, ds: "StreamedDataset"):
        self._ds = ds
        self.shape = (ds.num_rows, ds.num_features)
        self.dtype = ds.bin_dtype
        self.chunk_rows = ds.chunk_rows

    def __len__(self) -> int:
        return self.shape[0]

    def iter_chunks(self, prefetch: int = 2):
        """Delegate to the dataset's chunk stream (full-sweep consumers)."""
        return self._ds.iter_chunks(prefetch)

    def __getitem__(self, key):
        col: Optional[int] = None
        if isinstance(key, tuple):
            if len(key) != 2:
                raise TypeError("streamed matrix supports [rows] and [rows, col]")
            key, col = key
            col = int(col)
        rc = np.asarray(key)
        if rc.ndim != 1 or not np.issubdtype(rc.dtype, np.integer):
            raise TypeError(
                "streamed matrix gathers take a 1-D integer row-index array "
                f"(got {rc.dtype if hasattr(rc, 'dtype') else type(key)})")
        rc = rc.astype(np.int64, copy=False)
        ds = self._ds
        if rc.size == 0:
            return np.empty((0, ds.num_features) if col is None else 0, self.dtype)
        if rc[0] < 0 or rc[-1] >= ds.num_rows:
            raise IndexError("row index out of range")
        if rc.size > 1 and not bool((np.diff(rc) >= 0).all()):
            # searchsorted below would silently mis-gather on unsorted rows;
            # every trainer row set is an ascending subset by construction
            raise ValueError("streamed matrix gathers require ascending rows")
        out = np.empty((rc.size, ds.num_features) if col is None else rc.size,
                       self.dtype)
        for lo, hi in ds._chunk_bounds():
            i0 = int(np.searchsorted(rc, lo, side="left"))
            i1 = int(np.searchsorted(rc, hi, side="left"))
            if i0 == i1:
                continue
            lo2, hi2 = int(rc[i0]), int(rc[i1 - 1]) + 1
            buf = ds.read_rows(lo2, hi2)
            idx = rc[i0:i1] - lo2
            out[i0:i1] = buf[idx] if col is None else buf[idx, col]
        return out


class StreamedDataset(Dataset):
    """Dataset whose binned matrix is a row-chunked file on disk.

    Built by ``dataset_from_chunks(..., spill=path)`` /
    ``dataset_from_csr_chunks(..., spill=path)`` (the mapper sketch and
    two-pass keying are identical to the resident path) or spilled from a
    resident Dataset via ``from_dataset``.  Labels/weights/groups stay
    resident; ``X_binned`` is deliberately NOT materializable through the
    attribute (use ``binned_view()`` / ``read_rows()`` / ``iter_chunks()``
    / ``materialize()``).
    """

    is_streamed = True

    def __init__(self, path, mapper: BinMapper, y=None, *,
                 weight=None, group=None,
                 categorical_features: Sequence[int] = (),
                 num_rows: Optional[int] = None,
                 chunk_rows: int = DEFAULT_CHUNK_ROWS):
        self.categorical_features = tuple(int(c) for c in categorical_features)
        self.mapper = mapper
        self.path = os.fspath(path)
        self.bin_dtype = np.dtype(mapper.bin_dtype)
        self.num_features = int(mapper.num_features)
        row_bytes = self.num_features * self.bin_dtype.itemsize
        size = os.path.getsize(self.path)
        if num_rows is None:
            if row_bytes == 0 or size % row_bytes:
                raise ValueError(
                    f"{self.path}: size {size} is not a multiple of the "
                    f"row stride {row_bytes} (F={self.num_features}, "
                    f"dtype={self.bin_dtype})")
            num_rows = size // row_bytes
        elif int(num_rows) * row_bytes > size:
            raise ValueError(
                f"{self.path}: {size} bytes holds fewer than "
                f"{num_rows} x {row_bytes}-byte rows")
        self.num_rows = int(num_rows)
        self.chunk_rows = max(1, int(chunk_rows))
        self._attach_targets(y, weight, group)

    # the resident attribute is a trap on this class: everything that can
    # legitimately touch the matrix goes through the bounded accessors
    @property
    def X_binned(self):
        raise TypeError(
            "StreamedDataset keeps the binned matrix on disk — use "
            "binned_view()/read_rows()/iter_chunks(), or materialize() "
            "for a resident copy")

    @property
    def num_chunks(self) -> int:
        return -(-self.num_rows // self.chunk_rows)

    def _chunk_bounds(self) -> List[Tuple[int, int]]:
        return [(lo, min(lo + self.chunk_rows, self.num_rows))
                for lo in range(0, self.num_rows, self.chunk_rows)]

    def read_rows(self, lo: int, hi: int) -> np.ndarray:
        """Rows ``[lo, hi)`` as a fresh contiguous array.  ``np.fromfile``
        at an explicit offset: the pages land in the OS page cache, not in
        process RSS, so training residency stays bounded by chunk size."""
        lo, hi = int(lo), int(hi)
        if not 0 <= lo <= hi <= self.num_rows:
            raise ValueError(f"row range [{lo}, {hi}) outside [0, {self.num_rows})")
        count = (hi - lo) * self.num_features
        if count == 0:
            return np.empty((0, self.num_features), self.bin_dtype)
        with open(self.path, "rb") as f:
            f.seek(lo * self.num_features * self.bin_dtype.itemsize)
            buf = np.fromfile(f, dtype=self.bin_dtype, count=count)
        if buf.size != count:
            raise IOError(
                f"{self.path}: short read at rows [{lo}, {hi}) "
                f"({buf.size} of {count} elements)")
        return buf.reshape(hi - lo, self.num_features)

    def iter_chunks(self, prefetch: int = 2):
        """Yield ``(lo, hi, rows[lo:hi])`` in order.  With ``prefetch > 0``
        a bounded reader thread loads chunk i+1 while the caller works on
        chunk i (the two-deep pipeline); ``prefetch=0`` reads inline."""
        bounds = self._chunk_bounds()
        if prefetch <= 0 or len(bounds) <= 1:
            for lo, hi in bounds:
                yield lo, hi, self.read_rows(lo, hi)
            return
        pf = ChunkPrefetcher(lambda i: self.read_rows(*bounds[i]),
                             len(bounds), depth=prefetch)
        try:
            for i, buf in pf:
                yield bounds[i][0], bounds[i][1], buf
        finally:
            pf.close()

    def binned_view(self) -> _StreamedMatrix:
        """The CPU trainer's matrix stand-in (see ``_StreamedMatrix``)."""
        return _StreamedMatrix(self)

    @property
    def has_missing(self) -> bool:
        # same reduction as Dataset.has_missing, folded chunk-by-chunk
        if self._has_missing is None:
            zero_cols = np.zeros(self.num_features, bool)
            for _lo, _hi, buf in self.iter_chunks():
                zero_cols |= (buf == 0).any(axis=0)
            eligible = ~self.mapper.is_categorical
            bundled = getattr(self.mapper, "bundled_mask", None)
            if bundled is not None:
                eligible &= ~bundled
            self._has_missing = bool((zero_cols & eligible).any())
        return self._has_missing

    def strided_rows(self, stride: int) -> np.ndarray:
        """Exactly ``Xb[::stride]`` (the reference-profile subsample) via
        chunked reads — keeps train-time profiles bitwise-equal streamed
        vs resident."""
        stride = max(1, int(stride))
        parts: list = []
        for lo, hi, buf in self.iter_chunks(prefetch=0):
            first = -(-lo // stride) * stride  # first stride multiple >= lo
            if first >= hi:
                continue
            parts.append(np.ascontiguousarray(buf[first - lo::stride]))
        if not parts:
            return np.empty((0, self.num_features), self.bin_dtype)
        return parts[0] if len(parts) == 1 else np.concatenate(parts, axis=0)

    def device_arrays(self):
        """Chunk-by-chunk host->device assembly: the prefetcher reads chunk
        i+1 from disk while chunk i's async ``device_put`` is in flight;
        the parts concatenate ON DEVICE into the resident matrix the
        unchanged jitted programs consume.  Out-of-HOST-core — peak host
        residency is the prefetch window, never ``(N, F)``."""
        if self._device_cache is None:
            import jax
            import jax.numpy as jnp

            parts = [jax.device_put(buf) for _lo, _hi, buf in self.iter_chunks()]
            Xd = parts[0] if len(parts) == 1 else jnp.concatenate(parts, axis=0)
            self._device_cache = (
                Xd,
                None if self.y is None else jnp.asarray(self.y),
                None if self.weight is None else jnp.asarray(self.weight),
            )
        return self._device_cache

    def materialize(self) -> Dataset:
        """Resident Dataset over the identical binned matrix (debug/tests;
        reads the whole file — defeats the point at production scale)."""
        return Dataset.from_binned(
            self.read_rows(0, self.num_rows), self.mapper, self.y,
            weight=self.weight, group=self.group,
            categorical_features=self.categorical_features)

    @classmethod
    def from_dataset(cls, ds: Dataset, path, *,
                     chunk_rows: int = DEFAULT_CHUNK_ROWS) -> "StreamedDataset":
        """Spill a resident Dataset's binned matrix to ``path`` and return
        the streamed equivalent (the streamed ≡ resident test fixture)."""
        sink = SpillSink(path, ds.num_rows, ds.num_features,
                         np.dtype(ds.mapper.bin_dtype))
        step = max(1, int(chunk_rows))
        for lo in range(0, ds.num_rows, step):
            sink.write(ds.X_binned[lo:lo + step])
        sink.finish()
        return cls(path, ds.mapper, ds.y, weight=ds.weight, group=ds.group,
                   categorical_features=ds.categorical_features,
                   num_rows=ds.num_rows, chunk_rows=chunk_rows)


class SpillSink:
    """Sequential chunk writer into a preallocated raw on-disk matrix.

    Each block is written through a transient ``np.memmap`` window that is
    flushed and dropped from residency (``madvise(MADV_DONTNEED)``)
    immediately — the builder's peak RSS stays ~one chunk, never the full
    pass-2 matrix.  This is the spill target ``dataset_from_chunks`` /
    ``dataset_from_csr_chunks`` write through.
    """

    def __init__(self, path, total_rows: int, num_features: int,
                 dtype: np.dtype):
        self.path = os.fspath(path)
        self.total_rows = int(total_rows)
        self.num_features = int(num_features)
        self.dtype = np.dtype(dtype)
        self.row_bytes = self.num_features * self.dtype.itemsize
        with open(self.path, "wb") as f:
            f.truncate(self.total_rows * self.row_bytes)
        self.rows_written = 0

    def write(self, block: np.ndarray) -> None:
        block = np.asarray(block, self.dtype)
        n = block.shape[0]
        if n == 0:
            return
        if block.ndim != 2 or block.shape[1] != self.num_features:
            raise ValueError(
                f"spill block shape {block.shape} != (*, {self.num_features})")
        if self.rows_written + n > self.total_rows:
            raise ValueError(
                f"stream yielded more than the declared {self.total_rows} rows")
        mm = np.memmap(self.path, dtype=self.dtype, mode="r+",
                       offset=self.rows_written * self.row_bytes,
                       shape=(n, self.num_features))
        mm[:] = block
        mm.flush()
        try:
            mm._mmap.madvise(mmap.MADV_DONTNEED)
        except (AttributeError, ValueError, OSError):
            pass  # platform without madvise: correctness is unaffected
        del mm
        self.rows_written += n

    def finish(self) -> None:
        if self.rows_written != self.total_rows:
            raise ValueError(
                f"stream yielded {self.rows_written} rows, "
                f"expected {self.total_rows}")
