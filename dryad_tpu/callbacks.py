"""Training callbacks: logging/observability (SURVEY.md §5 metrics stream).

A callback is ``fn(iteration, info)`` where ``info`` carries at least
``{"iteration": int}`` plus ``valid_<metric>`` entries when a validation set
is present.  ``dryad.train`` accepts a list and fans out in order.

Note on timing under the device trainer: iterations dispatch asynchronously
(engine/train.py), so wall-clock deltas between callbacks measure dispatch,
not device execution — ``JsonlLogger`` records them as ``dispatch_s`` and
the end-of-training summary carries the true wall time.
"""

from __future__ import annotations

import json
import time
from typing import Callable, Optional, Sequence

Callback = Callable[[int, dict], None]


def combine(callbacks: Optional[Sequence[Callback]]) -> Optional[Callback]:
    if not callbacks:
        return None
    if len(callbacks) == 1:
        return callbacks[0]

    def fan_out(it: int, info: dict) -> None:
        for cb in callbacks:
            cb(it, info)

    return fan_out


def log_evaluation(period: int = 1, printer: Callable[[str], None] = print) -> Callback:
    """Print per-iteration eval metrics every ``period`` iterations."""

    def cb(it: int, info: dict) -> None:
        if period > 0 and it % period == 0:
            metrics = {k: v for k, v in info.items() if k != "iteration"}
            body = "  ".join(f"{k}: {v:.6g}" if isinstance(v, float) else f"{k}: {v}"
                             for k, v in metrics.items())
            printer(f"[{it}] {body}" if body else f"[{it}]")

    return cb


class JsonlLogger:
    """Append one JSON line per iteration to ``path``."""

    def __init__(self, path: str):
        self.path = path
        self._t0 = time.perf_counter()
        self._last = self._t0
        self._fh = open(path, "a", buffering=1)

    def __call__(self, it: int, info: dict) -> None:
        now = time.perf_counter()
        rec = dict(info)
        rec["dispatch_s"] = round(now - self._last, 6)
        rec["elapsed_s"] = round(now - self._t0, 6)
        self._last = now
        self._fh.write(json.dumps(rec) + "\n")

    def close(self) -> None:
        self._fh.close()
