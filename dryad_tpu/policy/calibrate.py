"""Calibration: the measured loop from stage probes to the policy table.

``run_sweep`` drives the liveness-proven stage probes
(``engine/probes``) in A/B arm pairs per gate — partition reduce vs
gather, hist_reduce fused vs feature at three widths, packed vs legacy
predict traversal, plus the two histogram passes as informational walls
— and ``derive_overrides`` turns the walls into per-gate table entries
(spread-vetoed: a >5% arm spread keeps the committed value, the
CLAUDE.md "suspect capture, never a verdict" rule).  ``calibrate``
stamps the result with device_kind/git_rev and merges it under that
device's key; ``check_calib`` diffs a live sweep's resolutions against
the committed table the way ``bench_trend --check`` does.

``run_selftest`` is the ci.sh gate (CPU, seeded, NO probes): the
committed golden must equal the code defaults, every gate must resolve
identically to the pre-PR hand-tuned constants across shapes straddling
each threshold, a perturbed table entry must flip EXACTLY the intended
gate and nothing else, and save/load must round-trip resolutions
bitwise.

Probe imports stay lazy inside the sweep functions: importing this
module (and running the selftest) is jax-free by lint — the sweep is
the one explicitly device-facing operation in the package.
"""

from __future__ import annotations

from typing import Optional

from dryad_tpu.policy import gates as _gates
from dryad_tpu.policy import table as _table

#: per-arm spread above this vetoes a derived override (CLAUDE.md)
SPREAD_SUSPECT = 0.05

#: the sweep plan: gate -> A/B probe arms at the widths that straddle
#: the committed threshold (num_features; bins fixed at 256 so u8 row
#: bytes == F).  ``derive`` names the rule below; None = informational.
SWEEP = (
    {"gate": "partition",
     "arms": {"reduce": "partition_reduce", "gather": "partition_gather"},
     "widths": (512, 4096, 8192),
     "derive": "max_winning_row_bytes"},
    {"gate": "hist_reduce",
     "arms": {"fused": "split_scan", "feature": "hist_reduce"},
     "widths": (128, 1024, 2000),
     "derive": "crossover_wide_bytes"},
    {"gate": "predict_layout",
     "arms": {"packed": "predict_traversal_packed",
              "legacy": "predict_traversal"},
     "widths": (28,),
     "derive": "preferred_arm"},
    {"gate": "hist_backend",
     "arms": {"masked": "hist_masked", "segmented": "hist_segmented"},
     "widths": (28,),
     "derive": None},
)

#: probe bins for every sweep shape (u8 binned matrix: row bytes == F)
_SWEEP_BINS = 256


def run_sweep(rows: Optional[int] = None, K: int = 3, reps: int = 2,
              num_slots: int = 8, quiet: bool = True) -> dict:
    """Measured walls: {gate: {width: {arm: {"ms", "spread"}}}}."""
    from dryad_tpu.engine import probes

    out: dict = {}
    for job in SWEEP:
        gate = job["gate"]
        out[gate] = {}
        for width in job["widths"]:
            out[gate][width] = {}
            for arm, probe in job["arms"].items():
                r = probes.run_probe(
                    probe, rows=rows, K=K, reps=reps,
                    num_features=width, total_bins=_SWEEP_BINS,
                    num_slots=num_slots)
                out[gate][width][arm] = {"ms": r["ms"],
                                         "spread": r["spread"]}
                if not quiet:
                    print(f"calib {gate:15s} F={width:<5d} {arm:10s} "
                          f"{r['ms']:10.2f} ms  spread {r['spread']:.3f}")
    return out


def _suspect(walls: dict) -> bool:
    return any(a["spread"] > SPREAD_SUSPECT for a in walls.values())


def derive_overrides(measured: dict) -> tuple[dict, dict]:
    """Walls -> per-gate table overrides + per-gate verdict notes.

    Rules (each keeps the committed value on a spread veto or when the
    measurements never cross — overrides only record what the device
    actually demonstrated):

    * ``max_winning_row_bytes`` (partition): the largest tested u8 width
      where the reduce arm still beats the gather becomes
      ``reduce_max_row_bytes`` (0 when the gather wins everywhere).
    * ``crossover_wide_bytes`` (hist_reduce): the smallest tested width
      where the feature arm beats the fused scan sets ``wide_bytes`` to
      that shape's F*B*bin_bytes.
    * ``preferred_arm`` (predict_layout): the faster traversal arm.
    """
    overrides: dict = {}
    notes: dict = {}
    rules = {job["gate"]: job["derive"] for job in SWEEP}
    for gate, by_width in measured.items():
        rule = rules.get(gate)
        if rule is None:
            notes[gate] = "informational"
            continue
        if any(_suspect(w) for w in by_width.values()):
            notes[gate] = "suspect capture (arm spread > "\
                f"{SPREAD_SUSPECT:.0%}) — committed value kept"
            continue
        if rule == "max_winning_row_bytes":
            wins = [w for w, arms in sorted(by_width.items())
                    if arms["reduce"]["ms"] <= arms["gather"]["ms"]]
            overrides[gate] = {"reduce_max_row_bytes":
                               (max(wins) if wins else 0)}
            notes[gate] = f"reduce wins at widths {wins}"
        elif rule == "crossover_wide_bytes":
            bin_bytes = 1 if _SWEEP_BINS <= 256 else 2
            crossed = [w for w, arms in sorted(by_width.items())
                       if arms["feature"]["ms"] < arms["fused"]["ms"]]
            if crossed:
                overrides[gate] = {"wide_bytes":
                                   crossed[0] * _SWEEP_BINS * bin_bytes}
                notes[gate] = f"feature wins from width {crossed[0]}"
            else:
                notes[gate] = "feature arm never won — committed kept"
        elif rule == "preferred_arm":
            (width, arms), = list(by_width.items())
            pick = min(arms, key=lambda a: arms[a]["ms"])
            overrides[gate] = {"preferred": pick}
            notes[gate] = f"{pick} faster at width {width}"
    return overrides, notes


def calibrate(device_kind: Optional[str] = None, rows: Optional[int] = None,
              quiet: bool = True) -> tuple[dict, dict]:
    """Run the sweep and build the refreshed ``devices`` map (committed
    devices + this device's derived entry, stamped) plus the flat
    ``CALIB_*`` artifact dict for the trend ledger."""
    from dryad_tpu.obs.trends import artifact_stamp
    from dryad_tpu.policy.device import current_device_kind

    if device_kind is None:
        device_kind = current_device_kind()
    measured = run_sweep(rows=rows, quiet=quiet)
    overrides, notes = derive_overrides(measured)
    stamp = artifact_stamp(device_kind=device_kind)
    devices = dict(_table.current_table().devices)
    if device_kind:
        devices[device_kind] = {
            "gates": overrides,
            "git_rev": stamp.get("git_rev"),
            "notes": notes,
        }
    artifact = dict(stamp)
    artifact["calib_schema"] = _table.SCHEMA_VERSION
    for gate, by_width in measured.items():
        for width, arms in by_width.items():
            for arm, w in arms.items():
                artifact[f"calib_ms_{gate}_{arm}_f{width}"] = w["ms"]
                artifact[f"calib_spread_{gate}_{arm}_f{width}"] = w["spread"]
    artifact["calibration"] = {"overrides": overrides, "notes": notes}
    return devices, artifact


def check_calib(device_kind: Optional[str] = None,
                rows: Optional[int] = None, quiet: bool = True) -> dict:
    """Diff a live sweep against the committed table: for every gate the
    sweep can derive, the committed table's resolution at each tested
    shape must match the live-derived table's (suspect captures are
    reported but never fail — bench_trend's verdict discipline)."""
    from dryad_tpu.policy.device import current_device_kind

    if device_kind is None:
        device_kind = current_device_kind()
    measured = run_sweep(rows=rows, quiet=quiet)
    overrides, notes = derive_overrides(measured)
    committed = _table.current_table()
    live = _table.CalibrationTable(
        devices={**committed.devices,
                 device_kind or "_live": {"gates": overrides}},
        source="<live sweep>")
    report: dict = {"ok": True, "device_kind": device_kind,
                    "notes": notes, "gates": {}}
    for job in SWEEP:
        gate = job["gate"]
        if job["derive"] is None or gate not in measured:
            continue
        suspect = any(_suspect(w) for w in measured[gate].values())
        diffs = []
        for width in job["widths"]:
            feats = _features_at(gate, width)
            want = _gates.resolve(gate, feats, device_kind=device_kind,
                                  table=committed)
            got = _gates.resolve(gate, feats,
                                 device_kind=device_kind or "_live",
                                 table=live)
            if want != got:
                diffs.append({"width": width, "committed": want,
                              "live": got})
        verdict = ("suspect" if (diffs and suspect)
                   else "drift" if diffs else "ok")
        report["gates"][gate] = {"verdict": verdict, "diffs": diffs}
        if verdict == "drift":
            report["ok"] = False
    return report


def _features_at(gate: str, width: int) -> dict:
    """The resolve() features a sweep shape exercises (u8, 256 bins)."""
    if gate == "partition":
        return {"num_features": width, "itemsize": 1}
    if gate == "hist_reduce":
        return {"num_features": width, "total_bins": _SWEEP_BINS,
                "n_shards": 8}
    if gate == "predict_layout":
        return {"fits": True}
    raise KeyError(gate)


# ---------------------------------------------------------------------------
# selftest (the ci.sh gate: CPU, seeded, no probes)

#: every gate's oracle sweep: (features, pre-PR-constant arm).  The
#: expected arms are the HAND-TUNED semantics spelled out, independent
#: of GATE_DEFAULTS — this is the parity anchor, not a tautology.
PARITY_CASES: dict = {
    "partition": [
        ({"num_features": 4096, "itemsize": 1}, "reduce"),
        ({"num_features": 4097, "itemsize": 1}, "gather"),
        ({"num_features": 2048, "itemsize": 2}, "reduce"),
        ({"num_features": 2049, "itemsize": 2}, "gather"),
        ({"num_features": 28, "itemsize": 1}, "reduce"),
        ({"num_features": 2000, "itemsize": 1}, "reduce"),
        ({"num_features": 2000, "itemsize": 2}, "reduce"),
        ({"num_features": 2000, "itemsize": 4}, "gather"),
    ],
    "hist_reduce": [
        ({"num_features": 28, "total_bins": 256, "n_shards": 1}, "fused"),
        ({"num_features": 28, "total_bins": 256, "n_shards": 8}, "fused"),
        ({"num_features": 1023, "total_bins": 256, "n_shards": 2}, "fused"),
        ({"num_features": 1024, "total_bins": 256, "n_shards": 2},
         "feature"),
        ({"num_features": 1024, "total_bins": 256, "n_shards": 1}, "fused"),
        ({"num_features": 2000, "total_bins": 256, "n_shards": 8},
         "feature"),
        ({"num_features": 256, "total_bins": 512, "n_shards": 2},
         "feature"),
        ({"num_features": 255, "total_bins": 512, "n_shards": 2}, "fused"),
    ],
    "hist_backend": [
        ({"platform": "cpu"}, "xla"),
        ({"platform": "tpu"}, "pallas"),
        ({"platform": "axon"}, "pallas"),
        ({"platform": "gpu"}, "xla"),
    ],
    "deep_layout": [
        ({"num_leaves": 512, "record_bytes": 128}, "layout"),
        ({"num_leaves": 513, "record_bytes": 128}, "legacy"),
        ({"num_leaves": 512, "record_bytes": 129}, "legacy"),
        ({"num_leaves": 31, "record_bytes": 37}, "layout"),
    ],
    "leafwise_layout": [
        ({"max_depth": 10}, "layout"),
        ({"max_depth": 11}, "legacy"),
        ({"max_depth": 1}, "layout"),
        ({"max_depth": 0}, "legacy"),
    ],
    "predict_layout": [
        ({"fits": True}, "packed"),
        ({"fits": False}, "legacy"),
    ],
    "predict_sharded": [
        ({"work": 32767}, "single"),
        ({"work": 32768}, "sharded"),
        ({"work": 1}, "single"),
    ],
    "chunk_cap": [
        ({}, "8/4/2"),
    ],
}

#: per-gate perturbation for the flip test: (override entry, the case
#: index in PARITY_CASES whose arm must flip under it)
_PERTURBATIONS: dict = {
    "partition": ({"reduce_max_row_bytes": 0}, 0),
    "hist_reduce": ({"wide_bytes": 1}, 1),
    "hist_backend": ({"pallas_platforms": []}, 1),
    "deep_layout": ({"max_leaves": 256}, 0),
    "leafwise_layout": ({"max_segments": 512}, 0),
    "predict_layout": ({"preferred": "legacy"}, 0),
    "predict_sharded": ({"min_work": 1}, 0),
    "chunk_cap": ({"ladder": [2]}, 0),
}

_SELFTEST_KIND = "calib-selftest-device"


def _resolve_all(table: _table.CalibrationTable, device_kind) -> dict:
    """Every parity case's arm under one table: {(gate, idx): arm}."""
    return {(g, i): _gates.resolve(g, feats, device_kind=device_kind,
                                   table=table)
            for g, cases in PARITY_CASES.items()
            for i, (feats, _want) in enumerate(cases)}


def run_selftest(quiet: bool = False) -> int:
    """The ci.sh gate; returns a process exit code."""
    import tempfile

    failures: list[str] = []

    # 1. the committed golden must load clean and equal the code defaults
    golden = _table.load_table(_table.GOLDEN_PATH, explicit=False)
    if golden.fallback_reason:
        failures.append(f"committed golden unusable: "
                        f"{golden.fallback_reason}")
    elif golden.devices.get(_table.DEFAULT_DEVICE_KEY, {}).get("gates") \
            != _table.GATE_DEFAULTS:
        failures.append("committed golden _default drifted from "
                        "table.GATE_DEFAULTS — recommit calibration.json")

    # 2. default-table parity: every gate == the pre-PR constants
    for gate, cases in PARITY_CASES.items():
        for feats, want in cases:
            got = _gates.resolve(gate, feats, device_kind=None,
                                 table=golden)
            if got != want:
                failures.append(
                    f"default parity: {gate} {feats} -> {got}, "
                    f"pre-PR constant says {want}")

    # 3. a perturbed entry flips EXACTLY the intended gate
    base = _resolve_all(golden, _SELFTEST_KIND)
    for gate, (override, flip_idx) in _PERTURBATIONS.items():
        perturbed = _table.CalibrationTable(
            devices={**golden.devices,
                     _SELFTEST_KIND: {"gates": {gate: override}}},
            source="<selftest>")
        got = _resolve_all(perturbed, _SELFTEST_KIND)
        flipped = {k for k in base if base[k] != got[k]}
        if (gate, flip_idx) not in flipped:
            failures.append(f"perturbing {gate} {override} did not flip "
                            f"its target case {flip_idx}")
        stray = {k for k in flipped if k[0] != gate}
        if stray:
            failures.append(f"perturbing {gate} leaked into {sorted(stray)}")

    # 4. save/load round-trip preserves every resolution bitwise
    with tempfile.NamedTemporaryFile("w", suffix=".json",
                                     delete=False) as f:
        path = f.name
    try:
        devices = {**golden.devices,
                   _SELFTEST_KIND: {"gates": {"partition":
                                              {"reduce_max_row_bytes": 64}},
                                    "git_rev": "deadbeef"}}
        _table.save_table(devices, path)
        loaded = _table.load_table(path)
        if loaded.fallback_reason:
            failures.append(f"round-trip reload failed: "
                            f"{loaded.fallback_reason}")
        elif loaded.devices != devices:
            failures.append("round-trip devices dict drifted")
        else:
            before = _resolve_all(
                _table.CalibrationTable(devices=devices), _SELFTEST_KIND)
            after = _resolve_all(loaded, _SELFTEST_KIND)
            if before != after:
                failures.append("round-trip resolutions drifted")
    finally:
        import os as _os

        _os.unlink(path)

    # 5. the derive rules on seeded walls (no probes)
    seeded = {
        "partition": {512: {"reduce": {"ms": 1.0, "spread": 0.0},
                            "gather": {"ms": 2.0, "spread": 0.0}},
                      4096: {"reduce": {"ms": 1.0, "spread": 0.0},
                             "gather": {"ms": 1.5, "spread": 0.0}},
                      8192: {"reduce": {"ms": 3.0, "spread": 0.0},
                             "gather": {"ms": 1.0, "spread": 0.0}}},
        "hist_reduce": {128: {"fused": {"ms": 1.0, "spread": 0.0},
                              "feature": {"ms": 2.0, "spread": 0.0}},
                        1024: {"fused": {"ms": 3.0, "spread": 0.0},
                               "feature": {"ms": 2.0, "spread": 0.0}},
                        2000: {"fused": {"ms": 5.0, "spread": 0.0},
                               "feature": {"ms": 2.0, "spread": 0.0}}},
        "predict_layout": {28: {"packed": {"ms": 1.0, "spread": 0.0},
                                "legacy": {"ms": 2.0, "spread": 0.0}}},
        "hist_backend": {28: {"masked": {"ms": 1.0, "spread": 0.0},
                              "segmented": {"ms": 1.0, "spread": 0.0}}},
        "suspect_gate_check": {},
    }
    seeded.pop("suspect_gate_check")
    ov, _notes = derive_overrides(seeded)
    if ov.get("partition") != {"reduce_max_row_bytes": 4096}:
        failures.append(f"derive partition: {ov.get('partition')}")
    if ov.get("hist_reduce") != {"wide_bytes": 1024 * 256}:
        failures.append(f"derive hist_reduce: {ov.get('hist_reduce')}")
    if ov.get("predict_layout") != {"preferred": "packed"}:
        failures.append(f"derive predict_layout: {ov.get('predict_layout')}")
    # the spread veto must keep the committed value
    seeded["partition"][512]["reduce"]["spread"] = 0.5
    ov2, notes2 = derive_overrides(seeded)
    if "partition" in ov2 or "suspect" not in notes2.get("partition", ""):
        failures.append("spread veto failed to hold the partition gate")

    for msg in failures:
        print(f"CALIB SELFTEST FAIL: {msg}")
    if not failures and not quiet:
        n = sum(len(c) for c in PARITY_CASES.values())
        print(f"CALIB SELFTEST OK: {n} parity cases pre-PR-identical, "
              f"{len(_PERTURBATIONS)} single-gate flips exact, "
              "round-trip + derive rules + spread veto green")
    return 1 if failures else 0
