"""Self-tuning dispatch: the device-keyed calibration policy layer (r23).

Every hand-tuned dispatch gate in the engine/serve/resilience stack —
``partition_prefers_reduce``'s 4 KB/row, ``hist_reduce_resolved``'s
256 KB wide-shape gate, ``deep_layout_supported``'s leaf/record caps,
serve's ``SHARDED_MIN_WORK``, hist backend "auto",
``Params.predict_layout="auto"``, the resilience chunk-cap ladder —
routes through ONE entry here (``gates.resolve``), backed by a
committed, schema-versioned, device-keyed calibration table
(``goldens/calibration.json``).  The committed defaults ARE the
hand-tuned constants, and an empty/missing/unknown device key resolves
bitwise-identically to the pre-policy behavior — so pre-existing saved
models, the jaxpr program digests, and every parity test are untouched
by construction.  ``calibrate.py`` (CLI: ``python -m dryad_tpu profile
--calibrate``) refreshes the table from the liveness-proven stage
probes; ``--check-calib`` diffs a live sweep against the committed
table the way ``bench_trend --check`` does.

The hard invariant, machine-pinned by ``analysis --ci``: policy flips
never change traced-program semantics — only which PRE-AUDITED arm
dispatches.  The package is jax-free by lint (``policy-jax-free``,
transitive): gate resolution must work in the fleet control plane while
a device is wedged.  The two sanctioned jax boundaries are lazy and
outside the resolution path: ``device.current_device_kind`` (waived
probe) and the calibration sweep's probe imports (an explicitly
device-facing operation, like fleet's replica subprocesses).
"""

from dryad_tpu.policy.device import current_device_kind
from dryad_tpu.policy.gates import (
    decisions,
    gate_value,
    resolve,
    stats_block,
)
from dryad_tpu.policy.table import (
    GATE_DEFAULTS,
    GOLDEN_PATH,
    SCHEMA_VERSION,
    CalibrationTable,
    current_table,
    load_table,
    reset_cache,
    save_table,
)

__all__ = [
    "CalibrationTable",
    "GATE_DEFAULTS",
    "GOLDEN_PATH",
    "SCHEMA_VERSION",
    "current_device_kind",
    "current_table",
    "decisions",
    "gate_value",
    "load_table",
    "reset_cache",
    "resolve",
    "save_table",
    "stats_block",
]
