"""The ONE ``device_kind`` derivation (r23 dedupe satellite).

``bench.py``, ``scripts/bench_serve.py``, the profile CLI, and the
calibration table all used to hand-roll ``getattr(dev, "device_kind",
None) or dev.platform`` independently; this helper is now the single
source, memoized per process (device topology cannot change mid-run).

It lives in the jax-free policy package because the calibration TABLE
keys off device_kind and must be loadable in the fleet control plane —
so the jax probe below is lazy, best-effort, and the one waived
exception to ``policy-jax-free``: importing this module never pulls
jax, and every failure mode (no jax, no devices, wedged runtime)
resolves to ``None``, which the table maps to the committed defaults.
"""

from __future__ import annotations

from typing import Optional

_UNRESOLVED = object()
_cached: object = _UNRESOLVED


def current_device_kind() -> Optional[str]:
    """The primary device's kind ("TPU v5e", "cpu", ...), or None when no
    jax runtime is reachable.  Memoized; ``reset()`` un-memoizes (tests)."""
    global _cached
    if _cached is _UNRESOLVED:
        try:
            import jax  # dryadlint: disable=policy-jax-free -- the ONE sanctioned lazy device probe; resolution paths pass device_kind explicitly or accept the None->defaults fallback

            dev = jax.devices()[0]
            _cached = getattr(dev, "device_kind", None) or dev.platform
        except Exception:  # noqa: BLE001 — a stamp/table probe never raises
            _cached = None
    return _cached  # type: ignore[return-value]


def reset() -> None:
    """Forget the memoized kind (test isolation)."""
    global _cached
    _cached = _UNRESOLVED
