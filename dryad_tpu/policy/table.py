"""The device-keyed calibration table (pure stdlib: json/os/warnings).

Layout of ``goldens/calibration.json`` (schema-versioned, round-tripped
by ``save_table``/``load_table``)::

    {"calibration_schema": 1,
     "devices": {
       "_default": {"gates": { <gate>: { <key>: value, ... }, ... }},
       "TPU v5e":  {"gates": {...}, "git_rev": "...", "measured": {...}}
     }}

Resolution overlays, most specific last: the CODE defaults below (the
pre-policy hand-tuned constants — the ultimate fallback when the file
itself is unreadable), then the table's ``"_default"`` entry, then the
entry for the caller's ``device_kind``.  A device_kind with no entry is
the NORMAL state for the committed table (it ships only ``"_default"``)
and resolves silently to the defaults; loud-once fallback (one
``warnings.warn`` per process, surfaced in ``gates.stats_block``) is
reserved for genuinely broken states: an unreadable/corrupt/
wrong-schema table file, or an unknown device key in an EXPLICITLY
loaded table (``DRYAD_POLICY_TABLE`` / ``load_table(path)``), where the
operator clearly expected calibrated entries to apply.

The committed ``_default`` gates MUST stay equal to ``GATE_DEFAULTS``
(``calibrate.run_selftest`` and tests/test_policy.py pin it): the
parity contract is that the default table resolves bitwise-identically
to the pre-PR hardcoded constants.
"""

from __future__ import annotations

import copy
import json
import os
import warnings
from typing import Optional

SCHEMA_VERSION = 1
DEFAULT_DEVICE_KEY = "_default"
GOLDEN_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "goldens", "calibration.json")
#: explicit table override for a whole process (tests, operators)
TABLE_ENV = "DRYAD_POLICY_TABLE"

#: The pre-policy hand-tuned constants, verbatim (module:line cites the
#: pre-r23 home).  These are CODE, not config: the committed golden's
#: ``_default`` entry must equal this dict byte-for-byte on load.
GATE_DEFAULTS: dict = {
    # levelwise.partition_prefers_reduce (r5): masked reduce over the
    # contiguous (N, F) matrix while F*itemsize <= 4 KB/row, else gather
    "partition": {"reduce_max_row_bytes": 4096},
    # config.HIST_REDUCE_WIDE_BYTES (r16): feature-parallel reduction
    # once F * B * bin_bytes >= 256 KB AND >1 shard participates
    "hist_reduce": {"wide_bytes": 262144},
    # histogram.resolve_backend "auto": the Pallas kernel on TPU-class
    # platforms (axon = the tunneled-TPU plugin), XLA everywhere else
    "hist_backend": {"pallas_platforms": ["axon", "tpu"]},
    # levelwise.deep_layout_supported (r10): calibrated caps — leaf
    # budgets past 512 mandate non-noise empty-segment movement; records
    # past 128 B multiply moved bytes past the recoverable sort+gather
    "deep_layout": {"max_leaves": 512, "max_record_bytes": 128},
    # leafwise_fast._MAX_WIRED_SEGMENTS (r10): the dense run bookkeeping
    # mandates >= 2*2^D + 2 tiles per level; past 1024 segments the
    # mandated movement stops being noise for any admitted row count
    "leafwise_layout": {"max_segments": 1024},
    # predict.stage_trees "auto" (r21): the packed node-word table when
    # every traversal field fits its limb width, legacy otherwise
    "predict_layout": {"preferred": "packed"},
    # predict.SHARDED_MIN_WORK: sharding a predict dispatch pays only
    # past ~32k row-outputs (per-shard blocks vs dispatch cost)
    "predict_sharded": {"min_work": 32768},
    # resilience.RetryPolicy.ch_max_ladder: chunk-cap degradation steps,
    # widest first, ending on the known-safe tunnel floor (STATUS r5)
    "chunk_cap": {"ladder": [8, 4, 2]},
}


class CalibrationTable:
    """A loaded table: overlay bookkeeping + the loud-fallback state."""

    def __init__(self, devices: Optional[dict] = None,
                 source: Optional[str] = None, explicit: bool = False,
                 fallback_reason: Optional[str] = None):
        self.devices = devices or {}
        self.source = source
        self.explicit = explicit
        self.fallback_reason = fallback_reason
        self._warned_kinds: set = set()

    def gate_values(self, gate: str, device_kind: Optional[str]) -> dict:
        """The effective key->value dict for one gate: code defaults
        overlaid with ``_default`` then the device entry."""
        out = copy.deepcopy(GATE_DEFAULTS.get(gate, {}))
        for key in (DEFAULT_DEVICE_KEY, device_kind):
            if key is None:
                continue
            entry = self.devices.get(key)
            if entry is None:
                if (key == device_kind and self.explicit
                        and key not in self._warned_kinds):
                    # loud once: the operator loaded a table expecting
                    # this device to be calibrated, and it is not
                    self._warned_kinds.add(key)
                    warnings.warn(
                        f"calibration table {self.source!r} has no entry "
                        f"for device_kind {key!r}; falling back to the "
                        "committed defaults", RuntimeWarning, stacklevel=3)
                continue
            out.update(copy.deepcopy(entry.get("gates", {}).get(gate, {})))
        return out

    def to_dict(self) -> dict:
        return {"calibration_schema": SCHEMA_VERSION,
                "devices": copy.deepcopy(self.devices)}


def load_table(path: Optional[str] = None,
               explicit: Optional[bool] = None) -> CalibrationTable:
    """Load a table file; NEVER raises.  A missing/corrupt/wrong-schema
    file returns an empty table carrying ``fallback_reason`` (the caller
    — ``current_table`` — warns once)."""
    src = path or GOLDEN_PATH
    if explicit is None:
        explicit = path is not None
    try:
        with open(src) as f:
            doc = json.load(f)
    except OSError as e:
        return CalibrationTable(source=src, explicit=explicit,
                                fallback_reason=f"unreadable: {e}")
    except ValueError as e:
        return CalibrationTable(source=src, explicit=explicit,
                                fallback_reason=f"corrupt JSON: {e}")
    if not isinstance(doc, dict) or not isinstance(doc.get("devices"), dict):
        return CalibrationTable(source=src, explicit=explicit,
                                fallback_reason="malformed: no devices map")
    if doc.get("calibration_schema") != SCHEMA_VERSION:
        return CalibrationTable(
            source=src, explicit=explicit,
            fallback_reason=(f"schema {doc.get('calibration_schema')!r} != "
                             f"{SCHEMA_VERSION}"))
    return CalibrationTable(devices=doc["devices"], source=src,
                            explicit=explicit)


def save_table(devices: dict, path: str) -> None:
    """Write a table file ``load_table`` round-trips exactly (sorted keys,
    trailing newline — the committed-goldens diff discipline)."""
    doc = {"calibration_schema": SCHEMA_VERSION, "devices": devices}
    with open(path, "w") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
        f.write("\n")


_current: Optional[CalibrationTable] = None
_warned_fallback = False


def current_table() -> CalibrationTable:
    """The process's table (memoized): ``DRYAD_POLICY_TABLE`` when set,
    else the committed golden.  Warns ONCE per process on a broken file
    (the loud-fallback satellite); resolution proceeds on defaults."""
    global _current, _warned_fallback
    if _current is None:
        env = os.environ.get(TABLE_ENV)
        _current = load_table(env) if env else load_table()
        if _current.fallback_reason and not _warned_fallback:
            _warned_fallback = True
            warnings.warn(
                f"calibration table {_current.source!r} unusable "
                f"({_current.fallback_reason}); every gate resolves on the "
                "committed defaults", RuntimeWarning, stacklevel=2)
    return _current


def reset_cache() -> None:
    """Forget the memoized table AND re-arm the loud-once fallback
    warning (test isolation; also lets an operator re-point
    ``DRYAD_POLICY_TABLE`` mid-process)."""
    global _current, _warned_fallback
    _current = None
    _warned_fallback = False
