"""``resolve(gate, shape_features, device_kind) -> arm`` — the ONE entry.

Each routed call site keeps its existing signature and calls in with its
shape features; the threshold CONSTANTS live in the table, the
COMPARISON SEMANTICS live here, verbatim from the pre-policy gate
bodies (cited per resolver).  Everything stays a pure function of
(params, feature/bin shape, shard count) — NEVER of the row count,
which under shard_map is the local shard and would let 1-shard and
N-shard runs choose different histogram programs (the CLAUDE.md
same-program rule).  Every resolution is recorded: ``decisions()`` is
the /stats block, ``dryad_policy_choice{gate,arm}`` the obs gauge
(no-ops with obs disabled — the registry owns that contract).
"""

from __future__ import annotations

from typing import Callable, Optional

from dryad_tpu.policy import table as _table
from dryad_tpu.policy.device import current_device_kind

_UNSET = object()

# gate -> (values, features) -> arm.  Comparison semantics only; every
# constant comes from the overlaid table values.
_RESOLVERS: dict = {}


def _resolver(name: str):
    def deco(fn: Callable) -> Callable:
        _RESOLVERS[name] = fn
        return fn
    return deco


@_resolver("partition")
def _partition(v: dict, f: dict) -> str:
    # levelwise.partition_prefers_reduce (r5): masked reduce while the
    # per-row sequential traffic stays under the calibrated row budget
    row_bytes = f["num_features"] * f["itemsize"]
    return "reduce" if row_bytes <= v["reduce_max_row_bytes"] else "gather"


@_resolver("hist_reduce")
def _hist_reduce(v: dict, f: dict) -> str:
    # config.hist_reduce_resolved (r16).  bin_bytes is the binned-matrix
    # itemsize (u8 below 257 bins, else u16) — structural, not calibrated
    bin_bytes = 1 if f["total_bins"] <= 256 else 2
    wide = (f["num_features"] * f["total_bins"] * bin_bytes
            >= v["wide_bytes"])
    return "feature" if (wide and f["n_shards"] > 1) else "fused"


@_resolver("hist_backend")
def _hist_backend(v: dict, f: dict) -> str:
    # histogram.resolve_backend "auto": Pallas on TPU-class platforms
    return "pallas" if f["platform"] in v["pallas_platforms"] else "xla"


@_resolver("deep_layout")
def _deep_layout(v: dict, f: dict) -> str:
    # levelwise.deep_layout_supported's CALIBRATED caps (the structural
    # exclusions — backend, packed-word widths, _REC_WB — stay at the
    # call site; a table can only narrow them, never widen past them)
    if f["num_leaves"] > v["max_leaves"]:
        return "legacy"
    if f["record_bytes"] > v["max_record_bytes"]:
        return "legacy"
    return "layout"


@_resolver("leafwise_layout")
def _leafwise_layout(v: dict, f: dict) -> str:
    # leafwise_fast's expansion-width cap: 2^D run slots vs the
    # calibrated mandatory-tile budget (_MAX_WIRED_SEGMENTS, r10)
    d = f["max_depth"]
    if not 0 < d or (1 << d) > v["max_segments"]:
        return "legacy"
    return "layout"


@_resolver("predict_layout")
def _predict_layout(v: dict, f: dict) -> str:
    # predict.stage_trees "auto" (r21): the preferred arm when every
    # traversal field fits its packed width, legacy otherwise
    return v["preferred"] if f["fits"] else "legacy"


@_resolver("predict_sharded")
def _predict_sharded(v: dict, f: dict) -> str:
    # predict.SHARDED_MIN_WORK: rows x num_outputs must carry real work
    return "sharded" if f["work"] >= v["min_work"] else "single"


@_resolver("chunk_cap")
def _chunk_cap(v: dict, f: dict) -> str:
    # resilience.RetryPolicy.ch_max_ladder — the decision record is the
    # ladder spelling; consumers take the tuple via gate_value()
    return "/".join(str(int(s)) for s in v["ladder"])


#: the gate catalog (stable order: README table, selftest sweep)
GATE_NAMES = tuple(_RESOLVERS)

#: newest decision per gate: {gate: {"arm", "detail", "count"}}
_DECISIONS: dict = {}
_LAST_ARM: dict = {}


def resolve(gate: str, shape_features: dict,
            device_kind=_UNSET, table=None,
            detail: Optional[str] = None) -> str:
    """Resolve one gate for one shape.  ``device_kind`` defaults to the
    process's device (``None`` explicitly = committed defaults);
    ``table`` defaults to the process table (``current_table``)."""
    if gate not in _RESOLVERS:
        raise KeyError(f"unknown policy gate {gate!r} "
                       f"(catalog: {', '.join(GATE_NAMES)})")
    tab = table if table is not None else _table.current_table()
    values = tab.gate_values(gate, _device_kind_for(tab, device_kind))
    arm = _RESOLVERS[gate](values, shape_features)
    _note(gate, arm, detail)
    return arm


def _device_kind_for(tab, device_kind):
    """Resolve the effective device key WITHOUT waking a device runtime
    when no table entry could change the answer: the committed table
    ships only ``_default``, so the common path (fleet control plane,
    RetryPolicy construction, CLI startup before the CPU-audit env is
    pinned) must never trigger the lazy jax probe.  Only a table that
    actually carries device-keyed entries pays the (memoized,
    best-effort) ``current_device_kind()`` call."""
    if device_kind is not _UNSET:
        return device_kind
    if not any(k != _table.DEFAULT_DEVICE_KEY for k in tab.devices):
        return None
    return current_device_kind()


def gate_value(gate: str, key: str, device_kind=_UNSET, table=None):
    """The raw calibrated value behind a gate (serve's threshold default,
    the resilience ladder) — same overlay as ``resolve``."""
    tab = table if table is not None else _table.current_table()
    device_kind = _device_kind_for(tab, device_kind)
    values = tab.gate_values(gate, device_kind)
    if key not in values:
        raise KeyError(f"gate {gate!r} has no value {key!r}")
    v = values[key]
    return tuple(v) if isinstance(v, list) else v


def _note(gate: str, arm: str, detail: Optional[str]) -> None:
    prev = _DECISIONS.get(gate)
    count = (prev["count"] + 1) if prev else 1
    _DECISIONS[gate] = {"arm": arm, "detail": detail, "count": count}
    try:
        from dryad_tpu.obs.registry import default_registry
    except Exception:  # noqa: BLE001 — decisions must survive a broken obs
        return
    reg = default_registry()
    if not reg.enabled:
        return
    fam = reg.gauge("dryad_policy_choice",
                    "Chosen dispatch arm per policy gate (1 = active)")
    last = _LAST_ARM.get(gate)
    if last is not None and last != arm:
        fam.labels(gate=gate, arm=last).set(0.0)
    _LAST_ARM[gate] = arm
    fam.labels(gate=gate, arm=arm).set(1.0)


def decisions() -> dict:
    """Snapshot of the newest decision per gate (the /stats block)."""
    return {g: dict(d) for g, d in _DECISIONS.items()}


def reset_decisions() -> None:
    """Forget recorded decisions (test isolation)."""
    _DECISIONS.clear()
    _LAST_ARM.clear()


def stats_block() -> dict:
    """The serve ``/stats`` "policy" block: where the table came from,
    whether it fell back, which device key resolutions use, and the
    newest decision per gate (incl. predict_layout's fallback reason —
    the r23 small-fix satellite: /stats now says WHY a model serves
    legacy)."""
    tab = _table.current_table()
    return {
        "device_kind": current_device_kind(),
        "table_source": tab.source,
        "table_explicit": tab.explicit,
        "fallback_reason": tab.fallback_reason,
        "device_keys": sorted(tab.devices),
        "decisions": decisions(),
    }
