"""dryad_tpu — a TPU-native gradient-boosted-decision-tree framework.

Public API mirrors the reference's ``dryad.train`` / ``dryad.predict``
surface (BASELINE.json:5).  The ``dryad`` package is an alias of this one.

    import dryad_tpu as dryad
    ds = dryad.Dataset(X, y)
    booster = dryad.train({"objective": "binary", "num_trees": 100}, ds)
    p = dryad.predict(booster, X_test)
"""

from __future__ import annotations

import contextlib
from typing import Any, Mapping, Optional

import numpy as np

from dryad_tpu.booster import Booster
from dryad_tpu.config import Params, make_params
from dryad_tpu.cv import cv
from dryad_tpu.dataset import Dataset

__version__ = "0.1.0"
__all__ = ["train", "predict", "cv", "Dataset", "Booster", "Params",
           "__version__"]


def train(
    params: "Params | Mapping[str, Any] | None" = None,
    train_set: Optional[Dataset] = None,
    valid_sets: Optional[list[Dataset]] = None,
    *,
    valid_names: Optional[list[str]] = None,
    backend: str = "auto",
    init_booster: Optional[Booster] = None,
    init_model: Optional[Booster] = None,
    callback=None,
    callbacks=None,
    checkpoint_dir: Optional[str] = None,
    checkpoint_every: int = 10,
    resume: bool = False,
    profile_dir: Optional[str] = None,
    chunk_hook=None,
    chunk_policy=None,
    mesh=None,
    **kw: Any,
) -> Booster:
    """Train a booster.  backend: 'auto' (TPU if available), 'tpu', 'cpu'.

    ``checkpoint_dir`` enables periodic atomic checkpoints every
    ``checkpoint_every`` iterations; with ``resume=True`` training continues
    from the newest checkpoint in that directory (reproducing the
    uninterrupted run bit for bit — see dryad_tpu/checkpoint.py).
    ``callbacks`` is a list of ``fn(iteration, info)`` (see
    dryad_tpu/callbacks.py); ``callback`` remains as a single-function alias.
    ``profile_dir`` captures a jax.profiler trace of the whole training run
    (open with XProf/Perfetto — SURVEY.md §5 tracing).
    ``chunk_hook``/``chunk_policy`` are the resilience subsystem's loop
    observation + adaptive chunk-cap surfaces (see engine/train.py and
    dryad_tpu/resilience/ — most callers want ``supervise_train`` instead of
    passing these directly).  ``mesh`` forwards an explicit device mesh to
    the device trainer (rows sharded, histograms psum'd; see
    ``distributed.train_distributed`` for the usual front door).

    ``init_model`` (r19, continual boosting) is the warm-start APPEND
    surface: resume boosting from a LOADED served model's carried scores
    on fresh rows — ``num_trees`` counts the NEW trees to append (0 is a
    valid no-op that returns a predict-identical copy), and the fresh
    rows must be binned in the model's frozen bin space
    (``Dataset(X, y, mapper=model.mapper)``).  It rides the checkpoint-
    resume machinery (carried scores rebuilt bitwise by tree replay), so
    a same-shape append reuses the already-compiled programs — the
    num_trees total is erased from the jit key.  ``init_booster`` remains
    the low-level TOTAL-count resume surface the checkpoint path uses;
    pass one or the other.  Apply ``Booster.refit``/leaf renewal BEFORE
    the append when the old trees' leaf values should be re-weighted
    toward the fresh rows.
    """
    p = make_params(params, **kw)
    if train_set is None:
        raise ValueError("train_set is required")
    if init_model is not None:
        if init_booster is not None:
            raise ValueError("pass init_model (append semantics) or "
                             "init_booster (total-count resume), not both")
        if resume:
            raise ValueError(
                "init_model with resume=True is ambiguous (the checkpoint "
                "would be shadowed by the warm start) — warm-started runs "
                "that need crash recovery go through "
                "resilience.supervise_train, which owns that hand-off")
        _check_append_compatible(p, train_set, init_model)
        p = p.replace(num_trees=p.num_trees + init_model.num_iterations)
        init_booster = init_model
    elif p.num_trees == 0:
        raise ValueError("num_trees=0 is only meaningful with init_model "
                         "(a 0-tree warm-start append)")
    if (any(p.monotone_constraints)
            and getattr(train_set.mapper, "bundled_mask", None) is not None):
        # EFB reorders/stacks columns, so positional per-feature constraints
        # would land on the wrong (and non-ordinal) columns
        raise ValueError(
            "monotone_constraints are positional over the original features "
            "and are incompatible with feature bundling — rebuild the "
            "Dataset with bundle=False")
    # every valid set is evaluated and logged per iteration; early stopping
    # watches the FIRST one (LightGBM semantics)
    valid = list(valid_sets) if valid_sets else None
    if valid_names is not None:
        if valid is None or len(valid_names) != len(valid):
            raise ValueError("valid_names must match valid_sets in length")
        valid = list(zip(valid_names, valid))
    if mesh is not None:
        if backend == "cpu":
            raise ValueError(
                "mesh requires the device trainer — backend='cpu' with an "
                "explicit mesh is contradictory (drop the mesh to run the "
                "CPU reference path)")
        backend = "tpu"           # an explicit mesh means the device path
    elif backend == "auto":
        backend = "tpu" if (_accelerator_present() and _engine_present()) else "cpu"

    checkpointer = None
    if checkpoint_dir is not None:
        from dryad_tpu.checkpoint import Checkpointer

        checkpointer = Checkpointer(checkpoint_dir, every=checkpoint_every)
        if resume and init_booster is None:
            latest = checkpointer.latest()
            if latest is not None:
                init_booster = latest[0]
    elif resume:
        raise ValueError("resume=True requires checkpoint_dir")

    from dryad_tpu.callbacks import combine

    cb = combine(([callback] if callback else []) + list(callbacks or []))

    if backend not in ("cpu", "tpu"):
        raise ValueError(f"unknown backend {backend!r}")

    if profile_dir is not None:
        import jax

        trace_ctx = jax.profiler.trace(profile_dir)
    else:
        trace_ctx = contextlib.nullcontext()

    with trace_ctx:
        if backend == "cpu":
            from dryad_tpu.cpu.trainer import train_cpu

            booster = train_cpu(p, train_set, valid,
                                init_booster=init_booster, callback=cb,
                                checkpointer=checkpointer,
                                chunk_hook=chunk_hook)
        else:
            from dryad_tpu.engine.train import train_device

            booster = train_device(p, train_set, valid,
                                   init_booster=init_booster, callback=cb,
                                   checkpointer=checkpointer, mesh=mesh,
                                   chunk_hook=chunk_hook,
                                   chunk_policy=chunk_policy)
    _attach_profile(booster, train_set, valid)
    return booster


def _check_append_compatible(p: Params, train_set: Dataset,
                             model: Booster) -> None:
    """A warm-start append is only well-defined when the fresh rows live
    in the model's frozen bin space and the tree geometry matches — the
    carried-score replay walks the OLD trees over the NEW binned matrix,
    so a re-sketched mapper would silently misroute every row."""
    m_new, m_old = train_set.mapper, model.mapper
    same = m_new is m_old
    if not same:
        try:
            same = m_new.to_json_dict() == m_old.to_json_dict()
        except AttributeError:
            same = False
    if not same:
        raise ValueError(
            "init_model append: the training set was binned with a "
            "different mapper than the model's frozen bin space — build "
            "it as Dataset(X, y, mapper=model.mapper) so the carried-"
            "score replay and the new trees share one bin vocabulary")
    if p.max_nodes != model.params.max_nodes:
        raise ValueError(
            f"init_model append: params imply max_nodes={p.max_nodes} but "
            f"the model was grown with {model.params.max_nodes} — derive "
            "the append params from model.params (e.g. "
            "model.params.replace(num_trees=K)) so tree arrays stack")


def _attach_profile(booster, train_set, valid_sets) -> None:
    """Train-completion hook: embed the drift baseline (data/profile.py)
    in the returned model.  Host-side and bounded (stride subsample +
    one CPU predict); ``DRYAD_PROFILE=0`` skips it (the tier-1 suite
    pins it off in conftest — hundreds of tiny trains need no baseline).
    Best-effort: a profile failure warns, it never fails a finished
    training run at the finish line."""
    import os

    if os.environ.get("DRYAD_PROFILE", "1") == "0":
        return
    try:
        from dryad_tpu.data.profile import build_reference_profile

        booster.profile = build_reference_profile(booster, train_set,
                                                  valid_sets)
    except Exception as e:  # noqa: BLE001 — telemetry must not kill a train
        import warnings

        warnings.warn(f"reference-profile capture failed ({e!r}); "
                      "the model ships without a drift baseline")


def predict(
    booster: Booster,
    X: np.ndarray,
    *,
    raw_score: bool = False,
    backend: str = "cpu",
    num_iteration: Optional[int] = None,
    pred_leaf: bool = False,
    pred_contrib: bool = False,
) -> np.ndarray:
    """Predict on raw features through the booster's frozen bin mapper."""
    return booster.predict(
        X, raw_score=raw_score, backend=backend, num_iteration=num_iteration,
        pred_leaf=pred_leaf, pred_contrib=pred_contrib
    )


def _accelerator_present() -> bool:
    try:
        import jax

        return any(d.platform != "cpu" for d in jax.devices())
    except Exception:
        return False


def _engine_present() -> bool:
    import importlib.util

    return importlib.util.find_spec("dryad_tpu.engine") is not None
