"""Leaf-ordered permutation kernel (VERDICT r4 #2 / CLAUDE.md open item
#1): maintain the per-tree record table GROUPED BY LEAF incrementally,
deleting the two dominant deep-level data-movement costs at 10M rows —
the full-N packed sort (~75 ms/level) and the half-N per-access record
gather (~110 ms/level).

Layout invariant.  Records live in a TILE-ALIGNED leaf-ordered buffer:
segment k (one leaf slot) owns ``lt[k] = max(ceil(cnt[k]/T), 1)``
consecutive row tiles; rows past cnt[k] in its range are ZERO (sentinel)
rows — zero weight, bin 0, contributing nothing to any histogram (the
same sentinel algebra pallas_hist's plans use).

Per level, every segment splits into (left, right) children (pass-through
segments keep all rows "left").  A row's destination is a pure function
of (source tile, side, stable rank within that (tile, side)), so the
movement decomposes into per-tile work with NO sort and NO row scatter:

* **stable two-way compaction on the MXU**: records are uint8 lanes
  (bytes are exact in bf16; the 0/1 one-hot times byte products
  accumulate exactly in f32), ``P_side (T, T) @ rec (T, WB)`` compacts
  one side's rows to the front in stable order and zero-fills the tail —
  and zeros ARE the sentinel encoding;
* **two fixed-size windowed writes per tile** at row offsets
  ``dst_side[i] = T·new_base(child) + (side-rows of earlier tiles of the
  same segment)``.

Write-ordering safety.  The new layout places ALL left children (in
source-segment order), one slack tile, ALL right children (same order),
one slack tile.  Pallas grid steps execute sequentially, and within a
region each write begins exactly where the previous real rows ended, so
a write's zero tail is either overwritten by a LATER step of the same
region, lands in the segment's own pad slots, or falls into slack —
never on rows written earlier.  (An interleaved [L_k][R_k] layout breaks
this: an L tail can cross into R territory that earlier steps already
wrote — found in design review, hence the region split.)

The histogram pass then reads the selected children's segments as
CONTIGUOUS tile runs (tile-granular gathers move ~20 KB per access —
bandwidth-bound, not access-bound), and no per-level sort exists at all.

Self-contained and bitwise-tested in interpret mode
(tests/test_leafperm.py); ``scripts/exp_r5_perm.py`` measures it
on-device against the sort+gather pair it replaces (51.4 vs
164.1 ms/level at 10M).  WIRED into ``levelwise.py``'s deep phase in r6
and EVERYWHERE in r10: both level-synchronous growers
(``levelwise.py`` — shallow AND deep levels — and the batched leaf-wise
expansion in ``leafwise_fast.py``) carry (rec, tile_run, run_slot)
through their level fori state.  The layout is now anchored at the ROOT
(``natural_root_layout``: the natural-order record buffer IS a valid
one-segment layout, out-of-bag rows encoded as sentinels), so the old
shallow->deep handoff sort+gather per tree (``initial_layout``) is gone
from the growers too — it remains as the probe/oracle constructor for
mid-tree layouts (bench, tests).  ``scripts/smoke_tpu.py --gate`` pins
wired-vs-legacy tree equality on device for both growers.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from dryad_tpu.engine import jax_compat

_TILE_ROWS = 512     # must match pallas_hist._TILE_ROWS (shared layouts)
# Destination-row granule: Mosaic can only slice an HBM uint8 memref at
# sublane-tile multiples ("failed to prove divisible by the tiling" for
# arbitrary offsets — measured on v5e), so every windowed write starts on
# a 32-row boundary.  Each source tile's per-side contribution therefore
# OCCUPIES roundup32(rows) slots; the ≤31-row gaps are zero sentinels.
# Overhead ≤ 2*32/512 = 12.5% extra rows per level, non-compounding (the
# next level's compaction drops sentinels and re-pads afresh).
_ALIGN = 32


def _interpret(platform: str | None = None) -> bool:
    return (platform or jax.default_backend()) == "cpu"


def aligned_layout(counts: jnp.ndarray, T: int = _TILE_ROWS):
    """(lt, base): per-segment tile counts (>= 1 each) and first-tile
    indices for exact row ``counts``; ``base[-1]`` = total tiles."""
    cnt = counts.astype(jnp.int32)
    lt = jnp.maximum((cnt + (T - 1)) // T, 1)
    base = jnp.concatenate([jnp.zeros((1,), jnp.int32),
                            jnp.cumsum(lt).astype(jnp.int32)])
    return lt, base


def _perm_kernel(dstl_ref, dstr_ref, pos_ref, rec_ref, init_ref, out_ref,
                 outl_vmem, outr_vmem, seml, semr, *, T: int, WB: int):
    """One source tile: two stable compactions + two windowed writes.

    ``pos`` (1, 2, T) int32: row j's in-tile output rank on its side
    (the other side's plane holds T = "no row"), so each one-hot
    ``iota_o == pos[side]`` compacts one side to the front and zero-fills
    the rest."""
    i = pl.program_id(0)
    # Mosaic has no direct u8->bf16 cast; route through i32/f32 (byte
    # values <= 255 are exact at every step)
    rec = (rec_ref[0].astype(jnp.int32).astype(jnp.float32)
           .astype(jnp.bfloat16))                      # (T, WB)
    iota_o = jax.lax.broadcasted_iota(jnp.int32, (T, T), 0)
    PL = (iota_o == pos_ref[0, 0][None, :]).astype(jnp.bfloat16)
    PR = (iota_o == pos_ref[0, 1][None, :]).astype(jnp.bfloat16)
    outl_vmem[...] = jax.lax.dot_general(
        PL, rec, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32).astype(jnp.int32).astype(
            jnp.uint8).reshape(T // _ALIGN, _ALIGN, WB)
    outr_vmem[...] = jax.lax.dot_general(
        PR, rec, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32).astype(jnp.int32).astype(
            jnp.uint8).reshape(T // _ALIGN, _ALIGN, WB)
    # the out ref is viewed in _ALIGN-row GRANULES (g, _ALIGN, WB) and the
    # dst scalars arrive pre-divided by _ALIGN: Mosaic cannot PROVE a raw
    # runtime row offset divisible by its tiling, but a leading-granule
    # index is divisible by construction
    cl = pltpu.make_async_copy(
        outl_vmem, out_ref.at[pl.ds(dstl_ref[i], T // _ALIGN)], seml)
    cr = pltpu.make_async_copy(
        outr_vmem, out_ref.at[pl.ds(dstr_ref[i], T // _ALIGN)], semr)
    cl.start()
    cr.start()
    # waits keep the writes ordered with the NEXT step's (they overlap a
    # predecessor's zero tail by design) and the scratch reusable
    cl.wait()
    cr.wait()


@functools.partial(jax.jit, static_argnames=("n_out_tiles", "platform",
                                             "axis_name"))
def permute_records(rec: jnp.ndarray, pos: jnp.ndarray, dstl: jnp.ndarray,
                    dstr: jnp.ndarray, n_out_tiles: int,
                    platform: str | None = None,
                    axis_name: str | None = None) -> jnp.ndarray:
    """Apply one level's movement.

    rec (n_tiles*T, WB) uint8; pos (n_tiles, 2, T) int32 in-tile ranks
    (T = no row, incl. every sentinel row); dstl/dstr (n_tiles,) int32
    destination ROW offsets.  ``n_out_tiles`` MUST include the two slack
    tiles ``level_moves`` accounts for.  Returns the new (n_out_tiles*T,
    WB) uint8 leaf-ordered buffer.

    ``axis_name`` marks the output device-varying when tracing under
    ``shard_map`` (each shard permutes its own local layout; no
    collective here — the histogram psum stays the growers' only one).

    The output is ALIASED to a zero buffer: rows no DMA write covers
    (inner pad rows of multi-tile segments with uneven source fill,
    untouched slack) must be zero sentinels — an uninitialized ANY-space
    buffer holds stale HBM bytes on real hardware (interpret mode
    zero-fills and masks this; caught in review)."""
    n_rows, WB = rec.shape
    T = _TILE_ROWS
    n_tiles = n_rows // T
    # memory-safety clamp (tile_plan's "safety squeeze" precedent): a
    # violated caller bound must misplace rows DETERMINISTICALLY inside
    # the buffer, never DMA past it (granule writes cover T rows from dst)
    dst_cap = jnp.int32((n_out_tiles - 1) * T)
    dstl = jnp.minimum(dstl, dst_cap)
    dstr = jnp.minimum(dstr, dst_cap)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(n_tiles,),
        in_specs=[
            pl.BlockSpec((1, 2, T), lambda i, dl, dr: (i, 0, 0)),
            pl.BlockSpec((1, T, WB), lambda i, dl, dr: (i, 0, 0)),
            pl.BlockSpec(memory_space=jax_compat.tpu_any_space()),
        ],
        out_specs=pl.BlockSpec(memory_space=jax_compat.tpu_any_space()),
        scratch_shapes=[
            pltpu.VMEM((T // _ALIGN, _ALIGN, WB), jnp.uint8),
            pltpu.VMEM((T // _ALIGN, _ALIGN, WB), jnp.uint8),
            pltpu.SemaphoreType.DMA,
            pltpu.SemaphoreType.DMA,
        ],
    )
    G = n_out_tiles * T // _ALIGN
    zeros = jnp.zeros((G, _ALIGN, WB), jnp.uint8)
    if axis_name is not None:
        # the aliased zero init must carry the same varying-manual-axes
        # as the (shard-local) output it becomes
        zeros = jax_compat.pcast_varying(zeros, axis_name)
    out = pl.pallas_call(
        functools.partial(_perm_kernel, T=T, WB=WB),
        grid_spec=grid_spec,
        out_shape=jax_compat.shape_dtype_struct((G, _ALIGN, WB),
                                                jnp.uint8, axis_name),
        # operand index counts the 2 prefetched scalars first: 2=pos,
        # 3=rec, 4=zeros -> alias the zero buffer to the output
        input_output_aliases={4: 0},
        interpret=_interpret(platform),
    )(dstl // _ALIGN, dstr // _ALIGN, pos.astype(jnp.int32),
      rec.reshape(n_tiles, T, WB), zeros)
    return out.reshape(n_out_tiles * T, WB)


def level_moves(tile_slot: jnp.ndarray, side: jnp.ndarray,
                n_parents: int, T: int = _TILE_ROWS):
    """XLA bookkeeping for one level — O(N) elementwise + O(n_tiles)
    prefix work, no sort.

    tile_slot (n_tiles,) int32: source segment per tile (layout
    invariant).  side (n_tiles*T,) int32: 0 = left child, 1 = right
    child, anything else = sentinel (vanishes).  ``n_parents`` (static):
    parent segment count P; pass-through parents route all rows left —
    their right segment still gets the mandatory 1-tile allocation but
    receives only zeros.

    Returns (pos, dstl, dstr, base_l, base_r, n_out_tiles): the new
    layout is [left children in parent order | slack | right children |
    slack]; ``base_l``/``base_r`` are (P+1,) FIRST-TILE indices of each
    parent's left/right child segment (right already offset past the
    left region), from which callers derive the next level's tile→segment
    map.  Within a segment, each source tile's contribution sits at an
    _ALIGN-rounded offset (interior runs of < _ALIGN zero sentinels — see
    the _ALIGN note), so real rows are NOT a contiguous prefix.
    ``n_out_tiles`` is a traced scalar — callers pick the static bound
    (see tiles_bound)."""
    n_tiles = tile_slot.shape[0]
    A = _ALIGN
    s2 = side.reshape(n_tiles, T)
    isl = (s2 == 0).astype(jnp.int32)
    isr = (s2 == 1).astype(jnp.int32)
    rkl = jnp.cumsum(isl, axis=1) - isl                # stable in-tile ranks
    rkr = jnp.cumsum(isr, axis=1) - isr
    # each tile's contribution OCCUPIES an _ALIGN-rounded slot run so its
    # write start stays Mosaic-sliceable (see _ALIGN note)
    nl_t = -(-isl.sum(axis=1) // A) * A
    nr_t = -(-isr.sum(axis=1) // A) * A
    cl = jnp.cumsum(nl_t) - nl_t                       # global tile prefixes
    cr = jnp.cumsum(nr_t) - nr_t
    first = jnp.concatenate([jnp.ones((1,), bool),
                             tile_slot[1:] != tile_slot[:-1]])
    # per-tile prefix WITHIN its segment = global prefix minus the
    # segment's first tile's global prefix (max-scan trick: cl is
    # non-decreasing, so carrying the last first-tile value is a max scan)
    segl = jax.lax.associative_scan(jnp.maximum, jnp.where(first, cl, -1))
    segr = jax.lax.associative_scan(jnp.maximum, jnp.where(first, cr, -1))
    prefl = cl - segl
    prefr = cr - segr

    # segment capacities cover the PADDED contributions (per-segment sum
    # of rounded per-tile sizes = last prefix + last size)
    lastl = jnp.where(
        jnp.concatenate([tile_slot[1:] != tile_slot[:-1],
                         jnp.ones((1,), bool)]), prefl + nl_t, -1)
    lastr = jnp.where(
        jnp.concatenate([tile_slot[1:] != tile_slot[:-1],
                         jnp.ones((1,), bool)]), prefr + nr_t, -1)
    P = int(n_parents)
    pad_l = jnp.zeros((P,), jnp.int32).at[tile_slot].max(lastl)
    pad_r = jnp.zeros((P,), jnp.int32).at[tile_slot].max(lastr)
    lt_l, base_l = aligned_layout(pad_l, T)            # left region
    lt_r, base_r = aligned_layout(pad_r, T)            # right region
    left_tiles = base_l[-1]
    # region layout: [left | 1 slack | right | 1 slack]
    off_r = left_tiles + 1
    dstl = (base_l[tile_slot] * T + prefl).astype(jnp.int32)
    dstr = ((off_r + base_r[tile_slot]) * T + prefr).astype(jnp.int32)
    n_out_tiles = off_r + base_r[-1] + 1

    pos = jnp.stack([jnp.where(s2 == 0, rkl, T),
                     jnp.where(s2 == 1, rkr, T)], axis=1).astype(jnp.int32)
    return pos, dstl, dstr, base_l, base_r + off_r, n_out_tiles


def tiles_bound(n_rows: int, n_parents: int, T: int = _TILE_ROWS) -> int:
    """Static bound for ``n_out_tiles``: every row lands somewhere, each
    source tile adds up to 2·(_ALIGN-1) interior pad rows (alignment
    rounding per side), plus per-segment tile-alignment waste, mandatory
    empty-segment tiles and the two slack tiles.  The padding does NOT
    compound across levels (pads drop at the next compaction): the tile
    count converges to ≲ rows/T · 1/(1 − 2·_ALIGN/T) ≈ 1.14x."""
    n_src_tiles = n_rows // T
    pad_rows = 2 * _ALIGN * n_src_tiles
    return (n_rows + pad_rows) // T + 2 * n_parents + 4


# ---------------------------------------------------------------------------
# layout records + histograms straight from the layout
# ---------------------------------------------------------------------------
# Layout record byte format (WB = 128):
#   [ g f32 (4) | h f32 (4) | valid u8 (1) | X bins u8/u16 (F·itemsize) ]
# padded with zeros to WB.  The valid flag distinguishes real rows from
# sentinels without assuming anything about g/h values; zero rows decode
# to valid=0, g=h=0, bin 0 — inert in every consumer by construction.
_REC_WB = 128


def make_layout_records(Xb: jnp.ndarray, g: jnp.ndarray, h: jnp.ndarray,
                        valid: jnp.ndarray | None = None) -> jnp.ndarray:
    """(N, _REC_WB) uint8 layout records in natural row order — the
    root-segment initial layout (pad to tile multiples before use).

    ``valid`` (N,) bool marks rows that participate (the bag mask for a
    root-anchored layout): rows outside it get valid flag 0 and are
    DROPPED by the first level's move (the side derivation sends
    flag-0 rows to the sentinel plane), so out-of-bag rows never ride a
    permute past level 0."""
    N, F = Xb.shape
    nbytes = F * Xb.dtype.itemsize
    assert 9 + nbytes <= _REC_WB, "feature bytes exceed the record"
    gb = jax.lax.bitcast_convert_type(
        g.astype(jnp.float32), jnp.uint8).reshape(N, 4)
    hb = jax.lax.bitcast_convert_type(
        h.astype(jnp.float32), jnp.uint8).reshape(N, 4)
    xb = (jax.lax.bitcast_convert_type(Xb, jnp.uint8).reshape(N, nbytes)
          if Xb.dtype != jnp.uint8 else Xb)
    flag = (jnp.ones((N, 1), jnp.uint8) if valid is None
            else valid.astype(jnp.uint8).reshape(N, 1))
    rec = jnp.concatenate([gb, hb, flag, xb], axis=1)
    return jnp.pad(rec, ((0, 0), (0, _REC_WB - rec.shape[1])))


def unpack_layout_records(rec: jnp.ndarray, num_features: int,
                          bin_dtype) -> tuple:
    """(g, h, valid, X_rows) views of a layout record buffer."""
    n = rec.shape[0]
    g = jax.lax.bitcast_convert_type(
        rec[:, 0:4].reshape(n, 1, 4), jnp.float32)[:, 0]
    h = jax.lax.bitcast_convert_type(
        rec[:, 4:8].reshape(n, 1, 4), jnp.float32)[:, 0]
    valid = rec[:, 8] == 1
    itemsize = jnp.dtype(bin_dtype).itemsize
    xb = rec[:, 9:9 + num_features * itemsize]
    if itemsize != 1:
        xb = jax.lax.bitcast_convert_type(
            xb.reshape(n, num_features, itemsize), bin_dtype)
    return g, h, valid, xb


def hist_from_layout(rec: jnp.ndarray, seg_first: jnp.ndarray,
                     seg_ntiles: jnp.ndarray, num_cols: int,
                     total_bins: int, num_features: int, bin_dtype,
                     n_sel_tiles: int, *,
                     axis_name: str | None = None,
                     platform: str | None = None,
                     hist_reduce: str = "fused") -> jnp.ndarray:
    """(P, 3, F, B) histograms for P selected segments of a leaf-ordered
    layout — NO sort, NO per-row gather: each segment is a CONTIGUOUS
    tile run, so the only data movement is a tile-granular gather
    (~_TILE_ROWS·_REC_WB = 64 KB per access — bandwidth-bound, unlike
    the per-access-bound row gather it replaces).

    seg_first/seg_ntiles (P,) int32: each selected segment's first tile
    and tile count in ``rec``.  ``n_sel_tiles`` MUST bound
    ``sum(max(seg_ntiles, 1))`` — every selection reserves at least one
    plan slot (an empty selection's mandatory slot zero-initializes its
    output block, tile_plan contract), so a bound on the raw tile sum
    alone would shift later segments past the end and silently truncate
    their histograms (caught in review; test-pinned).

    Parity note (test_hist_from_layout_bitwise_vs_plan): on a PAD-FREE
    layout (contiguous per-segment rows — the per-tree initial layout)
    this is BITWISE equal to the tile-plan path.  Post-permute layouts
    carry _ALIGN interior sentinels that shift rows across tile
    boundaries, regrouping the kernel's per-tile partial sums — an
    ulp-class difference (the chunked-vs-dispatch tolerance class in
    CLAUDE.md), so a wired grower must use ONE histogram path per config,
    never mix them mid-tree."""
    from dryad_tpu.engine import pallas_hist

    T = _TILE_ROWS
    P = int(num_cols)
    n_tiles_in = rec.shape[0] // T
    # dense plan: positions of each segment's tiles in the packed prefix
    base = jnp.concatenate([jnp.zeros((1,), jnp.int32),
                            jnp.cumsum(jnp.maximum(seg_ntiles, 1))
                            .astype(jnp.int32)])
    idx = jnp.arange(n_sel_tiles, dtype=jnp.int32)
    tile_leaf = jnp.searchsorted(base[1:], idx, side="right").astype(
        jnp.int32)
    lc = jnp.minimum(tile_leaf, P - 1)
    off = idx - base[lc]
    live = (tile_leaf < P) & (off < seg_ntiles[lc])
    src = jnp.where(live, seg_first[lc] + off, 0)
    src = jnp.clip(src, 0, n_tiles_in - 1)
    # ONE tile-granular gather of the selected runs
    sel_rec = rec.reshape(n_tiles_in, T * _REC_WB)[src].reshape(
        n_sel_tiles * T, _REC_WB)
    g, h, valid, X_rows = unpack_layout_records(sel_rec, num_features,
                                                bin_dtype)
    valid &= jnp.repeat(live, T)
    Xt = pallas_hist._tiles_from_rows(X_rows, n_sel_tiles, T, total_bins)
    Wt = pallas_hist._pack_weights(g.reshape(n_sel_tiles, T),
                                   h.reshape(n_sel_tiles, T),
                                   valid.reshape(n_sel_tiles, T))
    tile_first = jnp.concatenate([
        jnp.ones((1,), jnp.int32),
        (lc[1:] != lc[:-1]).astype(jnp.int32)])
    tile_skip = 1 - jnp.any(valid.reshape(n_sel_tiles, T),
                            axis=1).astype(jnp.int32)
    hist = pallas_hist._hist_tiles(
        Xt, Wt, lc, tile_first, tile_skip, num_cols=P,
        total_bins=int(total_bins), num_features=int(num_features),
        axis_name=axis_name, platform=platform)
    if axis_name is not None:
        # the same per-arm histogram reduction every builder tail issues:
        # the fused grad/hess/count psum (default) or the feature-arm
        # reduce-scatter (distributed.reduce_hist)
        from dryad_tpu.engine.distributed import reduce_hist

        hist = reduce_hist(hist, axis_name, hist_reduce)
    return hist


# ---------------------------------------------------------------------------
# levelwise deep-phase wiring (slot-keyed run bookkeeping)
# ---------------------------------------------------------------------------
# The wired grower (levelwise.py deep phase) carries the layout through its
# level fori state as (rec, tile_run, run_slot):
#
# * ``tile_run`` (n_buf_tiles,) int32 — per-tile RUN index, ascending in
#   layout order (the write-ordering safety of permute_records requires
#   destination order == source processing order, which holds exactly when
#   run ids ascend with tile position — the oracle's implicit invariant).
# * ``run_slot`` (L,) int32 — run index -> grower leaf-slot id (sentinel L
#   for unused run indices).  Runs <-> live leaf slots stay bijective:
#   every level keeps all left/pass-through segments as their old runs
#   (left children keep the parent's slot — the levelwise convention) and
#   appends one new run per executed split (the right child's slot), so
#   the run count is 1 + total splits <= L and the (L,)-dense bookkeeping
#   never overflows.  Empty segments level_moves mandates (non-splitting
#   parents' right segments, unused run indices) are ABSORBED into the
#   preceding run: their tiles hold only zero sentinels, which contribute
#   nothing to any move or histogram (the oracle's slack-absorption rule).


def wired_tiles_bound(n_row_tiles: int, num_slots: int) -> int:
    """Static FIXED-POINT tile bound for the carried layout buffer.

    One level maps an n_buf-tile layout holding <= n_row_tiles*T real rows
    to <= (rows + 2*_ALIGN*n_buf)/T + 2*L + 2 tiles (each source tile adds
    < _ALIGN pad per side; every one of the L dense run indices gets a
    mandatory tile per region plus the two slack tiles).  Solving
    out <= n_buf for the stationary buffer gives n_buf >= 8/7 * (rows/T +
    2L + 2) at _ALIGN/T = 1/16 — pads do NOT compound (the next level's
    compaction drops them), so the same buffer carries every level."""
    base = n_row_tiles + 2 * num_slots + 2
    assert 2 * _ALIGN * 8 <= _TILE_ROWS, "fixed point needs 2A/T <= 1/8"
    return -(-8 * base // 7) + 2


def wired_sel_tiles_bound(n_row_tiles: int, n_buf_tiles: int,
                          num_cols: int, half: bool) -> int:
    """Static bound on ``hist_from_layout``'s ``n_sel_tiles`` for a
    smaller-children selection out of a ``n_buf_tiles`` layout — the ONE
    definition shared by the wired grower and the bench probe (an
    insufficient bound silently truncates later segments' histograms, so
    the two callers must never drift).  ``half=True`` when the caller can
    PROVE the selection covers at most half the real rows (single device
    below 2^24 rows, where the fp32 counts backing the smaller-child
    choice are exact); the n_buf/16 term covers the _ALIGN interior
    sentinels, 2*num_cols the per-segment ceil and the empty selections'
    mandatory plan slots."""
    if half:
        return n_row_tiles // 2 + n_buf_tiles // 16 + 2 * num_cols + 8
    return n_buf_tiles + 2 * num_cols


def natural_root_layout(rec_nat: jnp.ndarray, num_runs: int,
                        n_buf_tiles: int, first_slot: int = 0,
                        sentinel: int | None = None,
                        axis_name: str | None = None):
    """Root-anchored layout (r10): the natural-order record buffer IS a
    valid layout with ONE segment — run 0 owns every tile, rows the
    caller marked invalid (``make_layout_records``' ``valid`` arg, i.e.
    out-of-bag) are dropped by level 0's move.  NO sort, NO gather: this
    replaces the shallow->deep ``initial_layout`` handoff entirely when
    the layout is live from level 0.

    Returns (rec_lay, tile_run, run_slot): records padded to
    ``n_buf_tiles`` tiles, all tiles in run 0, and a (num_runs,) dense
    run->slot table holding ``first_slot`` at run 0 and ``sentinel``
    (default ``num_runs``) elsewhere.  Under ``shard_map`` pass
    ``axis_name`` so the carried bookkeeping state enters the level loop
    device-varying like the outputs that replace it (same vma rule as
    permute_records' aliased zero init)."""
    N = rec_nat.shape[0]
    T = _TILE_ROWS
    assert N <= n_buf_tiles * T, (N, n_buf_tiles)
    rec_lay = jnp.pad(rec_nat, ((0, n_buf_tiles * T - N), (0, 0)))
    sent = num_runs if sentinel is None else sentinel
    tile_run = jnp.zeros((n_buf_tiles,), jnp.int32)
    run_slot = jnp.full((num_runs,), sent, jnp.int32).at[0].set(first_slot)
    if axis_name is not None:
        tile_run = jax_compat.pcast_varying(tile_run, axis_name)
        run_slot = jax_compat.pcast_varying(run_slot, axis_name)
    return rec_lay, tile_run, run_slot


def initial_layout(rec_nat: jnp.ndarray, sel: jnp.ndarray,
                   live: jnp.ndarray, num_slots: int, n_buf_tiles: int):
    """Mid-tree layout constructor: group natural-order layout records by
    leaf slot into the tile-aligned leaf-ordered layout.  Was the r6
    growers' shallow->deep handoff; since the r10 root anchoring
    (``natural_root_layout``) the growers never call it — it remains the
    bench probe's and the oracle tests' way to build a layout at an
    arbitrary tree depth (one ``tile_plan`` stable sort + one full-N
    record gather — exactly the pair the wired growers no longer pay).

    ``sel`` (N,) int32 in [0, L]; L drops the row (out-of-bag rows never
    enter the layout — their records would only ride dead weight through
    every level's move).  ``live`` (L,) bool marks slots that exist at the
    handoff depth; dead slots' mandatory plan tiles are absorbed into the
    preceding run.  Returns (rec_lay, tile_run, run_slot).

    Per-slot row order is the plan paths' STABLE row-id order (tile_plan's
    stable sort), and permute_records preserves source order within
    (segment, side) — so every later level's per-slot order matches what
    tile_plan_aligned would produce for the same selection, by
    construction (the integration contract test_leafperm pins)."""
    from dryad_tpu.engine.pallas_hist import tile_plan

    N = rec_nat.shape[0]
    L = int(num_slots)
    T = _TILE_ROWS
    buf, tile_leaf, _ = tile_plan(sel, N, L, T)
    nh = buf.shape[0] // T
    assert nh <= n_buf_tiles, (nh, n_buf_tiles)
    rec_lay = jnp.where((buf < N)[:, None],
                        rec_nat[jnp.minimum(buf, N - 1)], jnp.uint8(0))
    rec_lay = jnp.pad(rec_lay, ((0, (n_buf_tiles - nh) * T), (0, 0)))
    livec = jnp.cumsum(live.astype(jnp.int32))
    tl_full = jnp.concatenate([
        tile_leaf, jnp.full((n_buf_tiles - nh,), L - 1, jnp.int32)])
    tile_run = jnp.maximum(livec[tl_full] - 1, 0).astype(jnp.int32)
    run_slot = jnp.full((L,), L, jnp.int32).at[
        jnp.where(live, livec - 1, L)].set(
            jnp.arange(L, dtype=jnp.int32), mode="drop")
    return rec_lay, tile_run, run_slot


def advance_runs(run_slot: jnp.ndarray, run_do: jnp.ndarray,
                 run_right: jnp.ndarray, base_l: jnp.ndarray,
                 base_r: jnp.ndarray, n_buf_tiles: int,
                 sentinel: int | None = None):
    """Next level's (tile_run, run_slot) after ``level_moves``.

    ``run_do`` (L,) marks runs whose slot split this level; ``run_right``
    their right child's slot id.  Kept segments: every left segment of a
    live run (new run index = OLD index — left children keep the parent's
    slot) and the right segment of each splitting run (new runs R..R+S-1
    in run order).  Marking each kept segment's first tile and counting
    marks per tile yields the ascending tile->run map; everything between
    kept starts (empty mandatory segments, slack, the trailing buffer) is
    absorbed into the preceding run.

    ``sentinel`` is the "unused run" slot value (default: the run
    capacity L, the levelwise convention where slot ids < L).  The
    batched leaf-wise grower stores heap NODE ids (which exceed its run
    capacity) and passes sentinel = HN; when a kept run's slot id must
    CHANGE across the level (leaf-wise: the left child's node is 2n, not
    n), pre-apply that update to ``run_slot`` before calling — this
    helper only reads liveness from it and writes the appended right
    runs."""
    L = run_slot.shape[0]
    sent = L if sentinel is None else sentinel
    R = jnp.sum((run_slot < sent).astype(jnp.int32))
    ridx = jnp.arange(L, dtype=jnp.int32)
    marks = jnp.zeros((n_buf_tiles,), jnp.int32)
    marks = marks.at[jnp.where(ridx < R, base_l[:L], n_buf_tiles)].add(
        1, mode="drop")
    marks = marks.at[jnp.where(run_do, base_r[:L], n_buf_tiles)].add(
        1, mode="drop")
    tile_run = jnp.maximum(jnp.cumsum(marks) - 1, 0).astype(jnp.int32)
    rank = jnp.cumsum(run_do.astype(jnp.int32)) - run_do.astype(jnp.int32)
    run_slot = run_slot.at[jnp.where(run_do, R + rank, L)].set(
        run_right.astype(jnp.int32), mode="drop")
    return tile_run, run_slot


# ---------------------------------------------------------------------------
# numpy reference (the bitwise oracle for tests)
# ---------------------------------------------------------------------------

def permute_records_np(rec: np.ndarray, tile_slot: np.ndarray,
                       side: np.ndarray, n_parents: int, n_out_tiles: int,
                       T: int = _TILE_ROWS):
    """Reference: stable per-(segment, side) order into the
    [left | slack | right | slack] layout with _ALIGN-rounded per-tile
    contributions — mirrors level_moves exactly.

    Returns (out, tile_slot_new, row_seg_new): the permuted buffer plus
    the NEXT level's tile→segment map and per-row segment ids (−1 for
    sentinels), segments numbered [left children 0..P−1, then right
    children P..2P−1] in parent order."""
    A = _ALIGN
    n_tiles = tile_slot.shape[0]
    WB = rec.shape[1]
    P = n_parents
    # padded per-segment capacities (sum of rounded per-tile sizes)
    pad_l = np.zeros(P, np.int64)
    pad_r = np.zeros(P, np.int64)
    for i in range(n_tiles):
        s = tile_slot[i]
        sd = side[i * T:(i + 1) * T]
        pad_l[s] += -(-int((sd == 0).sum()) // A) * A
        pad_r[s] += -(-int((sd == 1).sum()) // A) * A
    lt_l = np.maximum(-(-pad_l // T), 1)
    lt_r = np.maximum(-(-pad_r // T), 1)
    base_l = np.concatenate([[0], np.cumsum(lt_l)]).astype(np.int64)
    off_r = base_l[-1] + 1
    base_r = off_r + np.concatenate([[0], np.cumsum(lt_r)]).astype(np.int64)
    out = np.zeros((n_out_tiles * T, WB), np.uint8)
    row_seg = np.full(n_out_tiles * T, -1, np.int64)
    tile_slot_new = np.full(n_out_tiles, -1, np.int64)
    for s in range(P):
        tile_slot_new[base_l[s]: base_l[s + 1]] = s
        tile_slot_new[base_r[s]: base_r[s + 1]] = P + s
    # slack (and trailing bound) tiles hold only sentinels: absorb them
    # into the PRECEDING segment so tile→segment stays a sequence of
    # consecutive runs (level_moves' prefix bookkeeping requires it); an
    # extra all-sentinel tile contributes a rounded-zero size — harmless
    for i in range(n_out_tiles):
        if tile_slot_new[i] < 0:
            tile_slot_new[i] = tile_slot_new[i - 1] if i else 0
    fill_l = np.zeros(P, np.int64)
    fill_r = np.zeros(P, np.int64)
    for i in range(n_tiles):
        s = tile_slot[i]
        nl = nr = 0
        for j in range(T):
            sd = side[i * T + j]
            if sd == 0:
                pos = base_l[s] * T + fill_l[s] + nl
                out[pos] = rec[i * T + j]
                row_seg[pos] = s
                nl += 1
            elif sd == 1:
                pos = base_r[s] * T + fill_r[s] + nr
                out[pos] = rec[i * T + j]
                row_seg[pos] = P + s
                nr += 1
        fill_l[s] += -(-nl // A) * A
        fill_r[s] += -(-nr // A) * A
    return out, tile_slot_new, row_seg
