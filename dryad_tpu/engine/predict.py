"""Device predict: level-synchronous tree traversal under jit.

Bit-identity contract (BASELINE.json:5): traversal decisions compare integer
bin ids — exact on any backend — and leaf-value accumulation runs in fp32 in
the same per-class tree order as ``cpu/predict.py`` (a ``lax.scan`` over
boosting iterations), so CPU and TPU raw scores are bit-identical given the
same model, not merely close.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np


def tree_leaves(tree: dict, Xb: jnp.ndarray, depth_bound) -> jnp.ndarray:
    """Leaf node id reached by every row in one tree (arrays shaped (M, ...)).

    ``depth_bound`` may be a Python int (static unroll bound) or a traced
    scalar (the grower's measured depth) — ``fori_loop`` accepts both.
    """
    N = Xb.shape[0]
    if isinstance(depth_bound, int):
        depth_bound = max(depth_bound, 1)
    else:
        depth_bound = jnp.maximum(depth_bound, 1)

    def body(_, node):
        f = tree["feature"][node]                      # (N,)
        internal = f >= 0
        fc = jnp.where(internal, f, 0).astype(jnp.int32)
        bins = jnp.take_along_axis(Xb, fc[:, None], axis=1)[:, 0].astype(jnp.int32)
        num_left = bins <= tree["threshold"][node]
        num_left &= tree["default_left"][node] | (bins != 0)
        bs = tree["cat_bitset"]
        word = bs[node, jnp.minimum(bins >> 5, bs.shape[1] - 1)]
        cat_left = ((word >> (bins & 31).astype(jnp.uint32)) & 1) > 0
        go_left = jnp.where(tree["is_cat"][node], cat_left, num_left)
        nxt = jnp.where(go_left, tree["left"][node], tree["right"][node])
        return jnp.where(internal, nxt, node)

    # derive the init from Xb so it inherits Xb's varying axes under shard_map
    node0 = (Xb[:, 0] * 0).astype(jnp.int32)
    return jax.lax.fori_loop(0, depth_bound, body, node0)


@partial(jax.jit, static_argnames=("depth_bound",))
def _accumulate(trees: dict, Xb: jnp.ndarray, init: jnp.ndarray, depth_bound: int):
    """Raw scores (N, K): scan boosting iterations, vmap the K class trees.

    ``trees`` arrays are shaped (n_iter, K, M, ...); per class the additions
    happen in iteration order — the exact fp32 summation order of the CPU
    reference path.
    """
    N = Xb.shape[0]
    K = trees["feature"].shape[1]
    score0 = jnp.broadcast_to(init.astype(jnp.float32), (N, K))

    def step(score, tree_k):
        leaves = jax.vmap(lambda tr: tree_leaves(tr, Xb, depth_bound))(tree_k)  # (K, N)
        delta = jnp.take_along_axis(tree_k["value"], leaves, axis=1)            # (K, N)
        return score + delta.T, None

    score, _ = jax.lax.scan(step, score0, trees)
    return score


def stage_trees(booster, num_iteration: Optional[int] = None):
    """Slice + reshape the tree tables for the device scan: (n_iter, K, M, ...)
    numpy arrays, the ``num_iteration``/``best_iteration`` semantics of
    ``predict_binned_cpu``.  Traversal-irrelevant tables (gain, cover) are
    dropped — they never feed an op, so removing them from the scan carry
    cannot change a bit of the result.  Shared by the one-shot device
    predict below and by the serving layer's model registry, which keeps
    the staged arrays device-resident across requests."""
    K = booster.num_outputs
    if num_iteration is None:
        n_iter = booster.best_iteration if booster.best_iteration > 0 else booster.num_iterations
    else:
        n_iter = min(num_iteration, booster.num_iterations)
    ta = booster.tree_arrays()
    T = n_iter * K
    trees = {
        k: v[:T].reshape((n_iter, K) + v.shape[1:])
        for k, v in ta.items() if k not in ("gain", "cover")
    }
    return trees, np.asarray(booster.init_score, np.float32), n_iter


def predict_binned_device(
    booster, Xb, num_iteration: Optional[int] = None
):
    """``dryad.predict`` device backend on pre-binned rows → raw scores
    (N, K).  Returns a device array — except under ``boosting='rf'``,
    where the final averaging transform runs on host (see below) and a
    numpy array comes back; the sole caller (Booster.predict_binned) ends
    in ``np.asarray`` either way."""
    trees_np, init, n_iter = stage_trees(booster, num_iteration)
    trees = {k: jnp.asarray(v) for k, v in trees_np.items()}
    Xb = jnp.asarray(Xb)
    raw = _accumulate(trees, Xb, jnp.asarray(init), max(booster.max_depth_seen, 1))
    if booster.params.boosting == "rf" and n_iter > 0:
        # rf averaging runs ON HOST via the ONE shared transform (device
        # FMA fusion is 1 ulp off — see cpu/predict.rf_average); the
        # accumulation stays on device, only the final elementwise
        # transform moves (predict ends in one host fetch anyway)
        from dryad_tpu.cpu.predict import rf_average

        return rf_average(np.asarray(raw), booster.init_score, n_iter)
    return raw
