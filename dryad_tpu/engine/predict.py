"""Device predict: level-synchronous tree traversal under jit.

Bit-identity contract (BASELINE.json:5): traversal decisions compare integer
bin ids — exact on any backend — and leaf-value accumulation runs in fp32 in
the same per-class tree order as ``cpu/predict.py`` (a ``lax.scan`` over
boosting iterations), so CPU and TPU raw scores are bit-identical given the
same model, not merely close.
"""

from __future__ import annotations

from functools import lru_cache, partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np


def tree_leaves(tree: dict, Xb: jnp.ndarray, depth_bound) -> jnp.ndarray:
    """Leaf node id reached by every row in one tree (arrays shaped (M, ...)).

    ``depth_bound`` may be a Python int (static unroll bound) or a traced
    scalar (the grower's measured depth) — ``fori_loop`` accepts both.
    """
    N = Xb.shape[0]
    if isinstance(depth_bound, int):
        depth_bound = max(depth_bound, 1)
    else:
        depth_bound = jnp.maximum(depth_bound, 1)

    def body(_, node):
        f = tree["feature"][node]                      # (N,)
        internal = f >= 0
        fc = jnp.where(internal, f, 0).astype(jnp.int32)
        bins = jnp.take_along_axis(Xb, fc[:, None], axis=1)[:, 0].astype(jnp.int32)
        num_left = bins <= tree["threshold"][node]
        num_left &= tree["default_left"][node] | (bins != 0)
        bs = tree["cat_bitset"]
        word = bs[node, jnp.minimum(bins >> 5, bs.shape[1] - 1)]
        cat_left = ((word >> (bins & 31).astype(jnp.uint32)) & 1) > 0
        go_left = jnp.where(tree["is_cat"][node], cat_left, num_left)
        nxt = jnp.where(go_left, tree["left"][node], tree["right"][node])
        return jnp.where(internal, nxt, node)

    # derive the init from Xb so it inherits Xb's varying axes under shard_map
    node0 = (Xb[:, 0] * 0).astype(jnp.int32)
    return jax.lax.fori_loop(0, depth_bound, body, node0)


def _accumulate_body(trees: dict, Xb: jnp.ndarray, init: jnp.ndarray,
                     depth_bound: int):
    """Raw scores (N, K): scan boosting iterations, vmap the K class trees.

    ``trees`` arrays are shaped (n_iter, K, M, ...); per class the additions
    happen in iteration order — the exact fp32 summation order of the CPU
    reference path.  Shared verbatim by the jitted single-device program
    and by each shard's block under ``shard_map`` (sharded_accumulate_fn):
    every op here is strictly per-row, which is what makes row sharding a
    bitwise no-op rather than an approximation.
    """
    N = Xb.shape[0]
    K = trees["feature"].shape[1]
    score0 = jnp.broadcast_to(init.astype(jnp.float32), (N, K))

    def step(score, tree_k):
        leaves = jax.vmap(lambda tr: tree_leaves(tr, Xb, depth_bound))(tree_k)  # (K, N)
        delta = jnp.take_along_axis(tree_k["value"], leaves, axis=1)            # (K, N)
        return score + delta.T, None

    score, _ = jax.lax.scan(step, score0, trees)
    return score


_accumulate = partial(jax.jit, static_argnames=("depth_bound",))(_accumulate_body)


@lru_cache(maxsize=None)
def sharded_accumulate_fn(mesh, depth_bound: int):
    """jit(shard_map(accumulate)): rows sharded over the mesh's data axis,
    tree tables replicated.  There are NO collectives inside — raw scores
    are per-row, so each device traverses its row block independently and
    the only cross-device motion is the implicit gather at the result edge
    when the host fetches the sharded output.  Cached per (mesh, depth) so
    warm serving traffic reuses one jitted program per bucket shape."""
    from jax.sharding import PartitionSpec as P

    from dryad_tpu.engine.distributed import AXIS
    from dryad_tpu.engine.jax_compat import shard_map

    def run(trees, Xb, init):
        return _accumulate_body(trees, Xb, init, depth_bound)

    return jax.jit(shard_map(
        run, mesh=mesh,
        in_specs=(P(), P(AXIS, None), P()),
        out_specs=P(AXIS, None),
    ))


# Sharding a predict dispatch pays only once the batch carries real work:
# below ~32k row-outputs the per-shard blocks are too small to beat the
# single-device program's dispatch cost, and interactive traffic stays on
# the fast path.  The serving layer exposes this as its default
# ``sharded_threshold``; callers gate on rows × num_outputs.
SHARDED_MIN_WORK = 1 << 15


def predict_binned_sharded(booster, Xb, num_iteration: Optional[int] = None,
                           mesh=None):
    """``predict_binned_device`` with the padded row batch sharded across
    the mesh (trees replicated).  Rows are padded with zero bins up to a
    multiple of the shard count; padding rows are sliced away before any
    host arithmetic, and every predict stage is per-row, so the result is
    BITWISE equal to the single-device path (tests pin it on the 8 fake
    CPU devices)."""
    import jax as _jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from dryad_tpu.engine import distributed as dist

    mesh = dist.make_mesh() if mesh is None else mesh
    n_shards = int(np.prod(mesh.devices.shape))
    trees_np, init, n_iter = stage_trees(booster, num_iteration)
    trees = {k: jnp.asarray(v) for k, v in trees_np.items()}
    Xb = np.asarray(Xb)
    n = int(Xb.shape[0])
    m = dist.padded_rows(max(n, 1), n_shards)
    if m != n:
        pad = np.zeros((m - n,) + Xb.shape[1:], Xb.dtype)
        Xp = np.concatenate([np.ascontiguousarray(Xb), pad])
    else:
        Xp = Xb
    Xp = _jax.device_put(Xp, NamedSharding(mesh, P(dist.AXIS, None)))
    depth = max(booster.max_depth_seen, 1)
    fn = sharded_accumulate_fn(mesh, depth)
    init_j = jnp.asarray(init)
    # compile-boundary introspection (r12): dryad_prog_* for the sharded
    # predict program; memoized per shape, observation-only
    from dryad_tpu.engine import introspect

    introspect.capture(
        "predict", ("sharded", n_shards, n_iter, booster.num_outputs,
                    Xp.shape, depth),
        fn, trees, Xp, init_j,
        labels={"arm": "sharded", "shards": n_shards})
    # np.asarray is the result-edge gather AND the one real host fetch
    raw = np.asarray(fn(trees, Xp, init_j))[:n]
    if booster.params.boosting == "rf" and n_iter > 0:
        from dryad_tpu.cpu.predict import rf_average

        return rf_average(raw, booster.init_score, n_iter)
    return raw


def stage_trees(booster, num_iteration: Optional[int] = None):
    """Slice + reshape the tree tables for the device scan: (n_iter, K, M, ...)
    numpy arrays, the ``num_iteration``/``best_iteration`` semantics of
    ``predict_binned_cpu``.  Traversal-irrelevant tables (gain, cover) are
    dropped — they never feed an op, so removing them from the scan carry
    cannot change a bit of the result.  Shared by the one-shot device
    predict below and by the serving layer's model registry, which keeps
    the staged arrays device-resident across requests."""
    K = booster.num_outputs
    if num_iteration is None:
        n_iter = booster.best_iteration if booster.best_iteration > 0 else booster.num_iterations
    else:
        n_iter = min(num_iteration, booster.num_iterations)
    ta = booster.tree_arrays()
    T = n_iter * K
    trees = {
        k: v[:T].reshape((n_iter, K) + v.shape[1:])
        for k, v in ta.items() if k not in ("gain", "cover")
    }
    return trees, np.asarray(booster.init_score, np.float32), n_iter


def predict_binned_device(
    booster, Xb, num_iteration: Optional[int] = None
):
    """``dryad.predict`` device backend on pre-binned rows → raw scores
    (N, K).  Returns a device array — except under ``boosting='rf'``,
    where the final averaging transform runs on host (see below) and a
    numpy array comes back; the sole caller (Booster.predict_binned) ends
    in ``np.asarray`` either way."""
    trees_np, init, n_iter = stage_trees(booster, num_iteration)
    trees = {k: jnp.asarray(v) for k, v in trees_np.items()}
    Xb = jnp.asarray(Xb)
    depth = max(booster.max_depth_seen, 1)
    init_j = jnp.asarray(init)
    # compile-boundary introspection (r12) — memoized per shape
    from dryad_tpu.engine import introspect

    introspect.capture(
        "predict", ("single", n_iter, booster.num_outputs, Xb.shape, depth),
        _accumulate, trees, Xb, init_j, depth,
        labels={"arm": "single", "shards": 1})
    raw = _accumulate(trees, Xb, init_j, depth)
    if booster.params.boosting == "rf" and n_iter > 0:
        # rf averaging runs ON HOST via the ONE shared transform (device
        # FMA fusion is 1 ulp off — see cpu/predict.rf_average); the
        # accumulation stays on device, only the final elementwise
        # transform moves (predict ends in one host fetch anyway)
        from dryad_tpu.cpu.predict import rf_average

        return rf_average(np.asarray(raw), booster.init_score, n_iter)
    return raw
