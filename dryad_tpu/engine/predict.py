"""Device predict: level-synchronous tree traversal under jit.

Bit-identity contract (BASELINE.json:5): traversal decisions compare integer
bin ids — exact on any backend — and leaf-value accumulation runs in fp32 in
the same per-class tree order as ``cpu/predict.py`` (a ``lax.scan`` over
boosting iterations), so CPU and TPU raw scores are bit-identical given the
same model, not merely close.

r21: two traversal table layouts share that contract.  The default packed
arm ("auto" resolves to it whenever the fields fit) stages each node's
traversal fields in one (M, 2)-uint32 limb table so every level pays ONE
small-table gather; ``predict_layout="legacy"`` keeps the
structure-of-arrays arm as the comparison baseline.  Packed ≡ legacy is
bitwise on the single-device and sharded arms (tests/test_predict_packed.py
pins it across numeric/cat/missing/multiclass/rf at 1/2/8 shards).
"""

from __future__ import annotations

from functools import lru_cache, partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from dryad_tpu.policy.table import GATE_DEFAULTS as _POLICY_DEFAULTS

# ---- packed node-word layout (r21) ----------------------------------------
# Gather cost on TPU is per-ACCESS, not per-byte (CLAUDE.md measured
# lowering facts), so the traversal fields of one node are packed into a
# single table row and the per-level body pays ONE small-table gather
# instead of the legacy structure-of-arrays ~7.  The repo never enables
# jax_enable_x64 — a device uint64 would silently truncate to uint32 — so
# the "word" is two uint32 limbs in a (..., M, 2) table; ``table[node]``
# still lowers to one gather instruction fetching 8 bytes per row.
#
#   limb0: left (bits 0..15) | right (bits 16..31)
#   limb1: threshold (0..15) | feature (16..27) | default_left (28)
#          | is_cat (29) | internal (30)
#
# Leaf nodes pack as all-zero fields with the internal bit clear; the
# traversal keeps the legacy leaf-self-loop via where(internal, nxt, node).
PACKED_CHILD_BITS = 16      # node ids: max_nodes = 2*num_leaves - 1
PACKED_THRESHOLD_BITS = 16  # bin ids: max_bins <= 65536
PACKED_FEATURE_BITS = 12    # column ids in the binned matrix


def packed_fallback_reason(feature, threshold, left, right):
    """The first traversal field that overflows its packed-word width,
    named (``"threshold max 70000 exceeds 16-bit packed width"``), or
    None when everything fits (checked against the ACTUAL staged values,
    not declared dims — a sliced model can fit even when the full one
    would not).  The reason rides the policy decision record into serve
    ``/stats`` so an operator can see WHY a model serves legacy (r23)."""
    feature = np.asarray(feature)
    internal = feature >= 0
    if not internal.any():
        return None
    named = (("feature", feature, PACKED_FEATURE_BITS),
             ("threshold", np.asarray(threshold), PACKED_THRESHOLD_BITS),
             ("left", np.asarray(left), PACKED_CHILD_BITS),
             ("right", np.asarray(right), PACKED_CHILD_BITS))
    for name, arr, bits in named:
        lo, hi = int(arr[internal].min()), int(arr[internal].max())
        if lo < 0 or hi >= (1 << bits):
            return (f"{name} range {lo}..{hi} exceeds its "
                    f"{bits}-bit packed width")
    return None


def packed_fields_fit(feature, threshold, left, right) -> bool:
    """True when every traversal field fits its packed-word width."""
    return packed_fallback_reason(feature, threshold, left, right) is None


def pack_node_words(feature, threshold, left, right, default_left,
                    is_cat) -> np.ndarray:
    """Pack per-node traversal fields (..., M) into the (..., M, 2) uint32
    limb table.  Width-asserted against the actual values; leaf fields are
    canonicalised to zero so the packing is a pure function of the
    traversal-relevant content."""
    feature = np.asarray(feature, np.int64)
    internal = feature >= 0
    fields = {
        "feature": np.where(internal, feature, 0),
        "threshold": np.where(internal, np.asarray(threshold, np.int64), 0),
        "left": np.where(internal, np.asarray(left, np.int64), 0),
        "right": np.where(internal, np.asarray(right, np.int64), 0),
    }
    widths = {"feature": PACKED_FEATURE_BITS,
              "threshold": PACKED_THRESHOLD_BITS,
              "left": PACKED_CHILD_BITS, "right": PACKED_CHILD_BITS}
    for name, arr in fields.items():
        if arr.size and (int(arr.min()) < 0
                         or int(arr.max()) >= (1 << widths[name])):
            raise ValueError(
                f"packed predict layout: field {name!r} does not fit "
                f"{widths[name]} bits (max value {int(arr.max())}); use "
                f"predict_layout='legacy' for this model")
    dl = np.where(internal & np.asarray(default_left, bool), 1, 0)
    ic = np.where(internal & np.asarray(is_cat, bool), 1, 0)
    limb0 = (fields["left"] | (fields["right"] << PACKED_CHILD_BITS))
    limb1 = (fields["threshold"]
             | (fields["feature"] << 16)
             | (dl << 28) | (ic << 29)
             | (np.where(internal, 1, 0) << 30))
    return np.stack([limb0.astype(np.uint32), limb1.astype(np.uint32)],
                    axis=-1)


def unpack_node_words(words: np.ndarray) -> dict:
    """Inverse of ``pack_node_words`` back to the canonical (leaf-zeroed)
    field dict — the round-trip anchor for the pack/unpack property test."""
    words = np.asarray(words, np.uint32)
    limb0 = words[..., 0].astype(np.int64)
    limb1 = words[..., 1].astype(np.int64)
    internal = ((limb1 >> 30) & 1) > 0
    return {
        "left": (limb0 & 0xFFFF).astype(np.int32),
        "right": (limb0 >> PACKED_CHILD_BITS).astype(np.int32),
        "threshold": (limb1 & 0xFFFF).astype(np.int32),
        "feature": np.where(
            internal, (limb1 >> 16) & 0xFFF, -1).astype(np.int32),
        "default_left": ((limb1 >> 28) & 1) > 0,
        "is_cat": ((limb1 >> 29) & 1) > 0,
    }


def staged_layout(trees: dict) -> str:
    """Layout of a staged trees dict — dict-key presence IS the dispatch
    (pytree structure is static under jit, so this costs nothing traced)."""
    return "packed" if "node_word" in trees else "legacy"


def tree_leaves(tree: dict, Xb: jnp.ndarray, depth_bound) -> jnp.ndarray:
    """Leaf node id reached by every row in one tree (arrays shaped (M, ...)).

    ``depth_bound`` may be a Python int (static unroll bound) or a traced
    scalar (the grower's measured depth) — ``fori_loop`` accepts both.

    Two table layouts (r21), dispatched on dict-key presence (static):
    ``node_word`` selects the packed arm — one (M, 2)-uint32 table gather
    per level plus the unavoidable per-row ``Xb`` column read; otherwise
    the legacy structure-of-arrays arm runs, itself issuing the
    ``cat_bitset`` gather only when the staged dict carries one (numeric
    models no longer pay the bitset gather).  Both arms compare the SAME
    int32 bin/threshold/child values, so packed ≡ legacy is bitwise.
    """
    N = Xb.shape[0]
    if isinstance(depth_bound, int):
        depth_bound = max(depth_bound, 1)
    else:
        depth_bound = jnp.maximum(depth_bound, 1)

    def body_packed(_, node):
        w = tree["node_word"][node]                    # (N, 2) — ONE gather
        w0, w1 = w[..., 0], w[..., 1]
        internal = (w1 >> jnp.uint32(30)) > 0          # bit 31 never set
        fc = ((w1 >> jnp.uint32(16)) & jnp.uint32(0xFFF)).astype(jnp.int32)
        bins = jnp.take_along_axis(Xb, fc[:, None], axis=1)[:, 0].astype(jnp.int32)
        num_left = bins <= (w1 & jnp.uint32(0xFFFF)).astype(jnp.int32)
        num_left &= (((w1 >> jnp.uint32(28)) & 1) > 0) | (bins != 0)
        if "cat_bitset" in tree:                       # static: model has cats
            bs = tree["cat_bitset"]
            word = bs[node, jnp.minimum(bins >> 5, bs.shape[1] - 1)]
            cat_left = ((word >> (bins & 31).astype(jnp.uint32)) & 1) > 0
            go_left = jnp.where(((w1 >> jnp.uint32(29)) & 1) > 0,
                                cat_left, num_left)
        else:
            go_left = num_left
        nxt = jnp.where(go_left,
                        (w0 & jnp.uint32(0xFFFF)).astype(jnp.int32),
                        (w0 >> jnp.uint32(16)).astype(jnp.int32))
        return jnp.where(internal, nxt, node)

    def body(_, node):
        f = tree["feature"][node]                      # (N,)
        internal = f >= 0
        fc = jnp.where(internal, f, 0).astype(jnp.int32)
        bins = jnp.take_along_axis(Xb, fc[:, None], axis=1)[:, 0].astype(jnp.int32)
        num_left = bins <= tree["threshold"][node]
        num_left &= tree["default_left"][node] | (bins != 0)
        if "cat_bitset" in tree:                       # static: model has cats
            bs = tree["cat_bitset"]
            word = bs[node, jnp.minimum(bins >> 5, bs.shape[1] - 1)]
            cat_left = ((word >> (bins & 31).astype(jnp.uint32)) & 1) > 0
            go_left = jnp.where(tree["is_cat"][node], cat_left, num_left)
        else:
            # satellite r21: a False is_cat mask selected num_left exactly,
            # so dropping the dead bitset/is_cat gathers is bitwise free
            go_left = num_left
        nxt = jnp.where(go_left, tree["left"][node], tree["right"][node])
        return jnp.where(internal, nxt, node)

    # derive the init from Xb so it inherits Xb's varying axes under shard_map
    node0 = (Xb[:, 0] * 0).astype(jnp.int32)
    step = body_packed if "node_word" in tree else body
    return jax.lax.fori_loop(0, depth_bound, step, node0)


def _accumulate_body(trees: dict, Xb: jnp.ndarray, init: jnp.ndarray,
                     depth_bound: int):
    """Raw scores (N, K): scan boosting iterations, vmap the K class trees.

    ``trees`` arrays are shaped (n_iter, K, M, ...); per class the additions
    happen in iteration order — the exact fp32 summation order of the CPU
    reference path.  Shared verbatim by the jitted single-device program
    and by each shard's block under ``shard_map`` (sharded_accumulate_fn):
    every op here is strictly per-row, which is what makes row sharding a
    bitwise no-op rather than an approximation.
    """
    N = Xb.shape[0]
    K = trees["value"].shape[1]    # present in both layouts
    score0 = jnp.broadcast_to(init.astype(jnp.float32), (N, K))

    def step(score, tree_k):
        leaves = jax.vmap(lambda tr: tree_leaves(tr, Xb, depth_bound))(tree_k)  # (K, N)
        delta = jnp.take_along_axis(tree_k["value"], leaves, axis=1)            # (K, N)
        return score + delta.T, None

    score, _ = jax.lax.scan(step, score0, trees)
    return score


_accumulate = partial(jax.jit, static_argnames=("depth_bound",))(_accumulate_body)


@lru_cache(maxsize=None)
def sharded_accumulate_fn(mesh, depth_bound: int):
    """jit(shard_map(accumulate)): rows sharded over the mesh's data axis,
    tree tables replicated.  There are NO collectives inside — raw scores
    are per-row, so each device traverses its row block independently and
    the only cross-device motion is the implicit gather at the result edge
    when the host fetches the sharded output.  Cached per (mesh, depth) so
    warm serving traffic reuses one jitted program per bucket shape."""
    from jax.sharding import PartitionSpec as P

    from dryad_tpu.engine.distributed import AXIS
    from dryad_tpu.engine.jax_compat import shard_map

    def run(trees, Xb, init):
        return _accumulate_body(trees, Xb, init, depth_bound)

    return jax.jit(shard_map(
        run, mesh=mesh,
        in_specs=(P(), P(AXIS, None), P()),
        out_specs=P(AXIS, None),
    ))


# Sharding a predict dispatch pays only once the batch carries real work:
# below ~32k row-outputs the per-shard blocks are too small to beat the
# single-device program's dispatch cost, and interactive traffic stays on
# the fast path.  The serving layer exposes this as its default
# ``sharded_threshold``; callers gate on rows × num_outputs.  r23: the
# constant lives in the policy table ("predict_sharded"/"min_work");
# this name is the compatibility re-export of the committed default —
# serve resolves its live default through gate_value() so a calibrated
# device entry can move it.
SHARDED_MIN_WORK = _POLICY_DEFAULTS["predict_sharded"]["min_work"]


def predict_binned_sharded(booster, Xb, num_iteration: Optional[int] = None,
                           mesh=None):
    """``predict_binned_device`` with the padded row batch sharded across
    the mesh (trees replicated).  Rows are padded with zero bins up to a
    multiple of the shard count; padding rows are sliced away before any
    host arithmetic, and every predict stage is per-row, so the result is
    BITWISE equal to the single-device path (tests pin it on the 8 fake
    CPU devices)."""
    import jax as _jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from dryad_tpu.engine import distributed as dist

    mesh = dist.make_mesh() if mesh is None else mesh
    n_shards = int(np.prod(mesh.devices.shape))
    trees_np, init, n_iter = stage_trees(booster, num_iteration)
    trees = {k: jnp.asarray(v) for k, v in trees_np.items()}
    Xb = np.asarray(Xb)
    n = int(Xb.shape[0])
    m = dist.padded_rows(max(n, 1), n_shards)
    if m != n:
        # np.concatenate already produces a fresh contiguous array, so the
        # old ascontiguousarray pre-copy paid a second full copy for nothing
        pad = np.zeros((m - n,) + Xb.shape[1:], Xb.dtype)
        Xp = np.concatenate([Xb, pad])
    else:
        Xp = Xb    # no padding needed -> zero-copy straight into device_put
    Xp = _jax.device_put(Xp, NamedSharding(mesh, P(dist.AXIS, None)))
    depth = max(booster.max_depth_seen, 1)
    fn = sharded_accumulate_fn(mesh, depth)
    init_j = jnp.asarray(init)
    # compile-boundary introspection (r12): dryad_prog_* for the sharded
    # predict program; memoized per shape, observation-only
    from dryad_tpu.engine import introspect

    layout = staged_layout(trees_np)
    introspect.capture(
        "predict", ("sharded", n_shards, n_iter, booster.num_outputs,
                    Xp.shape, depth, layout),
        fn, trees, Xp, init_j,
        labels={"arm": "sharded", "shards": n_shards, "layout": layout})
    # np.asarray is the result-edge gather AND the one real host fetch
    raw = np.asarray(fn(trees, Xp, init_j))[:n]
    if booster.params.boosting == "rf" and n_iter > 0:
        from dryad_tpu.cpu.predict import rf_average

        return rf_average(raw, booster.init_score, n_iter)
    return raw


def stage_trees(booster, num_iteration: Optional[int] = None,
                layout: Optional[str] = None):
    """Slice + reshape the tree tables for the device scan: (n_iter, K, M, ...)
    numpy arrays, the ``num_iteration``/``best_iteration`` semantics of
    ``predict_binned_cpu``.  Traversal-irrelevant tables (gain, cover) are
    dropped — they never feed an op, so removing them from the scan carry
    cannot change a bit of the result.  Shared by the one-shot device
    predict below and by the serving layer's model registry, which keeps
    the staged arrays device-resident across requests.

    ``layout`` (default: ``booster.params.predict_layout``) selects the
    staged table layout:

    * ``"packed"`` — the r21 node-word arm: traversal fields packed into a
      (n_iter, K, M, 2) uint32 limb table (``pack_node_words``, width-
      asserted), ``cat_bitset`` kept ONLY when the sliced model actually
      contains a categorical split, so numeric programs are statically
      bitset-free.  Raises when a field exceeds its packed width.
    * ``"legacy"`` — the structure-of-arrays comparison arm; numeric
      models drop ``is_cat``/``cat_bitset`` (they fed a dead select).
    * ``"auto"`` — packed when every field fits, legacy otherwise.

    Packing only rewrites TRAVERSAL inputs; ``value`` and the accumulation
    scan are untouched, so packed ≡ legacy predict is bitwise.
    """
    K = booster.num_outputs
    if num_iteration is None:
        n_iter = booster.best_iteration if booster.best_iteration > 0 else booster.num_iterations
    else:
        n_iter = min(num_iteration, booster.num_iterations)
    ta = booster.tree_arrays()
    T = n_iter * K
    trees = {
        k: v[:T].reshape((n_iter, K) + v.shape[1:])
        for k, v in ta.items() if k not in ("gain", "cover")
    }
    if layout is None:
        layout = getattr(booster.params, "predict_layout", "auto")
    if layout == "auto":
        from dryad_tpu.policy.gates import resolve

        reason = packed_fallback_reason(
            trees["feature"], trees["threshold"], trees["left"],
            trees["right"])
        layout = resolve("predict_layout", {"fits": reason is None},
                         detail=reason)
    has_cat = bool(np.asarray(trees["is_cat"]).any())
    if layout == "packed":
        words = pack_node_words(
            trees["feature"], trees["threshold"], trees["left"],
            trees["right"], trees["default_left"], trees["is_cat"])
        staged = {"node_word": words, "value": trees["value"]}
        if has_cat:
            staged["cat_bitset"] = trees["cat_bitset"]
        trees = staged
    elif not has_cat:
        trees = {k: v for k, v in trees.items()
                 if k not in ("is_cat", "cat_bitset")}
    return trees, np.asarray(booster.init_score, np.float32), n_iter


def predict_binned_device(
    booster, Xb, num_iteration: Optional[int] = None
):
    """``dryad.predict`` device backend on pre-binned rows → raw scores
    (N, K).  Returns a device array — except under ``boosting='rf'``,
    where the final averaging transform runs on host (see below) and a
    numpy array comes back; the sole caller (Booster.predict_binned) ends
    in ``np.asarray`` either way."""
    trees_np, init, n_iter = stage_trees(booster, num_iteration)
    trees = {k: jnp.asarray(v) for k, v in trees_np.items()}
    Xb = jnp.asarray(Xb)
    depth = max(booster.max_depth_seen, 1)
    init_j = jnp.asarray(init)
    # compile-boundary introspection (r12) — memoized per shape
    from dryad_tpu.engine import introspect

    layout = staged_layout(trees_np)
    introspect.capture(
        "predict", ("single", n_iter, booster.num_outputs, Xb.shape, depth,
                    layout),
        _accumulate, trees, Xb, init_j, depth,
        labels={"arm": "single", "shards": 1, "layout": layout})
    raw = _accumulate(trees, Xb, init_j, depth)
    if booster.params.boosting == "rf" and n_iter > 0:
        # rf averaging runs ON HOST via the ONE shared transform (device
        # FMA fusion is 1 ulp off — see cpu/predict.rf_average); the
        # accumulation stays on device, only the final elementwise
        # transform moves (predict ends in one host fetch anyway)
        from dryad_tpu.cpu.predict import rf_average

        return rf_average(np.asarray(raw), booster.init_score, n_iter)
    return raw
