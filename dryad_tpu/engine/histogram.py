"""Device histogram builder — the TPU equivalent of the reference's CUDA
per-feature histogram kernel (BASELINE.json:5; SURVEY.md §2 #5).

TPUs have no atomic scatter-add, so the bincount-style scatter the CUDA
kernel relies on is reformulated as a **masked one-hot matmul** that runs on
the MXU (SURVEY.md §7 step 2):

    hist[k, f, b] = sum_r w[k, r] * [bin(r, f) == b]      k in {grad, hess, count}

i.e. a (3, C) x (C, F*B) matmul per row-chunk, with the one-hot operand
built by comparing the chunk's bin ids against an iota and never leaving the
fusion scope of one chunk.  Chunks are processed under ``lax.scan`` so the
one-hot temporary stays bounded regardless of N (Epsilon's 2000 features
stress this — BASELINE.json:9).

Accumulation is fp32: exact for counts below 2**24 and within last-ulp of
the CPU reference's f64 histograms for gain argmax purposes (documented
tolerance, SURVEY.md §7 hard part c).

When ``axis_name`` is set the per-shard partial histogram is reduced
cross-shard by ``distributed.reduce_hist`` — the fused ``jax.lax.psum``
(the NCCL-allreduce replacement, SURVEY.md §2 #14; grad, hess, and count
ride one fused collective per call) or, for the level builders under
``hist_reduce="feature"`` (r16), a feature-partition reduce-scatter that
leaves each shard its owned fully-reduced F/n slice.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from dryad_tpu.engine.jax_compat import pcast_varying
from dryad_tpu.policy.table import GATE_DEFAULTS as _POLICY_DEFAULTS


# axon: the tunneled-TPU plugin platform.  r23: the platform list lives
# in the policy table ("hist_backend"/"pallas_platforms"); this name is
# the compatibility re-export of the committed default.
_PALLAS_PLATFORMS = tuple(
    _POLICY_DEFAULTS["hist_backend"]["pallas_platforms"])


def resolve_backend(backend: str, *, segmented: bool = False,
                    platform: str | None = None) -> str:
    """auto -> the measured winner per path: the Pallas kernel on a TPU
    for BOTH the leaf-segmented level pass (1.7x over the XLA matmul) and,
    since the round-3 pipeline shrink, the single-mask pass too (the XLA
    one-hot materializes C x F*B fp32 per chunk in HBM — 252 vs 136 ms at
    Higgs-10M, 1262 vs 320 ms at Epsilon shapes); XLA on CPU (Pallas would
    run interpreted) and on any non-TPU accelerator (the kernel uses
    TPU-only Mosaic features).

    ``platform`` overrides the process default backend when the caller
    knows the devices that will actually run the program (e.g. a CPU mesh
    forced on a TPU-attached process — train_device resolves against its
    mesh and passes a concrete backend down)."""
    if backend == "auto":
        from dryad_tpu.policy.gates import resolve

        return resolve("hist_backend",
                       {"platform": platform or jax.default_backend()})
    return backend


def _resolve_precision(precision: str):
    """exact -> HIGHEST (6-pass fp32 MXU; the default would round the f32
    operands to bf16 and break gain-argmax parity with the CPU reference).
    fast -> DEFAULT (single-pass bf16, ~6x; counts stay exact because the
    0/1 products accumulate in f32)."""
    import jax as _jax

    return (_jax.lax.Precision.HIGHEST if precision == "exact"
            else _jax.lax.Precision.DEFAULT)


def _chunk_rows(num_rows: int, num_features: int, total_bins: int,
                rows_per_chunk: int, elem_budget: int = 1 << 26) -> int:
    """Row-chunk size: respect the caller's cap and a one-hot element budget."""
    by_budget = max(256, elem_budget // max(num_features * total_bins, 1))
    c = min(rows_per_chunk, by_budget, max(num_rows, 1))
    return max(c, 1)


def build_hist(
    Xb: jnp.ndarray,
    g: jnp.ndarray,
    h: jnp.ndarray,
    mask: jnp.ndarray,
    total_bins: int,
    *,
    rows_per_chunk: int = 65536,
    axis_name: str | None = None,
    precision: str = "exact",
    backend: str = "xla",
    platform: str | None = None,
) -> jnp.ndarray:
    """Masked per-(feature, bin) sums -> (3, F, B) fp32: grad, hess, count.

    ``mask`` (N,) bool selects the rows that contribute (the rows of the leaf
    being histogrammed — the replacement for gathering a dynamic row list,
    which XLA's static-shape model rules out).
    """
    if resolve_backend(backend, platform=platform) == "pallas":
        from dryad_tpu.engine import pallas_hist

        if pallas_hist.supports(total_bins):
            return pallas_hist.build_hist_pallas(
                Xb, g, h, mask, total_bins, axis_name=axis_name,
                platform=platform,
            )
    # NOTE: this body must stay accumulation-order-identical to
    # build_hist_classes (its K=1 case) — test_build_hist_classes_matches_
    # per_class pins the bitwise contract with a multi-chunk fixture, and
    # scripts/smoke_tpu.py re-asserts it on the real device (the lowering
    # is fusion-sensitive there).  Delegating to the classes builder was
    # tried and measured 3.6x slower per call; unifying the other way
    # (precomputing w in the classes builder) would materialize (2K+1)*N
    # floats in HBM — 600 MB for K=7 at 10M rows — so the two bodies stay
    # separate on purpose.
    N, F = Xb.shape
    B = int(total_bins)
    prec = _resolve_precision(precision)
    C = _chunk_rows(N, F, B, rows_per_chunk)
    pad = (-N) % C
    if pad:
        Xb = jnp.pad(Xb, ((0, pad), (0, 0)))
        g = jnp.pad(g, (0, pad))
        h = jnp.pad(h, (0, pad))
        mask = jnp.pad(mask, (0, pad))
    n_chunks = (N + pad) // C

    Xc = Xb.reshape(n_chunks, C, F)
    m = mask.astype(jnp.float32).reshape(n_chunks, C)
    # weights (n_chunks, 3, C): grad, hess, count — one matmul covers all three
    w = jnp.stack(
        [g.astype(jnp.float32).reshape(n_chunks, C) * m,
         h.astype(jnp.float32).reshape(n_chunks, C) * m,
         m],
        axis=1,
    )
    iota = jnp.arange(B, dtype=jnp.int32)

    def body(acc, chunk):
        xc, wc = chunk
        onehot = (xc.astype(jnp.int32)[:, :, None] == iota).astype(jnp.float32)
        part = jax.lax.dot_general(
            wc, onehot.reshape(C, F * B),
            (((1,), (0,)), ((), ())),
            precision=prec,
            preferred_element_type=jnp.float32,
        )
        return acc + part, None

    acc0 = jnp.zeros((3, F * B), jnp.float32)
    if axis_name is not None:
        # under shard_map the carry must be marked device-varying to match
        # the varying per-chunk partials (JAX vma tracking)
        acc0 = pcast_varying(acc0, axis_name)
    acc, _ = jax.lax.scan(body, acc0, (Xc, w))
    hist = acc.reshape(3, F, B)
    if axis_name is not None:
        hist = jax.lax.psum(hist, axis_name)  # the NCCL-allreduce equivalent
    return hist


@partial(jax.jit, static_argnames=("total_bins", "rows_per_chunk"))
def build_hist_jit(Xb, g, h, mask, total_bins, rows_per_chunk=65536):
    return build_hist(Xb, g, h, mask, total_bins, rows_per_chunk=rows_per_chunk)


def build_hist_classes(
    Xb: jnp.ndarray,
    g_all: jnp.ndarray,   # (N, K) f32
    h_all: jnp.ndarray,   # (N, K) f32
    mask: jnp.ndarray,
    total_bins: int,
    *,
    rows_per_chunk: int = 65536,
    precision: str = "exact",
    axis_name: str | None = None,
) -> jnp.ndarray:
    """Shared-plan histograms for K classes in ONE pass -> (K, 3, F, B).

    Multiclass iterations grow K trees whose ROOT level histograms all
    cover the same rows (trees only diverge after the first split), so the
    K per-class root passes collapse into a single matmul whose weight
    matrix carries 2K+1 rows (g_0..g_{K-1}, h_0..h_{K-1} + one shared
    count) — the MXU pads the row dimension to 8/128 anyway, so K=7 costs
    the same pass a single class does (CLAUDE.md open item; Covertype).

    Per-class slices are accumulation-order-identical to ``build_hist``
    (same chunking, same products, same dot) — the bitwise contract is
    pinned by test_build_hist_classes_matches_per_class on a multi-chunk
    fixture; keep the two bodies in sync.
    """
    N, F = Xb.shape
    B = int(total_bins)
    K = g_all.shape[1]
    prec = _resolve_precision(precision)
    C = _chunk_rows(N, F, B, rows_per_chunk)
    pad = (-N) % C
    if pad:
        Xb = jnp.pad(Xb, ((0, pad), (0, 0)))
        g_all = jnp.pad(g_all, ((0, pad), (0, 0)))
        h_all = jnp.pad(h_all, ((0, pad), (0, 0)))
        mask = jnp.pad(mask, (0, pad))
    n_chunks = (N + pad) // C

    Xc = Xb.reshape(n_chunks, C, F)
    m = mask.astype(jnp.float32).reshape(n_chunks, C)
    # class-MAJOR chunk layout (n_chunks, K, C): the row dimension C stays
    # in lanes.  A (C, K) minor-dim-K layout pads K up to 128 under XLA's
    # (8, 128) tiling — measured 5x slower build_hist calls at K=1 when
    # this function became the shared implementation (CLAUDE.md lane rule)
    gc = g_all.astype(jnp.float32).T.reshape(K, n_chunks, C).transpose(1, 0, 2)
    hc = h_all.astype(jnp.float32).T.reshape(K, n_chunks, C).transpose(1, 0, 2)
    iota = jnp.arange(B, dtype=jnp.int32)

    def body(acc, chunk):
        xc, gk, hk, mk = chunk                      # gk/hk: (K, C)
        onehot = (xc.astype(jnp.int32)[:, :, None] == iota).astype(jnp.float32)
        # (2K+1, C) rows: g_0..g_{K-1}, h_0..h_{K-1}, count
        w = jnp.concatenate([gk * mk[None, :], hk * mk[None, :],
                             mk[None, :]])
        part = jax.lax.dot_general(
            w, onehot.reshape(C, F * B),
            (((1,), (0,)), ((), ())),
            precision=prec,
            preferred_element_type=jnp.float32,
        )
        return acc + part, None

    acc0 = jnp.zeros((2 * K + 1, F * B), jnp.float32)
    if axis_name is not None:
        # under shard_map the carry must be marked device-varying to match
        # the varying per-chunk partials (JAX vma tracking)
        acc0 = pcast_varying(acc0, axis_name)
    acc, _ = jax.lax.scan(body, acc0, (Xc, gc, hc, m))
    gs = acc[:K].reshape(K, 1, F, B)
    hs = acc[K: 2 * K].reshape(K, 1, F, B)
    cnt = jnp.broadcast_to(acc[2 * K].reshape(1, 1, F, B), (K, 1, F, B))
    hist = jnp.concatenate([gs, hs, cnt], axis=1)  # (K, 3, F, B)
    if axis_name is not None:
        hist = jax.lax.psum(hist, axis_name)  # the NCCL-allreduce equivalent
    return hist


def build_hist_multi(
    Xb: jnp.ndarray,
    g: jnp.ndarray,
    h: jnp.ndarray,
    sel: jnp.ndarray,
    num_cols: int,
    total_bins: int,
    *,
    rows_per_chunk: int = 65536,
    axis_name: str | None = None,
    precision: str = "exact",
    hist_reduce: str = "fused",
) -> jnp.ndarray:
    """Histograms for ``num_cols`` leaves in ONE pass -> (P, 3, F, B) fp32.

    ``sel`` (N,) assigns each row to a column in [0, P); P means "drop".
    This is the level-wise formulation (SURVEY.md §7 step 6): batching every
    leaf of a tree level into the matmul's N dimension costs barely more
    than a single masked pass, because the MXU pads N to 128 anyway — the
    per-leaf masked approach wastes that padding P times over.

    One ``psum`` covers all P leaves' grad/hess/count stats when
    ``axis_name`` is set — the per-level histogram allreduce.
    """
    N, F = Xb.shape
    B = int(total_bins)
    P = int(num_cols)
    prec = _resolve_precision(precision)
    C = _chunk_rows(N, F, B, rows_per_chunk)
    pad = (-N) % C
    if pad:
        Xb = jnp.pad(Xb, ((0, pad), (0, 0)))
        g = jnp.pad(g, (0, pad))
        h = jnp.pad(h, (0, pad))
        sel = jnp.pad(sel, (0, pad), constant_values=P)
    n_chunks = (N + pad) // C

    Xc = Xb.reshape(n_chunks, C, F)
    gc = g.astype(jnp.float32).reshape(n_chunks, C)
    hc = h.astype(jnp.float32).reshape(n_chunks, C)
    sc = sel.astype(jnp.int32).reshape(n_chunks, C)
    iota_b = jnp.arange(B, dtype=jnp.int32)
    iota_p = jnp.arange(P, dtype=jnp.int32)

    def body(acc, chunk):
        xc, gk, hk, sk = chunk
        onehot = (xc.astype(jnp.int32)[:, :, None] == iota_b).astype(jnp.float32)
        onesel = (sk[None, :] == iota_p[:, None]).astype(jnp.float32)  # (P, C)
        w = jnp.stack([onesel * gk[None, :], onesel * hk[None, :], onesel])
        part = jax.lax.dot_general(
            w.reshape(3 * P, C), onehot.reshape(C, F * B),
            (((1,), (0,)), ((), ())),
            precision=prec,
            preferred_element_type=jnp.float32,
        )
        return acc + part, None

    acc0 = jnp.zeros((3 * P, F * B), jnp.float32)
    if axis_name is not None:
        acc0 = pcast_varying(acc0, axis_name)
    acc, _ = jax.lax.scan(body, acc0, (Xc, gc, hc, sc))
    hist = acc.reshape(3, P, F, B).transpose(1, 0, 2, 3)
    if axis_name is not None:
        from dryad_tpu.engine.distributed import reduce_hist

        hist = reduce_hist(hist, axis_name, hist_reduce)
    return hist


def _segment_tile(num_rows: int, num_cols: int) -> int:
    """Tile size for the segmented builder: bound per-leaf padding overhead
    (each leaf wastes < one tile) while keeping tiles MXU-friendly."""
    t = 128
    while t < 1024 and t * 4 * num_cols < num_rows:
        t *= 2
    return t


def build_hist_segmented(
    Xb: jnp.ndarray,
    g: jnp.ndarray,
    h: jnp.ndarray,
    sel: jnp.ndarray,
    num_cols: int,
    total_bins: int,
    *,
    rows_per_chunk: int = 65536,
    axis_name: str | None = None,
    precision: str = "exact",
    backend: str = "xla",
    rows_bound: int | None = None,
    platform: str | None = None,
    records: jnp.ndarray | None = None,
    sel_counts: jnp.ndarray | None = None,
    stage_gather: bool = True,
    hist_reduce: str = "fused",
) -> jnp.ndarray:
    """Histograms for ``num_cols`` leaves -> (P, 3, F, B) fp32, O(N·F·B) work.

    The dense ``build_hist_multi`` weight matrix makes every row pay for
    every leaf column (3P·N·F·B MACs) — fine for a handful of leaves, fatal
    at depth 8.  Here rows are *sorted by leaf* so each leaf occupies
    contiguous tiles, every tile's (3, T) @ (T, F*B) matmul serves exactly
    one leaf, and per-tile results scatter to leaves with one tiny matmul.
    Work: 3·(N + P·T)·F·B MACs per level — leaf-count independent, the same
    asymptotics the reference's CUDA scatter-add kernel gets from atomics.

    ``sel`` (N,) in [0, P]; P drops the row.  Deterministic: stable sort +
    fixed tile accumulation order.
    """
    if resolve_backend(backend, segmented=True, platform=platform) == "pallas":
        from dryad_tpu.engine import pallas_hist

        if pallas_hist.supports(total_bins):
            return pallas_hist.build_hist_segmented_pallas(
                Xb, g, h, sel, num_cols, total_bins, axis_name=axis_name,
                rows_bound=rows_bound, platform=platform, records=records,
                sel_counts=sel_counts, stage_gather=stage_gather,
                hist_reduce=hist_reduce,
            )
    N, F = Xb.shape
    B = int(total_bins)
    P = int(num_cols)
    prec = _resolve_precision(precision)
    T = _segment_tile(N, P)
    # one shared bucketing plan with the Pallas path (incl. the rows_bound
    # safety squeeze); clamped trailing tiles hold only sentinel rows, so
    # their leaf assignment contributes zeros to the scatter below
    from dryad_tpu.engine.pallas_hist import tile_plan

    buf, tile_leaf, _ = tile_plan(sel, N, P, T, rows_bound=rows_bound)
    n_tiles = buf.shape[0] // T

    # gather rows (sentinel N -> zero row)
    Xp = jnp.concatenate([Xb, jnp.zeros((1, F), Xb.dtype)])
    gp = jnp.concatenate([g.astype(jnp.float32), jnp.zeros((1,), jnp.float32)])
    hp = jnp.concatenate([h.astype(jnp.float32), jnp.zeros((1,), jnp.float32)])
    Xt = Xp[buf].reshape(n_tiles, T, F)
    gt = gp[buf].reshape(n_tiles, T)
    ht = hp[buf].reshape(n_tiles, T)
    valid = (buf < N).astype(jnp.float32).reshape(n_tiles, T)

    # chunk tiles so the one-hot temporary stays bounded
    tiles_per_chunk = max(1, _chunk_rows(n_tiles * T, F, B, rows_per_chunk) // T)
    cpad = (-n_tiles) % tiles_per_chunk
    if cpad:
        Xt = jnp.pad(Xt, ((0, cpad), (0, 0), (0, 0)))
        gt = jnp.pad(gt, ((0, cpad), (0, 0)))
        ht = jnp.pad(ht, ((0, cpad), (0, 0)))
        valid = jnp.pad(valid, ((0, cpad), (0, 0)))
    nc = (n_tiles + cpad) // tiles_per_chunk
    iota_b = jnp.arange(B, dtype=jnp.int32)

    def body(_, chunk):
        xc, gk, hk, vk = chunk                      # (Tc, T, ...)
        onehot = (xc.astype(jnp.int32)[..., None] == iota_b).astype(jnp.float32)
        w = jnp.stack([gk * vk, hk * vk, vk], axis=1)      # (Tc, 3, T)
        part = jax.lax.dot_general(
            w, onehot.reshape(xc.shape[0], T, F * B),
            (((2,), (1,)), ((0,), (0,))),
            precision=prec,
            preferred_element_type=jnp.float32,
        )                                           # (Tc, 3, F*B)
        return None, part

    _, tile_hists = jax.lax.scan(
        body, None,
        (Xt.reshape(nc, tiles_per_chunk, T, F),
         gt.reshape(nc, tiles_per_chunk, T),
         ht.reshape(nc, tiles_per_chunk, T),
         valid.reshape(nc, tiles_per_chunk, T)),
    )
    tile_hists = tile_hists.reshape(n_tiles + cpad, 3 * F * B)[:n_tiles]

    # scatter tiles -> leaves: one (P, n_tiles) x (n_tiles, 3FB) matmul
    onehot_tl = (tile_leaf[None, :] == jnp.arange(P, dtype=jnp.int32)[:, None])
    hist = jax.lax.dot_general(
        onehot_tl.astype(jnp.float32), tile_hists,
        (((1,), (0,)), ((), ())),
        precision=prec,
        preferred_element_type=jnp.float32,
    ).reshape(P, 3, F, B)
    if axis_name is not None:
        from dryad_tpu.engine.distributed import reduce_hist

        hist = reduce_hist(hist, axis_name, hist_reduce)
    return hist
