"""Device leaf-wise tree grower — compiled replacement for the reference's
host-side grower + CUDA row-partition kernel (BASELINE.json:5; SURVEY.md §2
#7-8).

XLA traces once and forbids data-dependent shapes, so the reference's
dynamic per-leaf row lists become a **slot machine** (SURVEY.md §7 step 2):

* ``row_slot`` (N,) — every row carries the id of the leaf *slot* it lives
  in (slot L = out-of-bag sentinel).  The CUDA partition kernel's row
  shuffling becomes a vectorized ``where`` on this array.
* L leaf slots, each holding its node id, stats (G/H/C), depth, cached best
  split, and its full histogram — preallocated, validity-masked.
* the grow loop is a ``lax.fori_loop`` with exactly L-1 trips; a trip whose
  best gain is -inf is a compiled no-op (``lax.cond``), mirroring the CPU
  trainer's early break.

Semantics mirror ``cpu/trainer.py::_TreeGrower`` step for step: the left
child keeps the parent's slot, the right child takes slot k+1; child stats
come from the parent histogram prefix; the smaller child's histogram is
built directly and the larger obtained by subtraction (LightGBM trick —
halves histogram work); ties broken by first index.

Distribution (SURVEY.md §2 #13-14): under ``shard_map`` with rows sharded,
every device runs this same program on its shard; this SEQUENTIAL grower's
only cross-device exchange is the fused grad/hess/count histogram psum
inside ``build_hist`` — exactly where the reference placed its NCCL
allreduce (it ignores ``Params.hist_reduce``; the level-synchronous
growers own the r16 feature-parallel arm).  G/H/C stats are derived from
the (replicated) histogram, so all devices take identical split decisions
without further collectives.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

from dryad_tpu.booster import CAT_WORDS
from dryad_tpu.config import Params
from dryad_tpu.engine.histogram import build_hist
from dryad_tpu.engine.split import NEG_INF, find_best_split

_BIG_DEPTH = jnp.int32(2**30)


def grow_any(params, total_bins, Xb, g, h, bag_mask, feat_mask, is_cat_feat,
             *, has_cat=False, axis_name=None, platform=None,
             learn_missing=False, root_hist=None, bundled_mask=None,
             global_rows=None):
    """Route to the fastest grower for the growth policy.

    Depth-wise growth takes the level-synchronous path (one batched
    histogram pass per level — levelwise.py); leaf-wise keeps the exact
    one-split-at-a-time reference semantics below.  ``root_hist`` skips
    the root histogram pass when the caller already has it (multiclass
    shared-plan roots — histogram.build_hist_classes).
    """
    if params.growth == "depthwise" and params.max_depth > 0:
        from dryad_tpu.engine.levelwise import grow_tree_levelwise

        return grow_tree_levelwise(
            params, total_bins, Xb, g, h, bag_mask, feat_mask, is_cat_feat,
            has_cat=has_cat, axis_name=axis_name, platform=platform,
            learn_missing=learn_missing, root_hist=root_hist,
            bundled_mask=bundled_mask,
        )
    if params.growth == "leafwise":
        from dryad_tpu.engine import leafwise_fast

        # GLOBAL rows (static at trace time): the batched-vs-sequential
        # choice must not depend on the shard count, or N-shard ≡ 1-shard
        # breaks — under shard_map Xb is the local shard.  Sharded callers
        # pass the UNPADDED global N (local*n_shards counts the mesh pad,
        # which varies with shard count and could flip the envelope at the
        # boundary); single-device direct callers carry no pad.
        if global_rows is None:
            n_shards = int(jax.lax.psum(1, axis_name)) if axis_name else 1
            global_rows = Xb.shape[0] * n_shards
        if leafwise_fast.supports(params, Xb.shape[1], int(total_bins),
                                  global_rows):
            # depth-capped leaf-wise: exact best-first selection over a
            # level-synchronous full expansion — O(N·depth) instead of the
            # sequential grower's O(N·leaves) (gains are order-independent,
            # so the selected tree is the sequential one).  Unbounded depth
            # (max_depth <= 0) keeps the sequential path below.
            return leafwise_fast.grow_tree_leafwise_batched(
                params, total_bins, Xb, g, h, bag_mask, feat_mask,
                is_cat_feat, has_cat=has_cat, axis_name=axis_name,
                platform=platform, learn_missing=learn_missing,
                root_hist=root_hist, bundled_mask=bundled_mask,
            )
        if params.max_depth > 0 and params.hist_subtraction:
            # deterministic fallback with a visible, SPECIFIC reason
            # (VERDICT r3 #7) — the sequential grower is exact, just
            # O(N·leaves).  hist_subtraction=False is a deliberate,
            # documented config choice (the expansion derives larger
            # siblings by subtraction), so it does not warn.
            import warnings

            from dryad_tpu.config import MAX_FAST_DEPTH

            reason = ("max_depth above the batched grower's cap "
                      f"({MAX_FAST_DEPTH})"
                      if params.max_depth > MAX_FAST_DEPTH
                      else "peak-memory envelope "
                           "(config.leafwise_fast_supported)")
            warnings.warn(
                f"batched leaf-wise grower unavailable: {reason} — "
                "falling back to the sequential grower",
                stacklevel=2)
    return grow_tree(
        params, total_bins, Xb, g, h, bag_mask, feat_mask, is_cat_feat,
        has_cat=has_cat, axis_name=axis_name, platform=platform,
        learn_missing=learn_missing, root_hist=root_hist,
        bundled_mask=bundled_mask,
    )


def _monotone_array(p: Params, F: int):
    """(F,) int32 constraint array, or None when unconstrained (static)."""
    if not p.monotone_constraints or not any(p.monotone_constraints):
        return None
    mono = [0] * F
    for i, m in enumerate(p.monotone_constraints[:F]):
        mono[i] = int(m)
    return jnp.asarray(mono, jnp.int32)


def child_bounds(mono, sf, GL, HL, GR, HR, lam, lo_p, hi_p):
    """Monotone output bounds for the two children of a split (LightGBM
    "basic" mode): the midpoint of the clamped child outputs separates the
    subtrees across a ±1 split feature; m=0 splits inherit the parent
    bounds.  Shared by both device growers; cpu/trainer.py mirrors the same
    f32 arithmetic.  Works elementwise on scalars or (P,) candidate rows."""
    wl = jnp.clip(-(GL / (HL + lam)), lo_p, hi_p)
    wr = jnp.clip(-(GR / (HR + lam)), lo_p, hi_p)
    mid = jnp.float32(0.5) * (wl + wr)
    m = mono[jnp.maximum(sf, 0)]
    lo_l = jnp.where(m < 0, mid, lo_p)
    hi_l = jnp.where(m > 0, mid, hi_p)
    lo_r = jnp.where(m > 0, mid, lo_p)
    hi_r = jnp.where(m < 0, mid, hi_p)
    return lo_l, hi_l, lo_r, hi_r


def root_stats(hist0: jnp.ndarray):
    """Canonical leaf totals = feature-0 histogram sums (cpu/trainer.py
    contract) — shared by both growers so the derivation can never diverge."""
    return hist0[0, 0].sum(), hist0[1, 0].sum(), hist0[2, 0].sum()


def finalize_leaf_values(p: Params, M: int, slot_node, slot_G, slot_H,
                         value: jnp.ndarray, slot_lo=None, slot_hi=None) -> jnp.ndarray:
    """Newton leaf values with shrinkage, fp32, scattered to leaf nodes.

    ``slot_lo``/``slot_hi`` (monotone output bounds) clamp the raw Newton
    value before shrinkage; pass None when unconstrained so the compiled
    program is unchanged."""
    raw = -(slot_G / (slot_H + jnp.float32(p.lambda_l2)))
    if slot_lo is not None:
        raw = jnp.clip(raw, slot_lo, slot_hi)
    vals = raw * jnp.float32(p.effective_learning_rate)
    idx = jnp.where(slot_node >= 0, slot_node, M)
    return value.at[idx].set(vals, mode="drop")


def pack_cat_bitset(cat_mask_nodes: jnp.ndarray, M: int) -> jnp.ndarray:
    """(M, B) bool membership masks -> (M, CAT_WORDS) uint32 node bitsets,
    bit layout b -> word b>>5, bit b&31 (matches cpu/histogram.py)."""
    catm = cat_mask_nodes
    width = CAT_WORDS * 32
    if catm.shape[1] < width:
        catm = jnp.pad(catm, ((0, 0), (0, width - catm.shape[1])))
    bits = catm[:, :width].reshape(M, CAT_WORDS, 32).astype(jnp.uint32)
    return (bits << jnp.arange(32, dtype=jnp.uint32)).sum(axis=2, dtype=jnp.uint32)


def grow_tree(
    params: Params,
    total_bins: int,
    Xb: jnp.ndarray,          # (N, F) uint8/uint16 — local row shard
    g: jnp.ndarray,           # (N,) f32
    h: jnp.ndarray,           # (N,) f32
    bag_mask: jnp.ndarray,    # (N,) bool — bagging subsample
    feat_mask: jnp.ndarray,   # (F,) bool — colsample
    is_cat_feat: jnp.ndarray, # (F,) bool
    *,
    has_cat: bool = False,
    axis_name: str | None = None,
    platform: str | None = None,
    learn_missing: bool = False,
    root_hist: jnp.ndarray | None = None,
    bundled_mask: jnp.ndarray | None = None,
) -> dict[str, Any]:
    """Grow one tree; returns SoA tree arrays (max_nodes,) + max_depth.

    Pure function of its inputs — jit it (single device) or call it inside
    ``shard_map`` (rows sharded over ``axis_name``).
    """
    p = params
    N, F = Xb.shape
    B = int(total_bins)
    L = p.effective_num_leaves
    M = p.max_nodes
    depth_cap = p.max_depth if p.max_depth > 0 else L
    depthwise = p.growth == "depthwise"

    mono = _monotone_array(p, F)

    def best(hist, G, H, C, depth, lo=None, hi=None):
        allow = (depth < depth_cap) & (C >= 2 * p.min_data_in_leaf)
        return find_best_split(
            hist, G, H, C,
            lambda_l2=p.lambda_l2,
            min_child_weight=p.min_child_weight,
            min_data_in_leaf=p.min_data_in_leaf,
            min_split_gain=p.min_split_gain,
            feat_mask=feat_mask,
            is_cat_feat=is_cat_feat,
            allow=allow,
            has_cat=has_cat,
            monotone=mono,
            lo=lo,
            hi=hi,
            learn_missing=learn_missing,
            bundled_mask=bundled_mask,
        )

    def hist_of(mask):
        # bag gates HISTOGRAMS only; the row partition routes every row so
        # the final row_slot directly yields each row's leaf (no separate
        # post-grow traversal — at 10M rows that gather loop cost ~5 s/tree)
        return build_hist(
            Xb, g, h, mask & bag_mask, B,
            rows_per_chunk=p.rows_per_chunk, axis_name=axis_name,
            precision=p.hist_precision, backend=p.hist_backend,
            platform=platform,
        )

    # NOTE (measured): routing the small child through the bounded segmented
    # kernel (tile plan at N/2) is ~30% SLOWER here than the masked XLA pass
    # — the per-split stable sort in the tile plan dominates.  Leaf-wise
    # growth keeps the masked histogram; depthwise amortizes the sort per
    # level and is the TPU throughput path.

    # ---- root ---------------------------------------------------------------
    # ALL rows partitioned (see hist_of); derived from bag_mask so the init
    # inherits the varying-manual-axes of the shard under shard_map (a plain
    # constant would make the grow-loop cond branches' vma types diverge)
    row_slot = jnp.where(bag_mask, 0, 0).astype(jnp.int32)
    hist0 = root_hist if root_hist is not None else hist_of(row_slot == 0)
    G0, H0, C0 = root_stats(hist0)
    ninf, pinf = jnp.float32(-jnp.inf), jnp.float32(jnp.inf)
    root = best(hist0, G0, H0, C0, jnp.int32(0), ninf, pinf)

    st = {
        "row_slot": row_slot,
        "slot_node": jnp.full((L,), -1, jnp.int32).at[0].set(0),
        "slot_gain": jnp.full((L,), NEG_INF, jnp.float32).at[0].set(root.gain),
        "slot_G": jnp.zeros((L,), jnp.float32).at[0].set(G0),
        "slot_H": jnp.zeros((L,), jnp.float32).at[0].set(H0),
        "slot_C": jnp.zeros((L,), jnp.float32).at[0].set(C0),
        "slot_depth": jnp.zeros((L,), jnp.int32),
        "slot_lo": jnp.full((L,), ninf, jnp.float32),
        "slot_hi": jnp.full((L,), pinf, jnp.float32),
        "sp_feature": jnp.full((L,), -1, jnp.int32).at[0].set(root.feature),
        "sp_thresh": jnp.zeros((L,), jnp.int32).at[0].set(root.threshold),
        "sp_GL": jnp.zeros((L,), jnp.float32).at[0].set(root.g_left),
        "sp_HL": jnp.zeros((L,), jnp.float32).at[0].set(root.h_left),
        "sp_CL": jnp.zeros((L,), jnp.float32).at[0].set(root.c_left),
        "sp_catmask": jnp.zeros((L, root.cat_mask.shape[0]), bool).at[0].set(root.cat_mask),
        "sp_dleft": jnp.ones((L,), bool).at[0].set(root.default_left),
        "hists": jnp.zeros((L, 3, F, B), jnp.float32).at[0].set(hist0),
        "feature": jnp.full((M,), -1, jnp.int32),
        "threshold": jnp.zeros((M,), jnp.int32),
        "left": jnp.zeros((M,), jnp.int32),
        "right": jnp.zeros((M,), jnp.int32),
        "value": jnp.zeros((M,), jnp.float32),
        "gain": jnp.zeros((M,), jnp.float32),
        "cover": jnp.zeros((M,), jnp.float32).at[0].set(C0),
        "is_cat": jnp.zeros((M,), bool),
        "cat_mask_nodes": jnp.zeros((M, root.cat_mask.shape[0]), bool),
        "node_dleft": jnp.ones((M,), bool),
        "num_nodes": jnp.int32(1),
        "max_depth": jnp.int32(0),
    }

    # ---- grow loop ----------------------------------------------------------
    def pick_slot(s_gain, s_depth):
        finite = s_gain > NEG_INF
        if depthwise:
            # split the shallowest level first, best gain within it
            dmin = jnp.min(jnp.where(finite, s_depth, _BIG_DEPTH))
            masked = jnp.where(finite & (s_depth == dmin), s_gain, NEG_INF)
            return jnp.argmax(masked).astype(jnp.int32)
        return jnp.argmax(s_gain).astype(jnp.int32)

    def do_split(k, s, st):
        parent = st["slot_node"][s]
        sf = st["sp_feature"][s]
        thr = st["sp_thresh"][s]
        catm = st["sp_catmask"][s]
        cat_split = is_cat_feat[sf] if has_cat else jnp.bool_(False)

        bins_f = jnp.take(Xb, sf, axis=1).astype(jnp.int32)
        num_left = bins_f <= thr
        dl = st["sp_dleft"][s]
        if learn_missing:
            num_left &= dl | (bins_f > 0)
        if has_cat:
            go_left = jnp.where(cat_split, catm[jnp.minimum(bins_f, catm.shape[0] - 1)],
                                num_left)
        else:
            go_left = num_left
        in_slot = st["row_slot"] == s

        GL, HL, CL = st["sp_GL"][s], st["sp_HL"][s], st["sp_CL"][s]
        Gp, Hp, Cp = st["slot_G"][s], st["slot_H"][s], st["slot_C"][s]
        GR, HR, CR = Gp - GL, Hp - HL, Cp - CL

        left_id = st["num_nodes"]
        right_id = left_id + 1
        new_r = jnp.int32(k + 1)

        gain_arr = st["gain"].at[parent].set(st["slot_gain"][s])
        cover_arr = st["cover"].at[left_id].set(CL).at[right_id].set(CR)
        feature = st["feature"].at[parent].set(sf)
        threshold = st["threshold"].at[parent].set(jnp.where(cat_split, 0, thr))
        left = st["left"].at[parent].set(left_id)
        right = st["right"].at[parent].set(right_id)
        is_cat_arr = st["is_cat"].at[parent].set(cat_split)
        cat_nodes = st["cat_mask_nodes"].at[parent].set(
            jnp.where(cat_split, catm, jnp.zeros_like(catm))
        )
        node_dleft = st["node_dleft"].at[parent].set(dl | cat_split)

        # row partition/apply: left child keeps slot s, right child takes k+1
        row_slot = jnp.where(in_slot & ~go_left, new_r, st["row_slot"])

        # smaller child's histogram direct; larger by subtraction
        left_smaller = CL <= CR
        if p.hist_subtraction:
            small_slot = jnp.where(left_smaller, s, new_r)
            shist = hist_of(row_slot == small_slot)
            ohist = st["hists"][s] - shist
            hist_l = jnp.where(left_smaller, shist, ohist)
            hist_r = jnp.where(left_smaller, ohist, shist)
        else:
            hist_l = hist_of(row_slot == s)
            hist_r = hist_of(row_slot == new_r)
        hists = st["hists"].at[s].set(hist_l).at[new_r].set(hist_r)

        depth_c = st["slot_depth"][s] + 1
        lo_p, hi_p = st["slot_lo"][s], st["slot_hi"][s]
        if mono is not None:
            lo_l, hi_l, lo_r, hi_r = child_bounds(
                mono, sf, GL, HL, GR, HR, jnp.float32(p.lambda_l2), lo_p, hi_p)
        else:
            lo_l = lo_r = lo_p
            hi_l = hi_r = hi_p
        res_l = best(hist_l, GL, HL, CL, depth_c, lo_l, hi_l)
        res_r = best(hist_r, GR, HR, CR, depth_c, lo_r, hi_r)

        def put(a, vl, vr):
            return a.at[s].set(vl).at[new_r].set(vr)

        return {
            "row_slot": row_slot,
            "slot_node": put(st["slot_node"], left_id, right_id),
            "slot_gain": put(st["slot_gain"], res_l.gain, res_r.gain),
            "slot_G": put(st["slot_G"], GL, GR),
            "slot_H": put(st["slot_H"], HL, HR),
            "slot_C": put(st["slot_C"], CL, CR),
            "slot_depth": put(st["slot_depth"], depth_c, depth_c),
            "slot_lo": put(st["slot_lo"], lo_l, lo_r),
            "slot_hi": put(st["slot_hi"], hi_l, hi_r),
            "sp_feature": put(st["sp_feature"], res_l.feature, res_r.feature),
            "sp_thresh": put(st["sp_thresh"], res_l.threshold, res_r.threshold),
            "sp_GL": put(st["sp_GL"], res_l.g_left, res_r.g_left),
            "sp_HL": put(st["sp_HL"], res_l.h_left, res_r.h_left),
            "sp_CL": put(st["sp_CL"], res_l.c_left, res_r.c_left),
            "sp_catmask": put(st["sp_catmask"], res_l.cat_mask, res_r.cat_mask),
            "sp_dleft": put(st["sp_dleft"], res_l.default_left, res_r.default_left),
            "hists": hists,
            "feature": feature,
            "threshold": threshold,
            "left": left,
            "right": right,
            "value": st["value"],
            "gain": gain_arr,
            "cover": cover_arr,
            "is_cat": is_cat_arr,
            "cat_mask_nodes": cat_nodes,
            "node_dleft": node_dleft,
            "num_nodes": st["num_nodes"] + 2,
            "max_depth": jnp.maximum(st["max_depth"], depth_c),
        }

    def body(k, st):
        s = pick_slot(st["slot_gain"], st["slot_depth"])
        return jax.lax.cond(
            st["slot_gain"][s] > NEG_INF,
            lambda st_: do_split(k, s, st_),
            lambda st_: st_,
            st,
        )

    st = jax.lax.fori_loop(0, L - 1, body, st)

    # ---- finalize leaf values + node bitsets (shared helpers) ---------------
    value = finalize_leaf_values(
        p, M, st["slot_node"], st["slot_G"], st["slot_H"], st["value"],
        slot_lo=st["slot_lo"] if mono is not None else None,
        slot_hi=st["slot_hi"] if mono is not None else None,
    )
    cat_bitset = pack_cat_bitset(st["cat_mask_nodes"], M)

    return {
        "feature": st["feature"],
        "threshold": st["threshold"],
        "left": st["left"],
        "right": st["right"],
        "value": value,
        "gain": st["gain"],
        "cover": st["cover"],
        "is_cat": st["is_cat"],
        "cat_bitset": cat_bitset,
        "default_left": st["node_dleft"],
        "max_depth": st["max_depth"],
        # per-row leaf node id, straight from the partition state — the
        # train step's score update uses this instead of re-traversing
        "row_leaf": jnp.maximum(st["slot_node"], 0)[
            jnp.minimum(st["row_slot"], L - 1)],
    }
