"""Level-wise (depth-wise) tree grower — the TPU throughput path.

The generic grower (grower.py) mirrors the reference's one-split-at-a-time
control flow, which costs one full masked histogram pass per split — the
MXU pads the 3-row weight matrix to 128 rows, so per-split passes waste
~40x of the matrix unit.  Growing level-synchronously amortizes that: every
leaf of a level lands in one ``build_hist_multi`` call whose weight matrix
carries 3 columns per leaf, so a whole level of histograms costs roughly
ONE pass over the rows (SURVEY.md §7 step 6; the classic GPU engines get
the same effect from atomics — this is the MXU-shaped equivalent).

Semantics replicate ``cpu/trainer.py`` depth-wise growth exactly: within a
level, splits are applied in best-gain-first order (stable, first-slot
tie-break) until the ``num_leaves`` budget runs out; the left child keeps
the parent's slot, right children take consecutive slot ids in execution
order; child stats come from the parent-histogram prefix; the smaller child
is histogrammed directly, the larger derived by subtraction.

Distribution: identical contract to grower.py — call under ``shard_map``
with rows sharded; the single per-level fused psum inside
``build_hist_multi`` is the only collective.

Layout everywhere (r6 deep phase, r10 whole tree): when the gate admits
(``deep_layout_supported``) the tree carries the leaf-ordered record
layout (engine/leafperm.py) through the level fori_loop state from
LEVEL 0 — the natural-order record buffer is the root layout
(``leafperm.natural_root_layout``: one segment, out-of-bag rows as
sentinels), sides derive from the layout records, one stable per-tile
MXU compaction moves every row to its child segment, and the children's
histograms read the new layout as CONTIGUOUS tile runs.  The per-level
packed ``(slot<<24 | row)`` sort and the full-N record gather are GONE
(measured 51.4 vs 164 ms/level at 10M for the data movement they
replaced), and so is the r6 shallow->deep handoff sort+gather per tree
— nothing on the wired path ever sorts rows.  The plan-based path below
remains only for configs the layout cannot take (each exclusion's
verdict is written in ``deep_layout_supported``) and as the explicitly
requested ``deep_layout="legacy"`` comparison arm.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

from dryad_tpu.config import Params
from dryad_tpu.engine.grower import (
    child_bounds,
    finalize_leaf_values,
    pack_cat_bitset,
    root_stats,
)
from dryad_tpu.engine.histogram import (
    build_hist,
    build_hist_multi,
    build_hist_segmented,
)
from dryad_tpu.engine.split import NEG_INF, find_best_split

# STRUCTURAL packed-word caps for the wired layout (r10): the side
# derivation rides the same packed per-slot word as the natural-order
# partition (13-bit threshold, 16-bit slot fields).  These are encoding
# widths, not measured crossovers — they stay here, never in the policy
# calibration table (a table can only narrow the CALIBRATED caps that
# deep_layout_supported routes through policy below).
_MAX_PACKED_BINS = 1 << 13
_MAX_PACKED_LEAVES = 1 << 16


def partition_prefers_reduce(num_features: int, itemsize: int) -> bool:
    """Partition formulation choice, shared by both level-synchronous
    growers: the masked reduce over the CONTIGUOUS (N, F) matrix vs the
    per-row column gather.

    The reduce's traffic is N·F·itemsize sequential bytes; the gather
    costs ~per-ACCESS (CLAUDE.md: ~30 ms per 10M accesses, bytes nearly
    free).  Crossover: reading F·itemsize bytes/row beats one random
    access while F·itemsize ≲ 20 KB of sequential traffic per row-access
    saved — far above any supported width.  r4 gated the reduce at
    F <= 256 on the 10M=28-feature measurement alone, sending
    Epsilon-shaped (400k × 2000) configs to the ~320 ms-class gather; r5
    widens the gate to 4 KB/row (u8: F <= 4096, u16: F <= 2048), measured
    on the Epsilon shape (exp_r5_eps.py: reduce 11.1 ms vs gather 18.6 ms
    per pass at 400k x 2000; the whole-run effect measured 10.2 ->
    7.1 s/iter warm).  r23: the row-byte budget lives in the policy
    table ("partition"/"reduce_max_row_bytes"); the committed default is
    the 4 KB above, bitwise-identical resolution."""
    from dryad_tpu.policy.gates import resolve

    return resolve("partition", {"num_features": num_features,
                                 "itemsize": itemsize}) == "reduce"


def select_bins(Xb: jnp.ndarray, rf: jnp.ndarray) -> jnp.ndarray:
    """Each row's bin id on its per-row feature ``rf`` — THE partition
    column-select, shared by both level-synchronous growers so the
    formulation (and the gate above) can never diverge between them (the
    r4 F<=256 gate had to be widened in two copies; review r5).  Masked
    reduce over the contiguous (N, F) matrix when the gate admits (at
    most one column matches per row), per-row gather otherwise."""
    F = Xb.shape[1]
    if partition_prefers_reduce(F, Xb.dtype.itemsize):
        iota_f = jnp.arange(F, dtype=jnp.int32)
        return jnp.max(
            jnp.where(rf[:, None] == iota_f[None, :], Xb,
                      jnp.zeros((), Xb.dtype)),
            axis=1).astype(jnp.int32)
    return jnp.take_along_axis(Xb, rf[:, None], axis=1)[:, 0].astype(
        jnp.int32)


def deep_layout_supported(p: Params, num_features: int, total_bins: int,
                          bin_itemsize: int,
                          platform: str | None = None) -> bool:
    """Static gate for the wired (leaf-ordered layout) level-wise grower
    — since r10 the layout is live from LEVEL 0 (root-anchored), so this
    gates the whole tree, not just the deep phase.

    A pure function of (params, feature/bin shape, platform) — NEVER of
    the row count, which under ``shard_map`` is the local shard and would
    let 1-shard and N-shard runs of the same data choose different
    histogram programs (the CLAUDE.md same-program rule).  Exclusion
    verdicts (r10 retirement pass — each is either LIFTED with parity
    tests or kept with the measurement that makes it irrelevant):

    * ``hist_subtraction=False`` — LIFTED (r10): the wired level
      histograms BOTH children in one 2P-column ``hist_from_layout``
      pass over the new layout's contiguous runs (every live row read
      exactly once — cheaper than the legacy small-pass + full
      ``build_hist_multi`` pair); parity pinned by
      ``test_wired_no_subtraction_matches_legacy``.
    * wide records (9 + F*itemsize > _REC_WB = 128 B) — KEPT, measured
      irrelevant: the layout's win is the deleted per-level sort +
      record gather, whose cost is access-bound and scales with N
      (~164 ms/level at 10M with 128 B records ≈ ~7 ms/level at
      Epsilon's 400k rows), while an Epsilon-shaped record (9 + 2000 B
      -> 16x the granule) multiplies every level's MOVED bytes ~16x —
      the compaction alone would cost more than the sort+gather it
      replaces (scaling exp_r5_perm's 51.4 ms/level by 0.4/10 rows x
      16x bytes ≈ 33 ms/level vs ~7 to win back).  Wide-feature shapes
      already dodge the per-row gather via the partition reduce
      (exp_r5_eps: 11.1 ms/pass at 400k x 2000), so there is no
      ~110 ms/level to recover on this path.
    * leaf budgets past 512 — KEPT, structural: the (L,)-dense run
      bookkeeping mandates >= 2L+2 tiles per level (every unused run
      index owns a mandatory tile per region — level_moves contract).
      At L=512 that is ~525k zero-sentinel rows per level, ~5% of the
      10M headline's movement; past 512 the mandated tiles grow
      linearly in L while the recoverable sort+gather stays fixed at
      ~164 ms/level, so the empty-segment movement stops being noise
      for ANY row count the HBM budget admits (and the gate cannot
      consult N — same-program rule above).
    * non-Pallas histogram backends / bins past the Pallas cap
      (``pallas_hist.supports``) — structural: the layout feeds the
      tile kernel; there is no XLA consumer of a tile-aligned layout.
    * bins > 8192 / leaves >= 65536 — structural: the side derivation
      rides the same packed per-slot word as the natural-order
      partition (13-bit threshold, 16-bit slot fields).
    * ``deep_layout="legacy"`` (explicit opt-out: smoke gate + bench
      comparison arms, and the escape hatch if wired drifts on device).
    """
    from dryad_tpu.engine import pallas_hist
    from dryad_tpu.engine.histogram import resolve_backend
    from dryad_tpu.policy.gates import resolve

    if p.deep_layout == "legacy":
        return False
    if resolve_backend(p.hist_backend, segmented=True,
                       platform=platform) != "pallas":
        return False
    if not pallas_hist.supports(total_bins):
        return False
    L = p.effective_num_leaves
    if not (total_bins <= _MAX_PACKED_BINS and L < _MAX_PACKED_LEAVES):
        return False
    # the CALIBRATED caps (leaf budget, record width) route through the
    # policy table; structural exclusions above never do
    return resolve("deep_layout",
                   {"num_leaves": L,
                    "record_bytes": 9 + num_features * bin_itemsize}
                   ) == "layout"


def phase_plan(depth_cap: int, num_leaves: int, nat_live: bool):
    """(d_switch, P_narrow, P_full) for the two-phase level loop — the ONE
    definition of the phase boundary, shared with train._comm_stats so the
    observability accounting mirrors the grower's actual program (ADVICE
    r4).  The switch sits at depth 5 (<= 16 candidates = _NAT_SLOTS) when
    the natural-order pass is live so level 4 rides it too, else at the
    measured depth-4 boundary."""
    P_full = min(1 << (depth_cap - 1), num_leaves - 1)
    d_cut = 5 if nat_live else 4
    d_switch = d_cut if (depth_cap > d_cut and P_full > (1 << (d_cut - 1))) \
        else depth_cap
    P_narrow = min(1 << (d_switch - 1), num_leaves - 1)
    return d_switch, P_narrow, P_full


def grow_tree_levelwise(
    params: Params,
    total_bins: int,
    Xb: jnp.ndarray,
    g: jnp.ndarray,
    h: jnp.ndarray,
    bag_mask: jnp.ndarray,
    feat_mask: jnp.ndarray,
    is_cat_feat: jnp.ndarray,
    *,
    has_cat: bool = False,
    axis_name: str | None = None,
    platform: str | None = None,
    learn_missing: bool = False,
    root_hist: jnp.ndarray | None = None,
    bundled_mask: jnp.ndarray | None = None,
) -> dict[str, Any]:
    p = params
    N, F = Xb.shape
    B = int(total_bins)
    L = p.effective_num_leaves
    M = p.max_nodes
    depth_cap = p.max_depth
    assert depth_cap > 0, "levelwise growth requires max_depth > 0"

    # wired gate FIRST (r10): a layout-wired tree is wired from level 0
    # (root-anchored layout, no shallow->deep handoff) and never touches
    # the plan-path record table or the natural-order tiles — skip
    # building both (the record table alone is ~20 B/row of HBM)
    use_layout = deep_layout_supported(p, F, B, Xb.dtype.itemsize, platform)

    # one per-TREE record table [g, h, X] for the Pallas levels: every
    # level's segmented histogram then pays ONE row gather instead of an X
    # gather + a g/h gather (pallas_hist.make_records)
    from dryad_tpu.engine.histogram import resolve_backend

    records = None
    nat_tiles = None
    if not use_layout and resolve_backend(p.hist_backend, segmented=True,
                                          platform=platform) == "pallas":
        from dryad_tpu.engine import pallas_hist

        if pallas_hist.supports(B):
            records = pallas_hist.make_records(Xb, g, h)
            # shallow-level natural-order pass, gated on the GLOBAL
            # matrix size (pallas_hist.maybe_natural_tiles documents why)
            nat_tiles = pallas_hist.maybe_natural_tiles(Xb, B, axis_name)

    def pallas_hist_NAT_SLOTS():
        from dryad_tpu.engine import pallas_hist

        return pallas_hist._NAT_SLOTS

    from dryad_tpu.engine.grower import _monotone_array

    mono = _monotone_array(p, F)

    def best(hist, G, H, C, allow, lo, hi):
        return find_best_split(
            hist, G, H, C,
            lambda_l2=p.lambda_l2,
            min_child_weight=p.min_child_weight,
            min_data_in_leaf=p.min_data_in_leaf,
            min_split_gain=p.min_split_gain,
            feat_mask=feat_mask,
            is_cat_feat=is_cat_feat,
            allow=allow,
            has_cat=has_cat,
            monotone=mono,
            lo=lo,
            hi=hi,
            learn_missing=learn_missing,
            bundled_mask=bundled_mask,
        )

    # ---- histogram-reduction arm (r16): fused psum vs feature-parallel ------
    # reduce-scatter.  The gate (config.hist_reduce_resolved) is a pure
    # function of (params, F/B shape, shard count) — same-program rule.
    # On the feature arm every per-LEVEL builder reduce-scatters a static
    # contiguous feature partition (each shard owns Fs = ceil(F/n) fully
    # reduced columns, bitwise equal to the psum's slice), the split scan
    # runs on the owned slice only (find_best_split_sliced over sliced
    # masks), and one tiny per-level all_gather of packed records
    # (combine_best_splits) makes every shard pick the fused scan's
    # winner.  The ROOT stays on the fused psum + full scan: root_stats
    # reads feature 0's bins (only shard 0 would own them) and the root
    # is one slot — its payload is noise next to the P-wide levels.
    from dryad_tpu.config import hist_reduce_resolved
    from dryad_tpu.engine import distributed as _dist
    from dryad_tpu.engine.split import find_best_split_sliced

    n_shards = _dist.axis_shards(axis_name)
    hr_mode = hist_reduce_resolved(p, F, B, n_shards)
    feat_par = hr_mode == "feature"
    FH = _dist.feature_slice_width(F, n_shards) if feat_par else F
    if feat_par:
        f_off = _dist.feature_shard_offset(axis_name, F)
        fmask_s = _dist.feature_shard_slice(feat_mask, axis_name)
        iscat_s = _dist.feature_shard_slice(is_cat_feat, axis_name)
        mono_s = (_dist.feature_shard_slice(mono, axis_name)
                  if mono is not None else None)
        bund_s = (_dist.feature_shard_slice(bundled_mask, axis_name)
                  if bundled_mask is not None else None)

        def best_sliced(hist, G, H, C, lo, hi):
            return find_best_split_sliced(
                hist, G, H, C,
                feat_offset=f_off,
                num_features_total=F,
                lambda_l2=p.lambda_l2,
                min_child_weight=p.min_child_weight,
                min_data_in_leaf=p.min_data_in_leaf,
                feat_mask=fmask_s,
                is_cat_feat=iscat_s,
                has_cat=has_cat,
                monotone=mono_s,
                lo=lo,
                hi=hi,
                learn_missing=learn_missing,
                bundled_mask=bund_s,
            )

    def level_scan(ch_hist, ch_G, ch_H, ch_C, allow, ch_lo, ch_hi):
        """One level's children split finding — per-arm: the fused full
        scan, or sliced scan + replicated combine (ONE all_gather for the
        whole candidate batch)."""
        if not feat_par:
            return jax.vmap(best)(ch_hist, ch_G, ch_H, ch_C, allow,
                                  ch_lo, ch_hi)
        loc = jax.vmap(best_sliced)(ch_hist, ch_G, ch_H, ch_C, ch_lo, ch_hi)
        return _dist.combine_best_splits(
            loc, axis_name, allow=allow,
            min_split_gain=p.min_split_gain, has_cat=has_cat)

    # ---- root (shared canonical construction) --------------------------------
    # ALL rows are partitioned (bag gates histograms only) so the final
    # row_slot yields each row's leaf without a separate traversal pass;
    # derived from bag_mask to inherit the shard's varying-manual-axes
    row_slot = jnp.where(bag_mask, 0, 0).astype(jnp.int32)
    hist0 = root_hist if root_hist is not None else build_hist(
        Xb, g, h, bag_mask, B,
        rows_per_chunk=p.rows_per_chunk, axis_name=axis_name,
        precision=p.hist_precision, backend=p.hist_backend,
        platform=platform)
    G0, H0, C0 = root_stats(hist0)
    ninf, pinf = jnp.float32(-jnp.inf), jnp.float32(jnp.inf)
    root = best(hist0, G0, H0, C0,
                (jnp.int32(0) < depth_cap) & (C0 >= 2 * p.min_data_in_leaf),
                ninf, pinf)
    Bc = root.cat_mask.shape[0]

    slot_node = jnp.full((L,), -1, jnp.int32).at[0].set(0)
    slot_gain = jnp.full((L,), NEG_INF, jnp.float32).at[0].set(root.gain)
    slot_G = jnp.zeros((L,), jnp.float32).at[0].set(G0)
    slot_H = jnp.zeros((L,), jnp.float32).at[0].set(H0)
    slot_C = jnp.zeros((L,), jnp.float32).at[0].set(C0)
    slot_depth = jnp.zeros((L,), jnp.int32)
    slot_lo = jnp.full((L,), ninf, jnp.float32)
    slot_hi = jnp.full((L,), pinf, jnp.float32)
    sp_feature = jnp.full((L,), -1, jnp.int32).at[0].set(root.feature)
    sp_thresh = jnp.zeros((L,), jnp.int32).at[0].set(root.threshold)
    sp_GL = jnp.zeros((L,), jnp.float32).at[0].set(root.g_left)
    sp_HL = jnp.zeros((L,), jnp.float32).at[0].set(root.h_left)
    sp_CL = jnp.zeros((L,), jnp.float32).at[0].set(root.c_left)
    sp_catmask = jnp.zeros((L, Bc), bool).at[0].set(root.cat_mask)
    sp_dleft = jnp.ones((L,), bool).at[0].set(root.default_left)
    # feature arm: the carried histogram buffer holds each shard's OWNED
    # slice only (an n-fold HBM saving to boot); the replicated root hist
    # is sliced once here so level-0 subtraction stays slice-local
    hist0_loc = (_dist.feature_shard_slice(hist0, axis_name, axis=1)
                 if feat_par else hist0)
    hists = jnp.zeros((L, 3, FH, B), jnp.float32).at[0].set(hist0_loc)

    cover_arr = jnp.zeros((M,), jnp.float32).at[0].set(C0)
    feature = jnp.full((M,), -1, jnp.int32)
    threshold = jnp.zeros((M,), jnp.int32)
    gain_arr = jnp.zeros((M,), jnp.float32)
    left = jnp.zeros((M,), jnp.int32)
    right = jnp.zeros((M,), jnp.int32)
    is_cat_arr = jnp.zeros((M,), bool)
    cat_nodes = jnp.zeros((M, Bc), bool)
    node_dleft = jnp.ones((M,), bool)
    num_nodes = jnp.int32(1)
    splits_done = jnp.int32(0)
    max_depth = jnp.int32(0)

    # ---- levels: two fori_loop phases with level-appropriate widths ----------
    # A Python unroll over levels would multiply the XLA program by depth_cap
    # (pathological remote compile times); a single fori_loop must run EVERY
    # level at the deepest level's width P (the per-level cost of the
    # candidate machinery, tile plan and vmapped split scan all scale with
    # P).  Two phases split the difference: shallow levels run narrow, deep
    # levels at the full width — one extra traced body, most of the
    # narrow-level savings.  The switch sits at depth 5 (<= 16 candidates)
    # when the natural-order pass is live so level 4 rides it too
    # (_NAT_SLOTS = 16; sort+gather-free beats the plan path ~70 ms/level
    # at 10M), else at the measured depth-4 boundary.
    d_switch, P_narrow, P_full = phase_plan(depth_cap, L,
                                            nat_tiles is not None)

    # ---- wired (leaf-ordered layout) static plan -----------------------------
    # The gate is row-count free (same program at every shard count); the
    # SHAPES below come from the local row count, as every shard-local
    # buffer's do.  Since r10 the layout is live from level 0, so BOTH
    # phases get a selection bound at their own candidate width.
    from dryad_tpu.engine import leafperm

    # the ONE exact-f32-counts / single-device predicate, shared by the
    # wired plan's half bound and the legacy arm's bound_ok below — the
    # two must never drift (an unsafe half-sized n_sel_tiles silently
    # truncates histograms, hist_from_layout contract)
    half_bound_ok = axis_name is None and N < (1 << 24)
    n_buf_tiles = n_sel_narrow = n_sel_full = 0
    if use_layout:
        Tl = leafperm._TILE_ROWS
        n_buf_tiles = leafperm.wired_tiles_bound(-(-N // Tl), L)
        if p.hist_subtraction:
            # smaller children cover <= half the (in-bag) rows on a single
            # device (same argument as bound_ok below); under shard_map or
            # past 2^24 rows no bound applies and the whole-layout tile
            # count is the only safe cap (shared bound helper — see doc)
            n_sel_narrow = leafperm.wired_sel_tiles_bound(
                -(-N // Tl), n_buf_tiles, P_narrow, half=half_bound_ok)
            n_sel_full = leafperm.wired_sel_tiles_bound(
                -(-N // Tl), n_buf_tiles, P_full, half=half_bound_ok)
        else:
            # non-subtraction (r10 lift) histograms BOTH children in one
            # 2P-column pass — the selection covers every live row, so
            # only the whole-buffer bound applies
            n_sel_narrow = leafperm.wired_sel_tiles_bound(
                -(-N // Tl), n_buf_tiles, 2 * P_narrow, half=False)
            n_sel_full = leafperm.wired_sel_tiles_bound(
                -(-N // Tl), n_buf_tiles, 2 * P_full, half=False)

    st = {
        "row_slot": row_slot, "slot_node": slot_node, "slot_gain": slot_gain,
        "slot_G": slot_G, "slot_H": slot_H, "slot_C": slot_C,
        "slot_depth": slot_depth, "slot_lo": slot_lo, "slot_hi": slot_hi,
        "sp_feature": sp_feature,
        "sp_thresh": sp_thresh, "sp_GL": sp_GL, "sp_HL": sp_HL,
        "sp_CL": sp_CL, "sp_catmask": sp_catmask, "sp_dleft": sp_dleft,
        "hists": hists,
        "feature": feature, "threshold": threshold, "gain": gain_arr,
        "left": left, "right": right, "is_cat": is_cat_arr,
        "cat_nodes": cat_nodes, "node_dleft": node_dleft, "cover": cover_arr,
        "num_nodes": num_nodes,
        "splits_done": splits_done, "max_depth": max_depth,
    }
    def make_level_body(P, use_nat=False, use_layout=False, n_sel_tiles=0):
        def level_body(d, st):
            (row_slot, slot_node, slot_gain, slot_G, slot_H, slot_C, slot_depth,
             slot_lo, slot_hi,
             sp_feature, sp_thresh, sp_GL, sp_HL, sp_CL, sp_catmask, sp_dleft,
             hists,
             feature, threshold, gain_arr, left, right, is_cat_arr, cat_nodes,
             node_dleft, num_nodes, splits_done, max_depth) = (
                st["row_slot"], st["slot_node"], st["slot_gain"], st["slot_G"],
                st["slot_H"], st["slot_C"], st["slot_depth"],
                st["slot_lo"], st["slot_hi"], st["sp_feature"],
                st["sp_thresh"], st["sp_GL"], st["sp_HL"], st["sp_CL"],
                st["sp_catmask"], st["sp_dleft"],
                st["hists"], st["feature"], st["threshold"],
                st["gain"], st["left"], st["right"], st["is_cat"], st["cat_nodes"],
                st["node_dleft"], st["num_nodes"], st["splits_done"],
                st["max_depth"])
            at_level = (slot_depth == d) & (slot_gain > NEG_INF) & (slot_node >= 0)
            # gain-descending order, stable => lowest slot id wins ties, exactly
            # the CPU trainer's repeated first-max argmax sequence
            # dryadlint: disable=wired-grower-sort -- (L,)-slot gain ranking, L <= 512; not a row sort (rows never sort on the wired path)
            order = jnp.argsort(jnp.where(at_level, -slot_gain, jnp.inf), stable=True)
            cand = order[:P].astype(jnp.int32)
            budget_left = (L - 1) - splits_done
            do = at_level[cand] & (jnp.arange(P) < budget_left)
            n_do = jnp.sum(do.astype(jnp.int32))

            sj = cand
            parent_node = slot_node[sj]
            sf = sp_feature[sj]
            thr = sp_thresh[sj]
            GL, HL, CL = sp_GL[sj], sp_HL[sj], sp_CL[sj]
            Gp, Hp, Cp = slot_G[sj], slot_H[sj], slot_C[sj]
            GR, HR, CR = Gp - GL, Hp - HL, Cp - CL
            cat_split = (is_cat_feat[jnp.maximum(sf, 0)] & do) if has_cat else jnp.zeros((P,), bool)

            # slot/node allocation in execution (gain) order, as the CPU does
            ks = splits_done + jnp.cumsum(do.astype(jnp.int32)) - do.astype(jnp.int32)
            right_slot = jnp.where(do, ks + 1, L).astype(jnp.int32)
            left_id = jnp.where(do, num_nodes + 2 * (ks - splits_done), 0).astype(jnp.int32)
            right_id = left_id + 1

            pidx = jnp.where(do, parent_node, M)
            feature = feature.at[pidx].set(sf, mode="drop")
            gain_arr = gain_arr.at[pidx].set(
                jnp.where(do, slot_gain[sj], 0.0), mode="drop")
            threshold = threshold.at[pidx].set(jnp.where(cat_split, 0, thr), mode="drop")
            left = left.at[pidx].set(left_id, mode="drop")
            right = right.at[pidx].set(right_id, mode="drop")
            is_cat_arr = is_cat_arr.at[pidx].set(cat_split, mode="drop")
            cat_nodes = cat_nodes.at[pidx].set(
                jnp.where(cat_split[:, None], sp_catmask[sj], False), mode="drop"
            )
            node_dleft = node_dleft.at[pidx].set(sp_dleft[sj] | cat_split,
                                                 mode="drop")
            # per-node cover (training row count) for pred_contrib: the
            # children's counts come off the parent-histogram prefix
            cover_arr = st["cover"].at[
                jnp.where(do, left_id, M)].set(CL, mode="drop")
            cover_arr = cover_arr.at[
                jnp.where(do, right_id, M)].set(CR, mode="drop")

            # ---- row partition: every splitting leaf in one vectorized pass -----
            # Two measured rules shape this block (exp_level_bisect.py, 10M):
            # a per-row column gather (take_along_axis into the (N, F)
            # matrix) costs ~320 ms/level — random element access — while a
            # masked reduce over the feature axis reads the matrix
            # CONTIGUOUSLY and costs ~30 ms; and each (N,)-gather from a
            # small per-slot table costs ~30 ms, so the five per-slot
            # lookups ride ONE packed two-word record gather instead.
            # Integer/bool results are bit-identical to the gather
            # formulation, so every parity invariant is untouched.
            rs = jnp.minimum(row_slot, L - 1)
            rec_t = None
            if B <= (1 << 13) and L < (1 << 16):
                # cat_split above is already the per-candidate cat flag (its
                # & do is a no-op here: records only scatter where do holds)
                cat_c = cat_split if has_cat else jnp.zeros((P,), bool)
                w0_c = ((jnp.uint32(1) << 31)
                        | (sp_dleft[sj].astype(jnp.uint32) << 30)
                        | (cat_c.astype(jnp.uint32) << 29)
                        | (jnp.clip(thr, 0, B - 1).astype(jnp.uint32) << 16)
                        | right_slot.astype(jnp.uint32))
                rec_t = jnp.zeros((L + 1, 2), jnp.uint32).at[
                    jnp.where(do, sj, L + 1)].set(
                        jnp.stack([w0_c,
                                   jnp.maximum(sf, 0).astype(jnp.uint32)],
                                  axis=1), mode="drop")

                def packed_route(slot_idx, bins_of, rr=None):
                    """Per-row split routing off the packed per-slot table:
                    (splits?, goes-left?, packed word).  Shared by the
                    natural-order partition and the layout side derivation
                    so the two can never disagree on a row (identical
                    integer/bool arithmetic).  ``rr`` lets the caller pass
                    a pre-composed per-row record (one big gather instead
                    of two chained ones — the CLAUDE.md pack-the-lookups
                    rule); ``slot_idx`` is then only consulted for the
                    categorical bitset row."""
                    if rr is None:
                        rr = rec_t[jnp.minimum(slot_idx, L)]  # ONE gather
                    w0r = rr[:, 0]
                    rf = rr[:, 1].astype(jnp.int32)
                    bins_rf = bins_of(rf)
                    thr_r = ((w0r >> 16)
                             & jnp.uint32(0x1FFF)).astype(jnp.int32)
                    gl = bins_rf <= thr_r
                    if learn_missing:
                        gl &= ((w0r >> 30) & 1).astype(bool) | (bins_rf > 0)
                    if has_cat:
                        cat_row = sp_catmask[jnp.minimum(slot_idx, L - 1),
                                             jnp.minimum(bins_rf, Bc - 1)]
                        gl = jnp.where(((w0r >> 29) & 1).astype(bool),
                                       cat_row, gl)
                    return ((w0r >> 31) != 0), gl, w0r

                do_n, left_n, w0r = packed_route(
                    rs, lambda rf: select_bins(Xb, rf))
                row_do = do_n & (row_slot < L)
                row_slot = jnp.where(
                    row_do & ~left_n,
                    (w0r & jnp.uint32(0xFFFF)).astype(jnp.int32), row_slot)
            else:
                # exotic shapes (bins > 8192 or leaves >= 65536) exceed the
                # packed-word budget: keep the gather formulation (static
                # per-config choice, so every shard still runs one program)
                slot_do = jnp.zeros((L,), bool).at[
                    jnp.where(do, sj, L)].set(True, mode="drop")
                slot_right = jnp.full((L,), L, jnp.int32).at[
                    jnp.where(do, sj, L)].set(right_slot, mode="drop")
                row_do = slot_do[rs] & (row_slot < L)
                rf = jnp.maximum(sp_feature[rs], 0)
                bins_rf = jnp.take_along_axis(
                    Xb, rf[:, None].astype(jnp.int32), axis=1)[:, 0]
                bins_rf = bins_rf.astype(jnp.int32)
                go_left = bins_rf <= sp_thresh[rs]
                if learn_missing:
                    go_left &= sp_dleft[rs] | (bins_rf > 0)
                if has_cat:
                    cat_row = sp_catmask[rs, jnp.minimum(bins_rf, Bc - 1)]
                    go_left = jnp.where(is_cat_feat[rf], cat_row, go_left)
                row_slot = jnp.where(row_do & ~go_left, slot_right[rs],
                                     row_slot)

            # ---- one batched histogram pass for all smaller children ------------
            left_smaller = CL <= CR
            if use_layout:
                # WIRED level (r6 deep phase, r10 everywhere): no
                # per-level sort, no full-N record gather.  Sides come
                # straight off the carried leaf-ordered layout's records
                # via the SAME packed_route arithmetic the natural-order
                # partition used above (the two agree on every row —
                # identical integer/bool math), one stable per-tile MXU
                # compaction moves the rows, and the children read back
                # as contiguous tile runs.
                lay_rec = st["lay_rec"]
                lay_tr = st["lay_tile_run"]
                lay_rs = st["lay_run_slot"]
                row_run = jnp.repeat(lay_tr, leafperm._TILE_ROWS)
                # compose run -> packed record at the (L,) level, then pay
                # ONE per-row small-table gather (two chained (n_buf*T,)
                # gathers cost ~2x — the CLAUDE.md pack-the-lookups rule);
                # dead runs (lay_rs = L) compose to rec_t[L] = zeros, so
                # their rows route pass-through — and carry no valid rows
                # anyway (absorbed segments hold only sentinels)
                rr_lay = rec_t[jnp.minimum(lay_rs, L)][row_run]
                slot_lay = lay_rs[row_run] if has_cat else None
                _, _, valid_lay, xb_lay = leafperm.unpack_layout_records(
                    lay_rec, F, Xb.dtype)
                do_lay, left_lay, _ = packed_route(
                    slot_lay, lambda rf: select_bins(xb_lay, rf),
                    rr=rr_lay)
                side = jnp.where(
                    valid_lay,
                    jnp.where(do_lay & ~left_lay, 1, 0),
                    2).astype(jnp.int32)
                pos, dstl, dstr, base_l, base_r, _ = leafperm.level_moves(
                    lay_tr, side, L)
                lay_rec = leafperm.permute_records(
                    lay_rec, pos, dstl, dstr, lay_tr.shape[0],
                    platform=platform, axis_name=axis_name)
                # slot -> run inverse BEFORE advancing (candidates are
                # parents of this level's move); dead runs scatter to
                # L + 1 — OUT of the (L+1,) table so mode="drop" really
                # drops them (index L is in range and would overwrite the
                # sentinel cell the rj clamp below relies on)
                slot_run = jnp.full((L + 1,), L, jnp.int32).at[
                    jnp.where(lay_rs < L, lay_rs, L + 1)].set(
                        jnp.arange(L, dtype=jnp.int32), mode="drop")
                slot_do_t = (rec_t[:, 0] >> 31) != 0   # (L+1,) dense tables
                slot_right_t = (rec_t[:, 0]
                                & jnp.uint32(0xFFFF)).astype(jnp.int32)
                lrs_c = jnp.minimum(lay_rs, L)
                run_do = slot_do_t[lrs_c] & (lay_rs < L)
                run_right = slot_right_t[lrs_c]
                lay_tr_new, lay_rs_new = leafperm.advance_runs(
                    lay_rs, run_do, run_right, base_l, base_r,
                    lay_tr.shape[0])
                # children = contiguous segments of the NEW layout
                rj = slot_run[jnp.minimum(sj, L)]
                rjc = jnp.minimum(rj, L - 1)
                lt_l = base_l[1:] - base_l[:-1]
                lt_r = base_r[1:] - base_r[:-1]
                sel_ok = do & (rj < L)
                if p.hist_subtraction:
                    seg_first = jnp.where(
                        sel_ok,
                        jnp.where(left_smaller, base_l[rjc], base_r[rjc]), 0)
                    seg_nt = jnp.where(
                        sel_ok,
                        jnp.where(left_smaller, lt_l[rjc], lt_r[rjc]), 0)
                    hist_small = leafperm.hist_from_layout(
                        lay_rec, seg_first, seg_nt, P, B, F, Xb.dtype,
                        n_sel_tiles, axis_name=axis_name, platform=platform,
                        hist_reduce=hr_mode)
                    hist_large = hists[sj] - hist_small
                    ls = left_smaller[:, None, None, None]
                    hist_l = jnp.where(ls, hist_small, hist_large)
                    hist_r = jnp.where(ls, hist_large, hist_small)
                else:
                    # non-subtraction lift (r10): BOTH children in ONE
                    # 2P-column pass — columns [left 0..P-1 | right
                    # P..2P-1], every live row read exactly once (the
                    # legacy arm pays a small pass + a full
                    # build_hist_multi)
                    segf2 = jnp.concatenate([
                        jnp.where(sel_ok, base_l[rjc], 0),
                        jnp.where(sel_ok, base_r[rjc], 0)])
                    segn2 = jnp.concatenate([
                        jnp.where(sel_ok, lt_l[rjc], 0),
                        jnp.where(sel_ok, lt_r[rjc], 0)])
                    h2 = leafperm.hist_from_layout(
                        lay_rec, segf2, segn2, 2 * P, B, F, Xb.dtype,
                        n_sel_tiles, axis_name=axis_name, platform=platform,
                        hist_reduce=hr_mode)
                    hist_l, hist_r = h2[:P], h2[P:]
                st = dict(st, lay_rec=lay_rec, lay_tile_run=lay_tr_new,
                          lay_run_slot=lay_rs_new)
            else:
                small_slot = jnp.where(left_smaller, sj, right_slot)
                large_slot = jnp.where(left_smaller, right_slot, sj)
                # non-do candidates scatter to L+1 (out of bounds, dropped);
                # out-of-bag rows are excluded by the explicit bag_mask gate
                # below — row_slot itself stays in [0, L-1] for every row
                # now that the partition routes the whole dataset
                colof = jnp.full((L + 1,), P, jnp.int32).at[
                    jnp.where(do, small_slot, L + 1)].set(
                        jnp.arange(P, dtype=jnp.int32), mode="drop")
                # bag gates the histogram selection; out-of-bag rows are
                # partitioned but never accumulated
                smallsel = jnp.where(bag_mask,
                                     colof[jnp.minimum(row_slot, L)], P)
                # Single device, smaller children cover at most half the
                # rows (min(left,right) <= parent/2, parents disjoint) ->
                # half the tile grid.  Under shard_map the smaller child is
                # chosen on GLOBAL counts and one shard's share of it may
                # exceed half that shard, so no bound applies there; ditto
                # above 2^24 rows, where the fp32 histogram counts backing
                # the smaller-child choice stop being exact.
                bound_ok = half_bound_ok
                if use_nat:
                    from dryad_tpu.engine import pallas_hist

                    hist_small = pallas_hist.build_hist_small(
                        nat_tiles, g, h, smallsel, P, B, F,
                        axis_name=axis_name, platform=platform,
                        hist_reduce=hr_mode)
                else:
                    # exact per-column counts (smaller-child C off the
                    # parent histogram, integer-exact in f32 below 2**24)
                    # admit the pad-injected aligned sort inside
                    # build_hist_segmented — the plan's alignment gather
                    # drops out; single-device only, where the counts
                    # describe the whole selection
                    small_cnt = (jnp.where(do,
                                           jnp.where(left_smaller, CL, CR),
                                           0.0).astype(jnp.int32)
                                 if bound_ok else None)
                    hist_small = build_hist_segmented(
                        Xb, g, h, smallsel, P, B,
                        rows_per_chunk=p.rows_per_chunk, axis_name=axis_name,
                        precision=p.hist_precision, backend=p.hist_backend,
                        rows_bound=(N // 2 + 1) if bound_ok else None,
                        platform=platform, records=records,
                        sel_counts=small_cnt,
                        # staged prefixes only pay when the leaf budget caps
                        # deep levels (fills provably collapse); a full tree
                        # keeps every prefix ~100% and the extra gather
                        # branches only bloat (remote) compile
                        stage_gather=(L - 1) < (1 << (depth_cap - 1)),
                        hist_reduce=hr_mode,
                    )
                if p.hist_subtraction:
                    hist_large = hists[sj] - hist_small
                else:
                    largesel = jnp.full((L + 1,), P, jnp.int32).at[
                        jnp.where(do, large_slot, L + 1)].set(
                            jnp.arange(P, dtype=jnp.int32), mode="drop")
                    hist_large = build_hist_multi(
                        Xb, g, h,
                        jnp.where(bag_mask,
                                  largesel[jnp.minimum(row_slot, L)], P),
                        P, B,
                        rows_per_chunk=p.rows_per_chunk, axis_name=axis_name,
                        precision=p.hist_precision, hist_reduce=hr_mode,
                    )
                ls = left_smaller[:, None, None, None]
                hist_l = jnp.where(ls, hist_small, hist_large)
                hist_r = jnp.where(ls, hist_large, hist_small)
            hists = hists.at[jnp.where(do, sj, L)].set(hist_l, mode="drop")
            hists = hists.at[jnp.where(do, right_slot, L)].set(hist_r, mode="drop")

            # ---- children stats + their best splits (vmapped finder) ------------
            lo_p, hi_p = slot_lo[sj], slot_hi[sj]
            if mono is not None:
                lo_l, hi_l, lo_r, hi_r = child_bounds(
                    mono, sf, GL, HL, GR, HR, jnp.float32(p.lambda_l2), lo_p, hi_p)
            else:
                lo_l = lo_r = lo_p
                hi_l = hi_r = hi_p

            ch_slot = jnp.concatenate([sj, right_slot])
            ch_do = jnp.concatenate([do, do])
            ch_node = jnp.concatenate([left_id, right_id])
            ch_hist = jnp.concatenate([hist_l, hist_r])
            ch_G = jnp.concatenate([GL, GR])
            ch_H = jnp.concatenate([HL, HR])
            ch_C = jnp.concatenate([CL, CR])
            ch_lo = jnp.concatenate([lo_l, lo_r])
            ch_hi = jnp.concatenate([hi_l, hi_r])
            allow = ch_do & (d + 1 < depth_cap) & (ch_C >= 2 * p.min_data_in_leaf)
            res = level_scan(ch_hist, ch_G, ch_H, ch_C, allow, ch_lo, ch_hi)

            cidx = jnp.where(ch_do, ch_slot, L)
            slot_node = slot_node.at[cidx].set(ch_node, mode="drop")
            slot_gain = slot_gain.at[cidx].set(res.gain, mode="drop")
            slot_G = slot_G.at[cidx].set(ch_G, mode="drop")
            slot_H = slot_H.at[cidx].set(ch_H, mode="drop")
            slot_C = slot_C.at[cidx].set(ch_C, mode="drop")
            slot_depth = slot_depth.at[cidx].set(d + 1, mode="drop")
            slot_lo = slot_lo.at[cidx].set(ch_lo, mode="drop")
            slot_hi = slot_hi.at[cidx].set(ch_hi, mode="drop")
            sp_feature = sp_feature.at[cidx].set(res.feature, mode="drop")
            sp_thresh = sp_thresh.at[cidx].set(res.threshold, mode="drop")
            sp_GL = sp_GL.at[cidx].set(res.g_left, mode="drop")
            sp_HL = sp_HL.at[cidx].set(res.h_left, mode="drop")
            sp_CL = sp_CL.at[cidx].set(res.c_left, mode="drop")
            sp_catmask = sp_catmask.at[cidx].set(res.cat_mask, mode="drop")
            sp_dleft = sp_dleft.at[cidx].set(res.default_left, mode="drop")

            splits_done = splits_done + n_do
            num_nodes = num_nodes + 2 * n_do
            max_depth = jnp.where(n_do > 0, (d + 1).astype(jnp.int32), max_depth)

            out = {
                "row_slot": row_slot, "slot_node": slot_node,
                "slot_gain": slot_gain, "slot_G": slot_G, "slot_H": slot_H,
                "slot_C": slot_C, "slot_depth": slot_depth,
                "slot_lo": slot_lo, "slot_hi": slot_hi,
                "sp_feature": sp_feature, "sp_thresh": sp_thresh, "sp_GL": sp_GL,
                "sp_HL": sp_HL, "sp_CL": sp_CL, "sp_catmask": sp_catmask,
                "sp_dleft": sp_dleft,
                "hists": hists, "feature": feature, "threshold": threshold,
                "gain": gain_arr, "left": left, "right": right,
                "is_cat": is_cat_arr, "cat_nodes": cat_nodes,
                "node_dleft": node_dleft, "cover": cover_arr,
                "num_nodes": num_nodes, "splits_done": splits_done,
                "max_depth": max_depth,
            }
            if use_layout:
                out["lay_rec"] = st["lay_rec"]
                out["lay_tile_run"] = st["lay_tile_run"]
                out["lay_run_slot"] = st["lay_run_slot"]
            return out
        return level_body

    if use_layout:
        # ---- root-anchored layout (r10): live from level 0 ------------------
        # The natural-order record buffer IS the root layout (one
        # segment, no sort, no gather); out-of-bag rows enter as
        # sentinel-flagged records and are dropped by level 0's move.
        # The shallow->deep handoff sort+gather per tree is GONE — the
        # natural-order row_slot (still maintained above for the final
        # row_leaf) keeps routing out-of-bag rows.
        rec_nat = leafperm.make_layout_records(Xb, g, h, valid=bag_mask)
        lay_rec, lay_tr, lay_rs = leafperm.natural_root_layout(
            rec_nat, L, n_buf_tiles, axis_name=axis_name)
        st = dict(st, lay_rec=lay_rec, lay_tile_run=lay_tr,
                  lay_run_slot=lay_rs)
    st = jax.lax.fori_loop(
        0, d_switch,
        make_level_body(P_narrow,
                        use_nat=nat_tiles is not None
                        and P_narrow <= pallas_hist_NAT_SLOTS(),
                        use_layout=use_layout, n_sel_tiles=n_sel_narrow),
        st)
    if d_switch < depth_cap:
        st = jax.lax.fori_loop(
            d_switch, depth_cap,
            make_level_body(P_full,
                            use_nat=not use_layout
                            and nat_tiles is not None
                            and P_full <= pallas_hist_NAT_SLOTS(),
                            use_layout=use_layout, n_sel_tiles=n_sel_full),
            st)

    # ---- finalize leaf values + node bitsets (shared helpers) ----------------
    value = finalize_leaf_values(
        p, M, st["slot_node"], st["slot_G"], st["slot_H"],
        jnp.zeros((M,), jnp.float32),
        slot_lo=st["slot_lo"] if mono is not None else None,
        slot_hi=st["slot_hi"] if mono is not None else None,
    )
    cat_bitset = pack_cat_bitset(st["cat_nodes"], M)

    return {
        "feature": st["feature"],
        "threshold": st["threshold"],
        "left": st["left"],
        "right": st["right"],
        "value": value,
        "gain": st["gain"],
        "is_cat": st["is_cat"],
        "cat_bitset": cat_bitset,
        "default_left": st["node_dleft"],
        "cover": st["cover"],
        "max_depth": st["max_depth"],
        # per-row leaf node id from the partition state (no re-traversal)
        "row_leaf": jnp.maximum(st["slot_node"], 0)[
            jnp.minimum(st["row_slot"], L - 1)],
    }
