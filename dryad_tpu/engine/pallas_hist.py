"""Pallas TPU histogram kernel — the native-kernel equivalent of the
reference's CUDA per-feature histogram builder (BASELINE.json:5; SURVEY.md
§2 #5, §7 step 3).

Why a hand-written kernel beats the XLA one-hot matmul (engine/histogram.py):

* **No HBM one-hot.**  XLA materializes the (rows, F*B) one-hot operand in
  HBM (hundreds of MB per chunk); here it is built in VMEM per row tile and
  consumed by the MXU inside the same kernel step.
* **Exact fp32 in one MXU pass.**  The MXU multiplies bf16 and accumulates
  f32.  The one-hot operand is 0/1 — exact in bf16 — so splitting each f32
  grad/hess into three bf16 limbs (truncated 8+8+8 mantissa bits) makes the
  products exact.  The XLA path needs ``Precision.HIGHEST`` (six passes)
  for the same accuracy because it cannot know one operand is exact.
  grad-hi/mid/lo, hess-hi/mid/lo and count ride as rows of one weight
  matrix, so "exact" costs exactly what "fast" would.
* **Leaf-segmented accumulation in VMEM.**  Rows arrive pre-grouped by
  leaf (tiles of one leaf are consecutive); the output block index is the
  tile's leaf id (scalar-prefetched), so Pallas keeps one leaf's partial
  histogram resident in VMEM across its tiles and spills it exactly once.

Hard-won lowering constraints baked into the design (measured on v5e):

* The MXU contraction must have a 128-row operand: ``w (8, T) @ onehot``
  lowers ~4x slower than ``w (128, T) @ onehot`` sliced back to 8 rows.
* Weight limbs must be split with *bitmask truncation*: the naive
  ``x - f32(bf16(x))`` is folded to zero by XLA's excess-precision
  simplifier under jit, and ``lax.reduce_precision`` lowers ~30x slower
  than bitwise ops here.
* Row tiles of 256 hit a pathological Mosaic path (~5x); use 512.
* Bin tiles are stored FEATURE-MAJOR ``(n_fb, n_tiles, Fc, T)`` — with the
  row dim T in lanes the HBM buffer has no lane padding; the row-major
  ``(T, Fc)`` alternative pads 8x under XLA's (8,128) tiling (12.9 GB on
  Epsilon shapes) and reads ~20x slower in-kernel.

Grid layout: ``(feature_chunks, row_tiles)`` — row tiles innermost so the
revisited output block (leaf, chunk) stays in VMEM while a leaf's tiles
stream through.  Feature chunking bounds the VMEM one-hot for wide data
(Epsilon: 2000 features — BASELINE.json:9).

The kernel is pure accumulation; the surrounding XLA program does the
cheap O(N) bookkeeping (leaf bucketing, gathers, weight limb splitting)
and the cross-device ``psum`` that replaces the reference's NCCL allreduce.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from dryad_tpu.engine import jax_compat

# weight rows: g_hi g_mid g_lo h_hi h_mid h_lo count (+ pad to the MXU tile)
_WROWS = 8
_MXU_M = 128          # weight rows padded to a full MXU tile (see module doc)
_LANE_BUDGET = 8192   # max Fc*Bp per chunk: bounds the one-hot SUBLANE dim
                      # (8 MB bf16 at T=512 in VMEM) AND the output block's
                      # lane dim (out_specs (1, _WROWS, Fc*Bp))
_TILE_ROWS = 512      # rows per tile (MXU K dim; 256 lowers pathologically)
# cap: Fc floors at 8 for sublane alignment, so Bp must satisfy
# 8 * Bp <= _LANE_BUDGET or the per-step one-hot exceeds the VMEM budget
_MAX_PALLAS_BINS = 1024


def supports(total_bins: int) -> bool:
    return int(total_bins) <= _MAX_PALLAS_BINS


def _interpret(platform: str | None = None) -> bool:
    return (platform or jax.default_backend()) == "cpu"


def _pow2_bins(B: int) -> int:
    """Bin dim padded to a power of two (>=16) for lane alignment."""
    return max(16, 1 << (B - 1).bit_length())


def _feature_chunk(F: int, Bp: int) -> int:
    """Features per chunk: a power of two (>= 8) so the kernel can recover
    the bin index from the tiled one-hot layout with a shift, bounded so
    Fc*Bp one-hot lanes fit the VMEM budget; Fc*Bp stays a multiple of 128
    (lane rule) since both factors are pow2 with product >= 128.

    Among the admissible sizes, pick the one minimizing the padded total
    ceil(F/Fc)*Fc — the largest pow2 is NOT always best (F=130 would pad
    97% at Fc=256 but only 5% at Fc=8)."""
    budget = max(8, _LANE_BUDGET // Bp)
    best, best_padded = 8, None
    fc = 8
    while fc <= budget:
        padded = -(-F // fc) * fc
        if best_padded is None or padded <= best_padded:
            best, best_padded = fc, padded   # ties -> larger fc (fewer chunks)
        if fc >= F:
            break
        fc *= 2
    return best


def _split3(x: jnp.ndarray):
    """f32 -> three bf16 limbs whose f32 sum reconstructs x exactly.

    Implemented by masking mantissa bits (truncation split), for two
    reasons: (a) XLA's excess-precision simplifier folds the naive
    ``x - f32(bf16(x))`` to zero inside jit, silently deleting the mid/lo
    limbs; (b) ``lax.reduce_precision`` survives jit but lowers ~30x slower
    than bitwise ops on this backend.  Masking the low 16 mantissa bits is
    exact, the residuals are exact f32 subtractions, and after two
    truncations the final residual fits bf16 exactly.
    """
    mask16 = jnp.uint32(0xFFFF0000)
    u = jax.lax.bitcast_convert_type(x, jnp.uint32)
    hi = jax.lax.bitcast_convert_type(u & mask16, jnp.float32)
    r1 = x - hi
    u1 = jax.lax.bitcast_convert_type(r1, jnp.uint32)
    mid = jax.lax.bitcast_convert_type(u1 & mask16, jnp.float32)
    lo = (r1 - mid).astype(jnp.bfloat16)
    return hi.astype(jnp.bfloat16), mid.astype(jnp.bfloat16), lo


def _pack_weights(g: jnp.ndarray, h: jnp.ndarray, valid: jnp.ndarray) -> jnp.ndarray:
    """(n_tiles, T) f32 grad/hess + validity -> (n_tiles, 8, T) bf16 rows.

    Only the 8 real weight rows hit HBM; the kernel zero-pads to the 128-row
    MXU tile in VMEM (the old (n_tiles, 128, T) buffer materialized ~1.3 GB
    of zeros per deep 10M-row level and the kernel re-read all of it)."""
    v = valid.astype(jnp.float32)
    gv = g.astype(jnp.float32) * v
    hv = h.astype(jnp.float32) * v
    cnt = v.astype(jnp.bfloat16)
    w = jnp.stack([*_split3(gv), *_split3(hv), cnt], axis=-2)
    return jnp.pad(w, ((0, 0), (0, _WROWS - w.shape[-2]), (0, 0)))


def _hist_kernel(tile_leaf_ref, tile_first_ref, tile_skip_ref, x_ref, w_ref,
                 o_ref, *, padded_bins: int):
    """One (feature-chunk, row-tile) step: w (128,T) @ one-hot (Fc*Bp,T)^T.

    Tiles arrive FEATURE-MAJOR (Fc, T): the row dim T sits in lanes, so the
    HBM tile buffer has no lane padding (a (T, Fc) layout with Fc < 128
    pads up to 8x under XLA's (8,128) tiling — 12.9 GB for Epsilon-shaped
    data — and reads ~20x slower in-kernel).  The one-hot is built in the
    matching sublane-tiled layout: ``pltpu.repeat`` TILES the bin-id block
    Bp times along sublanes (row r of the one-hot holds feature r mod Fc,
    bin r >> log2(Fc)); a shifted iota supplies the compared bin.  (The
    obvious 3-D reshape is an "unsupported shape cast" to Mosaic whenever
    Bp < 128; this layout needs no relayout at all.)  Both dot operands
    contract their trailing (lane) dim — the MXU consumes the transposed
    RHS natively.  The caller untangles the bin-major row order once,
    outside the kernel.

    ``tile_skip`` marks tiles with zero live rows (the plan's static grid
    covers the worst-case N/2 smaller-children bound, but real levels often
    select far less — every padding tile used to pay the full one-hot +
    MXU dot for an exact-zero contribution).  Skipped tiles do no compute;
    their in_specs also remap to block 0 so consecutive skips elide the
    DMA.  An empty leaf's mandatory first tile still zero-initializes its
    output block.
    """
    i = pl.program_id(1)
    first = tile_first_ref[i] == 1
    skip = tile_skip_ref[i] == 1

    @pl.when(first & skip)
    def _():
        o_ref[0] = jnp.zeros(o_ref.shape[1:], o_ref.dtype)

    @pl.when(jnp.logical_not(skip))
    def _():
        x = x_ref[0, 0].astype(jnp.int32)          # (Fc, T) uint8 -> i32
        Fc, T = x.shape
        Bp = padded_bins
        shift = Fc.bit_length() - 1                # Fc is a power of two
        x_rep = jax_compat.tile_repeat(x, Bp, axis=0)   # (Fc*Bp, T) tiled
        iota_b = jax.lax.broadcasted_iota(jnp.int32, (Fc * Bp, T), 0) >> shift
        onehot = (x_rep == iota_b).astype(jnp.bfloat16)
        # zero-pad the 8 weight rows to the 128-row MXU tile in VMEM (HBM
        # only ever holds the real rows — see _pack_weights)
        w = jnp.concatenate(
            [w_ref[0], jnp.zeros((_MXU_M - _WROWS, T), jnp.bfloat16)], axis=0)
        part = jax.lax.dot_general(
            w, onehot,
            (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )[:_WROWS]                                 # (8, Fc*Bp)

        @pl.when(first)
        def _():
            o_ref[0] = part

        @pl.when(jnp.logical_not(first))
        def _():
            o_ref[0] = o_ref[0] + part


@functools.partial(
    jax.jit, static_argnames=("num_cols", "total_bins", "num_features",
                              "axis_name", "platform")
)
def _hist_tiles(Xt, Wt, tile_leaf, tile_first, tile_skip, *, num_cols: int,
                total_bins: int, num_features: int,
                axis_name: str | None = None,
                platform: str | None = None) -> jnp.ndarray:
    """Core pallas_call: leaf-grouped tiles -> (P, 3, F, B) f32 histograms.

    Xt (n_fb, n_tiles, Fc, T) uint8 bin ids (feature-chunked, -padded; the
    kernel converts — u8 tiles move 4x fewer HBM bytes than the old i32),
    Wt (n_tiles, 8, T) bf16 weight limb rows, tile_leaf (n_tiles,)
    monotone non-decreasing leaf per tile, tile_first (n_tiles,) 1 on a
    leaf's first tile, tile_skip (n_tiles,) 1 on tiles with zero live rows
    (no compute, no fresh DMA — see _hist_kernel).  Every leaf in [0, P)
    must own at least one tile so its output block is written.

    ``axis_name`` must name the shard_map axis when tracing inside one —
    the per-shard partial histogram varies over it (vma) until the caller's
    psum.
    """
    n_fb, n_tiles, Fc, T = Xt.shape
    B = int(total_bins)
    P = int(num_cols)
    F = int(num_features)
    Bp = _pow2_bins(B)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(n_fb, n_tiles),
        in_specs=[
            # skipped tiles remap to block 0: consecutive skips keep the
            # same block index, so Pallas elides their input DMA entirely
            pl.BlockSpec((1, 1, Fc, T),
                         lambda j, i, tl, tf, sk: (j, i * (1 - sk[i]),
                                                   0, 0)),
            pl.BlockSpec((1, _WROWS, T),
                         lambda j, i, tl, tf, sk: (i * (1 - sk[i]),
                                                   0, 0)),
        ],
        out_specs=pl.BlockSpec((1, _WROWS, Fc * Bp),
                               lambda j, i, tl, tf, sk: (tl[i], 0, j)),
    )
    out_shape = jax_compat.shape_dtype_struct(
        (P, _WROWS, n_fb * Fc * Bp), jnp.float32, axis_name)
    out = pl.pallas_call(
        functools.partial(_hist_kernel, padded_bins=Bp),
        grid_spec=grid_spec,
        out_shape=out_shape,
        interpret=_interpret(platform),
    )(tile_leaf, tile_first, tile_skip, Xt, Wt)

    # kernel columns are (bin-major, feature-minor) per chunk — untangle
    out = (out.reshape(P, _WROWS, n_fb, Bp, Fc)
              .transpose(0, 1, 2, 4, 3)
              .reshape(P, _WROWS, n_fb * Fc, Bp))[:, :, :F, :B]
    hg = out[:, 0] + out[:, 1] + out[:, 2]
    hh = out[:, 3] + out[:, 4] + out[:, 5]
    hc = out[:, 6]
    return jnp.stack([hg, hh, hc], axis=1)         # (P, 3, F, B)


def _tiles_from_rows(X_rows: jnp.ndarray, n_tiles: int, T: int, B: int) -> jnp.ndarray:
    """(n_tiles*T, F) gathered bin rows -> feature-chunked (n_fb, n_tiles, Fc, T).

    Always a real transpose (T and Fc swap) — its cost is part of every
    histogram call; the payoff is the unpadded, fast-reading tile buffer
    (see _hist_kernel).  Stays in the narrow storage dtype end to end (the
    kernel converts): the u8 transpose measured ~2x faster than i32 and the
    tile buffer is 4x smaller in HBM.
    """
    F = X_rows.shape[-1]
    Fc = _feature_chunk(F, _pow2_bins(B))
    fpad = (-F) % Fc
    if fpad:
        X_rows = jnp.pad(X_rows, ((0, 0), (0, fpad)))
    n_fb = (F + fpad) // Fc
    Xt = X_rows.reshape(n_tiles, T, n_fb, Fc)
    # feature-major (Fc, T) tiles: T in lanes -> no XLA lane padding on the
    # HBM buffer and a ~20x faster in-kernel read (see _hist_kernel doc)
    return Xt.transpose(2, 0, 3, 1)


def build_hist_pallas(
    Xb: jnp.ndarray,
    g: jnp.ndarray,
    h: jnp.ndarray,
    mask: jnp.ndarray,
    total_bins: int,
    *,
    axis_name: str | None = None,
    platform: str | None = None,
) -> jnp.ndarray:
    """Single-leaf masked histogram -> (3, F, B) f32 (root / leaf-wise path).

    Rows stream in natural order (no leaf bucketing needed); masked-out rows
    ride along with zero weight limbs.
    """
    N, F = Xb.shape
    B = int(total_bins)
    T = _TILE_ROWS
    pad = (-N) % T
    Xp = jnp.pad(Xb, ((0, pad), (0, 0)))           # stays u8/u16 (kernel casts)
    gp = jnp.pad(g.astype(jnp.float32), (0, pad))
    hp = jnp.pad(h.astype(jnp.float32), (0, pad))
    mp = jnp.pad(mask, (0, pad))
    n_tiles = (N + pad) // T

    Xt = _tiles_from_rows(Xp, n_tiles, T, B)
    mt = mp.reshape(n_tiles, T)
    Wt = _pack_weights(gp.reshape(n_tiles, T), hp.reshape(n_tiles, T), mt)
    tile_leaf = jnp.zeros((n_tiles,), jnp.int32)
    tile_first = jnp.zeros((n_tiles,), jnp.int32).at[0].set(1)
    tile_skip = 1 - jnp.any(mt, axis=1).astype(jnp.int32)

    hist = _hist_tiles(
        Xt, Wt, tile_leaf, tile_first, tile_skip,
        num_cols=1, total_bins=B, num_features=F, axis_name=axis_name,
        platform=platform,
    )[0]
    if axis_name is not None:
        hist = jax.lax.psum(hist, axis_name)
    return hist


def tile_plan(sel: jnp.ndarray, N: int, P: int, T: int,
              rows_bound: int | None = None):
    """Bucket rows by leaf into fixed tiles.

    Returns (buf, tile_leaf, tile_first): ``buf`` (n_tiles*T,) row ids with
    sentinel N for padding slots; ``tile_leaf`` monotone leaf per tile
    (every leaf owns >= 1 tile); ``tile_first`` marks each leaf's first
    tile.  Deterministic: stable sort by leaf, fixed slot order.

    ``rows_bound`` caps the total selected rows when the caller can prove a
    tighter bound than N — the level-wise grower histograms only smaller
    children, which cover at most half the rows, halving the static tile
    count (and the kernel's grid).  Rows beyond the bound would be silently
    dropped, so only pass a mathematically guaranteed bound.
    """
    bound = N if rows_bound is None else min(int(rows_bound), N)
    n_tiles = bound // T + P + 1
    sel = sel.astype(jnp.int32)
    if N <= (1 << 24) and P < 256:
        # pack (slot, row) into ONE uint32 word (slot<<24 | row) and sort the
        # single array — the two-operand argsort + the sel[order] re-gather
        # measured ~1.8x slower at 10M.  Stability is by construction (row id
        # in the low bits); the resulting plan is value-identical to the
        # argsort formulation, so every downstream program is unchanged.
        key = ((sel.astype(jnp.uint32) << jnp.uint32(24))
               | jnp.arange(N, dtype=jnp.uint32))
        srt = jnp.sort(key)
        sel_sorted = (srt >> jnp.uint32(24)).astype(jnp.int32)
        order = (srt & jnp.uint32(0xFFFFFF)).astype(jnp.int32)
    else:
        order = jnp.argsort(sel, stable=True).astype(jnp.int32)
        sel_sorted = sel[order]
    start = jnp.searchsorted(sel_sorted, jnp.arange(P + 1, dtype=jnp.int32),
                             side="left").astype(jnp.int32)
    counts = start[1:] - start[:-1]                       # (P,)
    # every leaf gets >= 1 tile so its (pallas) output block is initialized
    leaf_tiles = jnp.maximum((counts + (T - 1)) // T, 1)
    seg_base = jnp.concatenate([jnp.zeros((1,), jnp.int32),
                                jnp.cumsum(leaf_tiles).astype(jnp.int32)])
    # Safety squeeze: if the caller's rows_bound was violated, raw bases can
    # exceed the grid.  Clamp so leaf i starts no later than n_tiles-(P-i) —
    # every leaf keeps >= 1 in-range tile (outputs stay initialized) and
    # rows beyond a leaf's allotment drop deterministically instead of
    # corrupting a neighbour's tiles.
    seg_base = jnp.minimum(
        seg_base, jnp.int32(n_tiles) - (P - jnp.arange(P + 1, dtype=jnp.int32)))
    cap_rows = (seg_base[1:] - seg_base[:-1]) * T         # (P,)

    tile_leaf = jnp.searchsorted(seg_base[1:], jnp.arange(n_tiles, dtype=jnp.int32),
                                 side="right").astype(jnp.int32)
    # Fill tile slots by GATHERING from the sorted order (TPU scatters
    # serialize — the old (N,)-scatter construction cost ~250 ms at 10M
    # rows; this gather formulation is the same plan ~4x cheaper): slot j
    # of tile t holds the (j + t*T - seg_base[leaf]*T)-th row of leaf's
    # contiguous run in `order`, sentinel N when past the leaf's count/cap.
    # All plan lookups happen per TILE (n_tiles ≈ N/T entries) and broadcast
    # across the T slot positions — only the final order[src] gather touches
    # an (N,)-sized table.  tile_leaf == P marks trailing pad tiles.
    tile_idx = jnp.arange(n_tiles, dtype=jnp.int32)
    lc = jnp.minimum(tile_leaf, P - 1)                     # (n_tiles,)
    base_t = tile_idx * T - seg_base[lc] * T               # first slot's in-leaf offset
    cnt_t = jnp.minimum(counts[lc], cap_rows[lc])
    start_t = start[lc]
    j = jnp.arange(T, dtype=jnp.int32)
    off = base_t[:, None] + j[None, :]                     # (n_tiles, T)
    ok = (tile_leaf < P)[:, None] & (off >= 0) & (off < cnt_t[:, None])
    src = start_t[:, None] + off
    buf = jnp.where(ok, order[jnp.clip(src, 0, N - 1)], N).reshape(-1)
    tile_leaf = jnp.minimum(tile_leaf, P - 1)             # clamp trailing pad tiles
    tile_first = jnp.concatenate([
        jnp.ones((1,), jnp.int32),
        (tile_leaf[1:] != tile_leaf[:-1]).astype(jnp.int32),
    ])
    return buf, tile_leaf, tile_first


def tile_plan_aligned(sel: jnp.ndarray, counts: jnp.ndarray, N: int, P: int,
                      T: int, rows_bound: int | None = None):
    """``tile_plan`` when the caller KNOWS each slot's exact row count.

    The level-synchronous growers do: a slot's count is the chosen split's
    smaller-child count (CL/CR off the parent histogram — exact integers in
    f32 below 2**24).  Injecting ``(-count) % T`` pad keys per slot into
    the packed sort makes every slot's run tile-aligned IN the sorted array
    itself, so ``buf`` is a plain slice — the 5M-access ``order[src]``
    alignment gather of the generic plan (~55 ms/level at 10M) disappears.

    The produced (buf, tile_leaf, tile_first) is VALUE-IDENTICAL to
    ``tile_plan``'s (same stable row order per slot, same sentinel
    placement, same static shapes), so every downstream program is
    unchanged — tests pin the equality.

    Admissibility (callers gate): N <= 2**24 - 1 (the row field stores
    row ids < N plus the sentinel N itself — pad keys reuse the sentinel,
    never values past it), P <= 254 (slot 0xFF marks inert injected
    keys), and ``counts`` must be exact —
    a wrong count silently misaligns the plan (the generic path's safety
    squeeze has nothing to squeeze here), which is why only growers that
    read counts off their own histograms may pass them.
    """
    bound = N if rows_bound is None else min(int(rows_bound), N)
    n_tiles = bound // T + P + 1                   # same grid as tile_plan
    sel = sel.astype(jnp.int32)
    cnt = counts.astype(jnp.int32)                 # (P,) exact
    lt = jnp.maximum((cnt + (T - 1)) // T, 1)      # aligned tiles per slot
    seg_base = jnp.concatenate([jnp.zeros((1,), jnp.int32),
                                jnp.cumsum(lt).astype(jnp.int32)])

    key_real = ((sel.astype(jnp.uint32) << jnp.uint32(24))
                | jnp.arange(N, dtype=jnp.uint32))
    # slot p needs lt[p]*T - cnt[p] <= T pad keys (row field N = sentinel);
    # unused injected keys get slot 0xFF and sort past everything live
    pad_needed = lt * T - cnt                      # (P,) in [0, T]
    padj = jnp.arange(T, dtype=jnp.int32)[None, :]
    slot_col = jnp.arange(P, dtype=jnp.uint32)[:, None]
    key_pad = jnp.where(
        padj < pad_needed[:, None],
        (slot_col << jnp.uint32(24)) | jnp.uint32(N),
        jnp.uint32(0xFF) << jnp.uint32(24))
    # one extra inert tile: n_tiles*T can exceed N + P*T by up to T
    key_tail = jnp.full((T,), jnp.uint32(0xFF) << jnp.uint32(24), jnp.uint32)
    srt = jnp.sort(jnp.concatenate([key_real, key_pad.reshape(-1), key_tail]))
    srt = srt[: n_tiles * T]
    slot_s = (srt >> jnp.uint32(24)).astype(jnp.int32)
    row_s = (srt & jnp.uint32(0xFFFFFF)).astype(jnp.int32)
    buf = jnp.where(slot_s < P, row_s, N)          # pads carry row N already

    tile_idx = jnp.arange(n_tiles, dtype=jnp.int32)
    tile_leaf = jnp.searchsorted(seg_base[1:], tile_idx,
                                 side="right").astype(jnp.int32)
    tile_leaf = jnp.minimum(tile_leaf, P - 1)
    tile_first = jnp.concatenate([
        jnp.ones((1,), jnp.int32),
        (tile_leaf[1:] != tile_leaf[:-1]).astype(jnp.int32),
    ])
    return buf, tile_leaf, tile_first


def make_records(Xb: jnp.ndarray, g: jnp.ndarray, h: jnp.ndarray) -> jnp.ndarray:
    """Per-TREE (N, 2 + ceil(F*bytes/4)) int32 record table [g, h, X words].

    g/h are constant across a tree's levels, so interleaving them with the
    bin row once per tree lets every level pay ONE row gather instead of a
    separate X gather + g/h gather (the per-access overhead of 10M-row
    random gathers dominated the per-level cost; halving the access count
    measured ~1.7x on the whole level).  X bytes are bitcast back out by
    hist_from_plan; uint16 bins ride as 2-byte units of the same words.
    """
    N, F = Xb.shape
    nbytes = Xb.dtype.itemsize * F
    fw = -(-nbytes // 4)                     # ceil: rows pad up to whole words
    Xu8 = jax.lax.bitcast_convert_type(
        Xb, jnp.uint8).reshape(N, nbytes) if Xb.dtype != jnp.uint8 else Xb
    Xu8 = jnp.pad(Xu8, ((0, 0), (0, fw * 4 - nbytes)))
    Xw = jax.lax.bitcast_convert_type(
        Xu8.reshape(N, fw, 4), jnp.int32).reshape(N, fw)
    gw = jax.lax.bitcast_convert_type(g.astype(jnp.float32), jnp.int32)
    hw = jax.lax.bitcast_convert_type(h.astype(jnp.float32), jnp.int32)
    return jnp.concatenate([gw[:, None], hw[:, None], Xw], axis=1)


def hist_from_plan(
    Xb: jnp.ndarray,
    g: jnp.ndarray,
    h: jnp.ndarray,
    buf: jnp.ndarray,
    tile_leaf: jnp.ndarray,
    tile_first: jnp.ndarray,
    num_cols: int,
    total_bins: int,
    *,
    axis_name: str | None = None,
    platform: str | None = None,
    records: jnp.ndarray | None = None,
    stage_gather: bool = True,
    hist_reduce: str = "fused",
) -> jnp.ndarray:
    """Histogram leaf-grouped rows given a precomputed tile plan.

    Padding slots (sentinel N in ``buf``) clamp to row N-1 and ride with
    zero weight — their one-hot columns hit real bins but multiply zero, so
    the sums are unchanged (this replaces the old sentinel-row concatenate,
    which re-materialized the whole (N, F) matrix every level).

    ``records`` (make_records) collapses the X and g/h gathers into one.
    CONTRACT: it must have been built from the SAME (Xb, g, h) passed here —
    on the records path the g/h arguments are ignored (values come from the
    table) and Xb contributes only shape/dtype; a stale table silently
    yields histograms of the old gradients.
    """
    N, F = Xb.shape
    B = int(total_bins)
    T = _TILE_ROWS
    n_tiles = buf.shape[0] // T
    valid = (buf < N).reshape(n_tiles, T)
    live = jnp.any(valid, axis=1)                   # (n_tiles,)
    safe = jnp.minimum(buf, N - 1)

    if records is not None:
        # STAGED gather: the plan's static shape covers the worst-case N/2
        # smaller-children bound, but live tiles always form a PREFIX (both
        # plans pack leaf segments at the front; everything after the last
        # live tile is sentinel), and gather cost is per-ACCESS (CLAUDE.md)
        # — so when the actual selection is small, gathering a quarter- or
        # half-prefix and zero-padding the rest halves-to-quarters the
        # dominant per-level HBM cost.  lax.cond picks the smallest prefix
        # covering the live tiles at runtime; zero rows carry zero weights
        # and bin 0, contributing nothing (same sentinel algebra as pads).
        # Single-device only: under shard_map the predicate would vary by
        # shard (vma) and every shard must run one program.  Callers pass
        # stage_gather=False when the leaf budget fills every level (a
        # full tree keeps the prefix at ~100% and the cond's three gather
        # kernels only bloat compile — Epsilon-width programs measured
        # minutes of extra remote compile for zero runtime win).
        if stage_gather and axis_name is None and n_tiles >= 8:
            n_pref = jnp.max(jnp.where(
                live, jnp.arange(1, n_tiles + 1, dtype=jnp.int32), 0))

            def stage(nt):
                def go(b):
                    sf = jnp.minimum(b[: nt * T], N - 1)
                    r = records[sf]
                    return jnp.pad(r, ((0, (n_tiles - nt) * T), (0, 0)))
                return go

            q1, q2 = n_tiles // 4, n_tiles // 2
            rec = jax.lax.cond(
                n_pref <= q1,
                stage(q1),
                lambda b: jax.lax.cond(n_pref <= q2, stage(q2),
                                       stage(n_tiles), b),
                buf)
        else:
            rec = records[safe]                     # ONE (n_rows, 2+fw) gather
        gh = jax.lax.bitcast_convert_type(rec[:, :2], jnp.float32)
        gt = gh[:, 0].reshape(n_tiles, T)
        ht = gh[:, 1].reshape(n_tiles, T)
        fw = rec.shape[1] - 2
        nbytes = Xb.dtype.itemsize * F
        Xr = jax.lax.bitcast_convert_type(
            rec[:, 2:], jnp.uint8).reshape(n_tiles * T, fw * 4)[:, :nbytes]
        if Xb.dtype != jnp.uint8:
            Xr = jax.lax.bitcast_convert_type(
                Xr.reshape(n_tiles * T, F, Xb.dtype.itemsize), Xb.dtype)
        X_rows = Xr.reshape(n_tiles * T, F)
    else:
        # gather in the narrow storage dtype (the kernel casts): the (N, F)
        # u8 gather moves 4x fewer bytes than an i32 one
        X_rows = Xb[safe]
        ght = jnp.stack([g.astype(jnp.float32),
                         h.astype(jnp.float32)], axis=1)[safe]
        gt, ht = ght[:, 0].reshape(n_tiles, T), ght[:, 1].reshape(n_tiles, T)

    Xt = _tiles_from_rows(X_rows, n_tiles, T, B)
    Wt = _pack_weights(gt, ht, valid)

    hist = _hist_tiles(
        Xt, Wt, tile_leaf, tile_first, 1 - live.astype(jnp.int32),
        num_cols=int(num_cols), total_bins=B, num_features=F,
        axis_name=axis_name, platform=platform,
    )
    if axis_name is not None:
        from dryad_tpu.engine.distributed import reduce_hist

        hist = reduce_hist(hist, axis_name, hist_reduce)
    return hist


def build_hist_segmented_pallas(
    Xb: jnp.ndarray,
    g: jnp.ndarray,
    h: jnp.ndarray,
    sel: jnp.ndarray,
    num_cols: int,
    total_bins: int,
    *,
    axis_name: str | None = None,
    rows_bound: int | None = None,
    platform: str | None = None,
    records: jnp.ndarray | None = None,
    sel_counts: jnp.ndarray | None = None,
    stage_gather: bool = True,
    hist_reduce: str = "fused",
) -> jnp.ndarray:
    """Per-leaf histograms for a whole tree level -> (P, 3, F, B) f32.

    ``sel`` (N,) in [0, P]; P drops the row.  O(N·F·B) MXU work independent
    of leaf count — the TPU analog of the CUDA kernel's atomic scatter-add
    asymptotics.  ``records`` (make_records, computed once per tree) fuses
    the level's X and g/h gathers into one.  ``sel_counts`` (P,) — the
    exact per-slot row counts, when the caller reads them off its own
    histograms — switches to the pad-injected aligned sort
    (tile_plan_aligned), dropping the plan's alignment gather.
    """
    N = Xb.shape[0]
    P = int(num_cols)
    if sel_counts is not None and N <= (1 << 24) - 1 and P <= 254:
        buf, tile_leaf, tile_first = tile_plan_aligned(
            sel, sel_counts, N, P, _TILE_ROWS, rows_bound=rows_bound)
    else:
        buf, tile_leaf, tile_first = tile_plan(sel, N, P, _TILE_ROWS,
                                               rows_bound=rows_bound)
    return hist_from_plan(
        Xb, g, h, buf, tile_leaf, tile_first, num_cols, total_bins,
        axis_name=axis_name, platform=platform, records=records,
        stage_gather=stage_gather, hist_reduce=hist_reduce,
    )

# ---------------------------------------------------------------------------
# natural-order multi-slot pass (shallow levels: <= 16 slots)
# ---------------------------------------------------------------------------
_NAT_SLOTS = 16
_NAT_DROP = 31        # sel sentinel (any value >= _NAT_SLOTS drops the row)
# global-matrix gate for the natural-order pass, MB (see maybe_natural_tiles)
_NAT_GATE_MB = int(os.environ.get("DRYAD_NAT_MB", "512"))


def nat_gate_admits(num_rows: int, num_features: int, itemsize: int,
                    n_shards: int = 1) -> bool:
    """The ONE natural-order gate predicate (GLOBAL padded matrix bytes vs
    ``_NAT_GATE_MB``) — shared by maybe_natural_tiles and
    train._comm_stats so the observability accounting can never drift from
    the grower's actual program choice (ADVICE r4)."""
    return (num_rows * n_shards * num_features * itemsize
            <= (_NAT_GATE_MB << 20))


def maybe_natural_tiles(Xb: jnp.ndarray, total_bins: int,
                        axis_name: str | None = None):
    """natural_tiles when the GLOBAL matrix is small enough, else None.

    The gate must see the global size: under shard_map Xb is the local
    shard, and gating per-shard would let 1-shard and N-shard runs of the
    same data take different histogram programs (near-tie argmaxes could
    flip — the CLAUDE.md same-program rule).  psum of a constant folds to
    axis_size at trace time, so the check stays static.

    Gate history: r3 measured the nat pass REGRESSING the chunked 10M
    marginal 2x (buffer pressure in the then-program) and gated it at
    128 MB; after the r4 pipeline cuts (aligned plan, staged gather,
    skip-empty tiles, device-cached X) the same measurement shows it
    WINNING (2.78 -> 2.55 s/iter at 10M), so the default gate is now
    512 MB — wide enough for Higgs-10M's 280 MB, still excluding
    Epsilon-shaped 800 MB matrices: r5 finally measured that shape
    (exp_r5_eps.py: nat 347 vs plan 368 ms per 16-slot level — a ~6%
    win worth ~1% of an Epsilon iteration) and KEEPS the exclusion; the
    small win does not justify doubling peak bin-matrix residency.
    ``DRYAD_NAT_MB`` overrides for measurement — read ONCE at import (a
    per-call read would be silently ignored whenever the jit cache already
    holds a program for these shapes: the env var is not part of the key).
    """
    n_shards = int(jax.lax.psum(1, axis_name)) if axis_name else 1
    N, F = Xb.shape
    if not nat_gate_admits(N, F, Xb.dtype.itemsize, n_shards):
        return None
    return natural_tiles(Xb, total_bins)


def build_hist_small(nat_tiles, g, h, sel, num_cols: int, total_bins: int,
                     num_features: int, *, axis_name: str | None = None,
                     platform: str | None = None,
                     hist_reduce: str = "fused") -> jnp.ndarray:
    """(P, 3, F, B) via the natural-order pass: owns the drop-sentinel
    mapping (callers use sel == P for "drop") and the slot-budget check.

    ``num_cols`` is forwarded so the allreduce inside covers only the P live
    slots — psumming the full 16-slot kernel output shipped 2x the needed
    bytes at P=8 (ADVICE r3 #2); with the slice before the psum, the nat
    pass's collective payload equals the plan path's (P, 3, F, B), keeping
    ``train._comm_stats`` exact for both."""
    P = int(num_cols)
    assert P <= _NAT_SLOTS, "natural-order pass holds at most 16 slots"
    sel_nat = jnp.where(sel >= P, _NAT_DROP, sel)
    return build_hist_nat(nat_tiles, g, h, sel_nat,
                          total_bins=int(total_bins),
                          num_features=int(num_features),
                          num_cols=P,
                          axis_name=axis_name, platform=platform,
                          hist_reduce=hist_reduce)


def natural_tiles(Xb: jnp.ndarray, total_bins: int) -> jnp.ndarray:
    """Feature-chunked tiles of the WHOLE matrix in natural row order — a
    pure function of (Xb, bins), so the level-synchronous growers build it
    once per tree and every shallow level reuses it (no sort, no gather)."""
    N = Xb.shape[0]
    T = _TILE_ROWS
    pad = (-N) % T
    Xp = jnp.pad(Xb, ((0, pad), (0, 0)))
    return _tiles_from_rows(Xp, (N + pad) // T, T, total_bins)


def _nat_kernel(x_ref, w_ref, o_ref, *, padded_bins: int):
    """All (<=16) slots' histograms in ONE natural-order pass: slot s owns
    weight rows 8s..8s+6 of the 128-row MXU tile (16 x 8 = 128 exactly);
    row 8s+7 is dead (it carries the slot-id lane used for the row mask).
    No tile plan: the per-row slot id rides as ROW 7 of the 8-row weight
    block (slot values <= 31 are exact in bf16), and a shifted row-iota
    mask zeroes every weight row whose slot does not match the lane's."""
    i = pl.program_id(1)
    x = x_ref[0, 0].astype(jnp.int32)              # (Fc, T)
    Fc, T = x.shape
    Bp = padded_bins
    shift = Fc.bit_length() - 1
    x_rep = jax_compat.tile_repeat(x, Bp, axis=0)
    iota_b = jax.lax.broadcasted_iota(jnp.int32, (Fc * Bp, T), 0) >> shift
    onehot = (x_rep == iota_b).astype(jnp.bfloat16)

    limbs = w_ref[0]                               # (8, T): 7 limbs + sel row
    sel = limbs[7:8, :].astype(jnp.int32)
    w = jax_compat.tile_repeat(limbs, _NAT_SLOTS, axis=0)  # (128,T) r=limbs[r%8]
    row_iota = jax.lax.broadcasted_iota(jnp.int32, (_NAT_SLOTS * 8, T), 0)
    keep = ((row_iota >> 3) == sel) & ((row_iota & 7) != 7)
    w = jnp.where(keep, w, jnp.bfloat16(0))
    part = jax.lax.dot_general(
        w, onehot, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)        # (128, Fc*Bp)

    @pl.when(i == 0)
    def _():
        o_ref[0] = part

    @pl.when(i != 0)
    def _():
        o_ref[0] = o_ref[0] + part


@functools.partial(jax.jit, static_argnames=("total_bins", "num_features",
                                             "num_cols", "axis_name",
                                             "platform", "hist_reduce"))
def build_hist_nat(Xt_nat, g, h, sel, *, total_bins: int, num_features: int,
                   num_cols: int = _NAT_SLOTS,
                   axis_name: str | None = None,
                   platform: str | None = None,
                   hist_reduce: str = "fused") -> jnp.ndarray:
    """(num_cols, 3, F, B) histograms from natural-order tiles; ``sel`` (N,)
    in [0, 16); values >= 16 drop the row.  Replaces the plan+gather
    pipeline for levels with few candidates — measured 154 vs 281 ms at
    10M, P=8 (the tile plan's full-N sort and the row gather dominate
    there).  The kernel always produces all 16 slots (its 128-row MXU tile
    is fixed); ``num_cols`` slices BEFORE the psum so sharded callers
    allreduce only live slots (ADVICE r3 #2)."""
    B = int(total_bins)
    F = int(num_features)
    Bp = _pow2_bins(B)
    n_fb, n_tiles, Fc, T = Xt_nat.shape
    N = g.shape[0]
    pad = n_tiles * T - N
    gp = jnp.pad(g.astype(jnp.float32), (0, pad))
    hp = jnp.pad(h.astype(jnp.float32), (0, pad))
    sp = jnp.pad(sel.astype(jnp.int32), (0, pad),
                 constant_values=_NAT_DROP)
    sp = jnp.minimum(sp, _NAT_DROP)
    valid = (sp < _NAT_SLOTS).astype(jnp.float32)
    gv = (gp * valid).reshape(n_tiles, T)
    hv = (hp * valid).reshape(n_tiles, T)
    cnt = valid.astype(jnp.bfloat16).reshape(n_tiles, T)
    selr = sp.astype(jnp.bfloat16).reshape(n_tiles, T)
    W = jnp.stack([*_split3(gv), *_split3(hv), cnt, selr], axis=-2)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=0,
        grid=(n_fb, n_tiles),
        in_specs=[
            pl.BlockSpec((1, 1, Fc, T), lambda j, i: (j, i, 0, 0)),
            pl.BlockSpec((1, 8, T), lambda j, i: (i, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, _NAT_SLOTS * 8, Fc * Bp),
                               lambda j, i: (j, 0, 0)),
    )
    out_shape = jax_compat.shape_dtype_struct(
        (n_fb, _NAT_SLOTS * 8, Fc * Bp), jnp.float32, axis_name)
    out = pl.pallas_call(
        functools.partial(_nat_kernel, padded_bins=Bp),
        grid_spec=grid_spec,
        out_shape=out_shape,
        interpret=_interpret(platform),
    )(Xt_nat, W)
    out = (out.reshape(n_fb, _NAT_SLOTS, 8, Bp, Fc)
              .transpose(1, 2, 0, 4, 3)
              .reshape(_NAT_SLOTS, 8, n_fb * Fc, Bp))[:, :, :F, :B]
    out = out[:num_cols]
    hg = out[:, 0] + out[:, 1] + out[:, 2]
    hh = out[:, 3] + out[:, 4] + out[:, 5]
    hc = out[:, 6]
    hist = jnp.stack([hg, hh, hc], axis=1)         # (num_cols, 3, F, B)
    if axis_name is not None:
        from dryad_tpu.engine.distributed import reduce_hist

        hist = reduce_hist(hist, axis_name, hist_reduce)
    return hist
