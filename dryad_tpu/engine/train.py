"""``dryad.train`` device backend: the boosting loop driving the compiled
grower (SURVEY.md §3 train call stack).

Orchestration (objective dispatch, bagging draw, early stopping, callbacks,
resume) stays on the host — it is O(1) per iteration; every O(N) step
(grad/hess, histogramming, partition, traversal, score update) runs on
device under one jit program per (shapes, params) pair.

Bagging/colsample masks come from the same host-side Philox draw as the CPU
reference trainer (``cpu/trainer.py::sample_masks``), so sampling can never
break cross-backend parity.
"""

from __future__ import annotations

from functools import partial
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from dryad_tpu.booster import Booster, empty_tree_arrays
from dryad_tpu.config import Params
from dryad_tpu.cpu.trainer import sample_masks
from dryad_tpu.dataset import Dataset
from dryad_tpu.engine.grower import grow_any
from dryad_tpu.engine.predict import _accumulate, tree_leaves
from dryad_tpu.objectives import get_objective


@partial(jax.jit, static_argnames=("params", "total_bins", "has_cat"))
def _grow_and_apply(params, total_bins, has_cat, Xb, g, h, bag_mask, feat_mask,
                    is_cat_feat, score_k):
    """Grow one tree and apply its leaf deltas to the training scores."""
    tree = grow_any(
        params, total_bins, Xb, g, h, bag_mask, feat_mask, is_cat_feat,
        has_cat=has_cat,
    )
    leaves = tree_leaves(tree, Xb, tree["max_depth"])
    return tree, score_k + tree["value"][leaves]


@jax.jit
def _apply_tree(tree, Xb, score_k):
    """Apply an already-grown tree to another row set (validation scores)."""
    leaves = tree_leaves(tree, Xb, tree["max_depth"])
    return score_k + tree["value"][leaves]


def train_device(
    params: Params,
    data: Dataset,
    valid: Optional[Dataset] = None,
    *,
    num_trees: Optional[int] = None,
    init_booster: Optional[Booster] = None,
    callback: Optional[Callable[[int, dict], None]] = None,
    mesh=None,
) -> Booster:
    """Device trainer.  With ``mesh`` set, rows are sharded over the mesh's
    data axis and histograms allreduced by psum (engine/distributed.py)."""
    p = params.validate()
    obj = get_objective(p)
    N, F = data.X_binned.shape
    K = p.num_outputs
    B = data.mapper.total_bins
    is_cat_np = data.mapper.is_categorical
    has_cat = bool(is_cat_np.any())
    T = (num_trees if num_trees is not None else p.num_trees) * K

    Xb_np, y_np = data.X_binned, data.y
    w_np = data.weight
    pad = 0
    if mesh is not None:
        from dryad_tpu.engine.distributed import padded_rows, shard_rows

        Np = padded_rows(N, mesh.devices.size)
        pad = Np - N
        if pad:
            Xb_np = np.pad(Xb_np, ((0, pad), (0, 0)))
            y_np = np.pad(y_np, (0, pad))
            if w_np is not None:
                w_np = np.pad(w_np, (0, pad))
        Xb, y = shard_rows(mesh, jnp.asarray(Xb_np), jnp.asarray(y_np))
        weight = shard_rows(mesh, jnp.asarray(w_np))[0] if w_np is not None else None
    else:
        Xb = jnp.asarray(Xb_np)
        y = jnp.asarray(y_np)
        weight = jnp.asarray(w_np) if w_np is not None else None
    NP = N + pad
    is_cat_feat = jnp.asarray(is_cat_np)
    qoff = data.query_offsets

    out = empty_tree_arrays(T, p.max_nodes)
    init = np.asarray(obj.init_score(data.y, data.weight), np.float32).reshape(-1)
    score = jnp.broadcast_to(jnp.asarray(init), (NP, K)).astype(jnp.float32)
    max_depth_seen = 0

    start_iter = 0
    if init_booster is not None:
        prev = init_booster
        if prev.params.max_nodes != p.max_nodes or prev.num_outputs != K:
            raise ValueError(
                "init_booster is incompatible: num_leaves/max_depth/num_class must match"
            )
        if prev.num_total_trees > T:
            raise ValueError("new num_trees must cover the init_booster's iterations")
        prev_trees = {
            k: jnp.asarray(v).reshape((prev.num_iterations, K) + v.shape[1:])
            for k, v in prev.tree_arrays().items()
        }
        # same fp32 order as the CPU replay: broadcast(new init) += each tree
        score = _accumulate(prev_trees, Xb, jnp.asarray(init), max(prev.max_depth_seen, 1))
        for k_arr in out:
            out[k_arr][: prev.num_total_trees] = prev.tree_arrays()[k_arr]
        start_iter = prev.num_iterations
        max_depth_seen = prev.max_depth_seen

    vXb = jnp.asarray(valid.X_binned) if valid is not None else None
    vscore = (
        jnp.broadcast_to(jnp.asarray(init), (valid.num_rows, K)).astype(jnp.float32)
        if valid is not None
        else None
    )
    if valid is not None and init_booster is not None:
        vscore = _accumulate(prev_trees, vXb, jnp.asarray(init), max(prev.max_depth_seen, 1))
    best_iteration, best_value, stale = -1, None, 0

    ones_rows = np.ones((NP,), bool)
    ones_feat = jnp.ones((F,), bool)

    rank_plan = None
    if p.objective == "lambdarank":
        from dryad_tpu.engine.lambdarank import PaddingPlan

        rank_plan = PaddingPlan(np.asarray(qoff))  # loop-invariant scatter plan

    for it in range(start_iter, T // K):
        if p.objective == "lambdarank":
            # ragged per-query pairwise work on padded per-query segments
            # (engine/lambdarank.py); pad rows beyond N get zero gradients
            from dryad_tpu.engine.lambdarank import grad_hess_ranking

            w_rank = None if weight is None else weight[:N]
            g_all, h_all = grad_hess_ranking(obj, score[:N, 0], y[:N], w_rank, qoff,
                                             plan=rank_plan)
            if pad:
                g_all = jnp.pad(g_all, (0, pad))
                h_all = jnp.pad(h_all, (0, pad))
            g_all, h_all = g_all[:, None], h_all[:, None]
        elif K > 1:
            g_all, h_all = obj.grad_hess_jax(score, y, weight)
        else:
            g_all, h_all = obj.grad_hess_jax(score[:, 0], y, weight)
            g_all, h_all = g_all[:, None], h_all[:, None]

        row_mask_np, feat_mask_np = sample_masks(p, it, N, F)
        bag_np = ones_rows if row_mask_np is None else np.pad(row_mask_np, (0, pad))
        if pad:
            bag_np = bag_np.copy()
            bag_np[N:] = False
        fmask = ones_feat if feat_mask_np is None else jnp.asarray(feat_mask_np)
        bag = jnp.asarray(bag_np)

        for k in range(K):
            t = it * K + k
            if mesh is not None:
                from dryad_tpu.engine.distributed import grow_and_apply_sharded

                tree, new_col = grow_and_apply_sharded(
                    p, B, has_cat, mesh, Xb, g_all[:, k], h_all[:, k], bag,
                    fmask, is_cat_feat, score[:, k],
                )
            else:
                tree, new_col = _grow_and_apply(
                    p, B, has_cat, Xb, g_all[:, k], h_all[:, k], bag, fmask,
                    is_cat_feat, score[:, k],
                )
            score = score.at[:, k].set(new_col)
            max_depth_seen = max(max_depth_seen, int(tree["max_depth"]))
            for key in ("feature", "threshold", "left", "right", "value",
                        "is_cat", "cat_bitset"):
                out[key][t] = np.asarray(tree[key])
            if valid is not None:
                vscore = vscore.at[:, k].set(_apply_tree(tree, vXb, vscore[:, k]))

        info: dict = {"iteration": it}
        if valid is not None:
            from dryad_tpu.metrics import evaluate_raw

            vs = np.asarray(vscore)
            name, value, higher = evaluate_raw(
                p.objective, p.metric, valid.y, vs if K > 1 else vs[:, 0],
                valid.query_offsets, p.ndcg_at,
            )
            info[f"valid_{name}"] = value
            improved = best_value is None or (value > best_value if higher else value < best_value)
            if improved:
                best_iteration, best_value, stale = it + 1, value, 0
            else:
                stale += 1
            if p.early_stopping_rounds and stale >= p.early_stopping_rounds:
                if callback is not None:
                    callback(it, info)
                T = (it + 1) * K
                break
        if callback is not None:
            callback(it, info)

    for key in out:
        out[key] = out[key][:T]
    return Booster(
        p, data.mapper,
        out["feature"], out["threshold"], out["left"], out["right"], out["value"],
        out["is_cat"], out["cat_bitset"],
        init, max_depth_seen,
        best_iteration=best_iteration,
    )
