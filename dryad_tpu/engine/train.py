"""``dryad.train`` device backend: the boosting loop driving the compiled
grower (SURVEY.md §3 train call stack).

Orchestration (objective dispatch, bagging draw, early stopping, callbacks,
resume) stays on the host — it is O(1) per iteration; every O(N) step
(grad/hess, histogramming, partition, traversal, score update) runs on
device under one jit program per (shapes, params) pair.

**No per-iteration host↔device synchronization.**  Through a remote device
tunnel a single small fetch costs ~100 ms — an order of magnitude more than
growing the tree — so the trained tree arrays live on device (written into
preallocated (T, ...) output buffers with donated in-place updates) and are
fetched exactly once when training ends.  Iterations therefore dispatch
asynchronously and pipeline; the only forced syncs are per-iteration metric
evaluation when a validation set is supplied.

Bagging/colsample masks come from the same host-side Philox draw as the CPU
reference trainer (``cpu/trainer.py::sample_masks``), so sampling can never
break cross-backend parity.
"""

from __future__ import annotations

import os
from functools import partial
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from dryad_tpu.booster import CAT_WORDS, Booster
from dryad_tpu.config import Params, effective_depth_params
from dryad_tpu.cpu.trainer import (
    dart_drop_set,
    goss_uniform,
    sample_masks,
    update_best,
)
from dryad_tpu.dataset import Dataset

# compile-boundary introspection (r12): dryad_prog_* cost/memory capture
# + the recompile-tripwire key notes.  Called ONLY at compile boundaries
# (dryadlint introspect-compile-only); observation-only — the traced
# programs are untouched (the analysis goldens are the proof)
from dryad_tpu.engine import introspect
from dryad_tpu.engine.grower import grow_any
from dryad_tpu.engine.predict import _accumulate, tree_leaves
from dryad_tpu.objectives import get_objective

# per-stage span series (dryad_tpu/obs): host wall around work this loop
# already does — dispatch cost on the async sites, real fetch wall on the
# fetch sites.  Never a new device fetch; zero-cost when disabled.
from dryad_tpu.obs.registry import default_registry
from dryad_tpu.obs.spans import record as record_span
from dryad_tpu.obs.spans import span
from dryad_tpu.obs.tripwire import default_tripwire

# fetch-stall watchdog (r12): every REAL device->host fetch below is
# bracketed so the in-flight age is a live gauge and a stall flips
# /healthz BEFORE the ~60 s tunnel kill (STATUS r5).  Null context when
# obs is disabled.
from dryad_tpu.obs.watchdog import watch_fetch

_TREE_KEYS = ("feature", "threshold", "left", "right", "value", "is_cat",
              "cat_bitset", "gain", "default_left", "cover")

# widest (features * bins) program the chunked fori wrapper may compile.
# Round 2 measured Epsilon-shaped (2000 x 256) chunk programs failing
# remote compile; after the round-3 pipeline shrink (8-row weight buffers,
# no sentinel concatenates, u8 tiles) the same shape compiles in ~70 s and
# runs, so the limit is now the VERIFIED 2000*256 with headroom kept as a
# guard, not a cliff (VERDICT r2 #6)
_CHUNK_FB_LIMIT = 1 << 19


def _renew_values(value, feature, leaves, y, score_k, bag, alpha, lr, M):
    """Post-growth leaf renewal (objectives.renew_alpha): replace each
    leaf's Newton value with the type-1 (inverse-CDF, no interpolation)
    alpha-quantile of its in-bag residuals y - score, times the shrinkage.

    Convention shared BITWISE with cpu/trainer.renew_leaf_values_np: the
    order statistic at index clip(ceil(f32(alpha)·f32(cnt)) - 1, 0, cnt-1)
    is a pure element selection — no interpolation arithmetic — so both
    backends pick the identical f32 value and the only cross-backend
    wobble is the residuals' own ulp-level score differences.  One global
    two-key sort (leaf id primary, residual secondary; out-of-bag rows get
    sentinel id M and sink to the tail) + a searchsorted for the segment
    bounds — O(N log N) per tree, paid only by the robust objectives."""
    r = y - score_k
    lv = jnp.where(bag, leaves.astype(jnp.int32), M)
    lv_s, r_s = jax.lax.sort((lv, r), num_keys=2)
    bounds = jnp.searchsorted(lv_s, jnp.arange(M + 1, dtype=jnp.int32))
    cnt = bounds[1:] - bounds[:-1]                       # (M,) per node
    kf = jnp.ceil(jnp.float32(alpha) * cnt.astype(jnp.float32))
    kidx = jnp.clip(kf.astype(jnp.int32) - 1, 0, jnp.maximum(cnt - 1, 0))
    sel = jnp.clip(bounds[:-1] + kidx, 0, r_s.shape[0] - 1)
    stat = r_s[sel] * jnp.float32(lr)
    return jnp.where((feature < 0) & (cnt > 0), stat, value)


def _step_body(p, B, has_cat, mesh, platform, learn_missing, out, score, Xb,
               g_all, h_all, bag, fmask, is_cat_feat, t, k, root_hist=None,
               bmask=None, n_rows=None, value_scale=None, y=None,
               renew_alpha=None):
    """One (iteration, class) tree: grow, record into slot t, update scores.

    Shared by the per-iteration ``_step_jit`` dispatch and the chunked
    ``_chunk_jit`` fast path, so the two can never diverge.  ``root_hist``
    carries the class's slice of the shared-plan multiclass root pass
    (single-device path only).  ``renew_alpha`` (static) turns on L1-family
    leaf renewal — the residuals are taken against the PRE-update score,
    the same ensemble the gradients saw.
    """
    out = dict(out)
    g = jnp.take(g_all, k, axis=1)
    h = jnp.take(h_all, k, axis=1)
    if mesh is not None:
        from dryad_tpu.engine.distributed import grow_sharded

        tree, leaves = grow_sharded(
            p, B, has_cat, mesh, Xb, g, h, bag, fmask, is_cat_feat,
            platform=platform, learn_missing=learn_missing,
            root_hist=root_hist, bundled_mask=bmask,
            # UNPADDED global N: the envelope policy must see the same
            # rows at every shard count (and as the CPU mirror)
            global_rows=n_rows,
        )
    else:
        tree = grow_any(p, B, Xb, g, h, bag, fmask, is_cat_feat,
                        has_cat=has_cat, platform=platform,
                        learn_missing=learn_missing, root_hist=root_hist,
                        bundled_mask=bmask)
        # each row's leaf comes straight out of the grower's partition
        # state — re-traversing 10M rows cost ~5 s/tree (gather-bound)
        leaves = tree.pop("row_leaf")
    if renew_alpha is not None:
        tree = dict(tree, value=_renew_values(
            tree["value"], tree["feature"], leaves, y,
            jnp.take(score, k, axis=1), bag, renew_alpha,
            p.effective_learning_rate, p.max_nodes))
    if value_scale is not None:
        # DART: the new tree lands pre-scaled by 1/(k+1) — same f32
        # multiply order as the CPU mirror (finalize with lr, then scale)
        tree = dict(tree, value=tree["value"] * value_scale)
    col = jnp.take(score, k, axis=1) + tree["value"][leaves]
    score = jax.lax.dynamic_update_index_in_dim(score, col, k, axis=1)
    for key in _TREE_KEYS:
        out[key] = out[key].at[t].set(tree[key])
    out["max_depth"] = out["max_depth"].at[t].set(tree["max_depth"])
    return out, score


_step_jit = partial(jax.jit,
                    static_argnames=("p", "B", "has_cat", "mesh", "platform",
                                     "learn_missing", "n_rows",
                                     "renew_alpha"))(_step_body)
# Module-level jit keyed on the static (params, bins, mesh) triple — the
# compiled program is reused across ``train_device`` calls (a closure-local
# jit would recompile per call and dwarf the training itself).  out/score
# are NOT donated: through the axon tunnel each donated buffer costs
# ~220 ms of dispatch-time bookkeeping (measured; 18 ms undonated), and
# double-buffering a 40 MB score is free next to the grower's working set.


def _grads_body(p, N, K, pad, score, y, weight, qoff, rank_row_ids,
                rank_col_ids, rank_Q, rank_S):
    """Per-iteration grad/hess (N+pad, K) from the pre-iteration score.

    All K class trees of one boosting iteration share this single pass —
    exactly the CPU reference's semantics.
    """
    obj = get_objective(p)
    if p.objective == "lambdarank":
        from dryad_tpu.engine.lambdarank import PaddingPlan, grad_hess_ranking

        plan = PaddingPlan.__new__(PaddingPlan)
        plan.Q, plan.S = rank_Q, rank_S
        plan.row_ids, plan.col_ids = rank_row_ids, rank_col_ids
        w_rank = None if weight is None else weight[:N]
        g, h = grad_hess_ranking(obj, score[:N, 0], y[:N], w_rank, qoff,
                                 plan=plan)
        if pad:
            g = jnp.pad(g, (0, pad))
            h = jnp.pad(h, (0, pad))
        return g[:, None], h[:, None]
    if K > 1:
        return obj.grad_hess_jax(score, y, weight)
    g, h = obj.grad_hess_jax(score[:, 0], y, weight)
    return g[:, None], h[:, None]


_grads_jit = partial(jax.jit,
                     static_argnames=("p", "N", "K", "pad", "rank_Q",
                                      "rank_S"))(_grads_body)


def _grow_iteration(p, B, has_cat, mesh, platform, learn_missing, out, score,
                    Xb, y, g_all, h_all, bag_i, fmask_i, is_cat_feat, it, K,
                    bmask=None, n_rows=None, renew_alpha=None):
    """GOSS amplification + shared-plan multiclass roots + the K class
    trees of ONE boosting iteration (``it`` is the traced global iteration
    id; tree slots ``it*K + k``).  The single assembly shared by the
    chunked device loop and ``audit_iteration_fn`` — the jaxpr auditor's
    arms audit the trained program BY CONSTRUCTION, not a replica."""
    if p.boosting == "goss":
        # device-drawn uniforms (bit-identical to the host generator)
        # make GOSS chunkable: no per-iteration upload, same selection
        u = _goss_uniform_dev(p.seed, it, score.shape[0])
        g_all, h_all, bag_i = _goss_body(p, n_rows, g_all, h_all, u, bag_i)
    roots = None
    if K > 1 and _shared_roots_ok(p, platform):
        # shared-plan multiclass roots: all K trees' root histograms in
        # one matmul pass (2K+1 weight rows — histogram.py).  The mesh
        # path runs the SAME builder under shard_map: the (2K+1)-row
        # MXU lowering is fusion-sensitive (measured NOT bitwise vs the
        # 3-row pass on device), so both paths must share one program
        # or near-tie root argmaxes could differ 1-shard vs N-shard.
        if mesh is not None:
            from dryad_tpu.engine.distributed import roots_sharded

            roots = roots_sharded(mesh, Xb, g_all, h_all, bag_i, B,
                                  p.rows_per_chunk, p.hist_precision)
        else:
            from dryad_tpu.engine.histogram import build_hist_classes

            roots = build_hist_classes(
                Xb, g_all, h_all, bag_i, B,
                rows_per_chunk=p.rows_per_chunk,
                precision=p.hist_precision)
    for k in range(K):
        t = it * K + k
        out, score = _step_body(
            p, B, has_cat, mesh, platform, learn_missing, out, score,
            Xb, g_all, h_all, bag_i, fmask_i, is_cat_feat, t, k,
            root_hist=None if roots is None else roots[k], bmask=bmask,
            n_rows=n_rows, y=y, renew_alpha=renew_alpha)
    return out, score


@partial(jax.jit,
         static_argnames=("p", "B", "has_cat", "mesh", "platform",
                          "learn_missing", "N", "K", "pad", "rank_Q",
                          "rank_S", "metric_names", "ndcg_at", "eval_period",
                          "total_iters", "renew_alpha"))
def _chunk_jit(p, B, has_cat, mesh, platform, learn_missing, N, K, pad,
               rank_Q, rank_S, out, score, Xb, y, weight, bag, fmask,
               is_cat_feat, qoff, rank_row, rank_col, it0, n_iters,
               bmask=None, bag_bits=None, fmask_chunk=None,
               metric_names=(), ndcg_at=10, eval_period=1, total_iters=0,
               vXbs=(), vys=(), vqids=(), vscores=(), eval_buf=None,
               eval_its=None, eval_cnt=None, init_arr=None,
               renew_alpha=None):
    """``n_iters`` whole boosting iterations inside ONE program.

    Through a remote device tunnel every host dispatch costs seconds at 10M
    rows (measured ~5 s/iter of pure dispatch overhead vs the same body in
    a fori_loop), so the boosting loop itself runs on device in blocks:
    grads are recomputed from the carried score each trip — identical
    semantics to per-iteration dispatch.  ``it0`` and ``n_iters`` are
    traced, so one compiled program serves every chunk and tail length.

    Round-3 extensions (VERDICT r2 #2) let realistic configs chunk too:

    * **Bagging/colsample** — the host's Philox draws (the CPU-parity
      anchor) upload per chunk: ``bag_bits`` (CH, ceil(NP/8)) uint8 packs
      each iteration's row mask little-endian (unpacked on device),
      ``fmask_chunk`` (CH, F) carries the per-iteration feature masks.
    * **Validation** — per-tree valid-set scores update inside the loop
      (tree_leaves on the freshly written tree slot) and every
      ``eval_period``-th iteration evaluates ALL sets on device
      (metrics.device.eval_value), appending one (n_sets,) row into the
      carried ``eval_buf`` with its iteration id in ``eval_its``.  Nothing
      is fetched here; the host decides when to look.
    """
    n_valid = len(metric_names)

    def body(i, carry):
        out, score, vscores, eval_buf, eval_its, eval_cnt = carry
        if bag_bits is not None:
            u8 = bag_bits[i]                       # (ceil(NP/8),) uint8
            bits = ((u8[:, None] >> jnp.arange(8, dtype=jnp.uint8)) & 1)
            bag_i = bits.reshape(-1)[:score.shape[0]].astype(bool) & bag
        else:
            bag_i = bag
        fmask_i = fmask if fmask_chunk is None else fmask_chunk[i]
        # rf: grads at the CONSTANT init score (loop-invariant — XLA hoists
        # the computation out of the fori body); broadcast inside the trace
        # so no (NP, K) constant ships through the remote-compile tunnel
        score_g = (jnp.broadcast_to(init_arr.astype(jnp.float32),
                                    score.shape)
                   if p.boosting == "rf" else score)
        g_all, h_all = _grads_body(p, N, K, pad, score_g, y, weight, qoff,
                                   rank_row, rank_col, rank_Q, rank_S)
        out, score = _grow_iteration(
            p, B, has_cat, mesh, platform, learn_missing, out, score, Xb, y,
            g_all, h_all, bag_i, fmask_i, is_cat_feat, it0 + i, K,
            bmask=bmask, n_rows=N, renew_alpha=renew_alpha)

        if n_valid:
            new_vs = []
            for vi in range(n_valid):
                vs = vscores[vi]
                for k in range(K):
                    t = (it0 + i) * K + k
                    tree = {key: out[key][t] for key in _TREE_KEYS}
                    lv = tree_leaves(tree, vXbs[vi], out["max_depth"][t])
                    vs = vs.at[:, k].set(vs[:, k] + tree["value"][lv])
                new_vs.append(vs)
            vscores = tuple(new_vs)

            from dryad_tpu.metrics.device import eval_value

            it_now = it0 + i
            do_eval = (((it_now + 1) % eval_period == 0)
                       | (it_now + 1 == total_iters))

            def write(args):
                buf, its, cnt = args
                if p.boosting == "rf":
                    # score the AVERAGED model — same fp32 transform as
                    # predict (_rf_avg_jit / cpu mirror); the reciprocal is
                    # an exact IEEE division (identical to the host's), the
                    # iteration count is traced so it can't be host-side
                    initf = init_arr.astype(jnp.float32)
                    inv_it = jnp.float32(1.0) / (it_now + 1).astype(jnp.float32)
                    vs_eval = [initf + (vscores[vi] - initf) * inv_it
                               for vi in range(n_valid)]
                else:
                    vs_eval = list(vscores)
                vals = jnp.stack([
                    eval_value(metric_names[vi], ndcg_at, vys[vi],
                               vs_eval[vi], vqids[vi])
                    for vi in range(n_valid)])
                return (buf.at[cnt].set(vals), its.at[cnt].set(it_now),
                        cnt + 1)

            eval_buf, eval_its, eval_cnt = jax.lax.cond(
                do_eval, write, lambda a: a, (eval_buf, eval_its, eval_cnt))
        return out, score, vscores, eval_buf, eval_its, eval_cnt

    return jax.lax.fori_loop(
        0, n_iters, body,
        (out, score, tuple(vscores), eval_buf, eval_its, eval_cnt))


def _comm_stats(p, F: int, B: int, K: int, n_shards: int,
                shared_roots: bool = False,
                num_rows: int | None = None,
                padded_rows: int | None = None,
                platform: str | None = None,
                has_cat: bool = False) -> dict:
    """Static per-iteration collective payload, PER ARM (SURVEY.md §5
    observability; r16).  The payload is a pure function of the growth
    policy's per-level candidate widths — no runtime instrumentation
    needed (and none would survive jit without a host sync) — and the
    jaxpr auditor cross-checks every call count against the traced
    program (analysis/jaxpr_audit.py).

    Byte convention: each collective is accounted by the REDUCED/GATHERED
    output it delivers per device — psum: the full (..., 3, F, B) f32
    stack (each device receives the whole reduced array; the pre-r16
    numbers are unchanged); reduce-scatter: that stack / n_shards (each
    device receives only its owned feature slice, the (n-1)/n payload cut
    the feature arm exists for); all-gather: the gathered record block
    (n_shards * records).  Exact for the histogram collectives — incl.
    shallow levels on the natural-order pass, which slices its fixed
    16-slot kernel output to the P live slots BEFORE the reduction
    (pallas_hist.build_hist_small; ADVICE r3 #1/#2); the GOSS global sort
    and init-time collectives are excluded.

    Per-arm plan (``hist_reduce`` key):
    * fused — ONE fused grad/hess/count psum per builder call (root +
      every level), the classic contract.
    * feature — the ROOT keeps its fused psum (root_stats reads feature
      0's bins and one slot is noise); every LEVEL builder call issues
      one reduce-scatter of the feature-padded stack, plus ONE combine
      all-gather of the level's 2P packed best-split records (~29 + B
      bytes each).  The sequential (per-split) grower never consults the
      knob — its arm always reports fused."""
    from dryad_tpu.config import hist_reduce_resolved

    fb = 3 * F * B * 4
    L = p.effective_num_leaves
    level_synchronous = True
    if p.growth == "depthwise" and p.max_depth > 0:
        D = p.max_depth
        # the gate predicate and phase boundary are the growers' OWN
        # helpers (pallas_hist.nat_gate_admits, levelwise.phase_plan) so
        # this accounting cannot drift from the program choice (ADVICE r4)
        from dryad_tpu.engine import levelwise, pallas_hist
        from dryad_tpu.engine.histogram import resolve_backend

        bin_bytes = 1 if B <= 256 else 2
        # the nat gate sees the PADDED global matrix (shard shapes), the
        # leafwise envelope below the UNPADDED N (grower.py rule)
        gate_rows = padded_rows if padded_rows is not None else num_rows
        # r10: a layout-wired tree never builds the nat tiles (the wired
        # gate is consulted FIRST in grow_tree_levelwise), so its phase
        # plan runs nat_live=False — mirror that here or the accounted
        # d_switch/widths drift from the executed program
        use_layout = levelwise.deep_layout_supported(p, F, B, bin_bytes,
                                                     platform)
        nat_live = (not use_layout
                    and gate_rows is not None
                    and resolve_backend(p.hist_backend, segmented=True,
                                        platform=platform) == "pallas"
                    and pallas_hist.supports(B)
                    and pallas_hist.nat_gate_admits(gate_rows, F, bin_bytes))
        d_switch, P_narrow, P_full = levelwise.phase_plan(D, L, nat_live)
        scan_widths = [P_narrow] * d_switch + [P_full] * (D - d_switch)
        widths = list(scan_widths)
        level_calls = len(widths)
        if not p.hist_subtraction:
            # both children are histogrammed (no subtraction): the wired
            # path (r10 lift) pays ONE 2P-column hist_from_layout
            # reduction per level, the legacy path a P-column small pass
            # PLUS a P-column build_hist_multi — same bytes, more calls
            widths = [2 * w for w in widths]
            if not use_layout:
                level_calls = 2 * level_calls
    else:
        from dryad_tpu.engine import leafwise_fast

        if (p.growth == "leafwise"
                and leafwise_fast.supports(p, F, B, num_rows)):
            D = p.max_depth
            d_switch, P_narrow, Pf = leafwise_fast.phase_plan(D)
            scan_widths = [P_narrow] * d_switch + [Pf] * (D - d_switch)
            widths = list(scan_widths)
        else:
            widths = [1] * (L - 1)          # one masked pass per split
            scan_widths = list(widths)
            level_synchronous = False
        level_calls = len(widths)
    mode = (hist_reduce_resolved(p, F, B, n_shards)
            if level_synchronous else "fused")
    # multiclass shared-plan roots fold the K root passes into ONE psum of
    # the (K, 3, F, B) classes-builder output (same bytes, fewer calls)
    root_calls = 1 if (shared_roots and K > 1) else K
    if mode == "feature":
        n = max(int(n_shards), 1)
        fs = -(-F // n)                       # owned features per shard
        fb_slice = 3 * (fs * n) * B * 4 // n  # reduced slice delivered
        # one packed LocalSplit record per candidate child: the (8,)
        # uint32 word block (split.pack_local_split), plus the raw (B,)
        # bool categorical membership row on categorical configs — which
        # also rides its own gather, hence the per-level call count below
        rec_b = 8 * 4 + (B if has_cat else 0)
        ag_per_level = 2 if has_cat else 1
        psum_calls = root_calls
        psum_bytes = fb * K
        rs_calls = level_calls * K
        rs_bytes = K * sum(w * fb_slice for w in widths)
        ag_calls = len(scan_widths) * ag_per_level * K
        ag_bytes = K * sum(n * 2 * w * rec_b for w in scan_widths)
    else:
        psum_calls = root_calls + level_calls * K
        psum_bytes = (fb + sum(w * fb for w in widths)) * K  # root + levels
        rs_calls = rs_bytes = ag_calls = ag_bytes = 0
    return {
        "n_shards": int(n_shards),
        "hist_reduce": mode,
        "psum_calls_per_iter": psum_calls,
        "psum_bytes_per_iter": psum_bytes,
        "reduce_scatter_calls_per_iter": rs_calls,
        "reduce_scatter_bytes_per_iter": rs_bytes,
        "all_gather_calls_per_iter": ag_calls,
        "all_gather_bytes_per_iter": ag_bytes,
        "collective_calls_per_iter": psum_calls + rs_calls + ag_calls,
        "collective_bytes_per_iter": psum_bytes + rs_bytes + ag_bytes,
    }


def audit_iteration_fn(p, B, has_cat, mesh, platform, N, K=1, pad=0,
                       learn_missing=False, renew_alpha=None):
    """One whole boosting iteration as a pure traceable function — the
    jaxpr auditor's census hook (dryad_tpu/analysis/jaxpr_audit.py).

    Assembled from the SAME ``_grads_body`` / ``_goss_body`` /
    ``_step_body`` (plus the shared-plan multiclass root logic of
    ``_chunk_jit``) that the trainer dispatches, so the audited IR IS the
    trained program — a hand-maintained replica would drift exactly the
    way the grep lints this subsystem replaces did.  The returned function
    takes ``(out, score, Xb, y, bag, fmask, is_cat_feat)`` device arrays
    (abstract ``ShapeDtypeStruct`` values under ``jax.make_jaxpr``) and
    returns the updated ``(out, score)``; with ``mesh`` set the growers
    run under ``shard_map`` exactly as ``train_device`` runs them.
    Restricted to the arms the auditor traces: no lambdarank plan, no
    weights, no DART — those ride the per-iteration dispatch path whose
    collectives this same accounting already covers."""

    def fn(out, score, Xb, y, bag, fmask, is_cat_feat):
        g_all, h_all = _grads_body(p, N, K, pad, score, y, None, None,
                                   None, None, 0, 0)
        # iteration id traced (jnp.int32) exactly as the chunked loop's
        # it0 + i is — same program class, same dynamic tree-slot writes
        return _grow_iteration(
            p, B, has_cat, mesh, platform, learn_missing, out, score, Xb, y,
            g_all, h_all, bag, fmask, is_cat_feat, jnp.int32(0), K,
            n_rows=N, renew_alpha=renew_alpha)

    return fn


def _shared_roots_ok(p, platform) -> bool:
    """Shared-plan (XLA classes-builder) roots for multiclass ONLY where
    the masked histogram backend resolves to XLA anyway (CPU / non-TPU):
    there one fused (2K+1)-row pass beats K one-hot passes.  On TPU the
    round-4 kernel made per-class masked Pallas roots the winner — 52 vs
    103 ms at Covertype K=3, a dead tie at K=7 (exp_r4_roots.py,
    stall-robust min-of-3) — so every class simply grows its own root
    through the SAME build_hist path used everywhere else (one program,
    1-shard ≡ N-shard trivially; VERDICT r3 #8 resolved by measurement).
    """
    from dryad_tpu.engine.histogram import resolve_backend

    return resolve_backend(p.hist_backend, platform=platform) != "pallas"


@partial(jax.jit, static_argnames=("B", "rpc", "precision", "mesh"))
def _roots_jit(B, rpc, precision, mesh, Xb, g_all, h_all, bag):
    """Shared-plan multiclass root histograms (per-iteration dispatch path);
    with a mesh, the same builder runs under shard_map + one fused psum."""
    if mesh is not None:
        from dryad_tpu.engine.distributed import roots_sharded

        return roots_sharded(mesh, Xb, g_all, h_all, bag, B, rpc, precision)
    from dryad_tpu.engine.histogram import build_hist_classes

    return build_hist_classes(Xb, g_all, h_all, bag, B, rows_per_chunk=rpc,
                              precision=precision)


def _goss_uniform_dev(seed: int, iteration, num_rows: int) -> jnp.ndarray:
    """Device twin of ``cpu.trainer.goss_uniform`` — the same u32
    murmur3-finalizer hash of (seed, iteration, row id), traced so the
    chunked boosting program draws each iteration's uniforms ON DEVICE
    (the upload that forced GOSS onto per-iteration dispatch is gone).
    ``iteration`` is a traced int32; bit-identity with the host generator
    is pinned by test_goss_monotone."""
    M1, M2 = jnp.uint32(0x85EBCA6B), jnp.uint32(0xC2B2AE35)
    key = (jnp.uint32((seed * 0x9E3779B9 + 0x165667B1) % (1 << 32))
           + iteration.astype(jnp.uint32) * jnp.uint32(0x7FEB352D))
    key ^= key >> jnp.uint32(16)
    key = key * M1
    key ^= key >> jnp.uint32(13)
    key = key * M2
    key ^= key >> jnp.uint32(16)
    x = jnp.arange(num_rows, dtype=jnp.uint32) * jnp.uint32(0x9E3779B9)
    x ^= key
    x ^= x >> jnp.uint32(16)
    x = x * M1
    x ^= x >> jnp.uint32(13)
    x = x * M2
    x ^= x >> jnp.uint32(16)
    return (x >> jnp.uint32(8)).astype(jnp.float32) * jnp.float32(
        1.0 / (1 << 24))


def _goss_body(p, N, g_all, h_all, u, valid):
    """Device GOSS (mirrors cpu/trainer.py::goss_select_np — both run the
    selection in f32 so boundary rows classify identically): amplified
    grad/hess + the row mask.  ``valid`` excludes padded rows, whose real
    gradients must never compete in the top-quantile."""
    absg = jnp.sqrt(jnp.sum(g_all.astype(jnp.float32) ** 2, axis=1))
    absg = jnp.where(valid, absg, jnp.float32(-1.0))
    top_n = max(1, int(round(p.goss_top_rate * N)))
    thr = jnp.sort(absg)[absg.shape[0] - top_n]
    is_top = valid & (absg >= thr)
    n_top = jnp.sum(is_top.astype(jnp.int32))
    p_pick = jnp.minimum(
        jnp.float32(1.0),
        jnp.float32(p.goss_other_rate * N)
        / jnp.maximum(N - n_top, 1).astype(jnp.float32))
    picked = valid & ~is_top & (u < p_pick)
    amp = jnp.float32((1.0 - p.goss_top_rate) / p.goss_other_rate)
    w = jnp.where(picked, amp, jnp.float32(1.0))[:, None]
    return g_all * w, h_all * w, is_top | picked


_goss_jit = partial(jax.jit, static_argnames=("p", "N"))(_goss_body)


_dart_replay_jit = partial(jax.jit, static_argnames=("depth_bound",))(
    lambda trees, Xb, init, depth_bound: _accumulate(
        trees, Xb, init, depth_bound))


@jax.jit
def _rf_avg_jit(vs, init, inv):
    """rf eval transform: averaged raw score init + (Σ - init)*(1/n) with
    the HOST-computed reciprocal — the same arithmetic as both predict
    paths (cpu/predict.py), so the metric scores the model predict would
    serve (up to device FMA fusion of the multiply-add, a 1-ulp
    tie-flip-only difference)."""
    initf = init.astype(jnp.float32)
    return initf + (vs - initf) * inv


@partial(jax.jit, static_argnames=("depth_bound",))
def _dart_drop_jit(out, score, tids, tcls, Xb, factor_drop, depth_bound):
    """DART drop bookkeeping in ONE dispatch: ``tids`` (max_drop*K,)
    padded with -1 names the dropped tree slots, ``tcls`` their class
    columns, ``factor_drop`` = f32(k/(k+1)) computed HOST-side (the same
    rounding the CPU mirror uses — deriving it on device as 1 - 1/(k+1)
    lands 1 ulp off at e.g. k=2 and would let near-tie splits diverge by
    backend).  Returns (score - dcontrib, value table with dropped rows
    * factor_drop).  ``depth_bound`` is a STATIC bound >= any tree's
    depth — traversal is exact for any such bound, and out["max_depth"]
    cannot be trusted here (resume restores tree arrays but not the
    per-slot depth log, and the resumed run must reproduce the
    uninterrupted one bitwise)."""

    def body(i, acc):
        t = jnp.maximum(tids[i], 0)
        tree = {key: out[key][t] for key in _TREE_KEYS}
        lv = tree_leaves(tree, Xb, depth_bound)
        c = tree["value"][lv] * (tids[i] >= 0).astype(jnp.float32)
        return acc.at[:, tcls[i]].add(c)

    dcontrib = jax.lax.fori_loop(0, tids.shape[0], body,
                                 jnp.zeros_like(score))
    T = out["value"].shape[0]
    newval = out["value"].at[
        jnp.where(tids >= 0, tids, T)].multiply(factor_drop, mode="drop")
    return score - dcontrib, newval


@jax.jit
def _apply_valid_jit(out, t, vXb, vs_col, depth_bound):
    tree = {key: out[key][t] for key in _TREE_KEYS}
    leaves = tree_leaves(tree, vXb, depth_bound)
    return vs_col + tree["value"][leaves]


def _empty_out_device(T: int, M: int, cat_words: int) -> dict:
    return {
        "feature": jnp.full((T, M), -1, jnp.int32),
        "threshold": jnp.zeros((T, M), jnp.int32),
        "left": jnp.zeros((T, M), jnp.int32),
        "right": jnp.zeros((T, M), jnp.int32),
        "value": jnp.zeros((T, M), jnp.float32),
        "is_cat": jnp.zeros((T, M), bool),
        "cat_bitset": jnp.zeros((T, M, cat_words), jnp.uint32),
        "gain": jnp.zeros((T, M), jnp.float32),
        "default_left": jnp.ones((T, M), bool),
        "cover": jnp.zeros((T, M), jnp.float32),
        "max_depth": jnp.zeros((T,), jnp.int32),
    }


def _materialize(p, mapper, out, T, init, max_depth_prev, best_iteration,
                 best_value=None, stale=0) -> Booster:
    """Fetch the device tree tables (the one forced sync) into a Booster."""
    host = {key: np.asarray(out[key][:T]) for key in _TREE_KEYS}
    depths = np.asarray(out["max_depth"][:T])
    max_depth_seen = max(int(depths.max(initial=0)), max_depth_prev)
    return Booster(
        p, mapper,
        host["feature"], host["threshold"], host["left"], host["right"],
        host["value"], host["is_cat"], host["cat_bitset"],
        init, max_depth_seen,
        best_iteration=best_iteration,
        gain=host["gain"],
        train_state={"best_value": best_value, "stale": int(stale)},
        default_left=host["default_left"],
        cover=host["cover"],
    )


def train_device(
    params: Params,
    data: Dataset,
    valid: Optional[Dataset] = None,
    *,
    num_trees: Optional[int] = None,
    init_booster: Optional[Booster] = None,
    callback: Optional[Callable[[int, dict], None]] = None,
    mesh=None,
    checkpointer=None,
    chunk_hook: Optional[Callable[[str, int], None]] = None,
    chunk_policy=None,
) -> Booster:
    """Device trainer.  With ``mesh`` set, rows are sharded over the mesh's
    data axis and histograms allreduced by psum (engine/distributed.py).

    ``chunk_hook(site, iteration)`` observes the boosting loop's host-side
    events — ``site`` is ``"dispatch"`` (a chunk/iteration is about to be
    enqueued) or ``"fetch"`` (a real device->host fetch is about to run:
    calibration, run-ahead throttle, eval read, checkpoint/final
    materialize).  The resilience supervisor journals these and the
    deterministic fault injector raises the recorded tunnel error classes
    from them (resilience/faults.py); ``None`` (the default) costs nothing.
    ``chunk_policy`` is a live cap on chunk length (``cap() -> int``, 0 =
    uncapped, plus ``note_dispatch(n)`` / ``note_clean_chunk(n)`` feedback
    — the dispatch-time length report is load-bearing: the policy's
    degrade step must undercut what actually ran) consulted per chunk
    AFTER path selection and calibration, so the supervisor's mid-run
    degradation can never flip the compiled program — only shorten chunks
    (resume bit-identity is preserved by construction; chunk length is a
    traced scalar of one shared executable)."""
    p = params.validate()
    N, F = data.num_rows, data.num_features
    B = data.mapper.total_bins
    # documented max_depth=-1 policy (identical mapping on the CPU backend,
    # so cross-backend parity is untouched)
    p = effective_depth_params(p, F, B, N)
    obj = get_objective(p)
    K = p.num_outputs
    is_cat_np = data.mapper.is_categorical
    has_cat = bool(is_cat_np.any())
    T = (num_trees if num_trees is not None else p.num_trees) * K

    pad = 0
    shard_rows = None
    if mesh is not None:
        if getattr(data, "is_streamed", False):
            raise ValueError(
                "streamed datasets cannot train with mesh=...: the sharded "
                "arm pads and shards the resident matrix host-side — "
                "materialize() the dataset or train unsharded (on-device "
                "streaming past HBM is the staged follow-up)")
        from dryad_tpu.engine.distributed import padded_rows, shard_rows

        Xb_np, y_np = data.X_binned, data.y
        w_np = data.weight
        Np = padded_rows(N, mesh.devices.size)
        pad = Np - N
        if pad:
            Xb_np = np.pad(Xb_np, ((0, pad), (0, 0)))
            y_np = np.pad(y_np, (0, pad))
            if w_np is not None:
                w_np = np.pad(w_np, (0, pad))
        Xb, y = shard_rows(mesh, jnp.asarray(Xb_np), jnp.asarray(y_np))
        weight = shard_rows(mesh, jnp.asarray(w_np))[0] if w_np is not None else None
    else:
        # memoized on the Dataset: repeated train calls (bench arms, warm
        # restarts, parameter sweeps) skip the X upload entirely.  On a
        # StreamedDataset this is the overlapped chunk-by-chunk assembly
        # (prefetch read i+1 vs async device_put of i) — the jitted
        # programs downstream are IDENTICAL to the resident path, so the
        # audit goldens and _comm_stats are untouched by streaming.
        Xb, y, weight = data.device_arrays()
    NP = N + pad
    is_cat_feat = jnp.asarray(is_cat_np)
    qoff = data.query_offsets

    init = np.asarray(obj.init_score(data.y, data.weight), np.float32).reshape(-1)
    if init_booster is not None:
        # the carried base score is part of the model: a continuation (and
        # especially an r19 warm-start append on FRESH rows) must not
        # re-derive it from the current label distribution, or a 0-tree
        # append would shift every prediction.  Checkpoint resume is
        # unchanged bitwise — same labels produced the same init; this
        # runs BEFORE the rf constant-gradient capture below for the same
        # reason.
        init = np.asarray(init_booster.init_score, np.float32).reshape(-1)
    score = jnp.broadcast_to(jnp.asarray(init), (NP, K)).astype(jnp.float32)
    if mesh is not None:
        score = shard_rows(mesh, score)[0]

    rank_row = rank_col = None
    rank_Q = rank_S = 0
    qoff_j = None
    if p.objective == "lambdarank":
        from dryad_tpu.engine.lambdarank import PaddingPlan

        rank_plan = PaddingPlan(np.asarray(qoff))  # loop-invariant scatter plan
        rank_row, rank_col = rank_plan.row_ids, rank_plan.col_ids
        rank_Q, rank_S = rank_plan.Q, rank_plan.S
        qoff_j = jnp.asarray(qoff)

    # the devices that actually run the step may differ from the process
    # default backend (e.g. a CPU mesh forced on a TPU-attached process) —
    # resolve 'auto' against the real target platform all the way down
    plat = (mesh.devices.flat[0].platform if mesh is not None
            else jax.devices()[0].platform)

    # static jit key: strip fields that cannot affect the compiled programs
    # so e.g. a warmup run with fewer trees reuses the same executables
    # (ch_max only sizes host-side chunking, so supervisor retries that
    # vary the cap keep sharing one program)
    p_key = p.replace(num_trees=1, early_stopping_rounds=0, metric="",
                      ch_max=0)

    def grads(score):
        return _grads_jit(p_key, N, K, pad, score, y, weight, qoff_j,
                          rank_row, rank_col, rank_Q, rank_S)

    # rf: grad/hess at the CONSTANT init score, computed ONCE — trees
    # de-correlate only through the per-iteration bag (config.py rf note);
    # `score` itself still accumulates tree sums (predict-time averaging)
    rf_gh = grads(score) if p.boosting == "rf" else None
    # loop-invariant device-resident init, shared by the rf eval transform
    # and every chunk dispatch (re-wrapping the host array per call costs
    # a tunnel upload each); replicated explicitly on a mesh so the chunk
    # jit never sees mixed placements
    if mesh is not None:
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as _PS

        init_dev = jax.device_put(np.asarray(init),
                                  NamedSharding(mesh, _PS()))
    else:
        init_dev = jnp.asarray(init)

    learn_missing = data.has_missing
    if jax.process_count() > 1:
        # multi-host: the flag is a static jit arg and rows are sharded per
        # process — agree globally (any host has missing => all scan both
        # planes) or hosts would trace divergent programs and grow
        # different trees, breaking N-shard ≡ 1-shard
        from jax.experimental import multihost_utils

        learn_missing = bool(
            multihost_utils.process_allgather(np.int32(learn_missing)).max())

    comm = (_comm_stats(p_key, F, B, K, mesh.devices.size,
                        shared_roots=K > 1 and _shared_roots_ok(p, plat),
                        num_rows=N, padded_rows=NP, platform=plat,
                        has_cat=has_cat)
            if mesh is not None else None)
    if comm is not None:
        # comm-payload observability (r16): the static accounting becomes
        # dryad_comm_* gauges at this compile boundary, so a reduce-payload
        # regression (or an arm flip) is trend-visible on /metrics.  The
        # export is jax-free on the obs side (obs/comm.py) and a no-op on
        # a disabled registry.
        from dryad_tpu.obs.comm import export_comm_stats

        export_comm_stats(comm, growth=p.growth)

    # EFB bundle columns are masked out of the missing-right split plane
    # (their bin 0 means "all default", not "missing"); only materialized
    # when the plane is scanned at all, so NaN-free programs are unchanged
    bundled_np = getattr(data.mapper, "bundled_mask", None)
    bmask = (jnp.asarray(bundled_np)
             if learn_missing and bundled_np is not None and bundled_np.any()
             else None)

    # L1-family leaf renewal — the gate (weighted / boosting / monotone)
    # lives wholly in renew_alpha; imported LATE so test monkeypatching of
    # dryad_tpu.objectives.renew_alpha reaches this trainer too
    from dryad_tpu.objectives import renew_alpha as _obj_renew_alpha

    renew_a = _obj_renew_alpha(p, weighted=data.weight is not None)

    def step(out, score, g_all, h_all, bag, fmask, t, k, root_hist=None,
             value_scale=None):
        return _step_jit(p_key, B, has_cat, mesh, plat, learn_missing, out,
                         score, Xb, g_all, h_all, bag, fmask, is_cat_feat, t, k,
                         root_hist, bmask, n_rows=N, value_scale=value_scale,
                         y=y, renew_alpha=renew_a)

    # ---- resume / warm start -------------------------------------------------
    out = _empty_out_device(T, p.max_nodes, CAT_WORDS)
    start_iter = 0
    max_depth_prev = 0
    prev_trees = None
    if init_booster is not None:
        prev = init_booster
        if prev.params.max_nodes != p.max_nodes or prev.num_outputs != K:
            raise ValueError(
                "init_booster is incompatible: num_leaves/max_depth/num_class must match"
            )
        if prev.num_total_trees > T:
            raise ValueError("new num_trees must cover the init_booster's iterations")
        if ("rf" in (prev.params.boosting, p.boosting)
                and prev.params.boosting != p.boosting):
            raise ValueError(
                "cannot continue training across rf and non-rf boosting: "
                "rf predictions AVERAGE the trees, so a mixed tree table "
                "has no sound aggregation")
        prev_trees = {
            key: jnp.asarray(v).reshape((prev.num_iterations, K) + v.shape[1:])
            for key, v in prev.tree_arrays().items()
        }
        # same fp32 order as the CPU replay: broadcast(new init) += each tree
        score = _accumulate(prev_trees, Xb, jnp.asarray(init),
                            max(prev.max_depth_seen, 1))
        for key in _TREE_KEYS:
            out[key] = out[key].at[: prev.num_total_trees].set(
                jnp.asarray(prev.tree_arrays()[key]))
        start_iter = prev.num_iterations
        max_depth_prev = prev.max_depth_seen

    # every valid set is scored ON DEVICE (metrics/device.py); the FIRST
    # drives early stopping.  When something needs the value mid-run (early
    # stopping, a callback, checkpoint state) each eval fetches ONE f32
    # scalar; otherwise all evals stay device-side until training ends and
    # best_iteration is replayed from the bulk fetch — zero per-iteration
    # syncs even with validation.
    from dryad_tpu.cpu.trainer import normalize_valids
    from dryad_tpu.metrics.device import make_evaluator

    valids = normalize_valids(valid)
    for vname, vds in valids:
        if getattr(vds, "is_streamed", False):
            raise ValueError(
                f"valid set {vname!r} is streamed: device eval scores the "
                "resident matrix — materialize() it (valid sets are small "
                "relative to the training corpus)")
    evaluators = [make_evaluator(p.objective, p.metric, vds, p.ndcg_at)
                  for _, vds in valids]
    # a checkpointer does NOT force per-eval syncs: deferred evals are
    # flushed (bulk fetch + replay) right before each due checkpoint so the
    # saved best_iteration/stale state is exact
    sync_eval = bool(p.early_stopping_rounds) or callback is not None
    deferred: list[tuple[int, list]] = []
    # resume keeps the prior segment's deferred history so the merged run
    # matches the uninterrupted one (CLAUDE.md resume invariant)
    eval_history: dict[str, list] | None = None
    if init_booster is not None and init_booster.train_state.get("eval_history"):
        eval_history = {k: list(v) for k, v in
                        init_booster.train_state["eval_history"].items()}
    vXbs = [jnp.asarray(v.X_binned) for _, v in valids]
    vscores = [
        jnp.broadcast_to(jnp.asarray(init), (v.num_rows, K)).astype(jnp.float32)
        for _, v in valids
    ]
    if init_booster is not None:
        vscores = [
            _accumulate(prev_trees, vXb, jnp.asarray(init),
                        max(max_depth_prev, 1))
            for vXb in vXbs
        ]
    best_iteration, best_value, stale = -1, None, 0
    if init_booster is not None and p.boosting != "dart":
        # resume continues the eval/early-stop state exactly where it
        # stopped; DART continuations must NOT inherit a recorded
        # best_iteration (the coming drops rescale trees inside that
        # prefix — see update_best), and DART's own checkpoints carry -1
        best_iteration = init_booster.best_iteration
        best_value = init_booster.train_state.get("best_value")
        stale = init_booster.train_state.get("stale", 0)

    def fold_eval_row(it_d, vals):
        """Fold one eval's values into eval_history + best-iteration state —
        the ONE bookkeeping used by every deferred replay (per-iteration
        deferred flush and the chunked path's buffer flush), so the two can
        never diverge.  DART keeps eval_history but never records
        best_iteration (update_best itself is the no-op — see its
        docstring)."""
        nonlocal best_iteration, best_value, stale, eval_history
        _, higher0, _ = evaluators[0]
        if eval_history is None:
            eval_history = {}
        for vi, ((vname, _), (mname, _, _)) in enumerate(
                zip(valids, evaluators)):
            eval_history.setdefault(f"{vname}_{mname}", []).append(
                [int(it_d), float(vals[vi])])
        best_iteration, best_value, stale = update_best(
            p, best_iteration, best_value, stale, int(it_d), float(vals[0]),
            higher0)

    def flush_deferred():
        """Bulk-fetch pending deferred evals and replay the bookkeeping via
        the shared update_best — called before each due checkpoint and at
        training end, so the deferred path's state is exact wherever it is
        observed while staying fetch-free in between."""
        if not deferred:
            return
        fetched = jax.device_get([vals for _, vals in deferred])
        for (it_d, _), vals in zip(deferred, fetched):
            fold_eval_row(it_d, vals)
        deferred.clear()

    # pad rows are bagged out permanently: they must never touch a histogram
    ones_rows = jnp.asarray(np.pad(np.ones((N,), bool), (0, pad)))
    if mesh is not None:
        ones_rows = shard_rows(mesh, ones_rows)[0]
    ones_feat = jnp.ones((F,), bool)

    # ---- chunked fast path: whole iterations inside one program --------------
    # When nothing needs the host between iterations (no bagging/colsample
    # Philox draw, no GOSS uniforms, no validation sync) the boosting loop
    # runs on device in blocks — through the remote tunnel each host
    # dispatch costs ~5 s at 10M rows, the dominant non-compute cost.
    #
    # ACCEPTED TOLERANCE (same class as the CPU↔TPU near-tie note in
    # CLAUDE.md): the chunked program compiles the boosting step into a
    # DIFFERENT fusion shape than per-iteration dispatch, so merely adding
    # a validation set or subsample<1 (which switches paths) can flip a
    # near-tie split argmax on device.  Path selection is a deterministic
    # function of (params, valids), so resume and N-shard ≡ 1-shard — which
    # never change the path mid-run — are unaffected; only configs that
    # *straddle* the condition may see ulp-level tree differences, with
    # model quality untouched.
    # The same tolerance class covers the deep-phase data-movement choice
    # (r6): ``deep_layout="auto"`` carries the leaf-ordered record layout
    # through levelwise's deep levels (levelwise.deep_layout_supported
    # gates it on params + feature/bin shape, never rows, so every shard
    # and every run of one config picks the same path deterministically),
    # while "legacy" keeps the per-level sort + record gather.  Post-
    # permute layouts regroup per-tile f32 histogram partials at ulp
    # level, so flipping the knob — like switching dispatch ↔ chunked —
    # may flip a near-tie argmax on device; counts stay exact and the
    # smoke gate (scripts/smoke_tpu.py) pins bitwise tree equality on the
    # tie-free fixture.
    # Round 3: bagged/colsampled runs chunk too (host Philox masks upload
    # bit-packed per chunk), and validated runs evaluate INSIDE the chunk
    # program.  Round 4 (VERDICT r3 #4/#6): sharded bagged runs chunk as
    # well — the packed masks replicate over the mesh and each device
    # unpacks + slices its own rows, so no shard alignment is needed —
    # and GOSS chunks too, its uniforms drawn ON DEVICE per iteration by
    # the counter-based hash shared bit-for-bit with the CPU backend
    # (_goss_uniform_dev).  Per-iteration dispatch remains only for
    # host-fallback metrics and early stopping at eval_period=1 (the
    # value gates the next iteration, so a fetch per iteration is
    # semantically required).
    bagging = p.subsample < 1.0 or p.colsample < 1.0
    host_eval = any(getattr(fn, "host_only", True) for _, _, fn in evaluators)
    chunkable = (not (valids and host_eval)
                 and not (valids and p.early_stopping_rounds
                          and p.eval_period < 2)
                 # DART mutates previously grown trees every iteration
                 # (drop + rescale) — host-orchestrated dispatch only
                 and p.boosting != "dart")
    if chunkable:
        # the tunnel kills single programs running longer than ~60 s
        # (measured: 45 s OK, 65 s crashes the worker) — budget ~40 s per
        # chunk from a measured iteration-cost model calibrated at 10M
        # rows x 28 features x 256 bins (1.6e-7 s/row/class/pass) and
        # scaled by F·B, since histogram work is O(N·F·B) per pass
        # (Epsilon's 2000 features once packed a chunk ~70x past the
        # budget and the watchdog killed the worker).  Depthwise pays one
        # batched pass per level; leaf-wise one full-N masked pass per
        # SPLIT (L-1), so its estimate scales with the leaf budget.
        if p.growth == "depthwise" and p.max_depth > 0:
            passes_est = p.max_depth
        else:
            from dryad_tpu.engine import leafwise_fast

            if (p.growth == "leafwise"
                    and leafwise_fast.supports(p, F, B, N)):
                # batched leaf-wise: one level pass per expansion depth
                passes_est = p.max_depth
            else:
                passes_est = max(8, p.effective_num_leaves - 1)
        est_iter_s = (1.6e-7 * NP * K * passes_est
                      * max(F / 28.0, 1.0) * max(B / 256.0, 1.0))
        # per-MAC model (round 4): histogram work is N·K·passes·F·B MACs and
        # 5e-15 s/MAC sits mid-range of the measured configs (10M Higgs
        # 2.9 est vs 3.0 actual; Epsilon 8.2 vs 10.2; Covertype 2.3 vs
        # 1.15) — far tighter than the per-row model above, which
        # over-estimates up to 8x off its calibration point.  LambdaMART
        # keeps the over-estimating per-row model for chunk sizing: its λ
        # pass scales with query sizes the MAC model cannot see.
        est_iter_mac = 0.05 + 5e-15 * NP * K * passes_est * F * B
        est_for_ch = (est_iter_s if p.objective == "lambdarank"
                      else est_iter_mac)
        # 25 s budget on the tighter model (was 40 s on the loose one):
        # the ~60 s tunnel watchdog keeps 2.4x headroom even where the MAC
        # model under-estimates (Epsilon 1.25x); the second-chunk
        # calibration still re-derives CH from measurement either way
        CH = max(1, min(64, int(25.0 / max(est_for_ch, 1e-3))))
        # The chunk-length cap (initial AND calibrated) — an operational
        # escape hatch for tunnel phases that kill standard-length (~20 s)
        # chunk executions: the 2026-07-31 500-tree 10M headline runs died
        # 6/6 with CH 6-8 while CH <= 2 runs sailed through (same program,
        # same data).  Off by default.  Precedence (documented on
        # Params.ch_max): the DRYAD_CH_MAX env var, when set > 0, OVERRIDES
        # the threaded param; otherwise Params.ch_max applies; the
        # supervisor's chunk_policy caps individual chunks below either,
        # inside the loop.
        _ch_env = int(os.environ.get("DRYAD_CH_MAX", "0"))
        _ch_max = _ch_env if _ch_env > 0 else int(p.ch_max)
        if _ch_max > 0:
            CH = min(CH, _ch_max)
        # The cost model overestimates (measured 1.7-4x — fixed overheads
        # amortize sublinearly), so a model-derived CH of 1 may really
        # afford 2-4 iterations: admit single-iteration chunks when the
        # ESTIMATE itself fits the watchdog and let the second-chunk
        # calibration raise CH from measurement.  F*B caps program width
        # (remote-compile size guard, verified up to Epsilon's 2000*256).
        # (the model has only ever OVER-estimated, so an estimate within
        # the ~60 s watchdog means a real 1-iteration program is safe)
        chunkable = ((CH >= 2 or est_iter_s <= 40.0)
                     and F * B <= _CHUNK_FB_LIMIT)
    if chunkable:
        # VERDICT r3 #5: the chunk program's ONE-TIME remote compile scales
        # with program width (~K·F·B) and can dominate a short run (Epsilon
        # 20-tree acceptance: +204 s of compile for 204 s of training).
        # Skip chunking when the estimated total work is small next to the
        # estimated compile SURPLUS over the per-iteration path's own
        # compile.  The per-MAC work model here is separate from the
        # watchdog's est_iter_s above, which deliberately over-estimates
        # (safety); this one aims at the middle of the measured range so
        # the comparison is fair.  DRYAD_CHUNK=1 skips THIS heuristic only
        # (the base eligibility gates above — program-width limit,
        # watchdog sizing — still apply: overriding them would compile
        # unverified program widths or outrun the tunnel watchdog);
        # DRYAD_CHUNK=0 disables chunking outright.  bench.py pins =1 so
        # its short marginal arms measure the long-run chunked steady
        # state.  Unset keeps the deterministic (params, shapes) rule.
        _force = os.environ.get("DRYAD_CHUNK", "")
        if _force in ("0", "1"):
            chunkable = _force == "1"
        elif plat != "cpu":
            # remote/accelerator compile only — on the CPU backend (tests,
            # local runs) compile is cheap and chunking always pays
            compile_surplus = 15.0 + 4.5e-4 * K * F * B
            # FULL-run work, not the remaining segment: path choice must be
            # a pure function of (params, shapes) or a resumed run could
            # take a different program than the uninterrupted one and break
            # the resume bit-identity invariant (fusion-shape tolerance).
            # est_for_ch, not est_iter_mac: lambdarank's λ pass is
            # invisible to the MAC model (see chunk sizing above)
            chunkable = (T // K) * est_for_ch > compile_surplus
    if chunkable:
        import time as _time

        total_iters = T // K
        if (valids and p.early_stopping_rounds
                and stale >= p.early_stopping_rounds):
            total_iters = start_iter   # resume landed ON the stop boundary

        # eval machinery (device-resident; one (n_sets,) row per eval)
        n_sets = len(valids)
        metric_names = tuple(mname for mname, _, _ in evaluators)
        vXbs_t = tuple(vXbs)
        vys_t = tuple(fn.y_dev for _, _, fn in evaluators)
        vqids_t = tuple(fn.qids for _, _, fn in evaluators)
        eval_buf = jnp.zeros((max(total_iters, 1), n_sets), jnp.float32) \
            if n_sets else None
        eval_its = jnp.full((max(total_iters, 1),), -1, jnp.int32) \
            if n_sets else None
        eval_cnt = jnp.int32(0) if n_sets else None
        vscores_t = tuple(vscores)
        host_cnt = 0        # slots the host knows are written
        flushed_cnt = 0     # slots already folded into best/history state

        def eval_iters_in(lo, hi):
            return [j for j in range(lo, hi)
                    if (j + 1) % p.eval_period == 0 or j + 1 == total_iters]

        def next_eval_end(lo):
            j = lo
            while not ((j + 1) % p.eval_period == 0 or j + 1 == total_iters):
                j += 1
            return j + 1

        def flush_chunk_evals(upto):
            """Fold fetched eval rows [flushed_cnt, upto) into
            best-iteration state + eval_history via the shared
            fold_eval_row (the deferred-path replay, exact wherever it is
            observed)."""
            nonlocal flushed_cnt
            if upto <= flushed_cnt:
                return
            with span("train.fetch.eval_flush"):
                vals, its_arr = jax.device_get(
                    (eval_buf[flushed_cnt:upto], eval_its[flushed_cnt:upto]))
            for row, it_d in zip(np.asarray(vals), np.asarray(its_arr)):
                fold_eval_row(it_d, row)
            flushed_cnt = upto

        # per-chunk Philox mask upload buffers (fixed CH0 rows: a varying
        # leading dim would recompile the chunk program per tail length)
        CH0 = CH
        nbytes = (NP + 7) // 8
        row_sampled = p.subsample < 1.0
        col_sampled = p.colsample < 1.0

        # adaptive chunk budget: the 1.6e-7 model above is only the FIRST
        # guess — the second chunk (the first one free of compile time) is
        # timed and CH re-derived from measurement, never exceeding half
        # the ~60 s tunnel watchdog.  Mask uploads pin the array shape, so
        # CH can only shrink below CH0 once those exist.
        chunk_idx = 0
        t_mark = None
        calibrated = False
        inflight: list = []
        _obs = default_registry()
        # bound handles per the registry's hot-loop contract (no per-chunk
        # family lookup); bound on FIRST enabled use — eager binding would
        # register the families on a disabled registry
        _obs_chunks = _obs_iter = None
        # recompile tripwire (r12): a fresh run legitimately compiles its
        # chunk program once; after the first dispatch the family is ARMED
        # and any NEW program key (a mid-run p_key change — nothing may
        # cause one) fires dryad_recompile_unexpected_total + /healthz
        _tw = default_tripwire()
        _tw.begin_program("train.chunk")
        _shards_lbl = mesh.devices.size if mesh is not None else 1

        it = start_iter
        while it < total_iters:
            n = min(CH, total_iters - it)
            # the supervisor's live cap applies HERE — after path selection
            # and independent of calibration — so degradation mid-run only
            # shortens chunks (traced scalar), never changes the program
            ch_eff = _ch_max
            if chunk_policy is not None:
                cap_dyn = int(chunk_policy.cap())
                if cap_dyn > 0:
                    n = min(n, cap_dyn)
                    ch_eff = min(ch_eff, cap_dyn) if ch_eff > 0 else cap_dyn
            if checkpointer is not None:
                # land chunk ends exactly on checkpoint boundaries
                n = min(n, checkpointer.every - (it % checkpointer.every))
            if valids and p.early_stopping_rounds:
                # early stopping reads each eval before growing past it:
                # every chunk must END on an eval boundary
                n = min(n, next_eval_end(it) - it)
            if chunk_policy is not None:
                # report the length BEFORE anything can fault: a death at
                # this chunk's first fetch must still leave the policy
                # knowing what length was fatal (resilience/policy.py)
                chunk_policy.note_dispatch(n)
            if chunk_hook is not None:
                chunk_hook("dispatch", it)
            # None (not 0.0) when disabled: an enable() landing mid-chunk
            # must not record a since-process-boot wall into the counters
            _t_ch = _time.perf_counter() if _obs.enabled else None

            bag_bits = fmask_chunk = None
            if bagging:
                bb = (np.zeros((CH0, nbytes), np.uint8) if row_sampled
                      else None)
                fm = (np.ones((CH0, F), bool) if col_sampled else None)
                for j in range(n):
                    rm, fmk = sample_masks(p, it + j, N, F)
                    if bb is not None:
                        row = np.ones(N, bool) if rm is None else rm
                        bb[j] = np.packbits(np.pad(row, (0, pad)),
                                            bitorder="little")
                    if fm is not None and fmk is not None:
                        fm[j] = fmk
                if mesh is not None:
                    # replicate the packed masks over the mesh explicitly: a
                    # plain asarray commits to one device and the chunk jit
                    # would reject mixed placements.  The devices unpack the
                    # replicated bytes and slice their own row range — bit
                    # packs need no shard alignment (VERDICT r3 #6).
                    from jax.sharding import NamedSharding
                    from jax.sharding import PartitionSpec as PS

                    rep = NamedSharding(mesh, PS())
                    bag_bits = (jax.device_put(bb, rep)
                                if bb is not None else None)
                    fmask_chunk = (jax.device_put(fm, rep)
                                   if fm is not None else None)
                else:
                    bag_bits = jnp.asarray(bb) if bb is not None else None
                    fmask_chunk = jnp.asarray(fm) if fm is not None else None

            _chunk_args = (
                p_key, B, has_cat, mesh, plat, learn_missing, N, K, pad,
                rank_Q, rank_S, out, score, Xb, y, weight, ones_rows,
                ones_feat, is_cat_feat, qoff_j, rank_row, rank_col,
                jnp.int32(it), jnp.int32(n), bmask, bag_bits, fmask_chunk,
                metric_names, p.ndcg_at, p.eval_period, total_iters,
                vXbs_t, vys_t, vqids_t, vscores_t, eval_buf, eval_its,
                eval_cnt)
            if _obs.enabled:
                # compile-boundary introspection: the first chunk of a new
                # program key lowers (NO compile) for dryad_prog_* cost
                # series and notes the key on the tripwire; warm chunks
                # cost one memo lookup.  The key is the chunk jit's static
                # signature, so a changed program mid-run is caught here.
                introspect.capture(
                    "train.chunk",
                    ("chunk", p_key, B, has_cat, plat, N, K, pad,
                     metric_names, p.eval_period, total_iters, renew_a),
                    _chunk_jit, *_chunk_args, init_arr=init_dev,
                    renew_alpha=renew_a,
                    labels={"growth": p.growth, "shards": _shards_lbl})
            (out, score, vscores_t, eval_buf, eval_its,
             eval_cnt) = _chunk_jit(*_chunk_args, init_arr=init_dev,
                                    renew_alpha=renew_a)
            # expected-compile budget spent: arm every chunk (idempotent;
            # a key-less family stays inert, so a mid-run enable() arms
            # cleanly at the first ENABLED chunk instead of false-firing)
            _tw.arm("train.chunk")
            if _t_ch is not None:
                # async site: this is host dispatch wall (masks + enqueue),
                # not device execution — the fetch spans carry that
                record_span("train.chunk_dispatch",
                            _time.perf_counter() - _t_ch)
                if _obs_chunks is None:
                    _obs_chunks = _obs.counter(
                        "dryad_train_chunks_total",
                        "Chunk programs dispatched")
                    _obs_iter = _obs.gauge(
                        "dryad_train_iteration",
                        "Last host-side boosting iteration")
                _obs_chunks.inc()
                _obs_iter.set(it)

            if not calibrated:
                # drain the pipeline: chunk 0 absorbs compile, chunk 1 is
                # the measurement
                with watch_fetch("calibrate", it):
                    if chunk_hook is not None:
                        chunk_hook("fetch", it)
                    # deliberately NOT timed as a fetch span:
                    # block_until_ready returns instantly through the
                    # tunnel (CLAUDE.md), so a span here would advertise a
                    # ~0 fetch wall that never happened — the real-fetch
                    # sites below carry that series.  (The watchdog wrap
                    # is different: it times only the in-flight AGE, and
                    # an injected stall in the hook must be visible.)
                    jax.block_until_ready(out["max_depth"])
                now = _time.perf_counter()
                if chunk_idx == 1 and t_mark is not None:
                    per_iter = max((now - t_mark) / n, 1e-4)
                    cap = CH0 if bagging else 64
                    CH = max(1, min(cap, int(20.0 / per_iter)))
                    if _ch_max > 0:
                        CH = min(CH, _ch_max)
                    calibrated = True
                t_mark = now
            else:
                # Cap the async run-ahead to ~2 chunks.  Without this, a
                # deferred-eval 500-tree run enqueues its entire chunk
                # stream in seconds and the FIRST fetch (a checkpoint
                # flush or the end-of-run flush) then waits minutes behind
                # the queue — through the remote tunnel any request
                # pending much past ~60 s is killed and surfaces as a
                # device error (two headline runs died exactly this way,
                # 2026-07-31; sync-eval runs were immune because their
                # per-chunk fetch keeps the host in lockstep).  Blocking
                # on the chunk TWO dispatches back keeps one chunk of
                # pipeline overlap (chunks are calibrated to ~20 s, so any
                # later fetch waits <= ~2 chunks ~= 40 s).
                # a REAL one-element fetch, not block_until_ready — the
                # latter returned instantly on this tunnel for jit scalar
                # results (CLAUDE.md measuring notes) and would leave the
                # cap a no-op; the ~100 ms fetch RTT is <1% of a chunk
                inflight.append((it, out["max_depth"]))
                if len(inflight) > 2:
                    # the fetch blocks on the OLDEST inflight chunk — label
                    # the hook with ITS head iteration, not the current
                    # chunk's, so a tunnel kill here journals against the
                    # work that actually stalled
                    fetch_it, fetch_arr = inflight.pop(0)
                    with watch_fetch("runahead", fetch_it):
                        if chunk_hook is not None:
                            chunk_hook("fetch", fetch_it)
                        with span("train.fetch.runahead"):
                            jax.device_get(fetch_arr[:1])
            chunk_idx += 1

            evs = eval_iters_in(it, it + n)
            host_cnt += len(evs)
            stop = False
            if valids and sync_eval and evs:
                # one small fetch per chunk: the values feed early stopping
                # and live callbacks (the chunk ended ON the eval boundary,
                # so stopping here is iteration-exact)
                with watch_fetch("eval", it):
                    if chunk_hook is not None:
                        chunk_hook("fetch", it)
                    with span("train.fetch.eval"):
                        vals = np.asarray(jax.device_get(
                            eval_buf[host_cnt - len(evs):host_cnt]))
                _, higher0, _ = evaluators[0]
                val_rows = dict(zip(evs, vals))
                for j in range(it, it + n):
                    info = {"iteration": j, "ch_max_effective": ch_eff}
                    if comm is not None:
                        info.update(comm)
                    if j in val_rows:
                        for vi, ((vname, _), (mname, higher, _)) in enumerate(
                                zip(valids, evaluators)):
                            info[f"{vname}_{mname}"] = float(val_rows[j][vi])
                        best_iteration, best_value, stale = update_best(
                            p, best_iteration, best_value, stale, j,
                            float(val_rows[j][0]), higher0)
                        if (p.early_stopping_rounds
                                and stale >= p.early_stopping_rounds):
                            stop = True
                    if callback is not None:
                        callback(j, info)
                flushed_cnt = host_cnt  # consumed: keep deferred flush exact
            elif callback is not None:
                for j in range(it, it + n):
                    info = {"iteration": j, "ch_max_effective": ch_eff}
                    if comm is not None:
                        info.update(comm)
                    callback(j, info)
            it += n
            if checkpointer is not None and checkpointer.due(it):
                # _materialize is a real bulk fetch — the site the tunnel's
                # >1-min-pending kills surface at (STATUS r5)
                with watch_fetch("checkpoint", it):
                    if chunk_hook is not None:
                        chunk_hook("fetch", it)
                    with span("train.fetch.checkpoint"):
                        if valids and not sync_eval:
                            flush_chunk_evals(host_cnt)
                        ckpt = _materialize(p, data.mapper, out, it * K,
                                            init, max_depth_prev,
                                            best_iteration, best_value,
                                            stale)
                        if eval_history is not None:  # carried from resume
                            ckpt.train_state["eval_history"] = eval_history
                        checkpointer.save(ckpt, it)
            if chunk_policy is not None:
                # "clean" = dispatched + all due host work done; the async
                # run-ahead means device completion trails <= 2 chunks, so
                # a re-widen decision is at most two chunks optimistic
                # (documented in resilience/policy.py).  The length feeds
                # the policy's degrade target: the first step must actually
                # SHORTEN chunks relative to what has been running.
                chunk_policy.note_clean_chunk(n)
            if stop:
                total_iters = it
                break

        # hook BEFORE the deferred-eval flush: that flush is itself a bulk
        # fetch, and a tunnel kill inside it must attribute to a fetch site
        with watch_fetch("final", total_iters):
            if chunk_hook is not None:
                chunk_hook("fetch", total_iters)
            with span("train.fetch.final"):
                if valids and not sync_eval:
                    flush_chunk_evals(host_cnt)
                booster = _materialize(p, data.mapper, out, total_iters * K,
                                       init, max_depth_prev, best_iteration,
                                       best_value, stale)
        if eval_history is not None:
            booster.train_state["eval_history"] = eval_history
        if comm is not None:
            booster.train_state["comm_stats"] = comm
        # journals/benches read the cap that governed this run (0 = uncapped;
        # the supervisor's per-chunk cap additionally rides the info dicts)
        booster.train_state["ch_max_effective"] = _ch_max
        return booster

    # ---- boosting loop: async dispatch, zero per-iteration syncs -------------
    import time as _time

    _obs = default_registry()
    _obs_iter = None    # bound on first enabled use (see chunked path)
    # recompile tripwire, per-iteration arm: the step program is fixed
    # after the first iteration — except under DART, whose drop iterations
    # legitimately alternate the value_scale variant, so DART never arms
    _tw = default_tripwire()
    _tw.begin_program("train.step")
    _shards_lbl = mesh.devices.size if mesh is not None else 1
    for it in range(start_iter, T // K):
        # a checkpoint taken AT the early-stop boundary restores stale >=
        # rounds; growing anything past it would diverge from the stopped run
        if (valids and p.early_stopping_rounds
                and stale >= p.early_stopping_rounds):
            T = it * K
            break
        if chunk_hook is not None:
            chunk_hook("dispatch", it)
        _t_it = _time.perf_counter() if _obs.enabled else None
        row_mask_np, feat_mask_np = sample_masks(p, it, N, F)
        if row_mask_np is None:
            bag = ones_rows
        else:
            bag_np = np.pad(row_mask_np, (0, pad))
            bag = jnp.asarray(bag_np)
            if mesh is not None:
                bag = shard_rows(mesh, bag)[0]
        fmask = ones_feat if feat_mask_np is None else jnp.asarray(feat_mask_np)

        # ---- DART drop (mirrors cpu/trainer.py arithmetic exactly) --------
        value_scale = None
        if p.boosting == "dart":
            drop_np = dart_drop_set(p, it, it)
            if drop_np.size:
                kd = int(drop_np.size)
                inv = jnp.float32(1.0 / (kd + 1))
                fdrop = jnp.float32(np.float32(kd / (kd + 1.0)))
                Dmax = p.max_drop * K
                tids_np = np.full((Dmax,), -1, np.int32)
                tcls_np = np.zeros((Dmax,), np.int32)
                flat = (drop_np[:, None] * K
                        + np.arange(K)[None, :]).reshape(-1)
                tids_np[: flat.size] = flat
                tcls_np[: flat.size] = np.tile(np.arange(K), kd)
                tids = jnp.asarray(tids_np)
                tcls = jnp.asarray(tcls_np)
                db = (p.max_depth if p.max_depth > 0
                      else max(p.effective_num_leaves - 1, 1))
                score_eff, newval = _dart_drop_jit(
                    out, score, tids, tcls, Xb, fdrop, db)
                out = dict(out)
                out["value"] = newval
                value_scale = inv
                g_all, h_all = grads(score_eff)
                # score/vscores are REBUILT after the grow below by the
                # exact replay-sum a resumed run computes (_accumulate) —
                # incremental drop deltas round differently and would
                # break the resume bit-identity invariant
            else:
                g_all, h_all = grads(score)
        else:
            g_all, h_all = rf_gh if rf_gh is not None else grads(score)
        if p.boosting == "goss":
            u_np = np.pad(goss_uniform(p, it, N), (0, pad), constant_values=2.0)
            u = jnp.asarray(u_np)
            if mesh is not None:
                u = shard_rows(mesh, u)[0]
            g_all, h_all, goss_mask = _goss_jit(p_key, N, g_all, h_all, u, bag)
            bag = goss_mask
        roots = None
        if K > 1 and _shared_roots_ok(p, plat):
            # shared-plan multiclass roots (one pass for all K classes);
            # the histogram is feat_mask-independent — masked features'
            # columns simply never win the split scan
            roots = _roots_jit(B, p.rows_per_chunk, p.hist_precision, mesh,
                               Xb, g_all, h_all, bag)
        if _obs.enabled:
            # compile boundary of the per-iteration step program (one memo
            # lookup on warm iterations); the tripwire key carries the
            # value_scale variant so DART's two legitimate step programs
            # stay distinct keys instead of false-firing
            introspect.capture(
                "train.step",
                ("step", p_key, B, has_cat, plat, N, K, renew_a,
                 value_scale is not None),
                _step_jit, p_key, B, has_cat, mesh, plat, learn_missing,
                out, score, Xb, g_all, h_all, bag, fmask, is_cat_feat,
                it * K, 0, None if roots is None else roots[0], bmask,
                n_rows=N, value_scale=value_scale, y=y, renew_alpha=renew_a,
                labels={"growth": p.growth, "shards": _shards_lbl,
                        "arm": "per_iteration"})
        for k in range(K):
            t = it * K + k
            out, score = step(out, score, g_all, h_all, bag, fmask, t, k,
                              None if roots is None else roots[k],
                              value_scale=value_scale)
            if value_scale is None:
                for vi, vXb in enumerate(vXbs):
                    vscores[vi] = vscores[vi].at[:, k].set(
                        _apply_valid_jit(out, t, vXb, vscores[vi][:, k],
                                         out["max_depth"][t])
                    )
        if p.boosting != "dart":
            # idempotent per-iteration arm (key-less families stay inert —
            # see the chunked path); DART never arms: drop iterations
            # legitimately alternate the value_scale program variant
            _tw.arm("train.step")
        if value_scale is not None:
            # DART drop iteration: rebuild carried scores as the replay-sum
            # over the CURRENT (rescaled) value table — the construction a
            # resumed run performs, so checkpoint boundaries are bitwise
            trees_live = {key: out[key].reshape((T // K, K)
                                                + out[key].shape[1:])
                          for key in _TREE_KEYS}
            db = (p.max_depth if p.max_depth > 0
                  else max(p.effective_num_leaves - 1, 1))
            score = _dart_replay_jit(trees_live, Xb, jnp.asarray(init), db)
            vscores = [_dart_replay_jit(trees_live, vXb, jnp.asarray(init),
                                        db)
                       for vXb in vXbs]

        # ch_max_effective = 0 here: per-iteration dispatch has no chunking,
        # so no cap is in force — but the key is the documented contract
        # journals/benches read on every path
        info: dict = {"iteration": it, "ch_max_effective": 0}
        if comm is not None:
            info.update(comm)
        stop = False
        # eval every eval_period-th iteration, always including the last so
        # the training tail is never silently unscored
        eval_now = (it + 1) % p.eval_period == 0 or it + 1 == T // K
        if valids and eval_now:
            if p.boosting == "rf":
                # rf scores the AVERAGED model — same transform as predict
                inv_it = jnp.float32(np.float32(1.0) / np.float32(it + 1))
                vs_eval = [_rf_avg_jit(vs, init_dev, inv_it)
                           for vs in vscores]
            else:
                vs_eval = vscores
            vals_dev = [fn(vs_eval[vi])
                        for vi, (_, _, fn) in enumerate(evaluators)]
            if not sync_eval:
                deferred.append((it, vals_dev))
            else:
                with watch_fetch("eval", it):
                    if chunk_hook is not None:
                        chunk_hook("fetch", it)
                    with span("train.fetch.eval"):
                        vals = jax.device_get(vals_dev)  # ONE fetch, all sets
                for vi, ((vname, _), (mname, higher, _)) in enumerate(
                        zip(valids, evaluators)):
                    value = float(vals[vi])
                    info[f"{vname}_{mname}"] = value
                    if vi > 0:
                        continue  # early stopping watches the first set only
                    best_iteration, best_value, stale = update_best(
                        p, best_iteration, best_value, stale, it, value,
                        higher)
                    if (p.early_stopping_rounds
                            and stale >= p.early_stopping_rounds):
                        stop = True
        if callback is not None:
            callback(it, info)
        if checkpointer is not None and checkpointer.due(it + 1):
            with watch_fetch("checkpoint", it + 1):
                if chunk_hook is not None:
                    chunk_hook("fetch", it + 1)
                with span("train.fetch.checkpoint"):
                    flush_deferred()
                    ckpt = _materialize(p, data.mapper, out, (it + 1) * K,
                                        init, max_depth_prev,
                                        best_iteration, best_value, stale)
                    if eval_history is not None:
                        ckpt.train_state["eval_history"] = eval_history
                    checkpointer.save(ckpt, it + 1)
        if _t_it is not None:
            # async dispatch: this is the iteration's HOST dispatch wall
            record_span("train.iteration", _time.perf_counter() - _t_it)
            if _obs_iter is None:
                _obs_iter = _obs.gauge(
                    "dryad_train_iteration",
                    "Last host-side boosting iteration")
            _obs_iter.set(it)
        if stop:
            T = (it + 1) * K
            break

    # deferred evals: one final bulk fetch + replay; the full per-set
    # history lands on the booster (train_state["eval_history"]) since no
    # callback saw the values live
    with watch_fetch("final", T // K):
        if chunk_hook is not None:
            chunk_hook("fetch", T // K)
        with span("train.fetch.final"):
            flush_deferred()

            # ---- the single end-of-training fetch ----------------------------
            booster = _materialize(p, data.mapper, out, T, init,
                                   max_depth_prev, best_iteration,
                                   best_value, stale)
    if eval_history is not None:
        booster.train_state["eval_history"] = eval_history
    if comm is not None:
        booster.train_state["comm_stats"] = comm
    booster.train_state["ch_max_effective"] = 0   # per-iteration: no chunks
    return booster
