"""Version shims for the narrow set of JAX APIs that moved between the
releases this repo runs on (the TPU-attached environment's newer jax vs
the 0.4.x CI containers).

Every shim resolves the NEW spelling first so behavior on the tunneled
TPU is unchanged; the fallbacks are semantically equivalent on the old
release:

* ``shard_map`` — ``jax.shard_map`` vs ``jax.experimental.shard_map``.
* ``pcast`` — varying-manual-axes marking.  Old releases have no vma
  tracking at all, so the identity is the correct degenerate form.
* ``shape_dtype_struct`` — the ``vma`` kwarg on ``ShapeDtypeStruct``
  (pallas_call under shard_map).  Without vma tracking the plain struct
  is what old pallas expects.
* ``tpu_any_space`` — ``pltpu.MemorySpace.ANY`` vs the old
  ``pltpu.TPUMemorySpace.ANY``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental.pallas import tpu as pltpu

if hasattr(jax, "shard_map"):
    shard_map = jax.shard_map
else:                                                 # pragma: no cover
    from jax.experimental.shard_map import shard_map as _shard_map_old

    def shard_map(f, **kw):
        # the old replication checker mis-types scan carries fed by psum
        # results (its own error message recommends exactly this flag);
        # semantics are unchanged — the repo's collectives are all
        # explicit psums
        return _shard_map_old(f, check_rep=False, **kw)

def shard_map_norep(f, **kw):
    """``shard_map`` with replication checking off — required by the
    feature-parallel histogram arm: its combine's ``all_gather`` yields
    device-identical values, but the 0.4.x replication checker has no rep
    rule for all_gather outputs and rejects the replicated out_specs the
    tree arrays need.  Correctness there is pinned by the N-shard ≡
    1-shard ≡ fused parity tests instead; the fused arm keeps the full
    checker.  Tries the kwarg spellings across the supported releases."""
    for kwarg in ("check_rep", "check_vma"):
        try:
            return shard_map(f, **{kwarg: False}, **kw)
        except TypeError:
            continue
    return shard_map(f, **kw)


_HAS_PCAST = hasattr(jax.lax, "pcast")


def pcast_varying(x, axis_name):
    """Mark ``x`` device-varying over ``axis_name`` where the release
    tracks varying manual axes; identity elsewhere."""
    if _HAS_PCAST:
        return jax.lax.pcast(x, axis_name, to="varying")
    return x


def _vma_supported() -> bool:
    try:
        jax.ShapeDtypeStruct((1,), "float32", vma=frozenset())
        return True
    except TypeError:
        return False


_HAS_VMA = _vma_supported()


def shape_dtype_struct(shape, dtype, axis_name=None):
    """ShapeDtypeStruct carrying vma over ``axis_name`` when both are
    available (pallas_call outputs under shard_map need it there)."""
    if axis_name is not None and _HAS_VMA:
        return jax.ShapeDtypeStruct(shape, dtype,
                                    vma=frozenset({axis_name}))
    return jax.ShapeDtypeStruct(shape, dtype)


def tpu_any_space():
    if hasattr(pltpu, "MemorySpace"):
        return pltpu.MemorySpace.ANY
    return pltpu.TPUMemorySpace.ANY                   # pragma: no cover


# Whether ``pltpu.repeat`` TILES (concatenates whole copies — the
# semantics the histogram kernels' one-hot layout is built on).  The old
# releases' interpret path elementwise-repeats instead, silently wrecking
# every one-hot built on it.  Keyed off the SAME API-generation signal as
# the other shims (``MemorySpace`` arrived with the tiling repeat): a
# runtime pallas probe was tried first, but a probe fired inside a jit or
# kernel trace silently takes its exception fallback and picks the wrong
# semantics, and an import-time probe taxes every ``import dryad_tpu``
# ~0.2 s — the API signal is free and its fallback below is semantically
# correct on ANY release (concatenate always tiles).
_REPEAT_TILES = hasattr(pltpu, "MemorySpace")


def tile_repeat(x, n: int, axis: int = 0):
    """``pltpu.repeat`` with guaranteed TILE semantics: the output is n
    whole copies of ``x`` concatenated along ``axis`` (row r of the
    result holds x[r mod x.shape[axis]]).  On the release generation the
    kernels were measured with this IS pltpu.repeat (the Mosaic-native
    lowering); on older releases an explicit concatenate — always
    correct, at worst slower inside a compiled kernel."""
    if _REPEAT_TILES:
        return pltpu.repeat(x, n, axis)
    return jnp.concatenate([x] * n, axis=axis)        # pragma: no cover
