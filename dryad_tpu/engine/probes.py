"""Canonical timed-fori measurement harness + the named stage-probe registry.

Every device measurement in this repo rides ONE harness (``timed_fori``)
that codifies the CLAUDE.md measuring rules as code instead of as five
hand copies of the discipline (bench.py's private ``_timed_fori`` and the
four ``scripts/profile_*.py`` loop_time clones, retired in r13):

* K dependent iterations inside ONE jit via ``lax.fori_loop`` — per-call
  host timing lies through the axon tunnel (async dispatch, parts
  measuring slower OR faster than their sum);
* a carried perturbation scalar ``s`` the probe advances by WHOLE units
  (fractional advances round away in integer consumers — the r5 failure);
* every timed program ends in a REAL host fetch (``float(...)``) —
  ``block_until_ready`` returns instantly through this tunnel;
* min-of-reps + spread capture: tunnel stalls only ever ADD time, so the
  per-arm MIN is the estimator and max/min - 1 > 5% flags the capture.

The harness adds what the AST lint (``dead-perturbation``) can only
approximate: a **runtime liveness proof**.  A probe's step returns
``(s_next, contrib)`` where ``contrib`` is a scalar derived from the
timed stage's OUTPUT; the harness carries ``(s, acc)`` with
``acc += contrib`` and, before timing, runs the program at two
perturbation seeds.  A stage whose perturbation is dead — rounded away
(r5) or reachable only through non-carried inputs that while-loop LICM
hoists out of the loop (r10, the 2x-too-fast lies) — produces the SAME
fetched accumulator at both seeds and is **rejected at runtime** with
``DeadProbeError``, not discovered in review.  Because ``contrib`` is
accumulated separately from ``s``, the old ``s + out * 1e-20`` idiom
(whose stage term vanished below fp32 resolution, making the fetch
differ only through the trivially-live counter) cannot mask a hoist.

Seed choice: the two liveness seeds differ by 7 — probes that perturb by
rotation must have a period that does not divide the gap (every modular
period in this file is a power of two).  And because the accumulator is
order-independent, a PERIODIC perturbation must not make the two seeds'
K-trip windows the same multiset (a period-2 alternation under K=2 does
exactly that — caught by this very proof while building it): the modular
walks here all use period 8; keep K below the walk period.

``PROBES`` names one probe per hot-path stage (masked + segmented Pallas
histogram, split scan, the leafperm move + layout histogram, the packed
route gather, predict traversal, the GOSS/renewal sort arms); run them
via ``run_probe`` / ``python -m dryad_tpu profile``.  ``run_selftest``
(ci.sh) proves the proof: a seeded dead probe MUST be caught, and every
shipped probe must pass liveness on the CPU backend in seconds.

This module touches jax, so it lives in the engine; the jax-free
aggregation layer (gauges, stamped PROFILE artifacts, trend ingestion)
is ``dryad_tpu/obs/profiler.py``.
"""

from __future__ import annotations

import math
import time
from dataclasses import dataclass
from typing import Callable, Optional

import numpy as np

#: default probe shape knobs (the Higgs bench shape, scaled per platform)
DEFAULT_K = 3
DEFAULT_REPS = 2
DEFAULT_ROWS_DEVICE = 1_000_000
DEFAULT_ROWS_CPU = 8_192
#: the two liveness seeds; gap 7 is coprime to every power-of-two period
LIVENESS_SEEDS = (0.0, 7.0)
#: every registry probe's modular perturbation walk uses this period; at
#: K >= period the two seeds' K-trip windows are the same multiset and
#: the proof would false-fire on a LIVE stage (run_probe rejects such K)
WALK_PERIOD = 8
#: per-arm spread above this flags the capture (CLAUDE.md)
SPREAD_SUSPECT = 0.05


class DeadProbeError(RuntimeError):
    """The probe's perturbation never reached the timed stage — the stage
    would be hoisted/folded by XLA and the wall would be a lie."""


def timed_fori(step, K: int, reps: int, *args,
               label: str = "probe",
               seeds: tuple = LIVENESS_SEEDS,
               check_live: bool = True) -> tuple:
    """Time ``step`` under the canonical discipline; return (min_ms, spread).

    ``step(s, *args) -> (s_next, contrib)``: advance the carried scalar by
    whole units and return a scalar derived from the stage's OUTPUT.  The
    harness folds ``contrib`` into a separate fp32 accumulator (so the
    liveness signal cannot vanish under the counter, unlike ``s + x*1e-20``)
    and rejects the probe with ``DeadProbeError`` when two different seeds
    fetch identical accumulators (dead perturbation / hoisted stage) or a
    non-finite one (the perturbation broke the stage's domain).
    """
    import jax
    import jax.numpy as jnp

    def prog(s0, *a):
        def body(i, carry):
            s, acc = carry
            s2, contrib = step(s, *a)
            return s2, acc + jnp.asarray(contrib).astype(jnp.float32)
        return jax.lax.fori_loop(0, K, body, (s0, jnp.float32(0.0)))

    f = jax.jit(prog)
    out = f(jnp.float32(seeds[0]), *args)
    acc_a = float(out[1])                  # compile + warm; REAL fetch
    if check_live:
        out = f(jnp.float32(seeds[1]), *args)
        acc_b = float(out[1])
        if not (math.isfinite(acc_a) and math.isfinite(acc_b)):
            raise DeadProbeError(
                f"{label}: non-finite liveness accumulator "
                f"({acc_a!r} / {acc_b!r}) — the perturbation left the "
                "stage's numeric domain; rescale it")
        if acc_a == acc_b:
            raise DeadProbeError(
                f"{label}: identical fetched results at seeds {seeds} — "
                "the perturbation is DEAD (rounded away or hoisted by "
                "while-loop LICM; CLAUDE.md r5/r10) and the wall would "
                "measure a lie.  Make the carried scalar reach the stage "
                "and the stage's output reach the contrib")
    walls = []
    for r in range(reps):
        t0 = time.perf_counter()
        out = f(jnp.float32(seeds[0] + 2.0 * (r + 1)), *args)
        float(out[1])                      # real fetch ends the timed region
        walls.append((time.perf_counter() - t0) / K * 1000.0)
    return min(walls), max(walls) / min(walls) - 1.0


# ---------------------------------------------------------------------------
# the stage-probe registry
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class StageProbe:
    """One named hot-path stage.  ``build(rows, num_features, total_bins,
    num_slots, seed)`` returns ``(step, args, meta)`` — arrays ride as jit
    ARGUMENTS (never closures: the HTTP-413 jit-constant rule)."""

    name: str
    doc: str
    build: Callable
    cheap: bool = True      # eligible for the smoke/selftest tier


def _synth(rows: int, F: int, B: int, seed: int):
    rng = np.random.default_rng(seed)
    Xb = rng.integers(0, B, size=(rows, F),
                      dtype=np.uint8 if B <= 256 else np.uint16)
    g = rng.normal(size=rows).astype(np.float32)
    h = rng.uniform(0.1, 1.0, size=rows).astype(np.float32)
    return rng, Xb, g, h


def _build_hist_masked(rows, F, B, P, seed):
    """Masked histogram (build_hist): the root/shallow-level pass.  The
    perturbation rolls the MASK by the carried scalar — it must reach the
    kernel, not the weights (records carry g/h on the wired paths)."""
    import jax.numpy as jnp

    from dryad_tpu.engine.histogram import build_hist

    rng, Xb, g, h = _synth(rows, F, B, seed)
    mask = jnp.asarray(rng.random(rows) < 0.8)
    Xb, g, h = jnp.asarray(Xb), jnp.asarray(g), jnp.asarray(h)

    def step(s, Xb, g, h, mask):
        si = s.astype(jnp.int32)
        hist = build_hist(Xb, g, h, jnp.roll(mask, si), B, backend="auto")
        # slice-plane SUM, not a single bin: bin 0 can be empty in real
        # binned data and a constant-zero contrib reads as dead
        return s + 1.0, hist[0].sum()

    return step, (Xb, g, h, mask), {"rows": rows}


def _build_hist_segmented(rows, F, B, P, seed):
    """Segmented histogram (the per-level kernel call incl. its plan):
    perturb the SORT KEY — slot ids rotate mod P, the selected SET stays
    fixed so the exact draw count is the rows_bound (tile_plan contract)."""
    import jax.numpy as jnp

    from dryad_tpu.engine.histogram import build_hist_segmented

    rng, Xb, g, h = _synth(rows, F, B, seed)
    sel_np = rng.integers(0, 2 * P, size=rows).astype(np.int32)
    sel_np = np.where(sel_np < P, sel_np, P)
    bound = int((sel_np < P).sum())
    Xb, g, h = jnp.asarray(Xb), jnp.asarray(g), jnp.asarray(h)
    sel = jnp.asarray(sel_np)

    def step(s, Xb, g, h, sel):
        si = s.astype(jnp.int32)
        sel2 = jnp.where(sel < P, (sel + si) % P, P)
        hist = build_hist_segmented(Xb, g, h, sel2, P, B, backend="auto",
                                    rows_bound=bound)
        # slot-0 plane sum: the ALL-slot total is rotation-invariant and
        # a single bin can be empty — both would read as dead
        return s + 1.0, hist[0, 0].sum()

    return step, (Xb, g, h, sel), {"rows": rows, "num_slots": P}


def _build_split_scan(rows, F, B, P, seed):
    """vmapped best-split scan over 2P children.  ``rows`` only scales the
    synthetic histogram magnitudes — the scan is row-count independent."""
    import jax
    import jax.numpy as jnp

    from dryad_tpu.engine.split import find_best_split

    rng = np.random.default_rng(seed)
    hists = np.stack([
        rng.normal(size=(2 * P, F, B)),
        rng.uniform(0.1, 1.0, size=(2 * P, F, B)),
        rng.uniform(0.5, 2.0, size=(2 * P, F, B)),
    ], axis=1).astype(np.float32) * (rows / max(B, 1))
    hh0 = jnp.asarray(hists)
    fmask = jnp.ones((F,), bool)
    iscat = jnp.zeros((F,), bool)
    allow = jnp.ones((2 * P,), bool)

    def step(s, hh, fmask, iscat, allow):
        # period-8 walk (the module rule: a period inside K would repeat
        # the contrib multiset across the liveness seeds — gap 7 mod 4
        # collides at K=4, which the K < WALK_PERIOD guard admits)
        smod = s - jnp.floor(s / 8.0) * 8.0
        hh2 = hh * (1.0 + 0.01 * smod)       # gains are scale-sensitive
        G = hh2[:, 0].sum(axis=(1, 2))       # (lambda_l2 breaks homogeneity)
        H = hh2[:, 1].sum(axis=(1, 2))
        C = hh2[:, 2].sum(axis=(1, 2))

        def best(hh_, G_, H_, C_, a_):
            return find_best_split(
                hh_, G_, H_, C_, lambda_l2=1.0, min_child_weight=1e-3,
                min_data_in_leaf=20, min_split_gain=0.0, feat_mask=fmask,
                is_cat_feat=iscat, allow=a_, has_cat=False)

        res = jax.vmap(best)(hh2, G, H, C, allow)
        return s + 1.0, res.gain[0] + res.gain[-1]

    return step, (hh0, fmask, iscat, allow), {"rows": rows, "num_slots": P}


def _layout_fixture(rows, F, B, P, seed):
    """Shared wired-path setup: a P-slot leaf-ordered layout (the bench
    probes' initial_layout construction — the growers are root-anchored,
    the probes build mid-tree states directly)."""
    import jax.numpy as jnp

    from dryad_tpu.engine import leafperm

    T = leafperm._TILE_ROWS
    rng, Xb, g, h = _synth(rows, F, B, seed)
    rec_nat = leafperm.make_layout_records(
        jnp.asarray(Xb), jnp.asarray(g), jnp.asarray(h))
    slot = jnp.asarray(rng.integers(0, P, rows).astype(np.int32))
    n_buf = leafperm.wired_tiles_bound(-(-rows // T), P)
    rec_lay, tile_run, run_slot = leafperm.initial_layout(
        rec_nat, slot, jnp.ones((P,), bool), P, n_buf)
    return leafperm, T, n_buf, rec_lay, tile_run, run_slot


def _build_permute_records(rows, F, B, P, seed):
    """The leafperm movement kernel: one level's sides + level_moves +
    permute_records.  The side threshold alternates with the carried
    scalar, so the whole move chain stays in the loop."""
    import jax.numpy as jnp

    leafperm, T, n_buf, rec_lay, tile_run, _ = _layout_fixture(
        rows, F, B, P, seed)
    bin_dtype = jnp.uint8 if B <= 256 else jnp.uint16
    # the contrib must be PERMUTATION-sensitive: a plain sum over records
    # is invariant under the move, and a single byte + the (tile-granular)
    # segment bases can coincide across nearby thresholds — so sample
    # ~256 records and weight them by position (a <=257-element gather,
    # noise next to the full-buffer move being timed)
    stride = max(1, (n_buf * T) // 256)

    def step(s, rec_lay, tile_run):
        g_l, _, valid, _ = leafperm.unpack_layout_records(
            rec_lay, F, bin_dtype)
        # period-8 threshold walk: a period-2 alternation summed over K
        # trips gives the SAME contrib multiset at both liveness seeds
        # (the accumulator is order-independent) and reads as dead
        smod = s - jnp.floor(s / 8.0) * 8.0
        thr = -0.45 + 0.05 * smod            # strictly negative: < half go left
        side = jnp.where(valid, (g_l > thr).astype(jnp.int32), 2)
        pos, dstl, dstr, base_l, base_r, _ = leafperm.level_moves(
            tile_run, side, P)
        out = leafperm.permute_records(rec_lay, pos, dstl, dstr, n_buf)
        samp = out[::stride, 0].astype(jnp.float32)
        pos_w = jnp.arange(samp.shape[0], dtype=jnp.float32) + 1.0
        return (s + 1.0,
                jnp.dot(samp, pos_w) + base_l[P].astype(jnp.float32))

    return step, (rec_lay, tile_run), {"rows": rows, "num_slots": P}


def _build_hist_from_layout(rows, F, B, P, seed):
    """The layout histogram read (tile-run gather + kernel): the selection
    rotates over the P runs, so a different run is segment 0 every trip."""
    import jax.numpy as jnp

    leafperm, T, n_buf, rec_lay, tile_run, _ = _layout_fixture(
        rows, F, B, P, seed)
    bin_dtype = jnp.uint8 if B <= 256 else jnp.uint16
    tr = np.asarray(tile_run)
    first = np.zeros(P, np.int32)
    ntiles = np.zeros(P, np.int32)
    for r_ in range(P):
        w = np.nonzero(tr == r_)[0]
        if w.size:
            first[r_], ntiles[r_] = w[0], w.size
    n_sel = int(np.maximum(ntiles, 1).sum())   # rotation-invariant bound
    sf0, sn0 = jnp.asarray(first), jnp.asarray(ntiles)

    def step(s, rec_lay, sf, sn):
        si = s.astype(jnp.int32)
        hist = leafperm.hist_from_layout(
            rec_lay, jnp.roll(sf, si), jnp.roll(sn, si), P, B, F,
            bin_dtype, n_sel)
        return s + 1.0, hist[0, 0].sum()

    return step, (rec_lay, sf0, sn0), {"rows": rows, "num_slots": P}


def _build_route_gather(rows, F, B, P, seed):
    """The wired growers' per-level route: run->packed-word compose + ONE
    per-row small-table gather (the dominant wired-only bookkeeping cost).
    The run table is ROLLED by the carried scalar — a non-carried table is
    exactly the r10 LICM hoist this harness exists to reject."""
    import jax.numpy as jnp

    leafperm, T, n_buf, _, tile_run, run_slot = _layout_fixture(
        rows, F, B, P, seed)

    def step(s, tile_run, run_slot):
        si = s.astype(jnp.int32)
        rs_i = jnp.roll(run_slot, si)
        w0 = (jnp.uint32(1) << 31) | jnp.arange(P, dtype=jnp.uint32)
        tab = jnp.concatenate([w0, jnp.zeros((1,), jnp.uint32)])
        rr = tab[jnp.minimum(rs_i, P)][jnp.repeat(tile_run, T)]
        lo = (rr & jnp.uint32(0xFFFF)).astype(jnp.float32)
        return s + 1.0, lo[0] + lo[lo.shape[0] // 2] + lo[-1]

    return step, (tile_run, run_slot), {"rows": rows, "num_slots": P}


def _build_partition_reduce(rows, F, B, P, seed):
    """The partition column-select's masked-reduce arm (levelwise
    ``select_bins`` when ``partition_prefers_reduce`` admits): max over
    the CONTIGUOUS (N, F) matrix where the per-row feature id matches.
    The rf vector is ROLLED by the carried scalar so the selected column
    set changes every iteration — a whole-unit advance into integer
    indices, the r5 dead-input class this harness rejects.  Comparison
    arm for the r23 ``partition`` calibration gate (vs the gather probe
    below at the same shape)."""
    import jax.numpy as jnp

    rng, Xb, _, _ = _synth(rows, F, B, seed)
    rf = jnp.asarray(rng.integers(0, F, size=rows).astype(np.int32))
    Xb = jnp.asarray(Xb)

    def step(s, Xb, rf):
        si = s.astype(jnp.int32)
        rfi = jnp.roll(rf, si)
        iota_f = jnp.arange(Xb.shape[1], dtype=jnp.int32)
        sel = jnp.max(
            jnp.where(rfi[:, None] == iota_f[None, :], Xb,
                      jnp.zeros((), Xb.dtype)),
            axis=1).astype(jnp.float32)
        # whole-column SUM: the rolled rf re-selects random bins, so the
        # contrib moves by far more than its fp32 ulp
        return s + 1.0, sel[0] + jnp.sum(sel) / rows

    return step, (Xb, rf), {"rows": rows}


def _build_partition_gather(rows, F, B, P, seed):
    """The partition column-select's per-row gather arm
    (``take_along_axis`` into (N, F) — the ~per-ACCESS-cost formulation;
    CLAUDE.md gather facts).  Same fixture, perturbation, and contrib as
    the reduce probe so the pair is a clean A/B at any width."""
    import jax.numpy as jnp

    rng, Xb, _, _ = _synth(rows, F, B, seed)
    rf = jnp.asarray(rng.integers(0, F, size=rows).astype(np.int32))
    Xb = jnp.asarray(Xb)

    def step(s, Xb, rf):
        si = s.astype(jnp.int32)
        rfi = jnp.roll(rf, si)
        sel = jnp.take_along_axis(
            Xb, rfi[:, None], axis=1)[:, 0].astype(jnp.float32)
        return s + 1.0, sel[0] + jnp.sum(sel) / rows

    return step, (Xb, rf), {"rows": rows}


def _build_hist_reduce_scan(rows, F, B, P, seed, n_shards: int = 8):
    """The feature-parallel reduction's per-device scan stage (r16): the
    sliced best-split scan over ONE owned F/n feature slice + the packed
    record combine over all n shards' records — exactly what each shard
    computes per level under hist_reduce="feature" (the n-fold wire-
    payload cut itself is static accounting, _comm_stats / jaxpr census,
    not a single-device wall).  The other shards' records ride as fixed
    all-masked (-inf) args, so the perturbed owned slice always wins and
    the liveness signal flows scan -> combine -> contrib; the
    perturbation scales the histogram (gains are lambda_l2-inhomogeneous,
    same class as the split_scan probe — its fused scan is this probe's
    comparison arm at the same shape, bench.py hist_reduce_probe)."""
    import jax
    import jax.numpy as jnp

    from dryad_tpu.engine.split import (
        combine_local_splits,
        find_best_split_sliced,
        pack_local_split,
    )

    rng = np.random.default_rng(seed)
    Fs = -(-F // n_shards)
    # the jit ARGUMENT is the per-device operand — the OWNED (2P, 3, Fs,
    # B) slice, exactly what each shard scans under the feature arm (the
    # full-width stack would make the perturbation multiply ~n_shards
    # times wider than the measured stage and bias the fused-vs-feature
    # bench comparison toward parity, besides shipping n times the bytes
    # through the tunnel)
    hists = np.stack([
        rng.normal(size=(2 * P, Fs, B)),
        rng.uniform(0.1, 1.0, size=(2 * P, Fs, B)),
        rng.uniform(0.5, 2.0, size=(2 * P, Fs, B)),
    ], axis=1).astype(np.float32) * (rows / max(B, 1))
    hh0 = jnp.asarray(hists)
    fmask0 = jnp.ones((Fs,), bool)
    iscat0 = jnp.zeros((Fs,), bool)
    allow = jnp.ones((2 * P,), bool)
    # global node stats: scalars in the real arm (root/prefix records,
    # never histogram re-sums) — scaled with the perturbation below so
    # the gain grids stay consistent with the perturbed slice
    G0 = jnp.asarray(hists[:, 0].sum(axis=(1, 2)) * n_shards)
    H0 = jnp.asarray(hists[:, 1].sum(axis=(1, 2)) * n_shards)
    C0 = jnp.asarray(hists[:, 2].sum(axis=(1, 2)) * n_shards)

    def sliced(hh_slice, G_, H_, C_, fmask):
        def one(hh_, g_, h_, c_):
            return find_best_split_sliced(
                hh_, g_, h_, c_, feat_offset=jnp.int32(0),
                num_features_total=F, lambda_l2=1.0, min_child_weight=1e-3,
                min_data_in_leaf=20, feat_mask=fmask, is_cat_feat=iscat0,
                has_cat=False)
        return jax.vmap(one)(hh_slice, G_, H_, C_)

    # the non-owned shards' records: the SAME sliced scan, fully masked
    # (-inf gains) — realistic combine width, deterministic loser rows
    dead = pack_local_split(sliced(hh0, G0, H0, C0,
                                   jnp.zeros((Fs,), bool)))
    other_words = jnp.broadcast_to(dead[None],
                                   (n_shards - 1,) + dead.shape)

    def step(s, hh, G_, H_, C_, other):
        smod = s - jnp.floor(s / 8.0) * 8.0  # period-8 walk (module rule)
        scale = 1.0 + 0.01 * smod
        hh2 = hh * scale                     # gains are scale-sensitive
        words0 = pack_local_split(sliced(hh2, G_ * scale, H_ * scale,
                                         C_ * scale, fmask0))
        words = jnp.concatenate([words0[None], other], axis=0)
        res = combine_local_splits(words, None, allow=allow,
                                   min_split_gain=0.0, has_cat=False)
        return s + 1.0, res.gain[0] + res.gain[-1]

    return step, (hh0, G0, H0, C0, other_words), {"rows": rows,
                                                  "num_slots": P,
                                                  "n_shards": n_shards}


def _build_predict_traversal(rows, F, B, P, seed, depth: int = 6):
    """Per-tree traversal (tree_leaves) on a synthetic complete tree.  The
    thresholds shift by the carried parity — ~N/B rows per node change
    sides, so the leaf-id SUM moves by far more than its fp32 ulp (the
    contrib must not round the liveness signal away)."""
    import jax.numpy as jnp

    from dryad_tpu.engine.predict import tree_leaves

    rng, Xb, _, _ = _synth(rows, F, B, seed)
    n_internal = (1 << depth) - 1
    M = (1 << (depth + 1)) - 1
    feature = np.full(M, -1, np.int32)
    feature[:n_internal] = rng.integers(0, F, n_internal)
    threshold = np.zeros(M, np.int32)
    threshold[:n_internal] = rng.integers(B // 4, (3 * B) // 4, n_internal)
    nodes = np.arange(M, dtype=np.int32)
    tree = {
        "feature": jnp.asarray(feature),
        "threshold": jnp.asarray(threshold),
        "left": jnp.asarray(np.minimum(2 * nodes + 1, M - 1)),
        "right": jnp.asarray(np.minimum(2 * nodes + 2, M - 1)),
        "default_left": jnp.ones((M,), bool),
        "is_cat": jnp.zeros((M,), bool),
        "cat_bitset": jnp.zeros((M, max(1, -(-B // 32))), jnp.uint32),
    }
    Xb = jnp.asarray(Xb)

    def step(s, Xb, tr):
        si = s.astype(jnp.int32)
        # period-8 shift (not parity): seed windows must differ as
        # multisets, not just in order — see the permute probe's note
        lv = tree_leaves({**tr, "threshold": tr["threshold"] + si % 8},
                         Xb, depth)
        return s + 1.0, jnp.sum(lv.astype(jnp.float32))

    return step, (Xb, tree), {"rows": rows, "depth": depth}


def _build_predict_traversal_packed(rows, F, B, P, seed, depth: int = 6):
    """The r21 packed node-word twin of ``predict_traversal``: the SAME
    synthetic tree packed into the (M, 2)-uint32 limb table, numeric
    program (no cat_bitset key), so the per-level body is one node-word
    gather + the Xb column read.  The perturbation bumps limb1's
    threshold field (low 16 bits) by the carried period-8 parity — the
    synthetic thresholds top out at 3B/4, so +7 can never carry into the
    feature bits, and the liveness signal is the legacy probe's exactly."""
    import jax.numpy as jnp

    from dryad_tpu.engine.predict import pack_node_words, tree_leaves

    rng, Xb, _, _ = _synth(rows, F, B, seed)
    n_internal = (1 << depth) - 1
    M = (1 << (depth + 1)) - 1
    feature = np.full(M, -1, np.int32)
    feature[:n_internal] = rng.integers(0, F, n_internal)
    threshold = np.zeros(M, np.int32)
    threshold[:n_internal] = rng.integers(B // 4, (3 * B) // 4, n_internal)
    nodes = np.arange(M, dtype=np.int32)
    words = pack_node_words(
        feature, threshold,
        np.minimum(2 * nodes + 1, M - 1), np.minimum(2 * nodes + 2, M - 1),
        np.ones(M, bool), np.zeros(M, bool))
    Xb = jnp.asarray(Xb)
    nw = jnp.asarray(words)

    def step(s, Xb, nw):
        si = s.astype(jnp.int32)
        bump = jnp.array([0, 1], jnp.uint32) * (si % 8).astype(jnp.uint32)
        lv = tree_leaves({"node_word": nw + bump}, Xb, depth)
        return s + 1.0, jnp.sum(lv.astype(jnp.float32))

    return step, (Xb, nw), {"rows": rows, "depth": depth}


def _build_goss_sort(rows, F, B, P, seed):
    """The GOSS arm's +1 global sort per iteration (threshold quantile).
    Perturb the SORT KEY itself — a rolled key would sort to the same
    output and read as dead (sort(roll(x)) == sort(x))."""
    import jax.numpy as jnp

    rng = np.random.default_rng(seed)
    absg = jnp.asarray(np.abs(rng.normal(size=rows)).astype(np.float32))
    u = jnp.asarray(rng.uniform(0.0, 1.0, rows).astype(np.float32))
    top_n = max(1, int(round(0.2 * rows)))

    def step(s, absg, u):
        smod = s - jnp.floor(s / 8.0) * 8.0
        key = absg + 0.125 * smod * u        # perturb the SORT KEY
        thr = jnp.sort(key)[key.shape[0] - top_n]
        return s + 1.0, thr

    return step, (absg, u), {"rows": rows}


def _build_renewal_sort(rows, F, B, P, seed, M: int = 256):
    """The L1-family renewal's +1 global (leaf, residual) two-key sort per
    tree + the segment searchsorted.  Leaf ids rotate mod M, so a
    different leaf's residuals sort first every trip."""
    import jax
    import jax.numpy as jnp

    rng = np.random.default_rng(seed)
    lv = jnp.asarray(rng.integers(0, M, rows).astype(np.int32))
    r = jnp.asarray(rng.normal(size=rows).astype(np.float32))

    def step(s, lv, r):
        si = s.astype(jnp.int32)
        lv2 = (lv + si) % M
        lv_s, r_s = jax.lax.sort((lv2, r), num_keys=2)
        bounds = jnp.searchsorted(lv_s, jnp.arange(M + 1, dtype=jnp.int32))
        return s + 1.0, r_s[0] + bounds[1].astype(jnp.float32)

    return step, (lv, r), {"rows": rows}


PROBES: dict[str, StageProbe] = {p.name: p for p in (
    StageProbe("hist_masked",
               "masked Pallas/XLA histogram (root & shallow levels)",
               _build_hist_masked),
    StageProbe("hist_segmented",
               "segmented Pallas/XLA histogram incl. its tile plan",
               _build_hist_segmented),
    StageProbe("split_scan",
               "vmapped best-split scan over 2P children",
               _build_split_scan),
    StageProbe("hist_reduce",
               "feature-parallel per-device stage: sliced F/8 split scan "
               "+ packed record combine (hist_reduce='feature')",
               _build_hist_reduce_scan),
    StageProbe("permute_records",
               "leafperm movement kernel (sides + level_moves + permute)",
               _build_permute_records),
    StageProbe("hist_from_layout",
               "layout histogram read (tile-run gather + kernel)",
               _build_hist_from_layout),
    StageProbe("route_gather",
               "wired per-level packed route small-table gather",
               _build_route_gather),
    StageProbe("partition_reduce",
               "partition column-select, masked-reduce arm (select_bins)",
               _build_partition_reduce),
    StageProbe("partition_gather",
               "partition column-select, per-row gather arm",
               _build_partition_gather),
    StageProbe("predict_traversal",
               "per-tree traversal (tree_leaves) on a depth-6 tree",
               _build_predict_traversal),
    StageProbe("predict_traversal_packed",
               "packed node-word traversal (one table gather/level, r21)",
               _build_predict_traversal_packed),
    StageProbe("goss_sort",
               "GOSS global quantile sort (+1 sort/iteration arm)",
               _build_goss_sort),
    StageProbe("renewal_sort",
               "L1-renewal global (leaf, residual) two-key sort (+1/tree)",
               _build_renewal_sort),
)}

#: the cheap on-device smoke tier (scripts/smoke_tpu.py --gate)
SMOKE_PROBES = ("hist_segmented", "split_scan", "route_gather")


def run_probe(name: str, rows: Optional[int] = None, K: int = DEFAULT_K,
              reps: int = DEFAULT_REPS, *, num_features: int = 28,
              total_bins: int = 256, num_slots: int = 64, seed: int = 5,
              check_live: bool = True) -> dict:
    """Build + liveness-prove + time one named stage probe."""
    import jax

    probe = PROBES[name]
    if check_live and K >= WALK_PERIOD:
        # a full walk cycle per window makes the two liveness windows the
        # same multiset — the proof would reject a LIVE stage; fail the
        # configuration loudly instead of reporting a misleading "dead"
        raise ValueError(
            f"K={K} >= the probes' perturbation walk period "
            f"({WALK_PERIOD}): the liveness proof cannot distinguish "
            "seeds over whole cycles; use K < "
            f"{WALK_PERIOD} (or check_live=False)")
    platform = jax.devices()[0].platform
    if rows is None:
        rows = DEFAULT_ROWS_CPU if platform == "cpu" else DEFAULT_ROWS_DEVICE
    step, args, meta = probe.build(rows, num_features, total_bins,
                                   num_slots, seed)
    ms, spread = timed_fori(step, K, reps, *args, label=name,
                            check_live=check_live)
    out = {"stage": name, "ms": round(ms, 3), "spread": round(spread, 4),
           "K": K, "reps": reps, "platform": platform}
    out.update(meta)
    return out


def dead_probe_step():
    """The selftest fixture: the r5/r10 failure class reproduced on
    purpose.  The perturbation is consumed only through a rounded-away
    integer cast (``* 1e-30`` rather than ``+ tiny`` so the AST
    ``dead-perturbation`` rule stays silent — the RUNTIME proof must
    catch what the lint cannot), so the sort is loop-invariant and the
    fetched accumulator is seed-independent."""
    import jax.numpy as jnp

    def step(s, x):
        si = (s * 1e-30).astype(jnp.int32)       # always 0 — a dead input
        y = jnp.sort(x + si.astype(jnp.float32))  # hoistable stage
        return s + 1.0, y[0]

    return step


def run_selftest(rows: int = 4096, num_slots: int = 8,
                 quiet: bool = False) -> int:
    """The ci.sh gate: the liveness proof must FIRE on the seeded dead
    probe and PASS on every shipped probe (CPU, seconds).  Returns a
    process exit code."""
    import jax.numpy as jnp

    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.normal(size=rows).astype(np.float32))
    try:
        timed_fori(dead_probe_step(), 2, 1, x, label="seeded-dead")
    except DeadProbeError as e:
        if not quiet:
            print(f"selftest: seeded dead probe rejected ({e})")
    else:
        print("PROFILE SELFTEST FAIL: the seeded dead-perturbation probe "
              "was NOT caught — the liveness proof is broken")
        return 1
    failed = 0
    for name in PROBES:
        try:
            r = run_probe(name, rows=rows, K=2, reps=1,
                          num_slots=num_slots)
        except Exception as e:  # noqa: BLE001 — aggregate, report, exit 1
            failed += 1
            print(f"PROFILE SELFTEST FAIL: {name}: {e}")
            continue
        if not quiet:
            print(f"selftest: {name} live "
                  f"({r['ms']:.2f} ms on {r['platform']})")
    if failed:
        return 1
    print(f"PROFILE SELFTEST OK: dead probe caught, "
          f"{len(PROBES)} probes liveness-proven")
    return 0
