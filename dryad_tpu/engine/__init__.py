"""dryad_tpu.engine — the TPU-native training/predict engine.

The reference's three CUDA kernels (per-feature histogram builder, split-gain
scan, row-partition/apply — BASELINE.json:5) map here to XLA/Pallas programs
designed for the MXU + VMEM memory hierarchy rather than for CUDA's
atomic-scatter model:

* histogram.py — scatter-add has no TPU atomics, so the histogram is a
  masked one-hot matmul (MXU) or a Pallas row-tiled VMEM accumulation.
* split.py — split-gain scan as a vectorized cumsum + masked argmax.
* grower.py — the leaf-wise grower as a fixed-trip-count ``lax.fori_loop``
  with slot masking (XLA needs static shapes; the reference's dynamic
  host-side loop becomes compiled control flow).
* train.py / predict.py — the ``dryad.train`` / ``dryad.predict`` device
  backends; the histogram allreduce rides ``jax.lax.psum`` over ICI/DCN in
  place of the reference's NCCL (SURVEY.md §2 #13-14).
"""
