"""Batched leaf-wise growth: depth-capped full expansion + exact best-first
selection (SURVEY.md §2 #8 at scale).

The sequential leaf-wise grower (grower.py::grow_tree — the reference's
one-split-at-a-time control flow) pays one full-N masked histogram pass per
split: O(N·L) work per tree, ~L/depth times the depthwise cost at 255
leaves (VERDICT r2 missing #2).  This module removes that asymptotic
penalty using an exact equivalence:

    Split gains are ORDER-INDEPENDENT.  Splitting leaf A never changes
    leaf B's rows, histogram, or gain — so the sequential best-first
    procedure is a deterministic selection over a gain tree whose values
    do not depend on the order in which it is explored.

Therefore leaf-wise growth with a depth cap D factorizes into:

1. **Expansion** — grow ALL valid splits level-synchronously to depth D
   (the depthwise machinery: one segmented smaller-children histogram
   pass per level, subtraction for the larger sibling), recording every
   node's best split, gain, stats and monotone bounds into a binary-heap
   table (node 1 = root, children 2n / 2n+1).  Cost: O(N·D) — the same
   per-level passes the depthwise grower pays.
2. **Selection** — replay the exact slot-machine sequence of
   grow_tree on the PRECOMPUTED gains: L-1 trips of argmax over slot
   gains (first-max tie-break, left child keeps the parent slot, right
   child takes slot k+1, node ids in execution order).  O(L²) scalar
   work, microseconds.

The selected tree is identical to the sequential grower's, node ids and
all, whenever both compute identical gains (they histogram with different
programs, so near-tie fp flips fall under the documented CPU↔TPU
tolerance class).  The equivalence needs a finite depth cap: with
``max_depth`` unset the sequential path remains (an unbounded-depth tree
cannot be pre-expanded), so ``grow_any`` routes here only for
``0 < max_depth`` within the expansion memory budget.

Distribution contract matches levelwise.py: call under ``shard_map`` with
rows sharded; the fused psum inside the histogram builders is the only
collective; the selection runs replicated-identically on every shard.

Layout-wired expansion (r10): when ``leafwise_layout_supported`` admits
the config, the expansion fori carries the leaf-ordered record layout
(engine/leafperm.py) exactly as levelwise does — anchored at the root
(the natural-order record buffer, out-of-bag rows as sentinels), sides
derived from the layout records via the same packed-word arithmetic as
the natural-order partition, rows moved by the stable per-tile MXU
compaction, smaller children histogrammed as contiguous tile runs.  The
run bookkeeping stores heap NODE ids (``run_slot`` -> node): a split
keeps the parent's run for the LEFT child (node 2n) and appends a run
for the right (2n+1), so runs still ascend with tile position and
``leafperm.advance_runs`` applies with sentinel HN.  The per-expansion-
level sort + full-N record gather are gone from this path; the
expansion≡sequential equivalence and the psum-only collective contract
are untouched (test_leafwise_fast / test_leafperm_sharded).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

from dryad_tpu.config import Params
from dryad_tpu.engine.grower import (
    _monotone_array,
    child_bounds,
    finalize_leaf_values,
    pack_cat_bitset,
    root_stats,
)
from dryad_tpu.engine import levelwise
from dryad_tpu.engine.histogram import build_hist, build_hist_segmented
from dryad_tpu.engine.split import NEG_INF, find_best_split
from dryad_tpu.policy.table import GATE_DEFAULTS as _POLICY_DEFAULTS

from dryad_tpu.config import (  # noqa: F401  (re-exported API)
    LEAFWISE_HIST_BYTES_BUDGET as _HIST_BYTES_BUDGET,
    MAX_FAST_DEPTH as _MAX_FAST_DEPTH,
    effective_depth_params,
    leafwise_fast_supported,
)


def supports(p: Params, num_features: int, total_bins: int,
             num_rows: int | None = None) -> bool:
    """Fast leaf-wise needs a finite, memory-feasible expansion depth.

    The budget is checked against the PINNED (Pf, 3, F, B) buffer, but the
    widest level transiently holds ~5-6x that (hist_small/large/l/r plus
    the 2P-wide children concat for the vmapped split finder), so the cap
    is set to keep peak transients under ~1.5 GB.  Configs beyond it keep
    the sequential grower.  (The shape logic lives jax-free in
    ``config.leafwise_fast_supported`` so the CPU backend's max_depth=-1
    policy — config.effective_depth_params — can consult it without
    touching jax; a config that disables hist_subtraction is rejected
    there too, because the expansion derives every larger sibling by
    subtraction.)  ``num_rows`` must be the GLOBAL row count (see
    config.leafwise_fast_supported)."""
    return leafwise_fast_supported(p, num_features, total_bins, num_rows)


def phase_plan(depth_cap: int):
    """(d_switch, P_narrow, P_full) for the two-phase expansion loop — the
    ONE definition of the leafwise phase boundary, shared with
    train._comm_stats so the observability accounting mirrors the grower's
    actual per-level candidate widths (ADVICE r4 / r5 review)."""
    P_full = 1 << max(depth_cap - 1, 0)
    P_narrow = min(8, P_full)
    d_switch = 4 if (depth_cap > 4 and P_full > 8) else depth_cap
    return d_switch, P_narrow, P_full


# Run-capacity cap for the layout-wired expansion: the deepest move can
# produce one segment per level-D heap node, so the dense run bookkeeping
# is (2^D,)-wide and level_moves mandates >= 2*2^D + 2 tiles per level
# (one per run index per region) — the same structural cost class as
# levelwise's 512-leaf bound (2L+2 tiles).  At 2^D = 1024 that is ~1.05M
# zero-sentinel rows per level; past it the mandated movement stops being
# noise for any row count the expansion budget admits, while the
# recoverable per-level sort+gather stays fixed (~164 ms/level at 10M) —
# so deeper caps keep the legacy plan path (a written verdict, not a
# TODO; the gate cannot consult N — same-program rule).  r23: the cap
# lives in the policy table ("leafwise_layout"/"max_segments"); this
# name is the compatibility re-export of the committed default.
_MAX_WIRED_SEGMENTS = _POLICY_DEFAULTS["leafwise_layout"]["max_segments"]


def leafwise_layout_supported(p: Params, num_features: int, total_bins: int,
                              bin_itemsize: int,
                              platform: str | None = None) -> bool:
    """Static gate for the layout-wired batched leaf-wise expansion.

    Rides levelwise's ``deep_layout_supported`` (one gate surface: same
    record-width / bin / packed-word / backend exclusions and the
    ``deep_layout="legacy"`` opt-out; its num_leaves <= 512 bound is
    conservative here — leaf-wise runs are capped by expansion width,
    not the leaf budget, but a second knob would just invite drift) plus
    the expansion-width cap above.  Row-count free, like everything that
    picks a histogram program (CLAUDE.md same-program rule)."""
    from dryad_tpu.engine.levelwise import deep_layout_supported

    if not deep_layout_supported(p, num_features, total_bins, bin_itemsize,
                                 platform):
        return False
    # the expansion derives larger siblings by subtraction (supports()
    # rejects non-subtraction configs before this gate is consulted)
    if not p.hist_subtraction:
        return False
    from dryad_tpu.policy.gates import resolve

    return resolve("leafwise_layout",
                   {"max_depth": p.max_depth}) == "layout"


def grow_tree_leafwise_batched(
    params: Params,
    total_bins: int,
    Xb: jnp.ndarray,
    g: jnp.ndarray,
    h: jnp.ndarray,
    bag_mask: jnp.ndarray,
    feat_mask: jnp.ndarray,
    is_cat_feat: jnp.ndarray,
    *,
    has_cat: bool = False,
    axis_name: str | None = None,
    platform: str | None = None,
    learn_missing: bool = False,
    root_hist: jnp.ndarray | None = None,
    bundled_mask: jnp.ndarray | None = None,
) -> dict[str, Any]:
    p = params
    N, F = Xb.shape
    B = int(total_bins)
    L = p.effective_num_leaves
    M = p.max_nodes
    D = p.max_depth
    assert 0 < D <= _MAX_FAST_DEPTH
    HN = 1 << (D + 1)                 # heap slots (1-based; 0 unused)
    Pf = 1 << max(D - 1, 0)           # widest expansion level

    from dryad_tpu.engine.histogram import resolve_backend

    # wired gate FIRST (r10): a layout-wired expansion never touches the
    # plan-path record table or the natural-order tiles — skip both
    use_layout = leafwise_layout_supported(p, F, B, Xb.dtype.itemsize,
                                           platform)

    records = None
    nat_tiles = None
    if not use_layout and resolve_backend(p.hist_backend, segmented=True,
                                          platform=platform) == "pallas":
        from dryad_tpu.engine import pallas_hist

        if pallas_hist.supports(B):
            records = pallas_hist.make_records(Xb, g, h)
            # shallow-level natural-order pass, gated on the GLOBAL
            # matrix size (pallas_hist.maybe_natural_tiles documents why)
            nat_tiles = pallas_hist.maybe_natural_tiles(Xb, B, axis_name)

    def _nat_slots():
        from dryad_tpu.engine import pallas_hist

        return pallas_hist._NAT_SLOTS

    mono = _monotone_array(p, F)

    def best(hist, G, H, C, allow, lo, hi):
        return find_best_split(
            hist, G, H, C,
            lambda_l2=p.lambda_l2,
            min_child_weight=p.min_child_weight,
            min_data_in_leaf=p.min_data_in_leaf,
            min_split_gain=p.min_split_gain,
            feat_mask=feat_mask,
            is_cat_feat=is_cat_feat,
            allow=allow,
            has_cat=has_cat,
            monotone=mono,
            lo=lo,
            hi=hi,
            learn_missing=learn_missing,
            bundled_mask=bundled_mask,
        )

    # ---- histogram-reduction arm (r16) — levelwise.py's twin wiring:
    # feature-parallel reduce-scatter per expansion-level builder call,
    # sliced scan over the owned feature partition, one per-level
    # all_gather combine; the root keeps the fused psum + full scan
    # (root_stats reads feature 0's bins).  The selection replay below is
    # collective-free either way.
    from dryad_tpu.config import hist_reduce_resolved
    from dryad_tpu.engine import distributed as _dist
    from dryad_tpu.engine.split import find_best_split_sliced

    n_shards = _dist.axis_shards(axis_name)
    hr_mode = hist_reduce_resolved(p, F, B, n_shards)
    feat_par = hr_mode == "feature"
    FH = _dist.feature_slice_width(F, n_shards) if feat_par else F
    if feat_par:
        f_off = _dist.feature_shard_offset(axis_name, F)
        fmask_s = _dist.feature_shard_slice(feat_mask, axis_name)
        iscat_s = _dist.feature_shard_slice(is_cat_feat, axis_name)
        mono_s = (_dist.feature_shard_slice(mono, axis_name)
                  if mono is not None else None)
        bund_s = (_dist.feature_shard_slice(bundled_mask, axis_name)
                  if bundled_mask is not None else None)

        def best_sliced(hist, G, H, C, lo, hi):
            return find_best_split_sliced(
                hist, G, H, C,
                feat_offset=f_off,
                num_features_total=F,
                lambda_l2=p.lambda_l2,
                min_child_weight=p.min_child_weight,
                min_data_in_leaf=p.min_data_in_leaf,
                feat_mask=fmask_s,
                is_cat_feat=iscat_s,
                has_cat=has_cat,
                monotone=mono_s,
                lo=lo,
                hi=hi,
                learn_missing=learn_missing,
                bundled_mask=bund_s,
            )

    def level_scan(ch_hist, ch_G, ch_H, ch_C, allow, ch_lo, ch_hi):
        if not feat_par:
            return jax.vmap(best)(ch_hist, ch_G, ch_H, ch_C, allow,
                                  ch_lo, ch_hi)
        loc = jax.vmap(best_sliced)(ch_hist, ch_G, ch_H, ch_C, ch_lo, ch_hi)
        return _dist.combine_best_splits(
            loc, axis_name, allow=allow,
            min_split_gain=p.min_split_gain, has_cat=has_cat)

    # ---- root ----------------------------------------------------------------
    # ALL rows are routed (bag gates histograms only); derived from
    # bag_mask so the init inherits the shard's varying-manual-axes under
    # shard_map (a plain constant would make downstream vma types diverge —
    # same trick as grower.py / levelwise.py)
    row_node = jnp.where(bag_mask, 1, 1).astype(jnp.int32)
    hist0 = root_hist if root_hist is not None else build_hist(
        Xb, g, h, bag_mask, B,
        rows_per_chunk=p.rows_per_chunk, axis_name=axis_name,
        precision=p.hist_precision, backend=p.hist_backend,
        platform=platform)
    G0, H0, C0 = root_stats(hist0)
    ninf, pinf = jnp.float32(-jnp.inf), jnp.float32(jnp.inf)
    root = best(hist0, G0, H0, C0,
                (jnp.int32(0) < D) & (C0 >= 2 * p.min_data_in_leaf),
                ninf, pinf)
    Bc = root.cat_mask.shape[0]

    # heap-node tables (index = heap id; unwritten slots keep the defaults)
    nd_gain = jnp.full((HN,), NEG_INF, jnp.float32).at[1].set(root.gain)
    nd_feature = jnp.full((HN,), -1, jnp.int32).at[1].set(root.feature)
    nd_thresh = jnp.zeros((HN,), jnp.int32).at[1].set(root.threshold)
    nd_GL = jnp.zeros((HN,), jnp.float32).at[1].set(root.g_left)
    nd_HL = jnp.zeros((HN,), jnp.float32).at[1].set(root.h_left)
    nd_CL = jnp.zeros((HN,), jnp.float32).at[1].set(root.c_left)
    nd_G = jnp.zeros((HN,), jnp.float32).at[1].set(G0)
    nd_H = jnp.zeros((HN,), jnp.float32).at[1].set(H0)
    nd_C = jnp.zeros((HN,), jnp.float32).at[1].set(C0)
    nd_dleft = jnp.ones((HN,), bool).at[1].set(root.default_left)
    nd_catmask = jnp.zeros((HN, Bc), bool).at[1].set(root.cat_mask)
    nd_lo = jnp.full((HN,), ninf, jnp.float32)
    nd_hi = jnp.full((HN,), pinf, jnp.float32)

    # feature arm: the expansion buffer carries each shard's OWNED slice
    hist0_loc = (_dist.feature_shard_slice(hist0, axis_name, axis=1)
                 if feat_par else hist0)
    hists = jnp.zeros((Pf, 3, FH, B), jnp.float32).at[0].set(hist0_loc)

    exp_st = {
        "row_node": row_node, "hists": hists,
        "nd_gain": nd_gain, "nd_feature": nd_feature, "nd_thresh": nd_thresh,
        "nd_GL": nd_GL, "nd_HL": nd_HL, "nd_CL": nd_CL,
        "nd_G": nd_G, "nd_H": nd_H, "nd_C": nd_C,
        "nd_dleft": nd_dleft, "nd_catmask": nd_catmask,
        "nd_lo": nd_lo, "nd_hi": nd_hi,
    }

    # ---- wired (leaf-ordered layout) static plan (r10) -----------------------
    # Run capacity NR = 2^D: the deepest move yields one segment per
    # level-D heap node (leafwise_layout_supported caps it).  The shapes
    # below come from the LOCAL row count, like every shard-local buffer.
    from dryad_tpu.engine import leafperm

    d_switch, P_narrow, _ = phase_plan(D)
    NR = 1 << D
    half_bound_ok = axis_name is None and N < (1 << 24)
    n_buf_tiles = n_sel_narrow = n_sel_full = 0
    if use_layout:
        Tl = leafperm._TILE_ROWS
        n_buf_tiles = leafperm.wired_tiles_bound(-(-N // Tl), NR)
        # smaller children cover <= half the in-bag rows on a single
        # device (min(left,right) <= parent/2, parents disjoint) — the
        # same shared-bound rule as levelwise (see wired_sel_tiles_bound)
        n_sel_narrow = leafperm.wired_sel_tiles_bound(
            -(-N // Tl), n_buf_tiles, P_narrow, half=half_bound_ok)
        n_sel_full = leafperm.wired_sel_tiles_bound(
            -(-N // Tl), n_buf_tiles, Pf, half=half_bound_ok)
        # root-anchored layout: the natural-order record buffer IS the
        # root layout (run 0 -> heap node 1, sentinel HN elsewhere);
        # out-of-bag rows enter sentinel-flagged and are dropped by level
        # 0's move — no sort, no gather, no handoff
        rec_nat = leafperm.make_layout_records(Xb, g, h, valid=bag_mask)
        lay_rec, lay_tr, lay_ns = leafperm.natural_root_layout(
            rec_nat, NR, n_buf_tiles, first_slot=1, sentinel=HN,
            axis_name=axis_name)
        exp_st = dict(exp_st, lay_rec=lay_rec, lay_tile_run=lay_tr,
                      lay_run_slot=lay_ns)

    # ---- expansion: every valid split, level-synchronously -------------------
    def make_level_body(P, use_nat=False, use_layout=False, n_sel_tiles=0):
        def level_body(d, st):
            base = jnp.left_shift(jnp.int32(1), d)         # level-d heap base
            W = base                                        # level width
            jarr = jnp.arange(P, dtype=jnp.int32)
            idx = jnp.minimum(base + jarr, HN - 1)
            do = (st["nd_gain"][idx] > NEG_INF) & (jarr < W)
            sf = st["nd_feature"][idx]
            thr = st["nd_thresh"][idx]
            GL, HL, CL = st["nd_GL"][idx], st["nd_HL"][idx], st["nd_CL"][idx]
            Gp, Hp, Cp = st["nd_G"][idx], st["nd_H"][idx], st["nd_C"][idx]
            GR, HR, CR = Gp - GL, Hp - HL, Cp - CL

            # ---- partition: a row moves iff its node has a valid split.
            # Expansion splits EVERY valid-gain node at its level, so a row
            # can only sit at a valid-gain node when that node is at the
            # current level — no level check needed.  Same packed-word +
            # masked-reduce scheme as levelwise.py (measured there).
            rn = st["row_node"]
            valid_n = st["nd_gain"] > NEG_INF
            rec_t = None
            if B <= (1 << 13):
                cat_n = (is_cat_feat[jnp.maximum(st["nd_feature"], 0)]
                         if has_cat else jnp.zeros((HN,), bool))
                w0_t = ((valid_n.astype(jnp.uint32) << 31)
                        | (st["nd_dleft"].astype(jnp.uint32) << 30)
                        | (cat_n.astype(jnp.uint32) << 29)
                        | (jnp.clip(st["nd_thresh"], 0, B - 1)
                           .astype(jnp.uint32) << 16))
                rec_t = jnp.stack(
                    [w0_t, jnp.maximum(st["nd_feature"], 0).astype(jnp.uint32)],
                    axis=1)

                def packed_route(nodes, bins_of, rr=None):
                    """Per-row routing off the packed per-NODE table:
                    (splits?, goes-left?).  Shared by the natural-order
                    partition and the layout side derivation so the two
                    can never disagree on a row (identical integer/bool
                    arithmetic — levelwise.packed_route's convention).
                    ``rr`` lets the caller pass a pre-composed per-row
                    record (one small-table gather instead of two
                    chained ones); ``nodes`` is then only consulted for
                    the categorical bitset row."""
                    if rr is None:
                        rr = rec_t[nodes]                    # ONE gather
                    w0r = rr[:, 0]
                    rf = rr[:, 1].astype(jnp.int32)
                    bins_rf = bins_of(rf)
                    gl = bins_rf <= ((w0r >> 16)
                                     & jnp.uint32(0x1FFF)).astype(jnp.int32)
                    if learn_missing:
                        gl &= ((w0r >> 30) & 1).astype(bool) | (bins_rf > 0)
                    if has_cat:
                        cat_row = st["nd_catmask"][
                            jnp.minimum(nodes, HN - 1),
                            jnp.minimum(bins_rf, Bc - 1)]
                        gl = jnp.where(((w0r >> 29) & 1).astype(bool),
                                       cat_row, gl)
                    return ((w0r >> 31) != 0), gl

                row_do, go_left = packed_route(
                    rn, lambda rf: levelwise.select_bins(Xb, rf))
            else:
                row_do = valid_n[rn]
                rf = jnp.maximum(st["nd_feature"][rn], 0)
                bins_rf = jnp.take_along_axis(
                    Xb, rf[:, None].astype(jnp.int32), axis=1)[:, 0]
                bins_rf = bins_rf.astype(jnp.int32)
                go_left = bins_rf <= st["nd_thresh"][rn]
                if learn_missing:
                    go_left &= st["nd_dleft"][rn] | (bins_rf > 0)
                if has_cat:
                    cat_row = st["nd_catmask"][rn, jnp.minimum(bins_rf, Bc - 1)]
                    go_left = jnp.where(is_cat_feat[rf], cat_row, go_left)
            row_node = jnp.where(
                row_do, 2 * rn + jnp.where(go_left, 0, 1), rn)

            # ---- one batched histogram pass for all smaller children -----
            left_smaller = CL <= CR
            lay_new = None
            if use_layout:
                # WIRED level (r10): no per-level sort, no full-N record
                # gather.  Sides come off the carried layout's records
                # via the SAME packed_route arithmetic as the
                # natural-order partition above; one stable per-tile MXU
                # compaction moves the rows; the smaller children read
                # back as contiguous tile runs of the new layout.
                Tl = leafperm._TILE_ROWS
                lay_rec = st["lay_rec"]
                lay_tr = st["lay_tile_run"]
                lay_ns = st["lay_run_slot"]           # run -> heap node
                row_run = jnp.repeat(lay_tr, Tl)
                # compose run -> packed word at the (NR,) level, then pay
                # ONE per-row small-table gather (CLAUDE.md
                # pack-the-lookups rule); sentinel runs (lay_ns = HN)
                # compose to the zero pad row -> their rows route
                # pass-through, and carry no valid rows anyway
                rec_pad = jnp.concatenate(
                    [rec_t, jnp.zeros((1, 2), jnp.uint32)])
                rr_lay = rec_pad[jnp.minimum(lay_ns, HN)][row_run]
                node_lay = lay_ns[row_run] if has_cat else None
                _, _, valid_lay, xb_lay = leafperm.unpack_layout_records(
                    lay_rec, F, Xb.dtype)
                do_lay, left_lay = packed_route(
                    node_lay, lambda rf: levelwise.select_bins(xb_lay, rf),
                    rr=rr_lay)
                side = jnp.where(
                    valid_lay,
                    jnp.where(do_lay & ~left_lay, 1, 0),
                    2).astype(jnp.int32)
                pos, dstl, dstr, base_l, base_r, _ = leafperm.level_moves(
                    lay_tr, side, NR)
                lay_rec = leafperm.permute_records(
                    lay_rec, pos, dstl, dstr, lay_tr.shape[0],
                    platform=platform, axis_name=axis_name)
                # node -> run inverse BEFORE advancing (candidates are
                # parents of this level's move); sentinel runs scatter
                # past the (HN+1,) table so mode="drop" really drops them
                node_run = jnp.full((HN + 1,), NR, jnp.int32).at[
                    jnp.where(lay_ns < HN, lay_ns, HN + 1)].set(
                        jnp.arange(NR, dtype=jnp.int32), mode="drop")
                # a run's node carries a valid split only while that node
                # is at the current level (the expansion splits it NOW) —
                # left child keeps the run with node 2n, right child
                # appends node 2n+1 (advance_runs' pre-update contract)
                valid_tab = (rec_pad[:, 0] >> 31) != 0
                run_do = valid_tab[jnp.minimum(lay_ns, HN)] & (lay_ns < HN)
                ns2 = jnp.where(run_do, 2 * lay_ns, lay_ns)
                lay_tr_new, lay_ns_new = leafperm.advance_runs(
                    ns2, run_do, 2 * lay_ns + 1, base_l, base_r,
                    lay_tr.shape[0], sentinel=HN)
                lay_new = (lay_rec, lay_tr_new, lay_ns_new)
                # smaller children = contiguous segments of the NEW layout
                rj = node_run[idx]
                rjc = jnp.minimum(rj, NR - 1)
                lt_l = base_l[1:] - base_l[:-1]
                lt_r = base_r[1:] - base_r[:-1]
                sel_ok = do & (rj < NR)
                seg_first = jnp.where(
                    sel_ok,
                    jnp.where(left_smaller, base_l[rjc], base_r[rjc]), 0)
                seg_nt = jnp.where(
                    sel_ok,
                    jnp.where(left_smaller, lt_l[rjc], lt_r[rjc]), 0)
                hist_small = leafperm.hist_from_layout(
                    lay_rec, seg_first, seg_nt, P, B, F, Xb.dtype,
                    n_sel_tiles, axis_name=axis_name, platform=platform,
                    hist_reduce=hr_mode)
            else:
                small_heap = 2 * idx + jnp.where(left_smaller, 0, 1)
                colof = jnp.full((HN,), P, jnp.int32).at[
                    jnp.where(do, small_heap, HN)].set(jarr, mode="drop")
                smallsel = jnp.where(bag_mask, colof[row_node], P)
                bound_ok = axis_name is None and N < (1 << 24)
                if use_nat:
                    from dryad_tpu.engine import pallas_hist

                    hist_small = pallas_hist.build_hist_small(
                        nat_tiles, g, h, smallsel, P, B, F,
                        axis_name=axis_name, platform=platform,
                        hist_reduce=hr_mode)
                else:
                    # exact per-column counts (smaller-child C off the
                    # parent histogram) admit the pad-injected aligned
                    # sort inside build_hist_segmented — see levelwise.py
                    small_cnt = (jnp.where(do,
                                           jnp.where(left_smaller, CL, CR),
                                           0.0).astype(jnp.int32)
                                 if bound_ok else None)
                    hist_small = build_hist_segmented(
                        Xb, g, h, smallsel, P, B,
                        rows_per_chunk=p.rows_per_chunk, axis_name=axis_name,
                        precision=p.hist_precision, backend=p.hist_backend,
                        rows_bound=(N // 2 + 1) if bound_ok else None,
                        platform=platform, records=records,
                        sel_counts=small_cnt,
                        # deep caps leave most expansion slots empty —
                        # exactly where staged gather prefixes pay (see
                        # levelwise.py)
                        stage_gather=L < Pf,
                        hist_reduce=hr_mode,
                    )
            hist_large = st["hists"][jnp.minimum(jarr, Pf - 1)] - hist_small
            ls = left_smaller[:, None, None, None]
            hist_l = jnp.where(ls, hist_small, hist_large)
            hist_r = jnp.where(ls, hist_large, hist_small)
            # children hists land at level-(d+1) offsets 2j / 2j+1; the
            # final level's children (never split) fall off the buffer and
            # are dropped
            hists = st["hists"].at[
                jnp.where(do, 2 * jarr, Pf)].set(hist_l, mode="drop")
            hists = hists.at[
                jnp.where(do, 2 * jarr + 1, Pf)].set(hist_r, mode="drop")

            # ---- children stats + their best splits ----------------------
            lo_p, hi_p = st["nd_lo"][idx], st["nd_hi"][idx]
            if mono is not None:
                lo_l, hi_l, lo_r, hi_r = child_bounds(
                    mono, sf, GL, HL, GR, HR, jnp.float32(p.lambda_l2),
                    lo_p, hi_p)
            else:
                lo_l = lo_r = lo_p
                hi_l = hi_r = hi_p
            ch_heap = jnp.concatenate([2 * idx, 2 * idx + 1])
            ch_do = jnp.concatenate([do, do])
            ch_hist = jnp.concatenate([hist_l, hist_r])
            ch_G = jnp.concatenate([GL, GR])
            ch_H = jnp.concatenate([HL, HR])
            ch_C = jnp.concatenate([CL, CR])
            ch_lo = jnp.concatenate([lo_l, lo_r])
            ch_hi = jnp.concatenate([hi_l, hi_r])
            allow = ch_do & (d + 1 < D) & (ch_C >= 2 * p.min_data_in_leaf)
            res = level_scan(ch_hist, ch_G, ch_H, ch_C, allow, ch_lo, ch_hi)

            cidx = jnp.where(ch_do, ch_heap, HN)
            st_new = dict(st)
            st_new["row_node"] = row_node
            st_new["hists"] = hists
            st_new["nd_gain"] = st["nd_gain"].at[cidx].set(res.gain,
                                                           mode="drop")
            st_new["nd_feature"] = st["nd_feature"].at[cidx].set(
                res.feature, mode="drop")
            st_new["nd_thresh"] = st["nd_thresh"].at[cidx].set(
                res.threshold, mode="drop")
            st_new["nd_GL"] = st["nd_GL"].at[cidx].set(res.g_left, mode="drop")
            st_new["nd_HL"] = st["nd_HL"].at[cidx].set(res.h_left, mode="drop")
            st_new["nd_CL"] = st["nd_CL"].at[cidx].set(res.c_left, mode="drop")
            st_new["nd_G"] = st["nd_G"].at[cidx].set(ch_G, mode="drop")
            st_new["nd_H"] = st["nd_H"].at[cidx].set(ch_H, mode="drop")
            st_new["nd_C"] = st["nd_C"].at[cidx].set(ch_C, mode="drop")
            st_new["nd_dleft"] = st["nd_dleft"].at[cidx].set(
                res.default_left, mode="drop")
            st_new["nd_catmask"] = st["nd_catmask"].at[cidx].set(
                res.cat_mask, mode="drop")
            st_new["nd_lo"] = st["nd_lo"].at[cidx].set(ch_lo, mode="drop")
            st_new["nd_hi"] = st["nd_hi"].at[cidx].set(ch_hi, mode="drop")
            if use_layout:
                (st_new["lay_rec"], st_new["lay_tile_run"],
                 st_new["lay_run_slot"]) = lay_new
            return st_new
        return level_body

    exp_st = jax.lax.fori_loop(
        0, d_switch,
        make_level_body(P_narrow,
                        use_nat=nat_tiles is not None
                        and P_narrow <= _nat_slots(),
                        use_layout=use_layout, n_sel_tiles=n_sel_narrow),
        exp_st)
    if d_switch < D:
        exp_st = jax.lax.fori_loop(
            d_switch, D,
            make_level_body(Pf, use_nat=nat_tiles is not None
                            and Pf <= _nat_slots(),
                            use_layout=use_layout, n_sel_tiles=n_sel_full),
            exp_st)

    # ---- selection: replay grow_tree's slot machine on the gain tree ---------
    nd_gain = exp_st["nd_gain"]
    nd_feature = exp_st["nd_feature"]
    nd_thresh = exp_st["nd_thresh"]
    nd_dleft = exp_st["nd_dleft"]
    nd_catmask = exp_st["nd_catmask"]
    nd_G, nd_H = exp_st["nd_G"], exp_st["nd_H"]
    nd_C_sel = exp_st["nd_C"]
    nd_lo, nd_hi = exp_st["nd_lo"], exp_st["nd_hi"]

    sel_st = {
        "slot_heap": jnp.zeros((L,), jnp.int32).at[0].set(1),
        "slot_tree": jnp.full((L,), -1, jnp.int32).at[0].set(0),
        "slot_gain": jnp.full((L,), NEG_INF, jnp.float32).at[0].set(
            nd_gain[1]),
        "slot_depth": jnp.zeros((L,), jnp.int32),
        "feature": jnp.full((M,), -1, jnp.int32),
        "threshold": jnp.zeros((M,), jnp.int32),
        "gain": jnp.zeros((M,), jnp.float32),
        "cover": jnp.zeros((M,), jnp.float32).at[0].set(nd_C_sel[1]),
        "left": jnp.zeros((M,), jnp.int32),
        "right": jnp.zeros((M,), jnp.int32),
        "is_cat": jnp.zeros((M,), bool),
        "cat_nodes": jnp.zeros((M, Bc), bool),
        "node_dleft": jnp.ones((M,), bool),
        "selected": jnp.zeros((HN,), bool),
        "child_tree": jnp.zeros((HN,), jnp.int32),
        "num_nodes": jnp.int32(1),
        "max_depth": jnp.int32(0),
    }

    def do_split(k, s, st):
        n = st["slot_heap"][s]
        parent = st["slot_tree"][s]
        sf = nd_feature[n]
        cat_split = is_cat_feat[jnp.maximum(sf, 0)] if has_cat \
            else jnp.bool_(False)
        left_id = st["num_nodes"]
        right_id = left_id + 1
        depth_c = st["slot_depth"][s] + 1
        new_r = jnp.int32(k + 1)
        return {
            "slot_heap": st["slot_heap"].at[s].set(2 * n)
                                        .at[new_r].set(2 * n + 1),
            "slot_tree": st["slot_tree"].at[s].set(left_id)
                                        .at[new_r].set(right_id),
            "slot_gain": st["slot_gain"].at[s].set(nd_gain[2 * n])
                                        .at[new_r].set(nd_gain[2 * n + 1]),
            "slot_depth": st["slot_depth"].at[s].set(depth_c)
                                          .at[new_r].set(depth_c),
            "feature": st["feature"].at[parent].set(sf),
            "threshold": st["threshold"].at[parent].set(
                jnp.where(cat_split, 0, nd_thresh[n])),
            "gain": st["gain"].at[parent].set(st["slot_gain"][s]),
            "cover": st["cover"].at[left_id].set(nd_C_sel[2 * n])
                                .at[right_id].set(nd_C_sel[2 * n + 1]),
            "left": st["left"].at[parent].set(left_id),
            "right": st["right"].at[parent].set(right_id),
            "is_cat": st["is_cat"].at[parent].set(cat_split),
            "cat_nodes": st["cat_nodes"].at[parent].set(
                jnp.where(cat_split, nd_catmask[n],
                          jnp.zeros((Bc,), bool))),
            "node_dleft": st["node_dleft"].at[parent].set(
                nd_dleft[n] | cat_split),
            "selected": st["selected"].at[n].set(True),
            "child_tree": st["child_tree"].at[2 * n].set(left_id)
                                          .at[2 * n + 1].set(right_id),
            "num_nodes": st["num_nodes"] + 2,
            "max_depth": jnp.maximum(st["max_depth"], depth_c),
        }

    def sel_body(k, st):
        s = jnp.argmax(st["slot_gain"]).astype(jnp.int32)
        return jax.lax.cond(st["slot_gain"][s] > NEG_INF,
                            lambda st_: do_split(k, s, st_),
                            lambda st_: st_, st)

    sel_st = jax.lax.fori_loop(0, L - 1, sel_body, sel_st)

    # ---- finalize -------------------------------------------------------------
    sh = jnp.clip(sel_st["slot_heap"], 0, HN - 1)
    value = finalize_leaf_values(
        p, M, sel_st["slot_tree"], nd_G[sh], nd_H[sh],
        jnp.zeros((M,), jnp.float32),
        slot_lo=nd_lo[sh] if mono is not None else None,
        slot_hi=nd_hi[sh] if mono is not None else None,
    )
    cat_bitset = pack_cat_bitset(sel_st["cat_nodes"], M)

    # map every heap node to its leaf in the SELECTED tree: walking down,
    # a node resolves to its own tree id where its parent was selected,
    # else inherits the parent's resolution (D static levels)
    leaf_of = jnp.zeros((HN,), jnp.int32)
    selected = sel_st["selected"]
    child_tree = sel_st["child_tree"]
    idx_all = jnp.arange(HN, dtype=jnp.int32)
    for d in range(1, D + 1):
        lvl = (idx_all >> d) == 1
        par = idx_all >> 1
        leaf_of = jnp.where(lvl,
                            jnp.where(selected[par], child_tree[idx_all],
                                      leaf_of[par]),
                            leaf_of)

    return {
        "feature": sel_st["feature"],
        "threshold": sel_st["threshold"],
        "left": sel_st["left"],
        "right": sel_st["right"],
        "value": value,
        "gain": sel_st["gain"],
        "is_cat": sel_st["is_cat"],
        "cat_bitset": cat_bitset,
        "default_left": sel_st["node_dleft"],
        "cover": sel_st["cover"],
        "max_depth": sel_st["max_depth"],
        "row_leaf": leaf_of[jnp.clip(exp_st["row_node"], 0, HN - 1)],
    }
