"""Distributed data-parallel training: rows sharded over a device mesh.

The reference distributes GBDT training the NCCL way: shard rows across
workers, build local per-node grad/hess histograms, allreduce them, take
identical split decisions everywhere (BASELINE.json:5; SURVEY.md §2 #13-14).
The TPU-native translation keeps that exact dataflow but rides XLA
collectives:

* mesh axis ``"data"`` spans all chips (ICI within a slice, DCN across
  hosts on v5p-64 — the mesh abstracts both).
* the full per-class train step (grad/hess -> grow -> partition -> score
  update) runs under ``shard_map``: every device executes the same grower
  program on its row shard.
* the cross-device exchange is per-arm (``Params.hist_reduce``).  The
  "fused" arm keeps the classic contract: ONE fused grad/hess/count
  histogram ``jax.lax.psum`` per builder call — payload the full
  (P, 3, F, B) fp32 stack, exactly where the reference put NCCL; split
  decisions derive from the replicated histogram, so every device grows
  bit-identical trees with no further communication.  The "feature" arm
  (r16 — LightGBM's reduce-scatter data-parallel mode) replaces that
  all-reduce with ``reduce_scatter_hist``: each shard receives its OWN
  contiguous F/n feature slice fully reduced (per-device reduced payload
  shrinks n-fold), runs the split scan on the owned slice only
  (``split.find_best_split_sliced``), and one tiny per-level
  ``all_gather`` of packed best-split records (``combine_best_splits``)
  makes every shard pick the SAME winner — the packed tie key reproduces
  the fused scan's feature-major first-max order exactly, and the
  reduce-scattered slices are bitwise-equal to the psum's slices
  (measured; pinned by tests/test_hist_reduce.py).

Row counts must divide the mesh; ``pad_rows`` pads with bagged-out rows
(mask False) that cannot influence any histogram.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dryad_tpu.config import Params, hist_reduce_resolved
from dryad_tpu.engine.jax_compat import shard_map, shard_map_norep

AXIS = "data"


def make_mesh(devices=None) -> Mesh:
    devices = jax.devices() if devices is None else devices
    return Mesh(np.asarray(devices), (AXIS,))


def padded_rows(n: int, n_shards: int) -> int:
    return -(-n // n_shards) * n_shards


def replicate(mesh: Mesh, tree):
    """Place a pytree fully replicated over the mesh — one copy per device.

    The serving registry uses this for predict's tree tables: replicating
    once at stage time means every sharded predict dispatch finds its
    operands already resident instead of re-transferring them per call."""
    return jax.device_put(tree, NamedSharding(mesh, P()))


def shard_rows(mesh: Mesh, *arrays):
    """Place row-indexed arrays with rows split over the mesh's data axis."""
    out = []
    for a in arrays:
        spec = P(AXIS) if a.ndim == 1 else P(AXIS, *(None,) * (a.ndim - 1))
        out.append(jax.device_put(a, NamedSharding(mesh, spec)))
    return tuple(out)


# ---------------------------------------------------------------------------
# feature-parallel histogram reduction (hist_reduce="feature", r16)
# ---------------------------------------------------------------------------

def axis_shards(axis_name) -> int:
    """Static shard count inside shard_map (psum of a constant folds to
    the axis size at trace time — the pallas_hist.maybe_natural_tiles
    precedent); 1 outside any mesh."""
    return int(jax.lax.psum(1, axis_name)) if axis_name is not None else 1


def feature_slice_width(num_features: int, n_shards: int) -> int:
    """Owned features per shard: ceil(F / n).  Non-divisible F pads the
    reduced histogram (and the sliced masks) with dead features — all-pad
    shards contribute -inf records the combine can never pick."""
    return -(-num_features // max(n_shards, 1))


def reduce_scatter_hist(hist: jnp.ndarray, axis_name: str) -> jnp.ndarray:
    """(..., F, B) per-shard partial histograms -> (..., Fs, B) fully
    reduced OWNED slice (shard i owns features [i*Fs, (i+1)*Fs) of the
    zero-padded feature axis).  The reduce-scattered slice is bitwise
    equal to the corresponding slice of ``jax.lax.psum`` on this backend
    (measured; the fused-vs-feature bitwise parity tests ride on it)."""
    n = axis_shards(axis_name)
    F = hist.shape[-2]
    pad = feature_slice_width(F, n) * n - F
    if pad:
        width = [(0, 0)] * (hist.ndim - 2) + [(0, pad), (0, 0)]
        hist = jnp.pad(hist, width)
    return jax.lax.psum_scatter(hist, axis_name,
                                scatter_dimension=hist.ndim - 2, tiled=True)


def reduce_hist(hist: jnp.ndarray, axis_name, hist_reduce: str = "fused"):
    """The one histogram cross-shard reduction every builder tail calls:
    the fused psum (default — the classic single collective) or the
    feature-arm reduce-scatter.  No-op outside a mesh (the degenerate
    single-device "feature" program keeps the full slice)."""
    if axis_name is None:
        return hist
    if hist_reduce == "feature":
        return reduce_scatter_hist(hist, axis_name)
    return jax.lax.psum(hist, axis_name)


def feature_shard_slice(arr: jnp.ndarray, axis_name, axis: int = 0):
    """Slice a replicated feature-indexed array to this shard's owned
    features (zero/False padding on the tail shard — dead entries).  The
    identity outside a mesh: the degenerate 1-shard feature program scans
    the full slice."""
    if axis_name is None:
        return arr
    n = axis_shards(axis_name)
    F = arr.shape[axis]
    Fs = feature_slice_width(F, n)
    pad = Fs * n - F
    if pad:
        width = [(0, 0)] * arr.ndim
        width[axis] = (0, pad)
        arr = jnp.pad(arr, width)
    off = jax.lax.axis_index(axis_name).astype(jnp.int32) * Fs
    return jax.lax.dynamic_slice_in_dim(arr, off, Fs, axis=axis)


def feature_shard_offset(axis_name, num_features: int) -> jnp.ndarray:
    """This shard's first owned GLOBAL feature id (0 outside a mesh) —
    the sliced scan's ``feat_offset``, a traced scalar so every shard
    runs ONE program."""
    if axis_name is None:
        return jnp.int32(0)
    Fs = feature_slice_width(num_features, axis_shards(axis_name))
    return jax.lax.axis_index(axis_name).astype(jnp.int32) * Fs


def combine_best_splits(rec, axis_name, *, allow, min_split_gain: float,
                        has_cat: bool):
    """All-gather per-shard LocalSplit records and run the replicated
    combine — every shard computes the identical SplitResult batch.  The
    scalar fields ride ONE packed (…, 8)-word all-gather per level (plus
    one for the raw categorical rows when the config has them); outside a
    mesh the gather degenerates to a leading singleton axis (same combine
    program)."""
    from dryad_tpu.engine.split import combine_local_splits, pack_local_split

    words = pack_local_split(rec)
    cat = rec.cat_mask if has_cat else None
    if axis_name is not None:
        words = jax.lax.all_gather(words, axis_name, axis=0)
        if cat is not None:
            cat = jax.lax.all_gather(cat, axis_name, axis=0)
    else:
        words = words[None]
        cat = cat[None] if cat is not None else None
    return combine_local_splits(words, cat, allow=allow,
                                min_split_gain=min_split_gain,
                                has_cat=has_cat)


def grow_sharded(params: Params, total_bins: int, has_cat: bool,
                 mesh: Mesh, Xb, g, h, bag_mask, feat_mask, is_cat_feat,
                 platform=None, learn_missing=False, root_hist=None,
                 bundled_mask=None, global_rows=None):
    """One sharded tree grow; returns (replicated tree, row-sharded leaves).

    Called inside the device train step's jit: the tree arrays come back
    replicated, the per-row leaf assignment keeps the row sharding so the
    caller's score update stays shard-local.  ``root_hist`` (replicated)
    carries the class's slice of the shared-plan multiclass root pass.
    """
    from dryad_tpu.engine.grower import grow_any  # lazy: builders import us

    def run(Xb_l, g_l, h_l, bag_l, fmask, iscat, *extras):
        extras = list(extras)
        bmask_l = extras.pop(0) if bundled_mask is not None else None
        tree = grow_any(
            params, total_bins, Xb_l, g_l, h_l, bag_l, fmask, iscat,
            has_cat=has_cat, axis_name=AXIS, platform=platform,
            learn_missing=learn_missing,
            root_hist=extras[0] if extras else None,
            bundled_mask=bmask_l, global_rows=global_rows,
        )
        # per-shard leaf ids straight from the grower's partition state
        leaves = tree.pop("row_leaf")
        return tree, leaves

    row = P(AXIS)
    row2 = P(AXIS, None)
    rep = P()
    tree_specs = {
        "feature": rep, "threshold": rep, "left": rep, "right": rep,
        "value": rep, "gain": rep, "is_cat": rep, "cat_bitset": rep,
        "default_left": rep, "cover": rep, "max_depth": rep,
    }
    extra = () if bundled_mask is None else (bundled_mask,)
    extra += () if root_hist is None else (root_hist,)
    # the feature arm's combine all_gather has no replication rule in the
    # 0.4.x checker (its outputs ARE device-identical — the combine runs
    # on gathered records); the rep check comes off for that arm only,
    # with the parity tests standing in (jax_compat.shard_map_norep doc).
    # Only the LEVEL-SYNCHRONOUS growers run the feature program — the
    # sequential grower ignores hist_reduce — so the checker stays ON for
    # every fused program (mirrors _comm_stats' level_synchronous rule).
    level_sync = params.growth == "depthwise" and params.max_depth > 0
    if not level_sync and params.growth == "leafwise":
        from dryad_tpu.engine import leafwise_fast

        level_sync = leafwise_fast.supports(
            params, Xb.shape[1], int(total_bins),
            global_rows if global_rows is not None else Xb.shape[0])
    mode = (hist_reduce_resolved(params, Xb.shape[1], int(total_bins),
                                 mesh.devices.size)
            if level_sync else "fused")
    sm = shard_map_norep if mode == "feature" else shard_map
    return sm(
        run, mesh=mesh,
        in_specs=(row2, row, row, row, rep, rep) + (rep,) * len(extra),
        out_specs=(tree_specs, row),
    )(Xb, g, h, bag_mask, feat_mask, is_cat_feat, *extra)


def roots_sharded(mesh: Mesh, Xb, g_all, h_all, bag, total_bins,
                  rows_per_chunk, precision):
    """Shared-plan multiclass root histograms over the mesh -> replicated
    (K, 3, F, B); one fused psum carries all K classes' stats.  Runs the
    SAME builder program as the single-device path so near-tie root
    argmaxes cannot differ between 1-shard and N-shard runs (the MXU's
    lowering of the (2K+1)-row pass is fusion-sensitive — measured NOT
    bitwise vs the 3-row per-class pass on real hardware)."""
    from dryad_tpu.engine.histogram import build_hist_classes

    def run(X, gs, hs, bg):
        return build_hist_classes(
            X, gs, hs, bg, total_bins, rows_per_chunk=rows_per_chunk,
            precision=precision, axis_name=AXIS)

    row = P(AXIS)
    row2 = P(AXIS, None)
    return shard_map(
        run, mesh=mesh, in_specs=(row2, row2, row2, row), out_specs=P(),
    )(Xb, g_all, h_all, bag)
