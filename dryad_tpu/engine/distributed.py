"""Distributed data-parallel training: rows sharded over a device mesh.

The reference distributes GBDT training the NCCL way: shard rows across
workers, build local per-node grad/hess histograms, allreduce them, take
identical split decisions everywhere (BASELINE.json:5; SURVEY.md §2 #13-14).
The TPU-native translation keeps that exact dataflow but rides XLA
collectives:

* mesh axis ``"data"`` spans all chips (ICI within a slice, DCN across
  hosts on v5p-64 — the mesh abstracts both).
* the full per-class train step (grad/hess -> grow -> partition -> score
  update) runs under ``shard_map``: every device executes the same grower
  program on its row shard.
* the ONLY cross-device exchange is the fused grad/hess/count histogram
  ``jax.lax.psum`` inside ``build_hist`` — one latency-bound allreduce per
  split, payload (3, F, B) fp32, exactly where the reference put NCCL.
  Split decisions are then derived from the replicated histogram, so every
  device grows bit-identical trees with no further communication.

Row counts must divide the mesh; ``pad_rows`` pads with bagged-out rows
(mask False) that cannot influence any histogram.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dryad_tpu.config import Params
from dryad_tpu.engine.grower import grow_any
from dryad_tpu.engine.jax_compat import shard_map

AXIS = "data"


def make_mesh(devices=None) -> Mesh:
    devices = jax.devices() if devices is None else devices
    return Mesh(np.asarray(devices), (AXIS,))


def padded_rows(n: int, n_shards: int) -> int:
    return -(-n // n_shards) * n_shards


def replicate(mesh: Mesh, tree):
    """Place a pytree fully replicated over the mesh — one copy per device.

    The serving registry uses this for predict's tree tables: replicating
    once at stage time means every sharded predict dispatch finds its
    operands already resident instead of re-transferring them per call."""
    return jax.device_put(tree, NamedSharding(mesh, P()))


def shard_rows(mesh: Mesh, *arrays):
    """Place row-indexed arrays with rows split over the mesh's data axis."""
    out = []
    for a in arrays:
        spec = P(AXIS) if a.ndim == 1 else P(AXIS, *(None,) * (a.ndim - 1))
        out.append(jax.device_put(a, NamedSharding(mesh, spec)))
    return tuple(out)


def grow_sharded(params: Params, total_bins: int, has_cat: bool,
                 mesh: Mesh, Xb, g, h, bag_mask, feat_mask, is_cat_feat,
                 platform=None, learn_missing=False, root_hist=None,
                 bundled_mask=None, global_rows=None):
    """One sharded tree grow; returns (replicated tree, row-sharded leaves).

    Called inside the device train step's jit: the tree arrays come back
    replicated, the per-row leaf assignment keeps the row sharding so the
    caller's score update stays shard-local.  ``root_hist`` (replicated)
    carries the class's slice of the shared-plan multiclass root pass.
    """

    def run(Xb_l, g_l, h_l, bag_l, fmask, iscat, *extras):
        extras = list(extras)
        bmask_l = extras.pop(0) if bundled_mask is not None else None
        tree = grow_any(
            params, total_bins, Xb_l, g_l, h_l, bag_l, fmask, iscat,
            has_cat=has_cat, axis_name=AXIS, platform=platform,
            learn_missing=learn_missing,
            root_hist=extras[0] if extras else None,
            bundled_mask=bmask_l, global_rows=global_rows,
        )
        # per-shard leaf ids straight from the grower's partition state
        leaves = tree.pop("row_leaf")
        return tree, leaves

    row = P(AXIS)
    row2 = P(AXIS, None)
    rep = P()
    tree_specs = {
        "feature": rep, "threshold": rep, "left": rep, "right": rep,
        "value": rep, "gain": rep, "is_cat": rep, "cat_bitset": rep,
        "default_left": rep, "cover": rep, "max_depth": rep,
    }
    extra = () if bundled_mask is None else (bundled_mask,)
    extra += () if root_hist is None else (root_hist,)
    return shard_map(
        run, mesh=mesh,
        in_specs=(row2, row, row, row, rep, rep) + (rep,) * len(extra),
        out_specs=(tree_specs, row),
    )(Xb, g, h, bag_mask, feat_mask, is_cat_feat, *extra)


def roots_sharded(mesh: Mesh, Xb, g_all, h_all, bag, total_bins,
                  rows_per_chunk, precision):
    """Shared-plan multiclass root histograms over the mesh -> replicated
    (K, 3, F, B); one fused psum carries all K classes' stats.  Runs the
    SAME builder program as the single-device path so near-tie root
    argmaxes cannot differ between 1-shard and N-shard runs (the MXU's
    lowering of the (2K+1)-row pass is fusion-sensitive — measured NOT
    bitwise vs the 3-row per-class pass on real hardware)."""
    from dryad_tpu.engine.histogram import build_hist_classes

    def run(X, gs, hs, bg):
        return build_hist_classes(
            X, gs, hs, bg, total_bins, rows_per_chunk=rows_per_chunk,
            precision=precision, axis_name=AXIS)

    row = P(AXIS)
    row2 = P(AXIS, None)
    return shard_map(
        run, mesh=mesh, in_specs=(row2, row2, row2, row), out_specs=P(),
    )(Xb, g_all, h_all, bag)
