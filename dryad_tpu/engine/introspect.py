"""Compiled-program introspection at compile boundaries (``dryad_prog_*``).

Per-call host timing lies through the axon tunnel (CLAUDE.md measuring
rules), so the only trustworthy per-program telemetry is what the
compiler itself reports.  This module captures it — and it lives HERE,
in the engine, because it touches jax: the obs package is jax-free by
lint, and its registry contract says collectors only record values the
engine already fetched.  Everything recorded is a host scalar.

What happens at a compile boundary (``capture(family, key, jit_fn,
*args, **kwargs)``, called by engine/train.py, engine/predict.py and
serve/cache.py right before the FIRST dispatch of a program):

* ``dryad_prog_flops`` / ``dryad_prog_bytes_accessed`` gauges from
  ``jit_fn.lower(...).cost_analysis()`` — tracing + MLIR emission only,
  NO XLA compile, so the capture can never double a 70–120 s remote
  tunnel compile.  Measured on this container (jax 0.4.37): AOT
  ``lower().compile()`` does NOT share the executable cache with the
  normal call path — the call recompiles — which is why introspection
  must never compile on the dispatch path.
* ``dryad_prog_memory_bytes{kind=temp|argument|output}`` from
  ``compiled.memory_analysis()`` — this one NEEDS a real compile, so it
  is opt-in (``DRYAD_PROG_MEMORY=1``): a second local compile is cheap
  on the CPU backend (tests, the acceptance drill) and deliberate
  anywhere else.
* ``dryad_prog_compiles_total{program=...}`` via the recompile tripwire
  (obs/tripwire.py) — every boundary notes its program key there, so an
  armed family (serve after warmup, train after the first chunk) turns
  a NEW key into ``dryad_recompile_unexpected_total`` + a degraded
  ``/healthz``.
* ``dryad_prog_backend_compiles_total`` /
  ``dryad_prog_compile_seconds_total`` from a ``jax.monitoring``
  duration listener on the backend-compile event — the compile walls the
  runtime actually paid, process-wide, attributed to the boundary family
  that was active on the compiling thread (best-effort sticky label;
  compiles outside any declared boundary land on ``program="other"``).

Cost model: captures are memoized per (family, key) process-wide, so a
warm re-run (bench arms, repeated serve traffic) pays NOTHING — exactly
mirroring the jit executable cache.  Every entry point returns after one
``enabled`` check when the registry is disabled (the zero-cost
contract), and a capture failure increments
``dryad_prog_capture_errors_total`` instead of breaking the dispatch.

dryadlint's ``introspect-compile-only`` rule pins the discipline: the
``cost_analysis``/``memory_analysis``/AOT-``compile()`` calls below are
the ONLY legal sites, and nothing here may be called from a loop body —
the tripwire must never become a per-iteration host sync.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Optional

from dryad_tpu.obs.registry import default_registry
from dryad_tpu.obs.tripwire import default_tripwire

_seen: set = set()               # (family, key) already introspected
_seen_lock = threading.Lock()
_tls = threading.local()         # .program — sticky compile attribution
_listener_lock = threading.Lock()
_listener_installed = False

#: the jax.monitoring event real XLA compiles emit (verified on 0.4.37)
_COMPILE_EVENT_SUFFIX = "backend_compile_duration"


def memory_capture_enabled() -> bool:
    """Peak-memory capture costs one extra LOCAL compile per program —
    opt-in only (never silently doubles a tunnel compile)."""
    return os.environ.get("DRYAD_PROG_MEMORY", "0") == "1"


def _on_compile_duration(name: str, secs: float, **kw) -> None:
    if not name.endswith(_COMPILE_EVENT_SUFFIX):
        return
    reg = default_registry()
    if not reg.enabled:
        return
    program = getattr(_tls, "program", None) or "other"
    reg.counter("dryad_prog_backend_compiles_total",
                "Real XLA backend compiles by boundary family").labels(
        program=program).inc()
    reg.counter("dryad_prog_compile_seconds_total",
                "XLA backend compile wall by boundary family").labels(
        program=program).inc(float(secs))


def _install_listener() -> None:
    global _listener_installed
    if _listener_installed:
        return
    with _listener_lock:
        if _listener_installed:
            return
        import jax.monitoring

        jax.monitoring.register_event_duration_secs_listener(
            _on_compile_duration)
        _listener_installed = True


def seen(family: str, key) -> bool:
    with _seen_lock:
        return (family, key) in _seen


def reset_seen() -> None:
    """Forget the process memo (tests re-capture after clear_caches)."""
    with _seen_lock:
        _seen.clear()


def capture(family: str, key, jit_fn, *args,
            labels: Optional[dict] = None, note_tripwire: bool = True,
            **kwargs) -> bool:
    """Introspect one compile boundary; returns True when (family, key)
    was new and a capture ran.  ``jit_fn``/``args``/``kwargs`` must be
    EXACTLY what the caller is about to dispatch — the lowering is the
    program the jit call will compile.  Observation-only: the jit call
    path, and therefore every traced program, is untouched (the jaxpr
    auditor's digests are the proof)."""
    reg = default_registry()
    if not reg.enabled:
        return False
    if os.environ.get("DRYAD_PROG", "1") == "0":
        # operational kill switch: the capture's lower() doubles a
        # program's TRACE cost (never its compile) — skippable where even
        # that matters, without disabling the rest of the registry
        return False
    _install_listener()
    # sticky attribution for the compile the caller is about to trigger
    _tls.program = family
    if note_tripwire:
        default_tripwire().note_compile(family, key)
    with _seen_lock:
        if (family, key) in _seen:
            return False
        _seen.add((family, key))
    lbl = dict(labels or {})
    lbl["program"] = family
    try:
        t0 = time.perf_counter()
        lowered = jit_fn.lower(*args, **kwargs)
        cost = lowered.cost_analysis()
        d = cost[0] if isinstance(cost, (list, tuple)) else (cost or {})
        if "flops" in d:
            reg.gauge("dryad_prog_flops",
                      "Compiler flops estimate per program").labels(
                **lbl).set(float(d["flops"]))
        if "bytes accessed" in d:
            reg.gauge("dryad_prog_bytes_accessed",
                      "Compiler bytes-accessed estimate per program").labels(
                **lbl).set(float(d["bytes accessed"]))
        if memory_capture_enabled():
            compiled = lowered.compile()
            ma = compiled.memory_analysis()
            mem = reg.gauge("dryad_prog_memory_bytes",
                            "Compiled-program memory estimate by kind")
            for kind, attr in (("temp", "temp_size_in_bytes"),
                               ("argument", "argument_size_in_bytes"),
                               ("output", "output_size_in_bytes")):
                val = getattr(ma, attr, None)
                if val is not None:
                    mem.labels(kind=kind, **lbl).set(float(val))
        reg.counter("dryad_prog_captures_total",
                    "Successful compile-boundary introspections").labels(
            program=family).inc()
        reg.gauge("dryad_prog_capture_seconds",
                  "Wall of the last introspection per family").labels(
            program=family).set(round(time.perf_counter() - t0, 4))
    except Exception:   # noqa: BLE001 — introspection must never break
        reg.counter("dryad_prog_capture_errors_total",   # the dispatch
                    "Compile-boundary introspections that raised").labels(
            program=family).inc()
    return True
