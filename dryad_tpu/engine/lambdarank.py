"""LambdaMART gradient/hessian on device (SURVEY.md §7 hard part d).

The ragged per-query pairwise λ computation is reshaped for a vector
machine: queries are padded to a fixed document budget ``S`` (the max query
length rounded up), giving a dense (Q, S) layout on which ranks, |ΔNDCG|
weights, and the full S×S pair grid vectorize — then vmapped over queries.
Padding docs carry relevance -1 and participate in no valid pair.

Semantics match ``objectives.LambdaRank.grad_hess_np`` (the canonical host
path): stable sort by -score for ranks, gain 2^rel - 1, log2 discounts,
truncation to pairs touching the top-k, sigmoid-weighted λ with σ scaling.
Host path remains available via ``use_device=False`` and is the parity
oracle in tests.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np


class PaddingPlan:
    """Loop-invariant scatter plan for ragged query groups — build it once
    per dataset (train.py hoists it out of the boosting loop) since it
    depends only on the query offsets."""

    def __init__(self, query_offsets: np.ndarray, pad_multiple: int = 8):
        sizes = np.diff(query_offsets)
        self.Q = int(sizes.size)
        self.S = int(max(8, -(-int(sizes.max()) // pad_multiple) * pad_multiple))
        row_np = np.repeat(np.arange(self.Q, dtype=np.int32), sizes)
        col_np = np.concatenate([np.arange(int(s), dtype=np.int32) for s in sizes])
        self.row_ids = jnp.asarray(row_np)
        self.col_ids = jnp.asarray(col_np)




@partial(jax.jit, static_argnames=("Q", "S", "sigma", "truncation"))
def _lambda_grad_padded(score, rel, row_ids, col_ids, Q, S, sigma, truncation):
    n = score.shape[0]
    big_neg = jnp.float32(-1e30)

    # scatter docs into the (Q, S) padded layout
    s_pad = jnp.full((Q, S), big_neg, jnp.float32).at[row_ids, col_ids].set(score)
    r_pad = jnp.full((Q, S), -1.0, jnp.float32).at[row_ids, col_ids].set(rel)
    present = jnp.zeros((Q, S), bool).at[row_ids, col_ids].set(True)

    def per_query(s, rel_q, pres):
        # ranks: stable descending sort (padding sinks to the bottom)
        order = jnp.argsort(-s, stable=True)
        rank_of = jnp.zeros((S,), jnp.int32).at[order].set(jnp.arange(S, dtype=jnp.int32))
        rel_clip = jnp.maximum(rel_q, 0.0)
        gains = jnp.power(2.0, rel_clip) - 1.0
        discounts = 1.0 / jnp.log2(rank_of.astype(jnp.float32) + 2.0)
        # ideal DCG over the query's own docs (descending relevance)
        rel_sorted = -jnp.sort(-rel_clip * pres)
        ideal_disc = 1.0 / jnp.log2(jnp.arange(S, dtype=jnp.float32) + 2.0)
        max_dcg = jnp.sum((jnp.power(2.0, rel_sorted) - 1.0) * ideal_disc * (rel_sorted >= 0))
        inv_max_dcg = jnp.where(max_dcg > 0, 1.0 / max_dcg, 0.0)

        topk = rank_of < truncation
        rel_diff = rel_q[:, None] - rel_q[None, :]
        valid = (rel_diff > 0) & pres[:, None] & pres[None, :] & (topk[:, None] | topk[None, :])
        sdiff = s[:, None] - s[None, :]
        rho = 1.0 / (1.0 + jnp.exp(sigma * sdiff))
        delta_ndcg = (
            jnp.abs(gains[:, None] - gains[None, :])
            * jnp.abs(discounts[:, None] - discounts[None, :])
            * inv_max_dcg
        )
        lam = jnp.where(valid, sigma * rho * delta_ndcg, 0.0)
        hes = jnp.where(valid, sigma * sigma * rho * (1.0 - rho) * delta_ndcg, 0.0)
        g = -lam.sum(axis=1) + lam.sum(axis=0)
        h = hes.sum(axis=1) + hes.sum(axis=0)
        return g, h

    # batched map: a full vmap would materialize O(Q*S^2) pair tensors
    # (MSLR-scale queries OOM instantly); bound live memory to ~batch*S^2
    batch = max(1, min(Q, (1 << 22) // (S * S)))
    g_pad, h_pad = jax.lax.map(
        lambda args: per_query(*args), (s_pad, r_pad, present), batch_size=batch
    )
    g = g_pad[row_ids, col_ids]
    h = h_pad[row_ids, col_ids]
    return g.astype(jnp.float32), h.astype(jnp.float32)


def grad_hess_ranking(obj, score, y, weight, query_offsets, use_device: bool = True,
                      plan: "PaddingPlan | None" = None):
    """λ-gradients for one boosting iteration; device path with host oracle."""
    if query_offsets is None:
        raise ValueError("lambdarank requires query groups (Dataset(group=...))")
    if use_device:
        if plan is None:
            plan = PaddingPlan(np.asarray(query_offsets))
        g, h = _lambda_grad_padded(
            jnp.asarray(score, jnp.float32), jnp.asarray(y, jnp.float32),
            plan.row_ids, plan.col_ids,
            plan.Q, plan.S, float(obj.sigma), int(obj.truncation),
        )
        if weight is not None:
            w = jnp.asarray(weight)
            g, h = g * w, h * w
        return g, h
    g, h = obj.grad_hess_np(
        np.asarray(score), np.asarray(y),
        None if weight is None else np.asarray(weight),
        query_offsets=np.asarray(query_offsets),
    )
    return jnp.asarray(g), jnp.asarray(h)
