"""Device split-gain scan — TPU equivalent of the reference's CUDA split
kernel (BASELINE.json:5; SURVEY.md §2 #6).

Vectorized over the whole (feature, bin) grid at once: per-feature prefix
sums of the histogram (cumsum), the Newton gain formula on both sides, a
validity mask (min_data_in_leaf / min_child_weight / feature sampling), and
one flat argmax with first-index tie-breaking — semantics identical to
``dryad_tpu.cpu.histogram.find_best_split`` (the parity oracle), modulo fp32
vs f64 accumulation (documented tolerance, SURVEY.md §7 hard part c).

Categorical features use the LightGBM-style sorted-subset scan: bins ordered
by g/(h + smooth), the best prefix of that order becomes the left membership
set, returned as a (B,) bool mask (the host converts it to the node bitset).

Feature-parallel variant (r16, ``Params.hist_reduce="feature"``): under the
reduce-scatter arm each shard owns a contiguous feature slice of the fully
reduced histogram, so the scan factorizes into ``find_best_split_sliced``
(the SAME per-(feature, bin) arithmetic as ``find_best_split``, restricted
to the owned slice, WITHOUT the final ok-gating, plus a packed global tie
key) and ``combine_local_splits`` (argmax-of-argmaxes over the gathered
per-shard records, ok applied once to the global winner).  The tie key is
the fused scan's flattened argmax index itself — ``plane*F*B + f*B + t``
(plane-major, feature-major within a plane) — so max-gain / min-key
combination reproduces the fused first-max order EXACTLY; the 1-shard
"feature" program is the degenerate full slice.  The two scan bodies must
stay arithmetically in sync (the histogram.py twin-bodies precedent);
``test_hist_reduce.py`` pins the contract on seeded equal-gain grids.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp

NEG_INF = float("-inf")  # plain float: a jnp scalar here would init the backend at import
CAT_SMOOTH = 10.0  # matches cpu/histogram.py find_best_split default


class SplitResult(NamedTuple):
    gain: jnp.ndarray       # f32 scalar; -inf when no valid split exists
    feature: jnp.ndarray    # i32
    threshold: jnp.ndarray  # i32: numerical bin id / categorical prefix length
    g_left: jnp.ndarray     # f32
    h_left: jnp.ndarray     # f32
    c_left: jnp.ndarray     # f32
    cat_mask: jnp.ndarray   # (B,) bool — left membership set (cat splits only)
    default_left: jnp.ndarray  # bool — missing (bin 0) goes left at this split


def find_best_split(
    hist: jnp.ndarray,          # (3, F, B) f32
    G: jnp.ndarray,
    H: jnp.ndarray,
    C: jnp.ndarray,
    *,
    lambda_l2: float,
    min_child_weight: float,
    min_data_in_leaf: int,
    min_split_gain: float,
    feat_mask: jnp.ndarray,      # (F,) bool
    is_cat_feat: jnp.ndarray,    # (F,) bool
    allow: jnp.ndarray,          # scalar bool: depth/min-data pre-check
    has_cat: bool = True,        # static: skip the sorted-subset machinery
    monotone: jnp.ndarray | None = None,  # (F,) int32 in {-1, 0, +1}
    lo: jnp.ndarray | None = None,  # scalar f32: node output lower bound
    hi: jnp.ndarray | None = None,  # scalar f32: node output upper bound
    learn_missing: bool = False,    # static: scan missing-left AND missing-right
    bundled_mask: jnp.ndarray | None = None,  # (F,) bool: EFB bundle columns
) -> SplitResult:
    hg, hh, hc = hist[0], hist[1], hist[2]
    F, B = hg.shape
    iota = jnp.arange(B, dtype=jnp.int32)

    if has_cat:
        # categorical scan order: bins sorted by g/(h+smooth); empty bins last
        ratio = jnp.where(hc > 0, hg / (hh + CAT_SMOOTH), jnp.inf)
        cat_order = jnp.argsort(ratio, axis=1, stable=True).astype(jnp.int32)
        order = jnp.where(is_cat_feat[:, None], cat_order, iota[None, :])
        hg_o = jnp.take_along_axis(hg, order, axis=1)
        hh_o = jnp.take_along_axis(hh, order, axis=1)
        hc_o = jnp.take_along_axis(hc, order, axis=1)
    else:
        hg_o, hh_o, hc_o = hg, hh, hc

    GL = jnp.cumsum(hg_o, axis=1)
    HL = jnp.cumsum(hh_o, axis=1)
    CL = jnp.cumsum(hc_o, axis=1)

    def gain_of(GLx, HLx, CLx):
        """Masked gain grid for one scan direction given its left-side sums."""
        GRx, HRx, CRx = G - GLx, H - HLx, C - CLx
        valid = (
            (CLx >= min_data_in_leaf)
            & (CRx >= min_data_in_leaf)
            & (HLx >= min_child_weight)
            & (HRx >= min_child_weight)
            & feat_mask[:, None]
        )
        if monotone is not None:
            # LightGBM-"basic" monotone mode (mirrors cpu/histogram.py):
            # child outputs are clamped to the node's inherited [lo, hi]
            # bounds, the gain is computed with the clamped outputs
            # (objective reduction -(G w + (H+λ)w²/2), which collapses to
            # G²/(2(H+λ)) unclamped), and a ±1 feature may only split where
            # the clamped right value is >=/<= the clamped left value.
            # Descendants inherit tightened bounds from the grower, so deep
            # subtrees cannot cross a constrained ancestor's split —
            # unconstrained (0) features pass regardless of NaN child values.
            lam = jnp.float32(lambda_l2)
            wl = jnp.clip(-GLx / (HLx + lam), lo, hi)
            wr = jnp.clip(-GRx / (HRx + lam), lo, hi)
            wp = jnp.clip(-G / (H + lam), lo, hi)
            mcol = monotone.astype(jnp.float32)[:, None]
            valid &= (mcol == 0) | (mcol * (wr - wl) >= 0)
            red_l = -(GLx * wl + 0.5 * (HLx + lam) * wl * wl)
            red_r = -(GRx * wr + 0.5 * (HRx + lam) * wr * wr)
            red_p = -(G * wp + 0.5 * (H + lam) * wp * wp)
            gain = red_l + red_r - red_p
        else:
            parent_score = G * G / (H + lambda_l2)
            gain = 0.5 * (GLx * GLx / (HLx + lambda_l2)
                          + GRx * GRx / (HRx + lambda_l2) - parent_score)
        return jnp.where(valid, gain, NEG_INF)

    gain = gain_of(GL, HL, CL)
    if learn_missing:
        # second scan with the missing bin (ordered position 0 for numerical
        # features — the identity order keeps bin 0 first) moved to the RIGHT
        # child: left = bins 1..t.  Categorical features learn the missing
        # direction through subset membership already, so only the
        # missing-left plane applies to them.  The missing-left plane comes
        # FIRST in the flattened argmax, so on data with no missing values
        # (bin-0 stats all zero → both planes identical) the tie-break picks
        # missing-left and trees are unchanged.
        g0, h0, c0 = hg_o[:, :1], hh_o[:, :1], hc_o[:, :1]
        CL_r = CL - c0
        gain_r = gain_of(GL - g0, HL - h0, CL_r)
        # a right child holding ONLY missing rows mirrors the plane-0 t=0
        # split (sides swapped, bitwise-equal gain only in exact arithmetic);
        # exclude it so fp noise cannot flip the CPU/TPU argmax between the
        # two representations of the same partition
        gain_r = jnp.where((C - CL_r) > c0, gain_r, NEG_INF)
        if has_cat:
            gain_r = jnp.where(is_cat_feat[:, None], NEG_INF, gain_r)
        if bundled_mask is not None:
            # EFB bundle columns: bin 0 means "all members default", never
            # "missing" — a learned missing-right direction there would be
            # fiction (mirrors cpu/histogram.py exactly)
            gain_r = jnp.where(bundled_mask[:, None], NEG_INF, gain_r)
        flat2 = jnp.argmax(jnp.stack([gain.ravel(), gain_r.ravel()]).ravel())
        flat2 = flat2.astype(jnp.int32)
        dleft = flat2 < F * B
        flat = flat2 % (F * B)
        best_gain = jnp.where(dleft, gain.ravel()[flat], gain_r.ravel()[flat])
    else:
        flat = jnp.argmax(gain.ravel()).astype(jnp.int32)  # first-max tie-break
        dleft = jnp.bool_(True)
        best_gain = gain.ravel()[flat]
    f = flat // B
    t = flat % B
    ok = allow & jnp.isfinite(best_gain) & (best_gain > min_split_gain)

    if has_cat:
        # left membership for categorical: bins whose rank in `order` is <= t
        inv_order = jnp.zeros((B,), jnp.int32).at[order[f]].set(iota)
        cat_mask = (inv_order <= t) & is_cat_feat[f] & ok
    else:
        cat_mask = jnp.zeros((1,), bool)

    g_left, h_left, c_left = GL[f, t], HL[f, t], CL[f, t]
    if learn_missing:
        g_left = jnp.where(dleft, g_left, g_left - hg_o[f, 0])
        h_left = jnp.where(dleft, h_left, h_left - hh_o[f, 0])
        c_left = jnp.where(dleft, c_left, c_left - hc_o[f, 0])

    return SplitResult(
        gain=jnp.where(ok, best_gain, NEG_INF),
        feature=jnp.where(ok, f, -1).astype(jnp.int32),
        threshold=t.astype(jnp.int32),
        g_left=g_left,
        h_left=h_left,
        c_left=c_left,
        cat_mask=cat_mask,
        default_left=dleft | ~ok,
    )


class LocalSplit(NamedTuple):
    """One shard's RAW (pre-ok) winner over its owned feature slice —
    what the feature-parallel combine all-gathers.  ``key`` is the global
    flattened scan index of the winner (plane*F*B + f_global*B + t), so a
    max-gain / min-key reduction over shards reproduces the fused scan's
    first-max tie order bitwise."""

    gain: jnp.ndarray         # f32 raw winner gain (-inf: nothing valid)
    key: jnp.ndarray          # i32 global tie key
    feature: jnp.ndarray      # i32 GLOBAL feature id of the local winner
    threshold: jnp.ndarray    # i32 bin id / categorical prefix length
    g_left: jnp.ndarray       # f32 (plane-adjusted, like the fused scan)
    h_left: jnp.ndarray       # f32
    c_left: jnp.ndarray       # f32
    default_left: jnp.ndarray  # bool — raw plane flag (True: missing left)
    cat_mask: jnp.ndarray     # (B,) raw left membership (pre-ok)


def find_best_split_sliced(
    hist: jnp.ndarray,          # (3, Fs, B) f32 — the OWNED slice, reduced
    G: jnp.ndarray,
    H: jnp.ndarray,
    C: jnp.ndarray,
    *,
    feat_offset: jnp.ndarray,    # traced i32: first owned GLOBAL feature
    num_features_total: int,     # static F (the tie key's plane stride)
    lambda_l2: float,
    min_child_weight: float,
    min_data_in_leaf: int,
    feat_mask: jnp.ndarray,      # (Fs,) bool — sliced (padding False)
    is_cat_feat: jnp.ndarray,    # (Fs,) bool — sliced
    has_cat: bool = True,
    monotone: jnp.ndarray | None = None,   # (Fs,) sliced
    lo: jnp.ndarray | None = None,
    hi: jnp.ndarray | None = None,
    learn_missing: bool = False,
    bundled_mask: jnp.ndarray | None = None,  # (Fs,) sliced
) -> LocalSplit:
    """``find_best_split`` restricted to a feature slice: identical
    per-(feature, bin) gain arithmetic, local first-max argmax, NO
    ok-gating (``combine_local_splits`` applies ok ONCE to the global
    winner, exactly where the fused scan applies it), plus the packed
    global tie key.  KEEP THE TWO BODIES IN SYNC with find_best_split —
    the bitwise fused ≡ feature contract rides on it (the histogram.py
    twin-bodies precedent; pinned by test_hist_reduce.py)."""
    hg, hh, hc = hist[0], hist[1], hist[2]
    F, B = hg.shape
    iota = jnp.arange(B, dtype=jnp.int32)

    if has_cat:
        ratio = jnp.where(hc > 0, hg / (hh + CAT_SMOOTH), jnp.inf)
        cat_order = jnp.argsort(ratio, axis=1, stable=True).astype(jnp.int32)
        order = jnp.where(is_cat_feat[:, None], cat_order, iota[None, :])
        hg_o = jnp.take_along_axis(hg, order, axis=1)
        hh_o = jnp.take_along_axis(hh, order, axis=1)
        hc_o = jnp.take_along_axis(hc, order, axis=1)
    else:
        hg_o, hh_o, hc_o = hg, hh, hc

    GL = jnp.cumsum(hg_o, axis=1)
    HL = jnp.cumsum(hh_o, axis=1)
    CL = jnp.cumsum(hc_o, axis=1)

    def gain_of(GLx, HLx, CLx):
        GRx, HRx, CRx = G - GLx, H - HLx, C - CLx
        valid = (
            (CLx >= min_data_in_leaf)
            & (CRx >= min_data_in_leaf)
            & (HLx >= min_child_weight)
            & (HRx >= min_child_weight)
            & feat_mask[:, None]
        )
        if monotone is not None:
            lam = jnp.float32(lambda_l2)
            wl = jnp.clip(-GLx / (HLx + lam), lo, hi)
            wr = jnp.clip(-GRx / (HRx + lam), lo, hi)
            wp = jnp.clip(-G / (H + lam), lo, hi)
            mcol = monotone.astype(jnp.float32)[:, None]
            valid &= (mcol == 0) | (mcol * (wr - wl) >= 0)
            red_l = -(GLx * wl + 0.5 * (HLx + lam) * wl * wl)
            red_r = -(GRx * wr + 0.5 * (HRx + lam) * wr * wr)
            red_p = -(G * wp + 0.5 * (H + lam) * wp * wp)
            gain = red_l + red_r - red_p
        else:
            parent_score = G * G / (H + lambda_l2)
            gain = 0.5 * (GLx * GLx / (HLx + lambda_l2)
                          + GRx * GRx / (HRx + lambda_l2) - parent_score)
        return jnp.where(valid, gain, NEG_INF)

    gain = gain_of(GL, HL, CL)
    if learn_missing:
        g0, h0, c0 = hg_o[:, :1], hh_o[:, :1], hc_o[:, :1]
        CL_r = CL - c0
        gain_r = gain_of(GL - g0, HL - h0, CL_r)
        gain_r = jnp.where((C - CL_r) > c0, gain_r, NEG_INF)
        if has_cat:
            gain_r = jnp.where(is_cat_feat[:, None], NEG_INF, gain_r)
        if bundled_mask is not None:
            gain_r = jnp.where(bundled_mask[:, None], NEG_INF, gain_r)
        flat2 = jnp.argmax(jnp.stack([gain.ravel(), gain_r.ravel()]).ravel())
        flat2 = flat2.astype(jnp.int32)
        dleft = flat2 < F * B
        flat = flat2 % (F * B)
        best_gain = jnp.where(dleft, gain.ravel()[flat], gain_r.ravel()[flat])
    else:
        flat = jnp.argmax(gain.ravel()).astype(jnp.int32)  # first-max
        dleft = jnp.bool_(True)
        best_gain = gain.ravel()[flat]
    f = flat // B
    t = flat % B

    if has_cat:
        inv_order = jnp.zeros((B,), jnp.int32).at[order[f]].set(iota)
        cat_raw = (inv_order <= t) & is_cat_feat[f]
    else:
        cat_raw = jnp.zeros((1,), bool)

    g_left, h_left, c_left = GL[f, t], HL[f, t], CL[f, t]
    if learn_missing:
        g_left = jnp.where(dleft, g_left, g_left - hg_o[f, 0])
        h_left = jnp.where(dleft, h_left, h_left - hh_o[f, 0])
        c_left = jnp.where(dleft, c_left, c_left - hc_o[f, 0])

    f_global = f + feat_offset.astype(jnp.int32)
    # the GLOBAL flattened argmax index the fused scan would have picked:
    # plane-major (missing-left plane first), feature-major within a plane
    # — min-key over equal-gain shards == the fused first-max tie-break
    span = jnp.int32(num_features_total * B)
    key = (jnp.where(dleft, 0, span) + f_global * B + t).astype(jnp.int32)
    return LocalSplit(
        gain=best_gain,
        key=key,
        feature=f_global.astype(jnp.int32),
        threshold=t.astype(jnp.int32),
        g_left=g_left,
        h_left=h_left,
        c_left=c_left,
        default_left=dleft,
        cat_mask=cat_raw,
    )


_I32_MAX = 2**31 - 1

#: packed LocalSplit word layout (pack_local_split / combine_local_splits):
#: gain, key, feature, threshold, g_left, h_left, c_left, default_left
LOCAL_SPLIT_WORDS = 8


def pack_local_split(rec: LocalSplit) -> jnp.ndarray:
    """LocalSplit scalars -> one (..., 8) uint32 word block, so a whole
    level's combine pays ONE record all-gather (plus the categorical rows
    when present) instead of one per field.  Bitcasts are lossless — the
    combine's unpacked fields are bitwise the scan's."""
    import jax

    def fbits(x):
        return jax.lax.bitcast_convert_type(x.astype(jnp.float32),
                                            jnp.uint32)

    return jnp.stack([
        fbits(rec.gain),
        rec.key.astype(jnp.uint32),
        rec.feature.astype(jnp.uint32),      # raw winner ids are >= 0
        rec.threshold.astype(jnp.uint32),
        fbits(rec.g_left),
        fbits(rec.h_left),
        fbits(rec.c_left),
        rec.default_left.astype(jnp.uint32),
    ], axis=-1)


def combine_local_splits(words: jnp.ndarray, cat_rows, *, allow,
                         min_split_gain: float, has_cat: bool) -> SplitResult:
    """Argmax-of-argmaxes over gathered per-shard records -> SplitResult.

    ``words`` is the gathered ``pack_local_split`` block with a leading
    shard axis — (n, 8) scalar records or (n, C, 8) vmapped batches;
    ``cat_rows`` the gathered raw (n, ..., B) categorical membership rows
    (None when the config has no categorical features).  Winner = max
    gain, ties to the MINIMUM tie key, which is the fused scan's own
    flattened index — so on a degenerate 1-shard gather this IS the fused
    selection, and on n shards equal-gain candidates resolve in the
    identical plane-major / feature-major order.  The ok-gating (allow,
    finiteness, min_split_gain) runs HERE, once, on the global winner —
    gating per-shard first would let a lower-gain shard win after a
    higher-gain winner failed min_split_gain, which the fused scan never
    does."""
    import jax

    gains = jax.lax.bitcast_convert_type(words[..., 0], jnp.float32)
    keys = words[..., 1].astype(jnp.int32)
    best_gain = jnp.max(gains, axis=0)
    tie = jnp.where(gains == best_gain[None], keys, jnp.int32(_I32_MAX))
    win = jnp.argmin(tie, axis=0).astype(jnp.int32)

    def pick(x):
        idx = win.reshape((1,) + win.shape + (1,) * (x.ndim - 1 - win.ndim))
        return jnp.take_along_axis(
            x, jnp.broadcast_to(idx, (1,) + x.shape[1:]), axis=0)[0]

    w = pick(words)                               # (..., 8) winner block
    f = w[..., 2].astype(jnp.int32)
    t = w[..., 3].astype(jnp.int32)
    g_left = jax.lax.bitcast_convert_type(w[..., 4], jnp.float32)
    h_left = jax.lax.bitcast_convert_type(w[..., 5], jnp.float32)
    c_left = jax.lax.bitcast_convert_type(w[..., 6], jnp.float32)
    dleft = w[..., 7] != 0

    ok = allow & jnp.isfinite(best_gain) & (best_gain > min_split_gain)
    if has_cat and cat_rows is not None:
        cat_mask = pick(cat_rows) & ok[..., None]
    else:
        # the fused scan's no-cat placeholder shape: (..., 1) False
        cat_mask = jnp.zeros(win.shape + (1,), bool)
    return SplitResult(
        gain=jnp.where(ok, best_gain, NEG_INF),
        feature=jnp.where(ok, f, -1).astype(jnp.int32),
        threshold=t.astype(jnp.int32),
        g_left=g_left,
        h_left=h_left,
        c_left=c_left,
        cat_mask=cat_mask,
        default_left=dleft | ~ok,
    )
