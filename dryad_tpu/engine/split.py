"""Device split-gain scan — TPU equivalent of the reference's CUDA split
kernel (BASELINE.json:5; SURVEY.md §2 #6).

Vectorized over the whole (feature, bin) grid at once: per-feature prefix
sums of the histogram (cumsum), the Newton gain formula on both sides, a
validity mask (min_data_in_leaf / min_child_weight / feature sampling), and
one flat argmax with first-index tie-breaking — semantics identical to
``dryad_tpu.cpu.histogram.find_best_split`` (the parity oracle), modulo fp32
vs f64 accumulation (documented tolerance, SURVEY.md §7 hard part c).

Categorical features use the LightGBM-style sorted-subset scan: bins ordered
by g/(h + smooth), the best prefix of that order becomes the left membership
set, returned as a (B,) bool mask (the host converts it to the node bitset).
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp

NEG_INF = float("-inf")  # plain float: a jnp scalar here would init the backend at import
CAT_SMOOTH = 10.0  # matches cpu/histogram.py find_best_split default


class SplitResult(NamedTuple):
    gain: jnp.ndarray       # f32 scalar; -inf when no valid split exists
    feature: jnp.ndarray    # i32
    threshold: jnp.ndarray  # i32: numerical bin id / categorical prefix length
    g_left: jnp.ndarray     # f32
    h_left: jnp.ndarray     # f32
    c_left: jnp.ndarray     # f32
    cat_mask: jnp.ndarray   # (B,) bool — left membership set (cat splits only)
    default_left: jnp.ndarray  # bool — missing (bin 0) goes left at this split


def find_best_split(
    hist: jnp.ndarray,          # (3, F, B) f32
    G: jnp.ndarray,
    H: jnp.ndarray,
    C: jnp.ndarray,
    *,
    lambda_l2: float,
    min_child_weight: float,
    min_data_in_leaf: int,
    min_split_gain: float,
    feat_mask: jnp.ndarray,      # (F,) bool
    is_cat_feat: jnp.ndarray,    # (F,) bool
    allow: jnp.ndarray,          # scalar bool: depth/min-data pre-check
    has_cat: bool = True,        # static: skip the sorted-subset machinery
    monotone: jnp.ndarray | None = None,  # (F,) int32 in {-1, 0, +1}
    lo: jnp.ndarray | None = None,  # scalar f32: node output lower bound
    hi: jnp.ndarray | None = None,  # scalar f32: node output upper bound
    learn_missing: bool = False,    # static: scan missing-left AND missing-right
    bundled_mask: jnp.ndarray | None = None,  # (F,) bool: EFB bundle columns
) -> SplitResult:
    hg, hh, hc = hist[0], hist[1], hist[2]
    F, B = hg.shape
    iota = jnp.arange(B, dtype=jnp.int32)

    if has_cat:
        # categorical scan order: bins sorted by g/(h+smooth); empty bins last
        ratio = jnp.where(hc > 0, hg / (hh + CAT_SMOOTH), jnp.inf)
        cat_order = jnp.argsort(ratio, axis=1, stable=True).astype(jnp.int32)
        order = jnp.where(is_cat_feat[:, None], cat_order, iota[None, :])
        hg_o = jnp.take_along_axis(hg, order, axis=1)
        hh_o = jnp.take_along_axis(hh, order, axis=1)
        hc_o = jnp.take_along_axis(hc, order, axis=1)
    else:
        hg_o, hh_o, hc_o = hg, hh, hc

    GL = jnp.cumsum(hg_o, axis=1)
    HL = jnp.cumsum(hh_o, axis=1)
    CL = jnp.cumsum(hc_o, axis=1)

    def gain_of(GLx, HLx, CLx):
        """Masked gain grid for one scan direction given its left-side sums."""
        GRx, HRx, CRx = G - GLx, H - HLx, C - CLx
        valid = (
            (CLx >= min_data_in_leaf)
            & (CRx >= min_data_in_leaf)
            & (HLx >= min_child_weight)
            & (HRx >= min_child_weight)
            & feat_mask[:, None]
        )
        if monotone is not None:
            # LightGBM-"basic" monotone mode (mirrors cpu/histogram.py):
            # child outputs are clamped to the node's inherited [lo, hi]
            # bounds, the gain is computed with the clamped outputs
            # (objective reduction -(G w + (H+λ)w²/2), which collapses to
            # G²/(2(H+λ)) unclamped), and a ±1 feature may only split where
            # the clamped right value is >=/<= the clamped left value.
            # Descendants inherit tightened bounds from the grower, so deep
            # subtrees cannot cross a constrained ancestor's split —
            # unconstrained (0) features pass regardless of NaN child values.
            lam = jnp.float32(lambda_l2)
            wl = jnp.clip(-GLx / (HLx + lam), lo, hi)
            wr = jnp.clip(-GRx / (HRx + lam), lo, hi)
            wp = jnp.clip(-G / (H + lam), lo, hi)
            mcol = monotone.astype(jnp.float32)[:, None]
            valid &= (mcol == 0) | (mcol * (wr - wl) >= 0)
            red_l = -(GLx * wl + 0.5 * (HLx + lam) * wl * wl)
            red_r = -(GRx * wr + 0.5 * (HRx + lam) * wr * wr)
            red_p = -(G * wp + 0.5 * (H + lam) * wp * wp)
            gain = red_l + red_r - red_p
        else:
            parent_score = G * G / (H + lambda_l2)
            gain = 0.5 * (GLx * GLx / (HLx + lambda_l2)
                          + GRx * GRx / (HRx + lambda_l2) - parent_score)
        return jnp.where(valid, gain, NEG_INF)

    gain = gain_of(GL, HL, CL)
    if learn_missing:
        # second scan with the missing bin (ordered position 0 for numerical
        # features — the identity order keeps bin 0 first) moved to the RIGHT
        # child: left = bins 1..t.  Categorical features learn the missing
        # direction through subset membership already, so only the
        # missing-left plane applies to them.  The missing-left plane comes
        # FIRST in the flattened argmax, so on data with no missing values
        # (bin-0 stats all zero → both planes identical) the tie-break picks
        # missing-left and trees are unchanged.
        g0, h0, c0 = hg_o[:, :1], hh_o[:, :1], hc_o[:, :1]
        CL_r = CL - c0
        gain_r = gain_of(GL - g0, HL - h0, CL_r)
        # a right child holding ONLY missing rows mirrors the plane-0 t=0
        # split (sides swapped, bitwise-equal gain only in exact arithmetic);
        # exclude it so fp noise cannot flip the CPU/TPU argmax between the
        # two representations of the same partition
        gain_r = jnp.where((C - CL_r) > c0, gain_r, NEG_INF)
        if has_cat:
            gain_r = jnp.where(is_cat_feat[:, None], NEG_INF, gain_r)
        if bundled_mask is not None:
            # EFB bundle columns: bin 0 means "all members default", never
            # "missing" — a learned missing-right direction there would be
            # fiction (mirrors cpu/histogram.py exactly)
            gain_r = jnp.where(bundled_mask[:, None], NEG_INF, gain_r)
        flat2 = jnp.argmax(jnp.stack([gain.ravel(), gain_r.ravel()]).ravel())
        flat2 = flat2.astype(jnp.int32)
        dleft = flat2 < F * B
        flat = flat2 % (F * B)
        best_gain = jnp.where(dleft, gain.ravel()[flat], gain_r.ravel()[flat])
    else:
        flat = jnp.argmax(gain.ravel()).astype(jnp.int32)  # first-max tie-break
        dleft = jnp.bool_(True)
        best_gain = gain.ravel()[flat]
    f = flat // B
    t = flat % B
    ok = allow & jnp.isfinite(best_gain) & (best_gain > min_split_gain)

    if has_cat:
        # left membership for categorical: bins whose rank in `order` is <= t
        inv_order = jnp.zeros((B,), jnp.int32).at[order[f]].set(iota)
        cat_mask = (inv_order <= t) & is_cat_feat[f] & ok
    else:
        cat_mask = jnp.zeros((1,), bool)

    g_left, h_left, c_left = GL[f, t], HL[f, t], CL[f, t]
    if learn_missing:
        g_left = jnp.where(dleft, g_left, g_left - hg_o[f, 0])
        h_left = jnp.where(dleft, h_left, h_left - hh_o[f, 0])
        c_left = jnp.where(dleft, c_left, c_left - hc_o[f, 0])

    return SplitResult(
        gain=jnp.where(ok, best_gain, NEG_INF),
        feature=jnp.where(ok, f, -1).astype(jnp.int32),
        threshold=t.astype(jnp.int32),
        g_left=g_left,
        h_left=h_left,
        c_left=c_left,
        cat_mask=cat_mask,
        default_left=dleft | ~ok,
    )
