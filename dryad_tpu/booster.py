"""Booster: the trained model as structure-of-arrays tree tables.

Trees live in flat, preallocated SoA arrays (SURVEY.md §2 #12) so the same
representation feeds the vectorized CPU predict, the jit TPU predict, and
checkpointing without conversion:

* ``feature[t, n]``    int32   split feature, or -1 when node n is a leaf
* ``threshold[t, n]``  int32   split threshold *bin id*; rows with
                               ``bin <= threshold`` go left (numerical)
* ``is_cat[t, n]``     bool    categorical split: membership test instead
* ``cat_bitset[t,n,w]``uint32  bins in the left subset (categorical splits)
* ``left/right[t, n]`` int32   child node ids
* ``value[t, n]``      float32 leaf delta (learning-rate already applied)

Node 0 is the root.  Traversal compares *bin ids* (integers), so the CPU and
TPU predict paths are bit-identical by construction; the float work — summing
leaf deltas across trees — runs in the same fixed tree order and fp32 on both
backends (BASELINE.json:5 bit-identity contract).

Multiclass stores K trees per boosting iteration, ordered
``iteration * K + class``.
"""

from __future__ import annotations

import io
import json
from typing import Optional

import numpy as np

from dryad_tpu.config import Params
from dryad_tpu.data.sketch import BinMapper

CAT_WORDS = 8  # bitset words per node: supports max_bins <= 256 categorical splits


def _profile_from_dict(d):
    """Optional reference-profile section -> object (None passes through:
    models saved before r18 carry no profile and must keep loading)."""
    if not d:
        return None
    from dryad_tpu.data.profile import ReferenceProfile

    return ReferenceProfile.from_json_dict(d)


class Booster:
    def __init__(
        self,
        params: Params,
        mapper: BinMapper,
        feature: np.ndarray,
        threshold: np.ndarray,
        left: np.ndarray,
        right: np.ndarray,
        value: np.ndarray,
        is_cat: np.ndarray,
        cat_bitset: np.ndarray,
        init_score: np.ndarray,
        max_depth_seen: int,
        best_iteration: int = -1,
        gain: Optional[np.ndarray] = None,
        train_state: Optional[dict] = None,
        default_left: Optional[np.ndarray] = None,
        cover: Optional[np.ndarray] = None,
        profile=None,
    ):
        self.params = params
        self.mapper = mapper
        self.feature = feature
        self.threshold = threshold
        self.left = left
        self.right = right
        self.value = value
        self.is_cat = is_cat
        self.cat_bitset = cat_bitset
        self.init_score = np.asarray(init_score, np.float32).reshape(-1)  # (K,) or (1,)
        self.max_depth_seen = int(max_depth_seen)
        self.best_iteration = int(best_iteration)
        # per-node split gain (0 at leaves); optional for old checkpoints
        self.gain = (np.zeros_like(value) if gain is None
                     else np.asarray(gain, np.float32))
        # per-node training row count ("cover") — feeds exact TreeSHAP
        # (pred_contrib); optional for models saved before round 4
        self.cover = (np.zeros_like(value) if cover is None
                      else np.asarray(cover, np.float32))
        # per-node learned missing direction (numerical splits; True = bin 0
        # goes left).  Old models default to all-True — the historic rule.
        self.default_left = (np.ones(feature.shape, bool) if default_left is None
                             else np.asarray(default_left, bool))
        # loop state a resumed run needs to continue exactly (early stopping)
        self.train_state = dict(train_state or {})
        # train-time reference profile (data/profile.py) — the drift
        # baseline the serving layer monitors against; None for models
        # saved before round 18 (back-compat pinned) and for trainers
        # invoked below the dryad.train wrapper
        self.profile = profile

    # ---- shape helpers -----------------------------------------------------
    @property
    def num_total_trees(self) -> int:
        return int(self.feature.shape[0])

    @property
    def num_outputs(self) -> int:
        return self.params.num_outputs

    @property
    def num_iterations(self) -> int:
        return self.num_total_trees // self.num_outputs

    @property
    def has_categorical_splits(self) -> bool:
        """True when ANY tree holds a categorical split.  Device staging
        (engine/predict.stage_trees) uses the per-slice equivalent to drop
        the ``cat_bitset`` table from numeric programs — dict-key presence
        is static under jit, so the bitset gather disappears from the
        traced traversal entirely rather than being masked at runtime."""
        return bool(self.is_cat.any())

    def tree_arrays(self) -> dict[str, np.ndarray]:
        return {
            "feature": self.feature,
            "threshold": self.threshold,
            "left": self.left,
            "right": self.right,
            "value": self.value,
            "is_cat": self.is_cat,
            "cat_bitset": self.cat_bitset,
            "gain": self.gain,
            "default_left": self.default_left,
            "cover": self.cover,
        }

    # ---- predict -----------------------------------------------------------
    def predict(
        self,
        X: np.ndarray,
        *,
        raw_score: bool = False,
        backend: str = "cpu",
        num_iteration: Optional[int] = None,
        pred_leaf: bool = False,
        pred_contrib: bool = False,
        sharded: bool = False,
    ) -> np.ndarray:
        """Predict on raw features: bin through the frozen mapper, traverse."""
        X_binned = self.mapper.transform(np.asarray(X, np.float32))
        return self.predict_binned(
            X_binned, raw_score=raw_score, backend=backend,
            num_iteration=num_iteration, pred_leaf=pred_leaf,
            pred_contrib=pred_contrib, sharded=sharded,
        )

    def predict_binned(
        self,
        X_binned: np.ndarray,
        *,
        raw_score: bool = False,
        backend: str = "cpu",
        num_iteration: Optional[int] = None,
        pred_leaf: bool = False,
        pred_contrib: bool = False,
        sharded: bool = False,
    ) -> np.ndarray:
        if sharded and backend != "tpu":
            # silent fallback to the single-host numpy path would make a
            # sharded benchmark measure the wrong thing entirely
            raise ValueError("sharded=True requires backend='tpu'")
        if sharded and (pred_leaf or pred_contrib):
            # these run single-host CPU loops regardless of backend —
            # same silent-fallback hazard as above
            raise ValueError(
                "sharded=True is not supported with pred_leaf/pred_contrib")
        if pred_contrib:
            # exact TreeSHAP on the recorded per-node covers -> (N, F+1)
            # per output (last column = bias); contributions sum to the raw
            # prediction exactly (cpu/shap.py)
            from dryad_tpu.cpu.shap import predict_contrib

            return predict_contrib(self, X_binned,
                                   num_iteration=num_iteration)
        if pred_leaf:
            from dryad_tpu.cpu.predict import predict_tree_leaves

            if num_iteration is not None:
                n_iter = num_iteration
            elif self.best_iteration > 0:   # early-stopping semantics, as scores
                n_iter = self.best_iteration
            else:
                n_iter = self.num_iterations
            T = min(n_iter * self.num_outputs, self.num_total_trees)
            ta = self.tree_arrays()
            out = np.empty((X_binned.shape[0], T), np.int32)
            for t in range(T):
                out[:, t] = predict_tree_leaves(ta, X_binned, t,
                                                max(self.max_depth_seen, 1))
            return out
        if backend == "cpu":
            from dryad_tpu.cpu.predict import predict_binned_cpu

            raw = predict_binned_cpu(self, X_binned, num_iteration=num_iteration)
        elif backend == "tpu":
            if sharded:
                # rows sharded over the whole mesh, trees replicated —
                # bitwise equal to the single-device program (per-row
                # arithmetic; test_serve_sharded.py pins it)
                from dryad_tpu.engine.predict import predict_binned_sharded

                raw = np.asarray(predict_binned_sharded(
                    self, X_binned, num_iteration=num_iteration))
            else:
                from dryad_tpu.engine.predict import predict_binned_device

                raw = np.asarray(predict_binned_device(self, X_binned, num_iteration=num_iteration))
        else:
            raise ValueError(f"unknown backend {backend!r}")
        return self.transform_raw(raw, raw_score=raw_score)

    def transform_raw(self, raw: np.ndarray, *, raw_score: bool = False) -> np.ndarray:
        """Final output shaping shared by every predict path: (N, K) raw
        scores → the objective's link transform (or raw), squeezing the
        single-output column.  The serving layer applies this to slices of
        a coalesced batch; every transform is per-row, so slice-then-
        transform is bitwise equal to transform-then-slice."""
        if raw_score:
            return raw if self.num_outputs > 1 else raw[:, 0]
        from dryad_tpu.objectives import get_objective

        out = get_objective(self.params).transform_np(raw)
        return out if self.num_outputs > 1 else out[:, 0] if out.ndim == 2 else out

    # ---- refit -------------------------------------------------------------
    def refit(self, X: np.ndarray, y: np.ndarray, *,
              weight: Optional[np.ndarray] = None,
              decay_rate: float = 0.9) -> "Booster":
        """LightGBM-style refit: keep every tree's STRUCTURE, re-derive the
        leaf values from new data (model adaptation without regrowth).

        Walking trees in training order with scores accumulated as in
        training, each leaf gets ``decay_rate * old + (1 - decay_rate) *
        new``, where ``new`` is the Newton value -G/(H+λ) · shrinkage from
        the new data's grad/hess at the current refit scores (LightGBM's
        ``Booster.refit`` semantics).  L1-family objectives take the
        residual-percentile renewal instead of Newton (the same
        objectives.renew_alpha convention training uses).  Leaves that
        receive no new rows keep their old value.  ``X`` is binned through
        the model's OWN frozen mapper.  DART models are rejected (their
        value table mixes rescale generations — no per-tree gradient
        step to refit).  Returns a new Booster; eval/early-stop state and
        best_iteration are cleared (they describe the old fit).  Monotone
        constraints are NOT re-enforced: the split structure remains the
        monotone-chosen one, but refitted leaf values come from new-data
        statistics without the grower's bound clamping (documented
        divergence — the training-time bounds are not stored on the
        model).
        """
        from dryad_tpu.cpu.predict import predict_tree_leaves
        from dryad_tpu.objectives import get_objective
        from dryad_tpu.objectives import renew_alpha as _renew_alpha

        p = self.params
        if p.boosting == "dart":
            raise ValueError("refit is unsupported for DART models: the "
                             "value table mixes drop-rescale generations")
        if p.objective == "lambdarank":
            raise ValueError("refit is unsupported for lambdarank models: "
                             "per-query lambda gradients need query "
                             "groups, which refit does not take")
        if not (0.0 <= decay_rate <= 1.0):
            raise ValueError("decay_rate must be in [0, 1]")
        K = self.num_outputs
        Xb = self.mapper.transform(np.asarray(X, np.float32))
        y = np.asarray(y, np.float32)
        w = None if weight is None else np.asarray(weight, np.float32)
        obj = get_objective(p)
        N = Xb.shape[0]
        T = self.num_total_trees
        trees = self.tree_arrays()
        value = self.value.copy()
        lam = np.float32(p.lambda_l2)
        lr = np.float32(p.effective_learning_rate)
        decay = np.float32(decay_rate)
        renew_a = _renew_alpha(p, weighted=w is not None)
        score = np.broadcast_to(self.init_score, (N, K)).astype(np.float32).copy()
        score0 = score.copy()           # rf: gradients at the constant init
        g = h = None
        depth = max(self.max_depth_seen, 1)

        def _gh(sc):
            if K > 1:
                return obj.grad_hess_np(sc, y, w)
            g1, h1 = obj.grad_hess_np(sc[:, 0], y, w)
            return g1[:, None], h1[:, None]

        rf_gh = _gh(score0) if p.boosting == "rf" else None
        for t in range(T):
            k = t % K
            if k == 0:
                # rf gradients are constant (trainer parity) — one pass
                g, h = rf_gh if rf_gh is not None else _gh(score)
            lv = predict_tree_leaves(trees, Xb, t, depth)
            leaf_nodes = np.unique(lv)
            for node in leaf_nodes:
                m = lv == node
                if renew_a is not None:
                    from dryad_tpu.cpu.trainer import type1_quantile

                    rs = np.sort((y[m] - score[m, k]).astype(np.float32))
                    new_v = type1_quantile(rs, renew_a) * lr
                else:
                    G = np.float32(g[m, k].sum(dtype=np.float64))
                    H = np.float32(h[m, k].sum(dtype=np.float64))
                    if H + lam == 0.0:
                        # zero-hessian leaf (lambda_l2=0 + saturated
                        # scores): no Newton information — keep the old
                        # value rather than blending ±inf/NaN in
                        continue
                    new_v = np.float32(-(G / (H + lam))) * lr
                value[t, node] = (decay * value[t, node]
                                  + (np.float32(1.0) - decay) * new_v)
            score[:, k] += value[t, lv]
        return Booster(
            p, self.mapper, self.feature, self.threshold, self.left,
            self.right, value, self.is_cat, self.cat_bitset,
            self.init_score, self.max_depth_seen, best_iteration=-1,
            gain=self.gain, default_left=self.default_left,
            cover=self.cover,
        )

    # ---- serialization -----------------------------------------------------
    def save(self, path: str) -> None:
        with open(path, "wb") as f:
            f.write(self.to_bytes())

    def to_bytes(self) -> bytes:
        buf = io.BytesIO()
        np.savez_compressed(
            buf,
            feature=self.feature,
            threshold=self.threshold,
            left=self.left,
            right=self.right,
            value=self.value,
            is_cat=self.is_cat,
            cat_bitset=self.cat_bitset,
            gain=self.gain,
            cover=self.cover,
            default_left=self.default_left,
            init_score=self.init_score,
            meta=np.frombuffer(
                json.dumps(
                    {
                        "params": self.params.to_dict(),
                        "max_depth_seen": self.max_depth_seen,
                        "best_iteration": self.best_iteration,
                        "train_state": self.train_state,
                        "format_version": 1,
                        # optional (r18): the drift baseline; absent keys
                        # keep old readers loading new files and vice versa
                        **({"profile": self.profile.to_json_dict()}
                           if self.profile is not None else {}),
                    }
                ).encode(),
                dtype=np.uint8,
            ),
            mapper=np.frombuffer(self.mapper.to_bytes(), dtype=np.uint8),
        )
        return buf.getvalue()

    @classmethod
    def load(cls, path: str) -> "Booster":
        with open(path, "rb") as f:
            return cls.from_bytes(f.read())

    # ---- versioned TEXT format (interop + inspection) ----------------------
    TEXT_FORMAT_VERSION = 1

    def dump_text(self) -> str:
        """Versioned, human-readable JSON text dump of the FULL model —
        params, the frozen bin mapper (edges / categorical vocab / bundle
        plan), and every tree's node arrays incl. categorical bitsets,
        per-node covers, gains and learned missing directions — such that
        ``Booster.from_text(dump_text())`` predicts BIT-IDENTICALLY
        (test_model_text.py).  Floats serialize through Python float (an
        exact f64 widening of the stored f32), which json round-trips
        exactly; ±inf appears as JSON ``Infinity`` (Python's json default,
        documented deviation from strict JSON).  Categorical bitsets are
        stored sparsely as {node: [8 uint32 words]} for nodes with any
        set bit.

        train_state (eval history, early-stop staleness) is deliberately
        NOT serialized: the text format is the interop/inspection
        surface; resuming training mid-stream is the binary
        ``save``/``load`` (and checkpoint.py) contract.  A text-reloaded
        booster predicts identically but, used as ``init_booster``,
        continues with fresh early-stop state."""
        trees = []
        for t in range(self.num_total_trees):
            cat_rows = {}
            nz = np.flatnonzero(self.cat_bitset[t].any(axis=1))
            for n in nz:
                cat_rows[str(int(n))] = [int(w) for w in self.cat_bitset[t, n]]
            trees.append({
                "feature": [int(v) for v in self.feature[t]],
                "threshold": [int(v) for v in self.threshold[t]],
                "left": [int(v) for v in self.left[t]],
                "right": [int(v) for v in self.right[t]],
                "value": [float(v) for v in self.value[t]],
                "is_cat": [int(v) for v in self.is_cat[t]],
                "default_left": [int(v) for v in self.default_left[t]],
                "gain": [float(v) for v in self.gain[t]],
                "cover": [float(v) for v in self.cover[t]],
                "cat_bitset": cat_rows,
            })
        doc = {
            "format": "dryad-text",
            "format_version": self.TEXT_FORMAT_VERSION,
            "params": self.params.to_dict(),
            "init_score": [float(v) for v in self.init_score],
            "max_depth_seen": self.max_depth_seen,
            "best_iteration": self.best_iteration,
            "cat_words": int(self.cat_bitset.shape[2]),
            "max_nodes": int(self.feature.shape[1]),
            "mapper": self.mapper.to_json_dict(),
            "trees": trees,
        }
        if self.profile is not None:
            # optional r18 section: integer bin counts + score-histogram
            # states (data/profile.py) — json round-trips them exactly,
            # and readers that predate the key simply never look at it
            doc["profile"] = self.profile.to_json_dict()
        return json.dumps(doc, indent=1)

    def save_text(self, path: str) -> None:
        with open(path, "w") as f:
            f.write(self.dump_text())

    @classmethod
    def from_text(cls, text: str) -> "Booster":
        doc = json.loads(text)
        if doc.get("format") != "dryad-text":
            raise ValueError("not a dryad text model dump")
        if doc["format_version"] > cls.TEXT_FORMAT_VERSION:
            raise ValueError(
                f"text format version {doc['format_version']} is newer than "
                f"this library supports ({cls.TEXT_FORMAT_VERSION})")
        params = Params.from_dict(doc["params"])
        md = doc["mapper"]
        if md["type"] == "bundled":
            from dryad_tpu.data.bundling import BundledMapper

            mapper = BundledMapper.from_json_dict(md)
        else:
            mapper = BinMapper.from_json_dict(md)
        T, M = len(doc["trees"]), int(doc["max_nodes"])
        W = int(doc["cat_words"])
        feature = np.empty((T, M), np.int32)
        threshold = np.empty((T, M), np.int32)
        left = np.empty((T, M), np.int32)
        right = np.empty((T, M), np.int32)
        value = np.empty((T, M), np.float32)
        is_cat = np.empty((T, M), bool)
        default_left = np.empty((T, M), bool)
        gain = np.empty((T, M), np.float32)
        cover = np.empty((T, M), np.float32)
        cat_bitset = np.zeros((T, M, W), np.uint32)
        for t, tr in enumerate(doc["trees"]):
            feature[t] = tr["feature"]
            threshold[t] = tr["threshold"]
            left[t] = tr["left"]
            right[t] = tr["right"]
            value[t] = np.asarray(tr["value"], np.float32)
            is_cat[t] = np.asarray(tr["is_cat"], bool)
            default_left[t] = np.asarray(tr["default_left"], bool)
            gain[t] = np.asarray(tr["gain"], np.float32)
            cover[t] = np.asarray(tr["cover"], np.float32)
            for n_str, words in tr["cat_bitset"].items():
                cat_bitset[t, int(n_str)] = np.asarray(words, np.uint32)
        return cls(
            params, mapper, feature, threshold, left, right, value,
            is_cat, cat_bitset, np.asarray(doc["init_score"], np.float32),
            int(doc["max_depth_seen"]), int(doc.get("best_iteration", -1)),
            gain=gain, cover=cover, default_left=default_left,
            profile=_profile_from_dict(doc.get("profile")),
        )

    @classmethod
    def load_text(cls, path: str) -> "Booster":
        with open(path) as f:
            return cls.from_text(f.read())

    @classmethod
    def load_any(cls, path: str) -> "Booster":
        """Load a model from either on-disk format, sniffing the content:
        the binary ``save`` format is an npz (a zip — magic ``PK``),
        anything else is parsed as the versioned text dump."""
        with open(path, "rb") as f:
            magic = f.read(2)
        if magic == b"PK":
            return cls.load(path)
        return cls.load_text(path)

    @classmethod
    def from_bytes(cls, data: bytes) -> "Booster":
        with np.load(io.BytesIO(data)) as z:
            meta = json.loads(bytes(z["meta"]).decode())
            params = Params.from_dict(meta["params"])
            mapper = BinMapper.from_bytes(bytes(z["mapper"]))
            return cls(
                params,
                mapper,
                z["feature"],
                z["threshold"],
                z["left"],
                z["right"],
                z["value"],
                z["is_cat"],
                z["cat_bitset"],
                z["init_score"],
                meta["max_depth_seen"],
                meta.get("best_iteration", -1),
                gain=z["gain"] if "gain" in z.files else None,
                cover=z["cover"] if "cover" in z.files else None,
                train_state=meta.get("train_state"),
                default_left=z["default_left"] if "default_left" in z.files else None,
                profile=_profile_from_dict(meta.get("profile")),
            )

    # ---- introspection -----------------------------------------------------
    def feature_importance(self, importance_type: str = "split") -> np.ndarray:
        """Per-feature importance.

        'split': number of times each feature is used as a split.
        'gain':  total split gain accumulated by each feature.
        """
        F = self.mapper.num_features
        internal = self.feature >= 0
        used = self.feature[internal]
        if importance_type == "split":
            return np.bincount(used, minlength=F).astype(np.int64)
        if importance_type == "gain":
            return np.bincount(
                used, weights=self.gain[internal].astype(np.float64), minlength=F
            )
        raise ValueError("importance_type must be 'split' or 'gain'")

    def dump_model(self) -> dict:
        """Structured model dump (JSON-serializable), one dict per tree."""
        trees = []
        for t in range(self.num_total_trees):
            nodes = []
            n_nodes = int((self.feature[t] >= 0).sum()) * 2 + 1
            for n in range(n_nodes):
                f = int(self.feature[t, n])
                if f >= 0:
                    nodes.append({
                        "node": n,
                        "split_feature": f,
                        "threshold_bin": int(self.threshold[t, n]),
                        "is_categorical": bool(self.is_cat[t, n]),
                        "default_left": bool(self.default_left[t, n]),
                        "gain": float(self.gain[t, n]),
                        "left": int(self.left[t, n]),
                        "right": int(self.right[t, n]),
                    })
                else:
                    nodes.append({"node": n, "value": float(self.value[t, n])})
            trees.append({
                "tree_index": t,
                "class": t % self.num_outputs,
                "nodes": nodes,
            })
        return {
            "num_iterations": self.num_iterations,
            "num_class": self.num_outputs,
            "init_score": [float(v) for v in self.init_score],
            "params": self.params.to_dict(),
            "trees": trees,
        }


def empty_tree_arrays(num_total_trees: int, max_nodes: int) -> dict[str, np.ndarray]:
    return {
        "feature": np.full((num_total_trees, max_nodes), -1, np.int32),
        "threshold": np.zeros((num_total_trees, max_nodes), np.int32),
        "left": np.zeros((num_total_trees, max_nodes), np.int32),
        "right": np.zeros((num_total_trees, max_nodes), np.int32),
        "value": np.zeros((num_total_trees, max_nodes), np.float32),
        "is_cat": np.zeros((num_total_trees, max_nodes), bool),
        "cat_bitset": np.zeros((num_total_trees, max_nodes, CAT_WORDS), np.uint32),
        "gain": np.zeros((num_total_trees, max_nodes), np.float32),
        "default_left": np.ones((num_total_trees, max_nodes), bool),
        "cover": np.zeros((num_total_trees, max_nodes), np.float32),
    }
