"""Fault classification + deterministic injection for supervised runs.

The classes named here are the failure modes actually RECORDED against the
tunneled device (STATUS.md r5 "Infrastructure note"), not a hypothetical
taxonomy:

* **fetch_death** — a device->host fetch pending more than ~1 min behind
  queued work is killed by the tunnel and surfaces as an
  ``UNAVAILABLE: TPU device error`` / ``worker process crashed`` at the
  fetch site (2026-07-31: 6/6 first-fetch deaths on ~20 s chunks while
  ``DRYAD_CH_MAX=2`` runs always passed).  The remedy is chunk
  degradation (resilience/policy.py), which is why this class is split
  from the generic device error even though the message family overlaps —
  the distinguishing signal is the SITE the error was raised at, which the
  supervisor tracks through the trainer's ``chunk_hook``.
* **device_unavailable** — the same ``UNAVAILABLE`` family raised away
  from a fetch (dispatch-time device loss, worker crash, connection
  reset).  Remedy: plain resume from the latest checkpoint.
* **oom** — ``RESOURCE_EXHAUSTED`` / "out of memory" allocations.
* **preemption** — ``ABORTED`` / "preempted" worker revocations.
* **unknown** — everything else.  The supervisor FAILS CLOSED on these:
  retrying an unrecognized error hides real bugs behind checkpoints.

Classification matches on exception type family (RuntimeError/OSError —
jaxlib's ``XlaRuntimeError`` is a RuntimeError subclass) plus the recorded
message signatures, so the injected faults below and the real runtime's
errors classify identically.

``FaultInjector`` is the deterministic injection layer: it IS a
``chunk_hook`` (engine/train.py, cpu/trainer.py) and raises the real error
classes at configured (site, iteration) points, so every resilience path
runs under ``JAX_PLATFORMS=cpu`` in tier-1.  Not passing one costs
nothing — the trainers skip the hook entirely when it is None.
"""

from __future__ import annotations

import dataclasses
import re

FETCH_DEATH = "fetch_death"
DEVICE_UNAVAILABLE = "device_unavailable"
OOM = "oom"
PREEMPTION = "preemption"
UNKNOWN = "unknown"
#: not a fault CLASS but an injection KIND (r12): the injector SLEEPS at
#: the configured hook site instead of raising — the deterministic twin
#: of a tunnel fetch hanging toward the ~60 s kill line, used to test the
#: obs fetch-stall watchdog (the hook fires inside the trainer's
#: watch_fetch bracket, so the in-flight age gauge sees the hang)
STALL = "stall"

#: classes the supervisor may retry; UNKNOWN always fails closed
RETRYABLE = (FETCH_DEATH, DEVICE_UNAVAILABLE, OOM, PREEMPTION)

#: the site vocabulary of the trainers' chunk_hook
SITES = ("dispatch", "fetch")

_OOM_PAT = re.compile(r"RESOURCE_EXHAUSTED|out of memory|hbm.*exceeds",
                      re.IGNORECASE)
# "preempt" in any casing, but the grpc status token only as the exact
# uppercase word — prose like "compilation aborted" must NOT classify as
# a retryable preemption (it would burn the retry budget on a real bug)
_PREEMPT_PAT = re.compile(r"(?i:preempt)|\bABORTED\b")
_UNAVAILABLE_PAT = re.compile(
    r"UNAVAILABLE|TPU device error|worker process crashed"
    r"|socket closed|connection reset", re.IGNORECASE)
# a fetch death announced in the message itself (deadline class) — site
# information is then not required to classify it
_FETCH_PAT = re.compile(r"DEADLINE_EXCEEDED|fetch.*(timed out|killed)",
                        re.IGNORECASE)


def classify_fault(exc: BaseException, at_fetch: bool = False) -> str:
    """Map a raised exception onto the recorded fault classes.

    ``at_fetch`` says whether the trainer's last chunk_hook event before
    the raise was a ``"fetch"`` site — the supervisor tracks this; it is
    what splits fetch_death from device_unavailable for the overlapping
    ``UNAVAILABLE`` message family (see module docstring).
    """
    # only runtime-shaped errors can be device faults: a ValueError from
    # config validation (or any non-Exception) must never be retried
    if not isinstance(exc, (RuntimeError, OSError)):
        return UNKNOWN
    msg = f"{type(exc).__name__}: {exc}"
    if _OOM_PAT.search(msg):
        return OOM
    if _PREEMPT_PAT.search(msg):
        return PREEMPTION
    if _FETCH_PAT.search(msg):
        return FETCH_DEATH
    if _UNAVAILABLE_PAT.search(msg):
        return FETCH_DEATH if at_fetch else DEVICE_UNAVAILABLE
    return UNKNOWN


# the messages injection raises — the real signatures from STATUS r5, so
# classify_fault treats injected and genuine faults identically
_CANONICAL_MSG = {
    # "fetch ... killed" matches _FETCH_PAT, so the injected exception
    # classifies as fetch_death by MESSAGE alone — make_fault's contract
    # ("classifies as kind") holds at any site.  Real tunnel deaths carry
    # no such token and rely on the supervisor's fetch-site attribution.
    FETCH_DEATH: ("UNAVAILABLE: TPU device error: worker process crashed "
                  "(fetch pending >60s behind queued work killed by the "
                  "tunnel) [injected]"),
    DEVICE_UNAVAILABLE: "UNAVAILABLE: TPU device error [injected]",
    OOM: ("RESOURCE_EXHAUSTED: out of memory while trying to allocate "
          "device buffer [injected]"),
    PREEMPTION: "ABORTED: the TPU worker was preempted [injected]",
    UNKNOWN: "injected fault with no recorded tunnel signature",
}

_ERROR_CLS = None


def _error_class():
    """The real jaxlib error type when constructible (it subclasses
    RuntimeError), else RuntimeError — classification only reads the
    message, so both exercise identical supervisor paths."""
    global _ERROR_CLS
    if _ERROR_CLS is None:
        try:
            from jaxlib.xla_extension import XlaRuntimeError

            XlaRuntimeError("constructibility probe")
            _ERROR_CLS = XlaRuntimeError
        except Exception:
            _ERROR_CLS = RuntimeError
    return _ERROR_CLS


def make_fault(kind: str) -> BaseException:
    """An exception instance that classifies as ``kind`` (UNKNOWN included:
    its message matches no recorded signature)."""
    if kind not in _CANONICAL_MSG:
        raise ValueError(f"unknown fault kind {kind!r}")
    return _error_class()(_CANONICAL_MSG[kind])


@dataclasses.dataclass(frozen=True)
class FaultPoint:
    """One configured injection: fire at the FIRST chunk-hook event with
    ``site`` at/after ``iteration`` (>=, not ==: chunked dispatch only
    visits chunk-start iterations, so an exact match could never hit).
    ``kind=STALL`` sleeps ``stall_s`` seconds at the hook instead of
    raising (the hung-fetch twin; the run then proceeds normally)."""

    iteration: int
    kind: str = DEVICE_UNAVAILABLE
    site: str = "dispatch"
    stall_s: float = 0.0

    def __post_init__(self):
        if self.site not in SITES:
            raise ValueError(f"site must be one of {SITES}, got {self.site!r}")
        if self.kind != STALL and self.kind not in _CANONICAL_MSG:
            raise ValueError(f"unknown fault kind {self.kind!r}")
        if self.kind == STALL and self.stall_s <= 0:
            raise ValueError("a STALL point needs stall_s > 0")


class FaultInjector:
    """Deterministic fault injection, shaped as a trainer ``chunk_hook``.

    Each point fires EXACTLY ONCE per injector lifetime — the supervisor
    keeps one injector across retries, so a resumed segment replays past
    the already-fired point instead of dying on it again.  ``fired``
    records (point index, site, iteration, kind) for test assertions.
    """

    def __init__(self, points):
        self.points = [p if isinstance(p, FaultPoint) else FaultPoint(*p)
                       for p in points]
        self._armed = [True] * len(self.points)
        self.fired: list[dict] = []

    def __call__(self, site: str, iteration: int) -> None:
        for i, pt in enumerate(self.points):
            if self._armed[i] and site == pt.site and iteration >= pt.iteration:
                self._armed[i] = False
                self.fired.append({"point": i, "site": site,
                                   "iteration": int(iteration),
                                   "kind": pt.kind})
                if pt.kind == STALL:
                    # a hang, not a death: hold the hook (inside the
                    # trainer's watch_fetch bracket) so the watchdog sees
                    # the in-flight age rise, then let the run continue
                    import time

                    time.sleep(pt.stall_s)
                    continue
                raise make_fault(pt.kind)

    @property
    def pending(self) -> int:
        return sum(self._armed)
