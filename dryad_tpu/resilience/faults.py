"""Fault classification + deterministic injection for supervised runs.

The classes named here are the failure modes actually RECORDED against the
tunneled device (STATUS.md r5 "Infrastructure note"), not a hypothetical
taxonomy:

* **fetch_death** — a device->host fetch pending more than ~1 min behind
  queued work is killed by the tunnel and surfaces as an
  ``UNAVAILABLE: TPU device error`` / ``worker process crashed`` at the
  fetch site (2026-07-31: 6/6 first-fetch deaths on ~20 s chunks while
  ``DRYAD_CH_MAX=2`` runs always passed).  The remedy is chunk
  degradation (resilience/policy.py), which is why this class is split
  from the generic device error even though the message family overlaps —
  the distinguishing signal is the SITE the error was raised at, which the
  supervisor tracks through the trainer's ``chunk_hook``.
* **device_unavailable** — the same ``UNAVAILABLE`` family raised away
  from a fetch (dispatch-time device loss, worker crash, connection
  reset).  Remedy: plain resume from the latest checkpoint.
* **oom** — ``RESOURCE_EXHAUSTED`` / "out of memory" allocations.
* **preemption** — ``ABORTED`` / "preempted" worker revocations.
* **unknown** — everything else.  The supervisor FAILS CLOSED on these:
  retrying an unrecognized error hides real bugs behind checkpoints.

Classification matches on exception type family (RuntimeError/OSError —
jaxlib's ``XlaRuntimeError`` is a RuntimeError subclass) plus the recorded
message signatures, so the injected faults below and the real runtime's
errors classify identically.

``FaultInjector`` is the deterministic injection layer: it IS a
``chunk_hook`` (engine/train.py, cpu/trainer.py) and raises the real error
classes at configured (site, iteration) points, so every resilience path
runs under ``JAX_PLATFORMS=cpu`` in tier-1.  Not passing one costs
nothing — the trainers skip the hook entirely when it is None.

Replica-level kinds (r14, the serving-fleet drills): the same injector
shape doubles as a SERVE-process hook — the HTTP front end calls it at
``("request", n)`` per /predict and ``("health", n)`` per /healthz probe
(serve/http.py), with the points wired through the environment
(``DRYAD_REPLICA_FAULTS``; encode/decode below) so a fleet supervisor can
arm drills in subprocess replicas it spawns:

* **replica_crash** — the process hard-exits (``os._exit(REPLICA_CRASH_EXIT)``,
  no cleanup) at the configured point: the deterministic twin of a
  segfault/OOM-kill, used to test crash detection + respawn.
* **slow_health** — the hook sleeps ``stall_s`` at the point (usually the
  ``health`` site) and then proceeds: a probe that exceeds its timeout,
  the hang-detection twin.
* **reject_503** — raises ``InjectedReject``, which the HTTP front end
  maps to a 503 answer at that site (a replica stuck shedding, the
  stuck-503 twin).  Mark the point ``sticky`` for the latched form.

These are injection KINDS, not classification classes: ``classify_fault``
never returns them (a fleet supervisor observes replica death through the
process exit code / probe, not through a raised exception).
"""

from __future__ import annotations

import dataclasses
import re

FETCH_DEATH = "fetch_death"
DEVICE_UNAVAILABLE = "device_unavailable"
OOM = "oom"
PREEMPTION = "preemption"
UNKNOWN = "unknown"
#: not a fault CLASS but an injection KIND (r12): the injector SLEEPS at
#: the configured hook site instead of raising — the deterministic twin
#: of a tunnel fetch hanging toward the ~60 s kill line, used to test the
#: obs fetch-stall watchdog (the hook fires inside the trainer's
#: watch_fetch bracket, so the in-flight age gauge sees the hang)
STALL = "stall"

#: replica-level injection KINDS (r14; see module docstring) — executed by
#: the injector / the serve HTTP front end, never returned by classify_fault
REPLICA_CRASH = "replica_crash"
SLOW_HEALTH = "slow_health"
REJECT_503 = "reject_503"
REPLICA_KINDS = (REPLICA_CRASH, SLOW_HEALTH, REJECT_503)

#: continual-boosting injection KIND (r19): the retrain worker consults
#: the injector via ``take()`` at its ``("retrain", job_index)`` point and,
#: when armed, trains the generation against the WRONG data distribution —
#: the deterministic twin of a poisoned retrain data pipeline, used to
#: drill the probation auto-rollback (continual/publish.py).  Action-at-
#: caller: ``take()`` RETURNS the fired point instead of raising, because
#: the drill needs a structurally valid (merely drift-breaching) model.
BAD_GENERATION = "bad_generation"
CONTINUAL_KINDS = (BAD_GENERATION,)
#: the exit code an injected replica_crash dies with — fleet tests and the
#: ci smoke identify the injected death by it (any OTHER nonzero exit in a
#: drill is a real bug, not the drill)
REPLICA_CRASH_EXIT = 23

#: classes the supervisor may retry; UNKNOWN always fails closed
RETRYABLE = (FETCH_DEATH, DEVICE_UNAVAILABLE, OOM, PREEMPTION)

#: the site vocabulary of the trainers' chunk_hook
SITES = ("dispatch", "fetch")
#: the site vocabulary of the serve front end's replica fault hook
REPLICA_SITES = ("request", "health")
#: the site vocabulary of the continual retrain worker's fault hook (r19)
CONTINUAL_SITES = ("retrain",)


class InjectedReject(RuntimeError):
    """The REJECT_503 drill: the HTTP front end answers 503 at this site.
    Deliberately NOT classifiable (classify_fault -> UNKNOWN): a drilled
    rejection must never be mistaken for a recorded tunnel fault class."""

_OOM_PAT = re.compile(r"RESOURCE_EXHAUSTED|out of memory|hbm.*exceeds",
                      re.IGNORECASE)
# "preempt" in any casing, but the grpc status token only as the exact
# uppercase word — prose like "compilation aborted" must NOT classify as
# a retryable preemption (it would burn the retry budget on a real bug)
_PREEMPT_PAT = re.compile(r"(?i:preempt)|\bABORTED\b")
_UNAVAILABLE_PAT = re.compile(
    r"UNAVAILABLE|TPU device error|worker process crashed"
    r"|socket closed|connection reset", re.IGNORECASE)
# a fetch death announced in the message itself (deadline class) — site
# information is then not required to classify it
_FETCH_PAT = re.compile(r"DEADLINE_EXCEEDED|fetch.*(timed out|killed)",
                        re.IGNORECASE)


def classify_fault(exc: BaseException, at_fetch: bool = False) -> str:
    """Map a raised exception onto the recorded fault classes.

    ``at_fetch`` says whether the trainer's last chunk_hook event before
    the raise was a ``"fetch"`` site — the supervisor tracks this; it is
    what splits fetch_death from device_unavailable for the overlapping
    ``UNAVAILABLE`` message family (see module docstring).
    """
    # only runtime-shaped errors can be device faults: a ValueError from
    # config validation (or any non-Exception) must never be retried
    if not isinstance(exc, (RuntimeError, OSError)):
        return UNKNOWN
    msg = f"{type(exc).__name__}: {exc}"
    if _OOM_PAT.search(msg):
        return OOM
    if _PREEMPT_PAT.search(msg):
        return PREEMPTION
    if _FETCH_PAT.search(msg):
        return FETCH_DEATH
    if _UNAVAILABLE_PAT.search(msg):
        return FETCH_DEATH if at_fetch else DEVICE_UNAVAILABLE
    return UNKNOWN


# the messages injection raises — the real signatures from STATUS r5, so
# classify_fault treats injected and genuine faults identically
_CANONICAL_MSG = {
    # "fetch ... killed" matches _FETCH_PAT, so the injected exception
    # classifies as fetch_death by MESSAGE alone — make_fault's contract
    # ("classifies as kind") holds at any site.  Real tunnel deaths carry
    # no such token and rely on the supervisor's fetch-site attribution.
    FETCH_DEATH: ("UNAVAILABLE: TPU device error: worker process crashed "
                  "(fetch pending >60s behind queued work killed by the "
                  "tunnel) [injected]"),
    DEVICE_UNAVAILABLE: "UNAVAILABLE: TPU device error [injected]",
    OOM: ("RESOURCE_EXHAUSTED: out of memory while trying to allocate "
          "device buffer [injected]"),
    PREEMPTION: "ABORTED: the TPU worker was preempted [injected]",
    UNKNOWN: "injected fault with no recorded tunnel signature",
}

_ERROR_CLS = None


def _error_class():
    """The real jaxlib error type when constructible (it subclasses
    RuntimeError), else RuntimeError — classification only reads the
    message, so both exercise identical supervisor paths."""
    global _ERROR_CLS
    if _ERROR_CLS is None:
        try:
            from jaxlib.xla_extension import XlaRuntimeError

            XlaRuntimeError("constructibility probe")
            _ERROR_CLS = XlaRuntimeError
        except Exception:
            _ERROR_CLS = RuntimeError
    return _ERROR_CLS


def make_fault(kind: str) -> BaseException:
    """An exception instance that classifies as ``kind`` (UNKNOWN included:
    its message matches no recorded signature)."""
    if kind not in _CANONICAL_MSG:
        raise ValueError(f"unknown fault kind {kind!r}")
    return _error_class()(_CANONICAL_MSG[kind])


@dataclasses.dataclass(frozen=True)
class FaultPoint:
    """One configured injection: fire at the FIRST chunk-hook event with
    ``site`` at/after ``iteration`` (>=, not ==: chunked dispatch only
    visits chunk-start iterations, so an exact match could never hit).
    ``kind=STALL``/``SLOW_HEALTH`` sleeps ``stall_s`` seconds at the hook
    instead of raising (the hung-fetch / slow-probe twins; the run then
    proceeds normally).  ``sticky=True`` keeps the point armed after it
    fires — the latched form the stuck-503 drill needs (a replica that
    sheds ONE request is a blip; one that sheds every request from a
    point on is the recorded failure shape)."""

    iteration: int
    kind: str = DEVICE_UNAVAILABLE
    site: str = "dispatch"
    stall_s: float = 0.0
    sticky: bool = False

    def __post_init__(self):
        all_sites = SITES + REPLICA_SITES + CONTINUAL_SITES
        if self.site not in all_sites:
            raise ValueError(f"site must be one of {all_sites}, "
                             f"got {self.site!r}")
        if (self.kind not in (STALL,) + REPLICA_KINDS + CONTINUAL_KINDS
                and self.kind not in _CANONICAL_MSG):
            raise ValueError(f"unknown fault kind {self.kind!r}")
        # kinds and sites partition strictly: a replica kind at a trainer
        # site would never fire (or worse, os._exit a training run), and a
        # tunnel class at a replica site decodes cleanly but arms nothing —
        # both are the silent-typo'd-drill shape that must fail loudly
        if self.kind in REPLICA_KINDS and self.site not in REPLICA_SITES:
            raise ValueError(
                f"replica fault kind {self.kind!r} fires only at replica "
                f"sites {REPLICA_SITES}, got site {self.site!r}")
        if self.kind in CONTINUAL_KINDS and self.site not in CONTINUAL_SITES:
            raise ValueError(
                f"continual fault kind {self.kind!r} fires only at "
                f"continual sites {CONTINUAL_SITES}, got site {self.site!r}")
        if (self.kind not in REPLICA_KINDS
                and self.site in REPLICA_SITES):
            raise ValueError(
                f"fault kind {self.kind!r} is a trainer class and never "
                f"fires at replica site {self.site!r}; use one of "
                f"{REPLICA_KINDS}")
        if (self.kind not in CONTINUAL_KINDS
                and self.site in CONTINUAL_SITES):
            raise ValueError(
                f"fault kind {self.kind!r} never fires at continual site "
                f"{self.site!r}; use one of {CONTINUAL_KINDS}")
        if self.kind in (STALL, SLOW_HEALTH) and self.stall_s <= 0:
            raise ValueError(f"a {self.kind} point needs stall_s > 0")


class FaultInjector:
    """Deterministic fault injection, shaped as a trainer ``chunk_hook``.

    Each point fires EXACTLY ONCE per injector lifetime — the supervisor
    keeps one injector across retries, so a resumed segment replays past
    the already-fired point instead of dying on it again.  ``fired``
    records (point index, site, iteration, kind) for test assertions.

    Lock contract (r15): ``_lock`` (declared below) makes the armed
    check-and-clear atomic — the serve front end calls the hook from
    ThreadingHTTPServer handler threads, and without the lock a one-shot
    drill fired once per in-flight request (found by the r14 review,
    now pinned by the schedule harness's concurrent-fire drill).  The
    fault ACTIONS (sleep, raise, os._exit) run strictly OUTSIDE the
    lock: a SLOW_HEALTH stall must hold up only its own probe, never
    serialize concurrent injections — the no-blocking-under-lock lint
    keeps it that way.
    """

    GUARDED_BY = {"_armed": "_lock", "fired": "_lock"}

    def __init__(self, points):
        import threading

        self.points = [p if isinstance(p, FaultPoint) else FaultPoint(*p)
                       for p in points]
        self._armed = [True] * len(self.points)
        self.fired: list[dict] = []
        # the serve front end calls the hook from ThreadingHTTPServer
        # handler threads: the armed check-and-clear must be atomic or a
        # one-shot drill fires once per in-flight request (the trainer
        # path is single-threaded and pays one uncontended acquire)
        self._lock = threading.Lock()

    def __call__(self, site: str, iteration: int) -> None:
        to_fire: list[FaultPoint] = []
        with self._lock:
            for i, pt in enumerate(self.points):
                if (self._armed[i] and site == pt.site
                        and iteration >= pt.iteration):
                    if not pt.sticky:
                        self._armed[i] = False
                    self.fired.append({"point": i, "site": site,
                                       "iteration": int(iteration),
                                       "kind": pt.kind})
                    to_fire.append(pt)
                    if pt.kind not in (STALL, SLOW_HEALTH):
                        # a raising/exiting point ends THIS call's scan:
                        # later points stay armed for later events (three
                        # identical points = three successive faults, the
                        # repeated-same-point drill)
                        break
        # actions run OUTSIDE the lock: a SLOW_HEALTH sleep must stall
        # only its own probe, never serialize concurrent injections
        for pt in to_fire:
            if pt.kind in CONTINUAL_KINDS:
                # action-at-caller kinds are consumed via take(); firing
                # one through the raising hook is a drill wiring bug —
                # doing nothing here would silently disarm it
                raise ValueError(
                    f"{pt.kind} is an action-at-caller kind: consume it "
                    "with FaultInjector.take(), not the raising hook")
            if pt.kind in (STALL, SLOW_HEALTH):
                # a hang, not a death: hold the hook (inside the
                # trainer's watch_fetch bracket / the replica's probe
                # handler) so the watcher sees the latency rise, then
                # let the run continue
                import time

                time.sleep(pt.stall_s)
                continue
            if pt.kind == REPLICA_CRASH:
                # the deterministic twin of a segfault/OOM-kill: no
                # atexit, no flushes — the fleet supervisor must see
                # exactly what a real crash leaves behind
                import os

                os._exit(REPLICA_CRASH_EXIT)
            if pt.kind == REJECT_503:
                raise InjectedReject(
                    f"injected 503 rejection at {site} #{iteration}")
            raise make_fault(pt.kind)

    def take(self, site: str, iteration: int) -> "FaultPoint | None":
        """Atomic check-and-clear for ACTION-AT-CALLER kinds (r19
        ``bad_generation``): returns the first matching armed point
        (recorded in ``fired``) instead of raising/exiting — the caller
        owns the fault's effect.  Same one-shot/sticky discipline as
        ``__call__``; the two share ``_armed``, so a point consumed here
        can never also fire there."""
        with self._lock:
            for i, pt in enumerate(self.points):
                if (self._armed[i] and site == pt.site
                        and iteration >= pt.iteration):
                    if not pt.sticky:
                        self._armed[i] = False
                    self.fired.append({"point": i, "site": site,
                                       "iteration": int(iteration),
                                       "kind": pt.kind})
                    return pt
        return None

    @property
    def pending(self) -> int:
        with self._lock:
            return sum(self._armed)


# ---------------------------------------------------------------------------
# environment wire format (fleet drills -> subprocess replicas)
#
# A fleet supervisor arms drills in replicas it SPAWNS, so the points must
# survive an exec boundary: one env var, ``DRYAD_REPLICA_FAULTS``, holding
# comma-separated ``site:iteration:kind[:stall_s][:sticky]`` specs —
# e.g. ``request:3:replica_crash`` or ``health:1:slow_health:6.0:sticky``.
# The serve CLI decodes it at startup and threads the injector into the
# HTTP front end's fault hook; an absent/empty var costs nothing.

REPLICA_FAULTS_ENV = "DRYAD_REPLICA_FAULTS"
#: same wire format, consumed by the continual retrain worker (r19) — the
#: scheduler passes it through the subprocess env so a forced-bad-
#: generation drill survives the exec boundary like the replica drills do
CONTINUAL_FAULTS_ENV = "DRYAD_CONTINUAL_FAULTS"


def encode_points(points) -> str:
    """``FaultPoint``s (or their tuple spellings) -> the env-var string."""
    specs = []
    for p in points:
        if not isinstance(p, FaultPoint):
            p = FaultPoint(*p)
        spec = f"{p.site}:{p.iteration}:{p.kind}"
        if p.stall_s:
            spec += f":{p.stall_s}"
        if p.sticky:
            spec += ":sticky" if p.stall_s else ":0:sticky"
        specs.append(spec)
    return ",".join(specs)


def decode_points(value: str) -> list[FaultPoint]:
    """The env-var string -> validated ``FaultPoint``s (raises ValueError
    on malformed specs: a typo'd drill must fail loudly at replica start,
    not silently arm nothing)."""
    points = []
    for spec in (value or "").split(","):
        spec = spec.strip()
        if not spec:
            continue
        parts = spec.split(":")
        if len(parts) < 3:
            raise ValueError(f"malformed replica fault spec {spec!r} "
                             "(want site:iteration:kind[:stall_s][:sticky])")
        sticky = False
        if parts[-1] == "sticky":
            sticky = True
            parts = parts[:-1]
        if len(parts) > 4:
            # a misspelt "sticky" (or any extra token) must not silently
            # arm the non-latched form of the drill
            raise ValueError(
                f"malformed replica fault spec {spec!r}: unrecognized "
                f"trailing field {parts[4]!r} "
                "(want site:iteration:kind[:stall_s][:sticky])")
        stall_s = float(parts[3]) if len(parts) > 3 else 0.0
        points.append(FaultPoint(site=parts[0], iteration=int(parts[1]),
                                 kind=parts[2], stall_s=stall_s,
                                 sticky=sticky))
    return points


def injector_from_env(environ=None,
                      env_var: str = REPLICA_FAULTS_ENV
                      ) -> "FaultInjector | None":
    """Build an injector from the named env var (default: the replica
    drills' ``DRYAD_REPLICA_FAULTS``; the continual retrain worker passes
    ``CONTINUAL_FAULTS_ENV``).  None when unset/empty — the production
    path."""
    import os

    value = (environ if environ is not None else os.environ).get(env_var, "")
    points = decode_points(value)
    return FaultInjector(points) if points else None
