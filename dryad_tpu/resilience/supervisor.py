"""Supervised training: survive the recorded tunnel/device fault classes
without a human in the loop.

``supervise_train`` wraps ``dryad.train`` in a classify → degrade →
resume → retry loop.  The expensive invariant it exploits already exists
and is test-pinned (checkpoint → crash → resume is bitwise identical to
the uninterrupted run — tests/test_checkpoint.py, the mocked multi-host
drill); this module is the subsystem that exercises it automatically:

1. a raised failure is classified against the REAL recorded fault
   signatures (faults.py; STATUS r5) — unknown classes FAIL CLOSED,
2. fetch-death-class faults degrade the chunk cap stepwise toward the
   known-safe 2 (policy.ChunkCapPolicy; applied per chunk AFTER program
   selection, so degradation can never flip the compiled program),
3. the checkpoint cadence tightens after each fault (less replay at the
   next one),
4. training resumes from ``Checkpointer.latest()`` under an exponential
   backoff and a hard retry budget; repeated faults with NO checkpoint
   progress in between fail closed after ``policy.same_point_retries``.

Every classification, backoff, degradation, and resume decision lands in
the append-only run journal (journal.py).

Supervised output is bitwise identical to the uninterrupted run: resume
identity is the pinned invariant, and both of the supervisor's levers
(chunk length, checkpoint cadence) are host-side scheduling knobs of one
shared compiled program.
"""

from __future__ import annotations

import os
import time
from typing import Any, Optional

from dryad_tpu.checkpoint import Checkpointer
from dryad_tpu.obs.spans import record as record_span
from dryad_tpu.obs.spans import span
from dryad_tpu.obs.tripwire import default_tripwire
from dryad_tpu.obs.watchdog import default_watchdog
from dryad_tpu.resilience import faults as F
from dryad_tpu.resilience.journal import RunJournal
from dryad_tpu.resilience.policy import ChunkCapPolicy, RetryPolicy


class FaultError(RuntimeError):
    """Fail-closed terminus: the supervisor refuses to keep retrying.
    ``kind`` is the last fault's class, ``reason`` why retrying stopped
    (``unknown_fault`` / ``retry_budget_exhausted`` /
    ``repeated_fault_at_same_iteration``); the original exception is
    chained as ``__cause__``."""

    def __init__(self, message: str, kind: str, reason: str):
        super().__init__(message)
        self.kind = kind
        self.reason = reason


def supervise_train(
    params,
    train_set,
    valid_sets=None,
    *,
    policy: Optional[RetryPolicy] = None,
    backend: str = "auto",
    checkpoint_dir: Optional[str] = None,
    checkpoint_every: int = 10,
    journal: "RunJournal | str | None" = None,
    fault_injector=None,
    callbacks=None,
    callback=None,
    valid_names=None,
    mesh=None,
    **kw: Any,
):
    """Train under supervision; returns the finished Booster.

    ``checkpoint_dir`` is REQUIRED — resume is the recovery mechanism.
    NOTE the directory is continued unconditionally: checkpoints already
    present (a prior invocation's) resume exactly like a mid-run fault's.
    Callers owning a user surface should confirm cross-invocation
    continuation explicitly (the CLI requires ``--resume`` for it).
    ``journal`` takes a path (owned/closed here) or an open ``RunJournal``.
    ``fault_injector`` threads a deterministic ``faults.FaultInjector``
    into the trainer's chunk loop (CPU-testable resilience paths); extra
    ``**kw`` forward to ``dryad.train`` (and through it to params).
    """
    import dryad_tpu as dryad

    policy = policy or RetryPolicy()
    if checkpoint_dir is None:
        raise ValueError("supervise_train requires checkpoint_dir: resume "
                         "from the latest checkpoint is the recovery path")

    # a caller's warm-start booster seeds ONLY the first, checkpoint-less
    # segment: once any checkpoint exists it embodies warm start + progress,
    # and passing init_booster through would make dryad.train's
    # "resume only when init_booster is None" guard skip the checkpoint —
    # every retry would silently redo the whole faulted segment
    init_booster = kw.pop("init_booster", None)
    # init_model (r19): the public APPEND surface — num_trees counts NEW
    # trees.  Normalize it ONCE into the total-count init_booster form so
    # every resumed segment sees one consistent target; the append count
    # must live in ``params`` here (not a loose kwarg), because the
    # conversion happens before dryad.train's params merge.
    init_model = kw.pop("init_model", None)
    if init_model is not None:
        if init_booster is not None:
            raise ValueError("pass init_model (append semantics) or "
                             "init_booster (total-count resume), not both")
        from dryad_tpu.config import make_params
        p0 = make_params(params)
        dryad._check_append_compatible(p0, train_set, init_model)
        params = p0.replace(num_trees=p0.num_trees
                            + init_model.num_iterations)
        init_booster = init_model
    # the supervisor OWNS resume semantics (every segment passes
    # resume=True); a caller's resume= kwarg would otherwise collide in
    # dryad.train with an opaque TypeError.  An explicit resume=False is
    # contradictory — silently swallowing it would continue a stale
    # directory the caller just said NOT to continue.
    if kw.pop("resume", True) is False:
        raise ValueError(
            "supervise_train always resumes from checkpoint_dir (that IS "
            "the recovery mechanism); resume=False is contradictory — "
            "point checkpoint_dir at a fresh or cleared directory to "
            "start over")
    # likewise it owns the loop-observation surfaces it composes — reject
    # them up front instead of letting **kw collide deep inside a segment
    for owned in ("chunk_hook", "chunk_policy"):
        if owned in kw:
            raise ValueError(
                f"supervise_train composes its own {owned} (journal + "
                "injection + adaptive cap); pass fault_injector/journal "
                "here, or call dryad.train directly for raw hook access")

    own_journal = isinstance(journal, (str, os.PathLike))
    j = RunJournal(os.fspath(journal)) if own_journal else journal

    def jevent(kind: str, /, **fields) -> None:
        if j is not None:
            j.event(kind, **fields)

    # the trainers' chunk_hook: record loop events + track the last site so
    # a raised UNAVAILABLE can be attributed to a fetch (faults.py), then
    # give the injector its shot
    last = {"site": None, "iteration": -1}

    def hook(site: str, iteration: int) -> None:
        last["site"], last["iteration"] = site, int(iteration)
        jevent("chunk_" + site, iteration=int(iteration))
        if fault_injector is not None:
            fault_injector(site, iteration)

    # replay visibility: a resumed segment re-delivers callbacks for the
    # iterations re-grown since the checkpoint (values bitwise-identical to
    # the first delivery).  The attempt marker lets consumers dedupe —
    # keep the highest supervise_attempt per iteration.
    from dryad_tpu.callbacks import combine

    user_cb = combine(([callback] if callback else []) + list(callbacks or []))
    marked_cb = None
    if user_cb is not None:
        def marked_cb(it, info):
            info = dict(info)
            info["supervise_attempt"] = n_faults
            user_cb(it, info)

    chunk_cap = ChunkCapPolicy(policy)
    every = int(checkpoint_every)
    n_faults = 0
    same_point = 0
    last_resume_iter: Optional[int] = None
    t0 = time.perf_counter()

    def latest_iteration() -> int:
        # iterations() is a directory listing — never deserialize a
        # (potentially multi-hundred-MB) checkpoint just to read its number
        its = Checkpointer(checkpoint_dir, every=every).iterations()
        return its[-1] if its else 0

    jevent("run_start", checkpoint_dir=checkpoint_dir,
           checkpoint_every=every, backend=backend,
           retry_budget=policy.retry_budget)

    # r12: unexpected recompiles (obs/tripwire.py — a new program key
    # after the trainer armed its family) land in the journal as events,
    # so the flight recorder correlates them with the faults that follow
    _remove_tw = default_tripwire().add_listener(
        lambda program, detail: jevent("recompile_unexpected",
                                       program=program, detail=detail))

    def _loop():
        nonlocal n_faults, same_point, last_resume_iter, every
        while True:
            resume_iter = latest_iteration()
            # fresh site tracking per segment: a fault raised before this
            # segment's first hook (device re-init, compile, upload) must
            # not inherit the PREVIOUS segment's fetch attribution;
            # likewise the cap-consulted flag is per segment
            last["site"], last["iteration"] = None, -1
            chunk_cap.consulted = False
            jevent("segment_start", attempt=n_faults,
                   resume_iteration=resume_iter, ch_max=chunk_cap.peek(),
                   checkpoint_every=every)
            # segment wall via record(), NOT a with-span: a with-block here
            # would prefix every nested with-span the trainer emits
            # (train.fetch.* -> supervise.segment/train.fetch.*), splitting
            # the train series across supervised/unsupervised naming
            _t_seg = time.perf_counter()
            try:
                booster = dryad.train(
                    params, train_set, valid_sets,
                    valid_names=valid_names,
                    backend=backend, checkpoint_dir=checkpoint_dir,
                    checkpoint_every=every, resume=True,
                    # resume_iter > 0 iff a checkpoint exists (they
                    # number from 1): the checkpoint then embodies the
                    # warm start, which must not shadow it
                    init_booster=init_booster if resume_iter == 0 else None,
                    callback=marked_cb, mesh=mesh,
                    chunk_hook=hook, chunk_policy=chunk_cap, **kw)
                record_span("supervise.segment",
                            time.perf_counter() - _t_seg)
            except Exception as exc:  # noqa: BLE001 — classified just below
                record_span("supervise.segment",
                            time.perf_counter() - _t_seg)
                _t_cl = time.perf_counter()
                kind = F.classify_fault(exc, at_fetch=last["site"] == "fetch")
                record_span("supervise.classify",
                            time.perf_counter() - _t_cl)
                ckpt_iter = latest_iteration()
                # stall correlation (r12): if the fetch watchdog saw a
                # stall during THIS segment, record its age next to the
                # classification — the journal then shows "pending 43 s,
                # then fetch_death" instead of a death from nowhere
                stall = default_watchdog().last_stall()
                extra = {}
                if stall is not None and stall.get("ended_at", 0) >= _t_seg:
                    extra = {"stall_age_s": stall["age_s"],
                             "stall_site": stall["site"]}
                jevent("fault", kind=kind, site=last["site"],
                       iteration=last["iteration"], resume_point=ckpt_iter,
                       message=str(exc)[:300], **extra)
                if kind == F.UNKNOWN:
                    jevent("fail_closed", reason="unknown_fault",
                           message=str(exc)[:300])
                    raise FaultError(
                        f"unclassified failure — refusing to retry: {exc}",
                        kind, "unknown_fault") from exc
                n_faults += 1
                if n_faults > policy.retry_budget:
                    jevent("fail_closed", reason="retry_budget_exhausted",
                           faults=n_faults)
                    raise FaultError(
                        f"retry budget ({policy.retry_budget}) exhausted "
                        f"after a {kind} fault: {exc}", kind,
                        "retry_budget_exhausted") from exc
                if (last_resume_iter is not None
                        and ckpt_iter == last_resume_iter):
                    same_point += 1
                    if same_point >= policy.same_point_retries:
                        jevent("fail_closed",
                               reason="repeated_fault_at_same_iteration",
                               resume_point=ckpt_iter, repeats=same_point)
                        raise FaultError(
                            f"{kind} fault repeated {same_point}x with no "
                            f"checkpoint progress past iteration {ckpt_iter}",
                            kind, "repeated_fault_at_same_iteration") from exc
                else:
                    same_point = 0
                last_resume_iter = ckpt_iter
                # the recorded remedy — shorter chunks (STATUS r5) — engages
                # on a classified fetch-death, AND as a fallback on a
                # device_unavailable that REPEATS with no checkpoint
                # progress: with async dispatch a killed fetch can surface
                # at the next enqueue (a dispatch site), where site
                # attribution cannot see it — the remedy must still be
                # tried before the same-point breaker fails the run closed.
                degrade_now = kind == F.FETCH_DEATH or (
                    kind == F.DEVICE_UNAVAILABLE and same_point >= 1)
                if degrade_now:
                    # cap_consulted says whether the faulted segment's
                    # trainer ever READ the cap — False means a non-chunked
                    # dispatch path, where degradation is a no-op the
                    # operator should see as "inapplicable", not "tried
                    # and failed".
                    # changed=False tells the operator the remedy was
                    # already exhausted (cap at/below the ladder floor),
                    # not meaningfully re-applied
                    before = chunk_cap.peek()
                    consulted = chunk_cap.consulted
                    after = chunk_cap.degrade()
                    jevent("backoff_chunks", ch_max_from=before,
                           ch_max_to=after, cap_consulted=consulted,
                           changed=chunk_cap.last_shrunk,
                           trigger=("fetch_death" if kind == F.FETCH_DEATH
                                    else "same_point_device_unavailable"))
                new_every = policy.next_checkpoint_every(every)
                sleep_s = policy.backoff_s(n_faults - 1)
                jevent("resume", attempt=n_faults, from_iteration=ckpt_iter,
                       sleep_s=sleep_s, checkpoint_every=new_every)
                every = new_every
                if sleep_s > 0:
                    with span("supervise.backoff"):
                        time.sleep(sleep_s)
                continue
            wall = time.perf_counter() - t0
            jevent("complete", wall_s=round(wall, 3),
                   iterations=booster.num_iterations, faults=n_faults,
                   ch_max_final=chunk_cap.peek())
            return booster

    try:
        return _loop()
    finally:
        # EVERY exit — completion, fail-closed, an unexpected error raised
        # outside the classified path, Ctrl-C mid-backoff — releases an
        # owned journal handle (and the tripwire listener, which holds it)
        _remove_tw()
        _close(j, own_journal)


def _close(j: Optional[RunJournal], owned: bool) -> None:
    if owned and j is not None:
        j.close()
