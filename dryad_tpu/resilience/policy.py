"""Retry + degradation policy for supervised runs.

Two cooperating pieces:

* ``RetryPolicy`` — the static knobs: retry budget, exponential backoff,
  the chunk-cap degradation ladder, checkpoint-cadence tightening.
* ``ChunkCapPolicy`` — the LIVE chunk-cap controller the supervisor
  threads into ``train_device(chunk_policy=...)``.  The trainer consults
  ``cap()`` per chunk (after path selection and calibration, so a cap
  change can never flip the compiled program — engine/train.py) and calls
  ``note_clean_chunk()`` after each chunk's host work completes, which
  drives the re-widening side of the ladder.

Degradation walks ``ch_max_ladder`` stepwise toward the known-safe floor
(STATUS r5: ``DRYAD_CH_MAX=2`` survived every tunnel phase that killed
standard ~20 s chunks); re-widening walks back up one step after
``rewiden_after_clean_chunks`` consecutive clean chunks, eventually
returning to uncapped.  Because the trainer's run-ahead cap keeps device
completion within 2 chunks of the host, a "clean chunk" signal is at most
two chunks optimistic — the ladder step (not the counter's exactness) is
what bounds risk.
"""

from __future__ import annotations

import dataclasses


def _default_ladder() -> tuple[int, ...]:
    """The calibrated degradation ladder (r23: policy table
    "chunk_cap"/"ladder"; the committed default is the pre-r23
    ``(8, 4, 2)`` — STATUS r5's known-safe tunnel floor).  The policy
    package is stdlib-only, so this keeps the module jax-free."""
    from dryad_tpu.policy.gates import gate_value

    return tuple(int(s) for s in gate_value("chunk_cap", "ladder"))


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Static supervision knobs (see module docstring)."""

    #: total classified faults tolerated before failing closed
    retry_budget: int = 5
    #: faults tolerated at ONE resume point (no checkpoint progress in
    #: between) before failing closed — covers one full walk down the
    #: default chunk ladder, since degradation is the legitimate reason a
    #: same-point fault deserves another attempt
    same_point_retries: int = 3
    backoff_base_s: float = 1.0
    backoff_factor: float = 2.0
    backoff_max_s: float = 60.0
    #: chunk-cap degradation steps, widest first, ending on the known-safe
    #: floor; degrade() moves to the first step below the current cap
    ch_max_ladder: tuple[int, ...] = dataclasses.field(
        default_factory=_default_ladder)
    #: initial cap (0 = uncapped until the first fetch-death)
    ch_max_start: int = 0
    #: consecutive clean chunks before the cap re-widens one step
    rewiden_after_clean_chunks: int = 32
    #: checkpoint cadence after a fault: halve, but never below the floor
    #: and never above the current cadence.  The floor stays WELL above 1:
    #: each checkpoint is a bulk _materialize fetch, and per-iteration
    #: fetches are both the pattern CLAUDE.md forbids and extra exposure to
    #: the very fetch-death class being retried.
    checkpoint_tighten_factor: int = 2
    checkpoint_every_min: int = 5

    def backoff_s(self, fault_index: int) -> float:
        """Exponential backoff for the (0-based) Nth fault."""
        return min(self.backoff_base_s * self.backoff_factor ** fault_index,
                   self.backoff_max_s)

    def next_checkpoint_every(self, every: int) -> int:
        """Tightened cadence: monotone non-increasing (a caller already
        below the floor keeps their cadence)."""
        return min(every, max(self.checkpoint_every_min,
                              every // self.checkpoint_tighten_factor))


class ChunkCapPolicy:
    """Live chunk-length cap: the supervisor degrades it on fetch-death
    faults; the trainer's clean-chunk feedback re-widens it."""

    def __init__(self, policy: RetryPolicy | None = None):
        self.policy = policy or RetryPolicy()
        if not self.policy.ch_max_ladder:
            raise ValueError("ch_max_ladder must have at least one step")
        # normalize: the walk logic assumes widest-first, but an ascending
        # user ladder (2, 4, 8) is a natural spelling — don't let it
        # silently invert degrade AND re-widen
        self._ladder = tuple(sorted(set(self.policy.ch_max_ladder),
                                    reverse=True))
        self._cap = int(self.policy.ch_max_start)
        self._clean = 0
        self._seen = 0        # longest chunk actually run (trainer feedback)
        self._fatal = 0       # shortest length a fault was observed AT (0 = none)
        #: whether the last degrade() actually stepped BELOW the length
        #: that was running — False means the remedy had no room left
        #: (fatal length already at/below the ladder floor); the
        #: supervisor journals it so "applied" and "exhausted" read apart
        self.last_shrunk = False
        #: whether a trainer ever consulted cap() — False means the run
        #: took a non-chunked path where degradation is a no-op; the
        #: supervisor journals this so an operator can tell "remedy
        #: applied" from "remedy inapplicable"
        self.consulted = False

    def cap(self) -> int:
        """Current cap on iterations per chunk; 0 = uncapped.  This is the
        TRAINER's entry point — reading it marks the cap as consulted."""
        self.consulted = True
        return self._cap

    def peek(self) -> int:
        """The cap without marking it consulted (supervisor observability)."""
        return self._cap

    def degrade(self) -> int:
        """Step the cap down the ladder, targeting the first step STRICTLY
        below what has actually been running (the observed chunk length
        when known — a ladder top at/above the calibrated CH would replay
        the fatal length unchanged).  Returns the new cap; resets the
        clean-chunk counter.  A cap already at/below the ladder floor
        (e.g. ch_max_start=1) is kept — degrading must never WIDEN chunks.
        """
        self._clean = 0
        self.last_shrunk = False
        floor = self._ladder[-1]
        # the reference length the next step must undercut: the SMALLER of
        # the current cap and the longest observed chunk (a cap above the
        # calibrated CH never governed what actually ran), else unbounded.
        # It is also remembered as FATAL — re-widening must never return
        # to a length a fault was observed at, or a persistent tunnel
        # phase (the recorded r5 mode) would oscillate safe->fatal->safe,
        # burning the finite retry budget despite steady progress.
        ref = min([v for v in (self._cap, self._seen) if v], default=0)
        if ref:
            self._fatal = ref if self._fatal == 0 else min(self._fatal, ref)
        if self._cap != 0 and self._cap <= floor:
            return self._cap
        for step in self._ladder:
            if ref == 0 or step < ref:
                self._cap = step
                self.last_shrunk = True
                return self._cap
        # the fatal length is already at/below the floor: cap there anyway
        # (bounds future re-widening) but this did NOT shrink anything
        self._cap = floor
        return self._cap

    def note_dispatch(self, n: int) -> None:
        """Trainer feedback at DISPATCH time: a chunk of ``n`` iterations
        is about to be enqueued.  Recording the length here (not only on
        clean completion) is what makes the first degrade after a
        first-fetch death — the exact recorded r5 mode, where no chunk ever
        completed cleanly — step strictly below the fatal length."""
        if n:
            self._seen = max(self._seen, int(n))

    def note_clean_chunk(self, n: int = 0) -> None:
        """Trainer feedback: one chunk of ``n`` iterations completed its
        host work without a fault.  After ``rewiden_after_clean_chunks`` in
        a row the cap walks one ladder step back up (and past the top step,
        to uncapped)."""
        if n:
            self._seen = max(self._seen, int(n))
        if self._cap == 0:
            return
        self._clean += 1
        if self._clean < self.policy.rewiden_after_clean_chunks:
            return
        self._clean = 0
        # one ladder step back up, bounded STRICTLY below any known-fatal
        # length (never back to uncapped once a fatal length is on record)
        wider = [s for s in self._ladder
                 if s > self._cap and (self._fatal == 0 or s < self._fatal)]
        if wider:
            self._cap = wider[-1]
        elif self._fatal == 0:
            self._cap = 0
