"""Append-only JSONL run journal for supervised training.

Same shape discipline as ``callbacks.JsonlLogger`` (one JSON object per
line, line-buffered append, elapsed seconds since construction) but keyed
by EVENT rather than iteration: chunk dispatch/fetch, fault
classification, backoff decisions, resume points, completion wall.  The
journal is the supervised run's flight recorder — `scripts/headline_10m.py`
and the ci.sh supervisor smoke both read it back.

Event vocabulary (the ``event`` field; producers in supervisor.py):
``run_start``, ``segment_start``, ``chunk_dispatch``, ``chunk_fetch``,
``fault``, ``backoff_chunks``, ``resume``, ``fail_closed``, ``complete``.
"""

from __future__ import annotations

import json
import time


class RunJournal:
    """Append one JSON event line per supervision event to ``path``."""

    def __init__(self, path: str):
        self.path = path
        self._t0 = time.perf_counter()
        self._fh = open(path, "a", buffering=1)

    def event(self, kind: str, /, **fields) -> None:
        rec = {"event": kind,
               "elapsed_s": round(time.perf_counter() - self._t0, 6)}
        rec.update(fields)
        self._fh.write(json.dumps(rec) + "\n")

    def close(self) -> None:
        self._fh.close()

    def __enter__(self) -> "RunJournal":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    @staticmethod
    def read(path: str) -> list[dict]:
        """Parse a journal back into its event dicts (tests/smokes)."""
        with open(path) as f:
            return [json.loads(line) for line in f if line.strip()]

    @classmethod
    def read_last_run(cls, path: str) -> list[dict]:
        """Events of the LAST supervised run only.  The file is append-only
        across invocations, so any consumer counting faults/resumes must
        slice after the final run_start or it inherits a prior invocation's
        records (scripts/headline_10m.py reads artifact counts this way)."""
        events = cls.read(path)
        starts = [i for i, e in enumerate(events)
                  if e["event"] == "run_start"]
        return events[starts[-1]:] if starts else events
