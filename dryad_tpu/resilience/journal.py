"""Append-only JSONL run journal for supervised training.

Same shape discipline as ``callbacks.JsonlLogger`` (one JSON object per
line, line-buffered append, elapsed seconds since construction) but keyed
by EVENT rather than iteration: chunk dispatch/fetch, fault
classification, backoff decisions, resume points, completion wall.  The
journal is the supervised run's flight recorder — `scripts/headline_10m.py`
and the ci.sh supervisor smoke both read it back.

Event vocabulary (the ``event`` field; producers in supervisor.py):
``run_start``, ``segment_start``, ``chunk_dispatch``, ``chunk_fetch``,
``fault``, ``backoff_chunks``, ``resume``, ``fail_closed``, ``complete``.
The fleet supervisor (fleet/supervisor.py) adds the replica lifecycle
family (``fleet_start``, ``replica_spawn``/``ready``/``crash``/
``respawn``/``unhealthy``/``hang``/``fail_closed``/``backoff``, the
``push_*``/``replica_drain``/``replica_swapped`` rolling-push records),
and r18 adds ``drift_breach`` — the router's drift gate journals a
SUSTAINED model-drift verdict here (model, psi_max, score_psi, offending
features), which is the continual-boosting retrain/rollback trigger.
r19 closes that loop: the continual package (continual/scheduler.py,
continual/publish.py) journals ``retrain_triggered``/``retrain_skipped``
(reason: in_flight/budget/cooldown/retry_budget_exhausted/no_profile/
unknown_model/artifact_unreadable)/``retrain_complete``/
``retrain_failed``/``publish_error`` and the probation family
``push_probation``/``push_failed``/``generation_promoted`` (verdict:
clear/expired)/``generation_rolled_back`` (the rollback RE-PUSHES the
prior artifact — the registry is never mutated in place).
"""

from __future__ import annotations

import json
import threading
import time


class RunJournal:
    """Append one JSON event line per supervision event to ``path``.

    Lock contract (r15): a journal is written from more than one thread —
    the training supervisor's loop plus the tripwire listener it
    registers, and the fleet supervisor's monitor plus its per-slot
    recovery threads — so ``_lock`` (declared below) makes each
    ``event()`` line atomic: serialize + write happen under it, and
    ``close()`` takes the same lock so a concurrent event can never hit a
    closed handle.  The r14 review found the unlocked-write race by hand;
    the guarded-by lint and the schedule harness now pin the fix.
    Owners that also swap the journal OBJECT itself (the fleet
    supervisor's owned-journal close) keep their own outer lock for that
    — the two never nest in the journal->owner direction."""

    GUARDED_BY = {"_fh": "_lock"}

    def __init__(self, path: str):
        self.path = path
        self._t0 = time.perf_counter()
        self._lock = threading.Lock()
        self._fh = open(path, "a", buffering=1)

    def event(self, kind: str, /, **fields) -> None:
        rec = {"event": kind,
               "elapsed_s": round(time.perf_counter() - self._t0, 6)}
        rec.update(fields)
        line = json.dumps(rec) + "\n"
        with self._lock:
            self._fh.write(line)

    def close(self) -> None:
        with self._lock:
            self._fh.close()

    def __enter__(self) -> "RunJournal":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    @staticmethod
    def read(path: str) -> list[dict]:
        """Parse a journal back into its event dicts (tests/smokes)."""
        with open(path) as f:
            return [json.loads(line) for line in f if line.strip()]

    @classmethod
    def read_last_run(cls, path: str) -> list[dict]:
        """Events of the LAST supervised run only.  The file is append-only
        across invocations, so any consumer counting faults/resumes must
        slice after the final run_start or it inherits a prior invocation's
        records (scripts/headline_10m.py reads artifact counts this way)."""
        events = cls.read(path)
        starts = [i for i, e in enumerate(events)
                  if e["event"] == "run_start"]
        return events[starts[-1]:] if starts else events
