"""dryad_tpu.resilience — supervised training for long runs.

The subsystem that makes the recorded tunnel/device fault classes
survivable without a human: fault classification + deterministic
injection (faults.py), retry/degradation policy (policy.py), the
supervising driver (supervisor.py), and the append-only run journal
(journal.py).  Entry point::

    from dryad_tpu.resilience import supervise_train
    booster = supervise_train(params, ds, [vds], checkpoint_dir="ck/",
                              checkpoint_every=50, journal="run.jsonl")

or ``python -m dryad_tpu train ... --supervise --journal run.jsonl``.
"""

from dryad_tpu.resilience.faults import (
    DEVICE_UNAVAILABLE,
    FETCH_DEATH,
    OOM,
    PREEMPTION,
    REJECT_503,
    REPLICA_CRASH,
    REPLICA_CRASH_EXIT,
    REPLICA_KINDS,
    RETRYABLE,
    SLOW_HEALTH,
    UNKNOWN,
    FaultInjector,
    FaultPoint,
    InjectedReject,
    classify_fault,
    make_fault,
)
from dryad_tpu.resilience.journal import RunJournal
from dryad_tpu.resilience.policy import ChunkCapPolicy, RetryPolicy
from dryad_tpu.resilience.supervisor import FaultError, supervise_train

__all__ = [
    "DEVICE_UNAVAILABLE", "FETCH_DEATH", "OOM", "PREEMPTION", "RETRYABLE",
    "REJECT_503", "REPLICA_CRASH", "REPLICA_CRASH_EXIT", "REPLICA_KINDS",
    "SLOW_HEALTH", "UNKNOWN", "FaultInjector", "FaultPoint", "InjectedReject",
    "classify_fault", "make_fault",
    "RunJournal", "ChunkCapPolicy", "RetryPolicy", "FaultError",
    "supervise_train",
]
