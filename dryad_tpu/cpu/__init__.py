from dryad_tpu.cpu.trainer import train_cpu
from dryad_tpu.cpu.predict import predict_binned_cpu

__all__ = ["train_cpu", "predict_binned_cpu"]
