"""Exact TreeSHAP feature contributions (``predict(pred_contrib=True)``).

Implements the polynomial-time exact SHAP algorithm for tree ensembles
(Lundberg et al., "Consistent Individualized Feature Attribution for Tree
Ensembles": the EXTEND/UNWIND path-weight recursion), using the per-node
training row counts ("cover") the round-4 tree format records.  For every
row, the returned (F + 1) vector satisfies the SHAP efficiency property
EXACTLY: contributions + bias column == raw prediction (pinned by test).

Complexity O(rows · trees · leaves · depth²) in Python — intended for
explanation-sized batches (hundreds to a few thousand rows), not bulk
scoring.  Row routing decisions (numeric thresholds, learned missing
directions, categorical bitsets) are precomputed VECTORIZED per node with
the same rules as ``cpu/predict.py``, so the recursion itself never
re-derives routing.
"""

from __future__ import annotations

import numpy as np


def _node_decisions(trees: dict, t: int, Xb: np.ndarray) -> np.ndarray:
    """(N, M) bool: would row n go LEFT at node m (same rules as predict)."""
    feature = trees["feature"][t]
    threshold = trees["threshold"][t]
    is_cat = trees["is_cat"][t]
    cat_bs = trees["cat_bitset"][t]
    dleft = (trees["default_left"][t] if "default_left" in trees
             else np.ones_like(feature, bool))
    N = Xb.shape[0]
    M = feature.shape[0]
    f = np.maximum(feature, 0)
    bins = Xb[:, f].astype(np.int64)                    # (N, M)
    go_left = bins <= threshold[None, :]
    go_left &= dleft[None, :] | (bins != 0)
    word = cat_bs[np.arange(M)[None, :],
                  np.minimum(bins >> 5, cat_bs.shape[1] - 1)]
    cat_left = (word >> (bins & 31).astype(np.uint32)) & 1 > 0
    return np.where(is_cat[None, :], cat_left, go_left)


def _tree_shap_one(feature, left, right, value, cover, go_left_row, phi):
    """Accumulate one tree's exact SHAP values for one row into ``phi``.

    Path state arrays are preallocated to depth+2 and passed down by
    copy-on-extend (the textbook algorithm); ``d`` indexes the path depth.
    """
    def extend(pd, pz, po, pw, z, o, i):
        d = pd.shape[0]
        pd2 = np.empty((d + 1,), np.int32)
        pz2 = np.empty((d + 1,), np.float64)
        po2 = np.empty((d + 1,), np.float64)
        pw2 = np.empty((d + 1,), np.float64)
        pd2[:d], pz2[:d], po2[:d], pw2[:d] = pd, pz, po, pw
        pd2[d], pz2[d], po2[d] = i, z, o
        pw2[d] = 1.0 if d == 0 else 0.0
        for j in range(d - 1, -1, -1):
            pw2[j + 1] += o * pw2[j] * (j + 1) / (d + 1)
            pw2[j] = z * pw2[j] * (d - j) / (d + 1)
        return pd2, pz2, po2, pw2

    def unwound_sum(pd, pz, po, pw, i):
        d = pd.shape[0] - 1
        o, z = po[i], pz[i]
        total = 0.0
        nxt = pw[d]
        for j in range(d - 1, -1, -1):
            if o != 0.0:
                tmp = nxt * (d + 1) / ((j + 1) * o)
                total += tmp
                nxt = pw[j] - tmp * z * (d - j) / (d + 1)
            else:
                total += pw[j] / (z * (d - j) / (d + 1))
        return total

    def unwind(pd, pz, po, pw, i):
        d = pd.shape[0] - 1
        o, z = po[i], pz[i]
        pd2 = np.delete(pd, i)
        pz2 = np.delete(pz, i)
        po2 = np.delete(po, i)
        nxt = pw[d]
        w = pw.copy()
        for j in range(d - 1, -1, -1):
            if o != 0.0:
                tmp = nxt * (d + 1) / ((j + 1) * o)
                nxt = w[j] - tmp * z * (d - j) / (d + 1)
                w[j] = tmp
            else:
                w[j] = w[j] * (d + 1) / (z * (d - j))
        # weights are positional on the SHORTENED path — no index shift
        # (reference tree_shap implementation)
        return pd2, pz2, po2, w[:d]

    def recurse(node, pd, pz, po, pw, z, o, i):
        pd, pz, po, pw = extend(pd, pz, po, pw, z, o, i)
        if feature[node] < 0:                            # leaf
            v = float(value[node])
            for k in range(1, pd.shape[0]):
                s = unwound_sum(pd, pz, po, pw, k)
                phi[pd[k]] += s * (po[k] - pz[k]) * v
            return
        hot = left[node] if go_left_row[node] else right[node]
        cold = right[node] if go_left_row[node] else left[node]
        cn = max(float(cover[node]), 1e-12)
        iz, io = 1.0, 1.0
        # if this feature already appears on the path, unwind it first
        pathf = np.nonzero(pd[1:] == feature[node])[0]
        if pathf.size:
            k = int(pathf[0]) + 1
            iz, io = float(pz[k]), float(po[k])
            pd, pz, po, pw = unwind(pd, pz, po, pw, k)
        recurse(hot, pd, pz, po, pw,
                iz * float(cover[hot]) / cn, io, int(feature[node]))
        recurse(cold, pd, pz, po, pw,
                iz * float(cover[cold]) / cn, 0.0, int(feature[node]))

    recurse(0,
            np.empty((0,), np.int32), np.empty((0,), np.float64),
            np.empty((0,), np.float64), np.empty((0,), np.float64),
            1.0, 1.0, -1)


def predict_contrib(booster, Xb: np.ndarray,
                    num_iteration: int | None = None) -> np.ndarray:
    """Exact SHAP values -> (N, K, F+1) (squeezed to (N, F+1) for K=1).

    Column F is the bias (expected value): init_score + Σ_t cover-weighted
    mean leaf value; contributions + bias == raw prediction exactly (up to
    f64 summation of f32 leaf values).
    """
    K = booster.num_outputs
    N = Xb.shape[0]
    F = booster.mapper.num_features
    if num_iteration is None:
        n_iter = (booster.best_iteration if booster.best_iteration > 0
                  else booster.num_iterations)
    else:
        n_iter = min(num_iteration, booster.num_iterations)
    trees = booster.tree_arrays()
    # EVERY used tree needs a positive root cover — a booster resumed from
    # a pre-cover checkpoint has real covers only on its newer trees, and
    # zero covers would silently divide to NaN in the recursion
    root_covers = np.asarray(trees["cover"])[: n_iter * K, 0]
    if root_covers.size and float(root_covers.min()) <= 0:
        raise ValueError(
            "pred_contrib needs per-node covers on every tree; this model "
            "(or the checkpoint it resumed from) was saved by a version "
            "that did not record them — retrain to enable SHAP")
    out = np.zeros((N, K, F + 1), np.float64)
    out[:, :, F] += np.asarray(booster.init_score, np.float64)[None, :]
    depth_bound = max(booster.max_depth_seen, 1)

    for t in range(n_iter * K):
        k = t % K
        feature = trees["feature"][t]
        left, right = trees["left"][t], trees["right"][t]
        value = trees["value"][t]
        cover = trees["cover"][t].astype(np.float64)
        # expected value of this tree under the training distribution:
        # cover-weighted mean over leaves (computed once, iteratively)
        ev = _expected_value(feature, left, right, value, cover, depth_bound)
        out[:, k, F] += ev
        decisions = _node_decisions(trees, t, Xb)
        for n in range(N):
            _tree_shap_one(feature, left, right, value, cover,
                           decisions[n], out[n, k])
    if booster.params.boosting == "rf" and n_iter > 0:
        # rf predictions average the trees (config.py), so every per-tree
        # term — contributions AND tree expectations — scales by 1/n while
        # the init_score bias term does not; the efficiency property
        # (contributions + bias == raw prediction) is preserved exactly
        init = np.asarray(booster.init_score, np.float64)
        out /= n_iter
        out[:, :, F] += init[None, :] * (1.0 - 1.0 / n_iter)
    return out[:, 0] if K == 1 else out


def _expected_value(feature, left, right, value, cover, depth_bound):
    """Cover-weighted expectation of the tree's output at the root."""
    M = feature.shape[0]
    ev = value.astype(np.float64).copy()
    # propagate bottom-up: depth_bound passes of child mixing
    for _ in range(depth_bound):
        internal = feature >= 0
        cl = cover[np.maximum(left, 0)]
        cr = cover[np.maximum(right, 0)]
        tot = np.maximum(cl + cr, 1e-12)
        mixed = (cl * ev[np.maximum(left, 0)]
                 + cr * ev[np.maximum(right, 0)]) / tot
        ev = np.where(internal, mixed, ev)
    return float(ev[0])
