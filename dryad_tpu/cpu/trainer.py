"""Canonical CPU leaf-wise trainer — the parity oracle (BASELINE.json:5).

Pure numpy, deterministic.  Defines the exact tree-construction semantics the
TPU engine replicates (SURVEY.md §7 step 1):

* leaf-wise growth: split the leaf with the globally best gain next; the left
  child keeps the parent's leaf slot, the right child takes the next free
  slot; ties broken by lowest slot index (np.argmax first-max).
* child node stats come from the parent histogram's prefix at the chosen
  split (not from re-summing rows), exactly as the device path derives them.
* histogram subtraction (child = parent − sibling) on the larger child when
  enabled — the smaller child is built directly.
* bagging/colsample masks are drawn host-side from Philox(seed, iteration)
  and are shared verbatim with the TPU path, so sampling never breaks parity.
"""

from __future__ import annotations

import time
from typing import Callable, Optional

import numpy as np

from dryad_tpu.booster import CAT_WORDS, Booster, empty_tree_arrays
from dryad_tpu.config import Params, effective_depth_params
from dryad_tpu.cpu.histogram import (
    build_hist,
    cat_members_to_bitset,
    find_best_split,
    leaf_output,
)
from dryad_tpu.cpu.predict import predict_tree_leaves
from dryad_tpu.dataset import Dataset
from dryad_tpu.objectives import get_objective

# per-stage wall/count series (dryad_tpu/obs): host-side clocks around
# work this loop already does — zero-cost when the registry is disabled
from dryad_tpu.obs.registry import default_registry
from dryad_tpu.obs.spans import record as record_span
from dryad_tpu.obs.spans import span


def _binned_or_view(ds: Dataset):
    """The trainer's matrix handle: the resident ``X_binned`` array, or a
    StreamedDataset's bounded-read stand-in (identical access semantics on
    the patterns this file uses — see data/stream_dataset._StreamedMatrix)."""
    return ds.binned_view() if getattr(ds, "is_streamed", False) else ds.X_binned


def tree_leaves_any(trees, Xb, t: int, depth_bound: int) -> np.ndarray:
    """``predict_tree_leaves`` over a resident matrix OR a streamed view.

    The traversal is row-elementwise, so invoking it per chunk and
    concatenating is bitwise the resident result — full-sweep score
    updates/replays stay exact without ever materializing (N, F)."""
    it = getattr(Xb, "iter_chunks", None)
    if it is None:
        return predict_tree_leaves(trees, Xb, t, depth_bound)
    leaves = np.empty(Xb.shape[0], np.int64)
    for lo, hi, buf in it():
        leaves[lo:hi] = predict_tree_leaves(trees, buf, t, depth_bound)
    return leaves


def tree_leaves_rows(trees, Xb, rows: np.ndarray, t: int,
                     depth_bound: int) -> np.ndarray:
    """Leaves for a row SUBSET: positional chunking of ``rows`` keeps a
    streamed gather bounded (a near-full bag would otherwise materialize
    (N, F)); resident matrices take the plain fancy-index path."""
    if getattr(Xb, "iter_chunks", None) is None:
        return predict_tree_leaves(trees, Xb[rows], t, depth_bound)
    step = max(1, int(getattr(Xb, "chunk_rows", 1 << 20)))
    lv = np.empty(rows.size, np.int64)
    for s in range(0, rows.size, step):
        lv[s:s + step] = predict_tree_leaves(
            trees, Xb[rows[s:s + step]], t, depth_bound)
    return lv


def goss_uniform(params: Params, iteration: int, num_rows: int) -> np.ndarray:
    """Per-iteration uniforms for the GOSS Bernoulli pick: a counter-based
    murmur3-finalizer hash of (seed, iteration, row id).

    A pure u32 function (no PRNG state, no block structure) so the DEVICE
    can generate the very same draws inside the chunked boosting program
    (``engine/train._goss_uniform_dev`` — bit-identity pinned by
    ``test_goss_monotone.test_goss_uniform_device_parity``); the old host
    Philox draw forced GOSS onto per-iteration dispatch because uploading
    (N,) uniforms per iteration costs GBs at 10M rows (VERDICT r3 #4).
    The 24-bit mantissa uniform is exact in f32, so boundary rows classify
    identically on every backend.
    """
    M1, M2 = 0x85EBCA6B, 0xC2B2AE35
    key = (params.seed * 0x9E3779B9 + iteration * 0x7FEB352D + 0x165667B1) \
        % (1 << 32)
    key ^= key >> 16
    key = (key * M1) % (1 << 32)
    key ^= key >> 13
    key = (key * M2) % (1 << 32)
    key ^= key >> 16
    x = np.arange(num_rows, dtype=np.uint32) * np.uint32(0x9E3779B9)
    x ^= np.uint32(key)
    x ^= x >> np.uint32(16)
    x = x * np.uint32(M1)
    x ^= x >> np.uint32(13)
    x = x * np.uint32(M2)
    x ^= x >> np.uint32(16)
    return (x >> np.uint32(8)).astype(np.float32) * np.float32(1.0 / (1 << 24))


def goss_select_np(params: Params, g_all: np.ndarray, u: np.ndarray):
    """Canonical GOSS selection -> (mask, weight).

    Keep every row whose gradient magnitude reaches the top_rate quantile
    (ties included — deterministic), Bernoulli-pick the rest with the shared
    uniforms, amplify picked rows by (1-top)/other so histogram sums stay
    unbiased (the GOSS estimator).
    """
    p = params
    # f32 throughout — bit-matches the device selection (_goss_jit) so
    # boundary rows classify identically on both backends
    absg = np.sqrt((g_all.astype(np.float32) ** 2).sum(axis=1, dtype=np.float32))
    N = absg.shape[0]
    top_n = max(1, int(round(p.goss_top_rate * N)))
    thr = np.sort(absg)[N - top_n]
    is_top = absg >= thr
    n_top = int(is_top.sum())
    p_pick = min(np.float32(1.0),
                 np.float32(p.goss_other_rate * N) / np.float32(max(N - n_top, 1)))
    picked = (~is_top) & (u < p_pick)
    amp = (1.0 - p.goss_top_rate) / p.goss_other_rate
    weight = np.where(picked, amp, 1.0)
    return is_top | picked, weight


def normalize_valids(valid) -> list[tuple[str, Dataset]]:
    """Accept None | Dataset | list[Dataset | (name, Dataset)] → [(name, ds)].

    A single anonymous set keeps the historic name ``valid`` (JSONL keys
    like ``valid_auc``); multiple anonymous sets become ``valid_0``,
    ``valid_1``, ... (LightGBM-style).  Early stopping always watches the
    first set."""
    if valid is None:
        return []
    if isinstance(valid, Dataset):
        return [("valid", valid)]
    out: list[tuple[str, Dataset]] = []
    single = len(valid) == 1
    for i, v in enumerate(valid):
        if isinstance(v, tuple):
            out.append((str(v[0]), v[1]))
        else:
            out.append(("valid" if single else f"valid_{i}", v))
    return out


def update_best(p, best_iteration, best_value, stale, iteration, value,
                higher):
    """Early-stopping bookkeeping shared by every eval path (CPU sync,
    device sync, device deferred replay) — one definition so the three can
    never diverge.  Returns (best_iteration, best_value, stale).

    DART is a no-op BY CONSTRUCTION here (not at the call sites, so a new
    caller can't forget the gate — ADVICE r4): drops after the best
    iteration rescale EARLIER trees in place, so the prefix ending at
    best_iteration is not the ensemble that produced the best score and
    predict must never truncate there."""
    if p.boosting == "dart":
        return best_iteration, best_value, stale
    improved = best_value is None or (
        value > best_value if higher else value < best_value)
    if improved:
        return iteration + 1, value, 0
    return best_iteration, best_value, stale + 1


def type1_quantile(sorted_r: np.ndarray, alpha: float) -> np.float32:
    """Type-1 (inverse-CDF, no interpolation) quantile pick — THE renewal
    order-statistic convention: index clip(ceil(f32(alpha)·f32(cnt)) - 1,
    0, cnt-1) into the sorted residuals.  A pure element selection with
    the index arithmetic in f32, so the CPU trainer, Booster.refit and the
    device mirror (engine/train._renew_values) choose the bitwise-identical
    value for identical inputs."""
    cnt = sorted_r.size
    kf = np.ceil(np.float32(alpha) * np.float32(cnt))
    kidx = min(max(int(kf) - 1, 0), cnt - 1)
    return np.float32(sorted_r[kidx])


def renew_leaf_values_np(out, t, r, lv, alpha, lr):
    """L1-family leaf renewal, CPU mirror of engine/train._renew_values:
    replace each leaf's value with the type-1 alpha-quantile of its in-bag
    residuals ``r`` times the shrinkage (see type1_quantile)."""
    for node in np.unique(lv):
        rs = np.sort(r[lv == node])
        out["value"][t, node] = type1_quantile(rs, alpha) * np.float32(lr)


def sample_masks(params: Params, iteration: int, num_rows: int, num_features: int):
    """Host-side deterministic bagging/colsample masks, shared by both backends."""
    row_mask = None
    feat_mask = None
    if params.subsample < 1.0 or params.colsample < 1.0:
        rng = np.random.Generator(np.random.Philox(key=params.seed, counter=iteration))
        if params.subsample < 1.0:
            row_mask = rng.uniform(size=num_rows) < params.subsample
        if params.colsample < 1.0:
            k = max(1, int(round(params.colsample * num_features)))
            feat_mask = np.zeros(num_features, bool)
            feat_mask[rng.permutation(num_features)[:k]] = True
    return row_mask, feat_mask


def dart_drop_set(params: Params, iteration: int, n_prev: int) -> np.ndarray:
    """Deterministic DART drop set (prev-iteration ids), shared by both
    backends (Philox keyed like sample_masks, distinct counter stream).

    With prob ``skip_drop`` nothing drops; else each previous iteration
    drops independently with prob ``drop_rate``, capped at ``max_drop``
    (uniform subsample of the selection when over)."""
    if n_prev == 0 or params.drop_rate <= 0.0:
        return np.empty(0, np.int64)
    rng = np.random.Generator(np.random.Philox(
        key=params.seed, counter=(1 << 32) + iteration))
    if rng.uniform() < params.skip_drop:
        return np.empty(0, np.int64)
    sel = np.nonzero(rng.uniform(size=n_prev) < params.drop_rate)[0]
    if sel.size > params.max_drop:
        sel = np.sort(rng.permutation(sel)[: params.max_drop])
    return sel.astype(np.int64)


class _TreeGrower:
    """Grows one tree; mirrors engine/grower.py step-for-step."""

    def __init__(self, params: Params, Xb: np.ndarray, total_bins: int,
                 is_categorical: np.ndarray, learn_missing: bool = False,
                 bundled_mask: np.ndarray | None = None):
        self.p = params
        self.Xb = Xb
        self.B = total_bins
        self.is_cat_feat = is_categorical
        self.learn_missing = bool(learn_missing)
        self.bundled_mask = bundled_mask
        self.mono = None
        if params.monotone_constraints and any(params.monotone_constraints):
            # pad/truncate to F (same policy as the device _monotone_array)
            F = Xb.shape[1]
            self.mono = np.zeros(F, np.float64)
            k = min(F, len(params.monotone_constraints))
            self.mono[:k] = params.monotone_constraints[:k]

    def grow(
        self,
        g: np.ndarray,
        h: np.ndarray,
        rows: np.ndarray,
        feat_mask: Optional[np.ndarray],
        out: dict[str, np.ndarray],
        t: int,
    ) -> int:
        """Fill tree t's row of the SoA arrays; returns max depth reached."""
        p = self.p
        L = p.effective_num_leaves
        max_depth = p.max_depth if p.max_depth > 0 else L  # depth cap
        # per-leaf-slot state
        leaf_node = np.full(L, -1, np.int64)
        leaf_gain = np.full(L, -np.inf)
        leaf_rows: list[Optional[np.ndarray]] = [None] * L
        leaf_hist: list[Optional[np.ndarray]] = [None] * L
        leaf_split: list = [None] * L
        leaf_G = np.zeros(L)
        leaf_H = np.zeros(L)
        leaf_depth = np.zeros(L, np.int64)
        # monotone output bounds per slot (f32 values, as the device tracks)
        leaf_lo = np.full(L, -np.inf, np.float32)
        leaf_hi = np.full(L, np.inf, np.float32)

        hist0 = build_hist(self.Xb, g, h, rows, self.B)
        # canonical leaf totals: feature-0 histogram sums (device derives the
        # same way, keeping parent/child stat bookkeeping backend-identical)
        G0, H0, C0 = float(hist0[0, 0].sum()), float(hist0[1, 0].sum()), float(rows.size)
        leaf_node[0], leaf_rows[0], leaf_hist[0] = 0, rows, hist0
        out["cover"][t, 0] = C0
        leaf_G[0], leaf_H[0] = G0, H0
        leaf_split[0] = self._best(hist0, G0, H0, C0, 0, max_depth, feat_mask,
                                   leaf_lo[0], leaf_hi[0])
        leaf_gain[0] = leaf_split[0].gain if leaf_split[0] else -np.inf

        num_nodes, max_seen_depth = 1, 0
        depthwise = p.growth == "depthwise"
        for k in range(L - 1):
            if depthwise:
                # split shallowest level first (best gain within the level);
                # realizes true depth-wise growth in the leaf-wise machinery
                finite = np.isfinite(leaf_gain)
                if not finite.any():
                    break
                dmin = leaf_depth[finite].min()
                s = int(np.argmax(np.where(finite & (leaf_depth == dmin), leaf_gain, -np.inf)))
            else:
                s = int(np.argmax(leaf_gain))
            if not np.isfinite(leaf_gain[s]):
                break
            split = leaf_split[s]
            parent = int(leaf_node[s])
            prows = leaf_rows[s]
            phist = leaf_hist[s]
            pG, pH = leaf_G[s], leaf_H[s]
            depth = int(leaf_depth[s])

            bins_f = self.Xb[prows, split.feature].astype(np.int64)
            if split.is_cat:
                go_left = np.isin(bins_f, split.cat_members)
            else:
                go_left = bins_f <= split.threshold
                if not split.default_left:
                    go_left &= bins_f != 0  # missing learned to go right
            rows_l, rows_r = prows[go_left], prows[~go_left]

            left_id, right_id = num_nodes, num_nodes + 1
            num_nodes += 2
            out["feature"][t, parent] = split.feature
            out["threshold"][t, parent] = split.threshold if not split.is_cat else 0
            out["left"][t, parent] = left_id
            out["right"][t, parent] = right_id
            out["gain"][t, parent] = split.gain
            out["default_left"][t, parent] = split.default_left or split.is_cat
            if split.is_cat:
                out["is_cat"][t, parent] = True
                out["cat_bitset"][t, parent] = cat_members_to_bitset(split.cat_members, CAT_WORDS)
            max_seen_depth = max(max_seen_depth, depth + 1)

            # child stats from the parent-histogram prefix (canonical contract)
            GL, HL, CL = split.g_left, split.h_left, split.c_left
            GR, HR, CR = pG - GL, pH - HL, float(prows.size) - CL
            out["cover"][t, left_id] = CL
            out["cover"][t, right_id] = CR

            # monotone bounds for the children: on a ±1 split feature the
            # midpoint of the clamped child outputs separates the subtrees
            # (LightGBM "basic" mode); m=0 splits inherit the parent bounds.
            # f32 arithmetic mirrors the device grower bit for bit.
            lo_p, hi_p = leaf_lo[s], leaf_hi[s]
            lo_l = lo_r = lo_p
            hi_l = hi_r = hi_p
            if self.mono is not None:
                m = self.mono[split.feature]
                if m != 0:
                    lam32 = np.float32(self.p.lambda_l2)
                    wl = np.float32(min(max(
                        np.float32(-(np.float32(GL) / (np.float32(HL) + lam32))), lo_p), hi_p))
                    wr = np.float32(min(max(
                        np.float32(-(np.float32(GR) / (np.float32(HR) + lam32))), lo_p), hi_p))
                    mid = np.float32(np.float32(0.5) * (wl + wr))
                    if m > 0:
                        hi_l, lo_r = mid, mid
                    else:
                        lo_l, hi_r = mid, mid

            # histograms: smaller child direct, larger by subtraction
            left_smaller = rows_l.size <= rows_r.size
            srows = rows_l if left_smaller else rows_r
            shist = build_hist(self.Xb, g, h, srows, self.B)
            if self.p.hist_subtraction:
                ohist = phist - shist
            else:
                ohist = build_hist(self.Xb, g, h, rows_r if left_smaller else rows_l, self.B)
            hist_l, hist_r = (shist, ohist) if left_smaller else (ohist, shist)

            sl, sr = s, k + 1
            for slot, node_id, r_, hist_, G_, H_, C_, lo_, hi_ in (
                (sl, left_id, rows_l, hist_l, GL, HL, CL, lo_l, hi_l),
                (sr, right_id, rows_r, hist_r, GR, HR, CR, lo_r, hi_r),
            ):
                leaf_node[slot] = node_id
                leaf_rows[slot] = r_
                leaf_hist[slot] = hist_
                leaf_G[slot], leaf_H[slot] = G_, H_
                leaf_depth[slot] = depth + 1
                leaf_lo[slot], leaf_hi[slot] = lo_, hi_
                sp = self._best(hist_, G_, H_, C_, depth + 1, max_depth, feat_mask,
                                lo_, hi_)
                leaf_split[slot] = sp
                leaf_gain[slot] = sp.gain if sp else -np.inf

        # finalize leaf values
        for slot in range(L):
            node = int(leaf_node[slot])
            if node < 0:
                continue
            out["feature"][t, node] = -1
            out["value"][t, node] = leaf_output(
                leaf_G[slot], leaf_H[slot], self.p.lambda_l2,
                self.p.effective_learning_rate,
                leaf_lo[slot], leaf_hi[slot],
            )
        return max_seen_depth

    def _best(self, hist, G, H, C, depth, max_depth, feat_mask,
              lo=-np.inf, hi=np.inf):
        if depth >= max_depth or C < 2 * self.p.min_data_in_leaf:
            return None
        return find_best_split(
            hist, G, H, C,
            lambda_l2=self.p.lambda_l2,
            min_child_weight=self.p.min_child_weight,
            min_data_in_leaf=self.p.min_data_in_leaf,
            min_split_gain=self.p.min_split_gain,
            feature_mask=feat_mask,
            is_categorical=self.is_cat_feat,
            monotone=self.mono,
            lo=float(lo),
            hi=float(hi),
            learn_missing=self.learn_missing,
            bundled_mask=self.bundled_mask,
        )


def train_cpu(
    params: Params,
    data: Dataset,
    valid: Optional[Dataset] = None,
    *,
    num_trees: Optional[int] = None,
    init_booster: Optional[Booster] = None,
    callback: Optional[Callable[[int, dict], None]] = None,
    checkpointer=None,
    chunk_hook: Optional[Callable[[str, int], None]] = None,
) -> Booster:
    """Reference trainer: ``dryad.train`` semantics on the CPU backend.

    ``chunk_hook(site, iteration)`` mirrors the device trainer's loop
    observation points (resilience/faults.py injection + journaling) on
    this backend's per-iteration loop: ``"dispatch"`` at each iteration
    start, ``"fetch"`` at each checkpoint/final materialization — the
    sites the supervised-run fault classes attach to."""
    p = params.validate()
    Xb = _binned_or_view(data)
    y = data.y
    N, F = Xb.shape
    B = data.mapper.total_bins
    # documented max_depth=-1 policy — the EXACT (jax-free) mapping the
    # device trainer applies (config.effective_depth_params), so the two
    # backends keep growing identical trees on the default config
    p = effective_depth_params(p, F, B, N)
    obj = get_objective(p)
    K = p.num_outputs
    is_cat = data.mapper.is_categorical
    T = (num_trees if num_trees is not None else p.num_trees) * K

    out = empty_tree_arrays(T, p.max_nodes)
    init = np.asarray(obj.init_score(y, data.weight), np.float32).reshape(-1)
    if init_booster is not None:
        # the carried base score is part of the model: a continuation (and
        # especially an r19 warm-start append on FRESH rows) must not
        # re-derive it from the current label distribution, or a 0-tree
        # append would shift every prediction.  Checkpoint resume is
        # unchanged bitwise — same labels produced the same init.
        init = np.asarray(init_booster.init_score, np.float32).reshape(-1)
    score = np.broadcast_to(init, (N, K)).astype(np.float32).copy()
    qoff = data.query_offsets
    bundled_np = getattr(data.mapper, "bundled_mask", None)
    # the mask only matters when the missing-right plane is scanned at all
    bundled = (bundled_np if data.has_missing and bundled_np is not None
               and bundled_np.any() else None)
    grower = _TreeGrower(p, Xb, B, is_cat, learn_missing=data.has_missing,
                         bundled_mask=bundled)
    max_depth_seen = 0

    start_iter = 0
    if init_booster is not None:
        # resume: replay prior trees' scores, then keep growing (SURVEY.md §5)
        prev = init_booster
        if prev.params.max_nodes != p.max_nodes or prev.num_outputs != K:
            raise ValueError(
                "init_booster is incompatible: num_leaves/max_depth/num_class must match "
                f"(prev max_nodes={prev.params.max_nodes}, new={p.max_nodes}; "
                f"prev outputs={prev.num_outputs}, new={K})"
            )
        if prev.num_total_trees > T:
            raise ValueError(
                f"init_booster already has {prev.num_iterations} iterations; "
                f"new num_trees={T // K} must be >= that"
            )
        if ("rf" in (prev.params.boosting, p.boosting)
                and prev.params.boosting != p.boosting):
            raise ValueError(
                "cannot continue training across rf and non-rf boosting: "
                "rf predictions AVERAGE the trees, so a mixed tree table "
                "has no sound aggregation")
        for t in range(prev.num_total_trees):
            leaves = tree_leaves_any(prev.tree_arrays(), Xb, t, prev.max_depth_seen)
            score[:, t % K] += prev.value[t, leaves]
        for k_arr in out:
            out[k_arr][: prev.num_total_trees] = prev.tree_arrays()[k_arr]
        start_iter = prev.num_iterations
        max_depth_seen = prev.max_depth_seen

    # validation / early stopping state (SURVEY.md §5 metrics stream);
    # every set is scored, the FIRST drives early stopping
    valids = normalize_valids(valid)
    vXbs = [_binned_or_view(v) for _, v in valids]
    vscores = [
        np.broadcast_to(init, (vXb.shape[0], K)).astype(np.float32).copy()
        for vXb in vXbs
    ]
    best_iteration, best_value, stale = -1, None, 0
    # full per-set metric history, mirrored onto the booster exactly like
    # the device trainer's train_state["eval_history"] (same keys), so
    # cross-backend consumers (dryad.cv, callbacks) see one surface
    eval_history: dict[str, list] = {}
    if init_booster is not None:
        # resume continues the eval/early-stop state exactly where it stopped
        for vXb, vscore in zip(vXbs, vscores):
            for t in range(init_booster.num_total_trees):
                vleaves = tree_leaves_any(
                    init_booster.tree_arrays(), vXb, t, init_booster.max_depth_seen)
                vscore[:, t % K] += init_booster.value[t, vleaves]
        if p.boosting != "dart":
            best_iteration = init_booster.best_iteration
            best_value = init_booster.train_state.get("best_value")
            stale = init_booster.train_state.get("stale", 0)
        # else: a DART continuation from a booster that recorded
        # best_iteration (e.g. gbdt-with-early-stopping init) must NOT
        # inherit it — the coming drops rescale trees inside that prefix,
        # so truncating predict there would score a model that never
        # existed (ADVICE r4); DART's own checkpoints always carry -1
        if init_booster.train_state.get("eval_history"):
            # resume carries the prior segment's history (device-trainer
            # convention) so the merged run matches the uninterrupted one
            eval_history = {k: list(v) for k, v in
                            init_booster.train_state["eval_history"].items()}

    def _grad_hess(sc):
        if p.objective == "lambdarank":
            g_, h_ = obj.grad_hess_np(sc[:, 0], y, data.weight,
                                      query_offsets=qoff)
            return g_[:, None], h_[:, None]
        if K > 1:
            return obj.grad_hess_np(sc, y, data.weight)
        g_, h_ = obj.grad_hess_np(sc[:, 0], y, data.weight)
        return g_[:, None], h_[:, None]

    # rf: every tree fits the gradients at the CONSTANT init score — trees
    # de-correlate only through the per-iteration bag, never through
    # residual chaining — so grad/hess are computed ONCE (config.py rf note)
    rf_gh = (_grad_hess(np.broadcast_to(init, (N, K)).astype(np.float32))
             if p.boosting == "rf" else None)

    # L1-family leaf renewal — the gate lives wholly in renew_alpha
    from dryad_tpu.objectives import renew_alpha as _obj_renew_alpha

    renew_a = _obj_renew_alpha(p, weighted=data.weight is not None)

    all_rows = np.arange(N, dtype=np.int64)
    # span series use record() rather than a with-block: the loop body has
    # break edges a context manager would force a reindent across
    _obs = default_registry()
    # bound handle per the registry's hot-loop contract (no per-iteration
    # family lookup); bound on FIRST enabled use — eager binding would
    # register the family on a disabled registry
    _obs_iter = None
    for it in range(start_iter, T // K):
        # resuming from a checkpoint taken at the early-stop boundary must
        # not grow past it (the restored stale counter already says stop)
        if (valids and p.early_stopping_rounds
                and stale >= p.early_stopping_rounds):
            T = it * K
            break
        if chunk_hook is not None:
            chunk_hook("dispatch", it)
        # None (not 0.0) when disabled: an enable() landing mid-iteration
        # must not record a since-process-boot wall into the counters
        _t_it = time.perf_counter() if _obs.enabled else None
        # ---- DART: drop previous iterations before computing gradients ----
        # paper semantics (see config); arithmetic order mirrors the device
        # trainer exactly (score - drop; grads; score - drop/(k+1);
        # new tree pre-scaled by 1/(k+1); dropped values *= k/(k+1))
        drop = (dart_drop_set(p, it, it) if p.boosting == "dart"
                else np.empty(0, np.int64))
        value_scale = np.float32(1.0)
        if drop.size:
            kd = drop.size
            value_scale = np.float32(1.0 / (kd + 1))
            factor_drop = np.float32(kd / (kd + 1.0))
            dcontrib = np.zeros_like(score)
            for d_it in drop:
                for c in range(K):
                    td = int(d_it) * K + c
                    lv = tree_leaves_any(out, Xb, td, max(max_depth_seen, 1))
                    dcontrib[:, c] += out["value"][td, lv]
            # gradients see the pruned ensemble; the CARRIED scores are
            # rebuilt below by the exact replay-sum a resumed run computes,
            # so resume bit-identity holds through drop iterations
            score = score - dcontrib
            for d_it in drop:
                for c in range(K):
                    out["value"][int(d_it) * K + c] *= factor_drop

        grads, hess = rf_gh if rf_gh is not None else _grad_hess(score)


        row_mask, feat_mask = sample_masks(p, it, N, F)
        rows = all_rows if row_mask is None else all_rows[row_mask]
        if p.boosting == "goss":
            mask, w = goss_select_np(p, grads, goss_uniform(p, it, N))
            grads = grads * w[:, None]
            hess = hess * w[:, None]
            rows = all_rows[mask]
        _t_grow = time.perf_counter() if _obs.enabled else None
        for k in range(K):
            t = it * K + k
            d = grower.grow(grads[:, k], hess[:, k], rows, feat_mask, out, t)
            max_depth_seen = max(max_depth_seen, d)
            if renew_a is not None:
                lv = tree_leaves_rows(out, Xb, rows, t,
                                      max(max_depth_seen, 1))
                r = (y[rows] - score[rows, k]).astype(np.float32)
                renew_leaf_values_np(out, t, r, lv, renew_a,
                                     p.effective_learning_rate)
            if value_scale != 1.0:
                out["value"][t] *= value_scale
            if not drop.size:
                leaves = tree_leaves_any(out, Xb, t, max(max_depth_seen, 1))
                score[:, k] += out["value"][t, leaves]
                for vXb, vscore in zip(vXbs, vscores):
                    vleaves = tree_leaves_any(out, vXb, t, max(max_depth_seen, 1))
                    vscore[:, k] += out["value"][t, vleaves]
        if _t_grow is not None:
            record_span("train.grow", time.perf_counter() - _t_grow)
        if drop.size:
            # full replay-sum (ascending t, the resume construction): the
            # live score after a drop iteration is bitwise what a resumed
            # run would rebuild from the checkpointed value table
            score = np.broadcast_to(init, (N, K)).astype(np.float32).copy()
            for t2 in range((it + 1) * K):
                lv = tree_leaves_any(out, Xb, t2, max(max_depth_seen, 1))
                score[:, t2 % K] += out["value"][t2, lv]
            for vi, vXb in enumerate(vXbs):
                vs = np.broadcast_to(init, (vXb.shape[0], K)).astype(np.float32).copy()
                for t2 in range((it + 1) * K):
                    vlv = tree_leaves_any(out, vXb, t2, max(max_depth_seen, 1))
                    vs[:, t2 % K] += out["value"][t2, vlv]
                vscores[vi] = vs

        # ch_max_effective = 0: no chunking on this backend, no cap in
        # force — but the key is the documented contract journals/benches
        # read on every path (engine/train.py)
        info: dict = {"iteration": it, "ch_max_effective": 0}
        # eval every eval_period-th iteration, always including the last so
        # the training tail is never silently unscored
        eval_now = (it + 1) % p.eval_period == 0 or it + 1 == T // K
        stop = False
        _t_ev = time.perf_counter() if _obs.enabled else None
        if valids and eval_now:
            from dryad_tpu.metrics import evaluate_raw

            for vi, ((vname, vds), vscore) in enumerate(zip(valids, vscores)):
                if p.boosting == "rf":
                    # rf scores a model that AVERAGES the trees grown so
                    # far — the EXACT shared transform predict applies, so
                    # the streamed metric equals a post-hoc recompute
                    from dryad_tpu.cpu.predict import rf_average

                    vscore = rf_average(vscore, init, it + 1)
                name, value, higher = evaluate_raw(
                    p.objective, p.metric, vds.y,
                    vscore if K > 1 else vscore[:, 0],
                    vds.query_offsets, p.ndcg_at,
                )
                info[f"{vname}_{name}"] = value
                eval_history.setdefault(f"{vname}_{name}", []).append(
                    [it, float(value)])
                if vi > 0:
                    continue  # early stopping watches the first set only
                best_iteration, best_value, stale = update_best(
                    p, best_iteration, best_value, stale, it, value, higher)
                if p.early_stopping_rounds and stale >= p.early_stopping_rounds:
                    stop = True
                    T = (it + 1) * K  # trim unfilled trailing trees
        if valids and eval_now and _t_ev is not None:
            record_span("train.eval", time.perf_counter() - _t_ev)
        # stop falls through to the callback and the due boundary checkpoint
        # before breaking — same checkpoint stream as the device trainer
        if callback is not None:
            callback(it, info)
        if checkpointer is not None and checkpointer.due(it + 1):
            if chunk_hook is not None:
                chunk_hook("fetch", it + 1)
            with span("train.checkpoint"):
                ckpt = _make_booster(p, data.mapper, out, (it + 1) * K, init,
                                     max_depth_seen, best_iteration,
                                     best_value, stale)
                if eval_history:
                    ckpt.train_state["eval_history"] = eval_history
                checkpointer.save(ckpt, it + 1)
        if _t_it is not None:
            record_span("train.iteration", time.perf_counter() - _t_it)
            if _obs_iter is None:
                _obs_iter = _obs.gauge(
                    "dryad_train_iteration",
                    "Last host-side boosting iteration")
            _obs_iter.set(it)
        if stop:
            break

    if chunk_hook is not None:
        chunk_hook("fetch", T // K)
    booster = _make_booster(p, data.mapper, out, T, init, max_depth_seen,
                            best_iteration, best_value, stale)
    if eval_history:
        booster.train_state["eval_history"] = eval_history
    return booster


def _make_booster(p, mapper, out, T, init, max_depth_seen, best_iteration,
                  best_value=None, stale=0):
    return Booster(
        p, mapper,
        out["feature"][:T], out["threshold"][:T], out["left"][:T],
        out["right"][:T], out["value"][:T],
        out["is_cat"][:T], out["cat_bitset"][:T],
        init, max_depth_seen,
        best_iteration=best_iteration,
        gain=out["gain"][:T],
        train_state={"best_value": best_value, "stale": int(stale)},
        default_left=out["default_left"][:T],
        cover=out["cover"][:T],
    )
