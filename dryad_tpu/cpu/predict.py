"""Canonical CPU predict: vectorized level-synchronous tree traversal.

The contract (BASELINE.json:5): predict is bit-identical between CPU and TPU.
Traversal decisions compare integer bin ids (exact on both), and the float
accumulation of leaf deltas runs tree-by-tree in fp32 in the same order as
the device scan — so equality is structural, not approximate.
"""

from __future__ import annotations

from typing import Optional

import numpy as np


def predict_tree_leaves(
    trees: dict[str, np.ndarray], Xb: np.ndarray, t: int, depth_bound: int
) -> np.ndarray:
    """Leaf node id reached by every row in tree ``t``."""
    N = Xb.shape[0]
    node = np.zeros(N, np.int64)
    feature = trees["feature"][t]
    threshold = trees["threshold"][t]
    left, right = trees["left"][t], trees["right"][t]
    is_cat = trees["is_cat"][t]
    cat_bs = trees["cat_bitset"][t]
    # learned per-node missing direction; absent in pre-direction tree dicts
    # (missing then always travels left, the historic rule)
    dleft = trees["default_left"][t] if "default_left" in trees else None
    for _ in range(max(depth_bound, 1)):
        f = feature[node]
        internal = f >= 0
        if not internal.any():
            break
        fc = np.where(internal, f, 0)
        bins_v = Xb[np.arange(N), fc].astype(np.int64)
        num_left = bins_v <= threshold[node]
        if dleft is not None:
            num_left &= dleft[node] | (bins_v != 0)
        # bitset word index is clipped: bins beyond the bitset (>256 only on
        # numerical-split nodes) never consult cat_left
        word = cat_bs[node, np.minimum(bins_v >> 5, cat_bs.shape[1] - 1)]
        cat_left = (word >> (bins_v & 31).astype(np.uint32)) & 1 > 0
        go_left = np.where(is_cat[node], cat_left, num_left)
        nxt = np.where(go_left, left[node], right[node])
        node = np.where(internal, nxt, node)
    return node


def rf_average(raw, init_score, n_iter: int) -> np.ndarray:
    """THE rf averaging transform: init + (Σ - init) * (1/n) in f32 with a
    HOST-computed reciprocal (config.py rf note).  One definition shared by
    both predict backends and the CPU trainer's streamed eval — the
    arithmetic is a bit-identity invariant (a device division lowers as
    reciprocal-multiply and device multiply-add fuses to FMA, each 1 ulp
    off host; measured breaking CPU↔TPU predict equality)."""
    inv = np.float32(1.0) / np.float32(n_iter)
    init = np.asarray(init_score, np.float32)
    return (init + (np.asarray(raw) - init) * inv).astype(np.float32)


def predict_binned_cpu(
    booster, Xb: np.ndarray, num_iteration: Optional[int] = None
) -> np.ndarray:
    """Raw scores (N, K): init_score + Σ_t leaf value, fp32, fixed tree order."""
    K = booster.num_outputs
    N = Xb.shape[0]
    if num_iteration is None:
        # early stopping: default to the best iteration (LightGBM semantics)
        n_iter = booster.best_iteration if booster.best_iteration > 0 else booster.num_iterations
    else:
        n_iter = min(num_iteration, booster.num_iterations)
    trees = booster.tree_arrays()
    from dryad_tpu import native

    score = native.predict_accumulate(
        Xb, trees, booster.init_score, n_iter * K, K, booster.max_depth_seen
    )
    if score is None:
        score = np.broadcast_to(booster.init_score, (N, K)).astype(np.float32).copy()
        for t in range(n_iter * K):
            leaves = predict_tree_leaves(trees, Xb, t, booster.max_depth_seen)
            score[:, t % K] += booster.value[t, leaves]
    if booster.params.boosting == "rf" and n_iter > 0:
        score = rf_average(score, booster.init_score, n_iter)
    return score
