"""Canonical CPU histogram builder + split finder.

This module defines the *semantics* the TPU engine must reproduce
(SURVEY.md §2 #5-6): per-(feature, bin) gradient/hessian/count sums, prefix
scans, the exact gain formula, validity masks, and first-index tie-breaking.
CPU accumulates in float64 for numerical quality; the TPU path accumulates
fp32 on the MXU — tree-structure parity tests tolerate only the resulting
last-ulp argmax differences (none observed on continuous data).
"""

from __future__ import annotations

import dataclasses

import numpy as np

NEG_INF = np.float64(-np.inf)


def build_hist(
    Xb: np.ndarray,
    g: np.ndarray,
    h: np.ndarray,
    rows: np.ndarray,
    total_bins: int,
    elem_budget: int = 16_777_216,
) -> np.ndarray:
    """Σ grad / Σ hess / count per (feature, bin) over ``rows`` → (3, F, B) f64.

    Single fused bincount over combined (feature*B + bin) indices; the row
    chunk is sized as ``elem_budget / F`` so per-chunk temporaries stay
    bounded on wide data (Epsilon, 2000 features — BASELINE.json:9).
    """
    F = Xb.shape[1]
    B = int(total_bins)
    chunk = max(1, elem_budget // F)
    offsets = (np.arange(F, dtype=np.int32) * B)[None, :]
    hg = np.zeros(F * B, np.float64)
    hh = np.zeros(F * B, np.float64)
    hc = np.zeros(F * B, np.float64)
    for start in range(0, rows.size, chunk):
        rc = rows[start : start + chunk]
        idx = (Xb[rc].astype(np.int32) + offsets).ravel()
        gw = np.repeat(g[rc].astype(np.float64), F)
        hw = np.repeat(h[rc].astype(np.float64), F)
        hg += np.bincount(idx, weights=gw, minlength=F * B)
        hh += np.bincount(idx, weights=hw, minlength=F * B)
        hc += np.bincount(idx, minlength=F * B).astype(np.float64)
    return np.stack([hg, hh, hc]).reshape(3, F, B)


@dataclasses.dataclass
class SplitInfo:
    gain: float
    feature: int
    threshold: int          # numerical: bin id; categorical: prefix length
    is_cat: bool
    cat_members: np.ndarray  # categorical: sorted member bin ids of the left set
    g_left: float
    h_left: float
    c_left: float
    default_left: bool = True  # missing (bin 0) goes left at this split


def leaf_output(G: float, H: float, lambda_l2: float, learning_rate: float,
                lo: float = -np.inf, hi: float = np.inf) -> float:
    """Newton leaf value with shrinkage applied (fp32-rounded, both backends).

    ``lo``/``hi`` are the node's monotone output bounds (f32 values tracked
    by the growers); the raw Newton value is clamped before shrinkage,
    exactly as the device ``finalize_leaf_values`` does.
    """
    raw = np.float32(-(np.float32(G) / np.float32(H + lambda_l2)))
    raw = np.float32(min(max(raw, np.float32(lo)), np.float32(hi)))
    return float(np.float32(raw * np.float32(learning_rate)))


def find_best_split(
    hist: np.ndarray,
    G: float,
    H: float,
    C: float,
    *,
    lambda_l2: float,
    min_child_weight: float,
    min_data_in_leaf: int,
    min_split_gain: float,
    feature_mask: np.ndarray | None = None,
    is_categorical: np.ndarray | None = None,
    cat_smooth: float = 10.0,
    monotone: np.ndarray | None = None,
    lo: float = -np.inf,
    hi: float = np.inf,
    learn_missing: bool = False,
    bundled_mask: np.ndarray | None = None,
) -> SplitInfo | None:
    """Best (feature, threshold) over the histogram; None when nothing valid.

    Numerical: scan "bin <= t goes left" for every t; with ``learn_missing``
    a second plane scans "bins 1..t left, missing (bin 0) right" and the
    better plane wins (missing-left plane first on ties, so NaN-free data
    grows unchanged trees).  Categorical: LightGBM style sorted-subset —
    bins ordered by g/(h+smooth), best prefix becomes the left membership
    set (missing direction is part of the membership).  Tie-break: first
    index in flattened (plane, F, B) order (matches jnp.argmax).
    """
    hg, hh, hc = hist[0], hist[1], hist[2]
    F, B = hg.shape

    GL = np.cumsum(hg, axis=1)
    HL = np.cumsum(hh, axis=1)
    CL = np.cumsum(hc, axis=1)

    cat_order: dict[int, np.ndarray] = {}
    any_cat = is_categorical is not None and is_categorical.any()
    if any_cat:
        # Rewrite the scan to sorted-bin order, only for categorical rows.
        for f in np.where(is_categorical)[0]:
            with np.errstate(invalid="ignore", divide="ignore"):
                ratio = np.where(hc[f] > 0, hg[f] / (hh[f] + cat_smooth), np.inf)
            o = np.argsort(ratio, kind="stable")
            cat_order[int(f)] = o
            GL[f] = np.cumsum(hg[f][o])
            HL[f] = np.cumsum(hh[f][o])
            CL[f] = np.cumsum(hc[f][o])

    def gain_of(GLx, HLx, CLx):
        GRx, HRx, CRx = G - GLx, H - HLx, C - CLx
        valid = (
            (CLx >= min_data_in_leaf)
            & (CRx >= min_data_in_leaf)
            & (HLx >= min_child_weight)
            & (HRx >= min_child_weight)
        )
        if feature_mask is not None:
            valid &= feature_mask[:, None]
        if monotone is not None:
            # LightGBM-"basic" monotone mode (the device split.py mirrors
            # this): child outputs clamped to the node's inherited [lo, hi]
            # bounds, gain computed with the clamped outputs, and a ±1
            # feature may only split where the clamped right value is >=/<=
            # the clamped left value; unconstrained (0) features pass
            # regardless of NaN child values
            with np.errstate(invalid="ignore", divide="ignore"):
                wl = np.clip(-GLx / (HLx + lambda_l2), lo, hi)
                wr = np.clip(-GRx / (HRx + lambda_l2), lo, hi)
                wp = min(max(-G / (H + lambda_l2), lo), hi)
                valid &= (monotone[:, None] == 0) | (monotone[:, None] * (wr - wl) >= 0)
                red_l = -(GLx * wl + 0.5 * (HLx + lambda_l2) * wl * wl)
                red_r = -(GRx * wr + 0.5 * (HRx + lambda_l2) * wr * wr)
                red_p = -(G * wp + 0.5 * (H + lambda_l2) * wp * wp)
                gain = red_l + red_r - red_p
        else:
            with np.errstate(invalid="ignore", divide="ignore"):
                parent_score = G * G / (H + lambda_l2)
                gain = 0.5 * (GLx * GLx / (HLx + lambda_l2)
                              + GRx * GRx / (HRx + lambda_l2) - parent_score)
        return np.where(valid, gain, NEG_INF)

    gain = gain_of(GL, HL, CL)
    default_left = True
    if learn_missing:
        # missing-right plane: subtract the first scanned position's stats
        # (bin 0 for numerical features — identity order keeps it first)
        CL_r = CL - hc[:, :1]
        gain_r = gain_of(GL - hg[:, :1], HL - hh[:, :1], CL_r)
        # exclude right-child-holds-only-missing candidates: they mirror the
        # plane-0 t=0 split (sides swapped) and fp noise could flip the
        # CPU/TPU argmax between the two representations (device mirrors)
        gain_r = np.where((C - CL_r) > hc[:, :1], gain_r, NEG_INF)
        if bundled_mask is not None:
            # EFB bundle columns: bin 0 means "all members default", never
            # "missing" (mirrors engine/split.py exactly)
            gain_r[bundled_mask] = NEG_INF
        if any_cat:
            gain_r[is_categorical] = NEG_INF
        flat2 = int(np.argmax(np.concatenate([gain.ravel(), gain_r.ravel()])))
        default_left = flat2 < F * B
        flat = flat2 % (F * B)
        best_gain = float((gain if default_left else gain_r).ravel()[flat])
    else:
        flat = int(np.argmax(gain))
        best_gain = float(gain.ravel()[flat])
    if not np.isfinite(best_gain) or best_gain <= min_split_gain:
        return None
    f, t = flat // B, flat % B
    gl, hl, cl = float(GL[f, t]), float(HL[f, t]), float(CL[f, t])
    if not default_left:
        gl, hl, cl = gl - float(hg[f, 0]), hl - float(hh[f, 0]), cl - float(hc[f, 0])
    if is_categorical is not None and is_categorical[f]:
        members = np.sort(cat_order[int(f)][: t + 1]).astype(np.int32)
        return SplitInfo(best_gain, f, t, True, members, gl, hl, cl)
    return SplitInfo(best_gain, f, t, False, np.empty(0, np.int32), gl, hl, cl,
                     default_left=bool(default_left))


def cat_members_to_bitset(members: np.ndarray, words: int) -> np.ndarray:
    bs = np.zeros(words, np.uint32)
    for m in members:
        bs[m >> 5] |= np.uint32(1) << np.uint32(m & 31)
    return bs
