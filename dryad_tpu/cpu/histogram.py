"""Canonical CPU histogram builder + split finder.

This module defines the *semantics* the TPU engine must reproduce
(SURVEY.md §2 #5-6): per-(feature, bin) gradient/hessian/count sums, prefix
scans, the exact gain formula, validity masks, and first-index tie-breaking.
CPU accumulates in float64 for numerical quality; the TPU path accumulates
fp32 on the MXU — tree-structure parity tests tolerate only the resulting
last-ulp argmax differences (none observed on continuous data).
"""

from __future__ import annotations

import dataclasses

import numpy as np

NEG_INF = np.float64(-np.inf)


def build_hist(
    Xb: np.ndarray,
    g: np.ndarray,
    h: np.ndarray,
    rows: np.ndarray,
    total_bins: int,
    elem_budget: int = 16_777_216,
) -> np.ndarray:
    """Σ grad / Σ hess / count per (feature, bin) over ``rows`` → (3, F, B) f64.

    Single fused bincount over combined (feature*B + bin) indices; the row
    chunk is sized as ``elem_budget / F`` so per-chunk temporaries stay
    bounded on wide data (Epsilon, 2000 features — BASELINE.json:9).
    """
    F = Xb.shape[1]
    B = int(total_bins)
    chunk = max(1, elem_budget // F)
    offsets = (np.arange(F, dtype=np.int32) * B)[None, :]
    hg = np.zeros(F * B, np.float64)
    hh = np.zeros(F * B, np.float64)
    hc = np.zeros(F * B, np.float64)
    for start in range(0, rows.size, chunk):
        rc = rows[start : start + chunk]
        idx = (Xb[rc].astype(np.int32) + offsets).ravel()
        gw = np.repeat(g[rc].astype(np.float64), F)
        hw = np.repeat(h[rc].astype(np.float64), F)
        hg += np.bincount(idx, weights=gw, minlength=F * B)
        hh += np.bincount(idx, weights=hw, minlength=F * B)
        hc += np.bincount(idx, minlength=F * B).astype(np.float64)
    return np.stack([hg, hh, hc]).reshape(3, F, B)


@dataclasses.dataclass
class SplitInfo:
    gain: float
    feature: int
    threshold: int          # numerical: bin id; categorical: prefix length
    is_cat: bool
    cat_members: np.ndarray  # categorical: sorted member bin ids of the left set
    g_left: float
    h_left: float
    c_left: float


def leaf_output(G: float, H: float, lambda_l2: float, learning_rate: float,
                lo: float = -np.inf, hi: float = np.inf) -> float:
    """Newton leaf value with shrinkage applied (fp32-rounded, both backends).

    ``lo``/``hi`` are the node's monotone output bounds (f32 values tracked
    by the growers); the raw Newton value is clamped before shrinkage,
    exactly as the device ``finalize_leaf_values`` does.
    """
    raw = np.float32(-(np.float32(G) / np.float32(H + lambda_l2)))
    raw = np.float32(min(max(raw, np.float32(lo)), np.float32(hi)))
    return float(np.float32(raw * np.float32(learning_rate)))


def find_best_split(
    hist: np.ndarray,
    G: float,
    H: float,
    C: float,
    *,
    lambda_l2: float,
    min_child_weight: float,
    min_data_in_leaf: int,
    min_split_gain: float,
    feature_mask: np.ndarray | None = None,
    is_categorical: np.ndarray | None = None,
    cat_smooth: float = 10.0,
    monotone: np.ndarray | None = None,
    lo: float = -np.inf,
    hi: float = np.inf,
) -> SplitInfo | None:
    """Best (feature, threshold) over the histogram; None when nothing valid.

    Numerical: scan "bin <= t goes left" for every t.  Categorical: LightGBM
    style sorted-subset — bins ordered by g/(h+smooth), best prefix becomes
    the left membership set.  Tie-break: first index in flattened (F, B)
    order (matches both np.argmax and jnp.argmax).
    """
    hg, hh, hc = hist[0], hist[1], hist[2]
    F, B = hg.shape

    GL = np.cumsum(hg, axis=1)
    HL = np.cumsum(hh, axis=1)
    CL = np.cumsum(hc, axis=1)

    cat_order: dict[int, np.ndarray] = {}
    if is_categorical is not None and is_categorical.any():
        # Rewrite the scan to sorted-bin order, only for categorical rows.
        for f in np.where(is_categorical)[0]:
            with np.errstate(invalid="ignore", divide="ignore"):
                ratio = np.where(hc[f] > 0, hg[f] / (hh[f] + cat_smooth), np.inf)
            o = np.argsort(ratio, kind="stable")
            cat_order[int(f)] = o
            GL[f] = np.cumsum(hg[f][o])
            HL[f] = np.cumsum(hh[f][o])
            CL[f] = np.cumsum(hc[f][o])

    GR, HR, CR = G - GL, H - HL, C - CL
    valid = (
        (CL >= min_data_in_leaf)
        & (CR >= min_data_in_leaf)
        & (HL >= min_child_weight)
        & (HR >= min_child_weight)
    )
    if feature_mask is not None:
        valid &= feature_mask[:, None]
    if monotone is not None:
        # LightGBM-"basic" monotone mode (the device split.py mirrors this):
        # child outputs clamped to the node's inherited [lo, hi] bounds, gain
        # computed with the clamped outputs, and a ±1 feature may only split
        # where the clamped right value is >=/<= the clamped left value;
        # unconstrained (0) features pass regardless of NaN child values
        with np.errstate(invalid="ignore", divide="ignore"):
            wl = np.clip(-GL / (HL + lambda_l2), lo, hi)
            wr = np.clip(-GR / (HR + lambda_l2), lo, hi)
            wp = min(max(-G / (H + lambda_l2), lo), hi)
            valid &= (monotone[:, None] == 0) | (monotone[:, None] * (wr - wl) >= 0)
            red_l = -(GL * wl + 0.5 * (HL + lambda_l2) * wl * wl)
            red_r = -(GR * wr + 0.5 * (HR + lambda_l2) * wr * wr)
            red_p = -(G * wp + 0.5 * (H + lambda_l2) * wp * wp)
            gain = red_l + red_r - red_p
    else:
        with np.errstate(invalid="ignore", divide="ignore"):
            parent_score = G * G / (H + lambda_l2)
            gain = 0.5 * (GL * GL / (HL + lambda_l2) + GR * GR / (HR + lambda_l2) - parent_score)
    gain = np.where(valid, gain, NEG_INF)

    flat = int(np.argmax(gain))
    best_gain = float(gain.ravel()[flat])
    if not np.isfinite(best_gain) or best_gain <= min_split_gain:
        return None
    f, t = flat // B, flat % B
    if is_categorical is not None and is_categorical[f]:
        members = np.sort(cat_order[int(f)][: t + 1]).astype(np.int32)
        return SplitInfo(best_gain, f, t, True, members, float(GL[f, t]), float(HL[f, t]), float(CL[f, t]))
    return SplitInfo(best_gain, f, t, False, np.empty(0, np.int32), float(GL[f, t]), float(HL[f, t]), float(CL[f, t]))


def cat_members_to_bitset(members: np.ndarray, words: int) -> np.ndarray:
    bs = np.zeros(words, np.uint32)
    for m in members:
        bs[m >> 5] |= np.uint32(1) << np.uint32(m & 31)
    return bs
