"""Fetch-stall watchdog: in-flight fetch age as a live gauge.

The recorded tunnel fault class (STATUS r5, faults.py::FETCH_DEATH) is a
device->host fetch pending >~1 min behind queued work, killed by the
tunnel — today we learn about the stall only after the supervisor
classifies its corpse.  This monitor makes the stall visible WHILE it is
still recoverable: the device trainer brackets every real fetch site
(engine/train.py) with ``watch_fetch(site, iteration)``, and a daemon
monitor thread exports

* ``dryad_fetch_inflight_age_seconds`` (gauge) — age of the OLDEST
  in-flight fetch, 0 when idle;
* ``dryad_fetch_stalls_total{site=...}`` (counter) — fetches whose age
  crossed the stall threshold (default 30 s — deliberately below the
  known ~60 s tunnel death line; ``DRYAD_FETCH_STALL_S`` overrides);
* ``/healthz`` degraded (reason ``fetch_stall``) while any watched fetch
  is past the threshold, cleared when it completes.

``last_stall()`` keeps the most recent stall's (site, iteration, age) so
the supervisor can correlate stall-age with the fault it classifies
moments later (the journal's ``stall_age_s`` field).

Obs-package contracts: host-side only (the watchdog reads wall clocks the
trainer already pays for — it never touches jax or a device buffer), and
zero-cost when disabled (``watch_fetch`` returns a shared null context
before touching the clock; the monitor thread only exists once a watched
fetch has been seen on an enabled registry).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Optional

from dryad_tpu.obs.health import HealthState, default_health
from dryad_tpu.obs.registry import Registry, default_registry

#: stall threshold default — below the ~60 s tunnel kill line (STATUS r5)
STALL_THRESHOLD_S = 30.0
HEALTH_REASON = "fetch_stall"


class _NullWatch:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL = _NullWatch()


class _Watch:
    __slots__ = ("_dog", "site", "iteration", "token")

    def __init__(self, dog: "FetchWatchdog", site: str, iteration: int):
        self._dog = dog
        self.site = site
        self.iteration = iteration
        self.token = None

    def __enter__(self):
        self.token = self._dog.begin(self.site, self.iteration)
        return self

    def __exit__(self, *exc):
        self._dog.end(self.token)
        return False


class FetchWatchdog:
    """Tracks in-flight fetches and exports their age from a monitor
    thread.  One instance serves the whole process (``default_watchdog``);
    tests build private ones with tiny thresholds.

    ``_lock`` guards the in-flight table and its token counter (producer
    threads begin/end watches while the monitor ticks ages); gauge and
    counter publication happens OUTSIDE the lock so a contended registry
    family never extends this critical section."""

    GUARDED_BY = {"_inflight": "_lock", "_next_token": "_lock",
                  "_last_stall": "_lock", "_thread": "_lock"}

    def __init__(self, registry: Optional[Registry] = None,
                 threshold_s: Optional[float] = None,
                 poll_interval_s: float = 0.5,
                 health: Optional[HealthState] = None):
        if threshold_s is None:
            try:
                threshold_s = float(
                    os.environ.get("DRYAD_FETCH_STALL_S", "")
                    or STALL_THRESHOLD_S)
            except ValueError:
                threshold_s = STALL_THRESHOLD_S
        self.threshold_s = float(threshold_s)
        self.poll_interval_s = float(poll_interval_s)
        self._registry = registry
        self._health = health
        self._lock = threading.Lock()
        self._inflight: dict[int, dict] = {}
        self._next_token = 0
        self._last_stall: Optional[dict] = None
        self._thread: Optional[threading.Thread] = None
        self._wake = threading.Event()

    def _reg(self) -> Registry:
        return (self._registry if self._registry is not None
                else default_registry())

    def _hp(self) -> HealthState:
        return self._health if self._health is not None else default_health()

    # ---- producer side (the trainer's fetch sites) -------------------------
    def watch(self, site: str, iteration: int):
        """Context manager bracketing ONE real device->host fetch.  The
        null context comes back when the registry is disabled — the
        zero-cost contract."""
        if not self._reg().enabled:
            return _NULL
        return _Watch(self, site, int(iteration))

    def begin(self, site: str, iteration: int) -> Optional[int]:
        reg = self._reg()
        if not reg.enabled:
            return None
        now = time.perf_counter()
        with self._lock:
            token = self._next_token
            self._next_token += 1
            self._inflight[token] = {"site": str(site),
                                     "iteration": int(iteration),
                                     "t0": now, "stalled": False}
            oldest = now - min(w["t0"] for w in self._inflight.values())
        # publish the gauge at begin time so the family exists from the
        # FIRST watched fetch (scrapers see 0 rather than nothing); the
        # monitor ticks it upward while the fetch is pending
        reg.gauge("dryad_fetch_inflight_age_seconds",
                  "Age of the oldest in-flight device fetch").set(
            round(oldest, 3))
        self._ensure_thread()
        self._wake.set()
        return token

    def end(self, token: Optional[int]) -> None:
        if token is None:
            return
        now = time.perf_counter()
        with self._lock:
            info = self._inflight.pop(token, None)
            any_stalled = any(w["stalled"] for w in self._inflight.values())
            if info is not None and info["stalled"]:
                self._last_stall = {
                    "site": info["site"], "iteration": info["iteration"],
                    "age_s": round(now - info["t0"], 3), "ended_at": now}
            idle = not self._inflight
        reg = self._reg()
        if reg.enabled and idle:
            reg.gauge("dryad_fetch_inflight_age_seconds",
                      "Age of the oldest in-flight device fetch").set(0.0)
        if info is not None and info["stalled"] and not any_stalled:
            self._hp().clear(HEALTH_REASON)

    def last_stall(self) -> Optional[dict]:
        """Most recent completed-or-aborted stall (site, iteration, age_s,
        ended_at perf_counter timestamp) — the supervisor's correlation
        hook.  None until a stall has been observed."""
        with self._lock:
            return dict(self._last_stall) if self._last_stall else None

    # ---- monitor thread ----------------------------------------------------
    def _ensure_thread(self) -> None:
        # double-checked fast path: the per-begin() liveness probe; the
        # locked re-check below is the authoritative spawn decision
        # dryadlint: disable=guarded-by -- benign double-checked read (see above)
        if self._thread is None or not self._thread.is_alive():
            with self._lock:
                if self._thread is None or not self._thread.is_alive():
                    self._thread = threading.Thread(
                        target=self._run, daemon=True,
                        name="dryad-fetch-watchdog")
                    self._thread.start()

    def _run(self) -> None:
        while True:
            with self._lock:
                busy = bool(self._inflight)
            if not busy:
                # park until the next begin() (no spin while idle)
                self._wake.wait()
                self._wake.clear()
                continue
            self._tick()
            time.sleep(self.poll_interval_s)

    def _tick(self) -> None:
        now = time.perf_counter()
        newly_stalled = []
        with self._lock:
            if not self._inflight:
                return
            oldest = max(now - w["t0"] for w in self._inflight.values())
            for w in self._inflight.values():
                if not w["stalled"] and now - w["t0"] >= self.threshold_s:
                    w["stalled"] = True
                    newly_stalled.append((w["site"], w["iteration"]))
        reg = self._reg()
        if reg.enabled:
            reg.gauge("dryad_fetch_inflight_age_seconds",
                      "Age of the oldest in-flight device fetch").set(
                round(oldest, 3))
            for site, iteration in newly_stalled:
                reg.counter("dryad_fetch_stalls_total",
                            "Fetches pending past the stall threshold"
                            ).labels(site=site).inc()
        if newly_stalled:
            site, iteration = newly_stalled[-1]
            self._hp().degrade(
                HEALTH_REASON,
                f"fetch at {site} (iteration {iteration}) pending "
                f">{self.threshold_s:g}s")


_default: Optional[FetchWatchdog] = None
_default_lock = threading.Lock()


def default_watchdog() -> FetchWatchdog:
    global _default
    if _default is None:
        with _default_lock:
            if _default is None:
                _default = FetchWatchdog()
    return _default


def set_default_watchdog(dog: FetchWatchdog) -> FetchWatchdog:
    """Swap the process default (tests use tiny thresholds); returns the
    old one so callers can restore it."""
    global _default
    with _default_lock:
        old = _default if _default is not None else FetchWatchdog()
        _default = dog
    return old


def watch_fetch(site: str, iteration: int):
    """Module-level convenience over the default watchdog — the form the
    device trainer's fetch sites use."""
    return default_watchdog().watch(site, iteration)
