"""Model-quality drift telemetry: PSI monitors over the serve bin space.

r9–r17 built deep *systems* observability; nothing watched the MODEL.
This module closes that gap host-side: every served request is already
binned into the model's frozen per-feature bin space (the batcher's
``_prepare`` output) and its raw scores are already fetched, so drift
accounting is a counter increment on values the engine already touched —
squarely inside the obs contracts (registry.py):

* **host-side only, jax-free** — the monitor sees numpy arrays the serve
  pipeline already holds; nothing here fetches or imports jax;
* **zero-cost when disabled** — the serve layer allocates NO drift state
  when the obs registry is disabled (``PredictServer`` keeps the monitor
  table ``None``); the hot-path guard is one attribute read + branch;
* **merge counts, never quantiles/ratios** — replicas export raw window
  bin COUNTS (``export_state``); the fleet router adds the integer
  counts losslessly (``merge_drift_states``, the r17
  ``merge_hist_states`` discipline) and computes PSI once on the merged
  state, so the fleet verdict equals the verdict on the concatenated
  observations bitwise.

The reference side lives in the model artifact: ``data/profile.py``
persists a per-feature binned-count distribution and a score histogram
(on THIS module's fixed ``SCORE_BUCKETS`` layout) at train completion,
so every served model carries its own baseline and the monitor needs no
side channel.

PSI (population stability index) is the classic binned-distribution
divergence: ``sum_b (q_b - p_b) * ln(q_b / p_b)`` with a proportion
floor.  Rule-of-thumb interpretation (the default budget below): < 0.1
stable, 0.1–0.2 moderate shift, > 0.2 significant shift — the retrain /
rollback tripwire, not a 1% referee.

Lock contract: ``DriftMonitor._lock`` guards the rotating window state
(the two-epoch recency idiom serve/metrics.py uses); registry gauges are
set OUTSIDE it.  ``DriftGate._lock`` guards the breach streaks; health
notes, gauges and the journal callback run outside it (the SloGate
shape) — neither lock ever nests with another.
"""

from __future__ import annotations

import math
import threading
from typing import Callable, Optional, Sequence

import numpy as np

from dryad_tpu.obs.health import HealthState
from dryad_tpu.obs.registry import Registry, default_registry

__all__ = [
    "SCORE_BUCKETS", "DEFAULT_PSI_BUDGET", "score_bucket_index",
    "new_score_state", "observe_scores_state", "psi", "drift_report",
    "merge_drift_states", "DriftMonitor", "DriftGate", "parse_psi_budget",
]

# ---- the fixed score-bucket scheme ------------------------------------------
#
# Raw margin scores are signed and span decades, so the layout is a
# signed log grid: 4 buckets per decade over |s| in 1e-3 .. 1e4, mirrored
# around zero (scores inside ±1e-3 land in the first positive bucket).
# Like registry.LOG_BUCKETS the bounds are CODE, not configuration —
# every process shares the layout by construction, which is what makes
# the cross-replica count-merge exact.  Never give a score histogram
# custom buckets.
SCORE_MIN = 1e-3
SCORE_PER_DECADE = 4
SCORE_DECADES = 7
_POS = tuple(SCORE_MIN * 10.0 ** (i / SCORE_PER_DECADE)
             for i in range(SCORE_PER_DECADE * SCORE_DECADES + 1))
SCORE_BUCKETS = tuple(-b for b in reversed(_POS)) + _POS
# NOTE on numpy here: every array this module touches is a host numpy
# array the serve pipeline already holds (the batcher's binned batch,
# the executed raw scores) — nothing is ever materialized FROM a device
# buffer, which is what the obs lint's np.asarray ban is about; the
# coercions below are dtype-only astype/ravel on host arrays.
_SCORE_BOUNDS_NP = np.array(SCORE_BUCKETS, np.float64)

#: PSI above this is "significant shift" (the canonical 0.2 rule); the
#: default budget for both the per-feature max and the score shift
DEFAULT_PSI_BUDGET = 0.2
#: proportion floor inside the PSI log — an empty bin must not blow the
#: index to infinity (standard practice)
PSI_EPS = 1e-4


def parse_psi_budget(spec: str) -> Optional[float]:
    """CLI shape for ``--drift-psi``: a float budget, empty -> the
    default, ``off``/``none`` -> None (drift gating disabled)."""
    if not spec:
        return DEFAULT_PSI_BUDGET
    if spec.strip().lower() in ("off", "none"):
        return None
    return float(spec)


def score_bucket_index(value: float) -> int:
    """The 'le' bucket index on SCORE_BUCKETS: the smallest ``i`` with
    ``value <= SCORE_BUCKETS[i]``, overflow for values past the last
    bound; non-finite values land in the overflow bucket."""
    if value != value or value == float("inf"):      # NaN / +inf
        return len(SCORE_BUCKETS)
    lo, hi = 0, len(SCORE_BUCKETS)
    while lo < hi:
        mid = (lo + hi) // 2
        if value <= SCORE_BUCKETS[mid]:
            hi = mid
        else:
            lo = mid + 1
    return lo


def new_score_state() -> list:
    """A fresh ``[counts, sum, count]`` state on the SCORE_BUCKETS
    layout (mirrors registry.new_hist_state on LOG_BUCKETS)."""
    return [[0] * (len(SCORE_BUCKETS) + 1), 0.0, 0]


def observe_scores_state(state: list, values: np.ndarray) -> None:
    """Histogram a raw-score array into a standalone score state (caller
    locks; ``values`` is a host numpy array).  Vectorized: one
    searchsorted + one bincount per batch."""
    flat = np.ravel(values).astype(np.float64, copy=False)
    if flat.size == 0:
        return
    idx = np.searchsorted(_SCORE_BOUNDS_NP, flat, side="left")
    # non-finite scores overflow (searchsorted puts NaN at the end for
    # nan-last ordering, but be explicit so the layout contract holds)
    idx[~np.isfinite(flat)] = len(SCORE_BUCKETS)
    counts = np.bincount(idx, minlength=len(SCORE_BUCKETS) + 1)
    for i in np.flatnonzero(counts):
        state[0][int(i)] += int(counts[i])
    state[1] += float(np.where(np.isfinite(flat), flat, 0.0).sum())
    state[2] += int(flat.size)


def psi(ref_counts: Sequence[int], obs_counts: Sequence[int],
        eps: float = PSI_EPS) -> float:
    """Population stability index between two count vectors sharing one
    bin layout.  Proportions are floored at ``eps`` so empty bins
    contribute finitely; either side empty -> 0.0 (no evidence)."""
    if len(ref_counts) != len(obs_counts):
        raise ValueError("PSI needs one shared bin layout "
                         f"({len(ref_counts)} vs {len(obs_counts)} bins)")
    rt = float(sum(ref_counts))
    ot = float(sum(obs_counts))
    if rt <= 0 or ot <= 0:
        return 0.0
    s = 0.0
    for r, o in zip(ref_counts, obs_counts):
        p = max(r / rt, eps)
        q = max(o / ot, eps)
        s += (q - p) * math.log(q / p)
    return s


# ---- export / merge ---------------------------------------------------------


def merge_drift_states(blocks: Sequence[dict]) -> dict:
    """Exact count-merge of replica ``export_state`` blocks for ONE
    model: integer window counts add losslessly (the r17 histogram-merge
    discipline — merge counts, never quantiles or PSI values), so the
    merged block is the block of the concatenated observations.  The
    reference side is static (every replica serves the same artifact)
    and is taken from the first block; a block whose bin layout differs
    is rejected."""
    blocks = [b for b in blocks if isinstance(b, dict) and "features" in b]
    if not blocks:
        raise ValueError("nothing to merge")
    first = blocks[0]
    bins = list(first.get("bins") or [len(c) for c in first["features"]])
    out = {
        "model": first.get("model", "model"),
        "bins": bins,
        "rows": 0,
        "features": [[0] * nb for nb in bins],
        "ref_features": [list(map(int, c))
                         for c in first.get("ref_features") or []],
        "score": None,
        "ref_score": first.get("ref_score"),
    }
    score_states: list = []
    for b in blocks:
        feats = b["features"]
        if [len(c) for c in feats] != bins:
            raise ValueError("cannot merge drift blocks with different "
                             "bin layouts")
        for f, c in enumerate(feats):
            dst = out["features"][f]
            for i, v in enumerate(c):
                dst[i] += int(v)
        out["rows"] += int(b.get("rows", 0))
        if b.get("score") is not None:
            score_states.append(b["score"])
    if score_states:
        n = len(score_states[0][0])
        counts = [0] * n
        total = 0.0
        cnt = 0
        for c, s, k in score_states:
            if len(c) != n:
                raise ValueError("cannot merge score histograms with "
                                 "different layouts")
            for i, v in enumerate(c):
                counts[i] += int(v)
            total += float(s)
            cnt += int(k)
        out["score"] = [counts, total, cnt]
    return out


def drift_report(state: dict, *, budget_psi: Optional[float] = None,
                 top_k: int = 5) -> dict:
    """The one shared PSI readout — replicas (``DriftMonitor.snapshot``)
    and the fleet router (on the merged state) run THIS on an
    ``export_state``-shaped block, so local and fleet verdicts are the
    same arithmetic.  Returns per-feature PSI top-k, the max, the score
    shift, and (when a budget is given) the breach flags."""
    feats = state.get("features") or []
    refs = state.get("ref_features") or []
    rows = int(state.get("rows", 0))
    per_feature: list = []
    for f, counts in enumerate(feats):
        ref = refs[f] if f < len(refs) else None
        if not ref or sum(counts) == 0:
            continue
        per_feature.append((f, psi(ref, counts)))
    per_feature.sort(key=lambda t: (-t[1], t[0]))
    psi_max = per_feature[0][1] if per_feature else 0.0
    score_psi = 0.0
    if state.get("score") is not None and state.get("ref_score") is not None:
        score_psi = psi(state["ref_score"][0], state["score"][0])
    report = {
        "model": state.get("model", "model"),
        "rows": rows,
        "psi_max": round(psi_max, 6),
        "score_psi": round(score_psi, 6),
        "top": [{"feature": f, "psi": round(v, 6)}
                for f, v in per_feature[:max(0, int(top_k))]],
    }
    if budget_psi is not None:
        report["budget_psi"] = float(budget_psi)
        report["features_over"] = sum(1 for _f, v in per_feature
                                      if v > budget_psi)
        report["breached"] = bool(rows > 0 and (psi_max > budget_psi
                                                or score_psi > budget_psi))
    return report


# ---- the serve-path monitor -------------------------------------------------


class DriftMonitor:
    """Windowed per-feature bin-count + score-histogram accumulator.

    Fed from the serve pipeline's already-binned ``_prepare`` output
    (``observe_features``) and the already-fetched raw predictions
    (``observe_scores``); compares a two-epoch rotating window of recent
    traffic (the serve/metrics.py recency idiom: between window/2 and
    window rows) against the model's embedded reference profile.

    Lock contract: ``_lock`` guards the rotating window — the flat
    feature-count array, the row counter, the score states, and the
    previous-epoch snapshots; observes come from the batcher's collector
    AND executor threads concurrently.  Registry gauges are set outside
    the lock (each family has its own), and nothing blocking ever runs
    under it."""

    GUARDED_BY = {"_cur": "_lock", "_prev": "_lock",
                  "_cur_rows": "_lock", "_prev_rows": "_lock",
                  "_score_cur": "_lock", "_score_prev": "_lock"}

    def __init__(self, ref_feature_counts: Sequence[Sequence[int]], *,
                 ref_score_state: Optional[Sequence] = None,
                 model: str = "model", window_rows: int = 8192,
                 registry: Optional[Registry] = None, top_k: int = 5):
        self.model = str(model)
        self.ref_features = [list(map(int, c)) for c in ref_feature_counts]
        self.ref_score = (None if ref_score_state is None
                          else [list(map(int, ref_score_state[0])),
                                float(ref_score_state[1]),
                                int(ref_score_state[2])])
        self.n_features = len(self.ref_features)
        self._bins = [len(c) for c in self.ref_features]
        # flat layout: feature f's counts live at [_base[f], _base[f+1])
        base = np.zeros(self.n_features + 1, np.int64)
        np.cumsum(self._bins, out=base[1:])
        self._base = base
        self._col_base = base[:-1][None, :]            # (1, F) offsets
        self._nb_max = (np.array(self._bins, np.int64) - 1)[None, :]
        self._total_bins = int(base[-1])
        self.window_rows = max(2, int(window_rows))
        self._half = max(1, self.window_rows // 2)
        self.top_k = int(top_k)
        self._registry = registry
        self._lock = threading.Lock()
        self._cur = np.zeros(self._total_bins, np.int64)
        self._prev: Optional[np.ndarray] = None
        self._cur_rows = 0
        self._prev_rows = 0
        self._score_cur = new_score_state()
        self._score_prev: Optional[list] = None

    def _reg(self) -> Registry:
        return (self._registry if self._registry is not None
                else default_registry())

    # ---- recording ---------------------------------------------------------
    def observe_features(self, Xb: np.ndarray) -> None:
        """Fold one already-binned batch (n, F) into the current window.
        One vectorized bincount per batch — no per-row Python work."""
        n = int(Xb.shape[0])
        if n == 0 or int(Xb.shape[1]) != self.n_features:
            return
        # defensive two-sided clip: a client-binned request could carry
        # ids past the mapper's bin count — or, through the signed
        # direct API, below zero — and neither may bleed into another
        # feature's flat range (or crash the bincount)
        idx = np.clip(Xb.astype(np.int64, copy=False), 0, self._nb_max)
        counts = np.bincount((idx + self._col_base).ravel(),
                             minlength=self._total_bins)
        with self._lock:
            self._cur += counts
            self._cur_rows += n
            if self._cur_rows >= self._half:
                # two-epoch rotation: readers see prev + cur, i.e. the
                # most recent window/2 .. window rows
                self._prev = self._cur
                self._prev_rows = self._cur_rows
                self._cur = np.zeros(self._total_bins, np.int64)
                self._cur_rows = 0
                self._score_prev = self._score_cur
                self._score_cur = new_score_state()

    def observe_scores(self, raw: np.ndarray) -> None:
        """Fold one batch of raw margin scores (n,) or (n, K) into the
        current window's score histogram (multi-output models histogram
        every output — a shift in any class margin is a shift)."""
        flat = np.ravel(raw).astype(np.float64, copy=False)
        if flat.size == 0:
            return
        idx = np.searchsorted(_SCORE_BOUNDS_NP, flat, side="left")
        idx[~np.isfinite(flat)] = len(SCORE_BUCKETS)
        counts = np.bincount(idx, minlength=len(SCORE_BUCKETS) + 1)
        total = float(np.where(np.isfinite(flat), flat, 0.0).sum())
        with self._lock:
            st = self._score_cur
            for i in np.flatnonzero(counts):
                st[0][int(i)] += int(counts[i])
            st[1] += total
            st[2] += int(flat.size)

    # ---- reading -----------------------------------------------------------
    def _window_locked(self) -> tuple:
        """(flat counts, rows, score_state) of prev + cur — called with
        ``_lock`` held."""
        counts = (self._cur.copy() if self._prev is None
                  else self._cur + self._prev)
        rows = self._cur_rows + self._prev_rows
        sc, ss, sn = self._score_cur
        score = [list(sc), float(ss), int(sn)]
        if self._score_prev is not None:
            pc, ps, pn = self._score_prev
            score = [[a + b for a, b in zip(score[0], pc)],
                     score[1] + float(ps), score[2] + int(pn)]
        return counts, rows, score

    def export_state(self) -> dict:
        """The raw-count block a replica serves on ``/obs`` for the fleet
        router's exact merge: window counts per feature, the row count,
        the score state, and the static reference — COUNTS only, never a
        ratio or a PSI value (those are computed after the merge)."""
        with self._lock:
            counts, rows, score = self._window_locked()
        flat = counts.tolist()
        return {
            "model": self.model,
            "rows": int(rows),
            "window_rows": self.window_rows,
            "bins": list(self._bins),
            "features": [flat[int(self._base[f]):int(self._base[f + 1])]
                         for f in range(self.n_features)],
            "ref_features": [list(c) for c in self.ref_features],
            "score": score if score[2] else None,
            "ref_score": (None if self.ref_score is None
                          else [list(self.ref_score[0]),
                                self.ref_score[1], self.ref_score[2]]),
        }

    def snapshot(self, budget_psi: Optional[float] = None) -> dict:
        """The local PSI verdict (``drift_report`` on the window) plus
        the ``dryad_drift_*`` gauge mirror — gauges are set OUTSIDE the
        window lock (registry families own their locks)."""
        report = drift_report(self.export_state(), budget_psi=budget_psi,
                              top_k=self.top_k)
        reg = self._reg()
        if reg.enabled:
            reg.gauge("dryad_drift_psi_max",
                      "Max per-feature PSI over the recent window").labels(
                model=self.model).set(report["psi_max"])
            reg.gauge("dryad_drift_score_psi",
                      "Prediction-score PSI over the recent window").labels(
                model=self.model).set(report["score_psi"])
            reg.gauge("dryad_drift_rows",
                      "Rows in the drift window").labels(
                model=self.model).set(report["rows"])
            fam = reg.gauge("dryad_drift_psi",
                            "Per-feature PSI, top offenders")
            for item in report["top"]:
                fam.labels(model=self.model,
                           feature=item["feature"]).set(item["psi"])
        return report


# ---- the verdict gate -------------------------------------------------------


class DriftGate:
    """Sustained-drift verdicts over per-model drift reports.

    The SloGate shape, with one deliberate difference: drift is
    WARN-ONLY by default — a drifted model still serves (degrading the
    fleet for a data shift would trade availability for freshness), so
    a sustained breach surfaces ``drift:<model>`` in /healthz PAYLOADS
    and fires ``on_breach`` (the router journals ``drift_breach`` — the
    continual-boosting retrain/rollback trigger) without flipping the
    probe to 503.  Construct with ``degrade=True`` to make it gate
    health like the SLO does.

    Lock contract: ``_lock`` guards the streaks and the latched
    verdicts; gauges, health notes and the ``on_breach`` callback (a
    ctor-injected user callback — never callable under a lock) all run
    OUTSIDE it."""

    GUARDED_BY = {"_streaks": "_lock", "_verdicts": "_lock"}

    def __init__(self, budget_psi: float = DEFAULT_PSI_BUDGET, *,
                 breach_after: int = 2, degrade: bool = False,
                 registry: Optional[Registry] = None,
                 health: Optional[HealthState] = None,
                 on_breach: Optional[Callable] = None):
        self.budget_psi = float(budget_psi)
        self.breach_after = int(breach_after)
        self.degrade = bool(degrade)
        self._registry = registry
        self._health = health
        self.on_breach = on_breach
        self._lock = threading.Lock()
        self._streaks: dict[str, int] = {}
        self._verdicts: dict[str, dict] = {}

    def _reg(self) -> Registry:
        return (self._registry if self._registry is not None
                else default_registry())

    def evaluate(self, reports: dict) -> dict:
        """One pass over ``{model: drift_report}``.  An empty window
        (rows == 0) is no evidence — the streak and any standing warning
        HOLD, exactly like the SLO gate's empty-window rule; a breached
        non-empty window advances the streak, ``breach_after``
        consecutive make it sustained (journal + warning), an in-budget
        non-empty window clears it."""
        transitions: list = []
        with self._lock:
            for model, report in sorted(reports.items()):
                rows = int(report.get("rows", 0))
                breached = bool(rows > 0
                                and (report.get("psi_max", 0.0)
                                     > self.budget_psi
                                     or report.get("score_psi", 0.0)
                                     > self.budget_psi))
                prev = self._streaks.get(model, 0)
                streak = prev if rows <= 0 else (prev + 1 if breached else 0)
                self._streaks[model] = streak
                sustained = streak >= self.breach_after
                newly = sustained and prev < self.breach_after
                verdict = dict(report)
                verdict.update(budget_psi=self.budget_psi, breached=breached,
                               streak=streak, sustained=sustained)
                self._verdicts[model] = verdict
                transitions.append((model, verdict, rows, newly))
        reg = self._reg()
        health = self._health
        out: dict = {}
        for model, verdict, rows, newly in transitions:
            out[model] = verdict
            if reg.enabled:
                reg.gauge("dryad_drift_breach_streak",
                          "Consecutive over-budget drift windows").labels(
                    model=model).set(verdict["streak"])
                reg.gauge("dryad_drift_sustained",
                          "1 while the model's drift breach is "
                          "sustained").labels(model=model).set(
                    1 if verdict["sustained"] else 0)
            if health is not None and self.degrade:
                if verdict["sustained"]:
                    health.degrade(f"drift:{model}",
                                   f"psi_max {verdict['psi_max']} / "
                                   f"score {verdict['score_psi']} over "
                                   f"budget {self.budget_psi}")
                elif rows > 0 or verdict["streak"] == 0:
                    health.clear(f"drift:{model}")
            if newly and self.on_breach is not None:
                self.on_breach(model, verdict)
        return out

    def warnings(self) -> list[str]:
        """``drift:<model>`` for every model in sustained breach — the
        /healthz payload's warning list (warn-only: the payload carries
        it, the status code does not)."""
        with self._lock:
            return sorted(f"drift:{m}" for m, s in self._streaks.items()
                          if s >= self.breach_after)

    def verdicts(self) -> dict:
        """The latched per-model verdicts of the last evaluation."""
        with self._lock:
            return {m: dict(v) for m, v in self._verdicts.items()}

    @property
    def ok(self) -> bool:
        with self._lock:
            return all(s < self.breach_after for s in self._streaks.values())
