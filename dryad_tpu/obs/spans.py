"""Lightweight trace spans over the registry: per-stage wall/count series.

    with span("train.chunk_dispatch"):
        ... host-side work ...

Each exit adds the span's wall seconds to ``dryad_span_seconds_total`` and
1 to ``dryad_span_count_total``, labeled with the span's PATH.  Spans nest
per thread: a span opened inside another records under
``parent_path/name`` (tree -> level -> stage reads as
``tree/level/stage``), so per-stage series decompose their parent's wall
(children sum <= parent wall — test-pinned).

The timing here is HOST wall around work the caller already performs —
wrapping an existing fetch measures that fetch; no span ever ADDS a
device fetch or sync (the registry's host-side contract).  Under the
device trainer's async dispatch a span around a dispatch site therefore
measures dispatch cost, not device execution — same caveat as
callbacks.JsonlLogger's ``dispatch_s``.

Zero-cost when disabled: ``span()`` returns one shared null context
manager before touching the clock, and ``record()`` returns after the
enabled check — both allocation-free (test-pinned with tracemalloc).

``record(name, seconds)`` feeds the same series without a ``with`` block,
for loop bodies where a context manager would force a reindent across
``break`` edges (both trainers use it for their per-iteration series).

r13: an optional TRACE SINK (``set_trace_sink``) receives every completed
span as ``(path, t0_s, dur_s)`` — ``obs/trace_export.py`` installs a ring
buffer there and renders Chrome trace_event JSON from it.  The sink fires
only on the registry-enabled path (the disabled fast path is untouched)
and a sink exception never propagates into the instrumented caller.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

from dryad_tpu.obs.registry import Registry, default_registry

SECONDS = "dryad_span_seconds_total"
COUNT = "dryad_span_count_total"

_TLS = threading.local()

#: trace sink: None, or a callable(path, t0_s, dur_s) — see module doc
_TRACE_SINK = None


def set_trace_sink(sink) -> None:
    """Install (or clear, with ``None``) the span trace sink.  The sink
    must be cheap and non-raising and accept ``(path, t0_s, dur_s)``
    plus an optional keyword-able 4th ``trace`` argument (r17);
    trace_export.SpanTrace.record is the intended one."""
    global _TRACE_SINK
    _TRACE_SINK = sink


def sink_active() -> bool:
    """Whether a span trace sink is installed (the ring is listening)."""
    return _TRACE_SINK is not None


class _NullSpan:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL = _NullSpan()


def _emit(reg: Registry, path: str, seconds: float) -> None:
    # count BEFORE seconds (and snapshot() reads seconds before counts): a
    # scrape tearing between the two families then at worst sees a span
    # with count=1 and a not-yet-summed wall (benign), never the
    # self-contradictory total_s > 0 with count 0
    reg.counter(COUNT, "Completions per span path").labels(span=path).inc()
    reg.counter(SECONDS, "Aggregate wall seconds per span path").labels(
        span=path).inc(seconds)


class _Span:
    __slots__ = ("_reg", "name", "path", "_t0")

    def __init__(self, reg: Registry, name: str):
        self._reg = reg
        self.name = name
        self.path = name
        self._t0 = 0.0

    def __enter__(self):
        stack = getattr(_TLS, "stack", None)
        if stack is None:
            stack = _TLS.stack = []
        if stack:
            self.path = stack[-1].path + "/" + self.name
        stack.append(self)
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        dt = time.perf_counter() - self._t0
        stack = _TLS.stack
        if stack and stack[-1] is self:
            stack.pop()
        _emit(self._reg, self.path, dt)
        sink = _TRACE_SINK
        if sink is not None:
            try:
                sink(self.path, self._t0, dt)
            except Exception:   # noqa: BLE001 — tracing must never break
                pass            # the instrumented caller
        return False


def span(name: str, registry: Optional[Registry] = None):
    """A context manager timing one stage into the span series (nested
    under the thread's enclosing span, if any)."""
    reg = registry if registry is not None else default_registry()
    if not reg.enabled:
        return _NULL
    return _Span(reg, name)


def record(name: str, seconds: float,
           registry: Optional[Registry] = None) -> None:
    """Record one completed stage without a ``with`` block.  The name is
    taken as a FULL path (no nesting prefix) — callers timing a loop body
    manually own their naming."""
    reg = registry if registry is not None else default_registry()
    if not reg.enabled:
        return
    _emit(reg, name, seconds)
    sink = _TRACE_SINK
    if sink is not None:
        try:
            # the stage just ENDED; back-date its start by its duration
            sink(name, time.perf_counter() - seconds, seconds)
        except Exception:   # noqa: BLE001 — tracing must never break callers
            pass


def record_at(name: str, t0_s: float, seconds: float,
              trace: Optional[str] = None,
              registry: Optional[Registry] = None) -> None:
    """Record a completed stage with an EXPLICIT start time and an
    optional request trace id (r17: the serve/fleet request path stamps
    its per-request stage spans after the fact, from timestamps carried
    across the batcher hand-off — back-dating via ``record`` would lie
    about when the stage ran)."""
    reg = registry if registry is not None else default_registry()
    if not reg.enabled:
        return
    _emit(reg, name, seconds)
    sink = _TRACE_SINK
    if sink is not None:
        try:
            sink(name, t0_s, seconds, trace)
        except Exception:   # noqa: BLE001 — tracing must never break callers
            pass


def snapshot(registry: Optional[Registry] = None) -> dict:
    """``{path: {"count": n, "total_s": s, "mean_ms": m}}`` — the span
    slice of the registry, shaped for the ``/stats`` endpoint."""
    reg = registry if registry is not None else default_registry()
    walls = reg.counter(SECONDS).series()     # seconds first — see _emit
    counts = reg.counter(COUNT).series()

    def path_of(lbl: str) -> str:
        # label block is span="<path>"
        return lbl.split('"', 2)[1] if '"' in lbl else lbl

    out = {}
    for lbl, total in walls.items():
        n = counts.get(lbl, 0.0)
        out[path_of(lbl)] = {
            "count": int(n),
            "total_s": round(total, 6),
            "mean_ms": round(total / n * 1e3, 3) if n else 0.0,
        }
    return out
