"""Process health state behind ``/healthz`` (train exporter AND serve).

r9's ``/healthz`` was a liveness ping only — it said "the HTTP thread is
alive", never "the run is healthy".  r12 makes it a DEGRADATION surface:
subsystems raise named degradation reasons (a fetch pending past the
stall threshold, an unexpected recompile after warmup) and clear them on
recovery; ``/healthz`` answers 200 ``{"ok": true}`` while the reason set
is empty and 503 ``{"ok": false, "degraded": [...]}`` otherwise, so a
probe sees a hang while it is still recoverable (STATUS r5: fetches
pending >~1 min die — by the time the supervisor classifies the corpse,
the probe window is long gone).

Contracts (the obs package rules, registry.py):

* host-side only — reasons are strings set by code that already knows the
  condition; nothing here touches jax;
* zero-cost when disabled is N/A by construction: nothing records per
  iteration — ``degrade``/``clear`` fire on rare state TRANSITIONS, and
  reads happen only when a probe asks.

The degradation set also mirrors into the registry as the
``dryad_health_degraded{reason=...}`` gauge (1 while degraded, 0 after
recovery) so scrapers that only see ``/metrics`` get the same signal.
"""

from __future__ import annotations

import threading
from typing import Optional

from dryad_tpu.obs.registry import Registry, default_registry


class HealthState:
    """A named set of active degradation reasons, mirrored to a gauge."""

    GUARDED_BY = {"_reasons": "_lock"}

    def __init__(self, registry: Optional[Registry] = None):
        self._lock = threading.Lock()
        self._reasons: dict[str, str] = {}   # reason -> detail
        self._registry = registry

    def _reg(self) -> Registry:
        # resolved lazily so set_default_registry() swaps reach us (tests)
        return (self._registry if self._registry is not None
                else default_registry())

    def degrade(self, reason: str, detail: str = "") -> None:
        with self._lock:
            self._reasons[str(reason)] = str(detail)
        reg = self._reg()
        if reg.enabled:
            reg.gauge("dryad_health_degraded",
                      "1 while the named degradation is active").labels(
                reason=reason).set(1)

    def clear(self, reason: str) -> None:
        with self._lock:
            self._reasons.pop(str(reason), None)
        reg = self._reg()
        if reg.enabled:
            reg.gauge("dryad_health_degraded",
                      "1 while the named degradation is active").labels(
                reason=reason).set(0)

    def reset(self) -> None:
        """Drop every active reason (tests / a fresh serving generation)."""
        with self._lock:
            reasons = list(self._reasons)
        for r in reasons:
            self.clear(r)

    @property
    def ok(self) -> bool:
        with self._lock:
            return not self._reasons

    def reasons(self) -> dict[str, str]:
        with self._lock:
            return dict(self._reasons)


def healthz_payload(health: Optional[HealthState] = None) -> tuple[int, dict]:
    """(status_code, body) for a /healthz GET — shared by the standalone
    metrics exporter and the serve front end so both flip together.
    Always auth-exempt at the callers (probes must not need credentials).
    """
    h = health if health is not None else default_health()
    if h.ok:
        return 200, {"ok": True}
    return 503, {"ok": False, "degraded": sorted(h.reasons())}


_default: Optional[HealthState] = None
_default_lock = threading.Lock()


def default_health() -> HealthState:
    """The process-wide health state every /healthz endpoint serves."""
    global _default
    if _default is None:
        with _default_lock:
            if _default is None:
                _default = HealthState()
    return _default
