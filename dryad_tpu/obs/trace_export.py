"""Chrome trace_event export: spans + journal + stage walls, Perfetto-ready.

The obs stack already holds three time-shaped truths — the span tree
(per-stage host walls, nested), the supervised-run journal (faults,
backoffs, resumes with run-relative timestamps) and the stage profiler's
device walls (``engine/probes`` results) — but until r13 none of them
rendered as a timeline.  This module emits the Chrome ``trace_event``
JSON format (the ``{"traceEvents": [...]}`` object form), loadable in
Perfetto / ``chrome://tracing``:

* **spans** — complete events (``ph: "X"``) under pid 1, one track per
  thread, captured live by ``SpanTrace`` (a bounded ring buffer installed
  as the span trace sink — ``enable_tracing()``).  Nesting is preserved
  by construction: a child span's [ts, ts+dur] interval lies inside its
  parent's on the same tid, and longer events sort first at equal ts so
  viewers stack them correctly.
* **journal events** — instant events (``ph: "i"``) under pid 2.  Their
  clock is the journal's own run-relative ``elapsed_s``, so they live on
  a separate process track rather than pretending to share the span
  clock.
* **stage walls** — complete events under pid 3, laid out back to back.
  Probe walls are per-stage MINIMA from the timed-fori harness, not a
  recorded timeline; the sequential layout just makes their relative
  magnitudes visible next to the host spans.

Consumers: ``GET /trace`` on the metrics exporter and ``--trace-out`` on
the train CLI.  Pure stdlib — the obs package is jax-free by lint.
"""

from __future__ import annotations

import json
import threading
from collections import deque
from typing import Optional, Sequence

from dryad_tpu.obs import spans

#: ring capacity: ~64k spans ≈ hours of chunked training at obs cadence
DEFAULT_CAPACITY = 65536


class SpanTrace:
    """Bounded thread-safe ring of completed spans ``(path, t0_s, dur_s,
    tid)`` — the span trace sink (spans.set_trace_sink)."""

    GUARDED_BY = {"_events": "_lock", "dropped": "_lock"}

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        self._events: deque = deque(maxlen=int(capacity))
        self._lock = threading.Lock()
        self.dropped = 0

    def record(self, path: str, t0_s: float, dur_s: float) -> None:
        tid = threading.get_ident() & 0xFFFF
        with self._lock:
            if len(self._events) == self._events.maxlen:
                self.dropped += 1
            self._events.append((path, t0_s, dur_s, tid))

    def events(self) -> list:
        with self._lock:
            return list(self._events)

    def clear(self) -> None:
        with self._lock:
            self._events.clear()
            self.dropped = 0


_default: Optional[SpanTrace] = None
_default_lock = threading.Lock()


def enable_tracing(capacity: int = DEFAULT_CAPACITY) -> SpanTrace:
    """Install (idempotently) the process-default SpanTrace as the span
    sink and return it.  Spans record into it only while the registry is
    enabled (the zero-cost-disabled contract is untouched).  The ring is
    process-wide and NOT cleared here (a live /trace endpoint may still
    be serving it); a caller scoping a trace to one run clears the
    returned buffer itself — the train CLI's --trace-out does.  A
    ``capacity`` different from the existing default ring's is ignored."""
    global _default
    with _default_lock:
        if _default is None:
            _default = SpanTrace(capacity)
    spans.set_trace_sink(_default.record)
    return _default


def disable_tracing() -> None:
    spans.set_trace_sink(None)


def default_trace() -> Optional[SpanTrace]:
    return _default


def to_trace_events(span_events: Sequence = (),
                    journal_events: Sequence[dict] = (),
                    stages: Sequence[dict] = ()) -> list:
    """One flat, ts-sorted trace_event list from the three sources.
    Timestamps are microseconds; span ts keep their perf_counter origin
    (arbitrary but shared), journal ts are run-relative (own pid)."""
    meta = [
        {"ph": "M", "pid": 1, "tid": 0, "name": "process_name",
         "args": {"name": "dryad spans (host walls)"}},
        {"ph": "M", "pid": 2, "tid": 0, "name": "process_name",
         "args": {"name": "dryad journal (run-relative)"}},
        {"ph": "M", "pid": 3, "tid": 0, "name": "process_name",
         "args": {"name": "dryad stage walls (timed-fori minima)"}},
    ]
    evs = []
    for path, t0, dur, tid in span_events:
        evs.append({
            "ph": "X", "cat": "span", "pid": 1, "tid": int(tid),
            "name": str(path).rsplit("/", 1)[-1],
            "ts": round(float(t0) * 1e6, 3),
            "dur": round(float(dur) * 1e6, 3),
            "args": {"path": str(path)},
        })
    for e in journal_events:
        args = {k: v for k, v in e.items()
                if k not in ("event", "elapsed_s")
                and isinstance(v, (str, int, float, bool))}
        evs.append({
            "ph": "i", "cat": "journal", "pid": 2, "tid": 0, "s": "p",
            "name": str(e.get("event", "event")),
            "ts": round(float(e.get("elapsed_s", 0.0)) * 1e6, 3),
            "args": args,
        })
    cursor = 0.0
    for st in stages:
        name = str(st.get("stage", "stage"))
        if st.get("arm"):
            name = f"{name}[{st['arm']}]"
        dur = max(float(st.get("ms", 0.0)) * 1e3, 0.0)
        args = {k: v for k, v in st.items()
                if isinstance(v, (str, int, float, bool))}
        evs.append({"ph": "X", "cat": "stage", "pid": 3, "tid": 0,
                    "name": name, "ts": round(cursor, 3),
                    "dur": round(dur, 3), "args": args})
        cursor += dur
    # monotonic ts; longer events first at equal ts so nesting stacks
    evs.sort(key=lambda e: (e["ts"], -e.get("dur", 0.0)))
    return meta + evs


def dumps_trace(span_events: Sequence = (),
                journal_events: Sequence[dict] = (),
                stages: Sequence[dict] = ()) -> str:
    """The loadable JSON document (object form, ms display unit)."""
    return json.dumps({
        "traceEvents": to_trace_events(span_events, journal_events, stages),
        "displayTimeUnit": "ms",
    })


def write_trace(path: str, span_events: Sequence = (),
                journal_events: Sequence[dict] = (),
                stages: Sequence[dict] = ()) -> None:
    with open(path, "w") as f:
        f.write(dumps_trace(span_events, journal_events, stages))
        f.write("\n")
