"""Chrome trace_event export: spans + journal + stage walls, Perfetto-ready.

The obs stack already holds three time-shaped truths — the span tree
(per-stage host walls, nested), the supervised-run journal (faults,
backoffs, resumes with run-relative timestamps) and the stage profiler's
device walls (``engine/probes`` results) — but until r13 none of them
rendered as a timeline.  This module emits the Chrome ``trace_event``
JSON format (the ``{"traceEvents": [...]}`` object form), loadable in
Perfetto / ``chrome://tracing``:

* **spans** — complete events (``ph: "X"``) under pid 1, one track per
  thread, captured live by ``SpanTrace`` (a bounded ring buffer installed
  as the span trace sink — ``enable_tracing()``).  Nesting is preserved
  by construction: a child span's [ts, ts+dur] interval lies inside its
  parent's on the same tid, and longer events sort first at equal ts so
  viewers stack them correctly.
* **journal events** — instant events (``ph: "i"``) under pid 2.  Their
  clock is the journal's own run-relative ``elapsed_s``, so they live on
  a separate process track rather than pretending to share the span
  clock.
* **stage walls** — complete events under pid 3, laid out back to back.
  Probe walls are per-stage MINIMA from the timed-fori harness, not a
  recorded timeline; the sequential layout just makes their relative
  magnitudes visible next to the host spans.

Consumers: ``GET /trace`` on the metrics exporter and ``--trace-out`` on
the train CLI.  Pure stdlib — the obs package is jax-free by lint.
"""

from __future__ import annotations

import json
import threading
from collections import deque
from typing import Optional, Sequence

from dryad_tpu.obs import spans

#: ring capacity: ~64k spans ≈ hours of chunked training at obs cadence
DEFAULT_CAPACITY = 65536


class SpanTrace:
    """Bounded thread-safe ring of completed spans ``(path, t0_s, dur_s,
    tid, trace)`` — the span trace sink (spans.set_trace_sink).  ``trace``
    is the request trace id (r17) or None for untagged spans."""

    GUARDED_BY = {"_events": "_lock", "dropped": "_lock"}

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        self._events: deque = deque(maxlen=int(capacity))
        self._lock = threading.Lock()
        self.dropped = 0

    def record(self, path: str, t0_s: float, dur_s: float,
               trace: Optional[str] = None) -> None:
        tid = threading.get_ident() & 0xFFFF
        with self._lock:
            if len(self._events) == self._events.maxlen:
                self.dropped += 1
            self._events.append((path, t0_s, dur_s, tid, trace))

    def events(self) -> list:
        with self._lock:
            return list(self._events)

    def export(self) -> tuple:
        """(events, dropped) in one consistent read — the shape the
        replica ``/trace/events`` endpoint serializes."""
        with self._lock:
            return list(self._events), self.dropped

    def clear(self) -> None:
        with self._lock:
            self._events.clear()
            self.dropped = 0


_default: Optional[SpanTrace] = None
_default_lock = threading.Lock()


def enable_tracing(capacity: int = DEFAULT_CAPACITY) -> SpanTrace:
    """Install (idempotently) the process-default SpanTrace as the span
    sink and return it.  Spans record into it only while the registry is
    enabled (the zero-cost-disabled contract is untouched).  The ring is
    process-wide and NOT cleared here (a live /trace endpoint may still
    be serving it); a caller scoping a trace to one run clears the
    returned buffer itself — the train CLI's --trace-out does.  A
    ``capacity`` different from the existing default ring's is ignored."""
    global _default
    with _default_lock:
        if _default is None:
            _default = SpanTrace(capacity)
    spans.set_trace_sink(_default.record)
    return _default


def disable_tracing() -> None:
    spans.set_trace_sink(None)


def default_trace() -> Optional[SpanTrace]:
    return _default


def active_trace() -> Optional[SpanTrace]:
    """The SpanTrace actually receiving spans right now: the ring whose
    bound ``record`` is installed as the sink (a test/caller-scoped ring
    counts), else the process default.  Consumers that SERVE the ring
    (the fleet router's /trace) resolve through this, so they follow
    whatever sink is live instead of insisting on the default."""
    sink = spans._TRACE_SINK
    if sink is None:
        return None
    owner = getattr(sink, "__self__", None)
    return owner if isinstance(owner, SpanTrace) else _default


def tracing_active(registry=None) -> bool:
    """Whether request tracing is ON: a span ring is installed AND the
    registry records.  This is the per-request gate the serve/fleet
    request paths check FIRST — when it is False the request path mints
    no trace id and allocates no per-request context (the zero-cost-
    disabled contract, same idiom as the spans null context)."""
    from dryad_tpu.obs.registry import default_registry

    reg = registry if registry is not None else default_registry()
    return reg.enabled and spans.sink_active()


class TailSampler:
    """Tail sampling for merged traces: remember the slowest requests.

    ``observe(trace_id, dur_s)`` is O(window) only on eviction, O(1)
    amortized; ``slowest(k)`` returns the trace ids of the k slowest
    requests inside the current window (the last ``window`` observed
    requests).  The merged ``/trace`` endpoint keeps FULL span detail
    for those ids and drops the per-request detail of everything else,
    bounding trace size under sustained load while guaranteeing the
    interesting (slow) requests keep their whole story."""

    GUARDED_BY = {"_ring": "_lock"}

    def __init__(self, window: int = 512):
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=int(window))

    def observe(self, trace_id: Optional[str], dur_s: float) -> None:
        if trace_id is None:
            return
        with self._lock:
            self._ring.append((float(dur_s), str(trace_id)))

    def slowest(self, k: int) -> set:
        """Trace ids of the ``k`` slowest requests in the window
        (``k <= 0`` means keep everything observed)."""
        with self._lock:
            items = list(self._ring)
        if k <= 0:
            return {t for _, t in items}
        items.sort(key=lambda x: -x[0])
        return {t for _, t in items[:int(k)]}


def _span_event(ev, pid: int, offset_s: float = 0.0) -> dict:
    """ONE renderer for a ring event ``(path, t0_s, dur_s, tid[,
    trace])`` → a Chrome complete event — shared by the single-process
    and fleet documents so the tuple shape has exactly one decoder."""
    path, t0, dur, tid = ev[:4]
    trace = ev[4] if len(ev) > 4 else None
    args = {"path": str(path)}
    if trace is not None:
        args["trace"] = str(trace)
    return {
        "ph": "X", "cat": "span", "pid": int(pid), "tid": int(tid),
        "name": str(path).rsplit("/", 1)[-1],
        "ts": round((float(t0) + offset_s) * 1e6, 3),
        "dur": round(float(dur) * 1e6, 3),
        "args": args,
    }


def to_trace_events(span_events: Sequence = (),
                    journal_events: Sequence[dict] = (),
                    stages: Sequence[dict] = ()) -> list:
    """One flat, ts-sorted trace_event list from the three sources.
    Timestamps are microseconds; span ts keep their perf_counter origin
    (arbitrary but shared), journal ts are run-relative (own pid)."""
    meta = [
        {"ph": "M", "pid": 1, "tid": 0, "name": "process_name",
         "args": {"name": "dryad spans (host walls)"}},
        {"ph": "M", "pid": 2, "tid": 0, "name": "process_name",
         "args": {"name": "dryad journal (run-relative)"}},
        {"ph": "M", "pid": 3, "tid": 0, "name": "process_name",
         "args": {"name": "dryad stage walls (timed-fori minima)"}},
    ]
    evs = [_span_event(ev, pid=1) for ev in span_events]
    for e in journal_events:
        args = {k: v for k, v in e.items()
                if k not in ("event", "elapsed_s")
                and isinstance(v, (str, int, float, bool))}
        evs.append({
            "ph": "i", "cat": "journal", "pid": 2, "tid": 0, "s": "p",
            "name": str(e.get("event", "event")),
            "ts": round(float(e.get("elapsed_s", 0.0)) * 1e6, 3),
            "args": args,
        })
    cursor = 0.0
    for st in stages:
        name = str(st.get("stage", "stage"))
        if st.get("arm"):
            name = f"{name}[{st['arm']}]"
        dur = max(float(st.get("ms", 0.0)) * 1e3, 0.0)
        args = {k: v for k, v in st.items()
                if isinstance(v, (str, int, float, bool))}
        evs.append({"ph": "X", "cat": "stage", "pid": 3, "tid": 0,
                    "name": name, "ts": round(cursor, 3),
                    "dur": round(dur, 3), "args": args})
        cursor += dur
    # monotonic ts; longer events first at equal ts so nesting stacks
    evs.sort(key=lambda e: (e["ts"], -e.get("dur", 0.0)))
    return meta + evs


def dumps_trace(span_events: Sequence = (),
                journal_events: Sequence[dict] = (),
                stages: Sequence[dict] = ()) -> str:
    """The loadable JSON document (object form, ms display unit)."""
    return json.dumps({
        "traceEvents": to_trace_events(span_events, journal_events, stages),
        "displayTimeUnit": "ms",
    })


def write_trace(path: str, span_events: Sequence = (),
                journal_events: Sequence[dict] = (),
                stages: Sequence[dict] = ()) -> None:
    with open(path, "w") as f:
        f.write(dumps_trace(span_events, journal_events, stages))
        f.write("\n")


# ---------------------------------------------------------------------------
# fleet trace assembly (r17): one merged, clock-aligned document


def fleet_trace_events(tracks: Sequence[dict],
                       journal_events: Sequence[dict] = (),
                       keep: Optional[set] = None) -> list:
    """One merged trace from per-process span tracks.

    Each track is ``{"pid": int, "name": str, "events": [(path, t0_s,
    dur_s, tid, trace), ...], "offset_s": float}`` — ``offset_s`` maps
    the process's ``perf_counter`` origin onto the shared wall clock
    (the registration-time clock handshake), so router and replica spans
    line up on ONE timeline.  ``keep`` (when not None) is the tail
    sample: trace-TAGGED events survive only if their id is in it;
    untagged infrastructure spans always survive.  Journal events ride
    their own pid-0 track on the journal's run-relative clock (they
    annotate, not align — same convention as ``to_trace_events``)."""
    meta = [{"ph": "M", "pid": 0, "tid": 0, "name": "process_name",
             "args": {"name": "fleet journal (run-relative)"}}]
    evs = []
    for e in journal_events:
        args = {k: v for k, v in e.items()
                if k not in ("event", "elapsed_s")
                and isinstance(v, (str, int, float, bool))}
        evs.append({"ph": "i", "cat": "journal", "pid": 0, "tid": 0,
                    "s": "p", "name": str(e.get("event", "event")),
                    "ts": round(float(e.get("elapsed_s", 0.0)) * 1e6, 3),
                    "args": args})
    for track in tracks:
        pid = int(track["pid"])
        meta.append({"ph": "M", "pid": pid, "tid": 0,
                     "name": "process_name",
                     "args": {"name": str(track["name"])}})
        offset = float(track.get("offset_s") or 0.0)
        for ev in track["events"]:
            trace = ev[4] if len(ev) > 4 else None
            if keep is not None and trace is not None and trace not in keep:
                continue
            evs.append(_span_event(ev, pid=pid, offset_s=offset))
    evs.sort(key=lambda e: (e["ts"], -e.get("dur", 0.0)))
    return meta + evs


def dumps_fleet_trace(tracks: Sequence[dict],
                      journal_events: Sequence[dict] = (),
                      keep: Optional[set] = None) -> str:
    return json.dumps({
        "traceEvents": fleet_trace_events(tracks, journal_events, keep),
        "displayTimeUnit": "ms",
    })
