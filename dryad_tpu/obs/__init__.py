"""dryad_tpu.obs — the unified observability subsystem.

One process-wide telemetry registry spans training (both backends),
serving, and resilient-run supervision; trace spans decompose loop wall
into per-stage series; an stdlib HTTP exporter serves ``/metrics``
(Prometheus text), ``/stats`` (JSON), and ``/healthz``; and a journal
tail folds the supervised-run flight recorder into live series.

r12 adds the device-truth layer: a process health state behind
``/healthz`` (health.py), the fetch-stall watchdog
(``dryad_fetch_*`` — watchdog.py), the recompile tripwire
(``dryad_recompile_unexpected_total`` — tripwire.py), and the bench
trend ledger over the committed ``BENCH_r*.json`` history (trends.py).
The compiled-program cost/memory capture that FEEDS ``dryad_prog_*``
lives OUTSIDE this package (engine/introspect.py): it touches jax, and
obs collectors only record values the engine already fetched.

r13 adds the stage-profiler aggregation (``dryad_stage_ms`` gauges +
stamped ``PROFILE_r*.json`` artifacts — profiler.py; the timed-fori
harness that MEASURES them is engine/probes.py, outside this package
for the same jax-freedom reason) and Chrome trace_event export of the
span ring / journal / stage walls (trace_export.py, ``GET /trace``).

r17 adds the request-scoped layer: the fixed-log-bucket histogram kind
(registry.py — O(1) observe, exact cross-process merge, the fleet-wide
p99 substrate), trace-tagged spans (spans.record_at + the SpanTrace
``trace`` field), tail sampling (TailSampler) and fleet trace assembly
(trace_export.fleet_trace_events), and per-priority latency SLO gates
(slo.SloGate — sustained-breach /healthz degradation).

Hard contracts (see registry.py / scripts/ci.sh):

* host-side only — nothing here may touch jax or fetch from a device;
* zero-cost when disabled (``DRYAD_OBS=0`` or ``disable()``) — measured
  as ``obs_overhead_ms`` in bench.py, not just claimed.

    from dryad_tpu.obs import default_registry, span, start_exporter

    with span("my_stage"):
        ...
    exporter = start_exporter(port=9100)   # GET /stats, /metrics, /healthz
"""

from dryad_tpu.obs.exporter import MetricsExporter, start_exporter
from dryad_tpu.obs.health import HealthState, default_health, healthz_payload
from dryad_tpu.obs.journal_tail import JournalTail
from dryad_tpu.obs.registry import (
    LOG_BUCKETS,
    Registry,
    default_registry,
    hist_quantile,
    merge_hist_states,
    set_default_registry,
)
from dryad_tpu.obs.slo import SloGate, parse_budgets
from dryad_tpu.obs.spans import record, record_at, span
from dryad_tpu.obs.trace_export import (
    SpanTrace,
    TailSampler,
    default_trace,
    disable_tracing,
    enable_tracing,
    tracing_active,
)
from dryad_tpu.obs.tripwire import RecompileTripwire, default_tripwire
from dryad_tpu.obs.watchdog import (
    FetchWatchdog,
    default_watchdog,
    set_default_watchdog,
    watch_fetch,
)

__all__ = [
    "Registry",
    "default_registry",
    "set_default_registry",
    "span",
    "record",
    "MetricsExporter",
    "start_exporter",
    "JournalTail",
    "HealthState",
    "default_health",
    "healthz_payload",
    "FetchWatchdog",
    "default_watchdog",
    "set_default_watchdog",
    "watch_fetch",
    "RecompileTripwire",
    "default_tripwire",
    "SpanTrace",
    "enable_tracing",
    "disable_tracing",
    "default_trace",
    "record_at",
    "tracing_active",
    "TailSampler",
    "SloGate",
    "parse_budgets",
    "LOG_BUCKETS",
    "merge_hist_states",
    "hist_quantile",
]
