"""Recompile tripwire: "zero recompiles after warmup" as a live alarm.

Two production invariants exist only as test assertions today: warm serve
traffic never recompiles (tests/test_serve.py, bench_serve --smoke), and
a training run's compiled programs are fixed once the first chunk has
dispatched (``p_key`` strips every field that cannot affect the program,
train.py).  Through the remote tunnel a silent recompile is not a
slowdown but an outage — 70–120 s of compile wall mid-traffic — and the
fusion-shape change it implies is the near-tie argmax-flip class the
jaxpr auditor's digests guard offline.  This module is the ONLINE half:

* producers call ``note_compile(program, key)`` at each compile boundary
  (serve's compiled-entry cache on a cold key, the device trainer via
  engine/introspect.py);
* once the expected-compile budget is spent the producer calls
  ``arm(program)`` ("warmup complete / first chunk dispatched — nothing
  may compile again");
* a ``note_compile`` with a NEW key on an armed program increments
  ``dryad_recompile_unexpected_total{program=...}``, flips ``/healthz``
  to degraded (reason ``recompile``), and notifies listeners (the
  supervisor registers one that writes a ``recompile_unexpected`` event
  into the run journal).

``begin_program(program)`` resets a family for a new run/generation
(disarms, forgets keys, clears the degradation) — a second training run
or a rebuilt serve cache legitimately compiles fresh programs.

Obs contracts: host-side only (keys are hashable host values the caller
already holds — never an array), zero-cost when disabled (``note_compile``
returns after the enabled check; compile-boundary frequency anyway).
"""

from __future__ import annotations

import threading
from typing import Callable, Optional

from dryad_tpu.obs.health import HealthState, default_health
from dryad_tpu.obs.registry import Registry, default_registry

def health_reason(program: str) -> str:
    """The degradation key is scoped PER FAMILY: a training run beginning
    its own generation must never clear a co-located serve family's live
    recompile alarm (and vice versa)."""
    return f"recompile:{program}"


class RecompileTripwire:
    GUARDED_BY = {"_keys": "_lock", "_armed": "_lock",
                  "_listeners": "_lock"}

    def __init__(self, registry: Optional[Registry] = None,
                 health: Optional[HealthState] = None):
        self._registry = registry
        self._health = health
        self._lock = threading.Lock()
        self._keys: dict[str, set] = {}      # program -> seen keys
        self._armed: dict[str, bool] = {}
        self._listeners: list[Callable[[str, str], None]] = []

    def _reg(self) -> Registry:
        return (self._registry if self._registry is not None
                else default_registry())

    def _hp(self) -> HealthState:
        return self._health if self._health is not None else default_health()

    # ---- lifecycle ---------------------------------------------------------
    def begin_program(self, program: str) -> None:
        """A new run/generation of ``program`` starts: forget its keys,
        disarm, clear any standing degradation — for THIS family only."""
        with self._lock:
            self._keys[program] = set()
            self._armed[program] = False
        self._hp().clear(health_reason(program))

    def arm(self, program: str) -> None:
        """Expected-compile budget spent — any further NEW key on this
        program is an unexpected recompile.  Arming requires at least one
        NOTED key: with the registry disabled no keys are ever noted, and
        arming an empty family would turn a later mid-run ``enable()``
        (supported since r9) into a guaranteed false positive — an empty
        armed family cannot tell expected from unexpected, so it stays
        inert instead.  Arming also clears the family's standing
        degradation: re-warm + re-arm IS the documented recovery path
        after a deploy or a fired alarm."""
        with self._lock:
            if not self._keys.get(program):
                return
            self._armed[program] = True
        self._hp().clear(health_reason(program))

    def disarm(self, program: str) -> None:
        """Open a deploy window: a model load legitimately introduces new
        compiles, so the producer disarms (keeping the key history),
        warms the new programs, and re-arms via ``arm()``."""
        with self._lock:
            self._armed[program] = False
        self._hp().clear(health_reason(program))

    def armed(self, program: str) -> bool:
        with self._lock:
            return bool(self._armed.get(program))

    # ---- the boundary hook -------------------------------------------------
    def note_compile(self, program: str, key, detail: str = "") -> bool:
        """Record one compile boundary; returns True when the key is new.
        A new key on an ARMED program fires the tripwire."""
        reg = self._reg()
        if not reg.enabled:
            return False
        with self._lock:
            seen = self._keys.setdefault(program, set())
            new = key not in seen
            if new:
                seen.add(key)
            fired = new and self._armed.get(program, False)
        if new:
            reg.counter("dryad_prog_compiles_total",
                        "Compile boundaries by program family").labels(
                program=program).inc()
        if fired:
            self.unexpected(program, detail or f"new program key {key!r} "
                            "after warmup")
        return new

    def unexpected(self, program: str, detail: str = "") -> None:
        reg = self._reg()
        if reg.enabled:
            reg.counter("dryad_recompile_unexpected_total",
                        "Compiles observed after the expected-compile "
                        "budget was spent").labels(program=program).inc()
        self._hp().degrade(health_reason(program),
                           f"unexpected recompile in {program}: {detail}")
        with self._lock:
            listeners = list(self._listeners)
        for fn in listeners:
            try:
                fn(program, detail)
            except Exception:   # noqa: BLE001 — a dead listener must not
                pass            # break the producer's dispatch path

    # ---- listeners (the supervisor's journal hookup) -----------------------
    def add_listener(self, fn: Callable[[str, str], None]) -> Callable[[], None]:
        """Register ``fn(program, detail)`` for unexpected recompiles;
        returns a remover (duck-typed — the journal lives in resilience,
        which imports obs, so obs must not import it back)."""
        with self._lock:
            self._listeners.append(fn)

        def remove() -> None:
            with self._lock:
                if fn in self._listeners:
                    self._listeners.remove(fn)

        return remove


_default: Optional[RecompileTripwire] = None
_default_lock = threading.Lock()


def default_tripwire() -> RecompileTripwire:
    global _default
    if _default is None:
        with _default_lock:
            if _default is None:
                _default = RecompileTripwire()
    return _default
