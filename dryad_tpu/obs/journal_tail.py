"""Tail a resilience ``RunJournal`` (JSONL) into registry series.

This closes ROADMAP r8 follow-up (c): the supervised-run flight recorder
(fault classifications, chunk-cap degradations, resume points, chunk
traffic) becomes live series on the ``/stats``/``/metrics`` endpoint
instead of a file someone greps after the fact.

The adapter works on the FILE, not the ``RunJournal`` object: the journal
is line-buffered append (journal.py), so a poll sees every completed
event of a live run, and the same ``poll()`` replays a finished journal
post-hoc (parity with ``RunJournal.read()`` is test-pinned).  A partial
trailing line (a write raced mid-poll) is carried to the next poll, never
half-parsed.  Counters are the ONLY journal-event consumers in the
registry — the supervisor itself does not double-record them.

Series produced (event vocabulary from resilience/journal.py):

* ``dryad_run_events_total{event=...}`` — every event, by kind
* ``dryad_run_faults_total{kind=...}`` — fault classifications
* ``dryad_run_chunk_backoffs_total`` + ``dryad_run_ch_max`` (gauge) —
  chunk-cap degradations and the live cap
* ``dryad_run_resumes_total`` + ``dryad_run_resume_iteration`` (gauge)
* ``dryad_run_iteration`` (gauge) — last chunk_dispatch/fetch iteration
* ``dryad_run_attempt`` (gauge) — segment attempt counter
* ``dryad_run_wall_seconds`` / ``dryad_run_iterations`` (gauges) — from
  the ``complete`` event

Pure stdlib file reads — no jax, no device (the obs package contract).
"""

from __future__ import annotations

import json
import os
import threading
from typing import Optional

from dryad_tpu.obs.registry import Registry, default_registry


class JournalTail:
    """Incrementally fold a journal file's events into ``registry``.

    ``poll()`` consumes everything appended since the last poll and
    returns the number of events folded; ``start()`` polls on a daemon
    thread for live runs (``stop()`` runs one final poll so no tail
    events are lost at shutdown).  ``_lock`` serializes whole polls —
    offset, carry, and the fold are one atomic unit, so a caller's poll
    racing the background tick can never double-fold a line."""

    GUARDED_BY = {"_offset": "_lock", "_carry": "_lock",
                  "events_seen": "_lock"}

    def __init__(self, path: str, registry: Optional[Registry] = None,
                 poll_interval_s: float = 0.25):
        self.path = os.fspath(path)
        self.registry = registry if registry is not None else default_registry()
        self.poll_interval_s = float(poll_interval_s)
        self.events_seen = 0
        self._offset = 0
        self._carry = ""
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ---- consuming ---------------------------------------------------------
    def poll(self) -> int:
        """Fold newly appended events; safe to call concurrently with the
        background thread and after the run finished (post-hoc replay of a
        whole journal is just one big first poll)."""
        with self._lock:
            try:
                with open(self.path, "r") as fh:
                    fh.seek(self._offset)
                    chunk = fh.read()
                    self._offset = fh.tell()
            except (FileNotFoundError, OSError):
                return 0     # journal not created yet — not an error
            if not chunk:
                return 0
            data = self._carry + chunk
            lines = data.split("\n")
            self._carry = lines.pop()      # '' when chunk ended on a newline
            n = 0
            for line in lines:
                if not line.strip():
                    continue
                try:
                    event = json.loads(line)
                except ValueError:
                    continue               # torn/foreign line: skip, don't die
                self._fold(event)
                n += 1
            self.events_seen += n
            return n

    def _fold(self, e: dict) -> None:
        reg = self.registry
        kind = str(e.get("event", "unknown"))
        if kind == "run_start":
            # an appended/reused journal (--resume, repeated --supervise
            # invocations) begins a NEW run here: drop the prior run's
            # series so the live endpoint mirrors RunJournal.read_last_run
            # instead of presenting stale fault/backoff counts as current
            reg.reset_prefix("dryad_run_")
        reg.counter("dryad_run_events_total",
                    "Supervised-run journal events by kind").labels(
            event=kind).inc()
        if kind == "fault":
            reg.counter("dryad_run_faults_total",
                        "Classified faults by class").labels(
                kind=str(e.get("kind", "unknown"))).inc()
        elif kind == "backoff_chunks":
            reg.counter("dryad_run_chunk_backoffs_total",
                        "Chunk-cap degradations").inc()
            if "ch_max_to" in e:
                reg.gauge("dryad_run_ch_max",
                          "Live supervised chunk cap (0 = uncapped)").set(
                    e["ch_max_to"])
        elif kind == "resume":
            reg.counter("dryad_run_resumes_total",
                        "Auto-resumes from checkpoint").inc()
            if "from_iteration" in e:
                reg.gauge("dryad_run_resume_iteration",
                          "Last resume point").set(e["from_iteration"])
        elif kind == "fail_closed":
            reg.counter("dryad_run_fail_closed_total",
                        "Supervisor fail-closed exits").inc()
        elif kind in ("chunk_dispatch", "chunk_fetch"):
            if "iteration" in e:
                reg.gauge("dryad_run_iteration",
                          "Last journaled loop iteration").set(e["iteration"])
        elif kind == "segment_start":
            if "attempt" in e:
                reg.gauge("dryad_run_attempt",
                          "Supervised segment attempt").set(e["attempt"])
            if "ch_max" in e:
                reg.gauge("dryad_run_ch_max",
                          "Live supervised chunk cap (0 = uncapped)").set(
                    e["ch_max"])
        elif kind == "complete":
            if "wall_s" in e:
                reg.gauge("dryad_run_wall_seconds",
                          "Completed run wall").set(e["wall_s"])
            if "iterations" in e:
                reg.gauge("dryad_run_iterations",
                          "Completed run iterations").set(e["iterations"])

    # ---- live tailing ------------------------------------------------------
    def start(self) -> "JournalTail":
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, daemon=True, name="dryad-journal-tail")
            self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(self.poll_interval_s):
            self.poll()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        self.poll()    # final sweep: events appended after the last tick

    def __enter__(self) -> "JournalTail":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
