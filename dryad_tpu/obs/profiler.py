"""Stage-profile aggregation: gauges + stamped PROFILE artifacts.

The measurement itself lives in the engine (``engine/probes.py`` — it
touches jax); this module only aggregates the plain result dicts the
harness already fetched, per the obs package contract (jax-free by lint,
host-side only):

* ``export_stages`` folds results into ``dryad_stage_ms{stage=,arm=}``
  and ``dryad_stage_spread{stage=,arm=}`` gauges so per-stage device
  walls ride the same ``/metrics`` scrape as everything else;
* ``profile_artifact`` flattens results into the stamped
  ``PROFILE_r*.json`` shape (``stage_ms_<name>`` / ``stage_spread_<name>``
  + the r12 schema/git/device stamps) that ``obs/trends.py`` ingests —
  per-stage regressions get the same newest-vs-median + spread-veto
  verdicts as bench walls.

A result dict needs ``stage`` and ``ms``; ``spread``, ``rows`` and
``arm`` (bench's wired/legacy pairs) are optional.
"""

from __future__ import annotations

import json
from typing import Optional, Sequence

from dryad_tpu.obs.registry import Registry, default_registry
from dryad_tpu.obs.trends import artifact_stamp

STAGE_MS = "dryad_stage_ms"
STAGE_SPREAD = "dryad_stage_spread"


def _stage_key(result: dict) -> str:
    arm = result.get("arm")
    return f"{result['stage']}_{arm}" if arm else str(result["stage"])


def export_stages(results: Sequence[dict],
                  registry: Optional[Registry] = None) -> int:
    """Set one ms + one spread gauge per result; returns series touched."""
    reg = registry if registry is not None else default_registry()
    if not reg.enabled:
        return 0
    ms_fam = reg.gauge(STAGE_MS,
                       "Per-stage device wall (timed-fori min) in ms")
    sp_fam = reg.gauge(STAGE_SPREAD,
                       "Per-stage capture spread (max/min - 1)")
    n = 0
    for r in results:
        labels = {"stage": str(r["stage"])}
        if r.get("arm"):
            labels["arm"] = str(r["arm"])
        ms_fam.labels(**labels).set(float(r["ms"]))
        sp_fam.labels(**labels).set(float(r.get("spread", 0.0)))
        n += 1
    return n


def profile_artifact(results: Sequence[dict],
                     device_kind: Optional[str] = None,
                     root: Optional[str] = None) -> dict:
    """The flat stamped artifact dict (one ``stage_ms_*`` +
    ``stage_spread_*`` pair per stage, context fields untracked)."""
    out: dict = {"profile_schema": 1}
    for r in results:
        key = _stage_key(r)
        out[f"stage_ms_{key}"] = float(r["ms"])
        out[f"stage_spread_{key}"] = float(r.get("spread", 0.0))
        if r.get("rows") is not None:
            out[f"stage_rows_{key}"] = int(r["rows"])
    out.update(artifact_stamp(device_kind=device_kind, root=root))
    return out


def write_profile(results: Sequence[dict], path: str,
                  device_kind: Optional[str] = None,
                  root: Optional[str] = None) -> dict:
    """Write the stamped artifact to ``path``; returns the dict."""
    art = profile_artifact(results, device_kind=device_kind, root=root)
    with open(path, "w") as f:
        json.dump(art, f, indent=1)
        f.write("\n")
    return art
