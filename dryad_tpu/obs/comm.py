"""Comm-payload observability (r16): the growers' static per-iteration
collective accounting (``engine.train._comm_stats``) exported as gauges.

The engine computes the accounting — a pure function of (params, shapes,
shard count), cross-checked against the traced program by the jaxpr
auditor — and hands the finished dict here at its compile boundary; this
module only records values, per the obs registry contract (jax-free by
lint).  Labels: ``growth`` (depthwise/leafwise), ``arm`` (the resolved
``hist_reduce`` — fused/feature), ``shards``.

Series:

* ``dryad_comm_psum_bytes_per_iter`` — the fused-psum payload per
  boosting iteration (the full reduced stack each device receives; on
  the feature arm only the root rides a psum, so a reduce-payload
  regression shows up as this gauge jumping when the arm flips back).
* ``dryad_comm_collective_calls_per_iter`` — total collective calls per
  iteration (psum + reduce-scatter + the combine all-gather).
* ``dryad_comm_reduce_scatter_bytes_per_iter`` /
  ``dryad_comm_all_gather_bytes_per_iter`` /
  ``dryad_comm_collective_bytes_per_iter`` — the feature-arm breakdown
  and the per-device total the ≥4x wide-shape acceptance is stated on.
"""

from __future__ import annotations

from typing import Optional

from dryad_tpu.obs.registry import Registry, default_registry

_GAUGES = (
    ("dryad_comm_psum_bytes_per_iter",
     "Fused-psum histogram payload per boosting iteration (bytes)",
     "psum_bytes_per_iter"),
    ("dryad_comm_collective_calls_per_iter",
     "Collective calls per boosting iteration (psum + rs + ag)",
     "collective_calls_per_iter"),
    ("dryad_comm_reduce_scatter_bytes_per_iter",
     "Feature-arm reduce-scatter payload per iteration (bytes/device)",
     "reduce_scatter_bytes_per_iter"),
    ("dryad_comm_all_gather_bytes_per_iter",
     "Feature-arm combine all-gather payload per iteration (bytes)",
     "all_gather_bytes_per_iter"),
    ("dryad_comm_collective_bytes_per_iter",
     "Total per-device collective payload per iteration (bytes)",
     "collective_bytes_per_iter"),
)


def export_comm_stats(comm: dict, *, growth: str,
                      registry: Optional[Registry] = None) -> int:
    """Record one training run's collective accounting; returns the number
    of series set (0 on a disabled registry — the zero-cost contract)."""
    reg = registry if registry is not None else default_registry()
    if not reg.enabled or not comm:
        return 0
    labels = dict(growth=growth,
                  arm=str(comm.get("hist_reduce", "fused")),
                  shards=int(comm.get("n_shards", 1)))
    n = 0
    for name, doc, key in _GAUGES:
        if key in comm:
            reg.gauge(name, doc).labels(**labels).set(float(comm[key]))
            n += 1
    return n
