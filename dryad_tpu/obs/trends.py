"""Bench trend ledger: the committed ``BENCH_r*.json`` history as data.

ROADMAP's standing instruction — "bench.py trends, not points: acceptance
walls are cold single runs and noisy through the tunnel" — has had no
machinery behind it: the per-round artifacts exist, but nothing compares
them.  This module ingests the committed history, compares the newest
point against the history median with a spread-aware tolerance, and emits
a machine-readable regression report (``scripts/bench_trend.py`` runs it
in ci.sh; the ``/stats`` endpoint can mount it as an extra provider).

Verdict rules (the CLAUDE.md measuring discipline, applied across
rounds instead of within a run):

* a metric regresses only against the MEDIAN of the prior rounds that
  recorded it (a single noisy round can neither fake nor mask a trend);
* the newest point's own per-arm spread fields are consulted first: a
  spread > 5% (``SPREAD_SUSPECT``) marks the verdict ``suspect`` —
  "suspect capture, never a regression verdict";
* the tolerance is deliberately loose (default 15%): cold single runs
  through the tunnel wobble, and the ledger is a tripwire for real
  cliffs, not a 1% gate.

Artifact stamps (r12 satellite): ``bench.py``/``scripts/bench_serve.py``
write ``schema_version``, ``git_rev`` and ``device_kind`` into their JSON
so history keys off data, not filenames; the reader stays
backfill-tolerant for the unstamped r1–r7 files (driver wrapper shape
``{"n", "cmd", "rc", "tail", "parsed": {...}}`` or bench.py's flat line).

Pure stdlib (json/glob/statistics) — the obs package is jax-free by lint.
"""

from __future__ import annotations

import glob
import json
import os
import re
import statistics
from typing import Optional, Sequence

from dryad_tpu.obs.registry import Registry, default_registry

#: per-arm spread above this flags the capture (CLAUDE.md / serve bench)
SPREAD_SUSPECT = 0.05
#: relative regression tolerance vs the history median (trends, not points)
DEFAULT_TOLERANCE = 0.15
#: current bench artifact schema (the r12 stamping satellite)
SCHEMA_VERSION = 1

#: the r13 stage-profiler artifacts (obs/profiler.py writes them); every
#: ``stage_ms_<name>`` field is lower-better with its spread riding in
#: the sibling ``stage_spread_<name>`` — prefix rules, so new stage
#: probes are trend-tracked with no table edit here
PROFILE_PATTERN = "PROFILE_r*.json"
STAGE_MS_PREFIX = "stage_ms_"
STAGE_SPREAD_PREFIX = "stage_spread_"

#: the r23 calibration-sweep artifacts (policy/calibrate.py writes
#: them): every ``calib_ms_<gate>_<arm>_f<width>`` A/B wall is
#: lower-better with its spread in the sibling ``calib_spread_*`` field
#: — same prefix discipline as the stage profiler, so new sweep arms
#: are trend-tracked with no table edit here
CALIB_PATTERN = "CALIB_r*.json"
CALIB_MS_PREFIX = "calib_ms_"
CALIB_SPREAD_PREFIX = "calib_spread_"

#: r17 fleet-bench per-priority latency percentiles
#: (``fleet_<priority>_p{50,95,99}_ms_n<replicas>``) — pattern rule like
#: the stage profiler's, so new priorities/fleet sizes are tracked with
#: no table edit; lower is better, vouched by that fleet size's spread
_FLEET_PCT_RE = re.compile(r"^fleet_[a-z]+_p\d+_ms_(n\d+)$")

#: metric direction tables — anything in neither set is context, not a
#: tracked metric (row counts, spreads, tree counts, the stamps)
HIGHER_BETTER = frozenset({
    "value", "vs_baseline", "final_train_auc", "iters_per_sec_10m",
    "rows_per_s", "requests_per_s", "pipeline_speedup",
    # r14 fleet arm (scripts/bench_serve.py --fleet): closed-loop rows/s
    # through the router at N replicas, and the N-vs-1 scaling ratios
    "fleet_rows_per_s_n1", "fleet_rows_per_s_n2", "fleet_rows_per_s_n4",
    "fleet_scaling_n2", "fleet_scaling_n4",
    # r20 out-of-core training (scripts/stream_rss_probe.py): streamed
    # CPU train throughput
    "stream_train_rows_per_s",
    # r21 packed-vs-legacy serve layout A/B (scripts/bench_serve.py
    # --layout): closed-loop rows/s per traversal layout + their ratio
    "layout_rows_per_s_packed", "layout_rows_per_s_legacy",
    "predict_layout_speedup",
})
LOWER_BETTER = frozenset({
    "marginal_s_per_iter_10m", "wall_2tree_10m", "wall_8tree_10m",
    "deep_level_ms_wired", "deep_level_ms_legacy",
    "leafwise_level_ms_wired", "leafwise_level_ms_legacy",
    # r16 wide-shape histogram-reduction arms (bench.py hist_reduce_probe)
    "hist_reduce_ms_fused", "hist_reduce_ms_feature",
    "supervisor_overhead_ms", "obs_overhead_ms", "obs_overhead_pct",
    # r18 drift-monitor overhead (scripts/bench_serve.py --drift:
    # instrumented-vs-disabled serve arms, gate <= 2% like obs_overhead)
    "drift_overhead_ms", "drift_overhead_pct",
    # r20 streamed-vs-resident train overhead and the RSS proof peak
    "stream_overhead_pct", "stream_rss_peak_mb",
    # r21 per-layout predict traversal walls (bench.py
    # predict_layout_probe: one node-word table gather/level vs ~7)
    "predict_us_per_row_packed", "predict_us_per_row_legacy",
    # r22 elastic capacity (scripts/smoke_fleet.py ramp drill summary):
    # capacity actions and peak replica count a FIXED stepped ramp needs
    # to stay unshed — a stabler controller (or faster replicas) holds
    # the same load with fewer actions and a smaller pool
    "fleet_scale_up_total", "fleet_scale_down_total", "fleet_replicas",
    "p50_ms", "p99_ms",
})

#: metric -> the newest point's spread fields that vouch for it; the 10M
#: marginal is a (8-tree − 2-tree) difference, so BOTH arm spreads apply
_SPREAD_FIELDS = {
    "iters_per_sec_10m": ("spread_2tree_10m", "spread_8tree_10m"),
    "marginal_s_per_iter_10m": ("spread_2tree_10m", "spread_8tree_10m"),
    "wall_2tree_10m": ("spread_2tree_10m",),
    "wall_8tree_10m": ("spread_8tree_10m",),
    "deep_level_ms_wired": ("deep_level_spread_wired",),
    "deep_level_ms_legacy": ("deep_level_spread_legacy",),
    "leafwise_level_ms_wired": ("leafwise_level_spread_wired",),
    "leafwise_level_ms_legacy": ("leafwise_level_spread_legacy",),
    "hist_reduce_ms_fused": ("hist_reduce_spread_fused",),
    "hist_reduce_ms_feature": ("hist_reduce_spread_feature",),
    "supervisor_overhead_ms": ("supervisor_overhead_spread",),
    "obs_overhead_ms": ("obs_overhead_spread",),
    "obs_overhead_pct": ("obs_overhead_spread",),
    "drift_overhead_ms": ("drift_overhead_spread",),
    "drift_overhead_pct": ("drift_overhead_spread",),
    "stream_train_rows_per_s": ("stream_overhead_spread",),
    "stream_overhead_pct": ("stream_overhead_spread",),
    "predict_us_per_row_packed": ("predict_spread_packed",),
    "predict_us_per_row_legacy": ("predict_spread_legacy",),
    "layout_rows_per_s_packed": ("layout_spread_packed",),
    "layout_rows_per_s_legacy": ("layout_spread_legacy",),
    "predict_layout_speedup": ("layout_spread_packed",
                               "layout_spread_legacy"),
    "rows_per_s": ("spread_rows_per_s",),
    "fleet_rows_per_s_n1": ("fleet_spread_n1",),
    "fleet_rows_per_s_n2": ("fleet_spread_n2",),
    "fleet_rows_per_s_n4": ("fleet_spread_n4",),
    # the ratios inherit both arms' capture quality
    "fleet_scaling_n2": ("fleet_spread_n1", "fleet_spread_n2"),
    "fleet_scaling_n4": ("fleet_spread_n1", "fleet_spread_n4"),
}

_ROUND_RE = re.compile(r"_r0*(\d+)\.json$")


def _direction(name: str) -> Optional[str]:
    """Tracked-metric direction, or None for context fields.  Exact
    tables first, then the stage-profiler and fleet-percentile pattern
    rules."""
    if name in HIGHER_BETTER:
        return "higher_better"
    if (name in LOWER_BETTER or name.startswith(STAGE_MS_PREFIX)
            or name.startswith(CALIB_MS_PREFIX)
            or _FLEET_PCT_RE.match(name)):
        return "lower_better"
    return None


def _spread_fields_of(name: str) -> tuple:
    """The newest point's spread fields vouching for ``name``."""
    if name.startswith(STAGE_MS_PREFIX):
        return (STAGE_SPREAD_PREFIX + name[len(STAGE_MS_PREFIX):],)
    if name.startswith(CALIB_MS_PREFIX):
        return (CALIB_SPREAD_PREFIX + name[len(CALIB_MS_PREFIX):],)
    m = _FLEET_PCT_RE.match(name)
    if m:
        # percentile capture quality rides that fleet size's arm spread
        return (f"fleet_spread_{m.group(1)}",)
    return _SPREAD_FIELDS.get(name, ())


def _extract_metrics(doc: dict) -> Optional[dict]:
    """The flat numeric-metrics dict out of one artifact, whatever its
    vintage: the driver wrapper carries ``parsed``; a bare bench.py line
    saved directly IS the dict (it has ``metric``/``bench``); a profile
    artifact carries ``profile_schema`` even when its stamp failed."""
    if isinstance(doc.get("parsed"), dict):
        return doc["parsed"]
    if ("metric" in doc or "bench" in doc or "schema_version" in doc
            or "profile_schema" in doc or "calib_schema" in doc):
        return doc
    return None


def load_history(root: str = ".",
                 pattern: str = "BENCH_r*.json",
                 paths: Optional[Sequence[str]] = None) -> list[dict]:
    """Ordered bench points: ``{"round", "path", "metrics", "git_rev",
    "device_kind", "schema_version"}``.  Unstamped r1–r7 artifacts load
    with ``None`` stamps (backfill tolerance); unreadable or metric-less
    files are skipped, never fatal."""
    if paths is None:
        paths = sorted(glob.glob(os.path.join(root, pattern)))
    out = []
    for path in paths:
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue
        if not isinstance(doc, dict):
            continue
        metrics = _extract_metrics(doc)
        if not metrics:
            continue
        m = _ROUND_RE.search(os.path.basename(path))
        rnd = int(m.group(1)) if m else doc.get("n")
        out.append({
            "round": rnd if isinstance(rnd, int) else None,
            "path": os.path.basename(path),
            "metrics": {k: v for k, v in metrics.items()
                        if isinstance(v, (int, float))
                        and not isinstance(v, bool)},
            "schema_version": metrics.get("schema_version"),
            "git_rev": metrics.get("git_rev") or doc.get("git_rev"),
            "device_kind": metrics.get("device_kind") or doc.get("device_kind"),
        })
    out.sort(key=lambda p: (p["round"] is None, p["round"]))
    return out


def compare(history: Sequence[dict],
            tolerance: float = DEFAULT_TOLERANCE) -> dict:
    """Newest point vs the median of its history, per tracked metric.

    Returns ``{"ok", "newest", "n_points", "metrics": {name: {value,
    median, n_history, rel_delta, direction, spread, verdict}}}`` where
    verdict is ``ok`` / ``improved`` / ``regression`` / ``suspect`` (the
    spread veto) / ``new`` (no history records the metric).  ``ok`` is
    False only on a ``regression``.
    """
    if len(history) < 1:
        return {"ok": True, "n_points": 0, "newest": None, "metrics": {}}
    newest = history[-1]
    prior = list(history[:-1])
    report: dict = {"ok": True, "n_points": len(history),
                    "newest": newest["path"], "metrics": {}}
    for name, value in sorted(newest["metrics"].items()):
        direction = _direction(name)
        if direction is None:
            continue
        hist_vals = [p["metrics"][name] for p in prior
                     if name in p["metrics"]]
        entry = {"value": value, "n_history": len(hist_vals),
                 "direction": direction}
        if not hist_vals:
            entry.update(median=None, rel_delta=None, verdict="new")
            report["metrics"][name] = entry
            continue
        med = statistics.median(hist_vals)
        entry["median"] = med
        rel = (value - med) / abs(med) if med else 0.0
        entry["rel_delta"] = round(rel, 4)
        worse = -rel if direction == "higher_better" else rel
        spread = max((newest["metrics"].get(f, 0.0)
                      for f in _spread_fields_of(name)), default=0.0)
        entry["spread"] = spread
        if worse > tolerance:
            if spread > SPREAD_SUSPECT:
                # suspect capture, never a regression verdict (CLAUDE.md)
                entry["verdict"] = "suspect"
            else:
                entry["verdict"] = "regression"
                report["ok"] = False
        elif worse < -tolerance:
            entry["verdict"] = "improved"
        else:
            entry["verdict"] = "ok"
        report["metrics"][name] = entry
    return report


def ingest(history: Sequence[dict],
           registry: Optional[Registry] = None) -> int:
    """Fold the history into registry series — one
    ``dryad_bench_value{metric=..., round=...}`` gauge point per tracked
    metric per round, plus ``dryad_bench_rounds`` — so scrapers see the
    whole trajectory on ``/metrics``.  Returns the number of series set.
    """
    reg = registry if registry is not None else default_registry()
    if not reg.enabled:
        return 0
    fam = reg.gauge("dryad_bench_value",
                    "Committed bench-history metric values by round")
    n = 0
    for point in history:
        rnd = point["round"] if point["round"] is not None else -1
        for name, value in point["metrics"].items():
            if _direction(name) is not None:
                fam.labels(metric=name, round=rnd).set(float(value))
                n += 1
    reg.gauge("dryad_bench_rounds",
              "Bench-history points loaded").set(len(history))
    return n


def artifact_stamp(device_kind: Optional[str] = "auto",
                   root: Optional[str] = None) -> dict:
    """The r12 bench-artifact stamp: ``schema_version`` + ``git_rev`` +
    ``device_kind``.  r23: the default ``"auto"`` resolves through the
    ONE derivation (``policy.device.current_device_kind`` — itself a
    lazy, best-effort jax probe, so this module stays jax-free by lint);
    pass an explicit kind, or explicit ``None`` for a deliberately
    unstamped artifact.  Keys the history off data instead of filenames;
    failures stamp ``None``, never raise (a bench must not die because
    git is absent)."""
    if device_kind == "auto":
        try:
            from dryad_tpu.policy.device import current_device_kind
            device_kind = current_device_kind()
        except Exception:  # noqa: BLE001 — the stamp is best-effort
            device_kind = None
    rev = None
    try:
        import subprocess

        out = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"],
            capture_output=True, text=True, timeout=5,
            cwd=root or os.getcwd())
        rev = out.stdout.strip() or None
    except Exception:   # noqa: BLE001 — the stamp is best-effort
        rev = None
    return {"schema_version": SCHEMA_VERSION, "git_rev": rev,
            "device_kind": device_kind}


def stats_provider(root: str = ".", tolerance: float = DEFAULT_TOLERANCE):
    """An ``extra_stats`` provider for the /stats endpoint: loads the
    committed histories once (static for the life of a run) and serves
    the regression reports under ``bench_trends`` (always) and
    ``profile_trends`` (when any ``PROFILE_r*.json`` exists)."""
    cache: dict = {}

    def provide() -> dict:
        if "report" not in cache:
            history = load_history(root)
            cache["report"] = compare(history, tolerance) if history else {
                "ok": True, "n_points": 0, "newest": None, "metrics": {}}
            prof = load_history(root, pattern=PROFILE_PATTERN)
            cache["profile"] = compare(prof, tolerance) if prof else None
            cal = load_history(root, pattern=CALIB_PATTERN)
            cache["calib"] = compare(cal, tolerance) if cal else None
        out = {"bench_trends": cache["report"]}
        if cache["profile"] is not None:
            out["profile_trends"] = cache["profile"]
        if cache["calib"] is not None:
            out["calib_trends"] = cache["calib"]
        return out

    return provide
