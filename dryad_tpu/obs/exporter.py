"""Stdlib HTTP exposition of the telemetry registry (fleet monitoring).

Endpoints (shared by this standalone exporter AND the serve front end,
which mounts the same handlers next to /predict — serve/http.py):

    GET /metrics   Prometheus text exposition (registry.exposition())
    GET /stats     JSON: uptime, span summary, counters/gauges/histograms
                   (+ any extra_stats providers merged in)
    GET /trace     Chrome trace_event JSON of the live span ring
                   (obs/trace_export.py; empty traceEvents until
                   ``enable_tracing()`` installs the sink — the train
                   CLI's ``--trace-out`` does, as can any caller)
    GET /healthz   200 {"ok": true} while the process health state is
                   clean, 503 {"ok": false, "degraded": [...]} while any
                   subsystem holds a degradation (fetch stall, unexpected
                   recompile — obs/health.py).  ALWAYS auth-exempt
                   (probes must not need credentials)

Bearer-token auth: when ``auth_token`` is set every endpoint except
/healthz requires ``Authorization: Bearer <token>`` and answers 401
otherwise (constant-time compare).  ``python -m dryad_tpu train
--metrics-port N`` mounts this next to a training run; ``--auth-token``
(or DRYAD_AUTH_TOKEN) guards both this exporter and the serve front end.

The exporter only READS the registry — the host-side snapshot path.  It
never touches jax or the device (scripts/ci.sh lints the package).
"""

from __future__ import annotations

import hmac
import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Optional, Sequence

from dryad_tpu.obs import spans
from dryad_tpu.obs.health import healthz_payload
from dryad_tpu.obs.registry import Registry, default_registry


def authorized(handler: BaseHTTPRequestHandler,
               token: Optional[str]) -> bool:
    """Shared bearer check (also used by serve/http.py).  /healthz is the
    caller's responsibility to exempt BEFORE calling this."""
    if not token:
        return True
    header = handler.headers.get("Authorization", "")
    return hmac.compare_digest(header.encode(), f"Bearer {token}".encode())


def send_unauthorized(handler: BaseHTTPRequestHandler) -> None:
    body = b'{"error": "unauthorized"}'
    handler.send_response(401)
    handler.send_header("Content-Type", "application/json")
    handler.send_header("WWW-Authenticate", "Bearer")
    handler.send_header("Content-Length", str(len(body)))
    handler.end_headers()
    handler.wfile.write(body)


class _Handler(BaseHTTPRequestHandler):
    # the exporter rides on the server object (see MetricsExporter)

    def log_message(self, fmt, *args):  # quiet: this is a scrape target
        pass

    def _send(self, code: int, body: bytes, ctype: str) -> None:
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):  # noqa: N802 — stdlib handler API
        if self.path == "/healthz":
            code, body = healthz_payload()
            self._send(code, json.dumps(body).encode(), "application/json")
            return
        if not authorized(self, self.server.auth_token):
            send_unauthorized(self)
            return
        reg: Registry = self.server.obs_registry
        if self.path == "/metrics":
            self._send(200, reg.exposition().encode(),
                       "text/plain; version=0.0.4; charset=utf-8")
        elif self.path == "/trace":
            from dryad_tpu.obs import trace_export

            buf = trace_export.default_trace()
            body = trace_export.dumps_trace(
                span_events=buf.events() if buf is not None else ())
            self._send(200, body.encode(), "application/json")
        elif self.path == "/stats":
            self._send(200, json.dumps(stats_payload(
                reg, self.server.started_at,
                self.server.extra_stats)).encode(), "application/json")
        else:
            self._send(404, b'{"error": "unknown path"}', "application/json")


def stats_payload(registry: Registry, started_at: float,
                  extra_stats: Sequence[Callable[[], dict]] = ()) -> dict:
    """The /stats JSON body: registry snapshot + span summary + uptime,
    with any extra provider dicts merged in under their returned keys."""
    payload = {"uptime_s": round(time.monotonic() - started_at, 3),
               "spans": spans.snapshot(registry)}
    payload.update(registry.snapshot())
    for provider in extra_stats or ():
        try:
            payload.update(provider())
        except Exception as e:  # noqa: BLE001 — a dead provider must not
            payload.setdefault("stats_errors", []).append(repr(e))  # kill /stats
    return payload


class MetricsExporter:
    """Bind-and-serve wrapper; ``port=0`` picks a free port (read it back
    from ``.port`` after ``start()``)."""

    def __init__(self, registry: Optional[Registry] = None,
                 host: str = "127.0.0.1", port: int = 0, *,
                 auth_token: Optional[str] = None,
                 extra_stats: Sequence[Callable[[], dict]] = ()):
        self.registry = registry if registry is not None else default_registry()
        self._host, self._port = host, int(port)
        self._auth_token = auth_token
        self._extra_stats = tuple(extra_stats or ())
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    @property
    def host(self) -> str:
        return self._httpd.server_address[0] if self._httpd else self._host

    @property
    def port(self) -> int:
        return self._httpd.server_address[1] if self._httpd else self._port

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def start(self) -> "MetricsExporter":
        if self._httpd is not None:
            return self
        httpd = ThreadingHTTPServer((self._host, self._port), _Handler)
        httpd.daemon_threads = True
        httpd.obs_registry = self.registry
        httpd.auth_token = self._auth_token
        httpd.extra_stats = self._extra_stats
        httpd.started_at = time.monotonic()
        self._httpd = httpd
        self._thread = threading.Thread(target=httpd.serve_forever,
                                        daemon=True, name="dryad-obs-exporter")
        self._thread.start()
        return self

    def stop(self) -> None:
        if self._httpd is None:
            return
        self._httpd.shutdown()
        self._httpd.server_close()
        self._httpd = None
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def __enter__(self) -> "MetricsExporter":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()


def start_exporter(registry: Optional[Registry] = None,
                   host: str = "127.0.0.1", port: int = 0, *,
                   auth_token: Optional[str] = None,
                   extra_stats: Sequence[Callable[[], dict]] = ()
                   ) -> MetricsExporter:
    """Convenience: construct + start (the CLI front door)."""
    return MetricsExporter(registry, host, port, auth_token=auth_token,
                           extra_stats=extra_stats).start()
