"""Per-priority latency SLO gates over mergeable histograms (r17).

The ROADMAP's million-user predict-path acceptance is stated in latency
terms — "p99 latency budgets per priority class, not just rows/s" — and
the fleet-wide histograms (registry.LOG_HISTOGRAM + exact cross-process
merge) finally produce that number.  ``SloGate`` turns it into a
VERDICT: declared per-priority budgets are evaluated against histogram
states, a breach must be SUSTAINED (``breach_after`` consecutive
evaluations) before it degrades health — one slow scrape window is
telemetry, N in a row is an incident — and recovery clears the
degradation the same way the watchdog/tripwire reasons clear.

Contracts (the obs package rules, registry.py):

* host-side only, stdlib only — evaluation reads histogram state the
  caller already holds; nothing here touches jax;
* evaluation happens on the OBSERVER's cadence (a /healthz probe, a
  bench report), never per request — the request path only ever
  observes into the histograms it already owns.

Lock contract: ``_lock`` guards the per-priority breach streaks; the
health-state and registry mirrors are updated OUTSIDE it (each has its
own lock — the two domains never nest).
"""

from __future__ import annotations

import threading
from typing import Optional

from dryad_tpu.obs.health import HealthState, default_health
from dryad_tpu.obs.registry import Registry, default_registry, hist_quantile

#: default budgets, milliseconds — deliberately generous: the gate ships
#: as a tripwire for serving cliffs, not a 1% latency referee (the same
#: stance as the bench trend tolerance)
DEFAULT_BUDGETS_MS = {"interactive": 250.0, "bulk": 2000.0}


def parse_budgets(spec: str) -> dict:
    """``"interactive=250,bulk=2000"`` -> {"interactive": 250.0, ...}
    (the CLI flag shape); empty spec -> the defaults; ``off``/``none``
    -> ``{}``, which disables SLO health-gating entirely (a gate with no
    budgets never degrades — the pre-r17 /healthz contract)."""
    if not spec:
        return dict(DEFAULT_BUDGETS_MS)
    if spec.strip().lower() in ("off", "none"):
        return {}
    out = {}
    for part in spec.split(","):
        name, _, val = part.partition("=")
        if not name or not val:
            raise ValueError(f"bad SLO budget {part!r} "
                             "(want priority=milliseconds, or 'off')")
        out[name.strip()] = float(val)
    return out


class SloGate:
    """Sustained-breach evaluation of per-priority p-quantile budgets."""

    GUARDED_BY = {"_streaks": "_lock"}

    def __init__(self, budgets_ms: Optional[dict] = None, *,
                 quantile: float = 0.99, breach_after: int = 3,
                 registry: Optional[Registry] = None,
                 health: Optional[HealthState] = None):
        self.budgets_ms = dict(budgets_ms if budgets_ms is not None
                               else DEFAULT_BUDGETS_MS)
        self.quantile = float(quantile)
        self.breach_after = int(breach_after)
        self._registry = registry
        self._health = health
        self._lock = threading.Lock()
        self._streaks: dict[str, int] = {}

    def _reg(self) -> Registry:
        return (self._registry if self._registry is not None
                else default_registry())

    def _hstate(self) -> HealthState:
        return (self._health if self._health is not None
                else default_health())

    def evaluate(self, states: dict) -> dict:
        """One evaluation pass.  ``states`` maps priority -> a histogram
        ``(counts, sum, count)`` state on the fixed log scheme — a
        WINDOW of recent traffic (the router passes the delta since the
        previous evaluation), not a lifetime cumulative: cumulative
        state would let history dilute both breach detection and
        recovery.  Verdicts per priority: a window whose quantile
        exceeds its budget advances the breach streak; ``breach_after``
        consecutive breached windows degrade ``slo:<priority>``; an
        in-budget NON-EMPTY window clears it.  An EMPTY window (no
        traffic since the last evaluation) is no evidence either way —
        the streak and any active degradation HOLD, so a burst-induced
        incident neither clears itself through silence nor does silence
        ever raise one."""
        verdicts: dict = {}
        transitions: list = []
        with self._lock:
            for priority, budget_ms in sorted(self.budgets_ms.items()):
                counts, _total, n = states.get(priority) or ([], 0.0, 0)
                # n <= 0 is the empty/no-evidence case — including a
                # degenerate negative window a buggy caller could hand
                # us; it must hold, never flip verdicts
                p_ms = (hist_quantile(counts, self.quantile) * 1e3
                        if n > 0 else 0.0)
                breached = n > 0 and p_ms > budget_ms
                if n <= 0:
                    streak = self._streaks.get(priority, 0)   # hold
                else:
                    streak = self._streaks.get(priority, 0) + 1 \
                        if breached else 0
                self._streaks[priority] = streak
                sustained = streak >= self.breach_after
                verdicts[priority] = {
                    "p_ms": round(p_ms, 3), "budget_ms": budget_ms,
                    "count": int(n), "breached": breached,
                    "streak": streak, "sustained": sustained,
                }
                transitions.append((priority, p_ms, n, streak, sustained))
        # mirrors OUTSIDE _lock: health and registry own their locks
        reg = self._reg()
        health = self._hstate()
        for priority, p_ms, n, streak, sustained in transitions:
            if sustained:
                health.degrade(
                    f"slo:{priority}",
                    f"p{int(self.quantile * 100)} {p_ms:.1f} ms over "
                    f"budget {self.budgets_ms[priority]:.0f} ms "
                    f"({streak} consecutive windows)")
            elif n > 0 or streak == 0:
                # recovery needs evidence (a non-empty in-budget window)
                # or a never-breached priority; an empty window holds
                health.clear(f"slo:{priority}")
            if reg.enabled:
                reg.gauge("dryad_slo_p_ms",
                          "Evaluated per-priority SLO window quantile").labels(
                    priority=priority).set(p_ms)
                reg.gauge("dryad_slo_breach_streak",
                          "Consecutive over-budget windows").labels(
                    priority=priority).set(streak)
        return verdicts

    @property
    def ok(self) -> bool:
        """False while any priority's breach is sustained."""
        with self._lock:
            return all(s < self.breach_after for s in self._streaks.values())
