"""Process-wide telemetry registry: counters, gauges, histograms.

One registry spans train/serve/resilience (the "single pane of glass"
ROADMAP r8 follow-up (c) asks for): producers record into named families,
optionally labeled; consumers read one consistent ``snapshot()`` dict or
the Prometheus text ``exposition()`` the HTTP exporter serves.

Design contract (test-pinned in tests/test_obs.py):

* **Host-side only.**  Collectors record values the engine already holds
  on the host — a Python int/float the trainer fetched, a wall-clock
  delta, a queue depth.  Nothing in ``dryad_tpu/obs`` may touch jax or a
  device buffer (no fetch calls of any kind, no per-iteration syncs —
  CLAUDE.md's never-fetch rule); scripts/ci.sh lints the package for it.
* **Zero-cost when disabled.**  Every record method's FIRST action is the
  ``enabled`` check and the disabled path allocates nothing — no lock,
  no float boxing, no label-tuple build.  Hot loops keep a bound series
  handle (``family.labels(...)`` / the unlabeled family itself) so the
  disabled fast path is one attribute read + one branch.
* **Thread-safe when enabled.**  One lock per family; concurrent writers
  never lose increments.  ``snapshot()``/``exposition()`` take the same
  locks per family, so a read sees each family consistently.

Registries are instantiable (tests use private ones); production code
records into ``default_registry()``, toggled by ``DRYAD_OBS=0`` at import
or ``enable()``/``disable()`` at runtime (bench.py measures the
instrumented-vs-disabled delta as ``obs_overhead_ms``).

r17 adds the **fixed-log-bucket histogram kind** (``log_histogram``):
one process-invariant bucket layout (``LOG_BUCKETS``), O(1) observe, and
EXACT cross-process merge (``merge_hist_states`` — integer counts add
losslessly), which is what lets the fleet router serve one fleet-wide
p99 from per-replica scrapes.  ``hist_quantile`` is the shared
nearest-rank readout; never hand a log histogram custom buckets.
"""

from __future__ import annotations

import math
import os
import threading
from typing import Optional, Sequence

COUNTER = "counter"
GAUGE = "gauge"
HISTOGRAM = "histogram"
LOG_HISTOGRAM = "loghistogram"

#: default histogram bounds — tuned for serving/trainer wall times in
#: seconds (sub-ms batcher hops up to multi-second chunk fetches)
DEFAULT_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                   0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0)

# ---- the fixed-log-bucket scheme (r17 request-latency family) --------------
#
# One bucket layout for EVERY process, fixed at import: 10 buckets per
# decade from 0.1 ms to 100 s (61 bounds + overflow).  Because the
# bounds are code, not configuration, two processes' series can be
# merged EXACTLY by adding their integer count arrays — the property the
# fleet router's aggregated /metrics relies on (fleet-wide p99 from
# per-replica scrapes, bitwise-equal to a single-process histogram of
# the concatenated observations).  ``observe`` is O(1): the bucket index
# is one log, not a linear scan over 61 bounds.
#: the shared per-(priority, stage) request-latency family name — ONE
#: name at the fleet router and every serve replica, so the router's
#: exact cross-process merge is a label join (serve/metrics.py records
#: replica stages; fleet/router.py records stage="router" and merges)
REQUEST_LATENCY = "dryad_request_latency_seconds"

LOG_MIN = 1e-4            # seconds (0.1 ms) — the first bucket's bound
LOG_PER_DECADE = 10
LOG_DECADES = 6           # covers 0.1 ms .. 100 s
LOG_BUCKETS = tuple(LOG_MIN * 10.0 ** (i / LOG_PER_DECADE)
                    for i in range(LOG_PER_DECADE * LOG_DECADES + 1))
_LOG_SCALE = LOG_PER_DECADE / math.log(10.0)


def log_bucket_index(value: float) -> int:
    """The O(1) bucket index under 'le' semantics: the smallest ``i``
    with ``value <= LOG_BUCKETS[i]``, or ``len(LOG_BUCKETS)`` for the
    overflow bucket.  The float-log estimate is corrected by at most one
    step in each direction so edge values land exactly where the linear
    scan would put them (pinned against the scan in tests)."""
    if value <= LOG_MIN:
        return 0
    n = len(LOG_BUCKETS)
    i = int(math.ceil(math.log(value / LOG_MIN) * _LOG_SCALE))
    if i < 0:
        i = 0
    elif i > n:
        i = n
    while i > 0 and value <= LOG_BUCKETS[i - 1]:
        i -= 1
    while i < n and value > LOG_BUCKETS[i]:
        i += 1
    return i


def new_hist_state(n_bounds: int = len(LOG_BUCKETS)) -> list:
    """A fresh mutable histogram state ``[counts, sum, count]`` — the
    same shape the registry stores per series, usable standalone (the
    serve metrics percentile state)."""
    return [[0] * (n_bounds + 1), 0.0, 0]


def observe_log_state(state: list, value: float) -> None:
    """O(1) observe into a standalone log-bucket state (caller locks)."""
    state[0][log_bucket_index(value)] += 1
    state[1] += float(value)
    state[2] += 1


def merge_hist_states(states: Sequence) -> tuple:
    """Exact count-merge of ``(counts, sum, count)`` states sharing one
    bucket layout: integer counts add losslessly, so the merged
    histogram is the histogram of the concatenated observations."""
    states = list(states)
    if not states:
        return new_hist_state()
    n = len(states[0][0])
    counts = [0] * n
    total = 0.0
    count = 0
    for c, s, k in states:
        if len(c) != n:
            raise ValueError("cannot merge histograms with different "
                             f"bucket layouts ({len(c)} vs {n})")
        for i, v in enumerate(c):
            counts[i] += v
        total += s
        count += k
    return (counts, total, count)


def hist_quantile(counts: Sequence[int], q: float,
                  bounds: Sequence[float] = LOG_BUCKETS) -> float:
    """Nearest-rank quantile from bucket counts, in the bounds' unit
    (seconds for the log scheme).  Each bucket reports its UPPER bound —
    deterministic, monotone in ``q``, and mergeable (the quantile of a
    merged state equals the quantile of the concatenated observations up
    to bucket resolution); the overflow bucket reports the last finite
    bound.  Empty histogram -> 0.0."""
    total = sum(counts)
    if total == 0:
        return 0.0
    target = max(1, math.ceil(float(q) * total))
    cum = 0
    for i, c in enumerate(counts):
        cum += c
        if cum >= target:
            return bounds[min(i, len(bounds) - 1)]
    return bounds[-1]


def _label_key(labels: dict) -> tuple:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def _escape(value: str) -> str:
    return (value.replace("\\", r"\\").replace("\n", r"\n")
            .replace('"', r'\"'))


def _fmt_labels(key: tuple) -> str:
    """Prometheus label block for a sorted (k, v) tuple ('' if unlabeled)."""
    if not key:
        return ""
    return "{" + ",".join(f'{k}="{_escape(v)}"' for k, v in key) + "}"


def _fmt_value(v: float) -> str:
    # integers render without the trailing .0 — keeps counters greppable
    return str(int(v)) if float(v).is_integer() and abs(v) < 1e15 else repr(v)


class _Series:
    """Bound handle for ONE label set of a family — the hot-path object.

    The disabled check is the first statement of every record method: the
    disabled path is one attribute read + one branch, allocation-free
    (the zero-cost contract)."""

    __slots__ = ("_fam", "_key")

    def __init__(self, fam: "_Family", key: tuple):
        self._fam = fam
        self._key = key

    # counter / gauge -------------------------------------------------------
    def inc(self, amount: float = 1.0) -> None:
        fam = self._fam
        if not fam.registry.enabled:
            return
        if fam.kind == GAUGE:
            with fam.lock:
                fam.values[self._key] = fam.values.get(self._key, 0.0) + amount
            return
        if fam.kind != COUNTER:
            raise TypeError(f"{fam.name} is a {fam.kind}, not a counter")
        if amount < 0:
            raise ValueError("counters only go up")
        with fam.lock:
            fam.values[self._key] = fam.values.get(self._key, 0.0) + amount

    def set(self, value: float) -> None:
        fam = self._fam
        if not fam.registry.enabled:
            return
        if fam.kind != GAUGE:
            raise TypeError(f"{fam.name} is a {fam.kind}, not a gauge")
        with fam.lock:
            fam.values[self._key] = float(value)

    # histogram -------------------------------------------------------------
    def observe(self, value: float) -> None:
        fam = self._fam
        if not fam.registry.enabled:
            return
        if fam.kind == LOG_HISTOGRAM:
            # O(1) bucket index — no scan over the 61 log bounds
            i = log_bucket_index(value)
        elif fam.kind == HISTOGRAM:
            bounds = fam.buckets
            i = 0
            # Prometheus 'le' semantics: a value ON a bound lands in that
            # bound's bucket (test_histogram_bucket_edges)
            while i < len(bounds) and value > bounds[i]:
                i += 1
        else:
            raise TypeError(f"{fam.name} is a {fam.kind}, not a histogram")
        with fam.lock:
            state = fam.values.get(self._key)
            if state is None:
                state = fam.values[self._key] = [
                    [0] * (len(fam.buckets) + 1), 0.0, 0]
            state[0][i] += 1
            state[1] += float(value)
            state[2] += 1

    def value(self):
        """Current value (counter/gauge float; histogram
        (counts, sum, count) copy) — 0-initialized if never recorded."""
        fam = self._fam
        with fam.lock:
            if fam.kind in (HISTOGRAM, LOG_HISTOGRAM):
                state = fam.values.get(self._key)
                if state is None:
                    return ([0] * (len(fam.buckets) + 1), 0.0, 0)
                return (list(state[0]), state[1], state[2])
            return fam.values.get(self._key, 0.0)


class _Family:
    """One named metric family: a kind, a help string, and the labeled
    series under it.  The family itself doubles as its own unlabeled
    series, so ``registry.counter("x").inc()`` needs no ``.labels()``."""

    __slots__ = ("registry", "name", "kind", "help", "buckets", "lock",
                 "values", "_children", "_unlabeled")

    #: ``lock`` guards the recorded series and the bound-handle cache;
    #: ``_Series`` (the bound accessor this family hands out) honors the
    #: same contract — every ``fam.values`` touch there sits under
    #: ``fam.lock``, which the schedule harness's record-vs-snapshot
    #: drill verifies at runtime (a lexical lint cannot see the alias)
    GUARDED_BY = {"values": "lock", "_children": "lock"}

    def __init__(self, registry: "Registry", name: str, kind: str,
                 help: str = "", buckets: Optional[Sequence[float]] = None):
        self.registry = registry
        self.name = name
        self.kind = kind
        self.help = help
        self.buckets = tuple(float(b) for b in (buckets or ())) or None
        if kind == HISTOGRAM:
            self.buckets = self.buckets or DEFAULT_BUCKETS
            if list(self.buckets) != sorted(self.buckets):
                raise ValueError("histogram buckets must be sorted")
        elif kind == LOG_HISTOGRAM:
            # the layout is the fixed scheme or nothing — custom buckets
            # would silently break the cross-process exact merge
            if self.buckets is not None:
                raise ValueError("log histograms use the fixed LOG_BUCKETS "
                                 "scheme; custom buckets are not mergeable")
            self.buckets = LOG_BUCKETS
        self.lock = threading.Lock()
        self.values: dict = {}
        self._children: dict = {}
        self._unlabeled = _Series(self, ())

    def labels(self, **labels) -> _Series:
        if not labels:
            return self._unlabeled
        key = _label_key(labels)
        # double-checked fast path: a racy CPython-atomic dict read; the
        # locked setdefault below is the authoritative insert, a stale
        # None only costs one lock acquire
        # dryadlint: disable=guarded-by -- benign double-checked read (see above)
        child = self._children.get(key)
        if child is None:
            with self.lock:
                child = self._children.setdefault(key, _Series(self, key))
        return child

    # unlabeled passthroughs (the common hot path)
    def inc(self, amount: float = 1.0) -> None:
        self._unlabeled.inc(amount)

    def set(self, value: float) -> None:
        self._unlabeled.set(value)

    def observe(self, value: float) -> None:
        self._unlabeled.observe(value)

    def value(self):
        return self._unlabeled.value()

    def series(self) -> dict:
        """label-block string -> value (see _Series.value) for snapshot."""
        with self.lock:
            keys = list(self.values.keys())
        out = {}
        for key in keys:
            out[_fmt_labels(key).strip("{}")] = _Series(self, key).value()
        return out


class Registry:
    GUARDED_BY = {"_families": "_lock"}

    def __init__(self, enabled: bool = True):
        self.enabled = bool(enabled)
        self._lock = threading.Lock()
        self._families: dict[str, _Family] = {}

    # ---- lifecycle ---------------------------------------------------------
    def enable(self) -> None:
        self.enabled = True

    def disable(self) -> None:
        self.enabled = False

    # ---- family accessors (idempotent; kind mismatch raises) ---------------
    def _family(self, name: str, kind: str, help: str,
                buckets=None) -> _Family:
        # double-checked fast path: the hot accessor's lock-free read; the
        # locked re-check below is the authoritative create (families are
        # never removed, only reset)
        # dryadlint: disable=guarded-by -- benign double-checked read (see above)
        fam = self._families.get(name)
        if fam is None:
            with self._lock:
                fam = self._families.get(name)
                if fam is None:
                    fam = self._families[name] = _Family(
                        self, name, kind, help, buckets)
        if fam.kind != kind:
            raise ValueError(
                f"metric {name!r} already registered as a {fam.kind}")
        return fam

    def reset_prefix(self, prefix: str) -> None:
        """Drop every recorded series in families whose name starts with
        ``prefix``.  Used for run-scoped series (``dryad_run_*``): a
        reused/appended journal begins a new run with ``run_start``, and
        without the reset the live endpoint would present the PRIOR run's
        fault/backoff/resume counts as current.  Scrapers see a counter
        reset, which Prometheus ``rate()`` absorbs."""
        with self._lock:
            fams = [f for f in self._families.values()
                    if f.name.startswith(prefix)]
        for fam in fams:
            with fam.lock:
                fam.values.clear()

    def counter(self, name: str, help: str = "") -> _Family:
        return self._family(name, COUNTER, help)

    def gauge(self, name: str, help: str = "") -> _Family:
        return self._family(name, GAUGE, help)

    def histogram(self, name: str, help: str = "",
                  buckets: Optional[Sequence[float]] = None) -> _Family:
        return self._family(name, HISTOGRAM, help, buckets)

    def log_histogram(self, name: str, help: str = "") -> _Family:
        """A histogram on the process-invariant fixed-log-bucket scheme
        (``LOG_BUCKETS``): O(1) observe, and series merge EXACTLY across
        processes (``merge_hist_states``) because every process shares
        the layout by construction."""
        return self._family(name, LOG_HISTOGRAM, help)

    # ---- consumers (the explicitly-annotated SNAPSHOT PATH: the one place
    # obs is allowed to allocate freely; still jax-free by construction) ----
    def snapshot(self) -> dict:
        """One JSON-able dict of everything: ``{"counters": {name:
        {labelblock: value}}, "gauges": {...}, "histograms": {name:
        {labelblock: {"bounds", "counts", "sum", "count"}}}}``."""
        with self._lock:
            fams = list(self._families.values())
        out: dict = {"counters": {}, "gauges": {}, "histograms": {}}
        for fam in fams:
            if fam.kind in (HISTOGRAM, LOG_HISTOGRAM):
                # log families carry the marker so a cross-process merge
                # consumer (the fleet router) can find them in a scrape
                log = fam.kind == LOG_HISTOGRAM
                out["histograms"][fam.name] = {
                    lbl: {"bounds": list(fam.buckets), "counts": counts,
                          "sum": total, "count": n,
                          **({"log": True} if log else {})}
                    for lbl, (counts, total, n) in fam.series().items()}
            else:
                out[fam.kind + "s"][fam.name] = fam.series()
        return out

    def exposition(self) -> str:
        """Prometheus text exposition (format 0.0.4) of every family."""
        with self._lock:
            fams = sorted(self._families.values(), key=lambda f: f.name)
        lines: list[str] = []
        for fam in fams:
            if fam.help:
                lines.append(f"# HELP {fam.name} {_escape(fam.help)}")
            # the log kind is an implementation detail; on the wire it is
            # an ordinary Prometheus histogram (scrapers know no other)
            kind = HISTOGRAM if fam.kind == LOG_HISTOGRAM else fam.kind
            lines.append(f"# TYPE {fam.name} {kind}")
            with fam.lock:
                items = sorted(fam.values.items())
                for key, val in items:
                    if kind != HISTOGRAM:
                        lines.append(
                            f"{fam.name}{_fmt_labels(key)} {_fmt_value(val)}")
                        continue
                    counts, total, n = val
                    cum = 0
                    for bound, c in zip(fam.buckets, counts):
                        cum += c
                        lk = _fmt_labels(key + (("le", repr(float(bound))),))
                        lines.append(f"{fam.name}_bucket{lk} {cum}")
                    lk = _fmt_labels(key + (("le", "+Inf"),))
                    lines.append(f"{fam.name}_bucket{lk} {cum + counts[-1]}")
                    lines.append(
                        f"{fam.name}_sum{_fmt_labels(key)} {_fmt_value(total)}")
                    lines.append(f"{fam.name}_count{_fmt_labels(key)} {n}")
        return "\n".join(lines) + ("\n" if lines else "")


# ---- the process-wide default ----------------------------------------------

_default: Optional[Registry] = None
_default_lock = threading.Lock()


def default_registry() -> Registry:
    """The shared registry train/serve/resilience record into.  Created
    enabled unless ``DRYAD_OBS=0``; swap with ``set_default_registry``
    (tests) or toggle with ``enable()``/``disable()`` (bench arms)."""
    global _default
    if _default is None:
        with _default_lock:
            if _default is None:
                _default = Registry(
                    enabled=os.environ.get("DRYAD_OBS", "1") != "0")
    return _default


def set_default_registry(registry: Registry) -> Registry:
    """Replace the process default (tests/smokes); returns the OLD one so
    callers can restore it."""
    global _default
    with _default_lock:
        old = _default if _default is not None else Registry(
            enabled=os.environ.get("DRYAD_OBS", "1") != "0")
        _default = registry
    return old
