"""Process-wide telemetry registry: counters, gauges, histograms.

One registry spans train/serve/resilience (the "single pane of glass"
ROADMAP r8 follow-up (c) asks for): producers record into named families,
optionally labeled; consumers read one consistent ``snapshot()`` dict or
the Prometheus text ``exposition()`` the HTTP exporter serves.

Design contract (test-pinned in tests/test_obs.py):

* **Host-side only.**  Collectors record values the engine already holds
  on the host — a Python int/float the trainer fetched, a wall-clock
  delta, a queue depth.  Nothing in ``dryad_tpu/obs`` may touch jax or a
  device buffer (no fetch calls of any kind, no per-iteration syncs —
  CLAUDE.md's never-fetch rule); scripts/ci.sh lints the package for it.
* **Zero-cost when disabled.**  Every record method's FIRST action is the
  ``enabled`` check and the disabled path allocates nothing — no lock,
  no float boxing, no label-tuple build.  Hot loops keep a bound series
  handle (``family.labels(...)`` / the unlabeled family itself) so the
  disabled fast path is one attribute read + one branch.
* **Thread-safe when enabled.**  One lock per family; concurrent writers
  never lose increments.  ``snapshot()``/``exposition()`` take the same
  locks per family, so a read sees each family consistently.

Registries are instantiable (tests use private ones); production code
records into ``default_registry()``, toggled by ``DRYAD_OBS=0`` at import
or ``enable()``/``disable()`` at runtime (bench.py measures the
instrumented-vs-disabled delta as ``obs_overhead_ms``).
"""

from __future__ import annotations

import os
import threading
from typing import Optional, Sequence

COUNTER = "counter"
GAUGE = "gauge"
HISTOGRAM = "histogram"

#: default histogram bounds — tuned for serving/trainer wall times in
#: seconds (sub-ms batcher hops up to multi-second chunk fetches)
DEFAULT_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                   0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0)


def _label_key(labels: dict) -> tuple:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def _escape(value: str) -> str:
    return (value.replace("\\", r"\\").replace("\n", r"\n")
            .replace('"', r'\"'))


def _fmt_labels(key: tuple) -> str:
    """Prometheus label block for a sorted (k, v) tuple ('' if unlabeled)."""
    if not key:
        return ""
    return "{" + ",".join(f'{k}="{_escape(v)}"' for k, v in key) + "}"


def _fmt_value(v: float) -> str:
    # integers render without the trailing .0 — keeps counters greppable
    return str(int(v)) if float(v).is_integer() and abs(v) < 1e15 else repr(v)


class _Series:
    """Bound handle for ONE label set of a family — the hot-path object.

    The disabled check is the first statement of every record method: the
    disabled path is one attribute read + one branch, allocation-free
    (the zero-cost contract)."""

    __slots__ = ("_fam", "_key")

    def __init__(self, fam: "_Family", key: tuple):
        self._fam = fam
        self._key = key

    # counter / gauge -------------------------------------------------------
    def inc(self, amount: float = 1.0) -> None:
        fam = self._fam
        if not fam.registry.enabled:
            return
        if fam.kind == GAUGE:
            with fam.lock:
                fam.values[self._key] = fam.values.get(self._key, 0.0) + amount
            return
        if fam.kind != COUNTER:
            raise TypeError(f"{fam.name} is a {fam.kind}, not a counter")
        if amount < 0:
            raise ValueError("counters only go up")
        with fam.lock:
            fam.values[self._key] = fam.values.get(self._key, 0.0) + amount

    def set(self, value: float) -> None:
        fam = self._fam
        if not fam.registry.enabled:
            return
        if fam.kind != GAUGE:
            raise TypeError(f"{fam.name} is a {fam.kind}, not a gauge")
        with fam.lock:
            fam.values[self._key] = float(value)

    # histogram -------------------------------------------------------------
    def observe(self, value: float) -> None:
        fam = self._fam
        if not fam.registry.enabled:
            return
        if fam.kind != HISTOGRAM:
            raise TypeError(f"{fam.name} is a {fam.kind}, not a histogram")
        bounds = fam.buckets
        with fam.lock:
            state = fam.values.get(self._key)
            if state is None:
                state = fam.values[self._key] = [[0] * (len(bounds) + 1),
                                                 0.0, 0]
            counts, _, _ = state
            i = 0
            # Prometheus 'le' semantics: a value ON a bound lands in that
            # bound's bucket (test_histogram_bucket_edges)
            while i < len(bounds) and value > bounds[i]:
                i += 1
            counts[i] += 1
            state[1] += float(value)
            state[2] += 1

    def value(self):
        """Current value (counter/gauge float; histogram
        (counts, sum, count) copy) — 0-initialized if never recorded."""
        fam = self._fam
        with fam.lock:
            if fam.kind == HISTOGRAM:
                state = fam.values.get(self._key)
                if state is None:
                    return ([0] * (len(fam.buckets) + 1), 0.0, 0)
                return (list(state[0]), state[1], state[2])
            return fam.values.get(self._key, 0.0)


class _Family:
    """One named metric family: a kind, a help string, and the labeled
    series under it.  The family itself doubles as its own unlabeled
    series, so ``registry.counter("x").inc()`` needs no ``.labels()``."""

    __slots__ = ("registry", "name", "kind", "help", "buckets", "lock",
                 "values", "_children", "_unlabeled")

    #: ``lock`` guards the recorded series and the bound-handle cache;
    #: ``_Series`` (the bound accessor this family hands out) honors the
    #: same contract — every ``fam.values`` touch there sits under
    #: ``fam.lock``, which the schedule harness's record-vs-snapshot
    #: drill verifies at runtime (a lexical lint cannot see the alias)
    GUARDED_BY = {"values": "lock", "_children": "lock"}

    def __init__(self, registry: "Registry", name: str, kind: str,
                 help: str = "", buckets: Optional[Sequence[float]] = None):
        self.registry = registry
        self.name = name
        self.kind = kind
        self.help = help
        self.buckets = tuple(float(b) for b in (buckets or ())) or None
        if kind == HISTOGRAM:
            self.buckets = self.buckets or DEFAULT_BUCKETS
            if list(self.buckets) != sorted(self.buckets):
                raise ValueError("histogram buckets must be sorted")
        self.lock = threading.Lock()
        self.values: dict = {}
        self._children: dict = {}
        self._unlabeled = _Series(self, ())

    def labels(self, **labels) -> _Series:
        if not labels:
            return self._unlabeled
        key = _label_key(labels)
        # double-checked fast path: a racy CPython-atomic dict read; the
        # locked setdefault below is the authoritative insert, a stale
        # None only costs one lock acquire
        # dryadlint: disable=guarded-by -- benign double-checked read (see above)
        child = self._children.get(key)
        if child is None:
            with self.lock:
                child = self._children.setdefault(key, _Series(self, key))
        return child

    # unlabeled passthroughs (the common hot path)
    def inc(self, amount: float = 1.0) -> None:
        self._unlabeled.inc(amount)

    def set(self, value: float) -> None:
        self._unlabeled.set(value)

    def observe(self, value: float) -> None:
        self._unlabeled.observe(value)

    def value(self):
        return self._unlabeled.value()

    def series(self) -> dict:
        """label-block string -> value (see _Series.value) for snapshot."""
        with self.lock:
            keys = list(self.values.keys())
        out = {}
        for key in keys:
            out[_fmt_labels(key).strip("{}")] = _Series(self, key).value()
        return out


class Registry:
    GUARDED_BY = {"_families": "_lock"}

    def __init__(self, enabled: bool = True):
        self.enabled = bool(enabled)
        self._lock = threading.Lock()
        self._families: dict[str, _Family] = {}

    # ---- lifecycle ---------------------------------------------------------
    def enable(self) -> None:
        self.enabled = True

    def disable(self) -> None:
        self.enabled = False

    # ---- family accessors (idempotent; kind mismatch raises) ---------------
    def _family(self, name: str, kind: str, help: str,
                buckets=None) -> _Family:
        # double-checked fast path: the hot accessor's lock-free read; the
        # locked re-check below is the authoritative create (families are
        # never removed, only reset)
        # dryadlint: disable=guarded-by -- benign double-checked read (see above)
        fam = self._families.get(name)
        if fam is None:
            with self._lock:
                fam = self._families.get(name)
                if fam is None:
                    fam = self._families[name] = _Family(
                        self, name, kind, help, buckets)
        if fam.kind != kind:
            raise ValueError(
                f"metric {name!r} already registered as a {fam.kind}")
        return fam

    def reset_prefix(self, prefix: str) -> None:
        """Drop every recorded series in families whose name starts with
        ``prefix``.  Used for run-scoped series (``dryad_run_*``): a
        reused/appended journal begins a new run with ``run_start``, and
        without the reset the live endpoint would present the PRIOR run's
        fault/backoff/resume counts as current.  Scrapers see a counter
        reset, which Prometheus ``rate()`` absorbs."""
        with self._lock:
            fams = [f for f in self._families.values()
                    if f.name.startswith(prefix)]
        for fam in fams:
            with fam.lock:
                fam.values.clear()

    def counter(self, name: str, help: str = "") -> _Family:
        return self._family(name, COUNTER, help)

    def gauge(self, name: str, help: str = "") -> _Family:
        return self._family(name, GAUGE, help)

    def histogram(self, name: str, help: str = "",
                  buckets: Optional[Sequence[float]] = None) -> _Family:
        return self._family(name, HISTOGRAM, help, buckets)

    # ---- consumers (the explicitly-annotated SNAPSHOT PATH: the one place
    # obs is allowed to allocate freely; still jax-free by construction) ----
    def snapshot(self) -> dict:
        """One JSON-able dict of everything: ``{"counters": {name:
        {labelblock: value}}, "gauges": {...}, "histograms": {name:
        {labelblock: {"bounds", "counts", "sum", "count"}}}}``."""
        with self._lock:
            fams = list(self._families.values())
        out: dict = {"counters": {}, "gauges": {}, "histograms": {}}
        for fam in fams:
            if fam.kind == HISTOGRAM:
                out["histograms"][fam.name] = {
                    lbl: {"bounds": list(fam.buckets), "counts": counts,
                          "sum": total, "count": n}
                    for lbl, (counts, total, n) in fam.series().items()}
            else:
                out[fam.kind + "s"][fam.name] = fam.series()
        return out

    def exposition(self) -> str:
        """Prometheus text exposition (format 0.0.4) of every family."""
        with self._lock:
            fams = sorted(self._families.values(), key=lambda f: f.name)
        lines: list[str] = []
        for fam in fams:
            if fam.help:
                lines.append(f"# HELP {fam.name} {_escape(fam.help)}")
            lines.append(f"# TYPE {fam.name} {fam.kind}")
            with fam.lock:
                items = sorted(fam.values.items())
                for key, val in items:
                    if fam.kind != HISTOGRAM:
                        lines.append(
                            f"{fam.name}{_fmt_labels(key)} {_fmt_value(val)}")
                        continue
                    counts, total, n = val
                    cum = 0
                    for bound, c in zip(fam.buckets, counts):
                        cum += c
                        lk = _fmt_labels(key + (("le", repr(float(bound))),))
                        lines.append(f"{fam.name}_bucket{lk} {cum}")
                    lk = _fmt_labels(key + (("le", "+Inf"),))
                    lines.append(f"{fam.name}_bucket{lk} {cum + counts[-1]}")
                    lines.append(
                        f"{fam.name}_sum{_fmt_labels(key)} {_fmt_value(total)}")
                    lines.append(f"{fam.name}_count{_fmt_labels(key)} {n}")
        return "\n".join(lines) + ("\n" if lines else "")


# ---- the process-wide default ----------------------------------------------

_default: Optional[Registry] = None
_default_lock = threading.Lock()


def default_registry() -> Registry:
    """The shared registry train/serve/resilience record into.  Created
    enabled unless ``DRYAD_OBS=0``; swap with ``set_default_registry``
    (tests) or toggle with ``enable()``/``disable()`` (bench arms)."""
    global _default
    if _default is None:
        with _default_lock:
            if _default is None:
                _default = Registry(
                    enabled=os.environ.get("DRYAD_OBS", "1") != "0")
    return _default


def set_default_registry(registry: Registry) -> Registry:
    """Replace the process default (tests/smokes); returns the OLD one so
    callers can restore it."""
    global _default
    with _default_lock:
        old = _default if _default is not None else Registry(
            enabled=os.environ.get("DRYAD_OBS", "1") != "0")
        _default = registry
    return old
